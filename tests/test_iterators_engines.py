"""Iterator layer + distinct aggregation engines (reference oracles:
TestRoaringBitmap iterator suites, BatchIterator advanceIfNeeded contract
BatchIterator.java:72, TestFastAggregation equivalence of strategies)."""

import numpy as np
import pytest

from roaringbitmap_tpu import FastAggregation, ParallelAggregation, RoaringBitmap

rng = np.random.default_rng(0xFEEF1F0)


def shape_diverse_bitmap(seed=0):
    """Sparse + dense + run regions across several keys (SeededTestData-style)."""
    r = np.random.default_rng(seed)
    parts = [
        r.integers(0, 1 << 16, size=300).astype(np.uint32),  # sparse key 0
        (1 << 16) + np.arange(50000, dtype=np.uint32),  # run key 1
        (5 << 16) + r.integers(0, 1 << 16, size=9000).astype(np.uint32),  # dense
        (1000 << 16) + r.integers(0, 1 << 16, size=77).astype(np.uint32),
    ]
    bm = RoaringBitmap(np.concatenate(parts))
    bm.run_optimize()
    return bm


class TestIterators:
    def test_peekable_forward(self):
        bm = shape_diverse_bitmap(1)
        want = bm.to_array().tolist()
        it = bm.get_int_iterator()
        got = []
        while it.has_next():
            p = it.peek_next()
            v = it.next()
            assert p == v
            got.append(v)
        assert got == want

    def test_advance_if_needed(self):
        bm = shape_diverse_bitmap(2)
        arr = bm.to_array()
        for target in [0, int(arr[5]), int(arr[arr.size // 2]) - 1, int(arr[-1])]:
            it = bm.get_int_iterator()
            it.advance_if_needed(target)
            nxt = it.next()
            want = int(arr[np.searchsorted(arr, target)])
            assert nxt == want, f"target {target}"
        it = bm.get_int_iterator()
        it.advance_if_needed(int(arr[-1]) + 1)
        assert not it.has_next()
        # advancing backwards is a no-op
        it = bm.get_int_iterator()
        for _ in range(10):
            it.next()
        tenth = it.peek_next()
        it.advance_if_needed(0)
        assert it.peek_next() == tenth

    def test_reverse(self):
        bm = shape_diverse_bitmap(3)
        want = bm.to_array()[::-1].tolist()
        assert list(bm.get_reverse_int_iterator()) == want

    def test_rank_iterator(self):
        bm = shape_diverse_bitmap(4)
        it = bm.get_int_rank_iterator()
        seen = 0
        while it.has_next() and seen < 500:
            r = it.peek_next_rank()
            it.next()
            seen += 1
            assert r == seen

    def test_batch_iterator(self):
        bm = shape_diverse_bitmap(5)
        want = bm.to_array()
        it = bm.get_batch_iterator()
        buf = np.empty(1000, dtype=np.uint32)
        got = []
        while it.has_next():
            n = it.next_batch(buf)
            got.append(buf[:n].copy())
        assert np.array_equal(np.concatenate(got), want)

    def test_batch_advance_and_adapter(self):
        bm = shape_diverse_bitmap(6)
        arr = bm.to_array()
        it = bm.get_batch_iterator()
        target = int(arr[arr.size // 3])
        it.advance_if_needed(target)
        buf = np.empty(8, dtype=np.uint32)
        n = it.next_batch(buf)
        assert n and int(buf[0]) == target
        it2 = bm.get_batch_iterator()
        it2.advance_if_needed(target)
        assert list(it2.as_int_iterator())[:3] == arr[
            np.searchsorted(arr, target) :
        ][:3].tolist()


class TestEngines:
    """All OR/XOR/AND strategies agree (TestFastAggregation invariants)."""

    def setup_method(self):
        self.bms = [shape_diverse_bitmap(s) for s in range(8)] + [RoaringBitmap()]

    def test_or_strategies_agree(self):
        want = FastAggregation.or_(*self.bms, mode="cpu")
        assert FastAggregation.naive_or(*self.bms) == want
        assert FastAggregation.horizontal_or(*self.bms) == want
        assert FastAggregation.priorityqueue_or(*self.bms) == want
        assert ParallelAggregation.or_(*self.bms, mode="cpu") == want

    def test_xor_strategies_agree(self):
        want = FastAggregation.xor(*self.bms, mode="cpu")
        assert FastAggregation.naive_xor(*self.bms) == want
        assert FastAggregation.horizontal_xor(*self.bms) == want
        assert ParallelAggregation.xor(*self.bms, mode="cpu") == want

    def test_and_strategies_agree(self):
        dense = [shape_diverse_bitmap(s) for s in range(4)]
        want = FastAggregation.and_(*dense, mode="cpu")
        assert FastAggregation.naive_and(*dense) == want
        assert FastAggregation.workshy_and(*dense, mode="cpu") == want

    def test_empty_and_single(self):
        assert FastAggregation.horizontal_or().is_empty()
        assert FastAggregation.priorityqueue_or().is_empty()
        one = shape_diverse_bitmap(9)
        assert FastAggregation.priorityqueue_or(one) == one
        assert FastAggregation.naive_or(one) == one

    def test_cardinality_shortcuts(self):
        assert FastAggregation.or_cardinality(*self.bms) == FastAggregation.or_(
            *self.bms
        ).get_cardinality()
        assert FastAggregation.and_cardinality(*self.bms[:3]) == FastAggregation.and_(
            *self.bms[:3]
        ).get_cardinality()
