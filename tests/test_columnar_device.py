"""Columnar device tier + measured cutoff model (ISSUE 10): the 9-class
grid forced through the device tier bit-exact vs the numpy oracle,
cost-model boundary/default cases, ladder degradation under injected
``columnar.device`` faults, PACK_CACHE-fed vs cold-packed identity, and
the calibration persist/reload round-trip."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import columnar, insights, robust
from roaringbitmap_tpu.columnar import costmodel as col_costmodel
from roaringbitmap_tpu.columnar import device as col_device
from roaringbitmap_tpu.columnar import engine as col_engine
from roaringbitmap_tpu.columnar import kernels as col_kernels
from roaringbitmap_tpu.models.container import RunContainer
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.robust import faults as rfaults
from roaringbitmap_tpu.robust import ladder as rladder

OPS = {
    "and": RoaringBitmap.and_,
    "or": RoaringBitmap.or_,
    "xor": RoaringBitmap.xor,
    "andnot": RoaringBitmap.andnot,
}


@pytest.fixture(autouse=True)
def _isolated_model():
    """Every test starts from the uncalibrated default gate and leaves no
    calibration (or tripped breakers / resident colrows packs) behind."""
    col_costmodel.MODEL.reset()
    col_engine.config.force_device = False
    rladder.LADDER.reset()
    yield
    col_costmodel.MODEL.reset()
    col_engine.config.force_device = False
    rladder.LADDER.reset()
    store.PACK_CACHE.close()


def _chunk_values(kind: str, key: int, rng) -> np.ndarray:
    base = key << 16
    if kind == "array":
        vals = np.sort(rng.choice(1 << 16, 500, replace=False))
    elif kind == "bitmap":
        vals = np.sort(rng.choice(1 << 16, 9000, replace=False))
    else:  # run
        starts = np.arange(0, 1 << 16, 1 << 11)[:20]
        vals = np.unique(
            np.concatenate([np.arange(s, s + 900) for s in starts])
        )
    return (vals + base).astype(np.uint32)


def _typed_bitmap(kinds, rng) -> RoaringBitmap:
    bm = RoaringBitmap(
        np.concatenate([_chunk_values(k, i, rng) for i, k in enumerate(kinds)])
    )
    bm.run_optimize()
    return bm


def _nine_class_pair(rng):
    kinds = ["array", "bitmap", "run"]
    a = _typed_bitmap([k for k in kinds for _ in kinds], rng)
    b = _typed_bitmap([k for _ in kinds for k in kinds], rng)
    return a, b


@pytest.mark.parametrize("op", list(OPS))
def test_all_nine_classes_device_parity(op):
    """Every (array|bitmap|run)^2 matched class forced through the device
    tier, bit-exact vs the per-container engine AND the numpy columnar
    oracle."""
    rng = np.random.default_rng(105)
    a, b = _nine_class_pair(rng)
    ca = columnar.classify(a.high_low_container.containers)
    cb = columnar.classify(b.high_low_container.containers)
    assert columnar.class_histogram(ca, cb).tolist() == [1] * 9
    got = columnar.pairwise(op, a, b, tier="device")
    with columnar.disabled():
        want = OPS[op](a, b)
    assert got == want
    assert got.get_cardinality() == want.get_cardinality()
    assert np.array_equal(got.to_array(), want.to_array())
    # the device execution classes really engaged (dense always occupied;
    # and/andnot also probe through the device word-test gather)
    batch = insights.columnar_counters()["batch"]
    assert batch.get(f"{op}/device_pair", 0) > 0
    if op in ("and", "andnot"):
        assert batch.get(f"{op}/device_gather", 0) > 0


def test_device_vs_numpy_columnar_oracle(monkeypatch):
    """Device tier vs the banded-NUMPY columnar tier (native pinned off):
    the two independent implementations agree pair by pair."""
    monkeypatch.setattr(col_kernels, "_native", lambda: None)
    rng = np.random.default_rng(107)
    from roaringbitmap_tpu import fuzz

    for _ in range(15):
        a = fuzz.random_bitmap(rng)
        b = fuzz.random_bitmap(rng)
        for op in OPS:
            got = columnar.pairwise(op, a, b, tier="device")
            want = columnar.pairwise(op, a, b, tier="cpu")
            assert got == want, op


def test_pack_cache_fed_vs_cold_identical():
    """A device-tier op over PACK_CACHE-resident rows returns the same
    bits as one forced to re-pack cold (cache disabled)."""
    rng = np.random.default_rng(109)
    a, b = _nine_class_pair(rng)
    warm = {}
    col_device.rows_for(a)  # make both operands resident
    col_device.rows_for(b)
    assert col_device.rows_resident(a) and col_device.rows_resident(b)
    for op in OPS:
        warm[op] = columnar.pairwise(op, a, b, tier="device")
    store.PACK_CACHE.configure(0)  # disabled: every build is cold
    try:
        assert not col_device.rows_resident(a)
        for op in OPS:
            cold = columnar.pairwise(op, a, b, tier="device")
            assert cold == warm[op], op
    finally:
        store.PACK_CACHE.configure(2 << 30)


def test_device_fault_degrades_to_columnar_cpu():
    """An injected ``columnar.device`` fault rides the ladder down to the
    columnar-CPU tier bit-exactly, records the degradation edge, and a
    persistent fault trips the breaker (dead tier skipped, not
    re-attempted)."""
    rng = np.random.default_rng(111)
    a, b = _nine_class_pair(rng)
    with columnar.disabled():
        want = RoaringBitmap.and_(a, b)
    before = insights.robust_counters()["degrade"]
    with rfaults.inject(
        "columnar.device", robust.TransientDeviceError, every=1
    ) as inj:
        for _ in range(4):  # trip_after=3 consecutive failures trip
            assert columnar.pairwise("and", a, b, tier="device") == want
        assert inj.fired >= 3
    after = insights.robust_counters()["degrade"]
    edge = "columnar.device/columnar-device/columnar-cpu"
    assert after.get(edge, 0) > before.get(edge, 0)
    assert rladder.LADDER.breaker_state("columnar.device", "columnar-device") == "open"
    # breaker open: the device tier is skipped without attempting (no new
    # fault fires even with the rule armed)
    with rfaults.inject(
        "columnar.device", robust.TransientDeviceError, every=1
    ) as inj2:
        assert columnar.pairwise("and", a, b, tier="device") == want
        assert inj2.fired == 0


def test_empty_calibration_conservative_defaults():
    """Uncalibrated, the model reproduces the r11 hand-tuned gate
    verbatim: count window + dense-shape hint, never the device tier."""
    m = col_costmodel.MODEL
    assert not m.calibrated
    lo = columnar.config.min_containers
    hi = columnar.config.max_containers
    assert m.choose(lo - 1, lo, "run", False)[0] == "per-container"
    assert m.choose(lo, lo - 1, "run", True)[0] == "per-container"
    assert m.choose(hi + 1, hi, "run", True)[0] == "per-container"
    assert m.choose(lo, lo, "array", True)[0] == "per-container"
    for shape in ("bitmap", "run"):
        tier, inputs = m.choose(lo, hi, shape, True)
        assert tier == "columnar-cpu"
        assert inputs["model"] == "default-gate"


def test_calibrated_routes_losers_back_to_percontainer():
    """The measured model fixes the 0.3-0.9x small-operand regression
    zone: verdicts follow the measured per-engine estimates (not the old
    dense hint), run mixes (the measured 2-3x win) stay columnar, and on
    the default C-extension tier — where the per-container walk sits at
    its ~2-4 µs floor — small array mixes route back per-container. The
    slower native tiers legitimately measure different crossovers; the
    argmin consistency is the tier-independent contract."""
    m = columnar.calibrate(include_device=False)
    assert m.calibrated
    assert m.choose(32, 32, "run", False)[0] == "columnar-cpu"
    for n, shape in ((16, "array"), (64, "array"), (32, "bitmap"), (64, "bitmap")):
        tier, inputs = m.choose(n, n, shape, False)
        est = inputs["est_us"]
        assert tier == min(est, key=est.get), (n, shape)
        assert inputs["model"] == "calibrated"
    from roaringbitmap_tpu import native

    if native.backend_tier() == "ext":
        assert m.choose(64, 64, "array", False)[0] == "per-container"


def test_faulty_device_calibration_drops_device_coefficients():
    """A device that faults during calibration must NOT have the ladder's
    CPU-fallback timings installed as its coefficients — the device
    column is discarded and the tier stays unpriced (never chosen) until
    a healthy calibration re-prices it."""
    col_engine.config.force_device = True
    with rfaults.inject(
        "columnar.device", robust.TransientDeviceError, every=1
    ) as inj:
        m = columnar.calibrate(include_device=True)
    assert inj.fired > 0
    assert m.calibrated
    assert all("columnar-device" not in t for t in m.coeffs.values())
    # CPU routing is intact and the device tier is never the verdict
    assert m.choose(32, 32, "run", True, allow_device=True)[0] != (
        "columnar-device"
    )


def test_calibration_roundtrip_same_routing(tmp_path):
    """persist -> reload -> identical verdicts across the feature grid."""
    path = os.path.join(str(tmp_path), "colcal.json")
    m = columnar.calibrate(include_device=False, persist=path)
    assert os.path.isfile(path)
    m2 = col_costmodel.CostModel()
    assert m2.load(path)
    for na in (16, 64, 512, 4096):
        for shape in col_costmodel.SHAPES:
            for resident in (False, True):
                assert (
                    m2.choose(na, na, shape, resident)[0]
                    == m.choose(na, na, shape, resident)[0]
                ), (na, shape, resident)
    # a foreign-backend file is rejected, state untouched
    m3 = col_costmodel.CostModel()
    bad = dict(m.to_dict(), backend="tpu-imaginary")
    import json

    with open(path, "w") as f:
        json.dump(bad, f)
    assert not m3.load(path)
    assert not m3.calibrated


def test_routed_device_tier_end_to_end():
    """With the model calibrated and the device tier admitted
    (force_device on the CPU backend), the FACADE routes a resident
    dense pair through the device tier — visible in the route counter and
    the decision log — and stays bit-exact."""
    rng = np.random.default_rng(113)
    kinds = ["bitmap", "run"] * 16
    a, b = _typed_bitmap(kinds, rng), _typed_bitmap(kinds, rng)
    columnar.calibrate(include_device=True)
    col_engine.config.force_device = True
    # make the rows resident so the ship term is sunk — the device tier
    # must now price below columnar-cpu for this dense working set
    col_device.rows_for(a)
    col_device.rows_for(b)
    tier = columnar.route(a.high_low_container, b.high_low_container)
    routed = RoaringBitmap.and_(a, b)
    with columnar.disabled():
        want = RoaringBitmap.and_(a, b)
    assert routed == want
    decs = [
        d for d in insights.decisions() if d["site"] == "columnar.cutoff"
    ]
    assert decs and decs[-1]["decision"] == tier
    assert decs[-1]["inputs"]["model"] == "calibrated"
    if tier == "columnar-device":
        assert insights.columnar_counters()["route"].get("columnar-device", 0) > 0


def test_outside_gate_sampled_decision():
    """Outside-window verdicts (below min OR above max — the jmh-grid
    shapes) record 1-in-N (the calibration-data gap fix): driving > N
    routed calls lands at least one sampled entry tagged with the
    sampling factor, and the max cap holds in BOTH model modes."""
    small = RoaringBitmap(np.arange(40, dtype=np.uint32))  # 1 container
    hlc = small.high_low_container
    for _ in range(col_engine._BELOW_GATE.every + 1):
        assert columnar.route(hlc, hlc) == "per-container"
    samples = [
        d
        for d in insights.decisions()
        if d["site"] == "columnar.cutoff"
        and d["inputs"].get("reason") == "outside-gate"
    ]
    assert samples
    assert samples[-1]["inputs"]["sampled"] == col_engine._BELOW_GATE.every
    # above the cap the calibrated model must NOT extrapolate its
    # 16..64-cell fit: the r07 per-container floor argument stands
    big = RoaringBitmap((np.arange(5000, dtype=np.uint64) << 16).astype(np.uint32))
    columnar.calibrate(include_device=False)
    assert columnar.route(big.high_low_container, big.high_low_container) == (
        "per-container"
    )


def test_word_test_gather_matches_cpu_mask():
    """The on-device word-test gather and the CPU member_mask agree on a
    mixed probe batch (the array x bitmap class core)."""
    from roaringbitmap_tpu.columnar.partition import gather_values, stack_words
    from roaringbitmap_tpu.ops import device as dev

    rng = np.random.default_rng(115)
    kinds = ["array", "bitmap"] * 10
    a = _typed_bitmap(kinds, rng)
    b = _typed_bitmap(kinds[::-1], rng)
    acs = a.high_low_container.containers
    bcs = b.high_low_container.containers
    ca = columnar.classify(acs)
    cb = columnar.classify(bcs)
    idx = np.flatnonzero((ca == 0) & (cb == 1))
    assert idx.size
    vals, offs = gather_values(acs, idx)
    row_ids = np.repeat(idx, np.diff(offs))  # rows in b's resident block
    rows_b = col_device.rows_for(b)
    got = dev.word_test_rows_host(rows_b, row_ids, vals)
    mat = stack_words(bcs, idx)
    local = np.repeat(np.arange(idx.size, dtype=np.int64), np.diff(offs))
    want = col_kernels.member_mask(mat, local, vals)
    assert np.array_equal(got, want)


def test_colrows_residency_delta_invalidation():
    """A mutated operand's fingerprint moves, so the resident colrows
    entry stops matching (no stale device rows served) and the op stays
    correct."""
    rng = np.random.default_rng(117)
    a, b = _nine_class_pair(rng)
    col_device.rows_for(a)
    assert col_device.rows_resident(a)
    r1 = columnar.pairwise("or", a, b, tier="device")
    v = (3 << 16) + 12345
    while a.contains(v) or b.contains(v):  # must actually change the OR
        v += 1
    a.add(v)  # mutate: version bump -> new fingerprint
    assert not col_device.rows_resident(a)
    r2 = columnar.pairwise("or", a, b, tier="device")
    with columnar.disabled():
        assert r2 == RoaringBitmap.or_(a, b)
    assert r2.contains(v)
    assert r1 != r2
