"""Pipeline timeline tracer + latency histograms (ISSUE 6): ring-buffer
integrity under a multi-thread hammer, golden Perfetto/Chrome trace
export, quantile accuracy against a numpy percentile oracle, the
dump-on-anomaly hook, the zero-overhead contract when tracing is off, and
the marshal-pipeline stage instrumentation end to end."""

import json
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, observe
from roaringbitmap_tpu.observe import MetricError, Registry, latency_histogram
from roaringbitmap_tpu.observe import timeline as tl
from roaringbitmap_tpu.observe.histogram import log_time_buckets
from roaringbitmap_tpu.parallel import store


@pytest.fixture
def recording():
    """Timeline ON with a clean recorder; always restored to off."""
    prev = tl.mode_name()
    tl.configure(mode="on", budget_ms=0)
    tl.RECORDER.clear()
    try:
        yield tl.RECORDER
    finally:
        tl.configure(mode=prev, budget_ms=0)
        tl.RECORDER.clear()


# ---------------------------------------------------------------------------
# latency histogram: buckets + quantiles
# ---------------------------------------------------------------------------


def test_log_buckets_are_geometric_and_bounded():
    bs = log_time_buckets(1e-6, 100.0, per_decade=8)
    assert bs[0] == pytest.approx(1e-6)
    assert bs[-1] >= 100.0
    ratios = [b2 / b1 for b1, b2 in zip(bs, bs[1:])]
    # 10^(1/8) ~ 1.334, modulo the 4-significant-digit rounding
    assert all(1.30 < r < 1.37 for r in ratios)
    with pytest.raises(MetricError):
        log_time_buckets(1.0, 0.5)


def test_quantiles_match_numpy_percentile_oracle():
    reg = Registry()
    h = latency_histogram("rb_tpu_oracle_seconds", "", ("k",), registry=reg)
    rng = np.random.default_rng(7)
    vals = np.abs(rng.lognormal(mean=-6.0, sigma=1.8, size=8000))
    for v in vals:
        h.observe(float(v), ("a",))
    for q in (0.5, 0.9, 0.99):
        est = h.quantile(q, ("a",))
        true = float(np.percentile(vals, q * 100))
        # the estimate must land within one log-bucket ratio of the truth
        assert true / 1.35 <= est <= true * 1.35, (q, est, true)


def test_quantile_edge_cases():
    reg = Registry()
    h = latency_histogram("rb_tpu_edge_seconds", "", registry=reg)
    assert h.quantile(0.5) == 0.0  # empty series
    h.observe(1e9)  # beyond the last bound: clamps, never fabricates
    assert h.quantile(0.99) == h.buckets[-1]
    with pytest.raises(MetricError):
        h.quantile(1.5)


def test_latency_name_requires_seconds_suffix():
    with pytest.raises(MetricError):
        latency_histogram("rb_tpu_bad_total", "", registry=Registry())


def test_quantiles_flow_through_every_export():
    reg = Registry()
    h = latency_histogram("rb_tpu_flow_seconds", "", ("k",), registry=reg)
    for v in (0.001, 0.002, 0.004, 0.2):
        h.observe(v, ("x",))
    snap = reg.snapshot()["rb_tpu_flow_seconds"]["samples"][0]
    assert set(snap["quantiles"]) == {"p50", "p90", "p99"}
    assert snap["quantiles"]["p50"] <= snap["quantiles"]["p99"]
    [line] = [l for l in observe.jsonl_lines(reg) if "rb_tpu_flow_seconds" in l]
    assert set(json.loads(line)["quantiles"]) == {"p50", "p90", "p99"}
    txt = observe.prometheus_text(reg)
    assert 'rb_tpu_flow_seconds{k="x",quantile="0.5"}' in txt
    assert 'rb_tpu_flow_seconds{k="x",quantile="0.99"}' in txt
    lat = observe.sidecar_snapshot(reg)["latency"]["rb_tpu_flow_seconds"]["x"]
    assert lat["count"] == 4 and lat["p50"] <= lat["p90"] <= lat["p99"]


# ---------------------------------------------------------------------------
# flight recorder: ring semantics + thread hammer
# ---------------------------------------------------------------------------


def _ev(i):
    return tl.TimelineEvent(f"e{i}", "t", "X", i, 1, 0, None)


def test_ring_buffer_keeps_newest_window():
    rec = tl.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record(_ev(i))
    assert len(rec) == 4 and rec.total() == 10 and rec.dropped() == 6
    assert [e.name for e in rec.events()] == ["e6", "e7", "e8", "e9"]
    rec.resize(2)
    assert [e.name for e in rec.events()] == ["e8", "e9"]
    rec.clear()
    assert len(rec) == 0 and rec.dropped() == 0


def test_recorder_hammer_no_lost_or_torn_events():
    """8 threads x 500 spans: every event lands exactly once (modulo ring
    overwrite), no torn TimelineEvent, bounded memory."""
    rec = tl.FlightRecorder(capacity=10_000)
    n_threads, per_thread = 8, 500

    def worker(t):
        for i in range(per_thread):
            rec.record(
                tl.TimelineEvent(f"w{t}.{i}", "hammer", "X", i, 1, t, None)
            )

    with ThreadPoolExecutor(n_threads) as ex:
        list(ex.map(worker, range(n_threads)))
    evs = rec.events()
    assert rec.total() == n_threads * per_thread
    assert len(evs) == min(10_000, n_threads * per_thread)
    names = [e.name for e in evs]
    assert len(set(names)) == len(names)  # exactly-once: no duplicates
    for e in evs:  # no torn events: every field readable + consistent
        t = int(e.name[1:].split(".")[0])
        assert e.tid == t and e.ph == "X" and e.cat == "hammer"


def test_span_hammer_through_public_api(recording):
    tl.RECORDER.resize(100_000)
    n_threads, per_thread = 8, 200

    def worker(t):
        for i in range(per_thread):
            with tl.tspan(f"h{t}", "hammer", i=i):
                pass
            tl.instant(f"i{t}", "hammer")

    with ThreadPoolExecutor(n_threads) as ex:
        list(ex.map(worker, range(n_threads)))
    evs = tl.RECORDER.events()
    spans = [e for e in evs if e.ph == "X" and e.cat == "hammer"]
    instants = [e for e in evs if e.ph == "i" and e.cat == "hammer"]
    assert len(spans) == len(instants) == n_threads * per_thread
    # and the histogram agrees with the recorder
    st = observe.REGISTRY.get(observe.TIMELINE_SPAN_SECONDS).get(("hammer",))
    assert st["count"] >= n_threads * per_thread
    tl.RECORDER.resize(tl.DEFAULT_CAPACITY)


# ---------------------------------------------------------------------------
# golden Perfetto / Chrome trace export
# ---------------------------------------------------------------------------


def test_chrome_trace_golden_shape(recording):
    with tl.tspan("pack.host_words", "pack", rows=3):
        pass
    tl.instant("pack_cache.hit", "cache", kind="agg", bytes=128)
    trace = tl.chrome_trace(meta={"schema": "x/1"})
    assert trace["displayTimeUnit"] == "ms"
    assert trace["otherData"] == {"schema": "x/1"}
    span, inst, *meta_evs = trace["traceEvents"]
    assert span["name"] == "pack.host_words" and span["ph"] == "X"
    assert {"pid", "tid", "ts", "dur", "cat"} <= set(span)
    assert span["args"] == {"rows": 3}
    assert inst["ph"] == "i" and inst["s"] == "t"
    assert inst["args"] == {"kind": "agg", "bytes": 128}
    assert [e["ph"] for e in meta_evs] == ["M"]  # thread_name metadata
    assert meta_evs[0]["args"]["name"] == threading.current_thread().name
    json.dumps(trace)  # must be directly serializable


def test_write_chrome_trace_roundtrip(recording, tmp_path):
    with tl.tspan("s", "c"):
        pass
    p = tmp_path / "trace.json"
    tl.write_chrome_trace(str(p))
    loaded = json.loads(p.read_text())
    assert [e["name"] for e in loaded["traceEvents"]][0] == "s"


def test_stage_totals_sums_only_named_spans(recording):
    for _ in range(3):
        with tl.tspan("a", "c"):
            pass
    with tl.tspan("b", "c"):
        pass
    totals = tl.stage_totals(tl.RECORDER.events(), ["a", "missing"])
    assert totals["a"] > 0 and totals["missing"] == 0.0
    assert "b" not in totals


# ---------------------------------------------------------------------------
# dump-on-anomaly
# ---------------------------------------------------------------------------


def test_anomaly_budget_flushes_recorder(tmp_path):
    prev = tl.mode_name()
    dump = tmp_path / "anomaly.jsonl"
    tl.configure(mode="on", budget_ms=0.0001, dump_path=str(dump))
    tl.RECORDER.clear()
    before = observe.REGISTRY.get(observe.TIMELINE_ANOMALY_TOTAL).get(("slow",))
    try:
        with tl.tspan("slow.step", "slow"):
            import time

            time.sleep(0.002)  # >> 0.0001 ms budget
    finally:
        tl.configure(mode=prev, budget_ms=0)
    # the dump writes on a daemon thread (anomalies can fire under
    # framework locks); give it a bounded moment to land
    import time

    deadline = time.time() + 5.0
    while not dump.is_file() and time.time() < deadline:
        time.sleep(0.01)
    assert dump.is_file()
    lines = [json.loads(l) for l in dump.read_text().splitlines()]
    header, events = lines[0], lines[1:]
    assert header["schema"] == tl.DUMP_SCHEMA
    assert header["trigger"]["span"] == "slow.step"
    assert any(e["name"] == "slow.step" for e in events)
    after = observe.REGISTRY.get(observe.TIMELINE_ANOMALY_TOTAL).get(("slow",))
    assert after == before + 1
    # the anomaly marker itself lands on the timeline
    assert any(e.name == "timeline.anomaly" for e in tl.RECORDER.events())
    tl.RECORDER.clear()


def test_no_anomaly_without_budget(recording, tmp_path):
    dump = tmp_path / "never.jsonl"
    tl.configure(dump_path=str(dump))  # budget stays disabled
    with tl.tspan("slow", "s"):
        import time

        time.sleep(0.002)
    time.sleep(0.05)  # would-be async dump window
    assert not dump.exists()


# ---------------------------------------------------------------------------
# zero-overhead contract when disabled
# ---------------------------------------------------------------------------


def test_disabled_mode_allocates_no_span_objects(monkeypatch):
    """RB_TPU_TIMELINE unset => the pack hot path constructs zero timeline
    span/event objects and records nothing (the <2% overhead contract)."""
    assert tl.mode_name() == "off"  # conftest never sets RB_TPU_TIMELINE

    def boom(*a, **k):
        raise AssertionError("span object constructed while tracing is off")

    monkeypatch.setattr(tl, "_Span", boom)
    monkeypatch.setattr(tl, "TimelineEvent", boom)
    monkeypatch.setattr(tl.RECORDER, "record", boom)
    bms = [RoaringBitmap(np.arange(i, 40_000 + i, 9)) for i in range(8)]
    store.PACK_CACHE.close()
    packed = store.packed_for(bms)
    _ = packed.device_words
    bms[0].add(123_456)
    store.packed_for(bms)  # delta path
    store.PACK_CACHE.close()
    # the shared null context is reused, not allocated per call
    assert tl.tspan("a", "b") is tl.tspan("c", "d")


def test_disabled_spans_still_feed_latency_histograms():
    """stage() keeps observing its histogram with tracing off — quantiles
    must not require the flight recorder."""
    assert not tl.enabled()
    h = observe.REGISTRY.get(observe.STORE_PACK_STAGE_SECONDS)
    before = (h.get(("host_words",)) or {"count": 0})["count"]
    store.pack_rows_host(
        [RoaringBitmap([1, 2, 3]).high_low_container.containers[0]]
    )
    after = h.get(("host_words",))["count"]
    assert after == before + 1


# ---------------------------------------------------------------------------
# marshal pipeline instrumentation end to end
# ---------------------------------------------------------------------------


def test_pack_and_delta_stages_attribute_the_walls(recording):
    tl.configure(mode="fenced")
    bms = [RoaringBitmap(np.arange(i, 120_000 + i, 5)) for i in range(12)]
    store.PACK_CACHE.close()
    tl.RECORDER.clear()
    import time

    t0 = time.perf_counter()
    packed = store.packed_for(bms)
    pack_wall = time.perf_counter() - t0
    pack_stages = tl.stage_totals(
        tl.RECORDER.events(),
        # ISSUE 8: the cold pack builds a compact payload (pack.payload_build);
        # word expansion moved off the pack wall into pack.device_expand at
        # first device touch (asserted below)
        ["pack.key_plan", "pack.group_tables", "pack.payload_build",
         "pack.provenance"],
    )
    assert all(v > 0 for v in pack_stages.values())
    assert sum(pack_stages.values()) <= pack_wall * 1.01

    tl.RECORDER.clear()
    _ = packed.device_words
    expand_stages = tl.stage_totals(tl.RECORDER.events(), ["pack.device_expand"])
    assert expand_stages["pack.device_expand"] > 0

    _ = packed.device_words
    for bm in bms[:3]:
        # key 1 already packed, value absent from bms[:3] (78869 % 5 == 4):
        # a same-structure mutation, so the O(k) delta path must serve it
        bm.add(78_869)
    tl.RECORDER.clear()
    t0 = time.perf_counter()
    refreshed = store.packed_for(bms)
    delta_wall = time.perf_counter() - t0
    assert refreshed is packed
    evs = tl.RECORDER.events()
    delta_stages = tl.stage_totals(
        evs, ["delta.dirty_scan", "delta.host_rows", "delta.scatter", "delta.republish"]
    )
    assert all(v > 0 for v in delta_stages.values())
    assert sum(delta_stages.values()) <= delta_wall * 1.01
    assert any(e.name == "pack_cache.delta_hit" for e in evs)
    # and the always-on histograms carry the same stages with quantiles
    lat = observe.sidecar_snapshot()["latency"]
    assert "scatter" in lat["rb_tpu_store_delta_stage_seconds"]
    store.PACK_CACHE.close()


def test_cache_events_and_query_latency_on_timeline(recording):
    from roaringbitmap_tpu.query import Q, execute

    bms = [RoaringBitmap(np.arange(i, 50_000 + i, 3)) for i in range(4)]
    store.PACK_CACHE.close()
    tl.RECORDER.clear()
    expr = Q.or_(*bms[:3]) & bms[3]
    execute(expr)
    names = {e.name for e in tl.RECORDER.events()}
    assert "query.step" in names
    h = observe.REGISTRY.get(observe.QUERY_LATENCY_SECONDS)
    assert h.get(("execute",))["count"] >= 1
    assert h.quantile(0.5, ("execute",)) > 0
    store.PACK_CACHE.close()


def test_columnar_class_kernels_record_spans(recording):
    from roaringbitmap_tpu import columnar

    rng = np.random.default_rng(3)
    a = RoaringBitmap(rng.choice(2_000_000, size=400_000, replace=False))
    b = RoaringBitmap(rng.choice(2_000_000, size=400_000, replace=False))
    a.run_optimize()
    assert columnar.enabled_for(a.high_low_container, b.high_low_container)
    tl.RECORDER.clear()
    RoaringBitmap.and_(a, b)
    evs = tl.RECORDER.events()
    assert any(e.cat == "columnar" for e in evs)
    h = observe.REGISTRY.get(observe.COLUMNAR_CLASS_SECONDS)
    assert h is not None and len(h.series()) > 0


def test_fence_is_noop_unless_fenced():
    class Fenceable:
        calls = 0

        def block_until_ready(self):
            Fenceable.calls += 1

    x = Fenceable()
    prev = tl.mode_name()
    try:
        tl.configure(mode="on")
        assert tl.fence(x) is x and Fenceable.calls == 0
        tl.configure(mode="fenced")
        assert tl.fence(x) is x and Fenceable.calls == 1
        tl.fence(None)  # tolerated
        tl.fence(object())  # host value: AttributeError swallowed
    finally:
        tl.configure(mode=prev)
