"""Static-analysis framework tests (ISSUE 3): per-rule fixture snippets
(positive + negative + pragma-suppressed), baseline round-trip, the
lock-order witness, CLI exit codes, and the live-tree smoke gate (zero
non-baselined findings across all five rules)."""

import json
import os
import subprocess
import sys
import threading

import pytest

from roaringbitmap_tpu.analysis import (
    LockOrderError,
    LockWitness,
    ProjectContext,
    all_contract_rule_ids,
    all_rule_ids,
    baseline,
    fingerprints,
    get_project,
    knobs as knobs_mod,
    run_checks,
    run_contract_checks,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "roaringbitmap_tpu")


def _run_snippet(tmp_path, source, rules=None, name="snippet.py"):
    p = tmp_path / name
    p.write_text(source)
    return run_checks([str(p)], rules=rules, root=str(tmp_path))


def _rules_of(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# rule registry / framework basics
# ---------------------------------------------------------------------------


def test_all_five_rules_registered():
    assert all_rule_ids() == [
        "dtype-discipline",
        "exception-hygiene",
        "lock-discipline",
        "metric-naming",
        "trace-safety",
    ]


def test_unknown_rule_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown rule"):
        _run_snippet(tmp_path, "x = 1\n", rules=["no-such-rule"])


def test_findings_carry_location_and_snippet(tmp_path):
    res = _run_snippet(
        tmp_path,
        "try:\n    pass\nexcept Exception:\n    pass\n",
        rules=["exception-hygiene"],
    )
    (f,) = res.findings
    assert (f.line, f.rule, f.severity) == (3, "exception-hygiene", "error")
    assert f.snippet == "except Exception:"
    assert f.path.endswith("snippet.py")


# ---------------------------------------------------------------------------
# dtype-discipline
# ---------------------------------------------------------------------------

DTYPE_POS = """# rb-payload-path
import numpy as np
def f(a):
    return a.astype(np.int32)
def g(n):
    return np.zeros(n, dtype=np.int16)
def h(x):
    return np.int32(x)
"""


def test_dtype_positive(tmp_path):
    res = _run_snippet(tmp_path, DTYPE_POS, rules=["dtype-discipline"])
    assert len(res.findings) == 3
    assert {f.line for f in res.findings} == {4, 6, 8}


def test_dtype_negative_int64_and_unsigned_ok(tmp_path):
    src = """# rb-payload-path
import numpy as np
def f(a):
    return a.astype(np.int64) + np.cumsum(a, dtype=np.uint64)
"""
    res = _run_snippet(tmp_path, src, rules=["dtype-discipline"])
    assert res.findings == []


def test_dtype_scoped_to_payload_paths(tmp_path):
    # same code without the directive / payload filename: out of scope
    res = _run_snippet(
        tmp_path,
        "import numpy as np\ndef f(a):\n    return a.astype(np.int32)\n",
        rules=["dtype-discipline"],
    )
    assert res.findings == []


def test_dtype_pragma_suppressed(tmp_path):
    src = """# rb-payload-path
import numpy as np
def f(a):
    return a.astype(np.int32)  # rb-ok: dtype-discipline -- bounded by 2^16
"""
    res = _run_snippet(tmp_path, src, rules=["dtype-discipline"])
    assert res.findings == [] and res.suppressed == 1


def test_dtype_multiline_comment_pragma_covers_next_code_line(tmp_path):
    src = """# rb-payload-path
import numpy as np
def f(a):
    # rb-ok: dtype-discipline -- the justification is long and
    # continues on a second comment line before the code
    return a.astype(np.int32)
"""
    res = _run_snippet(tmp_path, src, rules=["dtype-discipline"])
    assert res.findings == [] and res.suppressed == 1


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------

TRACE_POS = """import functools
import jax
@jax.jit
def f(x):
    if x > 0:
        return int(x)
    return x.item()
"""


def test_trace_safety_positive(tmp_path):
    res = _run_snippet(tmp_path, TRACE_POS, rules=["trace-safety"])
    msgs = " ".join(f.message for f in res.findings)
    assert len(res.findings) == 3
    assert "`if`" in msgs and "int()" in msgs and ".item()" in msgs


def test_trace_safety_static_args_exempt(tmp_path):
    src = """import functools
import jax
@functools.partial(jax.jit, static_argnames=("op",))
def f(x, op):
    if op == "or":
        return x
    while op != "or":
        break
    return x
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert res.findings == []


def test_trace_safety_shape_and_none_checks_exempt(tmp_path):
    src = """import jax
import jax.numpy as jnp
@jax.jit
def f(x, seed=None):
    n = x.shape[0]
    if n > 2:
        return x
    if seed is None:
        seed = jnp.uint32(0)
    return x
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert res.findings == []


def test_trace_safety_untraced_function_clean(tmp_path):
    src = "def f(x):\n    return x.item() if x > 0 else int(x)\n"
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert res.findings == []


def test_trace_safety_pallas_kernel_and_wrapped(tmp_path):
    src = """import jax
from jax.experimental import pallas as pl
def kernel(ref, out):
    out[...] = ref[...].tolist()
def run(x):
    return pl.pallas_call(kernel)(x)
def wrapped(x):
    return x.item()
g = jax.jit(wrapped)
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert {f.line for f in res.findings} == {4, 8}


def test_trace_safety_one_level_closure_syncs_only(tmp_path):
    src = """import jax
def helper(x):
    if x:  # tracedness unknown at this level: not flagged
        return x.item()  # definite sync: flagged
    return x
@jax.jit
def f(x):
    return helper(x)
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert [f.line for f in res.findings] == [4]


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_SRC = """import threading
_L = threading.Lock()
_STATE = {}  # guarded-by: _L

def bad(k, v):
    _STATE[k] = v

def bad_mutator(k):
    _STATE.pop(k)

def good(k, v):
    with _L:
        _STATE[k] = v
        _STATE.update({k: v})

class C:
    POOL = None  # guarded-by: _POOL_LOCK
    _POOL_LOCK = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}  # guarded-by: self._lock
        self.count = 0  # init writes exempt

    def bad(self, k):
        self._entries[k] = 1
        C.POOL = object()

    def good(self, k):
        with self._lock:
            self._entries[k] = 1
        with C._POOL_LOCK:
            C.POOL = object()
"""


def test_lock_discipline(tmp_path):
    res = _run_snippet(tmp_path, LOCK_SRC, rules=["lock-discipline"])
    assert {f.line for f in res.findings} == {6, 9, 26, 27}
    assert all("guarded-by" in f.message for f in res.findings)


def test_lock_discipline_unannotated_state_ignored(tmp_path):
    src = "_S = {}\ndef f():\n    _S['x'] = 1\n"
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# exception-hygiene
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "handler,n",
    [
        ("except Exception:\n    pass", 1),
        ("except:\n    pass", 1),
        ("except (ValueError, Exception):\n    pass", 1),
        ("except BaseException:\n    pass", 1),
        ("except ValueError:\n    pass", 0),  # narrow: fine
        ("except Exception as e:\n    raise RuntimeError() from e", 0),  # re-wrap
        ("except BaseException:\n    x = 1\n    raise", 0),  # cleanup-then-reraise
        ("except Exception:  # rb-ok: exception-hygiene -- probe\n    pass", 0),
    ],
)
def test_exception_hygiene(tmp_path, handler, n):
    src = "def f():\n    try:\n        pass\n" + "\n".join(
        "    " + l for l in handler.splitlines()
    ) + "\n"
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    assert len(res.findings) == n, src


def test_exception_hygiene_classify_then_route_exempt(tmp_path):
    """The ladder's declared degradation idiom (ISSUE 7): classify() plus
    a (possibly nested) fatal re-raise is not a swallow."""
    src = (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception as e:\n"
        "        if errors.classify(e) == errors.FATAL:\n"
        "            raise\n"
        "        route_down(e)\n"
    )
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    assert res.findings == []


def test_exception_hygiene_classify_without_reraise_flagged(tmp_path):
    """classify() alone is not the idiom — without a fatal re-raise path a
    programming error is still swallowed."""
    src = (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception as e:\n"
        "        log(classify(e))\n"
    )
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    assert len(res.findings) == 1


def test_exception_hygiene_fault_site_rejects_pragma(tmp_path):
    """Inside a function containing a registered fault site, a raw broad
    except is flagged even when pragma'd (ISSUE 7 satellite: swallowing on
    a fault-site path defeats the chaos gate)."""
    src = (
        "def g():\n"
        '    faults.fault_point("store.ship")\n'
        "    try:\n"
        "        pass\n"
        "    except Exception:  # rb-ok: exception-hygiene -- swallowed anyway\n"
        "        pass\n"
    )
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    assert len(res.findings) == 1
    assert "fault-site" in res.findings[0].message


def test_exception_hygiene_fault_site_accepts_classify_route(tmp_path):
    src = (
        "def g():\n"
        '    faults.fault_point("store.ship")\n'
        "    try:\n"
        "        pass\n"
        "    except Exception as e:\n"
        "        if classify(e) == FATAL:\n"
        "            raise\n"
        "        degrade(e)\n"
    )
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# metric-naming
# ---------------------------------------------------------------------------

METRIC_SRC = """from roaringbitmap_tpu import observe
GOOD_TOTAL = "rb_tpu_good_total"
BAD_TOTAL = "rb_other_total"
A = observe.counter("rb_tpu_a_total", "ok", ("k",))
B = observe.counter("oops_total", "bad prefix")
C = observe.counter(GOOD_TOTAL, "ok")
D = observe.counter(BAD_TOTAL, "bad constant")
E = observe.gauge("rb_tpu_" + "computed", "computed name")
F = observe.histogram("rb_tpu_h_seconds", "labels not literal", labelnames=tuple(["a"]))
"""


def test_metric_naming(tmp_path):
    res = _run_snippet(tmp_path, METRIC_SRC, rules=["metric-naming"])
    by_line = {f.line for f in res.findings}
    # line 3: non-compliant ALL_CAPS constant; 5: bad literal; 7: bad
    # constant use; 8: computed name; 9: computed labelnames
    assert by_line == {3, 5, 7, 8, 9}


def test_metric_naming_forwarding_wrapper_exempt(tmp_path):
    src = """from roaringbitmap_tpu.observe import registry
def counter(name, help=""):
    return registry.REGISTRY.counter(name, help)
"""
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


LATENCY_SRC = """from roaringbitmap_tpu import observe
GOOD_SECONDS = "rb_tpu_good_seconds"
BAD_UNIT_TOTAL = "rb_tpu_oops_total"
A = observe.latency_histogram("rb_tpu_a_seconds", "ok", ("stage",))
B = observe.latency_histogram("rb_tpu_b_total", "bad unit suffix")
C = observe.latency_histogram("oops_seconds", "bad prefix")
D = observe.latency_histogram(GOOD_SECONDS, "ok via constant")
E = observe.latency_histogram(BAD_UNIT_TOTAL, "bad constant value")
F = observe.latency_histogram(observe.QUERY_LATENCY_SECONDS, "ok cross-module")
G = observe.latency_histogram(observe.QUERY_CACHE_TOTAL, "bad cross-module shape")
"""


def test_metric_naming_latency_histograms_need_seconds_suffix(tmp_path):
    res = _run_snippet(tmp_path, LATENCY_SRC, rules=["metric-naming"])
    by_line = {f.line for f in res.findings}
    # 5: literal lacking _seconds; 6: bad prefix; 8: constant value lacking
    # _seconds; 10: cross-module constant not _SECONDS-shaped. Lines 4/7/9
    # are compliant.
    assert by_line == {5, 6, 8, 10}


def test_metric_naming_plain_histogram_keeps_old_rules(tmp_path):
    # the _seconds requirement is latency-histogram-only: a plain registry
    # histogram under a _TOTAL-ish name stays legal (regression guard)
    src = 'from roaringbitmap_tpu import observe\n' \
          'H = observe.histogram("rb_tpu_plain_bytes", "not latency", ("k",))\n'
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    res = _run_snippet(
        tmp_path,
        "try:\n    pass\nexcept Exception:\n    pass\n",
        rules=["exception-hygiene"],
    )
    assert len(res.findings) == 1
    bl = tmp_path / "baseline.json"
    doc = baseline.dump(str(bl), res.findings)
    assert len(doc["findings"]) == 1
    fps = baseline.load(str(bl))
    new, old = baseline.partition(res.findings, fps)
    assert new == [] and len(old) == 1
    # a different violation is NOT covered by the baseline
    res2 = _run_snippet(
        tmp_path,
        "try:\n    x = 1\nexcept BaseException:\n    pass\n",
        rules=["exception-hygiene"],
        name="other.py",
    )
    new2, old2 = baseline.partition(res2.findings, fps)
    assert len(new2) == 1 and old2 == []


def test_baseline_fingerprints_survive_line_shifts(tmp_path):
    src = "try:\n    pass\nexcept Exception:\n    pass\n"
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    shifted = "import os\n\n" + src  # same finding, two lines lower
    res2 = _run_snippet(tmp_path, shifted, rules=["exception-hygiene"], name="snippet.py")
    assert fingerprints(res.findings) == fingerprints(res2.findings)


def test_baseline_missing_file_is_empty():
    assert baseline.load("/nonexistent/baseline.json") == set()


def test_baseline_rejects_foreign_json(tmp_path):
    p = tmp_path / "b.json"
    p.write_text('{"something": "else"}')
    with pytest.raises(ValueError, match="not a v1 analysis baseline"):
        baseline.load(str(p))


# ---------------------------------------------------------------------------
# lock-order witness (dynamic complement)
# ---------------------------------------------------------------------------


def test_lock_witness_consistent_order_passes():
    w = LockWitness()
    a = w.wrap("A", threading.Lock())
    b = w.wrap("B", threading.Lock())
    for _ in range(3):
        with a:
            with b:
                pass
    assert ("A", "B") in w.edges
    w.assert_consistent()


def test_lock_witness_detects_inversion():
    w = LockWitness()
    a = w.wrap("A", threading.Lock())
    b = w.wrap("B", threading.Lock())
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    with pytest.raises(LockOrderError, match="cycle"):
        w.assert_consistent()


def test_lock_witness_reentrant_rlock_no_self_edge():
    w = LockWitness()
    r = w.wrap("R", threading.RLock())
    with r:
        with r:
            pass
    assert ("R", "R") not in w.edges
    w.assert_consistent()


def test_lock_witness_threaded_stacks_are_isolated():
    w = LockWitness()
    a = w.wrap("A", threading.Lock())
    b = w.wrap("B", threading.Lock())
    barrier = threading.Barrier(2)

    def t1():
        barrier.wait()
        for _ in range(50):
            with a:
                with b:
                    pass

    def t2():
        barrier.wait()
        for _ in range(50):
            with b:
                pass  # holds only B: no (B, A) edge may appear
            with a:
                pass

    ts = [threading.Thread(target=t1), threading.Thread(target=t2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert ("B", "A") not in w.edges
    w.assert_consistent()


# ---------------------------------------------------------------------------
# live tree + CLI gate
# ---------------------------------------------------------------------------


def test_live_tree_zero_non_baselined_findings():
    """The acceptance gate, in-process: the shipped tree is clean across
    all five rules modulo the checked-in baseline."""
    res = run_checks([PKG], root=REPO)
    known = baseline.load(os.path.join(REPO, "ANALYSIS_BASELINE.json"))
    new, _old = baseline.partition(res.findings, known)
    assert new == [], "\n".join(f.render() for f in new)
    assert res.parse_errors == []
    assert res.files > 50  # the walk actually covered the package


def test_cli_check_clean_tree_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"), "--check"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout


def test_cli_check_injected_violation_exits_nonzero(tmp_path):
    (tmp_path / "bad.py").write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--check", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "exception-hygiene" in proc.stdout


def test_cli_json_output(tmp_path):
    (tmp_path / "bad.py").write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--json", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["new"] == 1 and doc["baselined"] == 0
    (f,) = doc["findings"]
    assert f["rule"] == "exception-hygiene" and f["fingerprint"]
    assert sorted(doc["rules"]) == all_rule_ids()


def test_cli_emits_analysis_metric():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.argv=['analyze.py']; "
         "import importlib.util, os; "
         "spec=importlib.util.spec_from_file_location('azcli', "
         f"os.path.join({REPO!r}, 'scripts', 'analyze.py')); "
         "m=importlib.util.module_from_spec(spec); spec.loader.exec_module(m); "
         "rc=m.main([]); "
         "from roaringbitmap_tpu import observe; "
         "snap=observe.snapshot()['rb_tpu_analysis_findings_total']; "
         "assert len(snap['samples']) == 5, snap; "
         "assert snap['labelnames'] == ['rule'], snap; "
         "sys.exit(rc)"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# review regressions: sync-method form, astype(dtype=...), CLI path typos,
# damaged baseline entries
# ---------------------------------------------------------------------------


def test_trace_safety_block_until_ready_method_form(tmp_path):
    src = """import jax
@jax.jit
def f(x):
    return x.block_until_ready()
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert len(res.findings) == 1 and "block_until_ready" in res.findings[0].message


def test_dtype_astype_keyword_form(tmp_path):
    src = """# rb-payload-path
import numpy as np
def f(a):
    return a.astype(dtype=np.int32)
"""
    res = _run_snippet(tmp_path, src, rules=["dtype-discipline"])
    assert len(res.findings) == 1


def test_nonexistent_path_is_an_error(tmp_path):
    with pytest.raises(ValueError, match="not a directory or .py file"):
        run_checks([str(tmp_path / "no_such_dir")], root=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--check", "no_such_dir_typo"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_baseline_entry_without_fingerprint_rejected(tmp_path):
    p = tmp_path / "b.json"
    p.write_text('{"version": 1, "findings": [{"rule": "x"}]}')
    with pytest.raises(ValueError, match="without fingerprint"):
        baseline.load(str(p))


def test_update_baseline_refuses_scoped_runs(tmp_path):
    for extra in (["--rules", "metric-naming"], [str(tmp_path)]):
        (tmp_path / "ok.py").write_text("x = 1\n")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
             "--update-baseline", "--baseline", str(tmp_path / "b.json"), *extra],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2, (extra, proc.stdout, proc.stderr)
        assert "full default run" in proc.stderr
        assert not (tmp_path / "b.json").exists()


def test_metric_naming_flags_metric_shaped_constants_without_rb(tmp_path):
    src = 'LEGACY_TOTAL = "legacy_findings_total"\nPLAIN = "not a metric"\n'
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert [f.line for f in res.findings] == [1]


def test_lock_discipline_local_shadow_is_not_a_write(tmp_path):
    src = """import threading
_L = threading.Lock()
_POOL = None  # guarded-by: _L

def local_shadow():
    _POOL = object()  # creates a local: no shared-state write
    return _POOL

def real_write():
    global _POOL
    _POOL = object()

def locked_write():
    global _POOL
    with _L:
        _POOL = object()
"""
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert [f.line for f in res.findings] == [11]


def test_trace_safety_np_array_constant_table_ok(tmp_path):
    src = """import jax
import numpy as np
@jax.jit
def f(x):
    table = np.array([0, 1, 2], np.uint8)  # trace-time constant: fine
    return x + int(table[0])
@jax.jit
def g(x):
    return np.asarray(x)  # traced value: materializes
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert [f.line for f in res.findings] == [9]


def test_trace_safety_kernel_factory_closure_checked(tmp_path):
    src = """import jax
from jax.experimental import pallas as pl
def _make_kernel(fn):
    def kernel(ref, out):
        out[...] = ref[...].item()  # sync inside the factory's closure
    return kernel
def run(x, fn):
    return pl.pallas_call(_make_kernel(fn))(x)
def one(x):
    return x.tolist()
g = jax.jit(jax.vmap(one))
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert {f.line for f in res.findings} == {5, 10}


def test_metric_naming_cross_module_constant_needs_shaped_name(tmp_path):
    src = """from roaringbitmap_tpu import observe
from somewhere import QUERY_DEPTH, OTHER_TOTAL
A = observe.histogram(QUERY_DEPTH, "unshaped name: unverifiable")
B = observe.counter(OTHER_TOTAL, "shaped name: validated at definition")
"""
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert [f.line for f in res.findings] == [3]


def test_metric_naming_shaped_constant_definition_validated(tmp_path):
    src = 'SPAN_SECONDS = "span_seconds"\n'  # shaped NAME, bad value
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert len(res.findings) == 1


def test_metric_naming_enum_gauge_state_status_suffixes(tmp_path):
    """_STATE/_STATUS are shaped enum-gauge suffixes (ISSUE 12): cross-
    module constants wearing them are accepted (their defining module
    validates the value), and a bad definition-site value is flagged."""
    src = """from roaringbitmap_tpu import observe
from somewhere import HEALTH_STATUS, HEALTH_RULE_STATE
A = observe.gauge(HEALTH_STATUS, "shaped: validated at definition")
B = observe.gauge(HEALTH_RULE_STATE, "shaped: validated at definition", ("rule",))
"""
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


def test_metric_naming_state_status_values_need_prefix(tmp_path):
    # an enum-gauge-suffixed VALUE without the rb_tpu_ prefix is flagged
    # at its definition, exactly like the _total/_seconds shapes
    src = 'WORKER_STATUS = "worker_status"\nPOOL_STATE = "pool_state"\n'
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert len(res.findings) == 2


def test_dtype_bare_from_import_cast_flagged(tmp_path):
    src = """# rb-payload-path
from numpy import int32
def f(x):
    return int32(x)
"""
    res = _run_snippet(tmp_path, src, rules=["dtype-discipline"])
    assert len(res.findings) == 1


def test_trace_safety_callsite_static_argnames_respected(tmp_path):
    src = """import jax
def f(x, op):
    if op == "or":
        return x
    return x
g = jax.jit(f, static_argnames=("op",))
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert res.findings == []


def test_trace_safety_kwonly_params_are_traced(tmp_path):
    src = """import jax
@jax.jit
def f(x, *, y):
    if y > 0:
        return int(y)
    return x
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert len(res.findings) == 2


def test_pragma_on_continuation_line_of_wrapped_call(tmp_path):
    src = """# rb-payload-path
import numpy as np
def f(a):
    return np.cumsum(
        a, dtype=np.int32)  # rb-ok: dtype-discipline -- bounded by 2^16
"""
    res = _run_snippet(tmp_path, src, rules=["dtype-discipline"])
    assert res.findings == [] and res.suppressed == 1


def test_pragma_inside_if_body_does_not_suppress_the_if(tmp_path):
    src = """import jax
@jax.jit
def f(x):
    if x > 0:
        return x  # rb-ok: trace-safety -- pragma on body line is not the `if`
    return x
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert len(res.findings) == 1


def test_metric_naming_star_forwarding_wrapper_exempt(tmp_path):
    src = """from roaringbitmap_tpu import observe
def counter(*args, **kw):
    return observe.counter(*args, **kw)
"""
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


def test_lock_discipline_nested_global_not_attributed_to_outer(tmp_path):
    src = """import threading
_L = threading.Lock()
_G = {}  # guarded-by: _L

def outer():
    _G = {}  # local shadow: exempt, despite inner's global decl
    def inner():
        global _G
        with _L:
            _G = {}
    return _G
"""
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert res.findings == []


def test_update_baseline_refuses_unparseable_files(tmp_path, monkeypatch):
    # a default-path run can't be forced to hit a syntax error without
    # touching the package, so exercise the refusal through run_checks +
    # the CLI's parse-error contract on a scoped scan instead
    (tmp_path / "broken.py").write_text("def f(:\n")
    res = run_checks([str(tmp_path)], root=str(tmp_path))
    assert len(res.parse_errors) == 1
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2 and "parse error" in proc.stderr


def test_lock_discipline_shadowed_local_mutations_exempt(tmp_path):
    src = """import threading
_L = threading.Lock()
_POOL = []  # guarded-by: _L

def local_only():
    _POOL = []
    _POOL.append(1)
    _POOL[0] = 2
    return _POOL

def real_mutation():
    _POOL.append(1)  # no local rebind: this is the module global
"""
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert [f.line for f in res.findings] == [12]


def test_exception_pragma_on_wrapped_clause_continuation(tmp_path):
    src = """def f():
    try:
        pass
    except (ValueError,
            Exception):  # rb-ok: exception-hygiene -- probe must degrade
        pass
"""
    res = _run_snippet(tmp_path, src, rules=["exception-hygiene"])
    assert res.findings == [] and res.suppressed == 1


def test_trace_safety_bare_from_import_sync_flagged(tmp_path):
    src = """import jax
from jax import device_get
@jax.jit
def f(x):
    return device_get(x)
"""
    res = _run_snippet(tmp_path, src, rules=["trace-safety"])
    assert len(res.findings) == 1 and "device_get" in res.findings[0].message


# ---------------------------------------------------------------------------
# metric-naming: unbounded-cardinality label values (ISSUE 9 satellite)
# ---------------------------------------------------------------------------

LABEL_VALUE_SRC = """from roaringbitmap_tpu import observe
_LV_TOTAL = observe.counter("rb_tpu_lv_total", "", ("kind",))
_LV_SECONDS = observe.latency_histogram("rb_tpu_lv_seconds", "", ("stage",))
CLASS_NAMES = ("aa", "ab")
def record(kind, op, klass, ci, trace_id, qid, bm):
    _LV_TOTAL.inc(1, ("agg",))
    _LV_TOTAL.inc(1, (kind,))
    _LV_TOTAL.inc(1, (op, klass))
    _LV_TOTAL.inc(1, (CLASS_NAMES[ci],))
    _LV_TOTAL.inc(1, (str(op),))
    _LV_TOTAL.inc(1, (trace_id,))
    _LV_TOTAL.inc(1, (f"q{qid}",))
    _LV_TOTAL.inc(1, labels=(qid,))
    _LV_TOTAL.inc(1, (bm.fingerprint(),))
    _LV_SECONDS.observe(0.1, ("pack_" + op,))
"""


def test_metric_label_values_reject_unbounded_cardinality(tmp_path):
    res = _run_snippet(tmp_path, LABEL_VALUE_SRC, rules=["metric-naming"])
    by_line = {f.line for f in res.findings}
    # 11: trace_id name; 12: f-string; 13: qid via labels=; 14: call
    # result (fingerprint); 15: string concatenation. Lines 6-10 are the
    # false-positive regressions: literal, benign enumerators (the
    # existing {kind} and {op,class} label shapes), frozen-set member,
    # and str() of a benign name.
    assert by_line == {11, 12, 13, 14, 15}


def test_metric_label_values_skip_non_constant_receivers(tmp_path):
    # instance attributes and locals wearing .inc/.observe are other
    # objects (the registry's internal series dicts, CounterMap views) —
    # only module-level metric constants are in scope
    src = (
        "def f(self, trace_id, m):\n"
        "    self._metric.inc(1, (trace_id,))\n"
        "    m.observe(0.1, (trace_id,))\n"
    )
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


def test_metric_label_values_variable_labels_out_of_scope(tmp_path):
    # a labels argument that is itself a variable is aliasing — out of
    # lexical scope by design (mirrors lock-discipline's aliasing rule)
    src = (
        'from roaringbitmap_tpu import observe\n'
        '_V_TOTAL = observe.counter("rb_tpu_v_total", "", ("k",))\n'
        "def f(labels):\n"
        "    _V_TOTAL.inc(1, labels)\n"
    )
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


def test_metric_label_values_pragma_suppresses(tmp_path):
    src = (
        'from roaringbitmap_tpu import observe\n'
        '_P_TOTAL = observe.counter("rb_tpu_p_total", "", ("k",))\n'
        "def f(trace_id):\n"
        "    _P_TOTAL.inc(1, (trace_id,))  # rb-ok: metric-naming -- bounded in this test harness\n"
    )
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert res.findings == []


TENANT_LABEL_SRC = """from roaringbitmap_tpu import observe
_SV_TOTAL = observe.counter("rb_tpu_sv_total", "", ("tenant", "phase"))
_SV_SECONDS = observe.latency_histogram(
    "rb_tpu_sv_seconds", "", ("tenant", "phase"))
TENANTS = object()
def record(tenant, phase, tenant_name):
    _SV_TOTAL.inc(1, (TENANTS[tenant], phase))
    _SV_SECONDS.observe(0.1, (TENANTS[tenant], "queue"))
    _SV_TOTAL.inc(1, (tenant, phase))
    _SV_SECONDS.observe(0.1, (tenant_name, "execute"))
"""


def test_metric_label_values_tenant_needs_declared_registry(tmp_path):
    # ISSUE 14 satellite: per-tenant label VALUES must come from the
    # bounded declared tenant registry — the {tenant, phase} LABEL SETS
    # register fine (lines 2-4), the TENANTS[tenant] subscript spelling
    # passes (lines 7-8, the declared-collection escape), and the bare
    # tenant / tenant_name variables are flagged with the
    # registry-pointing message (lines 9-10)
    res = _run_snippet(tmp_path, TENANT_LABEL_SRC, rules=["metric-naming"])
    assert {f.line for f in res.findings} == {9, 10}
    assert all("tenant registry" in f.message for f in res.findings)


def test_live_serve_tree_is_clean_under_tenant_rule():
    # the serving tier itself must pass the tenant discipline it
    # motivated: every mutation spells tenant values as TENANTS[...]
    import roaringbitmap_tpu.serve.admission as sadm
    import roaringbitmap_tpu.serve.harness as sharn
    import roaringbitmap_tpu.serve.slo as sslo

    from roaringbitmap_tpu.analysis import run_checks

    res = run_checks(
        [sslo.__file__, sadm.__file__, sharn.__file__],
        rules=["metric-naming"],
    )
    assert [f for f in res.findings] == []


EPOCH_LABEL_SRC = """from roaringbitmap_tpu import observe
_EP_TOTAL = observe.counter("rb_tpu_ep_total", "", ("stage",))
_EP_SECONDS = observe.latency_histogram(
    "rb_tpu_ep_seconds", "", ("stage",))
FLIP_STAGES = ("drain", "repack")
def flip(epoch, epoch_id, si, stage):
    _EP_SECONDS.observe(0.1, (FLIP_STAGES[si],))
    _EP_TOTAL.inc(1, ("drain",))
    _EP_TOTAL.inc(1, (stage,))
    _EP_TOTAL.inc(1, (epoch,))
    _EP_SECONDS.observe(0.1, (epoch_id,))
"""


def test_metric_label_values_epoch_ids_never_labels(tmp_path):
    # ISSUE 15 satellite: epoch ids are unbounded (one per flip,
    # forever) and must never be metric label values — the declared
    # FLIP_STAGES subscript (line 7), a stage literal (line 8), and a
    # benign `stage` enumerator variable (line 9) all pass; the bare
    # epoch / epoch_id variables (lines 10-11) are flagged with the
    # ledger-pointing message
    res = _run_snippet(tmp_path, EPOCH_LABEL_SRC, rules=["metric-naming"])
    assert {f.line for f in res.findings} == {10, 11}
    assert all("epoch ledger" in f.message for f in res.findings)


def test_live_epoch_tree_is_clean_under_epoch_rule():
    # the epoch tier itself must pass the discipline it motivated: epoch
    # ids ride gauges/ledger/attrs, stage labels come from the declared
    # FLIP_STAGES set, freshness labels from TENANTS[...]
    import roaringbitmap_tpu.serve.epochs as seps
    import roaringbitmap_tpu.serve.ingest as sing

    from roaringbitmap_tpu.analysis import run_checks

    res = run_checks(
        [seps.__file__, sing.__file__], rules=["metric-naming"],
    )
    assert [f for f in res.findings] == []


FORMAT_LABEL_SRC = """from roaringbitmap_tpu import observe
_ST_CONTAINERS = observe.gauge("rb_tpu_st_containers", "", ("format",))
FORMATS = {"array": "array"}
def census(fmt, container_format):
    _ST_CONTAINERS.set(1, (FORMATS[fmt],))
    _ST_CONTAINERS.set(1, ("run",))
    _ST_CONTAINERS.set(1, (fmt,))
    _ST_CONTAINERS.set(1, (container_format,))
"""


def test_metric_label_values_format_needs_declared_set(tmp_path):
    # ISSUE 16 satellite: container-format label VALUES must come from
    # the declared frozen format set — the FORMATS[fmt] subscript (line
    # 5, the declared-collection escape) and a literal "run" (line 6)
    # pass; the bare fmt / container_format variables (lines 7-8) are
    # flagged with the format-set-pointing message
    res = _run_snippet(tmp_path, FORMAT_LABEL_SRC, rules=["metric-naming"])
    assert {f.line for f in res.findings} == {7, 8}
    assert all("declared frozen" in f.message for f in res.findings)


def test_metric_naming_containers_census_suffix(tmp_path):
    # ISSUE 16 satellite: _CONTAINERS is a shaped census-gauge suffix
    # (a live-object count by declared format) — a cross-module constant
    # wearing it is accepted, an unshaped census name is still flagged
    src = """from roaringbitmap_tpu import observe
from somewhere import STRUCTURE_CONTAINERS, STRUCTURE_CENSUS
A = observe.gauge(STRUCTURE_CONTAINERS, "shaped: validated at definition", ("format",))
B = observe.gauge(STRUCTURE_CENSUS, "unshaped name: unverifiable")
"""
    res = _run_snippet(tmp_path, src, rules=["metric-naming"])
    assert [f.line for f in res.findings] == [4]


def test_live_structure_tree_is_clean_under_format_rule():
    # the structure observatory itself must pass the discipline it
    # motivated: census label values are spelled FORMATS[fmt], the
    # maintenance tier's outcome labels are declared literals
    import roaringbitmap_tpu.observe.structure as ostr
    import roaringbitmap_tpu.serve.maintain as smnt

    from roaringbitmap_tpu.analysis import run_checks

    res = run_checks(
        [ostr.__file__, smnt.__file__], rules=["metric-naming"],
    )
    assert [f for f in res.findings] == []


def test_live_tree_has_no_unbounded_label_values():
    # the rule runs over the real package in test_live_tree_is_clean-style
    # gates elsewhere; pin here that the columnar fold labels (the one
    # computed-label site this PR converted to a declared mapping) stay
    # clean under the extended rule
    import roaringbitmap_tpu.columnar.engine as eng

    from roaringbitmap_tpu.analysis import run_checks

    res = run_checks([eng.__file__], rules=["metric-naming"])
    assert [f for f in res.findings] == []


# ---------------------------------------------------------------------------
# whole-program contract tier (ISSUE 18): ProjectContext + contract rules
# ---------------------------------------------------------------------------


def _mini_project(tmp_path, files, root_files=None):
    """A synthetic package tree under tmp_path/pkg for contract-rule
    fixtures; ``root_files`` land beside the package (docs, KNOBS.md)."""
    for rel, src in files.items():
        p = tmp_path / "pkg" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    for rel, src in (root_files or {}).items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return ProjectContext(str(tmp_path), package="pkg")


def _contract(project, rule):
    return run_contract_checks(project, rules=[rule])


def test_contract_rules_registered():
    assert all_contract_rule_ids() == [
        "authority-surface",
        "decision-discipline",
        "epoch-pin",
        "fault-site-contract",
        "knob-doc",
        "metric-discipline",
        "sentinel-table-drift",
        "use-after-donation",
    ]


def test_contract_rule_ids_disjoint_from_lexical():
    assert not set(all_contract_rule_ids()) & set(all_rule_ids())


# -- fault-site-contract ----------------------------------------------------

_FAULT_FILES = {
    "robust/faults.py": 'SITES = (\n    "a.ok",\n    "a.bad",\n)\n',
    "mod.py": (
        "def f():\n"
        '    fault_point("a.ok")\n'
        '    LADDER.run("a.ok", None)\n'
        "def g():\n"
        '    fault_point("a.rogue")\n'
    ),
    "fuzz.py": '_EXERCISED = "a.ok"\n',
}


def test_fault_site_contract_seeded_mutants(tmp_path):
    # a.bad is declared but has no guard, no route, no exercise (3
    # findings on its SITES line); a.rogue is guarded but undeclared
    # (reverse finding on the call)
    project = _mini_project(tmp_path, _FAULT_FILES)
    res = _contract(project, "fault-site-contract")
    by_path = {}
    for f in res.findings:
        by_path.setdefault(os.path.basename(f.path), []).append(f)
    assert [f.line for f in by_path["faults.py"]] == [3, 3, 3]
    assert all("a.bad" in f.message for f in by_path["faults.py"])
    (rogue,) = by_path["mod.py"]
    assert rogue.line == 5 and "undeclared" in rogue.message


def test_fault_site_contract_waiver_pragma(tmp_path):
    files = dict(_FAULT_FILES)
    files["robust/faults.py"] = (
        "SITES = (\n"
        '    "a.ok",\n'
        '    "a.bad",  # rb-ok: fault-site-contract -- rides a.ok\n'
        ")\n"
    )
    files["mod.py"] = _FAULT_FILES["mod.py"].replace(
        'fault_point("a.rogue")', 'fault_point("a.ok")'
    )
    project = _mini_project(tmp_path, files)
    res = _contract(project, "fault-site-contract")
    assert res.findings == []
    assert res.suppressed == 3


def test_fault_site_contract_empty_registry_is_loud(tmp_path):
    project = _mini_project(
        tmp_path, {"robust/faults.py": "SITES = ()\nX = 1\n"}
    )
    res = _contract(project, "fault-site-contract")
    assert len(res.findings) == 1
    assert "could not extract" in res.findings[0].message


def test_live_fault_registry_extraction():
    project = get_project(REPO)
    assert "store.ship" in project.fault_sites
    assert len(project.fault_sites) >= 14
    # every declared site is guarded somewhere outside faults.py
    faults_rel = project.pkg_path("robust", "faults.py")
    for site in project.fault_sites:
        assert any(
            p != faults_rel for p, _ in project.fault_guards.get(site, ())
        ), site


# -- decision-discipline ----------------------------------------------------

_DECISION_SRC = """\
def discarded():
    record_decision("s.a", {"v": 1}, outcome=True)

def dropped():
    seq = record_decision("s.b", {"v": 1}, outcome=True)
    return None

def joined(t):
    seq = record_decision("s.c", {"v": 1}, outcome=True)
    resolve(seq, measured_s=t)

def threaded():
    return run_with(outcome_seq=record_decision("s.d", {}, outcome=True))

def fire_and_forget():
    record_decision("s.e", {"v": 1}, outcome=False)

def dynamic(flag):
    record_decision("s.f", {"v": 1}, outcome=flag)
"""


def test_decision_discipline_seeded_mutants(tmp_path):
    project = _mini_project(tmp_path, {"mod.py": _DECISION_SRC})
    res = _contract(project, "decision-discipline")
    assert [(f.line, f.message.split("'")[1]) for f in res.findings] == [
        (2, "s.a"),
        (5, "s.b"),
    ]
    assert "discards" in res.findings[0].message
    assert "never reads" in res.findings[1].message


def test_decision_discipline_pragma(tmp_path):
    src = (
        "def fire():\n"
        '    record_decision("s.a", {}, outcome=True)'
        "  # rb-ok: decision-discipline -- probe decision, join not wanted\n"
    )
    project = _mini_project(tmp_path, {"mod.py": src})
    res = _contract(project, "decision-discipline")
    assert res.findings == [] and res.suppressed == 1


# -- use-after-donation (CFG dataflow) --------------------------------------

_DONATE_SRC = """\
import functools, jax

@functools.partial(jax.jit, donate_argnums=(0,))
def scatter_rows_donated(d, rows):
    return d

def bad(d, rows):
    out = scatter_rows_donated(d, rows)
    return d.shape

def blessed(d, rows):
    d = scatter_rows_donated(d, rows)
    return d.shape

def loop_bad(d, rows):
    x = None
    for r in rows:
        x = scatter_rows_donated(d, r)
    return x

def loop_blessed(d, rows):
    for r in rows:
        d = scatter_rows_donated(d, r)
    return d

def branch_bad(d, rows, flag):
    if flag:
        x = scatter_rows_donated(d, rows)
    return d.nbytes
"""


def test_use_after_donation_seeded_mutants(tmp_path):
    project = _mini_project(tmp_path, {"dn.py": _DONATE_SRC})
    res = _contract(project, "use-after-donation")
    lines = sorted(f.line for f in res.findings)
    # bad: read d.shape after donation (line 9); loop_bad: the loop back
    # edge carries the donation into the next iteration's call (line 18);
    # branch_bad: the donated branch reaches the join's read (line 29)
    assert lines == [9, 18, 29]
    assert all("`d`" in f.message for f in res.findings)


def test_use_after_donation_pragma(tmp_path):
    src = _DONATE_SRC.replace(
        "    return d.shape\n\ndef blessed",
        "    return d.shape  # rb-ok: use-after-donation -- metadata probe\n"
        "\ndef blessed",
        1,
    ).replace(
        "    return x\n",
        "    return x  # noqa\n",
    )
    # keep only the first two functions for a focused waiver check
    src = src.split("def loop_bad")[0]
    project = _mini_project(tmp_path, {"dn.py": src})
    res = _contract(project, "use-after-donation")
    assert res.findings == [] and res.suppressed == 1


# -- epoch-pin (serve/ execution discipline) --------------------------------

_EPOCH_SRC = """\
import contextlib

def pinned(store, _exec, expr):
    with store.reader() as tk:
        return _exec.execute(expr)

def conditional(store, _exec, expr):
    pin = (store.reader() if store is not None else contextlib.nullcontext())
    with pin as tk:
        return _exec.execute(expr)

def unpinned(_exec, expr):
    return _exec.execute(expr)

def pooled(executor, expr):
    return executor.submit(expr)

def ingest_write(epoch_store, muts):
    return epoch_store.submit("tenant", muts)
"""


def test_epoch_pin_seeded_mutants(tmp_path):
    project = _mini_project(tmp_path, {"serve/h.py": _EPOCH_SRC})
    res = _contract(project, "epoch-pin")
    # the direct pin and the conditional-pin idiom pass; the bare execute
    # and the executor submit fail; the ingest-log submit (write path) is
    # not an execution call
    assert sorted(f.line for f in res.findings) == [13, 16]


def test_epoch_pin_ignores_non_serve_files(tmp_path):
    project = _mini_project(tmp_path, {"ops/h.py": _EPOCH_SRC})
    res = _contract(project, "epoch-pin")
    assert res.findings == []


def test_epoch_pin_pragma(tmp_path):
    src = _EPOCH_SRC.replace(
        "    return _exec.execute(expr)\n\ndef pooled",
        "    return _exec.execute(expr)  # rb-ok: epoch-pin -- serial oracle\n"
        "\ndef pooled",
    ).split("def pooled")[0]
    project = _mini_project(tmp_path, {"serve/h.py": src})
    res = _contract(project, "epoch-pin")
    assert res.findings == [] and res.suppressed == 1


# -- lock-discipline may-hold upgrade ---------------------------------------

_MAYHOLD_SRC = """\
import threading
_L = threading.Lock()
_N = {}  # guarded-by: _L

def _bump(k):
    _N[k] = 1

def locked_caller(k):
    with _L:
        _bump(k)
"""


def test_lock_mayhold_all_callers_locked(tmp_path):
    # the helper writes guarded state with no lexical `with`, but every
    # intra-module call site holds the lock — the may-hold propagation
    # clears what the lexical rule alone would flag
    res = _run_snippet(tmp_path, _MAYHOLD_SRC, rules=["lock-discipline"])
    assert res.findings == []


def test_lock_mayhold_one_unlocked_caller_flags(tmp_path):
    src = _MAYHOLD_SRC + "\ndef sneaky(k):\n    _bump(k)\n"
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert [f.line for f in res.findings] == [6]
    assert "guarded-by" in res.findings[0].message


def test_lock_mayhold_escaped_helper_flags(tmp_path):
    # a helper that escapes as a value (callback) can be invoked from
    # anywhere — the propagation must not assume its callers' locks
    src = _MAYHOLD_SRC + "\nCALLBACK = _bump\n"
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert [f.line for f in res.findings] == [6]


def test_lock_mayhold_transitive_chain(tmp_path):
    # locked caller -> middle helper -> writer: entry locks propagate
    # through the chain's intersection
    src = (
        _MAYHOLD_SRC
        + "\ndef _middle(k):\n    _bump(k)\n"
        + "\ndef outer(k):\n    with _L:\n        _middle(k)\n"
    )
    res = _run_snippet(tmp_path, src, rules=["lock-discipline"])
    assert res.findings == []


# -- registry contracts: metric / sentinel / authority / knob ---------------

def test_metric_discipline_seeded_mutants(tmp_path):
    files = {
        "observe/registry.py": (
            'GOOD_TOTAL = "rb_tpu_good_total"\n'
            'DEAD_TOTAL = "rb_tpu_dead_total"\n'
            "def counter(name, help, labels=()):\n    pass\n"
        ),
        "obs_use.py": (
            "from .observe import registry\n"
            'C = registry.counter(registry.GOOD_TOTAL, "h", ("op",))\n'
            'D = registry.counter("rb_tpu_inline_total", "h")\n'
            'E = registry.counter(registry.GOOD_TOTAL, "h", ("kind",))\n'
        ),
    }
    project = _mini_project(tmp_path, files)
    res = _contract(project, "metric-discipline")
    msgs = sorted(f.message for f in res.findings)
    assert len(res.findings) == 3
    assert any("DEAD_TOTAL" in m and "never referenced" in m for m in msgs)
    assert any("rb_tpu_inline_total" in m for m in msgs)
    assert any("label" in m for m in msgs)


def test_sentinel_table_drift_seeded_mutants(tmp_path):
    files = {
        "observe/health.py": (
            '"""Rules:\n'
            "\n"
            "alpha-drift      geomean over window\n"
            "beta-stall       p99 over budget\n"
            '"""\n'
            "class Rule:\n"
            "    def __init__(self, name, x):\n        pass\n"
            "DEFAULT_RULES = (\n"
            '    Rule("alpha-drift", 1),\n'
            '    Rule("gamma-new", 2),\n'
            ")\n"
        ),
    }
    project = _mini_project(tmp_path, files)
    res = _contract(project, "sentinel-table-drift")
    msgs = " | ".join(f.message for f in res.findings)
    assert len(res.findings) == 2
    assert "gamma-new" in msgs and "beta-stall" in msgs


def test_authority_surface_seeded_mutants(tmp_path):
    facade = (
        '"""Authorities:\n'
        "\n"
        "| authority | role |\n"
        "|-----------|------|\n"
        "| alpha     | x    |\n"
        '"""\n'
        "class Authority:\n"
        '    name = ""\n'
        "class AlphaAuthority(Authority):\n"
        '    name = "alpha"\n'
        "    def curves(self):\n        pass\n"
        "    def provenance(self):\n        pass\n"
        "    def refit_from_outcomes(self):\n        pass\n"
        "    def state(self):\n        pass\n"
        "    def load_state(self, s):\n        pass\n"
        "    def reset(self):\n        pass\n"
        "class BetaAuthority(Authority):\n"
        '    name = "beta"\n'
        "    def curves(self):\n        pass\n"
        'AUTHORITIES = {"alpha": AlphaAuthority(), "beta": BetaAuthority()}\n'
    )
    project = _mini_project(
        tmp_path,
        {"cost/facade.py": facade},
        root_files={"ARCHITECTURE.md": "the alpha authority\n"},
    )
    res = _contract(project, "authority-surface")
    # beta: incomplete lifecycle protocol, absent from the facade doc
    # table, absent from ARCHITECTURE.md — all anchored on its name line
    assert len(res.findings) == 3
    assert all("beta" in f.message for f in res.findings)
    assert {f.line for f in res.findings} == {24}


def test_live_authority_registry_extraction():
    project = get_project(REPO)
    assert len(project.authorities) >= 8
    assert all(a.registered for a in project.authorities)


def test_knob_doc_seeded_mutants(tmp_path):
    files = {
        "mod.py": 'import os\nV = os.environ.get("RB_TPU_X", "1")\n',
    }
    # no KNOBS.md at all: the read is undocumented
    project = _mini_project(tmp_path, files)
    res = _contract(project, "knob-doc")
    assert len(res.findings) == 1
    assert "RB_TPU_X" in res.findings[0].message
    # a table with the knob plus a stale row: only the stale row flags
    project = _mini_project(
        tmp_path,
        files,
        root_files={
            "KNOBS.md": "| `RB_TPU_X` | 1 | m | d |\n| `RB_TPU_GONE` | - | m | d |\n"
        },
    )
    res = _contract(project, "knob-doc")
    assert len(res.findings) == 1
    assert "RB_TPU_GONE" in res.findings[0].message


def test_knob_extractor_shapes():
    # every env-read idiom in the tree is caught: environ.get, getenv,
    # typed _env_* wrappers, and environ[...] subscripts
    project = get_project(REPO)
    assert len(project.knobs) >= 27
    for knob in ("RB_TPU_FAULTS", "RB_TPU_OUTCOMES_CAPACITY",
                 "RB_TPU_COST_STATE", "RB_TPU_SERVE_INFLIGHT"):
        assert knob in project.knobs, knob


def test_knobs_render_matches_committed_table():
    # the ci.sh --check-knobs gate, as a unit test: KNOBS.md is exactly
    # what the extractor renders for the current tree
    project = get_project(REPO)
    rendered = knobs_mod.render(project)
    with open(os.path.join(REPO, knobs_mod.KNOBS_DOC), encoding="utf-8") as f:
        committed = f.read()
    assert rendered == committed
    assert knobs_mod.documented_knobs(rendered) == set(project.knobs)


def test_knobs_render_rejects_undocumented_knob(tmp_path):
    project = _mini_project(
        tmp_path,
        {"mod.py": 'import os\nV = os.getenv("RB_TPU_NOT_A_REAL_KNOB")\n'},
    )
    with pytest.raises(ValueError, match="RB_TPU_NOT_A_REAL_KNOB"):
        knobs_mod.render(project)


# -- ProjectContext cache ----------------------------------------------------

def test_get_project_cache_reuse_and_invalidation(tmp_path):
    (tmp_path / "pkg").mkdir()
    f = tmp_path / "pkg" / "m.py"
    f.write_text("x = 1\n")
    p1 = get_project(str(tmp_path), package="pkg")
    p2 = get_project(str(tmp_path), package="pkg")
    assert p1 is p2
    f.write_text("x = 2  # changed: different size -> different stamp\n")
    p3 = get_project(str(tmp_path), package="pkg")
    assert p3 is not p1
    assert get_project(str(tmp_path), package="pkg") is p3


def test_get_project_thread_hammer(tmp_path):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "m.py").write_text(
        'import os\nV = os.getenv("RB_TPU_TIMELINE")\n'
    )
    errs = []
    results = []

    def worker():
        try:
            for _ in range(25):
                p = get_project(str(tmp_path), package="pkg")
                assert "RB_TPU_TIMELINE" in p.knobs
                results.append(p)
        except Exception as e:  # pragma: no cover - the assertion IS the test
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert results
    # after the stampede settles, the cache serves one instance
    assert get_project(str(tmp_path), package="pkg") is get_project(
        str(tmp_path), package="pkg"
    )


# -- live tree + CLI ---------------------------------------------------------

def test_live_tree_contract_tier_green():
    # the ISSUE 18 acceptance gate as a unit test: zero unwaived contract
    # findings on the real tree (waivers ride # rb-ok: pragmas)
    project = get_project(REPO)
    res = run_contract_checks(project)
    assert res.parse_errors == []
    assert res.findings == []


def test_cli_contracts_and_knobs_gate():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--check", "--contracts"],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "[lexical+contracts]" in p.stdout
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--check-knobs"],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_diff_mode_scopes_lexical_tier():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--check", "--contracts", "--diff", "HEAD"],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_update_baseline_refuses_diff_scope(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--update-baseline", "--diff", "HEAD",
         "--baseline", str(tmp_path / "b.json")],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert p.returncode == 2
    assert "full default run" in p.stderr
