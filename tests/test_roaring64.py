"""64-bit layer tests incl. byte-level parity with the CRoaring-written
portable golden files (reference oracle: TestRoaring64NavigableMap.java:1644+)."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import Roaring64Bitmap
from roaringbitmap_tpu import InvalidRoaringFormat

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_testdata = pytest.mark.skipif(
    not os.path.isdir(TESTDATA), reason="reference golden files not mounted"
)

MAXINT = (1 << 32) - 1


def random_values64(rng, n=5000):
    highs = rng.choice([0, 1, 5, 1 << 20, (1 << 32) - 1], size=n)
    lows = rng.integers(0, 1 << 32, size=n, dtype=np.uint64)
    return (highs.astype(np.uint64) << np.uint64(32)) | lows


def test_point_ops():
    bm = Roaring64Bitmap()
    big = (1 << 63) + 12345
    bm.add(0)
    bm.add(big)
    bm.add((1 << 64) - 1)
    assert bm.contains(big) and bm.contains(0) and bm.contains((1 << 64) - 1)
    assert not bm.contains(1)
    assert bm.get_cardinality() == 3
    bm.remove(big)
    assert not bm.contains(big)
    with pytest.raises(ValueError):
        bm.add(1 << 64)
    with pytest.raises(ValueError):
        bm.add(-1)


def test_add_many_to_array(rng):
    vals = random_values64(rng)
    bm = Roaring64Bitmap(vals)
    assert np.array_equal(bm.to_array(), np.unique(vals))
    assert bm.get_cardinality() == np.unique(vals).size


def test_algebra(rng):
    v1, v2 = random_values64(rng), random_values64(rng)
    b1, b2 = Roaring64Bitmap(v1), Roaring64Bitmap(v2)
    s1, s2 = set(v1.tolist()), set(v2.tolist())
    assert set((b1 | b2).to_array().tolist()) == s1 | s2
    assert set((b1 & b2).to_array().tolist()) == s1 & s2
    assert set((b1 ^ b2).to_array().tolist()) == s1 ^ s2
    assert set((b1 - b2).to_array().tolist()) == s1 - s2
    assert b1.intersects(b2) == bool(s1 & s2)
    c = b1.clone()
    c |= b2
    assert set(c.to_array().tolist()) == s1 | s2
    # inputs unchanged by static ops
    assert set(b1.to_array().tolist()) == s1


def test_rank_select_navigation(rng):
    vals = np.unique(random_values64(rng, 2000))
    bm = Roaring64Bitmap(vals)
    for j in [0, len(vals) // 2, len(vals) - 1]:
        assert bm.select(j) == vals[j]
        assert bm.rank(int(vals[j])) == j + 1
    assert bm.first() == vals[0]
    assert bm.last() == vals[-1]
    mid = int(vals[len(vals) // 2])
    assert bm.next_value(mid) == mid
    assert bm.previous_value(mid) == mid
    with pytest.raises(IndexError):
        bm.select(len(vals))


def test_ranges():
    bm = Roaring64Bitmap()
    start = (1 << 33) - 100
    bm.add_range(start, start + 200)  # crosses a high-32 bucket boundary
    assert bm.get_cardinality() == 200
    assert bm.get_high_to_bitmap_count() == 2
    assert bm.contains(start) and bm.contains(start + 199)
    bm.remove_range(start + 50, start + 150)
    assert bm.get_cardinality() == 100
    bm.flip_range(start, start + 50)
    assert bm.get_cardinality() == 50


def test_serialization_roundtrip(rng):
    vals = random_values64(rng)
    bm = Roaring64Bitmap(vals)
    bm.run_optimize()
    data = bm.serialize()
    assert len(data) == bm.serialized_size_in_bytes()
    back = Roaring64Bitmap.deserialize(data)
    assert back == bm
    assert back.serialize() == data


@needs_testdata
def test_golden_64map_files():
    """Byte-level parity with CRoaring-written portable files
    (TestRoaring64NavigableMap.java:1644-1731 expectations)."""
    with open(os.path.join(TESTDATA, "64mapempty.bin"), "rb") as f:
        data = f.read()
    bm = Roaring64Bitmap.deserialize(data)
    assert bm.get_cardinality() == 0
    assert bm.serialize() == data

    with open(os.path.join(TESTDATA, "64map32bitvals.bin"), "rb") as f:
        data = f.read()
    bm = Roaring64Bitmap.deserialize(data)
    assert bm.get_cardinality() == 10
    assert bm.get_high_to_bitmap_count() == 1
    assert bm.select(0) == 0 and bm.select(9) == 9
    assert bm.serialize() == data

    with open(os.path.join(TESTDATA, "64mapspreadvals.bin"), "rb") as f:
        data = f.read()
    bm = Roaring64Bitmap.deserialize(data)
    assert bm.get_cardinality() == 100
    assert bm.get_high_to_bitmap_count() == 10
    assert bm.select(90) == (9 << 32) + 0
    assert bm.select(99) == (9 << 32) + 9
    assert bm.serialize() == data

    with open(os.path.join(TESTDATA, "64maphighvals.bin"), "rb") as f:
        data = f.read()
    bm = Roaring64Bitmap.deserialize(data)
    assert bm.get_cardinality() == 121
    assert bm.get_high_to_bitmap_count() == 11
    assert bm.select(0) == ((MAXINT - 10) << 32) + (MAXINT - 10)
    assert bm.select(120) == (MAXINT << 32) + MAXINT
    assert bm.serialize() == data


def test_bad_input_rejected():
    with pytest.raises(InvalidRoaringFormat):
        Roaring64Bitmap.deserialize(b"\x00\x00")
    with pytest.raises(InvalidRoaringFormat):
        Roaring64Bitmap.deserialize(b"\xff" * 8)  # implausible bucket count


def test_add_many_rejects_negative():
    """Signed arrays with negatives must not wrap (code-review regression)."""
    bm = Roaring64Bitmap()
    with pytest.raises((ValueError, OverflowError)):
        bm.add_many(np.array([-1], dtype=np.int64))
    with pytest.raises((ValueError, OverflowError)):
        bm.add_many([5, -3])
    assert bm.is_empty()


def test_fast_aggregation64_engines_agree():
    """64-bit N-way or/xor/and: device-batched groups == CPU word folds ==
    pairwise reference folds, across several high-48 chunks and buckets."""
    import numpy as np

    from roaringbitmap_tpu import FastAggregation64, Roaring64Bitmap

    rng = np.random.default_rng(29)
    bms = []
    for i in range(12):
        parts = [
            rng.integers(0, 1 << 18, size=4000, dtype=np.uint64),
            (np.uint64(i % 3) << np.uint64(33))
            + rng.integers(0, 1 << 17, size=3000, dtype=np.uint64),
            (np.uint64(1) << np.uint64(55))
            + rng.integers(0, 1 << 16, size=2000, dtype=np.uint64),
        ]
        bms.append(Roaring64Bitmap(np.concatenate(parts)))

    # pairwise oracle
    want_or = bms[0].clone()
    for b in bms[1:]:
        want_or = Roaring64Bitmap.or_(want_or, b)
    want_xor = bms[0].clone()
    for b in bms[1:]:
        want_xor = Roaring64Bitmap.xor(want_xor, b)
    want_and = bms[0].clone()
    for b in bms[1:]:
        want_and = Roaring64Bitmap.and_(want_and, b)

    for mode in ("cpu", "device"):
        assert FastAggregation64.or_(*bms, mode=mode).serialize() == want_or.serialize(), mode
        assert FastAggregation64.xor(*bms, mode=mode).serialize() == want_xor.serialize(), mode
        assert FastAggregation64.and_(*bms, mode=mode).serialize() == want_and.serialize(), mode
    # cardinality-only engines (device path fetches only per-group counts)
    for mode in ("cpu", "device"):
        assert FastAggregation64.or_cardinality(*bms, mode=mode) == want_or.get_cardinality()
        assert FastAggregation64.xor_cardinality(*bms, mode=mode) == want_xor.get_cardinality()
        assert FastAggregation64.and_cardinality(*bms, mode=mode) == want_and.get_cardinality()
    # edge cases
    assert FastAggregation64.or_().is_empty()
    assert FastAggregation64.and_(bms[0]).serialize() == bms[0].serialize()
    disjoint = Roaring64Bitmap(np.array([1 << 60], dtype=np.uint64))
    assert FastAggregation64.and_(bms[0], disjoint).is_empty()
    assert FastAggregation64.and_cardinality(bms[0], disjoint) == 0
    assert FastAggregation64.or_cardinality() == 0


def test_or_navigable_bucketwise_engines():
    """NavigableMap wide-OR routes each high-32 bucket through the 32-bit
    engine; cpu and device modes equal the pairwise fold, signed order
    preserved."""
    import numpy as np

    from roaringbitmap_tpu import Roaring64NavigableMap
    from roaringbitmap_tpu.parallel.aggregation64 import or_navigable

    rng = np.random.default_rng(31)
    ms = []
    for i in range(10):
        vals = np.concatenate(
            [
                rng.integers(0, 1 << 20, size=5000, dtype=np.uint64),
                (np.uint64(2 + (i % 3)) << np.uint64(32))
                + rng.integers(0, 1 << 20, size=4000, dtype=np.uint64),
            ]
        )
        ms.append(Roaring64NavigableMap(vals))
    want = ms[0].clone()
    for m in ms[1:]:
        want.ior(m)
    for mode in ("cpu", "device"):
        got = or_navigable(*ms, mode=mode)
        assert got.serialize() == want.serialize(), mode
        assert got.get_long_cardinality() == want.get_long_cardinality()
    assert or_navigable().is_empty()
    one = or_navigable(ms[0])
    assert one.serialize() == ms[0].serialize()
    # signed order + supplier config follow the first operand
    a = Roaring64NavigableMap([1, (1 << 63) + 5], signed_longs=True)
    b = Roaring64NavigableMap([2, (1 << 63) + 7], signed_longs=True)
    sgot = or_navigable(a, b)
    assert sgot.signed_longs
    swant = a.clone()
    swant.ior(b)
    assert sgot.serialize() == swant.serialize()
    assert sgot.first() == swant.first()  # signed order: negative first


def test_contains_many_64bit_both_designs():
    """Vectorized membership on both 64-bit designs agrees with per-value
    contains, across buckets, absent chunks, and 2^63+ values."""
    from roaringbitmap_tpu.models.roaring64 import Roaring64NavigableMap
    from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap

    vals = np.array(
        [1, 2, (1 << 40) + 5, (1 << 63) + 9, (1 << 16) + 1, 1 << 48], dtype=np.uint64
    )
    for cls in (Roaring64Bitmap, Roaring64NavigableMap):
        bm = cls(vals)
        probe = np.concatenate([vals, vals + np.uint64(1), np.array([0, 1 << 50], dtype=np.uint64)])
        got = bm.contains_many(probe)
        want = np.array([bm.contains(int(p)) for p in probe])
        assert np.array_equal(got, want), cls.__name__
        assert bm.contains_many(np.array([], dtype=np.uint64)).size == 0
        # negative ints = two's-complement bit patterns (Java long semantics)
        neg = bm.contains_many(np.array([-1], dtype=np.int64))
        assert neg[0] == bm.contains((1 << 64) - 1)


def test_stream_serialization_64bit():
    """Stream overloads on both 64-bit designs and the 64-bit BSI: mixed
    objects written back-to-back on one stream read back exactly, leaving
    the position at the next byte (the reference's DataOutput/DataInput
    path; Roaring64Bitmap.java:880, Roaring64NavigableMap Externalizable)."""
    import io

    from roaringbitmap_tpu.models.bsi64 import Roaring64BitmapSliceIndex
    from roaringbitmap_tpu.models.roaring64 import (
        SERIALIZATION_MODE_LEGACY,
        Roaring64NavigableMap,
    )
    from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap

    vals = np.array([1, (1 << 40) + 5, (1 << 63) + 9], dtype=np.uint64)
    art = Roaring64Bitmap(vals)
    nav = Roaring64NavigableMap(vals)
    bsi = Roaring64BitmapSliceIndex()
    bsi.set_values(([3, (1 << 45) + 1], [7, (1 << 33) + 2]))
    buf = io.BytesIO()
    n1 = art.serialize_into(buf)
    n2 = nav.serialize_into(buf)
    n3 = nav.serialize_into(buf, mode=SERIALIZATION_MODE_LEGACY)
    n4 = bsi.serialize_into(buf)
    assert buf.tell() == n1 + n2 + n3 + n4
    buf.seek(0)
    assert Roaring64Bitmap.deserialize_from(buf) == art
    assert Roaring64NavigableMap.deserialize_from(buf) == nav
    assert (
        Roaring64NavigableMap.deserialize_from(buf, mode=SERIALIZATION_MODE_LEGACY)
        == nav
    )
    back = Roaring64BitmapSliceIndex.deserialize_from(buf)
    assert back == bsi and buf.read() == b""


def test_stream_deserialize_survives_short_reads():
    """Socket/pipe semantics: read(n) may legally return fewer bytes; the
    stream readers must loop, not report truncation (code-review r4)."""
    import io

    from roaringbitmap_tpu.models.bsi64 import Roaring64BitmapSliceIndex
    from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap

    class Dribble(io.RawIOBase):
        def __init__(self, data):
            self._b = io.BytesIO(data)

        def read(self, n=-1):
            return self._b.read(min(n, 1) if n and n > 0 else n)

    art = Roaring64Bitmap(np.array([1, (1 << 40) + 5], dtype=np.uint64))
    bsi = Roaring64BitmapSliceIndex()
    bsi.set_values(([2, (1 << 33)], [5, 1 << 20]))
    buf = io.BytesIO()
    art.serialize_into(buf)
    bsi.serialize_into(buf)
    stream = Dribble(buf.getvalue())
    assert Roaring64Bitmap.deserialize_from(stream) == art
    assert Roaring64BitmapSliceIndex.deserialize_from(stream) == bsi


def test_rank_many_64_matches_scalar():
    """Bulk rank on both 64-bit designs == scalar rank, across unsigned
    AND signed comparator order, probes in/out of buckets, and the
    above-2^63 band."""
    import numpy as np

    from roaringbitmap_tpu import Roaring64Bitmap, Roaring64NavigableMap

    rng = np.random.default_rng(61)
    vals = np.unique(
        np.concatenate(
            [
                rng.integers(0, 1 << 20, 8_000, dtype=np.uint64),
                rng.integers(0, 1 << 42, 5_000, dtype=np.uint64),
                np.uint64(1 << 63) + rng.integers(0, 1 << 16, 1_500, dtype=np.uint64),
            ]
        )
    )
    probes = np.concatenate(
        [
            vals[::7][:300],
            rng.integers(0, 1 << 43, 400, dtype=np.uint64),
            np.array([0, (1 << 64) - 1], dtype=np.uint64),
        ]
    )
    art = Roaring64Bitmap()
    art.add_many(vals)
    assert art.rank_many(probes).tolist() == [art.rank(int(p)) for p in probes]
    assert art.rank_many([]).size == 0
    for signed in (False, True):
        nav = Roaring64NavigableMap(signed_longs=signed)
        nav.add_many(vals)
        want = [nav.rank(int(p)) for p in probes]
        assert nav.rank_many(probes).tolist() == want, signed
    assert Roaring64NavigableMap().rank_many(probes).tolist() == [0] * probes.size


def test_select_many_64_matches_scalar():
    """Bulk select on both 64-bit designs == scalar select, comparator
    orders included, and inverse with rank_many."""
    import numpy as np
    import pytest

    from roaringbitmap_tpu import Roaring64Bitmap, Roaring64NavigableMap

    rng = np.random.default_rng(67)
    vals = np.unique(
        np.concatenate(
            [
                rng.integers(0, 1 << 42, 10_000, dtype=np.uint64),
                np.uint64(1 << 63) + rng.integers(0, 1 << 16, 1_000, dtype=np.uint64),
            ]
        )
    )
    ranks = np.concatenate([rng.integers(0, vals.size, 500), [0, vals.size - 1]])
    art = Roaring64Bitmap()
    art.add_many(vals)
    assert art.select_many(ranks).tolist() == [art.select(int(j)) for j in ranks]
    assert np.array_equal(art.rank_many(art.select_many(ranks)), ranks + 1)
    for signed in (False, True):
        nav = Roaring64NavigableMap(signed_longs=signed)
        nav.add_many(vals)
        assert nav.select_many(ranks).tolist() == [nav.select(int(j)) for j in ranks]
    with pytest.raises(IndexError):
        art.select_many([vals.size])
    assert art.select_many([]).size == 0
