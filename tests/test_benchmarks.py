"""Benchmark smoke tests — twin of jmh/src/test
(RealDataBenchmark{Or,And,HorizontalOr,...}Test): every suite runs with
tiny reps, and the realdata engines' outputs are asserted equal to the
naive fold before any timing is trusted."""

import numpy as np
import pytest

from benchmarks import SUITES, common
from roaringbitmap_tpu.models.buffer import BufferFastAggregation
from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu.parallel.aggregation import FastAggregation, ParallelAggregation


@pytest.fixture(scope="module")
def small_corpus(monkeypatch_module):
    # cap corpora so the whole smoke pass stays fast
    orig = common.corpus

    def capped(name, limit=None):
        return orig(name, limit=min(limit or 40, 40))

    monkeypatch_module.setattr(common, "corpus", capped)
    common._bitmap_cache.clear()
    return capped


@pytest.fixture(scope="module")
def monkeypatch_module():
    from _pytest.monkeypatch import MonkeyPatch

    mp = MonkeyPatch()
    yield mp
    mp.undo()


def test_realdata_engines_agree_with_naive(small_corpus):
    bms = common.corpus_bitmaps("census1881", limit=30)
    want = FastAggregation.naive_or(*bms)
    assert FastAggregation.or_(*bms, mode="cpu") == want
    assert FastAggregation.or_(*bms, mode="device") == want
    assert FastAggregation.horizontal_or(*bms) == want
    assert FastAggregation.priorityqueue_or(*bms) == want
    assert ParallelAggregation.or_(*bms, mode="cpu") == want
    assert ParallelAggregation.or_(*bms, mode="device") == want
    want_and = FastAggregation.naive_and(*bms)
    assert FastAggregation.workshy_and(*bms, mode="cpu") == want_and
    assert FastAggregation.workshy_and(*bms, mode="device") == want_and
    # cardinality-only engines on the same real-data group distributions
    assert FastAggregation.or_cardinality(*bms, mode="device") == want.get_cardinality()
    assert FastAggregation.and_cardinality(*bms, mode="device") == want_and.get_cardinality()
    blobs = [b.serialize() for b in bms]
    mapped = [ImmutableRoaringBitmap(x) for x in blobs]
    assert BufferFastAggregation.or_(*mapped) == want


@pytest.mark.parametrize("suite", SUITES + ["simplebenchmark"])
def test_suite_runs(suite, small_corpus, monkeypatch):
    import importlib

    mod = importlib.import_module(f"benchmarks.{suite}")
    # shrink the heavy builders for smoke purposes
    for attr, small in (
        ("N_ROWS", 5000),
        ("N", 20_000),
        ("N_DOCS", 50_000),
        ("N_QUERIES", 8),
        ("TOP_K", 200),
    ):
        if hasattr(mod, attr):
            monkeypatch.setattr(mod, attr, small)
    results = mod.run(reps=1, datasets=["census1881"])
    assert results, suite
    for r in results:
        assert np.isfinite(r.value) and r.value >= 0, (suite, r.benchmark)
        rec = r.json()
        assert r.benchmark in rec


def test_cli_runs(small_corpus, capsys):
    from benchmarks import run as runner

    assert runner.main(["ops", "--reps", "1", "--datasets", "census1881"]) == 0
    out = capsys.readouterr().out
    assert '"benchmark"' in out


def test_bitset_matrix_retriever():
    """gz raw-bitset corpus loader (real-roaring-dataset README.md:24:
    big-endian int32 row count, then per row int32 long-count + longs)."""
    from roaringbitmap_tpu.models.bitset import bitmap_of_words, words_of_bitmap
    from roaringbitmap_tpu.utils import datasets

    if not datasets.bitset_matrix_available():
        pytest.skip("reference gz corpus not mounted")
    rows = datasets.fetch_bitset_matrix(limit=200)
    assert len(rows) == 200
    assert all(r.dtype == np.uint64 for r in rows)
    # conversion round-trip against a numpy popcount oracle
    for r in rows[:20]:
        if not r.size:
            continue
        bm = bitmap_of_words(r)
        assert bm.get_cardinality() == int(np.unpackbits(r.view(np.uint8)).sum())
        back = words_of_bitmap(bm)
        assert np.array_equal(back, r[: back.size])
        assert not np.any(r[back.size :])


def test_wah_ewah_codecs_roundtrip():
    """The formats suite's WAH/EWAH codecs against a dense oracle across
    density regimes (the wrapper-format implementations must be right
    before their comparison rows mean anything)."""
    import numpy as np

    from benchmarks import formats as F

    rng = np.random.default_rng(1)
    universe = 200_000
    for density in (0.0, 0.001, 0.3, 0.95):
        n = int(universe * density)
        vals = (
            np.unique(rng.integers(0, universe, n)).astype(np.uint32)
            if n
            else np.empty(0, np.uint32)
        )
        n_groups = (universe + 30) // 31
        n_words = (universe + 63) >> 6
        s = F.wah_encode(vals, n_groups)
        acc = np.zeros(n_groups, dtype=np.uint32)
        F.wah_decode_into(s, acc, np.bitwise_or)
        assert np.array_equal(acc, F._dense_groups(vals, n_groups, 31, np.uint32))
        e = F.ewah_encode(vals, n_words)
        acc64 = np.zeros(n_words, dtype=np.uint64)
        F.ewah_decode_into(e, acc64, np.bitwise_or)
        assert np.array_equal(acc64, F._dense_groups(vals, n_words, 64, np.uint64))
        probes = np.sort(rng.integers(0, universe, 500).astype(np.uint32))
        want = np.isin(probes, vals)
        assert np.array_equal(F.wah_contains_many(s, probes), want)
        assert np.array_equal(F.ewah_contains_many(e, probes), want)
        # AND-fold identity: x AND full-universe == x
        full = np.arange(universe, dtype=np.uint32)
        sf = F.wah_encode(full, n_groups)
        acc = np.full(n_groups, F._WAH_FULL, dtype=np.uint32)
        F.wah_decode_into(s, acc, np.bitwise_and)
        F.wah_decode_into(sf, acc, np.bitwise_and)
        assert np.array_equal(acc, F._dense_groups(vals, n_groups, 31, np.uint32))
