"""Benchmark smoke tests — twin of jmh/src/test
(RealDataBenchmark{Or,And,HorizontalOr,...}Test): every suite runs with
tiny reps, and the realdata engines' outputs are asserted equal to the
naive fold before any timing is trusted."""

import numpy as np
import pytest

from benchmarks import SUITES, common
from roaringbitmap_tpu.models.buffer import BufferFastAggregation
from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu.parallel.aggregation import FastAggregation, ParallelAggregation


@pytest.fixture(scope="module")
def small_corpus(monkeypatch_module):
    # cap corpora so the whole smoke pass stays fast
    orig = common.corpus

    def capped(name, limit=None):
        return orig(name, limit=min(limit or 40, 40))

    monkeypatch_module.setattr(common, "corpus", capped)
    common._bitmap_cache.clear()
    return capped


@pytest.fixture(scope="module")
def monkeypatch_module():
    from _pytest.monkeypatch import MonkeyPatch

    mp = MonkeyPatch()
    yield mp
    mp.undo()


def test_realdata_engines_agree_with_naive(small_corpus):
    bms = common.corpus_bitmaps("census1881", limit=30)
    want = FastAggregation.naive_or(*bms)
    assert FastAggregation.or_(*bms, mode="cpu") == want
    assert FastAggregation.or_(*bms, mode="device") == want
    assert FastAggregation.horizontal_or(*bms) == want
    assert FastAggregation.priorityqueue_or(*bms) == want
    assert ParallelAggregation.or_(*bms, mode="cpu") == want
    assert ParallelAggregation.or_(*bms, mode="device") == want
    want_and = FastAggregation.naive_and(*bms)
    assert FastAggregation.workshy_and(*bms, mode="cpu") == want_and
    assert FastAggregation.workshy_and(*bms, mode="device") == want_and
    # cardinality-only engines on the same real-data group distributions
    assert FastAggregation.or_cardinality(*bms, mode="device") == want.get_cardinality()
    assert FastAggregation.and_cardinality(*bms, mode="device") == want_and.get_cardinality()
    blobs = [b.serialize() for b in bms]
    mapped = [ImmutableRoaringBitmap(x) for x in blobs]
    assert BufferFastAggregation.or_(*mapped) == want


@pytest.mark.parametrize("suite", SUITES + ["simplebenchmark"])
def test_suite_runs(suite, small_corpus, monkeypatch):
    import importlib

    mod = importlib.import_module(f"benchmarks.{suite}")
    # shrink the heavy builders for smoke purposes
    for attr, small in (
        ("N_ROWS", 5000),
        ("N", 20_000),
        ("N_DOCS", 50_000),
        ("N_QUERIES", 8),
        ("TOP_K", 200),
    ):
        if hasattr(mod, attr):
            monkeypatch.setattr(mod, attr, small)
    results = mod.run(reps=1, datasets=["census1881"])
    assert results, suite
    for r in results:
        assert np.isfinite(r.value) and r.value >= 0, (suite, r.benchmark)
        rec = r.json()
        assert r.benchmark in rec


def test_cli_runs(small_corpus, capsys):
    from benchmarks import run as runner

    assert runner.main(["ops", "--reps", "1", "--datasets", "census1881"]) == 0
    out = capsys.readouterr().out
    assert '"benchmark"' in out


def test_bitset_matrix_retriever():
    """gz raw-bitset corpus loader (real-roaring-dataset README.md:24:
    big-endian int32 row count, then per row int32 long-count + longs)."""
    from roaringbitmap_tpu.models.bitset import bitmap_of_words, words_of_bitmap
    from roaringbitmap_tpu.utils import datasets

    if not datasets.bitset_matrix_available():
        pytest.skip("reference gz corpus not mounted")
    rows = datasets.fetch_bitset_matrix(limit=200)
    assert len(rows) == 200
    assert all(r.dtype == np.uint64 for r in rows)
    # conversion round-trip against a numpy popcount oracle
    for r in rows[:20]:
        if not r.size:
            continue
        bm = bitmap_of_words(r)
        assert bm.get_cardinality() == int(np.unpackbits(r.view(np.uint8)).sum())
        back = words_of_bitmap(bm)
        assert np.array_equal(back, r[: back.size])
        assert not np.any(r[back.size :])
