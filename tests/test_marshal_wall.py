"""Marshal-wall rebuild (ISSUE 8): device-side container expansion, the
donated O(k) delta scatter, and the double-buffered overlap shipping lane.

The acceptance claims are asserted the way production observes them: the
``rb_tpu_store_transfer_bytes_total`` routes prove a k-row delta ships
O(k·2048) words and never re-materializes a second full flat tensor; the
donated-buffer checks prove the aliasing guard (a consumed buffer is never
served, the refreshed pack serves the post-delta bits); the fault-site
tests prove every new path degrades to the host ``pack.host_words``
pipeline bit-exactly.
"""

import numpy as np
import pytest

from roaringbitmap_tpu import observe
from roaringbitmap_tpu.models.container import (
    ArrayContainer,
    BitmapContainer,
    RunContainer,
)
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.parallel import overlap, store
from roaringbitmap_tpu.parallel.aggregation import FastAggregation as FA
from roaringbitmap_tpu.robust import errors as rerrors
from roaringbitmap_tpu.robust import faults as rfaults
from roaringbitmap_tpu.robust import ladder as rladder


def _bm(rng, n=2500, spread=1 << 19):
    return RoaringBitmap(
        np.sort(rng.choice(spread, size=n, replace=False)).astype(np.uint32)
    )


def _working_set(seed=11, k=5):
    rng = np.random.default_rng(seed)
    return [_bm(rng) for _ in range(k)]


def _mixed_containers(seed=3):
    """Array + bitmap + run containers, including run boundary cases (a
    run starting at bit 0, a run ending at bit 65535)."""
    rng = np.random.default_rng(seed)
    out = []
    for j in range(23):
        kind = j % 4
        if kind == 0:
            out.append(
                ArrayContainer(
                    np.sort(rng.choice(65536, 200, replace=False)).astype(np.uint16)
                )
            )
        elif kind == 1:
            w = np.zeros(1024, np.uint64)
            for x in rng.choice(65536, 5000, replace=False):
                w[x >> 6] |= np.uint64(1) << np.uint64(x & 63)
            out.append(BitmapContainer(w))
        elif kind == 2:
            s = np.sort(rng.choice(65530, 8, replace=False)).astype(np.uint16)
            out.append(RunContainer(s[::2], (s[1::2] - s[::2]).astype(np.uint16)))
        else:
            out.append(
                RunContainer(
                    np.array([0, 65000], np.uint16), np.array([5, 535], np.uint16)
                )
            )
    return out


def _xfer(route: str) -> int:
    c = observe.REGISTRY.get(observe.STORE_TRANSFER_BYTES_TOTAL)
    return c.get((route,)) if c is not None else 0


@pytest.fixture
def fresh():
    store.PACK_CACHE.close()
    overlap.LANE.drain()
    # pin the threaded lane so its machinery is exercised even on
    # single-core CI hosts (where "auto" stands down to inline staging)
    overlap.LANE.configure("on")
    rladder.LADDER.reset()
    yield
    store.PACK_CACHE.close()
    overlap.LANE.drain()
    overlap.LANE.configure("auto")
    store.configure_expansion("auto")


# ---------------------------------------------------------------------------
# device-side expansion: bit-exact vs the host pack.host_words path
# ---------------------------------------------------------------------------


def test_expansion_kernel_bit_exact_all_container_types(fresh):
    """The fused jit expansion kernel (forced via mode "device") must agree
    with the host expansion on every container class, including run
    boundary cases — the differential that backs the degradation's
    bit-exactness claim."""
    containers = _mixed_containers()
    want = store.pack_rows_host(containers)
    store.configure_expansion("device")
    got = np.asarray(store.ship_rows(containers))
    assert np.array_equal(got, want)


def test_adjacent_runs_expand_bit_exact(fresh):
    """Regression: a stop toggle landing on the NEXT run's start bit
    (adjacent runs — disjoint, and legal in the portable format) must
    CANCEL that start toggle, not scatter-add into a carry that inverts
    the rest of the row's fill."""
    cs = [
        RunContainer(np.array([0, 6], np.uint16), np.array([5, 4], np.uint16)),
        RunContainer(
            np.array([0, 6, 11], np.uint16), np.array([5, 4, 20], np.uint16)
        ),
        # adjacency across a word boundary: 10..42 then 43..143
        RunContainer(
            np.array([10, 43], np.uint16), np.array([32, 100], np.uint16)
        ),
    ]
    want = store.pack_rows_host(cs)
    store.configure_expansion("device")
    got = np.asarray(store.ship_rows(cs))
    assert np.array_equal(got, want)


def test_host_mode_device_rows_never_alias_the_mirror(fresh):
    """Regression: jax's CPU client zero-copies chance-64-byte-aligned
    host arrays on device_put — the retained ``.words`` mirror (mutated in
    place by apply_delta) must never back the live device rows."""
    store.configure_expansion("host")
    for seed in range(8):  # numpy alignment is chance: try several packs
        packed = store.pack_groups(
            store.group_by_key(_working_set(seed=100 + seed, k=3))
        )
        d0 = np.asarray(packed.device_words).copy()
        packed.words[...] ^= np.uint32(0xFFFFFFFF)
        assert np.array_equal(np.asarray(packed.device_words), d0), seed


def test_every_expansion_mode_serves_identical_bits(fresh):
    bms = _working_set(seed=21)
    bms[1].run_optimize()
    want = FA.naive_or(*bms)
    for mode in ("auto", "device", "host", "legacy"):
        store.configure_expansion(mode)
        store.PACK_CACHE.close()
        assert FA.or_(*bms, mode="device") == want, f"mode {mode} diverged"


def test_lazy_host_words_equal_eager_pack(fresh):
    bms = _working_set(seed=22)
    groups = store.group_by_key(bms)
    packed = store.pack_groups(groups)
    rows = [c for k in sorted(groups) for c in groups[k]]
    assert np.array_equal(packed.words, store.pack_rows_host(rows))


def test_expand_fault_degrades_to_host_words_bit_exact(fresh):
    """ISSUE 8 satellite: the store.expand site must fall back to the host
    pack.host_words path bit-exactly, recording the degrade edge."""
    bms = _working_set(seed=23)
    want = FA.naive_or(*bms)
    deg = observe.REGISTRY.get(observe.DEGRADE_TOTAL)
    before = deg.get(("store.expand", "device-expand", "host-words"))
    with rfaults.inject("store.expand", rerrors.TransientDeviceError, every=1):
        assert FA.or_(*bms, mode="device") == want
    after = deg.get(("store.expand", "device-expand", "host-words"))
    assert after > before, "the fallback must be recorded as a degrade edge"
    # and the fallback actually host-packed (the legacy pipeline ran)
    h = observe.REGISTRY.get(observe.HOST_OP_SECONDS)
    assert h.get(("store.pack_rows_host",)) is not None


# ---------------------------------------------------------------------------
# donated delta scatter: O(k) bytes, no second full tensor, no stale aliases
# ---------------------------------------------------------------------------


def test_delta_ships_o_k_rows_and_never_rematerializes(fresh):
    """A k-row delta ships exactly k·2048 uint32 words (pack_delta route)
    and moves NO other full-tensor traffic: the flat rows ship once at
    cold expansion, and the delta adds only its rows — the transfer
    ledger is the proof there is no hidden second materialization."""
    bms = _working_set(seed=31)
    packed = store.packed_for(bms)
    _ = packed.device_words  # cold expansion: the one full-block route
    full_routes = ("payload_expand", "flat_rows")
    full_before = sum(_xfer(r) for r in full_routes)
    delta_before = _xfer("pack_delta")
    k = 3
    for bm in bms[:k]:
        hb = int(bm.high_low_container.keys[0])
        bm.add((hb << 16) | 911)
    refreshed = store.packed_for(bms)
    refreshed.device_words.block_until_ready()
    assert refreshed is packed
    assert _xfer("pack_delta") - delta_before == k * store.ROW_BYTES
    assert sum(_xfer(r) for r in full_routes) == full_before, (
        "the delta path must not re-ship (or re-expand) the full flat tensor"
    )
    # pack-cache counters agree: k rows delta-repacked
    assert store.PACK_CACHE.stats()["delta_rows"] >= k


def test_donated_buffer_never_served_stale(fresh):
    """Donation-aliasing regression: after a delta, the OLD device buffer
    is consumed (deleted — any holder fails loudly instead of reading
    post-delta bits through a pre-delta handle), the pack serves a fresh
    buffer generation, and the served bits are the post-delta truth."""
    bms = _working_set(seed=32)
    packed = store.packed_for(bms)
    old = packed.device_words
    gen0 = packed._buffer_gen
    hb = int(bms[0].high_low_container.keys[0])
    bms[0].add((hb << 16) | 4242)
    refreshed = store.packed_for(bms)
    assert refreshed is packed
    assert packed._buffer_gen == gen0 + 1
    assert old.is_deleted(), "the donated-away buffer must be consumed"
    # mutate-after-delta serves correct bits: differential vs a fresh pack
    fresh_pack = store.pack_groups(store.group_by_key(bms))
    assert np.array_equal(np.asarray(packed.device_words), fresh_pack.words)
    # derived layouts rebuilt from the new buffer, not the dead one
    padded = packed.padded_device(0)
    if padded is not None:
        padded.block_until_ready()


def test_delta_on_unmaterialized_host_words_converges(fresh):
    """Deltas applied while the host mirror is NOT materialized ride the
    row-override path; a later host materialization must replay them."""
    bms = _working_set(seed=33)
    packed = store.packed_for(bms)
    assert packed._host_words is None, "payload path must not host-pack"
    for bm in bms[:2]:
        hb = int(bm.high_low_container.keys[0])
        bm.add((hb << 16) | 1717)
    refreshed = store.packed_for(bms)
    assert refreshed is packed and packed._row_overrides
    want = store.pack_groups(store.group_by_key(bms))
    assert np.array_equal(packed.words, want.words)  # overrides replayed
    assert not packed._row_overrides, "materialization folds the overrides"


def test_wholesale_mutation_skips_dirty_scan(fresh):
    """ISSUE 8 small fix: mark_all_dirty already forces the full repack —
    the delta validator must decide from the version counters alone, not
    pay a dirty scan first (the wasted delta.dirty_scan of r09)."""
    bms = _working_set(seed=34)
    store.packed_for(bms)
    h = observe.REGISTRY.get(observe.STORE_DELTA_STAGE_SECONDS)
    scans_before = (h.get(("dirty_scan",)) or {"count": 0})["count"]
    bms[0].high_low_container.mark_all_dirty()
    repacked = store.packed_for(bms)  # full repack, no scan
    scans_after = (h.get(("dirty_scan",)) or {"count": 0})["count"]
    assert scans_after == scans_before
    assert np.array_equal(
        repacked.words, store.pack_groups(store.group_by_key(bms)).words
    )


# ---------------------------------------------------------------------------
# overlap shipping lane
# ---------------------------------------------------------------------------


def test_prefetch_stages_the_pack_and_wait_joins_it(fresh):
    bms = _working_set(seed=41)
    from roaringbitmap_tpu.parallel import aggregation

    ticket = aggregation.prefetch(bms, "or", mode="device")
    assert ticket is not None
    staged = overlap.LANE.wait(bms, None)
    assert staged is not None
    assert staged.device_words is not None
    # the consumer's normal lookup is a resident hit on the staged pack
    assert store.packed_for(bms) is staged
    g = observe.REGISTRY.get(observe.STORE_OVERLAP_RATIO)
    assert 0.0 <= g.get(("ship",)) <= 1.0


def test_lane_window_is_double_buffered(fresh):
    sets = [_working_set(seed=50 + i, k=3) for i in range(3)]
    t0 = overlap.LANE.prefetch(sets[0])
    assert t0 is not None
    # depth=1: a second staging while the first is pending is dropped
    # (either it is still pending, or it finished and the window freed)
    overlap.LANE.prefetch(sets[1])
    pending = overlap.LANE.stats()["pending"]
    assert pending <= overlap.LANE.depth
    overlap.LANE.drain()


def test_lane_stands_down_without_parallelism(fresh):
    """On a host with nothing to overlap against, the lane must not stage
    (the thread would time-slice the consumer's core for the same work
    plus switch tax): prefetch returns None and the pipelined results
    still match — the consumer just packs synchronously."""
    overlap.LANE.configure("off")
    bms = _working_set(seed=55, k=3)
    assert overlap.LANE.prefetch(bms) is None
    assert overlap.LANE.stats()["pending"] == 0
    assert overlap.LANE.wait(bms) is None
    jobs = [(_working_set(seed=56 + i, k=3), "or") for i in range(2)]
    want = [FA.naive_or(*b) for b, _ in jobs]
    got = overlap.run_pipelined(jobs, mode="device")
    assert all(g == w for g, w in zip(got, want))
    # "auto" resolves from the core count — on a 1-core host it inlines
    overlap.LANE.configure("auto")
    import os as _os

    assert overlap.LANE.threaded() == ((_os.cpu_count() or 1) > 1)


def test_run_pipelined_matches_serial_bits(fresh):
    jobs = [(_working_set(seed=60 + i, k=4), op)
            for i, op in enumerate(("or", "xor", "and", "or"))]
    want = [
        getattr(FA, {"or": "naive_or", "xor": "naive_xor", "and": "naive_and"}[op])(*b)
        for b, op in jobs
    ]
    got = overlap.run_pipelined(jobs, mode="device")
    assert all(g == w for g, w in zip(got, want))


def test_lane_fault_degrades_to_sync_bit_exact(fresh):
    """A fault on the lane thread (store.expand fires during staging) must
    never escape prefetch/wait: the consumer packs synchronously and the
    bits stay exact (fuzz family 26's invariant, unit-sized)."""
    jobs = [(_working_set(seed=70 + i, k=3), "or") for i in range(2)]
    want = [FA.naive_or(*b) for b, _ in jobs]
    with rfaults.inject("store.expand", rerrors.TransientDeviceError, every=1):
        got = overlap.run_pipelined(jobs, mode="device")
    assert all(g == w for g, w in zip(got, want))


def test_execute_pipelined_matches_execute(fresh):
    from roaringbitmap_tpu.query import Q, execute
    from roaringbitmap_tpu.query.exec import execute_pipelined

    bms = _working_set(seed=80, k=5)
    exprs = [
        Q.or_(*[Q.leaf(b) for b in bms]),
        Q.xor(*[Q.leaf(b) for b in bms[:3]]),
        Q.and_(*[Q.leaf(b) for b in bms[1:]]),
    ]
    want = [execute(e, cache=None, mode="device") for e in exprs]
    store.PACK_CACHE.close()
    got = execute_pipelined(exprs, cache=None, mode="device")
    assert all(g == w for g, w in zip(got, want))


def test_pipelined_consumers_pop_their_stagings(fresh):
    """Regression: a pipelined run must JOIN every staging it prefetches —
    an unjoined staging would hold the depth-1 window (and the staged
    working set) for the life of the process, silently degrading every
    later prefetch to window_full."""
    from roaringbitmap_tpu.parallel import aggregation
    from roaringbitmap_tpu.query import Q
    from roaringbitmap_tpu.query.exec import execute_pipelined

    jobs = [(_working_set(seed=90 + i, k=3), "or") for i in range(3)]
    overlap.run_pipelined(jobs, mode="device")
    assert overlap.LANE.stats()["pending"] == 0

    bms = _working_set(seed=95, k=5)
    exprs = [
        Q.or_(*[Q.leaf(b) for b in bms]),
        Q.xor(*[Q.leaf(b) for b in bms[:3]]),
    ]
    execute_pipelined(exprs, cache=None, mode="device")
    assert overlap.LANE.stats()["pending"] == 0
    # the window is free: the next prefetch stages instead of dropping
    ticket = aggregation.prefetch(
        _working_set(seed=96, k=3), "or", mode="device"
    )
    assert ticket is not None
    overlap.LANE.drain()


def test_lane_reaps_orphaned_stagings(fresh):
    """Regression: a done-but-never-joined staging (e.g. the consumer's
    bitmaps mutated, so the join key no longer matches) must not wedge the
    depth-1 window forever — prefetch reaps finished futures before
    declaring the window full."""
    a, b = _working_set(seed=97, k=3), _working_set(seed=98, k=3)
    t0 = overlap.LANE.prefetch(a)
    assert t0 is not None
    t0.future.result()  # staged and done, but never joined
    t1 = overlap.LANE.prefetch(b)
    assert t1 is not None  # the orphan was reaped, the window is free
    assert overlap.LANE.stats()["pending"] == 1
    overlap.LANE.drain()


def test_fatal_in_reaped_orphan_does_not_wedge_the_window(fresh):
    """Regression: when prefetch reaps an orphaned staging whose parked
    error classifies FATAL, the re-raise must happen BEFORE the new
    staging is inserted — a never-submitted Future left in the window
    would block every later wait on its key forever."""
    a, b = _working_set(seed=110, k=3), _working_set(seed=111, k=3)
    with rfaults.inject("store.expand", ValueError, every=1):
        t0 = overlap.LANE.prefetch(a)
        assert t0 is not None
        assert isinstance(t0.future.exception(), ValueError)  # parked FATAL
    with pytest.raises(ValueError):
        overlap.LANE.prefetch(b)  # reaps the orphan, re-raises its FATAL
    assert overlap.LANE.stats()["pending"] == 0  # b was never inserted
    t1 = overlap.LANE.prefetch(b)  # the window is usable again
    assert t1 is not None
    overlap.LANE.drain()


def test_join_pops_staging_by_op_marker(fresh):
    """LANE.join addresses a staging by (op, fingerprints) without paying
    the dispatch prelude a second time — including AND's key-filtered
    marker."""
    from roaringbitmap_tpu.parallel import aggregation

    bms = _working_set(seed=99, k=3)
    assert aggregation.prefetch(bms, "and", mode="device") is not None
    staged = overlap.LANE.join(bms, "and")
    assert staged is not None
    assert overlap.LANE.stats()["pending"] == 0


# ---------------------------------------------------------------------------
# ship_rows (query kernels' first-operand rows)
# ---------------------------------------------------------------------------


def test_ship_rows_matches_host_pack(fresh):
    containers = _mixed_containers(seed=5)
    want = store.pack_rows_host(containers)
    assert np.array_equal(np.asarray(store.ship_rows(containers)), want)
    with rfaults.inject("store.expand", rerrors.TransientDeviceError, every=1):
        assert np.array_equal(np.asarray(store.ship_rows(containers)), want)
