"""Health sentinel, declarative rules, cost facade, and flight bundles
(ISSUE 12): rule hysteresis + flap suppression on a fake clock, actuation
cooldown/idempotence, the seeded-drift → auto-refit e2e with provenance
persisted through RB_TPU_COLUMNAR_CAL, bundle write → manifest
round-trip, the unified artifact sink, the 16-thread hammer with the
lock witness proving sentinel state is a leaf lock, and the off-mode
zero-allocation pin on the inline pacing hook."""

import copy
import json
import os
import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import columnar, cost, insights, observe
from roaringbitmap_tpu.analysis.lockwitness import LockWitness
from roaringbitmap_tpu.columnar import costmodel
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.observe import (
    artifacts,
    bundle,
    decisions,
    health,
    outcomes,
    sentinel,
)
from roaringbitmap_tpu.observe import timeline as tl
from roaringbitmap_tpu.query.plan import CARD_MODEL
from roaringbitmap_tpu.robust import ladder as rladder


# ---------------------------------------------------------------------------
# helpers: a dial-driven rule + a snapshot stub (no registries involved)
# ---------------------------------------------------------------------------


class _Dial:
    """A probe whose value tests turn by hand."""

    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, snap):
        return self.value


def _stub_snap():
    return health.Snapshot(
        metrics={}, breaker_open_ages={}, drift={}, outcome_sites={}, now=0.0
    )


def _mk(rule, **kw):
    """A private sentinel on a fake clock with the given single rule."""
    clock = kw.pop("clock", lambda: 0.0)
    return sentinel.Sentinel(rules=(rule,), clock=clock, **kw)


@pytest.fixture(autouse=True)
def _clean_state():
    outcomes.reset()
    sentinel.SENTINEL.reset()
    yield
    outcomes.reset()
    sentinel.SENTINEL.reset()
    sentinel.configure(inline=False)


# ---------------------------------------------------------------------------
# rule hysteresis + bands (fake clock: every tick is explicit)
# ---------------------------------------------------------------------------


def test_rule_fires_only_after_n_consecutive_ticks():
    dial = _Dial(0.0)
    rule = health.Rule("r", "", dial, warn=10.0, critical=100.0,
                       fire_after=3, clear_after=2)
    s = _mk(rule)
    dial.value = 50.0  # warn band
    for i in range(2):
        r = s.tick(now=float(i), snap=_stub_snap())
        assert r["rules"]["r"]["level"] == health.OK, f"fired early at {i}"
    r = s.tick(now=2.0, snap=_stub_snap())
    assert r["rules"]["r"]["level"] == health.WARN
    assert r["rules"]["r"]["transition"] == (health.OK, health.WARN)
    assert r["status_name"] == "yellow"


def test_rule_clears_only_after_m_consecutive_ok_ticks():
    dial = _Dial(50.0)
    rule = health.Rule("r", "", dial, warn=10.0, critical=100.0,
                       fire_after=1, clear_after=3)
    s = _mk(rule)
    s.tick(now=0.0, snap=_stub_snap())
    assert s.status()[1] == "yellow"
    dial.value = 0.0
    for i in range(2):
        s.tick(now=1.0 + i, snap=_stub_snap())
        assert s.status()[1] == "yellow", "cleared early"
    s.tick(now=3.0, snap=_stub_snap())
    assert s.status()[1] == "green"


def test_warn_vs_critical_bands_and_escalation():
    dial = _Dial(50.0)
    rule = health.Rule("r", "", dial, warn=10.0, critical=100.0,
                       fire_after=2, clear_after=2)
    s = _mk(rule)
    s.tick(now=0.0, snap=_stub_snap())
    s.tick(now=1.0, snap=_stub_snap())
    assert s.rule_states()["r"]["level"] == health.WARN
    dial.value = 500.0  # escalate: needs fire_after ticks above critical
    s.tick(now=2.0, snap=_stub_snap())
    assert s.rule_states()["r"]["level"] == health.WARN
    r = s.tick(now=3.0, snap=_stub_snap())
    assert r["rules"]["r"]["transition"] == (health.WARN, health.CRITICAL)
    assert s.status()[1] == "red"


def test_none_value_is_no_data_not_a_fire():
    rule = health.Rule("r", "", lambda s: None, warn=1.0, critical=2.0,
                       fire_after=1, clear_after=1)
    s = _mk(rule)
    r = s.tick(now=0.0, snap=_stub_snap())
    assert r["rules"]["r"]["level"] == health.OK


def test_probe_exception_is_reported_not_fatal():
    def boom(snap):
        raise RuntimeError("probe broke")

    rule = health.Rule("r", "", boom, warn=1.0, critical=2.0)
    s = _mk(rule)
    r = s.tick(now=0.0, snap=_stub_snap())
    assert r["status_name"] == "green"
    assert "probe broke" in r["probe_errors"]["r"]


def test_flap_suppression_holds_fired_level_and_then_recovers():
    dial = _Dial(0.0)
    rule = health.Rule("r", "", dial, warn=10.0, critical=100.0,
                       fire_after=1, clear_after=1,
                       flap_window=8, flap_limit=4)
    s = _mk(rule)
    # oscillate: each tick crosses the warn band boundary
    held_at_warn = 0
    for i in range(16):
        dial.value = 50.0 if i % 2 == 0 else 0.0
        r = s.tick(now=float(i), snap=_stub_snap())
    st = s.rule_states()["r"]
    assert st["flapping"], "oscillating input must mark the rule flapping"
    # while flapping, the fired level is held (downward suppressed): the
    # last oscillation ticks must all report WARN
    hist = s.history("r", 6)
    assert all(h["level"] == health.WARN for h in hist), hist
    assert any(h["suppressed"] for h in hist)
    # stabilize: band stops changing -> window drains -> flap clears ->
    # the clear hysteresis finally applies
    dial.value = 0.0
    for i in range(16, 16 + rule.flap_window + rule.clear_after + 1):
        s.tick(now=float(i), snap=_stub_snap())
    st = s.rule_states()["r"]
    assert not st["flapping"]
    assert st["level"] == health.OK


# ---------------------------------------------------------------------------
# actuations: alert on fire transition, refit cooldown + idempotence,
# bundle once per red episode
# ---------------------------------------------------------------------------


def test_alert_fires_once_per_episode_with_instant(monkeypatch):
    dial = _Dial(50.0)
    rule = health.Rule("r", "", dial, warn=10.0, critical=100.0,
                       fire_after=1, clear_after=1, actuation="alert")
    s = _mk(rule)
    prev_mode = tl.mode_name()
    tl.configure(mode="on")
    try:
        r1 = s.tick(now=0.0, snap=_stub_snap())
        r2 = s.tick(now=1.0, snap=_stub_snap())  # still warn: no re-alert
    finally:
        tl.configure(mode=prev_mode)
    assert [a["kind"] for a in r1["actuated"]] == ["alert"]
    assert r2["actuated"] == []
    names = [e.name for e in tl.RECORDER.events()]
    assert "sentinel.alert" in names
    acts = s.actuations()
    assert len(acts) == 1 and acts[0]["rule"] == "r"


def test_refit_actuation_cooldown_and_idempotence(monkeypatch):
    calls = []
    monkeypatch.setattr(cost, "refit_all", lambda: calls.append(1) or {})
    dial = _Dial(5.0)
    rule = health.Rule("r", "", dial, warn=1.0, critical=100.0,
                       fire_after=1, clear_after=1, actuation="refit")
    s = _mk(rule, refit_cooldown_s=60.0)
    s.tick(now=0.0, snap=_stub_snap())
    assert len(calls) == 1
    # still firing, inside the cooldown: actuation must NOT re-run
    s.tick(now=1.0, snap=_stub_snap())
    s.tick(now=59.0, snap=_stub_snap())
    assert len(calls) == 1, "refit re-ran inside its cooldown"
    # past the cooldown and still drifted: one more refit
    s.tick(now=61.0, snap=_stub_snap())
    assert len(calls) == 2
    kinds = [a["kind"] for a in s.actuations()]
    assert kinds == ["refit", "refit"]


def test_bundle_one_shot_per_red_episode(tmp_path, monkeypatch):
    paths = []

    def fake_bundle(reason, trigger=None, dir=None, health_dump=None):
        paths.append(reason)
        return str(tmp_path / f"b{len(paths)}")

    monkeypatch.setattr(bundle, "write_bundle", fake_bundle)
    dial = _Dial(500.0)
    rule = health.Rule("r", "", dial, warn=10.0, critical=100.0,
                       fire_after=1, clear_after=1)
    s = _mk(rule, bundle_cooldown_s=300.0)
    s.tick(now=0.0, snap=_stub_snap())
    assert paths == ["r"], "entering red must write exactly one bundle"
    # staying red: no second bundle
    s.tick(now=1.0, snap=_stub_snap())
    s.tick(now=2.0, snap=_stub_snap())
    assert paths == ["r"]
    # clear, then red again AFTER the cooldown: a new episode bundles
    dial.value = 0.0
    s.tick(now=3.0, snap=_stub_snap())
    dial.value = 500.0
    s.tick(now=400.0, snap=_stub_snap())
    assert paths == ["r", "r"]


def test_health_gauges_exported():
    dial = _Dial(50.0)
    rule = health.Rule("gauge-rule", "", dial, warn=10.0, critical=100.0,
                       fire_after=1, clear_after=1)
    s = _mk(rule)
    s.tick(now=0.0, snap=_stub_snap())
    g = observe.REGISTRY.get(observe.HEALTH_STATUS)
    assert g.get(()) == health.WARN
    rs = observe.REGISTRY.get(observe.HEALTH_RULE_STATE)
    assert rs.get(("gauge-rule",)) == health.WARN


# ---------------------------------------------------------------------------
# default rule probes over real snapshots
# ---------------------------------------------------------------------------


def test_default_rules_green_on_healthy_process():
    r = sentinel.SENTINEL.tick()
    assert r["status_name"] == "green", r


def test_breaker_stuck_open_rule_sees_ladder_ages():
    rladder.LADDER.reset()
    rladder.LADDER.configure(cooldown_s=600.0)
    try:
        for _ in range(3):
            rladder.LADDER.record_failure("sent-test", "device")
        ages = rladder.LADDER.open_ages(now=time.monotonic() + 120.0)
        assert ages.get("sent-test/device", 0) >= 120.0
        snap = health.snapshot(refresh_hbm=False)
        snap.breaker_open_ages = {"sent-test/device": 120.0}
        assert health.DEFAULT_RULES[2].probe(snap) == 120.0
    finally:
        rladder.LADDER.reset()
        rladder.LADDER.configure(cooldown_s=5.0)


def test_open_age_measures_the_episode_not_the_last_retrip():
    """A stuck tier under traffic fails one half-open probe per cooldown;
    each failed probe re-trips the breaker. The age must run from the
    EPISODE start, or it could never exceed one cooldown and the
    stuck-open rule could never fire (review regression)."""
    rladder.LADDER.reset()
    rladder.LADDER.configure(cooldown_s=5.0)
    try:
        t0 = time.monotonic()
        for _ in range(3):
            rladder.LADDER.record_failure("age-test", "device")
        assert rladder.LADDER.breaker_state("age-test", "device") == "open"
        # simulate 10 failed half-open probes across 10 cooldowns
        for i in range(10):
            with rladder.LADDER._lock:
                b = rladder.LADDER._breaker("age-test", "device")
                b.allow(t0 + (i + 1) * 5.0)
                b.failure(t0 + (i + 1) * 5.0)
        ages = rladder.LADDER.open_ages(now=t0 + 60.0)
        assert ages["age-test/device"] >= 59.0, ages
        # recovery clears the episode: a later trip starts a NEW episode
        rladder.LADDER.record_success("age-test", "device")
        for _ in range(3):
            rladder.LADDER.record_failure("age-test", "device")
        assert rladder.LADDER.open_ages(
            now=time.monotonic() + 1.0
        )["age-test/device"] < 10.0
    finally:
        rladder.LADDER.reset()
        rladder.LADDER.configure(cooldown_s=5.0)


def test_counter_delta_first_tick_reports_zero():
    snap = health.snapshot(refresh_hbm=False)
    assert snap.counter_delta(observe.OUTCOME_ANOMALY_TOTAL) == 0.0
    # second snapshot with the first's sums: still zero without traffic
    snap2 = health.snapshot(prev_sums=snap.sums, refresh_hbm=False)
    assert snap2.counter_delta(observe.OUTCOME_ANOMALY_TOTAL) == 0.0


def test_regret_fraction_uses_measured_denominator():
    seq = decisions.record_decision(
        "columnar.cutoff", "columnar-cpu", outcome=True, op="and",
        na=20, nb=20, shape="run",
        est_us={"columnar-cpu": 50.0, "per-container": 10.0},
    )
    outcomes.resolve(seq, "columnar.cutoff", 100e-6, engine="columnar-cpu")
    snap = health.snapshot(refresh_hbm=False)
    frac = health._regret_fraction(snap)
    # regret = 100us measured - 10us predicted alternative = 90us of 100us
    assert 0.8 < frac <= 1.0
    summary = outcomes.summary()["columnar.cutoff"]
    assert summary["measured_s"] == pytest.approx(100e-6, rel=0.01)


# ---------------------------------------------------------------------------
# seeded drift -> auto-refit e2e (the ROADMAP item 4 auto-trigger)
# ---------------------------------------------------------------------------


def _run_mix(n=40):
    vals = []
    for k in range(n):
        base = k << 16
        starts = np.arange(0, 1 << 16, 1 << 12)[:14]
        v = np.unique(np.concatenate([np.arange(s, s + 900) for s in starts]))
        vals.append((v + base).astype(np.uint32))
    bm = RoaringBitmap(np.concatenate(vals))
    bm.run_optimize()
    return bm


def test_seeded_drift_auto_refit_e2e(tmp_path, monkeypatch):
    cal_path = str(tmp_path / "cal.json")
    monkeypatch.setenv("RB_TPU_COLUMNAR_CAL", cal_path)
    costmodel.MODEL.reset()
    columnar.calibrate(include_device=False, persist=cal_path)
    a, b = _run_mix(), _run_mix()
    tier = str(columnar.route(
        a.high_low_container, b.high_low_container, record=False
    ))
    group = costmodel.op_group("and")
    true_cell = list(costmodel.MODEL.coeffs[group][tier]["run"])
    with costmodel.MODEL._lock:
        costmodel.MODEL.coeffs = copy.deepcopy(costmodel.MODEL.coeffs)
        costmodel.MODEL.coeffs[group][tier]["run"] = [
            round(true_cell[0] / 16, 3), round(true_cell[1] / 16, 4)
        ]
    try:
        for _ in range(8):  # routed joins under the poisoned pricing
            RoaringBitmap.and_(a, b)
        cell = (group, tier, "run")
        drifted = outcomes.LEDGER.drift()[cell]
        assert drifted > health.DEFAULT_RULES[0].critical, (
            f"seeded poisoning only drifted to {drifted}"
        )
        s = sentinel.Sentinel(clock=lambda: 0.0, refit_cooldown_s=60.0,
                              bundle_cooldown_s=300.0)
        # fire_after=2 for costmodel-drift: tick twice
        r1 = s.tick(now=0.0)
        assert not any(a_["kind"] == "refit" for a_ in r1["actuated"])
        r2 = s.tick(now=1.0)
        kinds = [a_["kind"] for a_ in r2["actuated"]]
        assert "refit" in kinds, r2
        # the columnar authority moved the poisoned cell back toward truth
        refit_cell = costmodel.MODEL.coeffs[group][tier]["run"]
        n_mid = min(a.get_container_count(), b.get_container_count())
        measured = float(np.median([
            sm["measured_us"] for sm in outcomes.samples()
            if sm["engine"] == tier and sm["shape"] == "run"
        ]))
        def cost_of(c):
            return c[0] + n_mid * c[1]
        assert abs(cost_of(refit_cell) - measured) < abs(
            cost_of([true_cell[0] / 16, true_cell[1] / 16]) - measured
        ), "auto-refit did not move the poisoned cell toward measured truth"
        assert costmodel.MODEL.provenance == "refit-from-traffic"
        # provenance PERSISTED through RB_TPU_COLUMNAR_CAL: a fresh model
        # reloading the file keeps the refit-from-traffic lineage
        fresh = costmodel.CostModel()
        assert fresh.load(cal_path)
        assert fresh.provenance == "refit-from-traffic"
        # the refit actuation log names the authority + provenance
        refit_acts = [a_ for a_ in s.actuations() if a_["kind"] == "refit"]
        assert refit_acts and refit_acts[0]["authorities"][
            "columnar-cutoff"]["provenance"] == "refit-from-traffic"
        # drift re-based: the rule clears and the process returns green
        s.tick(now=2.0)
        r4 = s.tick(now=3.0)
        assert r4["rules"]["costmodel-drift"]["level"] == health.OK
        assert outcomes.LEDGER.drift()[cell] == 1.0
    finally:
        costmodel.MODEL.reset()


# ---------------------------------------------------------------------------
# cost facade: all authorities, one protocol, one state lifecycle
# ---------------------------------------------------------------------------


def test_cost_facade_registers_all_authorities():
    assert cost.names() == [
        "columnar-cutoff", "compaction", "device-breakeven", "epoch-flip",
        "fusion-batch", "pack-residency", "planner-cardinality",
        "serve-admission",
    ]
    state = cost.calibration_state()
    assert state["schema"] == cost.STATE_SCHEMA
    for name in cost.names():
        sub = state["authorities"][name]
        assert {"curves", "provenance", "drift"} <= set(sub)


def test_cost_state_round_trip(tmp_path):
    cost.reset_all()
    costmodel.MODEL.reset()
    try:
        columnar.calibrate(include_device=False)
        with CARD_MODEL._lock:
            CARD_MODEL.corrections["and"] = 0.25
            CARD_MODEL.provenance = "refit-from-traffic"
        path = str(tmp_path / "cost_state.json")
        assert cost.save_state(path) == path
        coeffs_before = json.loads(json.dumps(costmodel.MODEL.coeffs))
        cost.reset_all()
        assert CARD_MODEL.corrections["and"] == 1.0
        assert not costmodel.MODEL.calibrated
        verdicts = cost.load_state(path)
        assert verdicts["columnar-cutoff"] and verdicts["planner-cardinality"]
        assert costmodel.MODEL.calibrated
        assert costmodel.MODEL.coeffs == coeffs_before
        assert CARD_MODEL.corrections["and"] == 0.25
        assert CARD_MODEL.provenance == "refit-from-traffic"
    finally:
        cost.reset_all()
        costmodel.MODEL.reset()


def test_breakeven_authority_fits_curves_and_moves_gate():
    from roaringbitmap_tpu.cost import breakeven
    from roaringbitmap_tpu.parallel import aggregation

    breakeven.MODEL.reset()
    old_gate = aggregation.config.min_device_containers
    try:
        # synthetic joined samples: device has high overhead, low slope;
        # cpu the reverse -> crossover where device starts winning
        samples = []
        for rows in (32, 64, 128, 256):
            for _ in range(3):
                samples.append({
                    "site": "agg.dispatch", "engine": "device",
                    "measured_s": (500.0 + rows * 1.0) / 1e6,
                    "inputs": {"rows": rows},
                })
                samples.append({
                    "site": "agg.dispatch", "engine": "per-container",
                    "measured_s": (10.0 + rows * 5.0) / 1e6,
                    "inputs": {"rows": rows},
                })
        rep = breakeven.MODEL.refit_from_outcomes(samples)
        assert rep["provenance"] == "refit-from-traffic"
        assert "gate_rows" in rep["moved"]
        # crossover of 500 + n = 10 + 5n -> n = 122.5 -> gate 123
        assert breakeven.MODEL.gate_rows == 123
        assert aggregation.config.min_device_containers == 123
        # state round-trips and reapplies the gate
        d = breakeven.MODEL.to_dict()
        breakeven.MODEL.reset()
        aggregation.config.min_device_containers = old_gate
        assert breakeven.MODEL.from_dict(d)
        assert aggregation.config.min_device_containers == 123
    finally:
        breakeven.MODEL.reset()
        aggregation.config.min_device_containers = old_gate


def test_priced_eviction_scores_residency_pricing():
    """Once the residency authority has learned a kind's re-pack cost,
    the pack cache prices evictions of that kind (est_us on the evict
    decision) and the evict-regret join scores the pricing with an
    error ratio — the fourth authority's verdicts become auditable like
    the other three (ISSUE 12)."""
    from roaringbitmap_tpu.cost import residency
    from roaringbitmap_tpu.parallel.store import PackCache

    residency.MODEL.reset()
    cache = PackCache(max_bytes=1000)
    try:
        residency.MODEL.refit_from_outcomes([
            {"site": "pack_cache.evict", "engine": "rebuild",
             "measured_s": 0.001, "inputs": {"kind": "bsi"}},
        ])
        cache.get_or_build(("bsi", "k1"), lambda: ("v1", 800))
        cache.get_or_build(("bsi", "k2"), lambda: ("v2", 800))  # evicts k1
        ev = [d for d in decisions.decisions()
              if d["site"] == "pack_cache.evict"]
        assert ev, "eviction recorded no decision"
        est = ev[-1]["inputs"].get("est_us")
        assert est and est["rebuild"] == pytest.approx(1000.0), ev[-1]
        # the re-build of the remembered eviction joins with BOTH the
        # measured regret and a scored prediction
        def rebuild():
            time.sleep(0.001)
            return ("v1b", 800)

        cache.get_or_build(("bsi", "k1"), rebuild)
        joins = [e for e in outcomes.tail()
                 if e["site"] == "pack_cache.evict"]
        assert joins, "re-build did not join the evict decision"
        assert joins[-1]["regret_s"] > 0
        assert joins[-1]["error_ratio"] is not None
    finally:
        cache.close()
        residency.MODEL.reset()


def test_residency_authority_learns_repack_cost_from_evict_regret():
    from roaringbitmap_tpu.cost import residency

    residency.MODEL.reset()
    try:
        samples = [
            {"site": "pack_cache.evict", "engine": "repack",
             "measured_s": 0.04, "inputs": {"kind": "agg", "bytes": 1 << 20}},
            {"site": "pack_cache.evict", "engine": "repack",
             "measured_s": 0.06, "inputs": {"kind": "agg", "bytes": 1 << 20}},
        ]
        rep = residency.MODEL.refit_from_outcomes(samples)
        assert rep["provenance"] == "refit-from-traffic"
        curves = residency.MODEL.curves_view()
        assert 0.04 <= curves["repack_s"]["agg"] <= 0.06
        # the ship coefficient is the columnar calibration's — shared,
        # not re-measured
        assert curves["ship_us_per_row"] == costmodel.MODEL.ship_us_per_row
    finally:
        residency.MODEL.reset()


def test_residency_refit_consumes_ledger_samples_at_most_once():
    """The sentinel re-runs refit_all against the SAME retained ledger
    every cooldown: ledger-sourced samples (seq-carrying) must fold into
    the EWMA at most once — a second refit over an unchanged ledger is a
    no-op (review regression: re-folding walked the EWMA and
    double-counted samples)."""
    from roaringbitmap_tpu.cost import residency

    residency.MODEL.reset()
    try:
        for s in (0.04, 0.06):
            seq = decisions.record_decision(
                "pack_cache.evict", "lru", outcome=True, kind="agg",
                bytes=1 << 20,
            )
            outcomes.resolve(seq, "pack_cache.evict", s, engine="repack",
                             regret_s=s)
        residency.MODEL.refit_from_outcomes()
        first = residency.MODEL.curves_view()["repack_s"]["agg"]
        n_first = residency.MODEL.samples["agg"]
        rep2 = residency.MODEL.refit_from_outcomes()
        assert rep2["moved"] == {}, "unchanged ledger moved the EWMA"
        assert residency.MODEL.curves_view()["repack_s"]["agg"] == first
        assert residency.MODEL.samples["agg"] == n_first
        # NEW traffic still folds
        seq = decisions.record_decision(
            "pack_cache.evict", "lru", outcome=True, kind="agg", bytes=1,
        )
        outcomes.resolve(seq, "pack_cache.evict", 0.10, engine="repack",
                         regret_s=0.10)
        rep3 = residency.MODEL.refit_from_outcomes()
        assert "agg" in rep3["moved"]
    finally:
        residency.MODEL.reset()


def test_cost_state_rejects_foreign_backend_for_new_authorities():
    """Breakeven curves and residency re-pack costs are per-host
    measurements: a state stamped with another backend must be refused,
    leaving this host's gate/config untouched (review regression)."""
    from roaringbitmap_tpu.cost import breakeven, residency
    from roaringbitmap_tpu.parallel import aggregation

    old_gate = aggregation.config.min_device_containers
    breakeven.MODEL.reset()
    residency.MODEL.reset()
    try:
        assert not breakeven.MODEL.from_dict({
            "schema": breakeven.SCHEMA, "backend": "tpu",
            "curves": {"device": [1.0, 0.01]}, "gate_rows": 16,
        })
        assert aggregation.config.min_device_containers == old_gate
        assert not residency.MODEL.from_dict({
            "schema": residency.SCHEMA, "backend": "tpu",
            "repack_s": {"agg": 0.5},
        })
        assert residency.MODEL.curves_view()["repack_s"] == {}
        # backend-less (legacy/hand-written) states still load
        assert breakeven.MODEL.from_dict({
            "schema": breakeven.SCHEMA,
            "curves": {"per-container": [1.0, 0.01]},
        })
    finally:
        breakeven.MODEL.reset()
        residency.MODEL.reset()
        aggregation.config.min_device_containers = old_gate


# ---------------------------------------------------------------------------
# flight bundles + the unified artifact sink
# ---------------------------------------------------------------------------


def test_bundle_write_manifest_round_trip(tmp_path):
    s = sentinel.Sentinel(clock=lambda: 0.0)
    s.tick(now=0.0, snap=_stub_snap())
    path = bundle.write_bundle(
        "test-reason", trigger={"why": "test"}, dir=str(tmp_path),
        health_dump=s.health_dump(),
    )
    assert os.path.dirname(path) == str(tmp_path)
    m = bundle.read_manifest(path)  # verifies sizes + sha256
    assert m["schema"] == bundle.SCHEMA
    assert m["reason"] == "test-reason"
    assert m["trigger"] == {"why": "test"}
    assert set(m["files"]) == {
        "timeline.jsonl", "decisions.json", "outcomes.json", "metrics.jsonl",
        "calibration.json", "observatory.json", "health.json",
    }
    # sections parse and carry their schemas/content
    with open(os.path.join(path, "calibration.json")) as f:
        cal = json.load(f)
    assert cal["schema"] == cost.STATE_SCHEMA
    with open(os.path.join(path, "health.json")) as f:
        hd = json.load(f)
    assert hd["status_name"] == "green"
    assert "rules" in hd
    first = open(os.path.join(path, "timeline.jsonl")).readline()
    assert json.loads(first)["schema"] == tl.DUMP_SCHEMA
    # tamper detection
    with open(os.path.join(path, "decisions.json"), "a") as f:
        f.write("tampered\n")
    with pytest.raises(ValueError):
        bundle.read_manifest(path)
    # no temp directory left behind
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp-")]


def test_artifact_sink_routes_bare_names_not_cwd(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    old = artifacts.artifact_dir()
    sink = tmp_path / "sink"
    artifacts.configure(dir=str(sink))
    try:
        assert artifacts.resolve("foo.jsonl") == str(sink / "foo.jsonl")
        # explicit paths (anything with a directory component) win
        assert artifacts.resolve("/abs/x.jsonl") == "/abs/x.jsonl"
        assert artifacts.resolve("rel/x.jsonl") == "rel/x.jsonl"
        # a timeline anomaly dump with the DEFAULT bare name lands in the
        # sink, and nothing lands loose in the CWD
        prev_mode = tl.mode_name()
        tl.configure(mode="on", budget_ms=0.0001,
                     dump_path="rb_tpu_timeline_anomaly.jsonl")
        try:
            with tl.tspan("slow-span", "test"):
                time.sleep(0.002)
        finally:
            tl.configure(mode=prev_mode, budget_ms=0)
        deadline = time.monotonic() + 5.0
        target = sink / "rb_tpu_timeline_anomaly.jsonl"
        while not target.is_file() and time.monotonic() < deadline:
            time.sleep(0.01)  # the dump writer is a daemon thread
        assert target.is_file(), "anomaly dump did not land in the sink"
        assert not [
            f for f in os.listdir(tmp_path) if f.endswith(".jsonl")
        ], "anomaly dump leaked into the CWD"
    finally:
        artifacts.configure(dir=old)
        tl.configure(dump_path="rb_tpu_timeline_anomaly.jsonl")


def test_sentinel_red_tick_writes_bundle_into_sink(tmp_path):
    old = artifacts.artifact_dir()
    artifacts.configure(dir=str(tmp_path / "sink"))
    try:
        dial = _Dial(500.0)
        rule = health.Rule("red-rule", "", dial, warn=10.0, critical=100.0,
                           fire_after=1, clear_after=1)
        s = _mk(rule)
        r = s.tick(now=0.0, snap=_stub_snap())
        assert r["status_name"] == "red"
        bundles = [a for a in r["actuated"] if a["kind"] == "bundle"]
        assert len(bundles) == 1 and "path" in bundles[0]
        assert bundles[0]["path"].startswith(str(tmp_path / "sink"))
        m = bundle.read_manifest(bundles[0]["path"])
        assert m["trigger"]["rules"]["red-rule"]["level"] == "critical"
        with open(os.path.join(bundles[0]["path"], "health.json")) as f:
            hd = json.load(f)
        assert hd["rules"]["red-rule"]["level"] == health.CRITICAL
        assert hd["rules"]["red-rule"]["history"], "rule history missing"
    finally:
        artifacts.configure(dir=old)


# ---------------------------------------------------------------------------
# read APIs: insights.health(), sidecar health block, observatory
# ---------------------------------------------------------------------------


def test_insights_health_and_sidecar_block():
    dial = _Dial(50.0)
    rule = health.Rule("side-rule", "", dial, warn=10.0, critical=100.0,
                       fire_after=1, clear_after=1)
    s = sentinel.Sentinel(rules=(rule,), clock=lambda: 0.0)
    s.tick(now=0.0, snap=_stub_snap())
    # the sidecar block is a pure registry derivation
    from roaringbitmap_tpu.observe import export as obs_export

    side = obs_export.sidecar_snapshot()
    h = side["health"]
    assert h["status"] == health.WARN and h["status_name"] == "yellow"
    assert h["rules"].get("side-rule") == health.WARN
    # the live insights view reads the PROCESS sentinel
    live = insights.health()
    assert {"status", "status_name", "rules", "actuations"} <= set(live)
    obs = insights.observatory()
    assert "health" in obs


# ---------------------------------------------------------------------------
# 16-thread hammer: sentinel state is a leaf lock
# ---------------------------------------------------------------------------


def test_sentinel_hammer_16_threads_lockwitness_leaf():
    w = LockWitness()
    s = sentinel.Sentinel(
        rules=health.DEFAULT_RULES, refit_cooldown_s=1e9, bundle_cooldown_s=1e9
    )
    sent_lock = s._lock
    s._lock = w.wrap("sentinel.state", sent_lock)
    reg_lock = observe.REGISTRY._lock
    observe.REGISTRY._lock = w.wrap("registry", reg_lock)
    led_lock = outcomes.LEDGER._lock
    outcomes.LEDGER._lock = w.wrap("outcomes.ledger", led_lock)
    log_lock = decisions.LOG._lock
    decisions.LOG._lock = w.wrap("decisions.log", log_lock)
    rec_lock = tl.RECORDER._lock
    tl.RECORDER._lock = w.wrap("recorder", rec_lock)
    prev_mode = tl.mode_name()
    tl.configure(mode="on")
    stop = time.monotonic() + 1.0
    errors = []

    def ticker():
        while time.monotonic() < stop:
            try:
                s.tick(snap=health.snapshot(refresh_hbm=False))
                s.rule_states()
                s.health_dump()
            except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)

    def traffic(i):
        k = 0
        while time.monotonic() < stop:
            k += 1
            try:
                seq = decisions.record_decision(
                    "columnar.cutoff", "columnar-cpu", outcome=True,
                    na=20 + i, nb=20, shape="run", op="and",
                    est_us={"columnar-cpu": 50.0, "per-container": 80.0},
                )
                outcomes.resolve(seq, "columnar.cutoff", 60e-6,
                                 engine="columnar-cpu")
            except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)

    threads = [threading.Thread(target=ticker) for _ in range(4)]
    threads += [threading.Thread(target=traffic, args=(i,)) for i in range(12)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        tl.configure(mode=prev_mode)
        s._lock = sent_lock
        observe.REGISTRY._lock = reg_lock
        outcomes.LEDGER._lock = led_lock
        decisions.LOG._lock = log_lock
        tl.RECORDER._lock = rec_lock
    assert not errors, errors[:3]
    w.assert_consistent()
    assert w.acquisitions.get("sentinel.state", 0) > 0
    # leaf property: NOTHING is acquired while holding the sentinel lock
    assert not [e for e in w.edges if e[0] == "sentinel.state"], sorted(w.edges)


# ---------------------------------------------------------------------------
# off-mode zero-allocation pin (the inline pacing hook)
# ---------------------------------------------------------------------------


def test_inline_hook_off_mode_allocates_nothing(monkeypatch):
    """RB_TPU_SENTINEL unset => maybe_tick() is one module-bool check:
    no snapshot built, no tick run, nothing allocated (the timeline
    off-mode discipline applied to the sentinel)."""
    assert not sentinel.running()  # conftest never sets RB_TPU_SENTINEL

    def boom(*a, **k):
        raise AssertionError("sentinel work ran while inline mode is off")

    monkeypatch.setattr(sentinel.SENTINEL, "tick", boom)
    monkeypatch.setattr(health, "snapshot", boom)
    monkeypatch.setattr(health, "Snapshot", boom)
    for _ in range(100):
        assert sentinel.maybe_tick() is False
    # armed inline, the hook ticks at most once per interval
    ticks = []
    monkeypatch.setattr(sentinel.SENTINEL, "tick", lambda: ticks.append(1))
    sentinel.configure(inline=True, inline_interval_s=3600.0)
    try:
        for _ in range(50):
            sentinel.maybe_tick()
        assert len(ticks) == 1
    finally:
        sentinel.configure(inline=False)


def test_inline_hook_rides_the_aggregation_dispatch(monkeypatch):
    from roaringbitmap_tpu.parallel import aggregation

    ticks = []
    monkeypatch.setattr(sentinel.SENTINEL, "tick", lambda: ticks.append(1))
    sentinel.configure(inline=True, inline_interval_s=0.0)
    try:
        bms = [RoaringBitmap(np.arange(i, 5000 + i, 7)) for i in range(4)]
        aggregation.FastAggregation.or_(*bms, mode="cpu")
        assert ticks, "dispatch path never consulted the inline hook"
    finally:
        sentinel.configure(inline=False)


def test_background_thread_start_stop():
    sentinel.start(interval_s=0.01)
    try:
        assert sentinel.running()
        deadline = time.monotonic() + 5.0
        while sentinel.SENTINEL._tick_no == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sentinel.SENTINEL._tick_no > 0, "thread never ticked"
    finally:
        sentinel.stop()
    assert not sentinel.running()
