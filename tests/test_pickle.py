"""Pickle round-trips — the Externalizable/Kryo analogue (SURVEY §5
checkpoint/resume: RoaringBitmap.java:2627/3287, Kryo recipe
README.md:285-312). Every serializable facade must pickle to its own type
through the portable wire format."""

import pickle

import numpy as np
import pytest

from roaringbitmap_tpu import (
    FastRankRoaringBitmap,
    ImmutableBitSliceIndex,
    ImmutableRoaringBitmap,
    MutableBitSliceIndex,
    MutableRoaringBitmap,
    RangeBitmap,
    Roaring64Bitmap,
    Roaring64BitmapSliceIndex,
    Roaring64NavigableMap,
    RoaringBitmap,
    RoaringBitmapSliceIndex,
    RoaringBitSet,
)


def roundtrip(obj):
    back = pickle.loads(pickle.dumps(obj))
    assert type(back) is type(obj)
    return back


@pytest.mark.parametrize("cls", [RoaringBitmap, MutableRoaringBitmap, FastRankRoaringBitmap])
def test_roaring_family(cls):
    b = cls()
    b.add_many([0, 7, 65536, 1 << 20, (1 << 32) - 1])
    b.run_optimize()
    assert roundtrip(b) == b


def test_empty():
    assert roundtrip(RoaringBitmap()) == RoaringBitmap()


def test_immutable():
    src = RoaringBitmap(np.arange(100, 200, dtype=np.uint32))
    imm = ImmutableRoaringBitmap(src.serialize())
    back = roundtrip(imm)
    assert back.get_cardinality() == 100 and back.serialize() == imm.serialize()


@pytest.mark.parametrize("cls", [Roaring64Bitmap, Roaring64NavigableMap])
def test_64bit(cls):
    b = cls()
    b.add_many([1, 2, 1 << 40, (1 << 63) + 5])
    back = roundtrip(b)
    assert back == b


def test_64_signed_flag():
    b = Roaring64NavigableMap(signed_longs=True)
    b.add(5)
    assert roundtrip(b).signed_longs is True


@pytest.mark.parametrize(
    "cls", [RoaringBitmapSliceIndex, MutableBitSliceIndex, Roaring64BitmapSliceIndex]
)
def test_bsi(cls):
    bsi = cls()
    bsi.set_values([(i, i * 37 % 1000) for i in range(500)])
    back = roundtrip(bsi)
    assert back.get_value(3) == bsi.get_value(3)
    assert back.get_cardinality() == bsi.get_cardinality()


def test_immutable_bsi():
    base = MutableBitSliceIndex()
    base.set_values([(i, i + 1) for i in range(100)])
    imm = ImmutableBitSliceIndex(base.serialize())
    back = roundtrip(imm)
    assert back.get_value(50) == imm.get_value(50)


def test_range_bitmap():
    app = RangeBitmap.appender(10_000)
    app.add_many(range(0, 10_000, 3))
    rb = app.build()
    back = roundtrip(rb)
    assert back.lte_cardinality(5000) == rb.lte_cardinality(5000)


def test_bitset():
    bs = RoaringBitSet()
    bs.set_range(10, 50)
    assert roundtrip(bs) == bs


def test_64_supplier_survives_pickle():
    m = Roaring64NavigableMap(supplier=MutableRoaringBitmap)
    m.add(5)
    back = pickle.loads(pickle.dumps(m))
    assert back.supplier is MutableRoaringBitmap
    # pre-existing buckets are re-adopted into the supplier's type too
    assert type(back._buckets[0]) is MutableRoaringBitmap
    back.add(1 << 40)
    assert type(back._buckets[1 << 8]) is MutableRoaringBitmap
