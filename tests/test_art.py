"""ART trie unit tests (reference oracles: art/Node4Test, Node16Test,
Node48Test, Node256Test, plus Art insert/find/remove/iteration behavior,
art/Art.java:35/:47) and cross-design equivalence of the two 64-bit
bitmaps (SURVEY §4's cross-implementation oracle pattern)."""

import numpy as np
import pytest

from roaringbitmap_tpu import Roaring64Bitmap, Roaring64NavigableMap
from roaringbitmap_tpu.models.art import Art

rng = np.random.default_rng(0xFEEF1F0)


def k6(x: int) -> bytes:
    return int(x).to_bytes(6, "big")


class TestArt:
    def test_insert_find(self):
        art = Art()
        assert art.find(k6(1)) is None
        for i in range(100):
            art.insert(k6(i * 7919), i)
        assert len(art) == 100
        for i in range(100):
            assert art.find(k6(i * 7919)) == i
        assert art.find(k6(5)) is None

    def test_replace(self):
        art = Art()
        art.insert(k6(42), "a")
        art.insert(k6(42), "b")
        assert len(art) == 1
        assert art.find(k6(42)) == "b"

    @pytest.mark.parametrize("n", [1, 3, 5, 17, 49, 200, 256])
    def test_node_growth_levels(self, n):
        """Exercise Node4 -> Node16 -> Node48 -> Node256 upgrades by
        fanning out n children under one parent byte position."""
        art = Art()
        # all keys share the first 5 bytes -> one node with n children
        for i in range(n):
            art.insert(bytes([1, 2, 3, 4, 5, i]), i)
        assert len(art) == n
        for i in range(n):
            assert art.find(bytes([1, 2, 3, 4, 5, i])) == i
        got = [int.from_bytes(k, "big") & 0xFF for k, _ in art.items()]
        assert got == sorted(got)

    def test_ordered_iteration_random(self):
        art = Art()
        keys = rng.integers(0, 1 << 48, size=500, dtype=np.uint64)
        for k in np.unique(keys):
            art.insert(k6(int(k)), int(k))
        seq = [v for _, v in art.items()]
        assert seq == sorted(seq)
        rev = [v for _, v in art.items_reverse()]
        assert rev == sorted(seq, reverse=True)
        assert art.first()[1] == seq[0]
        assert art.last()[1] == seq[-1]

    def test_items_from(self):
        art = Art()
        vals = sorted({int(x) for x in rng.integers(0, 1 << 20, size=300)})
        for v in vals:
            art.insert(k6(v), v)
        for probe in [0, vals[0], vals[10] + 1, vals[-1], vals[-1] + 5]:
            want = [v for v in vals if v >= probe]
            got = [v for _, v in art.items_from(k6(probe))]
            assert got == want, f"probe {probe}"

    def test_remove_and_path_compression(self):
        art = Art()
        vals = sorted({int(x) for x in rng.integers(0, 1 << 30, size=400)})
        for v in vals:
            art.insert(k6(v), v)
        removed = set(vals[::3])
        for v in removed:
            assert art.remove(k6(v))
            assert not art.remove(k6(v))  # second remove is a no-op
        remaining = [v for v in vals if v not in removed]
        assert len(art) == len(remaining)
        assert [v for _, v in art.items()] == remaining
        for v in remaining:
            assert art.find(k6(v)) == v
        for v in removed:
            assert art.find(k6(v)) is None

    def test_remove_everything(self):
        art = Art()
        for i in range(60):
            art.insert(k6(i), i)
        for i in range(60):
            assert art.remove(k6(i))
        assert art.is_empty()
        assert art.first() is None

    def test_node_downgrade(self):
        """Fill past 48 children (table form), then remove back below the
        downgrade threshold; order and lookups must survive."""
        art = Art()
        for i in range(256):
            art.insert(bytes([9, 9, 9, 9, 9, i]), i)
        for i in range(0, 256, 2):
            art.remove(bytes([9, 9, 9, 9, 9, i]))
        kept = list(range(1, 256, 2))
        assert [v for _, v in art.items()] == kept
        for i in kept:
            assert art.find(bytes([9, 9, 9, 9, 9, i])) == i


class TestCrossDesign64:
    """The two 64-bit designs must agree on everything (the reference's
    heap-vs-buffer-vs-64-bit agreement oracle, SURVEY §4)."""

    def _pair(self, vals):
        return Roaring64Bitmap(vals), Roaring64NavigableMap(vals)

    def random_values(self, n=3000):
        mix = np.concatenate(
            [
                rng.integers(0, 1 << 20, size=n // 3, dtype=np.uint64),
                rng.integers(0, 1 << 48, size=n // 3, dtype=np.uint64),
                rng.integers(0, 1 << 64, size=n // 3, dtype=np.uint64),
            ]
        )
        return np.unique(mix)

    def test_construction_and_order_stats(self):
        vals = self.random_values()
        art_bm, nav_bm = self._pair(vals)
        assert art_bm.get_cardinality() == nav_bm.get_cardinality() == vals.size
        assert np.array_equal(art_bm.to_array(), nav_bm.to_array())
        assert art_bm.first() == nav_bm.first() == int(vals[0])
        assert art_bm.last() == nav_bm.last() == int(vals[-1])
        for j in [0, 17, int(vals.size) - 1]:
            assert art_bm.select(j) == nav_bm.select(j)
        for probe in vals[::500]:
            p = int(probe)
            assert art_bm.rank(p) == nav_bm.rank(p)
            assert art_bm.contains(p) and nav_bm.contains(p)
            assert art_bm.next_value(p) == nav_bm.next_value(p) == p
        assert art_bm.next_value(int(vals[0]) + 1) == nav_bm.next_value(int(vals[0]) + 1)
        assert art_bm.previous_value(int(vals[-1]) - 1) == nav_bm.previous_value(
            int(vals[-1]) - 1
        )

    def test_algebra_agreement(self):
        a_vals, b_vals = self.random_values(2000), self.random_values(2000)
        a1, a2 = self._pair(a_vals)
        b1, b2 = self._pair(b_vals)
        for op in ("or_", "and_", "xor", "andnot"):
            r1 = getattr(Roaring64Bitmap, op)(a1, b1)
            r2 = getattr(Roaring64NavigableMap, op)(a2, b2)
            assert np.array_equal(r1.to_array(), r2.to_array()), op

    def test_serialization_interop(self):
        """Both designs speak the portable spec byte-for-byte."""
        vals = self.random_values(1500)
        art_bm, nav_bm = self._pair(vals)
        assert art_bm.serialize() == nav_bm.serialize_portable()
        back = Roaring64NavigableMap.deserialize_portable(art_bm.serialize())
        assert np.array_equal(back.to_array(), vals)
        back2 = Roaring64Bitmap.deserialize(nav_bm.serialize_portable())
        assert np.array_equal(back2.to_array(), vals)

    def test_ranges_and_mutation(self):
        art_bm, nav_bm = self._pair([1, 2, 3])
        for bm in (art_bm, nav_bm):
            bm.add_range(100, 200_000)
            bm.remove_range(150, 400)
            bm.flip_range(190_000, 210_000)
            bm.add((1 << 50) + 7)
            bm.remove(2)
        assert np.array_equal(art_bm.to_array(), nav_bm.to_array())
        assert art_bm.run_optimize() == nav_bm.run_optimize()
        assert np.array_equal(art_bm.to_array(), nav_bm.to_array())


class TestNavigableMapModes:
    def test_legacy_round_trip(self):
        vals = [1, 1 << 33, (1 << 63) + 5, 0xFFFF_FFFF_FFFF_FFFF]
        bm = Roaring64NavigableMap(vals)
        data = bm.serialize_legacy()
        back = Roaring64NavigableMap.deserialize_legacy(data)
        assert np.array_equal(back.to_array(), bm.to_array())
        assert data[0] == 0  # unsigned flag
        assert bm.serialized_size_in_bytes(mode=0) == len(data)

    def test_mode_switch(self):
        vals = [5, 1 << 40]
        bm = Roaring64NavigableMap(vals)
        try:
            Roaring64NavigableMap.SERIALIZATION_MODE = 0  # legacy
            data = bm.serialize()
            back = Roaring64NavigableMap.deserialize(data)
            assert np.array_equal(back.to_array(), bm.to_array())
        finally:
            Roaring64NavigableMap.SERIALIZATION_MODE = 1
        assert bm.serialize() == bm.serialize_portable()

    def test_signed_ordering(self):
        vals = [5, (1 << 63) + 1, 10]
        bm = Roaring64NavigableMap(vals, signed_longs=True)
        # two's-complement order: negative half first
        assert bm.first() == (1 << 63) + 1
        assert bm.last() == 10
        arr = bm.to_array().tolist()
        assert arr == [(1 << 63) + 1, 5, 10]
        assert bm.select(0) == (1 << 63) + 1
        assert bm.rank(6) == 2  # the negative value and 5
        legacy = bm.serialize_legacy()
        assert legacy[0] == 1
        back = Roaring64NavigableMap.deserialize_legacy(legacy)
        assert back.signed_longs
        assert np.array_equal(back.to_array(), bm.to_array())


def test_bulk_load_equivalent_to_incremental():
    """Art.bulk_load (one bottom-up pass over sorted distinct keys) must
    produce byte-identical traversal order, size, and adaptive-width
    histogram to per-key insert — and refuse a non-empty trie."""
    import numpy as np
    import pytest

    from roaringbitmap_tpu.models.art import Art

    rng = np.random.default_rng(77)
    keys = sorted({rng.integers(0, 1 << 48).item().to_bytes(6, "big") for _ in range(4000)})
    bulk, incr = Art(), Art()
    bulk.bulk_load([(k, i) for i, k in enumerate(keys)])
    for i, k in enumerate(keys):
        incr.insert(k, i)
    assert len(bulk) == len(incr) == len(keys)
    assert list(bulk.items()) == list(incr.items())
    assert list(bulk.items_reverse()) == list(incr.items_reverse())
    assert bulk.node_width_histogram() == incr.node_width_histogram()
    mid = keys[len(keys) // 2]
    assert list(bulk.items_from(mid)) == list(incr.items_from(mid))
    assert list(bulk.items_to(mid)) == list(incr.items_to(mid))
    assert bulk.find(mid) == incr.find(mid)
    with pytest.raises(ValueError):
        bulk.bulk_load([(keys[0], 0)])
    empty = Art()
    empty.bulk_load([])
    assert empty.is_empty()


def test_roaring64art_bulk_ingest_matches_chunked():
    """Roaring64Bitmap.add_many's empty-trie bulk path == the incremental
    (non-empty trie) path over the same values, incl. mutation after."""
    import numpy as np

    from roaringbitmap_tpu import Roaring64Bitmap

    rng = np.random.default_rng(78)
    vals = np.unique(rng.choice(1 << 44, 60_000, replace=True).astype(np.uint64))
    a = Roaring64Bitmap()
    a.add_many(vals)
    b = Roaring64Bitmap()
    for chunk in np.array_split(vals, 5):
        b.add_many(chunk)
    assert np.array_equal(a.to_array(), vals)
    assert a == b
    a.add(123456789)
    a.remove(int(vals[7]))
    assert a.contains(123456789) and not a.contains(int(vals[7]))


def test_backward_shuttle_streams_in_odepth_memory():
    """Reverse traversal is the explicit-stack BackwardShuttle
    (art/BackwardShuttle.java:1 / AbstractShuttle.java:1): O(depth) live
    frames, never a materialized node list — pinned by a tracemalloc bound
    far below what reversed(list(items())) would allocate, plus exact
    equality with the reversed forward order."""
    import tracemalloc

    from roaringbitmap_tpu.models.art import Art

    rng = np.random.default_rng(99)
    keys = np.unique(rng.integers(0, 1 << 48, 200_000).astype(np.uint64))
    art = Art()
    art.bulk_load([(int(k).to_bytes(6, "big"), i) for i, k in enumerate(keys)])

    # equality with reversed(forward) on the full set
    fwd = list(art.items())
    assert len(fwd) == len(keys)
    it = art.items_reverse()
    # prime the generator so setup allocations (first frame) are excluded
    first = next(it)
    assert first == fwd[-1]
    expect = reversed(fwd[:-1])  # the oracle's slice stays outside the bound
    tracemalloc.start()
    rest = 0
    for (k, v), (fk, fv) in zip(it, expect):
        assert k == fk and v == fv
        rest += 1
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert rest == len(fwd) - 1
    # materializing ~200k (bytes, int) pairs costs megabytes; the shuttle's
    # live state is a handful of iterator frames
    assert peak < 256 * 1024, f"reverse walk allocated {peak} bytes"


def test_roaring64art_reverse_iterator_streams():
    """get_reverse_long_iterator rides the streaming shuttle: first values
    arrive without touching the rest of a large trie, and the full order
    equals reversed(forward)."""
    import itertools
    import tracemalloc

    from roaringbitmap_tpu import Roaring64Bitmap

    rng = np.random.default_rng(7)
    vals = np.unique(rng.integers(0, 1 << 40, 50_000).astype(np.uint64))
    bm = Roaring64Bitmap(vals)
    assert list(bm.get_reverse_long_iterator()) == vals[::-1].tolist()
    # previous_value seeks through the same backward walk
    probe = int(vals[len(vals) // 2])
    assert bm.previous_value(probe) == probe
    assert bm.previous_value(probe - 1) == int(vals[len(vals) // 2 - 1])
    # streaming: taking the top 10 values must not materialize the trie
    it = bm.get_reverse_long_iterator()
    next(it)
    tracemalloc.start()
    top = list(itertools.islice(it, 10))
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert top == vals[-11:-1][::-1].tolist()
    assert peak < 256 * 1024, f"top-10 reverse peel allocated {peak} bytes"
