"""Device kernel differential tests vs numpy references (runs on the CPU
backend with 8 virtual devices; the same code paths execute on TPU)."""

import numpy as np
import pytest

from roaringbitmap_tpu.ops import device as dev
from roaringbitmap_tpu.utils import bits


@pytest.fixture
def word_batch():
    rng = np.random.default_rng(42)
    host64 = rng.integers(0, 1 << 64, size=(37, dev.HOST_WORDS), dtype=np.uint64)
    host64[5] = 0
    host64[6] = 0xFFFFFFFFFFFFFFFF
    return host64


def test_device_word_layout_roundtrip(word_batch):
    u32 = dev.to_device_words(word_batch)
    assert u32.shape == (37, dev.DEVICE_WORDS)
    assert np.array_equal(dev.from_device_words(u32), word_batch)


def test_popcount_rows(word_batch):
    import jax.numpy as jnp

    u32 = jnp.asarray(dev.to_device_words(word_batch))
    got = np.asarray(dev.popcount_rows(u32))
    want = bits.popcount64(word_batch).sum(axis=1)
    assert np.array_equal(got, want)


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_wide_reduce(word_batch, op, npop):
    import jax.numpy as jnp

    u32 = jnp.asarray(dev.to_device_words(word_batch))
    got = dev.from_device_words(np.asarray(dev.wide_reduce(u32, op=op))[None])[0]
    want = npop.reduce(word_batch, axis=0)
    assert np.array_equal(got, want)
    red, card = dev.wide_reduce_with_cardinality(u32, op=op)
    assert int(card) == int(bits.popcount64(want).sum())


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
@pytest.mark.parametrize("stage_groups", [1, 3, 128])
def test_wide_reduce_two_stage(word_batch, op, npop, stage_groups):
    """Two-stage == flat, incl. N not a multiple of stage_groups (identity
    padding) and stage_groups > N (clamped)."""
    import jax.numpy as jnp

    u32 = jnp.asarray(dev.to_device_words(word_batch))
    red, card = dev.wide_reduce_two_stage(u32, op=op, stage_groups=stage_groups)
    want = npop.reduce(np.asarray(dev.to_device_words(word_batch)), axis=0)
    assert np.array_equal(np.asarray(red), want), (op, stage_groups)
    assert int(card) == int(np.unpackbits(want.view(np.uint8)).sum())


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_grouped_reduce(op, npop):
    import jax.numpy as jnp

    rng = np.random.default_rng(43)
    host = rng.integers(0, 1 << 64, size=(4, 5, dev.HOST_WORDS), dtype=np.uint64)
    u32 = jnp.asarray(host.view(np.uint32).reshape(4, 5, dev.DEVICE_WORDS))
    red, card = dev.grouped_reduce_with_cardinality(u32, op=op)
    for g in range(4):
        want = npop.reduce(host[g], axis=0)
        got = np.asarray(red[g]).view(np.uint64) if False else np.ascontiguousarray(np.asarray(red[g])).view(np.uint64)
        assert np.array_equal(got, want)
        assert int(card[g]) == int(bits.popcount64(want).sum())


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_segmented_reduce(op, npop):
    import jax.numpy as jnp

    rng = np.random.default_rng(44)
    host = rng.integers(0, 1 << 64, size=(11, dev.HOST_WORDS), dtype=np.uint64)
    offsets = [0, 3, 4, 9, 11]
    seg_start = np.zeros(11, dtype=bool)
    seg_start[offsets[:-1]] = True
    u32 = jnp.asarray(dev.to_device_words(host))
    vals = np.asarray(dev.segmented_reduce(u32, jnp.asarray(seg_start), op=op))
    for s, e in zip(offsets[:-1], offsets[1:]):
        want = npop.reduce(host[s:e], axis=0)
        got = np.ascontiguousarray(vals[e - 1]).view(np.uint64)
        assert np.array_equal(got, want)


def test_batched_pairwise(word_batch):
    import jax.numpy as jnp

    a = jnp.asarray(dev.to_device_words(word_batch))
    b = jnp.asarray(dev.to_device_words(word_batch[::-1].copy()))
    an = word_batch
    bn = word_batch[::-1]
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_or(a, b))), an | bn)
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_and(a, b))), an & bn)
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_xor(a, b))), an ^ bn)
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_andnot(a, b))), an & ~bn)


def test_rank_rows():
    import jax.numpy as jnp

    rng = np.random.default_rng(45)
    host = rng.integers(0, 1 << 64, size=(6, dev.HOST_WORDS), dtype=np.uint64)
    positions = np.array([0, 100, 65535, 32768, 7, 63], dtype=np.int32)
    u32 = jnp.asarray(dev.to_device_words(host))
    got = np.asarray(dev.rank_rows(u32, jnp.asarray(positions)))
    for i in range(6):
        want = bits.cardinality_in_range(host[i], 0, int(positions[i]) + 1)
        assert got[i] == want


def test_pallas_wide_reduce_interpret():
    """Pallas kernel correctness via the interpreter (real-TPU execution is
    exercised by bench.py / __graft_entry__.py on hardware)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(46)
    host = rng.integers(0, 1 << 64, size=(300, dev.HOST_WORDS), dtype=np.uint64)
    u32 = jnp.asarray(dev.to_device_words(host))
    for op, npop in [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)]:
        red, card = pk.wide_reduce_cardinality_pallas(u32, op=op, interpret=True)
        want = npop.reduce(host, axis=0)
        assert np.array_equal(np.ascontiguousarray(np.asarray(red)).view(np.uint64), want)
        assert int(card) == int(bits.popcount64(want).sum())


def test_pallas_grouped_reduce_interpret():
    """Grouped Pallas kernel vs numpy per-group fold (interpreter mode)."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    import jax.numpy as jnp

    rng = np.random.default_rng(42)
    g, m = 3, 300  # g not a multiple of G_TILE, m not of the row tile -> padding
    host = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
    for op, fold in [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)]:
        red, card = pk.grouped_reduce_cardinality_pallas(
            jnp.asarray(host), op=op, interpret=True
        )
        want = fold.reduce(host, axis=1)
        assert np.array_equal(np.asarray(red), want), op
        want_cards = [int(np.unpackbits(want[i].view(np.uint8)).sum()) for i in range(g)]
        assert np.asarray(card).tolist() == want_cards, op


# ---------------------------------------------------------------------------
# Mosaic block-spec legality — hardware-independent (VERDICT r2 #2: the round-2
# BENCH crash was a (1, 2048) grouped output block over [66, 2048], which
# interpret-mode tests can't catch; these assert the rule itself on CPU).
# ---------------------------------------------------------------------------


def test_mosaic_rule_rejects_round2_block():
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    # the exact shape that crashed BENCH_r02: block (1, 2048), array (66, 2048)
    assert not pk.mosaic_block_ok((1, 2048), (66, 2048))
    # block == array is legal even when not divisible
    assert pk.mosaic_block_ok((66, 2048), (66, 2048))
    assert pk.mosaic_block_ok((8, 2048), (66, 2048))
    assert pk.mosaic_block_ok((1, 2048), (1, 2048))
    assert not pk.mosaic_block_ok((8, 100), (66, 2048))
    # only the last two dims are constrained; leading dims are free
    assert pk.mosaic_block_ok((4, 128, 2048), (8, 256, 2048))
    assert not pk.mosaic_block_ok((4, 3, 2048), (8, 256, 2048))


@pytest.mark.parametrize("n", [1, 7, 66, 255, 256, 1000, 10_000])
def test_wide_plan_blocks_legal(n):
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    plan = pk.wide_plan(n, 2048)
    assert pk.plan_ok(plan), (plan["in_block"], plan["out_block"])
    # grid covers exactly the padded array
    assert plan["grid"][0] * pk.ROW_TILE == n + plan["pad_rows"]


@pytest.mark.parametrize("g,m", [(1, 1), (66, 151), (3, 300), (8, 64), (13, 4097)])
def test_grouped_plan_blocks_legal(g, m):
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    plan = pk.grouped_plan(g, m, 2048)
    assert pk.plan_ok(plan), (plan["in_block"], plan["out_block"])
    g_pad = g + plan["pad_groups"]
    m_pad = m + plan["pad_rows"]
    assert plan["grid"] == (g_pad // pk.G_TILE, m_pad // pk.G_ROW_TILE)
    assert plan["out_array"] == (g_pad, 2048)
    # the output block must tile the group axis in multiples of 8
    assert plan["out_block"][0] % 8 == 0


def test_broken_plan_fails_checker():
    """A deliberately broken spec (the round-2 bug reintroduced) must fail."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    plan = pk.grouped_plan(66, 151, 2048)
    broken = dict(plan, out_block=(1, 2048), out_array=(66, 2048))
    assert not pk.plan_ok(broken)


def test_mosaic_smem_rule_rejects_blocked_1d():
    """The round-3 segmented-scan failure class: a *blocked* 1-D SMEM operand
    was legal by the (8,128) rule yet died on hardware with an XLA(T(1024))
    vs Mosaic(T(128)) layout mismatch. SMEM 1-D operands must be whole-array
    (VERDICT r3 #9)."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    # the exact failing spec: s32[1024] streamed in 128-element blocks
    assert not pk.mosaic_block_ok((128,), (1024,), memory_space="smem")
    # whole-array 1-D SMEM is what seg_plan's bit-packed flags use — legal
    assert pk.mosaic_block_ok((1024,), (1024,), memory_space="smem")
    # VMEM semantics are unchanged by the parameter
    assert pk.mosaic_block_ok((128,), (1024,), memory_space="vmem")
    assert pk.mosaic_block_ok((8, 2048), (66, 2048), memory_space="smem")


@pytest.mark.parametrize("n", [7, 256, 1000])
@pytest.mark.parametrize("w_tile", [512, 1024])
def test_wide_plan_wsplit_legal(n, w_tile):
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    plan = pk.wide_plan(n, 2048, w_tile=w_tile)
    assert pk.plan_ok(plan), (plan["in_block"], plan["out_block"])
    assert plan["grid"] == (2048 // w_tile, (n + plan["pad_rows"]) // pk.ROW_TILE)
    assert plan["m_dim"] == 1  # the N walk moved to the inner grid dim


@pytest.mark.parametrize("g,m", [(3, 300), (66, 151)])
@pytest.mark.parametrize("w_tile", [512, 1024])
def test_grouped_plan_wsplit_legal(g, m, w_tile):
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    plan = pk.grouped_plan(g, m, 2048, w_tile=w_tile)
    assert pk.plan_ok(plan), (plan["in_block"], plan["out_block"])
    g_pad, m_pad = g + plan["pad_groups"], m + plan["pad_rows"]
    assert plan["grid"] == (g_pad // pk.G_TILE, 2048 // w_tile, m_pad // pk.G_ROW_TILE)
    assert plan["m_dim"] == 2
    assert plan["out_block"] == (pk.G_TILE, w_tile)


def test_wide_plan_wsplit_must_divide():
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    with pytest.raises(ValueError, match="divide"):
        pk.wide_plan(256, 2048, w_tile=600)
    with pytest.raises(ValueError, match="divide"):
        pk.grouped_plan(8, 64, 2048, w_tile=600)


def test_pallas_wide_reduce_variants_interpret():
    """The sweep-staged wide variants (w-split grid, linear fold, dimension
    semantics) must agree with numpy in interpreter mode."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(52)
    host = rng.integers(0, 1 << 32, size=(300, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    want = np.bitwise_or.reduce(host, axis=0)
    want_card = int(np.unpackbits(want.view(np.uint8)).sum())
    for kw in (
        {"w_tile": 512},
        {"fold": "linear"},
        {"w_tile": 1024, "fold": "linear", "dimsem": True},
    ):
        if kw.get("dimsem") and not pk.supports_dimension_semantics():
            # capability-probed skip (ISSUE 7): this jaxlib's pallas lacks
            # GridDimensionSemantics/CompilerParams; the plain variants
            # above were still asserted before skipping
            pytest.skip(
                "jax.experimental.pallas.tpu lacks GridDimensionSemantics: "
                "the dimsem kernel variant cannot run on this jaxlib"
            )
        red, card = pk.wide_reduce_cardinality_pallas(arr, op="or", interpret=True, **kw)
        assert np.array_equal(np.asarray(red), want), kw
        assert int(card) == want_card, kw


def test_pallas_grouped_reduce_variants_interpret():
    """The sweep-staged grouped variants vs numpy per-group folds, including
    a non-power-of-two row tile (legal with the linear fold: no halving)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(53)
    g, m = 3, 170
    host = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    want = np.bitwise_or.reduce(host, axis=1)
    for kw in (
        {"w_tile": 512},
        {"fold": "linear", "row_tile": 24},  # 24 % 8 == 0, not a power of two
        {"w_tile": 1024, "fold": "linear", "dimsem": True},
    ):
        if kw.get("dimsem") and not pk.supports_dimension_semantics():
            # capability-probed skip (ISSUE 7): see the wide variant above
            pytest.skip(
                "jax.experimental.pallas.tpu lacks GridDimensionSemantics: "
                "the dimsem kernel variant cannot run on this jaxlib"
            )
        red, cards = pk.grouped_reduce_cardinality_pallas(
            arr, op="or", interpret=True, **kw
        )
        assert np.array_equal(np.asarray(red), want), kw
        want_cards = [int(np.unpackbits(want[i].view(np.uint8)).sum()) for i in range(g)]
        assert np.asarray(cards).tolist() == want_cards, kw


def test_grouped_kernel_vmem_budget():
    """Input + output blocks (double-buffered) must fit comfortably in the
    ~16 MiB/core v5e VMEM."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    plan = pk.grouped_plan(64, 4096, 2048)
    in_bytes = 4 * plan["in_block"][0] * plan["in_block"][1] * plan["in_block"][2]
    out_bytes = 4 * plan["out_block"][0] * plan["out_block"][1]
    assert 2 * in_bytes + out_bytes <= 12 * 2**20, (in_bytes, out_bytes)


def test_best_reduce_dispatch_falls_back_off_tpu():
    """On the CPU backend the dispatchers must serve from the XLA path and
    record the choice (observability counters, VERDICT r2 #9)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(47)
    host = rng.integers(0, 1 << 32, size=(5, 3, 2048), dtype=np.uint64).astype(np.uint32)
    before = pk.DISPATCH_COUNTS[("grouped", "xla")]
    red, card = pk.best_grouped_reduce(jnp.asarray(host), op="or")
    assert pk.DISPATCH_COUNTS[("grouped", "xla")] == before + 1
    want = np.bitwise_or.reduce(host, axis=1)
    assert np.array_equal(np.asarray(red), want)


def test_probed_call_marks_bad_kernel_and_falls_back(monkeypatch):
    """A kernel that raises is probed once, marked bad, and never retried."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    calls = {"n": 0}

    def boom(words3, op="or"):
        calls["n"] += 1
        raise ValueError("mosaic says no")

    monkeypatch.setattr(pk, "grouped_reduce_cardinality_pallas", boom)
    monkeypatch.setattr(pk, "on_tpu", lambda: True)
    monkeypatch.setattr(pk, "HAS_PALLAS", True)
    # the probe mechanism under test only engages when Pallas is preferred
    monkeypatch.setattr(pk, "GROUPED_PREFER_XLA", False)
    pk._PROBED.clear()
    rng = np.random.default_rng(48)
    host = rng.integers(0, 1 << 32, size=(4, 2, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    want = np.bitwise_or.reduce(host, axis=1)
    for _ in range(3):
        red, card = pk.best_grouped_reduce(arr, op="or")
        assert np.array_equal(np.asarray(red), want)
    assert calls["n"] == 1  # probed exactly once
    pk._PROBED.clear()


def test_non_power_of_two_tile_rejected():
    """row_tile/g_tile must be powers of two: the halving fold would silently
    drop rows otherwise (code-review regression)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    arr = jnp.zeros((8, 2048), dtype=jnp.uint32)
    with pytest.raises(ValueError, match="power of two"):
        pk.wide_reduce_pallas(arr, op="or", interpret=True, row_tile=96)


def test_oneil_pallas_interpret_matches_scan():
    """Fused O'Neil Pallas kernel vs the XLA scan oracle for every op,
    including the dual-recurrence RANGE, on K not a multiple of the tile."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.models.bsi import o_neil_math
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(51)
    s, k = 6, 11  # k deliberately not a multiple of ONEIL_K_TILE
    slices = rng.integers(0, 1 << 32, size=(s, k, 2048), dtype=np.uint64).astype(np.uint32)
    ebm = np.bitwise_or.reduce(slices, axis=0)
    fixed = rng.integers(0, 1 << 32, size=(k, 2048), dtype=np.uint64).astype(np.uint32)
    predicate, hi = 0b100110, 0b110101
    bits = np.array([(predicate >> i) & 1 for i in range(s - 1, -1, -1)], dtype=bool)
    bits_hi = np.array([(hi >> i) & 1 for i in range(s - 1, -1, -1)], dtype=bool)
    for op in ("GE", "GT", "LT", "LE", "EQ", "NEQ"):
        want_out, want_cards = o_neil_math(
            jnp.asarray(slices), jnp.asarray(bits), jnp.asarray(ebm), jnp.asarray(fixed), op
        )
        got_out, got_cards = pk.oneil_compare_pallas(
            jnp.asarray(slices), jnp.asarray(bits), jnp.asarray(ebm), jnp.asarray(fixed),
            op=op, interpret=True,
        )
        assert np.array_equal(np.asarray(got_out), np.asarray(want_out)), op
        assert np.array_equal(np.asarray(got_cards), np.asarray(want_cards)), op
    bits2 = np.stack([bits, bits_hi])
    want_out, want_cards = o_neil_math(
        jnp.asarray(slices), jnp.asarray(bits2), jnp.asarray(ebm), jnp.asarray(fixed), "RANGE"
    )
    got_out, got_cards = pk.oneil_compare_pallas(
        jnp.asarray(slices), jnp.asarray(bits2), jnp.asarray(ebm), jnp.asarray(fixed),
        op="RANGE", interpret=True,
    )
    assert np.array_equal(np.asarray(got_out), np.asarray(want_out))
    assert np.array_equal(np.asarray(got_cards), np.asarray(want_cards))


@pytest.mark.parametrize("s,k", [(1, 1), (32, 11), (64, 24), (6, 1526)])
def test_oneil_plan_blocks_legal(s, k):
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    # default plan == what the kernel dispatch runs (w_tile=-1 resolution
    # lives in oneil_plan itself, so this covers the shipped layout)
    plan = pk.oneil_plan(s, k, 2048)
    assert pk.mosaic_block_ok(plan["slices_block"], plan["slices_array"])
    assert pk.mosaic_block_ok(plan["kw_block"], plan["kw_array"])
    # VMEM: double-buffered slices block + 3 kw blocks + state must fit
    _, kt, w_eff = plan["slices_block"]
    in_bytes = 4 * s * kt * w_eff
    assert 2 * in_bytes + 6 * 4 * kt * w_eff <= 12 * 2**20


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_segmented_pallas_interpret(op, npop):
    """One-pass Pallas segmented scan vs numpy per-segment folds, with
    segment boundaries straddling row tiles (interpret mode)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(61)
    n = 300  # not a multiple of SEG_ROW_TILE; several segments per tile
    host = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint64).astype(np.uint32)
    offsets = [0, 1, 5, 130, 131, 250, n]
    seg_start = np.zeros(n, dtype=bool)
    seg_start[offsets[:-1]] = True
    vals = np.asarray(
        pk.segmented_reduce_pallas(
            jnp.asarray(host), jnp.asarray(seg_start), op=op, interpret=True
        )
    )
    for s, e in zip(offsets[:-1], offsets[1:]):
        want = npop.reduce(host[s:e], axis=0)
        assert np.array_equal(vals[e - 1], want), (op, s, e)


def test_seg_plan_blocks_legal():
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    for n in (1, 127, 128, 300, 4096):
        plan = pk.seg_plan(n, 2048)
        assert pk.mosaic_block_ok(plan["rows_block"], plan["rows_array"])
        assert plan["grid"][0] * pk.SEG_ROW_TILE == n + plan["pad_rows"]


def test_segmented_pallas_unflagged_prefix_matches_xla():
    """seg_start[0]=False is legal: rows before the first flag must fold
    from the op identity exactly like the XLA scan (code-review regression:
    a zero-initialized accumulator broke op='and')."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(62)
    n = 10
    host = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint64).astype(np.uint32)
    seg = np.zeros(n, dtype=bool)
    seg[4] = True
    for op in ("and", "or", "xor"):
        want = np.asarray(dev.segmented_reduce(jnp.asarray(host), jnp.asarray(seg), op=op))
        got = np.asarray(
            pk.segmented_reduce_pallas(jnp.asarray(host), jnp.asarray(seg), op=op, interpret=True)
        )
        assert np.array_equal(got, want), op


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_grouped_pallas_linear_fold_interpret(op, npop):
    """fold='linear' (the staged accumulate variant) == fold='log' == numpy
    (interpret mode; the on-chip comparison lives in scripts/tile_sweep.py)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(71)
    host = rng.integers(0, 1 << 32, size=(5, 9, 2048), dtype=np.uint64).astype(np.uint32)
    want = npop.reduce(host, axis=1)
    want_cards = [int(np.unpackbits(want[g].view(np.uint8)).sum()) for g in range(5)]
    for fold in ("log", "linear"):
        red, cards = pk.grouped_reduce_cardinality_pallas(
            jnp.asarray(host), op=op, interpret=True, fold=fold
        )
        assert np.array_equal(np.asarray(red), want), (op, fold)
        assert np.asarray(cards).tolist() == want_cards, (op, fold)
    with pytest.raises(ValueError):
        pk.grouped_reduce_pallas(jnp.asarray(host), op=op, interpret=True, fold="lin")


def test_w_tile_must_be_mosaic_legal():
    """w_tile values that divide the width but violate the 128-minor rule
    must be rejected in the plan, not on chip (code-review r4)."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    with pytest.raises(ValueError, match="128"):
        pk.wide_plan(256, 2048, w_tile=64)
    with pytest.raises(ValueError, match="128"):
        pk.grouped_plan(8, 64, 2048, w_tile=64)


def test_grouped_pallas_config_reaches_kernel(monkeypatch):
    """A sweep-crowned tiling in GROUPED_PALLAS_CONFIG must be applied by
    the dispatcher (flipping GROUPED_PREFER_XLA alone would otherwise
    serve the default tiling, not the measured winner), and changing the
    config must re-probe rather than reuse a stale verdict."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    seen = []

    def fake_kernel(words3, op="or", **kw):
        seen.append(kw)
        import numpy as _np

        host = _np.asarray(words3)
        red = _np.bitwise_or.reduce(host, axis=1)
        cards = _np.unpackbits(red.view(_np.uint8), axis=-1).sum(axis=-1)
        return jnp.asarray(red), jnp.asarray(cards.astype(_np.int32))

    monkeypatch.setattr(pk, "grouped_reduce_cardinality_pallas", fake_kernel)
    monkeypatch.setattr(pk, "on_tpu", lambda: True)
    monkeypatch.setattr(pk, "HAS_PALLAS", True)
    monkeypatch.setattr(pk, "GROUPED_PREFER_XLA", False)
    cfg = {"row_tile": 128, "w_tile": 512, "fold": "linear"}
    monkeypatch.setattr(pk, "GROUPED_PALLAS_CONFIG", cfg)
    pk._PROBED.clear()
    rng = np.random.default_rng(71)
    host = rng.integers(0, 1 << 32, size=(4, 3, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    red, _ = pk.best_grouped_reduce(arr, op="or")
    assert np.array_equal(np.asarray(red), np.bitwise_or.reduce(host, axis=1))
    assert seen[-1] == cfg
    # a different config is a different probe key: the kernel is probed again
    monkeypatch.setattr(pk, "GROUPED_PALLAS_CONFIG", {"row_tile": 64})
    n_before = len(seen)
    pk.best_grouped_reduce(arr, op="or")
    assert len(seen) > n_before and seen[-1] == {"row_tile": 64}
    pk._PROBED.clear()


def test_grouped_pallas_config_validated_loudly(monkeypatch):
    """Misconfiguration must raise, not silently pin the XLA fallback via
    a probe marked bad (code-review r4)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "on_tpu", lambda: True)
    monkeypatch.setattr(pk, "HAS_PALLAS", True)
    monkeypatch.setattr(pk, "GROUPED_PREFER_XLA", False)
    arr = jnp.zeros((2, 2, 2048), dtype=jnp.uint32)
    monkeypatch.setattr(pk, "GROUPED_PALLAS_CONFIG", {"rowtile": 128})  # typo
    with pytest.raises(ValueError, match="unknown keys"):
        pk.best_grouped_reduce(arr, op="or")
    monkeypatch.setattr(pk, "GROUPED_PALLAS_CONFIG", {"w_tile": [512]})  # unhashable
    with pytest.raises(ValueError, match="hashable"):
        pk.best_grouped_reduce(arr, op="or")
    pk._PROBED.clear()


def test_wide_dispatch_policies(monkeypatch):
    """WIDE_DISPATCH must route to the crowned engine with WIDE_CONFIG
    applied, validate configs per policy, and keep the off-TPU default."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(72)
    host = rng.integers(0, 1 << 32, size=(10, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    want = np.bitwise_or.reduce(host, axis=0)

    # off-TPU: XLA serves regardless of policy
    red, _ = pk.best_wide_reduce(arr, op="or")
    assert np.array_equal(np.asarray(red), want)

    # two_stage policy with its config
    monkeypatch.setattr(pk, "on_tpu", lambda: True)
    monkeypatch.setattr(pk, "WIDE_DISPATCH", "two_stage")
    monkeypatch.setattr(pk, "WIDE_CONFIG", {"stage_groups": 4})
    red, card = pk.best_wide_reduce(arr, op="or")
    assert np.array_equal(np.asarray(red), want)
    assert int(card) == int(np.unpackbits(want.view(np.uint8)).sum())
    assert pk.DISPATCH_COUNTS[("wide", "two_stage")] >= 1

    # config keys are policy-scoped
    monkeypatch.setattr(pk, "WIDE_CONFIG", {"row_tile": 128})
    with pytest.raises(ValueError, match="invalid for policy"):
        pk.best_wide_reduce(arr, op="or")
    monkeypatch.setattr(pk, "WIDE_DISPATCH", "warp")
    with pytest.raises(ValueError, match="WIDE_DISPATCH"):
        pk.best_wide_reduce(arr, op="or")
