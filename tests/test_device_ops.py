"""Device kernel differential tests vs numpy references (runs on the CPU
backend with 8 virtual devices; the same code paths execute on TPU)."""

import numpy as np
import pytest

from roaringbitmap_tpu.ops import device as dev
from roaringbitmap_tpu.utils import bits


@pytest.fixture
def word_batch():
    rng = np.random.default_rng(42)
    host64 = rng.integers(0, 1 << 64, size=(37, dev.HOST_WORDS), dtype=np.uint64)
    host64[5] = 0
    host64[6] = 0xFFFFFFFFFFFFFFFF
    return host64


def test_device_word_layout_roundtrip(word_batch):
    u32 = dev.to_device_words(word_batch)
    assert u32.shape == (37, dev.DEVICE_WORDS)
    assert np.array_equal(dev.from_device_words(u32), word_batch)


def test_popcount_rows(word_batch):
    import jax.numpy as jnp

    u32 = jnp.asarray(dev.to_device_words(word_batch))
    got = np.asarray(dev.popcount_rows(u32))
    want = bits.popcount64(word_batch).sum(axis=1)
    assert np.array_equal(got, want)


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_wide_reduce(word_batch, op, npop):
    import jax.numpy as jnp

    u32 = jnp.asarray(dev.to_device_words(word_batch))
    got = dev.from_device_words(np.asarray(dev.wide_reduce(u32, op=op))[None])[0]
    want = npop.reduce(word_batch, axis=0)
    assert np.array_equal(got, want)
    red, card = dev.wide_reduce_with_cardinality(u32, op=op)
    assert int(card) == int(bits.popcount64(want).sum())


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_grouped_reduce(op, npop):
    import jax.numpy as jnp

    rng = np.random.default_rng(43)
    host = rng.integers(0, 1 << 64, size=(4, 5, dev.HOST_WORDS), dtype=np.uint64)
    u32 = jnp.asarray(host.view(np.uint32).reshape(4, 5, dev.DEVICE_WORDS))
    red, card = dev.grouped_reduce_with_cardinality(u32, op=op)
    for g in range(4):
        want = npop.reduce(host[g], axis=0)
        got = np.asarray(red[g]).view(np.uint64) if False else np.ascontiguousarray(np.asarray(red[g])).view(np.uint64)
        assert np.array_equal(got, want)
        assert int(card[g]) == int(bits.popcount64(want).sum())


@pytest.mark.parametrize("op,npop", [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)])
def test_segmented_reduce(op, npop):
    import jax.numpy as jnp

    rng = np.random.default_rng(44)
    host = rng.integers(0, 1 << 64, size=(11, dev.HOST_WORDS), dtype=np.uint64)
    offsets = [0, 3, 4, 9, 11]
    seg_start = np.zeros(11, dtype=bool)
    seg_start[offsets[:-1]] = True
    u32 = jnp.asarray(dev.to_device_words(host))
    vals = np.asarray(dev.segmented_reduce(u32, jnp.asarray(seg_start), op=op))
    for s, e in zip(offsets[:-1], offsets[1:]):
        want = npop.reduce(host[s:e], axis=0)
        got = np.ascontiguousarray(vals[e - 1]).view(np.uint64)
        assert np.array_equal(got, want)


def test_batched_pairwise(word_batch):
    import jax.numpy as jnp

    a = jnp.asarray(dev.to_device_words(word_batch))
    b = jnp.asarray(dev.to_device_words(word_batch[::-1].copy()))
    an = word_batch
    bn = word_batch[::-1]
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_or(a, b))), an | bn)
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_and(a, b))), an & bn)
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_xor(a, b))), an ^ bn)
    assert np.array_equal(dev.from_device_words(np.asarray(dev.batched_andnot(a, b))), an & ~bn)


def test_rank_rows():
    import jax.numpy as jnp

    rng = np.random.default_rng(45)
    host = rng.integers(0, 1 << 64, size=(6, dev.HOST_WORDS), dtype=np.uint64)
    positions = np.array([0, 100, 65535, 32768, 7, 63], dtype=np.int32)
    u32 = jnp.asarray(dev.to_device_words(host))
    got = np.asarray(dev.rank_rows(u32, jnp.asarray(positions)))
    for i in range(6):
        want = bits.cardinality_in_range(host[i], 0, int(positions[i]) + 1)
        assert got[i] == want


def test_pallas_wide_reduce_interpret():
    """Pallas kernel correctness via the interpreter (real-TPU execution is
    exercised by bench.py / __graft_entry__.py on hardware)."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(46)
    host = rng.integers(0, 1 << 64, size=(300, dev.HOST_WORDS), dtype=np.uint64)
    u32 = jnp.asarray(dev.to_device_words(host))
    for op, npop in [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)]:
        red, card = pk.wide_reduce_cardinality_pallas(u32, op=op, interpret=True)
        want = npop.reduce(host, axis=0)
        assert np.array_equal(np.ascontiguousarray(np.asarray(red)).view(np.uint64), want)
        assert int(card) == int(bits.popcount64(want).sum())


def test_pallas_grouped_reduce_interpret():
    """Grouped Pallas kernel vs numpy per-group fold (interpreter mode)."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.HAS_PALLAS:
        pytest.skip("pallas unavailable")
    import jax.numpy as jnp

    rng = np.random.default_rng(42)
    g, m = 3, 300  # m not a multiple of the tile -> exercises padding
    host = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
    for op, fold in [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)]:
        red, card = pk.grouped_reduce_cardinality_pallas(
            jnp.asarray(host), op=op, interpret=True
        )
        want = fold.reduce(host, axis=1)
        assert np.array_equal(np.asarray(red), want), op
        want_cards = [int(np.unpackbits(want[i].view(np.uint8)).sum()) for i in range(g)]
        assert np.asarray(card).tolist() == want_cards, op
