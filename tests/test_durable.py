"""Durable epochs (ISSUE 17): the frozen mmap corpus format (round-trip,
structural validation, zero-copy contract), atomic priced persistence
(crash points failing closed, idempotent re-persist, the priced verdict
+ outcome join), crash recovery (newest complete manifest wins, torn
artifacts skipped with provenance), warm restart (resume + lazy
PACK_CACHE readmit teaching the residency readmit curve), the fourth
residency rung (demote-to-mapped), the two new sentinel rules, the
sidecar/insights durable blocks, the fuzz family 31 seed pin, and the
zero-copy serialization satellite's regression pins."""

import json
import os

import numpy as np
import pytest

from roaringbitmap_tpu import insights, observe
from roaringbitmap_tpu import serialization
from roaringbitmap_tpu.cost import epoch as epoch_cost
from roaringbitmap_tpu.cost import residency as residency_cost
from roaringbitmap_tpu.durable import (
    DurableStore,
    MappedCorpus,
    PERSIST_STAGES,
    Recovery,
    recover,
    write_corpus,
)
from roaringbitmap_tpu.durable import format as dformat
from roaringbitmap_tpu.durable import recovery as drecovery
from roaringbitmap_tpu.durable import store as dstore_mod
from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.observe import export as obs_export
from roaringbitmap_tpu.observe import health, outcomes
from roaringbitmap_tpu.parallel import store as pstore
from roaringbitmap_tpu.robust import faults
from roaringbitmap_tpu.robust import ladder as ladder_mod
from roaringbitmap_tpu.robust.errors import TransientDeviceError
from roaringbitmap_tpu.serialization import InvalidRoaringFormat
from roaringbitmap_tpu.serve import EpochStore, slo


@pytest.fixture(autouse=True)
def _clean_state():
    slo.reset()
    outcomes.reset()
    faults.clear()
    ladder_mod.LADDER.reset()
    epoch_cost.MODEL.reset()
    residency_cost.MODEL.reset()
    pstore.set_demotion_probe(None)
    yield
    slo.reset()
    outcomes.reset()
    faults.clear()
    ladder_mod.LADDER.reset()
    epoch_cost.MODEL.reset()
    residency_cost.MODEL.reset()
    pstore.set_demotion_probe(None)
    pstore.PACK_CACHE.close()


def _corpus(n=4, seed=3, card=1200):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(1 << 18, card, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


def _epoch_store(bms, tenant="t-dur"):
    slo.TENANTS.declare(tenant, quota_qps=1e6, burst=1e6)
    return EpochStore(bms)


def _flip_once(es, tenant="t-dur", idx=0, values=(7, 11, 13)):
    es.submit(tenant, {idx: list(values)})
    return es.flip(reason="test")


# ---------------------------------------------------------------------------
# the frozen corpus format
# ---------------------------------------------------------------------------


def test_corpus_round_trip_mixed_sources(tmp_path):
    bms = _corpus()
    path = str(tmp_path / "corpus.rbd")
    # mixed inputs: heap bitmaps, pre-serialized blobs, mapped bitmaps
    stats = write_corpus(path, [bms[0], bms[1].serialize(), bms[2], bms[3]])
    assert stats["n"] == 4
    assert stats["artifact_bytes"] == os.path.getsize(path)
    mc = MappedCorpus(path)
    assert len(mc) == 4
    for i, want in enumerate(bms):
        assert bytes(mc.payload(i)) == want.serialize()
        assert mc.bitmap(i).to_mutable() == want
        # the directory keeps every payload word-aligned for the
        # zero-copy u64 views
        assert mc._dir[i][0] % dformat.ALIGN == 0
    # a mapped bitmap re-persists as its backing slice, byte-identical
    path2 = str(tmp_path / "corpus2.rbd")
    write_corpus(path2, mc.bitmaps())
    with open(path, "rb") as f1, open(path2, "rb") as f2:
        assert f1.read() == f2.read()
    mc.close()


def test_corpus_rejects_structural_corruption(tmp_path):
    bms = _corpus(n=2)
    path = str(tmp_path / "corpus.rbd")
    write_corpus(path, bms)
    raw = bytearray(open(path, "rb").read())
    bad_magic = bytearray(raw)
    bad_magic[:4] = b"NOPE"
    bad = str(tmp_path / "bad.rbd")
    open(bad, "wb").write(bytes(bad_magic))
    with pytest.raises(InvalidRoaringFormat):
        MappedCorpus(bad)
    open(bad, "wb").write(bytes(raw[: len(raw) // 2]))
    with pytest.raises(InvalidRoaringFormat):
        MappedCorpus(bad)
    # an out-of-bounds directory entry is structural, not content
    torn = bytearray(raw)
    dformat.DIRENT.pack_into(torn, dformat.HEADER.size, len(raw) + 8, 64)
    open(bad, "wb").write(bytes(torn))
    with pytest.raises(InvalidRoaringFormat):
        MappedCorpus(bad)


def test_mapped_bitmaps_serve_zero_copy(tmp_path):
    bms = _corpus(n=2, card=9000)  # dense enough for bitmap containers
    path = str(tmp_path / "corpus.rbd")
    write_corpus(path, bms)
    mc = MappedCorpus(path)
    got = mc.bitmap(0)
    # serialize() is the backing slice, not a re-encode
    assert got.serialize() == bms[0].serialize()
    hlc = got.high_low_container
    for c in hlc.containers:
        arr = getattr(c, "words", None)
        if arr is None:
            arr = getattr(c, "values", None)
        if arr is not None and arr.size:
            assert not arr.flags["WRITEABLE"], (
                "mapped container payloads must be read-only views"
            )


# ---------------------------------------------------------------------------
# atomic persistence
# ---------------------------------------------------------------------------


def test_persist_publishes_and_is_idempotent(tmp_path):
    bms = _corpus()
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path))
    _flip_once(es)
    rec = ds.persist(es)
    assert rec["outcome"] == "persisted" and rec["fresh"] is True
    assert rec["epoch"] == 1 and os.path.isdir(rec["dir"])
    manifest = json.load(open(os.path.join(rec["dir"], "MANIFEST.json")))
    assert manifest["schema"] == dstore_mod.SCHEMA
    assert set(manifest["files"]) == {"corpus.rbd", "lineage.json"}
    again = ds.persist(es)
    assert again["outcome"] == "persisted" and again["fresh"] is False
    assert ds.pending_epochs(es) == 0


def test_persist_aborts_fail_closed_at_every_crash_point(tmp_path):
    """A non-fatal fault at ANY of the five crash points aborts the
    persist memory-only: no published dir appears, no tmp dir leaks
    past the next persist, and the aborted outcome is counted."""
    bms = _corpus(n=2)
    counter = observe.REGISTRY.get(observe.DURABLE_PERSIST_TOTAL)
    for crash_at in range(4):  # points 1-4 precede the rename
        faults.clear()
        es = _epoch_store(bms, tenant=f"t-ab{crash_at}")
        ds = DurableStore(str(tmp_path / f"ab{crash_at}"))
        _flip_once(es, tenant=f"t-ab{crash_at}")
        before = counter.get(("aborted",))
        with faults.inject(
            "durable.persist", TransientDeviceError, after=crash_at
        ):
            rec = ds.persist(es)
        assert rec["outcome"] == "aborted", f"crash point {crash_at + 1}"
        assert counter.get(("aborted",)) == before + 1
        assert not os.path.isdir(
            os.path.join(ds.root, dstore_mod.epoch_dir_name(1))
        ), f"crash point {crash_at + 1} published a torn epoch"
        # the abort leaves pending exposure for the stall rule, and the
        # next clean persist sweeps any tmp orphan and publishes
        assert ds.pending_epochs(es) > 0
        rec2 = ds.persist(es)
        assert rec2["outcome"] == "persisted"
        assert not [
            d for d in os.listdir(ds.root) if d.startswith(".tmp-")
        ], "tmp orphan survived the next persist"


def test_persist_raises_fatal(tmp_path):
    bms = _corpus(n=2)
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path))
    _flip_once(es)
    # a ValueError classifies FATAL: a deterministic misconfiguration
    # must surface, never degrade to memory-only silently
    with faults.inject("durable.persist", ValueError, after=0):
        with pytest.raises(ValueError):
            ds.persist(es)


def test_priced_verdict_and_outcome_join(tmp_path):
    bms = _corpus(n=2)
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path))
    _flip_once(es)
    # small corpus + the declared 20ms/epoch exposure rate => persist
    rec = ds.maybe_persist(es)
    assert rec["outcome"] == "persisted"
    assert ds.maybe_persist(es)["outcome"] == "noop"
    # the measured wall joined the durable.persist decision site
    tail = outcomes.LEDGER.tail(16)
    joined = [t for t in tail if t["site"] == "durable.persist"]
    assert joined and joined[-1]["engine"] == "persist"
    # an artificially huge predicted wall flips the verdict to skip
    es2 = _epoch_store(_corpus(n=2, seed=9), tenant="t-skip")
    ds2 = DurableStore(str(tmp_path / "skip"))
    _flip_once(es2, tenant="t-skip")
    old = epoch_cost.MODEL.coeffs["persist_overhead_us"]
    try:
        epoch_cost.MODEL.coeffs["persist_overhead_us"] = 1e12
        rec2 = ds2.maybe_persist(es2)
    finally:
        epoch_cost.MODEL.coeffs["persist_overhead_us"] = old
    assert rec2["outcome"] == "skipped" and rec2["pending"] > 0


def test_flip_hook_attaches_persistence(tmp_path):
    bms = _corpus(n=2)
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path))
    es.attach_durable(ds)
    rec = _flip_once(es)
    assert rec["durable"] == "persisted"
    assert ds.stats()["persisted_epoch"] == 1


def test_gc_keeps_newest_artifacts(tmp_path):
    bms = _corpus(n=2)
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path), keep=2)
    for i in range(4):
        _flip_once(es, values=(100 + i,))
        ds.persist(es)
    kept = sorted(d for d in os.listdir(ds.root) if d.startswith("epoch_"))
    assert kept == [
        dstore_mod.epoch_dir_name(3), dstore_mod.epoch_dir_name(4)
    ]


# ---------------------------------------------------------------------------
# recovery + warm restart
# ---------------------------------------------------------------------------


def test_recover_newest_and_resume(tmp_path):
    bms = _corpus()
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path), keep=3)
    _flip_once(es, values=(1, 2))
    ds.persist(es)
    _flip_once(es, values=(3, 4))
    ds.persist(es)
    rec = recover(str(tmp_path))
    assert isinstance(rec, Recovery) and rec.epoch == 2
    for i, live in enumerate(es.corpus):
        assert rec.corpus.bitmap(i).to_mutable() == live
    assert drecovery.LAST["epoch"] == 2
    assert drecovery.LAST["torn_skipped"] == 0
    resumed = rec.resume_store()
    assert resumed.current() == 2
    assert [r["epoch"] for r in resumed.lineage()] == [
        r["epoch"] for r in rec.lineage
    ]
    # the resumed store keeps flipping from where the crash left off
    slo.TENANTS.declare("t-resume", quota_qps=1e6, burst=1e6)
    resumed.submit("t-resume", {0: [99]})
    assert resumed.flip(reason="post-resume")["epoch"] == 3


def test_recover_skips_torn_artifact(tmp_path):
    bms = _corpus(n=2)
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path), keep=3)
    _flip_once(es, values=(1,))
    ds.persist(es)
    _flip_once(es, values=(2,))
    ds.persist(es)
    # corrupt one payload byte of the NEWEST artifact: sha256 mismatch
    newest = os.path.join(str(tmp_path), dstore_mod.epoch_dir_name(2))
    corpus_path = os.path.join(newest, "corpus.rbd")
    raw = bytearray(open(corpus_path, "rb").read())
    raw[-1] ^= 0xFF
    open(corpus_path, "wb").write(bytes(raw))
    with pytest.raises(ValueError):
        drecovery.verify_manifest(newest)
    torn_counter = observe.REGISTRY.get(observe.DURABLE_RECOVERY_TOTAL)
    before = torn_counter.get(("torn",))
    rec = recover(str(tmp_path))
    assert rec is not None and rec.epoch == 1, (
        "recovery must fall back to the parent epoch"
    )
    assert torn_counter.get(("torn",)) == before + 1
    assert drecovery.LAST["torn_skipped"] == 1


def test_recover_empty_root(tmp_path):
    assert recover(str(tmp_path)) is None
    assert drecovery.LAST["epoch"] is None


def test_readmit_warms_cache_and_teaches_curve(tmp_path):
    bms = _corpus(n=3)
    es = _epoch_store(bms)
    ds = DurableStore(str(tmp_path))
    _flip_once(es)
    ds.persist(es)
    rec = recover(str(tmp_path))
    assert residency_cost.MODEL.readmit_estimate("agg") is None
    out = rec.readmit()
    assert out["working_sets"] == 1
    est = residency_cost.MODEL.readmit_estimate("agg")
    assert est is not None and est > 0, (
        "the readmit join must teach the residency readmit curve"
    )
    # the warmed pack is a cache hit for the mapped working set
    hits = observe.REGISTRY.get(observe.PACK_CACHE_HITS_TOTAL)
    before = hits.get(("agg",))
    pstore.packed_for(rec.corpus.bitmaps())
    assert hits.get(("agg",)) == before + 1


# ---------------------------------------------------------------------------
# the fourth residency rung
# ---------------------------------------------------------------------------


def test_eviction_demotes_to_mapped_only_with_probe():
    demote = observe.REGISTRY.get(observe.DURABLE_DEMOTE_TOTAL)

    def _fill(cache, n=3):
        rng = np.random.default_rng(17)
        sets = [
            [
                RoaringBitmap(
                    np.sort(
                        rng.choice(1 << 18, 1500, replace=False)
                    ).astype(np.uint32)
                )
                for _ in range(2)
            ]
            for _ in range(n)
        ]
        return [cache.get_packed(s) for s in sets]

    cache = pstore.PackCache(max_bytes=1 << 60)
    packs = _fill(cache)
    before_discard = demote.get(("discard",))
    cache.configure(max_bytes=packs[0].words.nbytes + 1)
    assert demote.get(("discard",)) > before_discard
    cache.close()
    # with a durable map covering the corpus, the same eviction demotes
    # to the mapped rung instead of discarding
    pstore.set_demotion_probe(lambda kind: True)
    cache = pstore.PackCache(max_bytes=1 << 60)
    packs = _fill(cache)
    before_mapped = demote.get(("mapped",))
    cache.configure(max_bytes=packs[0].words.nbytes + 1)
    assert demote.get(("mapped",)) > before_mapped
    cache.close()


def test_persist_installs_demotion_probe(tmp_path):
    assert pstore._DEMOTE_PROBE is None
    es = _epoch_store(_corpus(n=2))
    ds = DurableStore(str(tmp_path))
    _flip_once(es)
    ds.persist(es)
    assert pstore._DEMOTE_PROBE is not None
    assert pstore._DEMOTE_PROBE("agg") is True


# ---------------------------------------------------------------------------
# sentinel rules + observability panels
# ---------------------------------------------------------------------------


def test_rule_registry_pins():
    names = [r.name for r in health.DEFAULT_RULES]
    assert "epoch-persist-stall" in names
    assert "recovery-manifest-torn" in names
    stall = next(r for r in health.DEFAULT_RULES
                 if r.name == "epoch-persist-stall")
    assert (stall.warn, stall.critical) == (4.0, 64.0)
    torn = next(r for r in health.DEFAULT_RULES
                if r.name == "recovery-manifest-torn")
    assert (torn.warn, torn.critical) == (0.5, 1.0)
    assert torn.fire_after == 1, "any torn artifact must go red in one tick"


def test_persist_stall_rule_fires_on_backlog_without_persists(tmp_path):
    stall = next(r for r in health.DEFAULT_RULES
                 if r.name == "epoch-persist-stall")
    s0 = health.snapshot(refresh_hbm=False)
    es = _epoch_store(_corpus(n=2))
    ds = DurableStore(str(tmp_path))
    # a deep backlog with zero completed persists in the window
    observe.REGISTRY.get(observe.DURABLE_PENDING_COUNT).set(70)
    s1 = health.snapshot(prev_sums=s0.sums, refresh_hbm=False)
    assert stall.probe(s1) >= 64.0
    # a completed persist in the window clears the signal
    _flip_once(es)
    ds.persist(es)
    s2 = health.snapshot(prev_sums=s1.sums, refresh_hbm=False)
    assert stall.probe(s2) == 0.0


def test_sidecar_and_insights_durable_blocks(tmp_path):
    es = _epoch_store(_corpus(n=2))
    ds = DurableStore(str(tmp_path))
    es.attach_durable(ds)
    _flip_once(es)
    recover(str(tmp_path))
    side = obs_export.sidecar_snapshot()
    blk = side["durable"]
    assert blk["epoch"] == 1 and blk["pending_epochs"] == 0
    assert blk["persists"].get("persisted", 0) >= 1
    assert blk["recoveries"].get("recovered", 0) >= 1
    assert set(blk["persist_stages"]) <= set(PERSIST_STAGES)
    live = insights.durable()
    assert live["store_live"]["persisted_epoch"] == 1
    assert live["recovery_last"]["epoch"] == 1
    assert "durable" in insights.observatory()


def test_metric_and_site_name_pins():
    assert observe.DURABLE_PERSIST_TOTAL == "rb_tpu_durable_persist_total"
    assert observe.DURABLE_RECOVERY_TOTAL == "rb_tpu_durable_recovery_total"
    assert observe.DURABLE_DEMOTE_TOTAL == "rb_tpu_durable_demote_total"
    assert observe.DURABLE_PENDING_COUNT == "rb_tpu_durable_pending_count"
    assert "durable.persist" in faults.SITES
    assert PERSIST_STAGES == ("snapshot", "lineage", "manifest", "publish")


# ---------------------------------------------------------------------------
# fuzz family 31 seed pin
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fuzz_family_31_seed_pin():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_durable_crash_invariance(
        "crash-at-any-flip-stage-vs-recovery-oracle", iterations=3, seed=61
    )


# ---------------------------------------------------------------------------
# zero-copy serialization satellite (regression pins)
# ---------------------------------------------------------------------------


def _payload_arrays(bm):
    """Every container payload array (array content, bitmap words, run
    starts/lengths) of a deserialized bitmap."""
    out = []
    for c in bm.high_low_container.containers:
        for attr in ("content", "words", "starts", "lengths"):
            arr = getattr(c, attr, None)
            if arr is not None and getattr(arr, "size", 0):
                out.append(arr)
    return out


def _mixed_bm(seed=5):
    """One chunk dense (bitmap container), one sparse (array container)."""
    rng = np.random.default_rng(seed)
    dense = rng.choice(1 << 16, 9000, replace=False)
    sparse = (1 << 16) + rng.choice(1 << 16, 500, replace=False)
    return RoaringBitmap(
        np.sort(np.concatenate([dense, sparse])).astype(np.uint32)
    )


def test_deserialize_copy_false_shares_memory():
    want = _mixed_bm()
    blob = np.frombuffer(want.serialize(), dtype=np.uint8)
    zc = serialization.deserialize(blob, copy=False)
    assert zc == want
    arrays = _payload_arrays(zc)
    assert arrays and all(np.shares_memory(a, blob) for a in arrays), (
        "copy=False must build every payload as a view into the source"
    )
    # the default path must NOT alias the caller's buffer
    cp = serialization.deserialize(blob, copy=True)
    assert not any(np.shares_memory(a, blob) for a in _payload_arrays(cp))


def test_zero_copy_views_are_read_only():
    want = _mixed_bm()
    zc = serialization.deserialize(want.serialize(), copy=False)
    dense = [
        c for c in zc.high_low_container.containers if hasattr(c, "words")
    ]
    assert dense, "the mixed bitmap must hold a bitmap container"
    with pytest.raises(ValueError):
        dense[0].words[0] = np.uint64(1)


def test_serial_bytes_counter_same_on_both_paths():
    blob = _mixed_bm().serialize()
    c = observe.REGISTRY.get(observe.SERIAL_BYTES_TOTAL)
    before = c.get(("deserialize",))
    serialization.deserialize(blob, copy=True)
    copied = c.get(("deserialize",)) - before
    serialization.deserialize(blob, copy=False)
    viewed = c.get(("deserialize",)) - before - copied
    assert copied == viewed == len(blob), (
        "both paths consume (and count) the same serialized bytes"
    )


def test_immutable_over_ndarray_is_zero_copy():
    want = _mixed_bm()
    blob = np.frombuffer(want.serialize(), dtype=np.uint8)
    imm = ImmutableRoaringBitmap(blob)
    assert imm.to_mutable() == want
    assert bytes(imm.serialize()) == want.serialize()
    arrays = _payload_arrays(imm)
    assert arrays and all(np.shares_memory(a, blob) for a in arrays), (
        "an ndarray-backed immutable must not copy its source"
    )
