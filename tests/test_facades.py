"""RoaringBitSet, FastRankRoaringBitmap, BitSetUtil conversions."""

import numpy as np
import pytest

from roaringbitmap_tpu.models.bitset import (
    RoaringBitSet,
    bitmap_of_words,
    words_of_bitmap,
)
from roaringbitmap_tpu.models.fastrank import FastRankRoaringBitmap
from roaringbitmap_tpu import RoaringBitmap


def test_bitset_api():
    bs = RoaringBitSet()
    bs.set(5)
    bs.set(100000)
    assert bs.get(5) and bs.get(100000) and not bs.get(6)
    assert bs.cardinality() == 2
    assert bs.length() == 100001
    bs.flip(5)
    assert not bs.get(5)
    bs.set_range(10, 20)
    assert bs.next_set_bit(0) == 10
    assert bs.next_clear_bit(10) == 20
    assert bs.previous_set_bit(15) == 15
    bs.clear_range(10, 20)
    assert bs.cardinality() == 1
    bs.clear()
    assert bs.is_empty()


def test_bitset_logical_ops():
    a, b = RoaringBitSet(), RoaringBitSet()
    a.set_range(0, 100)
    b.set_range(50, 150)
    assert a.intersects(b)
    a.and_(b)
    assert a.cardinality() == 50
    a.or_(b)
    assert a.cardinality() == 100
    a.xor(b)
    assert a.cardinality() == 0


def test_words_roundtrip(rng):
    words = rng.integers(0, 1 << 64, size=3000, dtype=np.uint64)
    bm = bitmap_of_words(words)
    values = np.nonzero(np.unpackbits(words.view(np.uint8), bitorder="little"))[0]
    assert np.array_equal(bm.to_array(), values.astype(np.uint32))
    back = words_of_bitmap(bm)
    # back is sized to the last set bit; compare set bits
    assert np.array_equal(
        np.nonzero(np.unpackbits(back.view(np.uint8), bitorder="little"))[0], values
    )


def test_fastrank_matches_plain(rng):
    vals = rng.integers(0, 1 << 24, size=20000, dtype=np.uint64)
    plain = RoaringBitmap(vals)
    fast = FastRankRoaringBitmap(vals)
    u = np.unique(vals)
    for j in [0, 5000, len(u) - 1]:
        assert fast.select(j) == plain.select(j) == u[j]
        assert fast.rank(int(u[j])) == plain.rank(int(u[j]))
    # cache invalidation on mutation
    fast.add(int(u[0]) + 1) if int(u[0]) + 1 not in set(u.tolist()) else fast.remove(int(u[0]))
    assert fast.rank(int(u[-1])) == fast.get_cardinality()
    # range mutation invalidates too
    fast2 = FastRankRoaringBitmap([1, 2, 3])
    assert fast2.select(2) == 3
    fast2.add_range(10, 20)
    assert fast2.select(12) == 19
    fast2.remove_range(10, 20)
    assert fast2.rank(100) == 3


def test_fetch_bit_position_ranges_parsing(tmp_path, monkeypatch):
    """Range-format zip parsing, incl. entries that span multiple lines."""
    import zipfile

    from roaringbitmap_tpu.utils import datasets

    z = tmp_path / "fake_ranges.zip"
    with zipfile.ZipFile(z, "w") as zf:
        zf.writestr("a.txt", "5-9,12-15,\n100-200")
    monkeypatch.setattr(datasets, "REFERENCE_DATASET_DIR", str(tmp_path))
    (ranges,) = datasets.fetch_bit_position_ranges("fake_ranges")
    assert ranges.tolist() == [[5, 9], [12, 15], [100, 200]]
