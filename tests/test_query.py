"""Query expression engine (ISSUE 2): DAG construction + hash-consing,
planner rewrites (exactness asserted structurally), golden explain() string,
executor-vs-naive differentials (incl. Not over an explicit universe and
Threshold edge cases), engine parity across forced cpu/device regimes, and
the observe-registry cache counters."""

import numpy as np
import pytest

from roaringbitmap_tpu import FastAggregation, Q, RoaringBitmap, observe
from roaringbitmap_tpu.query import (
    ResultCache,
    evaluate_naive,
    execute,
    kernels,
    plan,
    rewrite,
)


def _bm(*ranges):
    out = RoaringBitmap()
    for start, end, step in ranges:
        out.add_many(np.arange(start, end, step, dtype=np.uint32))
    return out


@pytest.fixture
def abcd():
    a = _bm((0, 1000, 2))
    b = _bm((0, 1000, 3))
    c = _bm((500, 1500, 1))
    d = _bm((0, 100, 1))
    return a, b, c, d


# ---------------------------------------------------------------------------
# DAG construction + hash-consing
# ---------------------------------------------------------------------------


def test_hash_consing_shares_nodes(abcd):
    a, b, c, _ = abcd
    assert Q.leaf(a) is Q.leaf(a)
    assert (Q.leaf(a) & Q.leaf(b)) is (Q.leaf(a) & Q.leaf(b))
    assert (Q.leaf(a) & Q.leaf(b)) is not (Q.leaf(b) & Q.leaf(a))
    assert Q.threshold(2, Q.leaf(a), Q.leaf(b)) is Q.threshold(2, Q.leaf(a), Q.leaf(b))
    assert Q.threshold(2, Q.leaf(a), Q.leaf(b)) is not Q.threshold(
        3, Q.leaf(a), Q.leaf(b)
    )
    # operator overloading coerces raw bitmaps to (the same) leaves
    assert (Q.leaf(a) & b) is (Q.leaf(a) & Q.leaf(b))


def test_shared_subtree_planned_once(abcd):
    a, b, c, _ = abcd
    shared = Q.leaf(a) & Q.leaf(b)
    q = shared | (shared ^ Q.leaf(c))
    p = plan(q)
    assert len(p.steps) == 3  # and, xor, or — the AND is CSE'd, not planned twice


# ---------------------------------------------------------------------------
# planner rewrites (structural, on the folded DAG)
# ---------------------------------------------------------------------------


def test_flatten_and_dedup(abcd):
    a, b, c, _ = abcd
    r = rewrite(Q.and_(Q.and_(Q.leaf(a), Q.leaf(b)), Q.leaf(c), Q.leaf(a)))
    assert r.op == "and" and len(r.children) == 3
    assert rewrite(Q.and_(Q.leaf(a), Q.leaf(a))) is Q.leaf(a)


def test_de_morgan_pushdown_fuses_to_nary_andnot(abcd):
    a, b, c, _ = abcd
    u = Q.leaf(c)
    r = rewrite(Q.not_(Q.or_(Q.leaf(a), Q.leaf(b)), u))
    # U \ (a|b) = (U\a) & (U\b) -> one n-ary difference andnot(U, a, b)
    assert r.op == "andnot"
    assert r.children[0] is u
    assert set(x.uid for x in r.children[1:]) == {Q.leaf(a).uid, Q.leaf(b).uid}


def test_double_not_same_universe(abcd):
    a, _, c, _ = abcd
    u = Q.leaf(c)
    r = rewrite(Q.not_(Q.not_(Q.leaf(a), u), u))
    assert r.op == "and"  # U \ (U \ a) = U & a


def test_difference_pull_up_and_chain_flatten(abcd):
    a, b, c, d = abcd
    r = rewrite(Q.and_(Q.leaf(a), Q.andnot(Q.leaf(b), Q.leaf(c))))
    assert r.op == "andnot" and r.children[0].op == "and"
    r2 = rewrite(Q.andnot(Q.andnot(Q.leaf(a), Q.leaf(b)), Q.leaf(c), Q.leaf(d)))
    assert r2.op == "andnot" and len(r2.children) == 4  # a \ (b|c|d)


def test_constant_folding(abcd):
    a, b, _, _ = abcd
    empty = Q.leaf(RoaringBitmap())
    assert rewrite(Q.and_(Q.leaf(a), empty)).op == "leaf"
    assert rewrite(Q.and_(Q.leaf(a), empty)).bitmap.is_empty()
    assert rewrite(Q.or_(Q.leaf(a), empty)) is Q.leaf(a)
    assert rewrite(Q.xor(Q.leaf(a), Q.leaf(a))).bitmap.is_empty()
    assert rewrite(Q.andnot(Q.leaf(a), Q.leaf(a))).bitmap.is_empty()
    assert rewrite(Q.andnot(Q.leaf(a), empty)) is Q.leaf(a)
    assert rewrite(Q.threshold(3, Q.leaf(a), Q.leaf(b))).bitmap.is_empty()
    assert rewrite(Q.threshold(1, Q.leaf(a), Q.leaf(b))).op == "or"
    assert rewrite(Q.threshold(2, Q.leaf(a), Q.leaf(b))).op == "and"


def test_threshold_k_validation(abcd):
    a, _, _, _ = abcd
    with pytest.raises(ValueError, match="k must be >= 1"):
        Q.threshold(0, Q.leaf(a))


# ---------------------------------------------------------------------------
# golden explain()
# ---------------------------------------------------------------------------


def test_explain_golden(abcd):
    a, b, c, d = abcd
    q = (Q.leaf(a) & Q.leaf(b) | Q.leaf(c)) - Q.leaf(d)
    assert plan(q).explain() == "\n".join(
        [
            "plan: 3 steps over 4 leaves",
            "  L0 leaf card=500",
            "  L1 leaf card=334",
            "  L2 leaf card=1000",
            "  L3 leaf card=100",
            "  s0 and(L1, L0) engine=pairwise est_card=334 est_rows=2",
            "  s1 or(s0, L2) engine=pairwise est_card=1334 est_rows=3",
            "  s2 andnot(s1, L3) engine=pairwise est_card=1334 est_rows=4",
            "  root: s2",
        ]
    )
    # stable across replans
    assert plan(q).explain() == plan(q).explain()


def test_explain_shows_device_engines_and_threshold(abcd):
    a, b, c, d = abcd
    p = plan(Q.or_(Q.leaf(a), Q.leaf(b), Q.leaf(c)), mode="device")
    assert "engine=device-or" in p.explain()
    p2 = plan(Q.threshold(2, Q.leaf(a), Q.leaf(b), Q.leaf(c), Q.leaf(d)))
    assert "threshold[k=2](L0, L1, L2, L3) engine=threshold-bitsliced[cpu]" in p2.explain()


def test_and_operands_ordered_ascending(abcd):
    a, b, c, _ = abcd  # cards: a=500, b=334, c=1000
    p = plan(Q.and_(Q.leaf(a), Q.leaf(b), Q.leaf(c)))
    (step,) = p.steps
    cards = [o.bitmap.get_cardinality() for o in step.operands]
    assert cards == sorted(cards) == [334, 500, 1000]


# ---------------------------------------------------------------------------
# executor vs naive (the acceptance differential)
# ---------------------------------------------------------------------------


def _random_leaves(rng, n):
    from roaringbitmap_tpu.fuzz import random_bitmap

    return [random_bitmap(rng) for _ in range(n)]


@pytest.mark.parametrize("mode", [None, "cpu", "device"])
def test_randomized_dags_match_naive(mode):
    from roaringbitmap_tpu.fuzz import random_expression

    rng = np.random.default_rng(77)
    cache = ResultCache(max_entries=16)
    for _ in range(12):
        leaves = _random_leaves(rng, int(rng.integers(2, 5)))
        expr = random_expression(rng, leaves)
        assert execute(expr, cache=cache, mode=mode) == evaluate_naive(expr)


def test_not_over_explicit_universe(abcd):
    a, b, _, _ = abcd
    u = Q.leaf(_bm((0, 600, 1)))  # universe smaller than the operands
    q = Q.not_(Q.leaf(a) ^ Q.leaf(b), u)
    got = execute(q)
    want = evaluate_naive(q)
    assert got == want
    # spot-check semantics: U \ (a ^ b), values outside U never appear
    assert got.contains_bitmap(RoaringBitmap()) and (got.is_empty() or got.last() < 600)


def test_threshold_edge_cases(abcd):
    a, b, c, _ = abcd
    leaves = [Q.leaf(a), Q.leaf(b), Q.leaf(c)]
    n = len(leaves)
    union = evaluate_naive(Q.or_(*leaves))
    inter = evaluate_naive(Q.and_(*leaves))
    assert execute(Q.threshold(1, *leaves)) == union  # k=1 == OR
    assert execute(Q.threshold(n, *leaves)) == inter  # k=N == AND
    assert execute(Q.threshold(n + 1, *leaves)).is_empty()  # k>N
    for k in range(1, n + 2):
        t = Q.threshold(k, *leaves)
        assert execute(t) == evaluate_naive(t), k
        assert execute(t, mode="device") == evaluate_naive(t), k
    # multiset: a repeated child counts with multiplicity
    t2 = Q.threshold(2, Q.leaf(a), Q.leaf(a))
    assert execute(t2) == a


def test_threshold_kernel_direct_general_k(abcd):
    a, b, c, d = abcd
    bms = [a, b, c, d]
    for k in (2, 3):
        want = evaluate_naive(Q.threshold(k, *[Q.leaf(x) for x in bms]))
        assert kernels.threshold(k, bms, mode="cpu") == want
        assert kernels.threshold(k, bms, mode="device") == want


def test_andnot_nway_kernel_and_wrappers(abcd):
    a, b, c, d = abcd
    want = evaluate_naive(Q.andnot(Q.leaf(c), Q.leaf(a), Q.leaf(b), Q.leaf(d)))
    assert kernels.andnot_nway(c, a, b, d, mode="cpu") == want
    assert kernels.andnot_nway(c, a, b, d, mode="device") == want
    assert FastAggregation.andnot(c, a, b, d) == want
    for mode in ("cpu", "device"):
        assert (
            FastAggregation.andnot_cardinality(c, a, b, d, mode=mode)
            == want.get_cardinality()
        )
    # degenerate arities
    assert FastAggregation.andnot(c) == c
    assert kernels.andnot_nway(RoaringBitmap(), a).is_empty()


# ---------------------------------------------------------------------------
# cache counters in the observe registry (acceptance)
# ---------------------------------------------------------------------------


def test_cache_hit_counter_and_mutation_reset(abcd):
    a, b, c, d = abcd
    counter = observe.REGISTRY.get(observe.QUERY_CACHE_TOTAL)
    q = (Q.leaf(a) & Q.leaf(b) | Q.leaf(c)) - Q.leaf(d)
    cache = ResultCache(max_entries=64)

    execute(q, cache=cache)  # cold: all misses
    assert cache.stats()["hits"] == 0 and cache.stats()["misses"] > 0
    base_hits = counter.get(("hit",))
    first = execute(q, cache=cache)  # warm: every step short-circuits
    assert counter.get(("hit",)) > base_hits  # registry hit counter rose
    assert cache.stats()["hits"] == len(plan(q).steps)

    # leaf mutation bumps the fingerprint: the warm keys miss, the query
    # recomputes against the new contents, and the hit-rate resets
    # (105 is an odd multiple of 3 outside c's and d's ranges, so a&b —
    # and with it the query result — gains it)
    a.add(105)
    hits_before = cache.stats()["hits"]
    got = execute(q, cache=cache)
    assert cache.stats()["hits"] == hits_before  # zero hits on this run
    assert got == evaluate_naive(q) and got != first
    # and warms back up
    execute(q, cache=cache)
    assert cache.stats()["hits"] == hits_before + len(plan(q).steps)


def test_returned_bitmap_is_private(abcd):
    a, b, _, _ = abcd
    cache = ResultCache()
    q = Q.leaf(a) & Q.leaf(b)
    r1 = execute(q, cache=cache)
    r1.add_range(0, 1 << 20)  # caller mutation must not corrupt the cache
    assert execute(q, cache=cache) == evaluate_naive(q)


def test_execute_without_cache(abcd):
    a, b, _, _ = abcd
    q = Q.leaf(a) ^ Q.leaf(b)
    assert execute(q, cache=None) == evaluate_naive(q)


def test_leaf_root_and_prebuilt_plan(abcd):
    a, _, _, _ = abcd
    assert execute(Q.leaf(a)) == a
    q = Q.leaf(a) | Q.leaf(a)  # folds to the leaf
    p = plan(q)
    assert not p.steps
    assert execute(p) == a
