"""64-bit BSI + buffer BSI twins (reference oracles:
bsi/longlong/Roaring64BitmapSliceIndexTest, bsi/buffer tests; differential
oracle: a plain dict of column -> value)."""

import numpy as np
import pytest

from roaringbitmap_tpu import (
    ImmutableBitSliceIndex,
    MutableBitSliceIndex,
    Operation,
    Roaring64Bitmap,
    Roaring64BitmapSliceIndex,
    RoaringBitmap,
)

rng = np.random.default_rng(0xFEEF1F0)


def build64(n=800):
    cols = np.unique(rng.integers(0, 1 << 40, size=n, dtype=np.uint64))
    vals = rng.integers(0, 1 << 36, size=cols.size, dtype=np.uint64)
    bsi = Roaring64BitmapSliceIndex()
    bsi.set_values((cols, vals))
    return bsi, dict(zip(cols.tolist(), vals.tolist()))


class TestRoaring64BSI:
    def test_set_get(self):
        bsi, model = build64()
        assert bsi.get_long_cardinality() == len(model)
        for c, v in list(model.items())[::97]:
            assert bsi.get_value(c) == (v, True)
        assert bsi.get_value(123456789) == (0, False) or 123456789 in model
        assert bsi.min_value == min(model.values())
        assert bsi.max_value == max(model.values())

    def test_point_updates(self):
        bsi = Roaring64BitmapSliceIndex()
        bsi.set_value(1 << 35, 42)
        bsi.set_value(7, (1 << 50) + 3)
        assert bsi.get_value(1 << 35) == (42, True)
        assert bsi.get_value(7) == ((1 << 50) + 3, True)
        bsi.set_value(7, 9)  # overwrite clears old bits
        assert bsi.get_value(7) == (9, True)

    @pytest.mark.parametrize(
        "op", [Operation.EQ, Operation.NEQ, Operation.LT, Operation.LE,
               Operation.GT, Operation.GE]
    )
    def test_compare_vs_model(self, op):
        bsi, model = build64(400)
        vals = sorted(model.values())
        for predicate in [vals[0], vals[len(vals) // 2], vals[-1], vals[-1] + 10]:
            got = set(bsi.compare(op, predicate).to_array().tolist())
            pyop = {
                Operation.EQ: lambda v: v == predicate,
                Operation.NEQ: lambda v: v != predicate,
                Operation.LT: lambda v: v < predicate,
                Operation.LE: lambda v: v <= predicate,
                Operation.GT: lambda v: v > predicate,
                Operation.GE: lambda v: v >= predicate,
            }[op]
            want = {c for c, v in model.items() if pyop(v)}
            assert got == want, f"{op} {predicate}"

    def test_range_and_found_set(self):
        bsi, model = build64(400)
        vals = sorted(model.values())
        lo, hi = vals[50], vals[300]
        got = set(bsi.compare(Operation.RANGE, lo, hi).to_array().tolist())
        want = {c for c, v in model.items() if lo <= v <= hi}
        assert got == want
        some_cols = list(model)[::3]
        fs = Roaring64Bitmap(np.array(some_cols, dtype=np.uint64))
        got = set(bsi.compare(Operation.GE, lo, 0, fs).to_array().tolist())
        want = {c for c in some_cols if model[c] >= lo}
        assert got == want

    def test_sum_topk_transpose(self):
        bsi, model = build64(300)
        fs = bsi.get_existence_bitmap()
        total, count = bsi.sum(fs)
        assert count == len(model) and total == sum(model.values())
        k = 25
        top = bsi.top_k(fs, k)
        assert top.get_cardinality() == k
        kth = sorted(model.values(), reverse=True)[k - 1]
        assert all(model[c] >= kth for c in top.to_array().tolist())
        tr = bsi.transpose()
        assert set(tr.to_array().tolist()) == set(model.values())
        twc = bsi.transpose_with_count()
        from collections import Counter

        counts = Counter(model.values())
        for v, n in list(counts.items())[::29]:
            assert twc.get_value(v) == (n, True)

    def test_add_merge(self):
        a, ma = build64(150)
        b = Roaring64BitmapSliceIndex()
        cols = np.array([c + (1 << 41) for c in list(ma)[:50]], dtype=np.uint64)
        b.set_values((cols, np.arange(50, dtype=np.uint64)))
        a2 = a.clone()
        a2.merge(b)
        assert a2.get_long_cardinality() == len(ma) + 50
        c = a.clone()
        c.add(a)  # doubles every value
        for col, v in list(ma.items())[::37]:
            assert c.get_value(col) == (2 * v, True)
        with pytest.raises(ValueError):
            a.clone().merge(a)

    def test_serialization_round_trip(self):
        bsi, _ = build64(200)
        bsi.run_optimize()
        data = bsi.serialize()
        assert len(data) == bsi.serialized_size_in_bytes()
        back = Roaring64BitmapSliceIndex.deserialize(data)
        assert back == bsi
        assert back.min_value == bsi.min_value and back.max_value == bsi.max_value
        from roaringbitmap_tpu import InvalidRoaringFormat

        with pytest.raises(InvalidRoaringFormat):
            Roaring64BitmapSliceIndex.deserialize(b"\x01" * 10)


class TestBufferTwins:
    def build(self, n=500):
        cols = np.unique(rng.integers(0, 1 << 20, size=n).astype(np.uint32))
        vals = rng.integers(0, 1 << 24, size=cols.size).astype(np.int64)
        bsi = MutableBitSliceIndex()
        bsi.set_values((cols, vals))
        return bsi, dict(zip(cols.tolist(), vals.tolist()))

    def test_named_ranges(self):
        bsi, model = self.build()
        mid = sorted(model.values())[len(model) // 2]
        assert set(bsi.range_lt(None, mid).to_array().tolist()) == {
            c for c, v in model.items() if v < mid
        }
        assert set(bsi.range_ge(None, mid).to_array().tolist()) == {
            c for c, v in model.items() if v >= mid
        }
        lo, hi = sorted(model.values())[10], sorted(model.values())[-10]
        assert set(bsi.range(None, lo, hi).to_array().tolist()) == {
            c for c, v in model.items() if lo <= v <= hi
        }
        assert bsi.parallel_in(4, Operation.EQ, mid) == bsi.range_eq(None, mid)

    def test_immutable_cast_and_guard(self):
        bsi, model = self.build(200)
        imm = bsi.to_immutable_bit_slice_index()
        assert imm.get_long_cardinality() == len(model)
        c = next(iter(model))
        assert imm.get_value(c) == (model[c], True)
        with pytest.raises(TypeError):
            imm.set_value(1, 2)
        with pytest.raises(TypeError):
            imm.run_optimize()
        # buffer-parse constructor
        imm2 = ImmutableBitSliceIndex(bsi.serialize())
        assert imm2 == imm
        back = imm2.to_mutable_bit_slice_index()
        back.set_value(999999, 7)  # mutable again
        assert back.get_value(999999) == (7, True)

    def test_topk_and_transpose_with_count(self):
        bsi, model = self.build(300)
        k = 10
        top = bsi.top_k(bsi.get_existence_bitmap(), k)
        kth = sorted(model.values(), reverse=True)[k - 1]
        assert top.get_cardinality() == k
        assert all(model[c] >= kth for c in top.to_array().tolist())
        twc = bsi.parallel_transpose_with_count(None)
        from collections import Counter

        counts = Counter(model.values())
        v = next(iter(counts))
        assert twc.get_value(v) == (counts[v], True)

    def test_mutable_deserialize(self):
        bsi, _ = self.build(100)
        back = MutableBitSliceIndex.deserialize(bsi.serialize())
        assert isinstance(back, MutableBitSliceIndex)
        assert back == bsi
        assert back.range_eq(None, bsi.max_value) == bsi.range_eq(None, bsi.max_value)


def test_immutable_bsi_maps_lazily_zero_copy():
    """ImmutableBitSliceIndex(buffer) must be a lazy zero-copy map: no slice
    decoded at construction, payloads viewed from the source buffer
    (ImmutableBitSliceIndex.java:52; VERDICT r2: the buffer BSI was a
    deserialize-everything delegate)."""
    import numpy as np

    from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex
    from roaringbitmap_tpu.models.bsi_buffer import ImmutableBitSliceIndex, _LazySlices

    rng = np.random.default_rng(5)
    cols = np.arange(200_000, dtype=np.uint32)
    vals = rng.integers(0, 1 << 20, size=cols.size).astype(np.int64)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values((cols, vals))
    data = bsi.serialize()

    imm = ImmutableBitSliceIndex(data)
    lazy = imm._base.slices
    assert isinstance(lazy, _LazySlices)
    assert not lazy._cache, "construction decoded a slice"
    med = int(np.median(vals))
    got = imm.compare(Operation.GE, med, 0, None, mode="cpu")
    want = bsi.compare(Operation.GE, med, 0, None, mode="cpu")
    assert got == want
    assert imm.get_cardinality() == bsi.get_cardinality()
    assert imm.serialize() == data
    # equality against the eager twin
    assert imm == RoaringBitmapSliceIndex.deserialize(data)
    # mutation still refused
    import pytest as _pytest

    with _pytest.raises(TypeError):
        imm.set_value(1, 2)
    # round-trips back to a mutable deep copy
    mut = imm.to_mutable_bit_slice_index()
    mut.set_value(0, 123)
    assert imm.get_value(0)[0] != 123 or bsi.get_value(0)[0] == 123


def test_bsi64_device_path_matches_cpu():
    """The 64-bit index's fused device O'Neil (over high-48 chunk keys) must
    agree with the CPU whole-bitmap walk for every op, across multiple
    high-32 buckets, with and without found sets."""
    import numpy as np

    from roaringbitmap_tpu.models.bsi64 import config
    from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap

    rng = np.random.default_rng(23)
    # columns spread over three high-32 buckets (and several high-48 chunks)
    cols = np.unique(
        np.concatenate(
            [
                rng.integers(0, 1 << 20, size=30_000, dtype=np.uint64),
                (np.uint64(5) << np.uint64(32)) + rng.integers(0, 1 << 18, size=20_000, dtype=np.uint64),
                (np.uint64(1) << np.uint64(60)) + rng.integers(0, 1 << 17, size=10_000, dtype=np.uint64),
            ]
        )
    )
    vals = rng.integers(0, 1 << 40, size=cols.size, dtype=np.uint64)
    bsi = Roaring64BitmapSliceIndex()
    bsi.set_values((cols, vals))
    found = Roaring64Bitmap(cols[::3].copy())
    med = int(np.median(vals))

    for op in (Operation.GE, Operation.LT, Operation.EQ, Operation.NEQ):
        for fs in (None, found):
            cpu = bsi.compare(op, med, 0, fs, mode="cpu")
            dev = bsi.compare(op, med, 0, fs, mode="device")
            assert dev.serialize() == cpu.serialize(), (op, fs is not None)
    cpu = bsi.compare(Operation.RANGE, med // 2, med * 2, found, mode="cpu")
    dev = bsi.compare(Operation.RANGE, med // 2, med * 2, found, mode="device")
    assert dev.serialize() == cpu.serialize()
    # NEQ with found-set columns outside the ebm's chunks
    stray = Roaring64Bitmap(np.array([1 << 50, (1 << 50) + 7], dtype=np.uint64))
    fs2 = Roaring64Bitmap.or_(found, stray)
    cpu = bsi.compare(Operation.NEQ, med, 0, fs2, mode="cpu")
    dev = bsi.compare(Operation.NEQ, med, 0, fs2, mode="device")
    assert dev.serialize() == cpu.serialize()
    # the pack is resident in the shared cache until mutation (ISSUE 4)
    from roaringbitmap_tpu.parallel import store

    key = ("bsi64", id(bsi), bsi._version)
    assert key in store.PACK_CACHE
    v = bsi._version
    bsi.set_value(int(cols[0]), 7)
    assert bsi._version != v
    assert ("bsi64", id(bsi), bsi._version) != key  # mutation re-keys


def test_bsi64_compare_cardinality():
    import numpy as np

    from roaringbitmap_tpu.models.bsi import Operation
    from roaringbitmap_tpu.models.bsi64 import Roaring64BitmapSliceIndex

    rng = np.random.default_rng(43)
    b = Roaring64BitmapSliceIndex()
    cols = rng.choice(1 << 40, size=5_000, replace=False).astype(np.int64)
    vals = rng.integers(0, 1 << 30, size=5_000).astype(np.int64)
    b.set_values(list(zip(cols.tolist(), vals.tolist())))
    med = int(np.median(vals))
    for op, a, e in (
        (Operation.GE, med, 0),
        (Operation.LT, med, 0),
        (Operation.RANGE, med // 2, med * 2),
        (Operation.GE, 0, 0),  # min/max verdict 'all' — no materialization
        (Operation.GT, 1 << 40, 0),  # verdict 'empty'
    ):
        want = b.compare(op, a, e, None).get_cardinality()
        assert b.compare_cardinality(op, a, e, None) == want, op


def test_bsi64_compare_cardinality_device_paths():
    """Device count-only == CPU materialized count, incl. NEQ's
    outside-ebm chunk remainder (the path the device sum must add back)."""
    import numpy as np

    from roaringbitmap_tpu.models.bsi import Operation
    from roaringbitmap_tpu.models.bsi64 import Roaring64BitmapSliceIndex
    from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap

    rng = np.random.default_rng(47)
    b = Roaring64BitmapSliceIndex()
    base = np.uint64(1) << np.uint64(35)
    cols = (
        base + rng.choice(1 << 18, size=20_000, replace=False).astype(np.uint64)
    ).astype(np.int64)
    vals = rng.integers(0, 1 << 24, size=20_000).astype(np.int64)
    b.set_values(list(zip(cols.tolist(), vals.tolist())))
    med = int(np.median(vals))
    outside = (base + np.uint64(1 << 20)) + np.arange(1500, dtype=np.uint64)
    fs = Roaring64Bitmap(
        np.sort(np.concatenate([cols[:4000].astype(np.uint64), outside]))
    )
    for op, a, e in (
        (Operation.GE, med, 0),
        (Operation.NEQ, int(vals[3]), 0),
        (Operation.RANGE, med // 2, med * 2),
    ):
        want = b.compare(op, a, e, fs, mode="cpu").get_cardinality()
        assert b.compare_cardinality(op, a, e, fs, mode="device") == want, op


def test_immutable_range_api_and_parallel_surface():
    """The reference defines rangeEQ..range / parallelIn /
    parallelTransposeWithCount on the base BOTH buffer twins extend
    (BitSliceIndexBase.java:351-620); the Immutable twin must expose the
    whole family, including over a lazily mapped buffer."""
    from roaringbitmap_tpu.models.bsi import Operation
    from roaringbitmap_tpu.models.bsi_buffer import (
        ImmutableBitSliceIndex,
        MutableBitSliceIndex,
    )

    rng = np.random.default_rng(0xB51)
    cols = np.unique(rng.integers(0, 200_000, 3000)).astype(np.uint32)
    vals = rng.integers(0, 5000, cols.size).astype(np.int64)
    mut = MutableBitSliceIndex()
    mut.set_values((cols, vals))
    med = int(np.median(vals))
    found = __import__("roaringbitmap_tpu").RoaringBitmap(cols[::3])
    for imm in (ImmutableBitSliceIndex(mut), ImmutableBitSliceIndex(mut.serialize())):
        assert imm.range_ge(found, med) == mut.range_ge(found, med)
        assert imm.range_lt(None, med) == mut.compare(Operation.LT, med, 0, None)
        assert imm.range(found, med // 2, med * 2) == mut.range(found, med // 2, med * 2)
        assert imm.parallel_in(4, Operation.EQ, med) == mut.range_eq(None, med)
        t_imm = imm.parallel_transpose_with_count(found)
        t_mut = mut.parallel_transpose_with_count(found)
        assert t_imm == t_mut and isinstance(t_imm, MutableBitSliceIndex)
    assert imm.has_run_compression() == mut.has_run_compression()


def test_bsi_stream_serialization_roundtrip():
    """Stream overloads (the reference's DataOutput path,
    MutableBitSliceIndex.java:331/:379): back-to-back BSIs read back
    sequentially, and the Mutable subclass reconstructs its own type."""
    import io

    from roaringbitmap_tpu.models.bsi import RoaringBitmapSliceIndex
    from roaringbitmap_tpu.models.bsi_buffer import MutableBitSliceIndex

    a = RoaringBitmapSliceIndex()
    a.set_values(([1, 5, 9], [10, 20, 30]))
    b = MutableBitSliceIndex()
    b.set_values(([2, 4], [7, 1 << 20]))
    b.run_optimize()
    buf = io.BytesIO()
    n_a = a.serialize_into(buf)
    n_b = b.serialize_into(buf)
    assert buf.tell() == n_a + n_b
    buf.seek(0)
    back_a = RoaringBitmapSliceIndex.deserialize_from(buf)
    back_b = MutableBitSliceIndex.deserialize_from(buf)
    assert back_a == a and back_b == b
    assert isinstance(back_b, MutableBitSliceIndex)
    assert back_b.run_optimized and buf.read() == b""


def test_bsi64_get_values_bulk():
    """64-bit bulk read agrees with per-column get_value, including values
    above 2^63 (object-dtype exact path) and absent columns."""
    from roaringbitmap_tpu.models.bsi64 import Roaring64BitmapSliceIndex

    b = Roaring64BitmapSliceIndex()
    cols = [1, (1 << 40) + 3, 7]
    vals = [10, (1 << 35) + 1, 99]
    b.set_values((cols, vals))
    values, exists = b.get_values(np.array(cols + [12345], dtype=np.uint64))
    assert exists.tolist() == [True, True, True, False]
    assert values.tolist() == vals + [0]
    # >63-slice exact path
    big = Roaring64BitmapSliceIndex()
    big.set_value(5, (1 << 63) + 7)
    v, e = big.get_values([5, 6])
    assert list(v) == [(1 << 63) + 7, 0] and e.tolist() == [True, False]


def test_bsi64_compare_cardinality_many():
    """Batched 64-bit counts == per-predicate counts (both modes), incl.
    short-circuit thresholds, a found set with outside-ebm chunks (NEQ
    remainder), and per-query RANGE ends."""
    r = np.random.default_rng(57)
    b = Roaring64BitmapSliceIndex()
    cols = r.choice(1 << 40, size=8_000, replace=False).astype(np.int64)
    vals = r.integers(0, 1 << 28, size=8_000).astype(np.int64)
    b.set_values(list(zip(cols.tolist(), vals.tolist())))
    found = Roaring64Bitmap.bitmap_of(
        *cols[: 2000].tolist(), *(int(c) + (1 << 50) for c in cols[:50])
    )
    qs = np.array(
        [int(np.median(vals)), 0, 1 << 30, int(vals[3])], dtype=np.int64
    )
    for op in (Operation.GE, Operation.NEQ, Operation.LT):
        for fs in (None, found):
            want = np.array(
                [b.compare_cardinality(op, int(v), 0, fs, mode="cpu") for v in qs],
                dtype=np.int64,
            )
            for mode in ("cpu", "device"):
                got = b.compare_cardinality_many(op, qs, found_set=fs, mode=mode)
                assert np.array_equal(got, want), (op, mode, fs is not None)
    ends = qs + 999
    want = np.array(
        [
            b.compare_cardinality(Operation.RANGE, int(a), int(e), None, mode="cpu")
            for a, e in zip(qs, ends)
        ],
        dtype=np.int64,
    )
    for mode in ("cpu", "device"):
        got = b.compare_cardinality_many(Operation.RANGE, qs, ends=ends, mode=mode)
        assert np.array_equal(got, want), mode


def test_buffer_bsi_compare_cardinality_delegation():
    """The Immutable twin answers the count-only family (incl. the batched
    form) over lazily mapped buffers, equal to the heap twin."""
    from roaringbitmap_tpu.models.bsi import RoaringBitmapSliceIndex

    r = np.random.default_rng(71)
    heap = RoaringBitmapSliceIndex()
    cols = np.sort(r.choice(200_000, size=6_000, replace=False)).astype(np.uint32)
    vals = r.integers(0, 1 << 16, size=6_000).astype(np.int64)
    heap.set_values((cols, vals))
    imm = ImmutableBitSliceIndex(heap.serialize())
    med = int(np.median(vals))
    qs = np.array([med, med // 2, 0, 1 << 20], dtype=np.int64)
    assert imm.compare_cardinality(Operation.GE, med) == heap.compare_cardinality(
        Operation.GE, med
    )
    assert np.array_equal(
        imm.compare_cardinality_many(Operation.GE, qs),
        heap.compare_cardinality_many(Operation.GE, qs),
    )
