"""Invariant fuzzing — the reference's fuzz-tests invariants
(Fuzzer.java: algebraic identities, cardinality consistency, serialization
round-trip, optimized-vs-naive aggregation equivalence) plus the
TPU-specific oracle: CPU path == device path."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import FastAggregation, RoaringBitmap
from roaringbitmap_tpu.fuzz import (
    InvarianceFailure,
    random_bitmap,
    reproduce,
    verify_invariance,
)

# per-invariant; full campaigns crank ROARINGBITMAP_TPU_FUZZ_ITERATIONS
ITER = int(os.environ.get("ROARINGBITMAP_TPU_FUZZ_ITERATIONS", "24"))


def test_de_morgan_and_distributivity():
    def pred(a, b, c):
        lhs = RoaringBitmap.and_(a, RoaringBitmap.or_(b, c))
        rhs = RoaringBitmap.or_(RoaringBitmap.and_(a, b), RoaringBitmap.and_(a, c))
        return lhs == rhs

    verify_invariance("and-distributes-over-or", pred, arity=3, iterations=ITER, seed=1)


def test_xor_identities():
    def pred(a, b):
        x = RoaringBitmap.xor(a, b)
        return (
            RoaringBitmap.xor(x, b) == a
            and x == RoaringBitmap.or_(RoaringBitmap.andnot(a, b), RoaringBitmap.andnot(b, a))
        )

    verify_invariance("xor-involution", pred, arity=2, iterations=ITER, seed=2)


def test_cardinality_consistency():
    def pred(a, b):
        return (
            RoaringBitmap.or_cardinality(a, b)
            == a.get_cardinality() + b.get_cardinality() - RoaringBitmap.and_cardinality(a, b)
            and RoaringBitmap.or_(a, b).get_cardinality() == RoaringBitmap.or_cardinality(a, b)
        )

    verify_invariance("inclusion-exclusion", pred, arity=2, iterations=ITER, seed=3)


def test_contains_add_remove():
    def pred(a):
        x = 123_456_789 % (1 << 32)
        c = a.clone()
        c.add(x)
        if not c.contains(x):
            return False
        c.remove(x)
        return not c.contains(x)

    verify_invariance("contains-after-add", pred, arity=1, iterations=ITER, seed=4)


def test_serialization_roundtrip_invariant():
    def pred(a):
        data = a.serialize()
        back = RoaringBitmap.deserialize(data)
        return back == a and back.serialize() == data

    verify_invariance("serde-roundtrip", pred, arity=1, iterations=ITER, seed=5)


def test_rank_select_inverse():
    def pred(a):
        card = a.get_cardinality()
        for j in {0, card // 2, card - 1}:
            if a.rank(a.select(j)) != j + 1:
                return False
        return True

    verify_invariance("rank-select-inverse", pred, arity=1, iterations=ITER, seed=6)


def test_flip_involution():
    def pred(a):
        c = a.clone()
        c.flip_range(0, 1 << 22)
        c.flip_range(0, 1 << 22)
        return c == a

    verify_invariance("flip-involution", pred, arity=1, iterations=ITER, seed=7)


def test_aggregation_cpu_equals_device_and_naive():
    def pred(a, b, c):
        naive = RoaringBitmap.or_(RoaringBitmap.or_(a, b), c)
        return (
            FastAggregation.or_(a, b, c, mode="cpu") == naive
            and FastAggregation.or_(a, b, c, mode="device") == naive
        )

    verify_invariance("wide-or-engines-agree", pred, arity=3, iterations=max(1, ITER // 2), seed=8)


def test_failure_report_reproduces():
    """The harness must emit base64 payloads that reproduce the inputs."""
    with pytest.raises(InvarianceFailure) as exc_info:
        verify_invariance("always-false", lambda a: False, arity=1, iterations=1, seed=9)
    repro = exc_info.value.repro
    assert len(repro) == 1
    bm = reproduce(repro[0])
    rng = np.random.default_rng(9)
    assert bm == random_bitmap(rng)


def test_predicate_crash_is_reported():
    def boom(a):
        raise RuntimeError("kaboom")

    with pytest.raises(InvarianceFailure, match="kaboom"):
        verify_invariance("crash", boom, arity=1, iterations=1, seed=10)


def test_buffer_invariants():
    """Mapped bitmaps behave identically to their heap originals
    (BufferFuzzer equivalence family)."""
    from roaringbitmap_tpu import BufferFastAggregation, RoaringBitmap
    from roaringbitmap_tpu.fuzz import verify_buffer_invariance

    def pred(ma, mb, ha, hb):
        return (
            BufferFastAggregation.or_(ma, mb) == RoaringBitmap.or_(ha, hb)
            and RoaringBitmap.and_cardinality(ma, mb) == RoaringBitmap.and_cardinality(ha, hb)
            and ma.rank_long(123456) == ha.rank_long(123456)
            and ma.serialize() == ha.serialize()
        )

    verify_buffer_invariance("buffer-heap-equivalence", pred, arity=2, iterations=max(1, ITER // 2), seed=21)


def test_64bit_cross_design_oracle():
    """NavigableMap and ART designs agree on algebra + serialization."""
    from roaringbitmap_tpu import Roaring64Bitmap
    from roaringbitmap_tpu.fuzz import verify_invariance64

    def pred(a, b):
        aa = Roaring64Bitmap(a.to_array())
        bb = Roaring64Bitmap(b.to_array())
        union = a.clone()
        union.ior(b)
        art_union = Roaring64Bitmap.or_(aa, bb)
        return (
            union.serialize() == art_union.serialize()
            and union.get_long_cardinality() == art_union.get_long_cardinality()
            and a.serialize() == aa.serialize()
        )

    verify_invariance64("64bit-cross-design", pred, arity=2, iterations=max(1, ITER // 3), seed=22)


def test_device_layouts_forced_by_construction():
    """All three prepare_reduce layouts (padded, bucketed, segmented-scan)
    are exercised by construction and must agree with all CPU OR engines
    (VERDICT r2 #6: the skewed shapes that trigger the scan path never
    arose from the generic generator; round 4 added the bucketed regime
    and the geometric-pyramid shape that defeats the bucket rescue)."""
    from roaringbitmap_tpu.fuzz import verify_layout_invariance

    verify_layout_invariance("layouts-vs-engines", op="or", iterations=max(4, ITER // 4), seed=31)


def test_campaign_runner_smoke():
    """The CI-mode campaign entry point runs every invariant family."""
    from roaringbitmap_tpu.fuzz import run_campaign

    res = run_campaign(8, verbose=False)
    assert len(res) >= 10
    # full-rate invariants run n; derated families record their true count
    assert res["and-distributes-over-or"] == 8
    assert res["64bit-cross-design"] == 1
    assert all(1 <= v <= 8 for v in res.values())


def test_query_differential_invariant():
    """ISSUE 2: planner + executor output equals naive recursive set
    algebra on every sampled DAG (and/or/xor/n-ary andnot/not over an
    explicit universe/threshold), through a small shared result cache so
    memoization is part of the property."""
    from roaringbitmap_tpu.fuzz import verify_query_invariance

    verify_query_invariance(
        "query-planner-vs-naive", iterations=max(4, ITER // 2), seed=51
    )


def test_query_differential_device_mode():
    """Same property with every engine forced onto the device regime
    (runs on the CPU backend like the other mode='device' invariants)."""
    from roaringbitmap_tpu.fuzz import verify_query_invariance

    verify_query_invariance(
        "query-planner-vs-naive(device)",
        iterations=max(2, ITER // 4), seed=52, mode="device",
    )


def test_random_expression_covers_node_kinds():
    """The generator must produce every node kind across a sample — a
    degenerate generator would silently gut the differential."""
    import numpy as np

    from roaringbitmap_tpu.fuzz import random_bitmap, random_expression

    rng = np.random.default_rng(99)
    seen = set()
    for _ in range(40):
        leaves = [random_bitmap(rng) for _ in range(3)]
        stack = [random_expression(rng, leaves)]
        while stack:
            n = stack.pop()
            seen.add(n.op)
            stack.extend(n.children)
    assert {"leaf", "and", "or", "xor", "andnot", "not", "threshold"} <= seen


def test_layout_fuzz_rejects_and():
    """Per-key grouped AND has no multi-bitmap oracle; the harness must say
    so instead of reporting spurious failures (code-review regression)."""
    from roaringbitmap_tpu.fuzz import verify_layout_invariance

    with pytest.raises(ValueError, match="decomposable"):
        verify_layout_invariance("bad", op="and", iterations=1, seed=1)
