"""BSI differential tests vs a plain dict column->value model
(reference oracle: bsi/ test suite + O'Neil semantics,
RoaringBitmapSliceIndex.java:432-513)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex


@pytest.fixture
def column_data(rng):
    cols = np.unique(rng.integers(0, 300_000, size=4000)).astype(np.uint32)
    vals = rng.integers(0, 10_000, size=cols.size).astype(np.int64)
    return cols, vals


@pytest.fixture
def bsi(column_data):
    cols, vals = column_data
    b = RoaringBitmapSliceIndex()
    b.set_values((cols, vals))
    return b


def ref_compare(cols, vals, op, v, end=0):
    if op == Operation.EQ:
        m = vals == v
    elif op == Operation.NEQ:
        m = vals != v
    elif op == Operation.LT:
        m = vals < v
    elif op == Operation.LE:
        m = vals <= v
    elif op == Operation.GT:
        m = vals > v
    elif op == Operation.GE:
        m = vals >= v
    else:
        m = (vals >= v) & (vals <= end)
    return set(cols[m].tolist())


def test_set_get(bsi, column_data):
    cols, vals = column_data
    assert bsi.get_cardinality() == cols.size
    for i in [0, cols.size // 2, cols.size - 1]:
        got, exists = bsi.get_value(int(cols[i]))
        assert exists and got == vals[i]
    absent = 299_999
    while absent in set(cols.tolist()):
        absent -= 1
    assert bsi.get_value(absent) == (0, False)
    assert bsi.min_value == vals.min() and bsi.max_value == vals.max()


def test_get_values_bulk(bsi, column_data):
    """The vectorized bulk read must agree with per-column get_value,
    including absent columns reading as (0, False)."""
    cols, vals = column_data
    absent = np.array([299_999, 299_998], dtype=np.uint32)
    present = set(cols.tolist())
    absent = absent[[a not in present for a in absent.tolist()]]
    query = np.concatenate([cols[:100], absent, cols[-3:]])
    values, exists = bsi.get_values(query)
    assert values.dtype == np.int64 and exists.dtype == bool
    for q, v, e in zip(query.tolist(), values.tolist(), exists.tolist()):
        assert (v, e) == bsi.get_value(q), q
    # all-absent fast path
    values, exists = bsi.get_values(absent)
    assert not exists.any() and not values.any()


def test_set_value_overwrite():
    b = RoaringBitmapSliceIndex()
    b.set_value(7, 100)
    b.set_value(7, 3)
    assert b.get_value(7) == (3, True)
    # bulk overwrite path
    b2 = RoaringBitmapSliceIndex()
    b2.set_values(([1, 2], [10, 20]))
    b2.set_values(([2, 3], [5, 6]))
    assert b2.get_value(1) == (10, True)
    assert b2.get_value(2) == (5, True)
    assert b2.get_value(3) == (6, True)


@pytest.mark.parametrize(
    "op", [Operation.EQ, Operation.NEQ, Operation.LT, Operation.LE, Operation.GT, Operation.GE]
)
@pytest.mark.parametrize("mode", ["cpu", "device"])
def test_compare_ops(bsi, column_data, op, mode):
    cols, vals = column_data
    for v in [0, 1, int(np.median(vals)), int(vals.max()), int(vals.max()) + 5]:
        got = bsi.compare(op, v, 0, None, mode=mode)
        want = ref_compare(cols, vals, op, v)
        assert set(got.to_array().tolist()) == want, (op, v, mode)


@pytest.mark.parametrize("mode", ["cpu", "device"])
def test_range_and_found_set(bsi, column_data, mode):
    cols, vals = column_data
    lo, hi = int(np.percentile(vals, 25)), int(np.percentile(vals, 75))
    got = bsi.compare(Operation.RANGE, lo, hi, None, mode=mode)
    assert set(got.to_array().tolist()) == ref_compare(cols, vals, Operation.RANGE, lo, hi)
    # with a found_set filter
    found = RoaringBitmap(cols[::2])
    got2 = bsi.compare(Operation.GE, lo, 0, found, mode=mode)
    want2 = ref_compare(cols, vals, Operation.GE, lo) & set(cols[::2].tolist())
    assert set(got2.to_array().tolist()) == want2


def test_neq_found_set_outside_index(bsi, column_data):
    """Java semantics: NEQ does not intersect found_set with the ebm, so
    out-of-index columns qualify."""
    cols, vals = column_data
    outside = 400_000
    found = RoaringBitmap([int(cols[0]), outside])
    for mode in ("cpu", "device"):
        got = bsi.compare(Operation.NEQ, int(vals[0]), 0, found, mode=mode)
        assert outside in set(got.to_array().tolist())
        assert int(cols[0]) not in set(got.to_array().tolist())


def test_sum(bsi, column_data):
    cols, vals = column_data
    found = RoaringBitmap(cols[: cols.size // 2])
    total, count = bsi.sum(found)
    assert count == cols.size // 2
    assert total == int(vals[: cols.size // 2].sum())
    assert bsi.sum(None) == (0, 0)


def test_merge_and_add():
    a = RoaringBitmapSliceIndex()
    a.set_values(([1, 2], [10, 20]))
    b = RoaringBitmapSliceIndex()
    b.set_values(([3, 4], [5, 300]))
    a.merge(b)
    assert a.get_value(3) == (5, True) and a.get_value(4) == (300, True)
    assert a.min_value == 5 and a.max_value == 300
    with pytest.raises(ValueError):
        a.merge(b)  # no longer disjoint

    # element-wise add with carry
    x = RoaringBitmapSliceIndex()
    x.set_values(([1, 2], [3, 7]))
    y = RoaringBitmapSliceIndex()
    y.set_values(([1, 2, 5], [1, 9, 4]))
    x.add(y)
    assert x.get_value(1) == (4, True)
    assert x.get_value(2) == (16, True)  # 7+9 ripples through all bits
    assert x.get_value(5) == (4, True)
    assert x.min_value == 4 and x.max_value == 16


def test_serialization_roundtrip(bsi):
    data = bsi.serialize()
    assert len(data) == bsi.serialized_size_in_bytes()
    back = RoaringBitmapSliceIndex.deserialize(data)
    assert back == bsi
    assert back.min_value == bsi.min_value and back.max_value == bsi.max_value
    assert back.serialize() == data


def test_clone_independent(bsi):
    c = bsi.clone()
    assert c == bsi
    c.set_value(12345678, 42)
    assert c != bsi or bsi.value_exist(12345678) is False


def test_set_values_input_forms():
    """Pairs vs parallel arrays, duplicates last-wins, empty input
    (code-review regression)."""
    b = RoaringBitmapSliceIndex()
    b.set_values([])  # no-op
    assert b.get_cardinality() == 0
    b.set_values([(1, 3), (1, 4)])  # duplicate column: last wins
    assert b.get_value(1) == (4, True)
    b2 = RoaringBitmapSliceIndex()
    b2.set_values([[1, 2], [3, 4]])  # list-of-lists = pairs
    assert b2.get_value(1) == (2, True) and b2.get_value(3) == (4, True)
    b3 = RoaringBitmapSliceIndex()
    b3.set_values(([1, 3], [2, 4]))  # 2-tuple = parallel arrays
    assert b3.get_value(1) == (2, True) and b3.get_value(3) == (4, True)


def test_transpose():
    b = RoaringBitmapSliceIndex()
    b.set_values(([1, 2, 3, 4], [7, 7, 0, 12]))
    assert set(b.transpose().to_array().tolist()) == {0, 7, 12}


def test_neq_predicate_beyond_bit_depth():
    """NEQ with out-of-range predicate returns everything (code-review
    regression; stricter than the reference's bit truncation)."""
    b = RoaringBitmapSliceIndex()
    b.set_values(([1, 2, 3], [0, 5, 10]))
    assert set(b.compare(Operation.NEQ, 1 << 20, 0, None).to_array().tolist()) == {1, 2, 3}


def test_sum_device_matches_cpu_and_oracle():
    rng = np.random.default_rng(17)
    bsi = RoaringBitmapSliceIndex()
    cols = rng.choice(100_000, size=20_000, replace=False)
    vals = rng.integers(0, 1 << 30, size=20_000)
    pairs = [(int(c), int(v)) for c, v in zip(cols, vals)]
    bsi.set_values(pairs)
    found = RoaringBitmap(rng.choice(100_000, size=8_000, replace=False).astype(np.uint32))
    cpu = bsi.sum(found, mode="cpu")
    dev = bsi.sum(found, mode="device")
    assert cpu == dev
    lookup = dict(pairs)
    want = sum(lookup[c] for c in found.to_array().tolist() if c in lookup)
    assert cpu[0] == want and cpu[1] == found.get_cardinality()


def test_compare_cardinality_matches_materialized():
    """Count-only compare == compare().get_cardinality() across every op,
    mode, and found-set shape (incl. NEQ's outside-ebm chunks, the path
    where the device count must add the unpacked remainder)."""
    rng = np.random.default_rng(23)
    bsi = RoaringBitmapSliceIndex()
    cols = np.sort(rng.choice(500_000, size=60_000, replace=False))
    vals = rng.integers(0, 1 << 24, size=60_000)
    bsi.set_values((cols, vals))
    med = int(np.median(vals))
    found = RoaringBitmap(
        rng.choice(900_000, size=40_000, replace=False).astype(np.uint32)
    )
    cases = [
        (Operation.GE, med, 0, None),
        (Operation.LT, med, 0, found),
        (Operation.EQ, int(vals[0]), 0, None),
        (Operation.NEQ, int(vals[1]), 0, found),
        (Operation.RANGE, med // 2, med * 2, None),
        (Operation.GT, 1 << 30, 0, None),  # min/max short-circuit
    ]
    for op, a, b, fs in cases:
        want = bsi.compare(op, a, b, fs, mode="cpu").get_cardinality()
        for mode in ("cpu", "device"):
            got = bsi.compare_cardinality(op, a, b, fs, mode=mode)
            assert got == want, (op, mode)


def test_get_values_beyond_int63():
    """Values at/above 2^63 (which set_value accepts) must read back exactly
    from the bulk path too (code-review r4: int64 accumulator wrapped)."""
    b = RoaringBitmapSliceIndex()
    b.set_value(1, 1 << 63)
    b.set_value(2, (1 << 64) + 5)
    values, exists = b.get_values([1, 2, 3])
    assert exists.tolist() == [True, True, False]
    assert list(values) == [1 << 63, (1 << 64) + 5, 0]
    assert b.get_value(1) == (1 << 63, True)


def test_compare_cardinality_many_matches_single():
    """Batched multi-predicate counts == per-predicate compare_cardinality
    across ops, modes, found sets, and short-circuit mixes (the vmapped
    device walk answers all Q predicates in one dispatch)."""
    rng = np.random.default_rng(31)
    bsi = RoaringBitmapSliceIndex()
    cols = np.sort(rng.choice(400_000, size=30_000, replace=False))
    vals = rng.integers(0, 1 << 20, size=30_000)
    bsi.set_values((cols, vals))
    found = RoaringBitmap(
        rng.choice(800_000, size=25_000, replace=False).astype(np.uint32)
    )
    # thresholds spanning in-range, below-min and above-max (short-circuits)
    qs = np.array(
        [int(np.median(vals)), 0, (1 << 22), int(vals[5]), 1 + int(vals.max())],
        dtype=np.int64,
    )
    for op in (Operation.GE, Operation.LT, Operation.EQ, Operation.NEQ):
        for fs in (None, found):
            want = np.array(
                [bsi.compare_cardinality(op, int(v), 0, fs, mode="cpu") for v in qs],
                dtype=np.int64,
            )
            for mode in ("cpu", "device"):
                got = bsi.compare_cardinality_many(op, qs, found_set=fs, mode=mode)
                assert np.array_equal(got, want), (op, mode, fs is not None)
    # RANGE with per-query ends (incl. an oversized end that must clamp)
    ends = qs + np.array([1000, 50, 1 << 40, 0, 10], dtype=np.int64)
    for fs in (None, found):
        want = np.array(
            [
                bsi.compare_cardinality(Operation.RANGE, int(a), int(b), fs, mode="cpu")
                for a, b in zip(qs, ends)
            ],
            dtype=np.int64,
        )
        for mode in ("cpu", "device"):
            got = bsi.compare_cardinality_many(
                Operation.RANGE, qs, ends=ends, found_set=fs, mode=mode
            )
            assert np.array_equal(got, want), ("RANGE", mode, fs is not None)
    # empty batch, misaligned ends
    assert bsi.compare_cardinality_many(Operation.GE, []).size == 0
    with pytest.raises(ValueError):
        bsi.compare_cardinality_many(Operation.RANGE, qs)
    with pytest.raises(ValueError):
        bsi.compare_cardinality_many(Operation.RANGE, qs, ends=ends[:2])


def test_compare_cardinality_many_beyond_int63():
    """Thresholds at/above 2^63 must not wrap through an int64 cast
    (code-review r4): the batched path must match the single-predicate
    engine on an index holding huge values."""
    bsi = RoaringBitmapSliceIndex()
    bsi.set_value(1, 7)
    bsi.set_value(2, 1 << 63)
    bsi.set_value(3, (1 << 64) + 5)
    qs = np.array([1 << 63], dtype=np.uint64)
    want = bsi.compare_cardinality(Operation.GE, 1 << 63)
    assert want == 2
    got = bsi.compare_cardinality_many(Operation.GE, qs)
    assert got.tolist() == [2]
    got = bsi.compare_cardinality_many(Operation.GE, [(1 << 64) + 5])
    assert got.tolist() == [1]
    # RANGE ends beyond the bit depth clamp instead of wrapping
    got = bsi.compare_cardinality_many(Operation.RANGE, [0], ends=[(1 << 64) + 100])
    assert got.tolist() == [3]
