"""Buffer-package twins: mixed-operand algebra over mapped bitmaps.

Oracle: heap vs buffer equivalence (SURVEY §4 — the reference's tests
assert heap/buffer variants agree; buffer/BufferFastAggregation.java,
buffer/MutableRoaringBitmap.java).
"""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import (
    BufferFastAggregation,
    BufferParallelAggregation,
    FastAggregation,
    ImmutableRoaringBitmap,
    MutableRoaringBitmap,
    RoaringBitmap,
)
from roaringbitmap_tpu.fuzz import random_bitmap


def _mapped(bm: RoaringBitmap) -> ImmutableRoaringBitmap:
    return ImmutableRoaringBitmap(bm.serialize())


@pytest.fixture(scope="module")
def pairs():
    rng = np.random.default_rng(0xB0FF)
    return [(random_bitmap(rng), random_bitmap(rng)) for _ in range(8)]


@pytest.mark.parametrize("op", ["and_", "or_", "xor", "andnot"])
def test_mixed_pairwise_matches_heap(pairs, op):
    for a, b in pairs:
        want = getattr(RoaringBitmap, op)(a, b)
        ia, ib = _mapped(a), _mapped(b)
        # immutable x immutable, immutable x heap, heap x immutable
        for x, y in ((ia, ib), (ia, b), (a, ib)):
            got = getattr(MutableRoaringBitmap, op)(x, y)
            assert got == want
            assert isinstance(got, MutableRoaringBitmap)


@pytest.mark.parametrize(
    "name", ["and_cardinality", "or_cardinality", "xor_cardinality", "andnot_cardinality"]
)
def test_mixed_cardinality_variants(pairs, name):
    for a, b in pairs:
        want = getattr(RoaringBitmap, name)(a, b)
        assert getattr(MutableRoaringBitmap, name)(_mapped(a), _mapped(b)) == want


def test_intersects_mixed(pairs):
    for a, b in pairs:
        assert MutableRoaringBitmap.intersects(_mapped(a), b) == RoaringBitmap.intersects(a, b)


def test_immutable_static_algebra(pairs):
    a, b = pairs[0]
    assert ImmutableRoaringBitmap.and_(_mapped(a), _mapped(b)) == RoaringBitmap.and_(a, b)
    assert ImmutableRoaringBitmap.or_(_mapped(a), b) == RoaringBitmap.or_(a, b)


def test_buffer_fast_aggregation_matches_heap(pairs):
    heap = [bm for pair in pairs for bm in pair]
    mapped = [_mapped(bm) for bm in heap]
    mixed = [m if i % 2 else h for i, (h, m) in enumerate(zip(heap, mapped))]
    for engine, ref in [
        (BufferFastAggregation.or_, FastAggregation.or_),
        (BufferFastAggregation.and_, FastAggregation.and_),
        (BufferFastAggregation.xor, FastAggregation.xor),
        (BufferFastAggregation.naive_or, FastAggregation.naive_or),
        (BufferFastAggregation.horizontal_or, FastAggregation.horizontal_or),
        (BufferFastAggregation.priorityqueue_or, FastAggregation.priorityqueue_or),
        (BufferFastAggregation.naive_and, FastAggregation.naive_and),
    ]:
        want = ref(*heap)
        assert engine(*mapped) == want
        assert engine(*mixed) == want
    assert BufferFastAggregation.or_cardinality(*mapped) == FastAggregation.or_(
        *heap
    ).get_cardinality()
    assert BufferFastAggregation.and_cardinality(*mapped) == FastAggregation.and_(
        *heap
    ).get_cardinality()


def test_buffer_fast_aggregation_single_iterable_arg(pairs):
    heap = [a for a, _ in pairs]
    mapped = [_mapped(bm) for bm in heap]
    assert BufferFastAggregation.or_(mapped) == FastAggregation.or_(heap)
    # single mapped operand must not be mis-iterated as a list of bitmaps
    assert BufferFastAggregation.or_(mapped[0]) == heap[0]


def test_buffer_parallel_aggregation(pairs):
    heap = [bm for pair in pairs for bm in pair]
    mapped = [_mapped(bm) for bm in heap]
    assert BufferParallelAggregation.or_(*mapped) == FastAggregation.or_(*heap)
    assert BufferParallelAggregation.xor(*mapped) == FastAggregation.xor(*heap)
    groups = BufferParallelAggregation.group_by_key(*mapped)
    assert sum(len(v) for v in groups.values()) == sum(
        bm.get_container_count() for bm in heap
    )


def test_buffer_aggregation_device_mode(pairs):
    heap = [bm for pair in pairs for bm in pair]
    mapped = [_mapped(bm) for bm in heap]
    want = FastAggregation.or_(*heap, mode="cpu")
    assert BufferFastAggregation.or_(*mapped, mode="device") == want
    assert BufferParallelAggregation.or_(*mapped, mode="device") == want


def test_mutable_roundtrip_and_casts(pairs):
    a, _ = pairs[0]
    m = MutableRoaringBitmap.of(a)
    assert m == a
    m.add(123456789)
    assert a != m  # deep copy
    imm = m.to_immutable()
    assert imm == m
    assert imm.serialize() == m.serialize()
    back = MutableRoaringBitmap.deserialize(imm.serialize())
    assert back == m


def test_immutable_view_o1_cast(pairs):
    a, _ = pairs[0]
    m = MutableRoaringBitmap.of(a)
    v = m.as_immutable_view()
    assert v.get_cardinality() == m.get_cardinality()
    assert v.contains(next(iter(m)))
    with pytest.raises(AttributeError):
        v.add(42)
    # the view is live: mutations through the mutable are visible
    m.add(987654321)
    assert v.contains(987654321)
    # views interoperate as operands
    assert RoaringBitmap.and_(v, m) == m


def test_mapped_file_algebra(tmp_path, pairs):
    a, b = pairs[0]
    pa, pb = tmp_path / "a.bin", tmp_path / "b.bin"
    pa.write_bytes(a.serialize())
    pb.write_bytes(b.serialize())
    ma = ImmutableRoaringBitmap.map_file(str(pa))
    mb = ImmutableRoaringBitmap.map_file(str(pb))
    assert MutableRoaringBitmap.or_(ma, mb) == RoaringBitmap.or_(a, b)
    assert ma.clone() == a
    assert ma.get_size_in_bytes() == os.path.getsize(pa)


def test_mutable_factories_stay_in_buffer_world():
    """Inherited factories must return MutableRoaringBitmap, not the heap
    base class, so the buffer-world casts stay reachable."""
    m = MutableRoaringBitmap.bitmap_of(1, 2, 3)
    for got in (
        m,
        m.clone(),
        m.limit(2),
        m.select_range(0, 10),
        MutableRoaringBitmap.bitmap_of_range(5, 50),
        MutableRoaringBitmap.flip(m, 0, 10),
        MutableRoaringBitmap.add_offset(m, 100),
    ):
        assert type(got) is MutableRoaringBitmap
        got.to_immutable()  # the buffer-world API the class exists for


def test_memory_mapped_file_on_disk(tmp_path, random_bitmap_factory):
    """TestMemoryMapping analogue: serialize many bitmaps into one file,
    mmap it, query + aggregate the mapped views, byte-identity preserved."""
    import mmap

    from roaringbitmap_tpu import BufferFastAggregation, FastAggregation

    bitmaps = [random_bitmap_factory()[0] for _ in range(8)]
    path = tmp_path / "bitmaps.bin"
    offsets = []
    with open(path, "wb") as f:
        for bm in bitmaps:
            offsets.append(f.tell())
            f.write(bm.serialize())
        total = f.tell()
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        mapped = []
        for i, off in enumerate(offsets):
            end = offsets[i + 1] if i + 1 < len(offsets) else total
            mapped.append(ImmutableRoaringBitmap(memoryview(mm)[off:end]))
        for src, m in zip(bitmaps, mapped):
            assert m.get_cardinality() == src.get_cardinality()
            assert m.serialize() == src.serialize()
            v = src.first()
            assert m.contains(v) and m.rank_long(v) == src.rank_long(v)
        assert BufferFastAggregation.or_(*mapped) == FastAggregation.naive_or(*bitmaps)
        # NOTE: mm.close() would raise BufferError while container views are
        # alive — the mapped views legitimately pin the mapping (zero-copy
        # contract); the map is released when the views are garbage collected.


def test_buffer_cardinality_only_mixed_operands():
    """Count-only N-way engines accept mixed heap/mapped operands and match
    materialize-then-count on both dispatch modes."""
    rng = np.random.default_rng(53)
    heap = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 20, 4000)).astype(np.uint32))
        for _ in range(6)
    ]
    mapped = [ImmutableRoaringBitmap(b.serialize()) for b in heap[:3]]
    operands = mapped + heap[3:]
    want_or = BufferFastAggregation.or_(*operands).get_cardinality()
    want_and = BufferFastAggregation.and_(*operands).get_cardinality()
    for mode in ("cpu", "device"):
        assert BufferFastAggregation.or_cardinality(*operands, mode=mode) == want_or
        assert BufferFastAggregation.and_cardinality(*operands, mode=mode) == want_and
        assert BufferFastAggregation.xor_cardinality(*operands, mode=mode) == (
            BufferFastAggregation.xor(*operands).get_cardinality()
        )


def test_mapped_run_views_zero_copy(tmp_path):
    """VERDICT r3 #5: a mapped run-heavy bitmap must answer and/contains/
    rank operating off the (start, length) buffer slices — run payloads are
    strided views into the map (MappeableRunContainer.java's buffer-view
    contract), never materialized to words or copied to the heap.

    Two proofs: (a) the container's starts/lengths share memory with the
    mapping; (b) tracemalloc over the whole query mix stays far below the
    word-materialized footprint (~8 KB x containers)."""
    import mmap
    import tracemalloc

    from roaringbitmap_tpu.models.container import RunContainer

    # run-heavy: 48 containers of long runs -> ~66 runs per container
    vals = np.concatenate(
        [np.arange(s, s + 900, dtype=np.uint32) for s in range(0, 3_000_000, 1000)]
    )
    rb = RoaringBitmap(vals)
    rb.run_optimize()
    other = RoaringBitmap(
        np.concatenate(
            [np.arange(s, s + 500, dtype=np.uint32) for s in range(400, 3_000_000, 1000)]
        )
    )
    other.run_optimize()
    path = tmp_path / "runs.bin"
    path.write_bytes(rb.serialize())
    imm = ImmutableRoaringBitmap.map_file(str(path))
    n_containers = imm.get_container_count()

    # (a) payload arrays are views into the mapping, run-typed throughout
    buf = np.frombuffer(imm._buf, dtype=np.uint8)
    for i in range(n_containers):
        c = imm.high_low_container.get_container_at_index(i)
        assert isinstance(c, RunContainer), i
        assert np.shares_memory(c.starts, buf), i
        assert np.shares_memory(c.lengths, buf), i

    # (b) the query mix allocates nowhere near the 8 KB/container word form
    probe = [int(v) for v in vals[:: len(vals) // 97]]
    tracemalloc.start()
    tracemalloc.reset_peak()
    inter = RoaringBitmap.and_(imm, other)
    inter_card = inter.get_cardinality()
    hits = sum(imm.contains(p) for p in probe)
    ranks = [imm.rank(p) for p in probe]
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    word_form = 8192 * n_containers
    assert peak < word_form // 2, (peak, word_form)

    # correctness oracle vs the heap path
    want = RoaringBitmap.and_(rb, other)
    assert inter_card == want.get_cardinality() and inter == want
    assert hits == len(probe)
    assert ranks == [rb.rank(p) for p in probe]


def test_mapped_bulk_probes_match_heap():
    """contains_many/rank_many/select_many run over the lazily mapped
    views, equal to the heap facade (and the rank prefix reads only the
    header cardinalities, no payload decode)."""
    rng = np.random.default_rng(41)
    vals = np.unique(rng.choice(1 << 22, 50_000, replace=False)).astype(np.uint32)
    heap = RoaringBitmap(vals)
    heap.run_optimize()
    imm = ImmutableRoaringBitmap(heap.serialize())
    probes = rng.choice(1 << 23, 2000).astype(np.uint32)
    assert np.array_equal(imm.contains_many(probes), heap.contains_many(probes))
    assert np.array_equal(imm.rank_many(probes), heap.rank_many(probes))
    ranks = rng.integers(0, vals.size, 2000)
    assert np.array_equal(imm.select_many(ranks), heap.select_many(ranks))
