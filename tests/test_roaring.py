"""RoaringBitmap facade differential tests vs Python-set semantics
(reference suite: TestRoaringBitmap.java, 5,590 LoC)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap


def test_point_ops():
    bm = RoaringBitmap()
    assert bm.is_empty()
    bm.add(1)
    bm.add(1 << 20)
    bm.add((1 << 32) - 1)
    assert bm.contains(1) and bm.contains(1 << 20) and bm.contains((1 << 32) - 1)
    assert not bm.contains(2)
    assert bm.get_cardinality() == 3
    bm.remove(1 << 20)
    assert not bm.contains(1 << 20)
    assert bm.get_cardinality() == 2
    assert bm.checked_add(5)
    assert not bm.checked_add(5)
    assert bm.checked_remove(5)
    assert not bm.checked_remove(5)


def test_value_range_validation():
    bm = RoaringBitmap()
    with pytest.raises(ValueError):
        bm.add(-1)
    with pytest.raises(ValueError):
        bm.add(1 << 32)


def test_add_many_and_to_array(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    assert np.array_equal(bm.to_array(), np.unique(vals))
    assert bm.get_cardinality() == np.unique(vals).size


def test_pairwise_algebra(random_bitmap_factory):
    for _ in range(5):
        b1, v1 = random_bitmap_factory()
        b2, v2 = random_bitmap_factory()
        s1, s2 = set(v1.tolist()), set(v2.tolist())
        assert set(RoaringBitmap.and_(b1, b2).to_array().tolist()) == s1 & s2
        assert set(RoaringBitmap.or_(b1, b2).to_array().tolist()) == s1 | s2
        assert set(RoaringBitmap.xor(b1, b2).to_array().tolist()) == s1 ^ s2
        assert set(RoaringBitmap.andnot(b1, b2).to_array().tolist()) == s1 - s2
        assert RoaringBitmap.and_cardinality(b1, b2) == len(s1 & s2)
        assert RoaringBitmap.or_cardinality(b1, b2) == len(s1 | s2)
        assert RoaringBitmap.xor_cardinality(b1, b2) == len(s1 ^ s2)
        assert RoaringBitmap.andnot_cardinality(b1, b2) == len(s1 - s2)
        assert RoaringBitmap.intersects(b1, b2) == bool(s1 & s2)


def test_operators(random_bitmap_factory):
    b1, v1 = random_bitmap_factory()
    b2, v2 = random_bitmap_factory()
    s1, s2 = set(v1.tolist()), set(v2.tolist())
    assert set((b1 | b2).to_array().tolist()) == s1 | s2
    assert set((b1 & b2).to_array().tolist()) == s1 & s2
    assert set((b1 ^ b2).to_array().tolist()) == s1 ^ s2
    assert set((b1 - b2).to_array().tolist()) == s1 - s2
    c = b1.clone()
    c |= b2
    assert set(c.to_array().tolist()) == s1 | s2


def test_or_not():
    b1 = RoaringBitmap([1, 100])
    b2 = RoaringBitmap([2, 3])
    # b1 | ~b2 over [0, 6) = {1,100} | {0,1,4,5} = {0,1,4,5,100}
    got = RoaringBitmap.or_not(b1, b2, 6)
    assert set(got.to_array().tolist()) == {0, 1, 4, 5, 100}


def test_range_ops():
    bm = RoaringBitmap()
    bm.add_range(100, 200000)
    assert bm.get_cardinality() == 200000 - 100
    assert bm.contains_range(100, 200000)
    assert not bm.contains_range(99, 200000)
    assert bm.contains(65536)
    bm.remove_range(150, 70000)
    assert bm.get_cardinality() == (200000 - 100) - (70000 - 150)
    assert not bm.contains(65536)
    bm.flip_range(0, 100)
    assert bm.contains(0) and bm.contains(99)
    assert bm.range_cardinality(0, 100) == 100
    # flip is involutive
    bm.flip_range(0, 100)
    assert not bm.contains(0)


def test_flip_static():
    bm = RoaringBitmap([1, 3])
    flipped = RoaringBitmap.flip(bm, 0, 5)
    assert set(flipped.to_array().tolist()) == {0, 2, 4}
    assert set(bm.to_array().tolist()) == {1, 3}


def test_cross_container_range():
    bm = RoaringBitmap()
    bm.add_range(0, 1 << 20)  # 16 full chunks
    assert bm.get_cardinality() == 1 << 20
    assert bm.has_run_compression() or True  # full chunks are run containers
    assert bm.contains_range(0, 1 << 20)
    bm.remove_range(65536, 131072)  # drop one whole chunk
    assert bm.get_cardinality() == (1 << 20) - 65536
    assert not bm.contains(65536)


def test_rank_select(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    u = np.unique(vals)
    for j in [0, len(u) // 3, len(u) - 1]:
        assert bm.select(j) == u[j]
        assert bm.rank(int(u[j])) == j + 1
    with pytest.raises(IndexError):
        bm.select(len(u))
    assert bm.first() == u[0]
    assert bm.last() == u[-1]


def test_next_previous(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    u = np.unique(vals)
    mid = int(u[len(u) // 2])
    assert bm.next_value(mid) == mid
    assert bm.previous_value(mid) == mid
    if mid + 1 not in set(u.tolist()):
        nxt = bm.next_value(mid + 1)
        expected = u[u > mid]
        assert nxt == (int(expected[0]) if expected.size else -1)
    assert bm.next_value(int(u[-1]) + 1 if u[-1] < (1 << 32) - 1 else int(u[-1])) in (-1, u[-1])
    assert bm.previous_value(0) in (-1, 0)


def test_absent_values():
    bm = RoaringBitmap(range(10, 20))
    assert bm.next_absent_value(10) == 20
    assert bm.next_absent_value(0) == 0
    assert bm.previous_absent_value(19) == 9
    # across a full chunk
    bm2 = RoaringBitmap()
    bm2.add_range(0, 65536)
    assert bm2.next_absent_value(0) == 65536
    assert bm2.previous_absent_value(70000) == 70000


def test_add_offset():
    bm = RoaringBitmap([0, 1, 65535, 65536, 1000000])
    shifted = RoaringBitmap.add_offset(bm, 10)
    assert set(shifted.to_array().tolist()) == {10, 11, 65545, 65546, 1000010}
    neg = RoaringBitmap.add_offset(bm, -2)
    assert set(neg.to_array().tolist()) == {65533, 65534, 999998}
    # offset pushing past the universe drops values
    top = RoaringBitmap.add_offset(bm, (1 << 32) - 100)
    assert top.get_cardinality() == 2  # only 0,1 survive


def test_limit_and_select_range(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    u = np.unique(vals)
    k = min(100, len(u))
    lim = bm.limit(k)
    assert np.array_equal(lim.to_array(), u[:k])
    sr = bm.select_range(5, 15)
    assert np.array_equal(sr.to_array(), u[5:15])


def test_contains_bitmap_subset(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    sub = bm.limit(bm.get_cardinality() // 2)
    assert bm.contains_bitmap(sub)
    sub.add(99)  # 99 unlikely in chunk keys >= 0... force a miss value
    if not bm.contains(99):
        assert not bm.contains_bitmap(sub)


def test_hamming_similar():
    b1 = RoaringBitmap([1, 2, 3])
    b2 = RoaringBitmap([1, 2, 4])
    assert b1.is_hamming_similar(b2, 2)
    assert not b1.is_hamming_similar(b2, 1)


def test_iteration(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    u = np.unique(vals)
    assert np.array_equal(np.array(list(bm), dtype=np.uint32), u)
    assert np.array_equal(np.array(list(reversed(bm)), dtype=np.uint32), u[::-1])
    batches = list(bm.batch_iterator(256))
    assert all(b.size <= 256 for b in batches)
    assert np.array_equal(np.concatenate(batches), u)


def test_run_optimize_preserves_values(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    before = bm.to_array()
    bm.run_optimize()
    assert np.array_equal(bm.to_array(), before)
    bm.remove_run_compression()
    assert np.array_equal(bm.to_array(), before)
    assert not bm.has_run_compression()


def test_equality_and_hash(random_bitmap_factory):
    bm, vals = random_bitmap_factory()
    assert bm == bm.clone()
    other = bm.clone()
    other.add(0) if not bm.contains(0) else other.remove(0)
    assert bm != other


def test_empty_edge_cases():
    bm = RoaringBitmap()
    assert bm.to_array().size == 0
    assert list(bm) == []
    assert not bm
    with pytest.raises(ValueError):
        bm.first()
    assert bm.next_value(0) == -1
    assert bm.previous_value((1 << 32) - 1) == -1
    assert bm.rank(12345) == 0


def test_constructor_accepts_any_iterable():
    """Sets and generators, not just sequences (code-review regression)."""
    assert set(RoaringBitmap({1, 2, 3}).to_array().tolist()) == {1, 2, 3}
    assert set(RoaringBitmap(v for v in [5, 6]).to_array().tolist()) == {5, 6}
    assert RoaringBitmap(iter([])).is_empty()


def test_andnot_range_matches_set_oracle(random_bitmap_factory):
    """Ranged difference (RoaringBitmap.andNot(x1, x2, start, end),
    RoaringBitmap.java:1396-1402): both operands restricted to the range."""
    a, va = random_bitmap_factory()
    b, vb = random_bitmap_factory()
    sa, sb = set(map(int, va)), set(map(int, vb))
    lo = int(np.min(va)) + 1000
    hi = max(int(np.max(va)) // 2 + (1 << 17), lo)
    got = RoaringBitmap.andnot_range(a, b, lo, hi)
    want = {v for v in sa - sb if lo <= v < hi}
    assert set(map(int, got.to_array())) == want
    # range boundaries inside one container, empty range, full range
    assert RoaringBitmap.andnot_range(a, b, 5, 5).is_empty()
    full = RoaringBitmap.andnot_range(a, b, 0, 1 << 32)
    assert full == RoaringBitmap.andnot(a, b)


def test_varargs_facade_delegates_to_aggregation(random_bitmap_factory):
    """or/and/xor facade overloads over >2 operands delegate to
    FastAggregation like RoaringBitmap.java:831-844."""
    bms = [random_bitmap_factory()[0] for _ in range(4)]
    sets = [set(map(int, bm.to_array())) for bm in bms]
    assert set(map(int, RoaringBitmap.or_(*bms).to_array())) == set.union(*sets)
    assert set(map(int, RoaringBitmap.and_(*bms).to_array())) == set.intersection(*sets)
    want_xor = set()
    for s in sets:
        want_xor ^= s
    assert set(map(int, RoaringBitmap.xor(*bms).to_array())) == want_xor


def test_rank_many_matches_scalar(random_bitmap_factory):
    """Vectorized bulk rank == scalar rank_long across container shapes,
    absent chunks, boundaries, and the empty bitmap."""
    bm, vals = random_bitmap_factory()
    rng = np.random.default_rng(7)
    qs = np.concatenate(
        [
            rng.integers(0, 1 << 23, 600).astype(np.uint32),
            np.unique(vals)[:50],
            np.array([0, (1 << 32) - 1], dtype=np.uint32),
        ]
    )
    assert bm.rank_many(qs).tolist() == [bm.rank_long(int(q)) for q in qs]
    assert RoaringBitmap().rank_many(qs).tolist() == [0] * qs.size
    assert bm.rank_many([]).size == 0
    with pytest.raises(ValueError):
        bm.rank_many([-1])


def test_select_many_matches_scalar(random_bitmap_factory):
    """Vectorized bulk select == scalar select; select_many/rank_many are
    inverse on present values; out-of-range raises like the scalar."""
    bm, vals = random_bitmap_factory()
    u = np.unique(vals)
    rng = np.random.default_rng(13)
    ranks = np.concatenate(
        [rng.integers(0, u.size, 200), np.array([0, u.size - 1])]
    )
    got = bm.select_many(ranks)
    assert np.array_equal(got, u[ranks])
    assert np.array_equal(bm.rank_many(got), ranks + 1)
    with pytest.raises(IndexError):
        bm.select_many([u.size])
    with pytest.raises(IndexError):
        bm.select_many([-1])
    assert bm.select_many([]).size == 0
