"""Query-scoped tracing, decision provenance, and the resource
observatory (ISSUE 9): trace-context scoping + explicit lane handoff
(incl. the 16-thread no-bleed hammer), the bounded decision log wired to
every deciding site, lock-wait histograms with the off-mode contract and
lockwitness leaf-safety, the jit compile/retrace counter + anomaly dump,
device-memory reconciliation, flow events, and golden exporter output
for the new metrics."""

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, insights, observe
from roaringbitmap_tpu.analysis.lockwitness import LockWitness, WitnessedLock
from roaringbitmap_tpu.observe import Registry, latency_histogram
from roaringbitmap_tpu.observe import compilewatch, context, decisions, lockstats
from roaringbitmap_tpu.observe import timeline as tl
from roaringbitmap_tpu.parallel import aggregation, overlap, store
from roaringbitmap_tpu.query import Q, execute


@pytest.fixture
def recording():
    prev = tl.mode_name()
    tl.configure(mode="on", budget_ms=0)
    tl.RECORDER.clear()
    try:
        yield tl.RECORDER
    finally:
        tl.configure(mode=prev, budget_ms=0)
        tl.RECORDER.clear()


def _bitmaps(n=4, size=1200, span=1 << 18, seed=3):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(span, size, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# trace context: scoping rules
# ---------------------------------------------------------------------------


def test_trace_scope_mints_reuses_and_resets():
    assert context.current_trace() is None
    with context.trace_scope() as outer:
        assert outer.trace_id is not None
        assert context.current_trace() == outer.trace_id
        with context.trace_scope() as inner:  # nested: same query
            assert inner.trace_id == outer.trace_id
        with context.trace_scope("pinned") as pinned:  # explicit: pins
            assert context.current_trace() == "pinned"
            assert pinned.trace_id == "pinned"
        assert context.current_trace() == outer.trace_id
    assert context.current_trace() is None


def test_trace_ids_are_process_unique():
    ids = {context.new_trace_id() for _ in range(1000)}
    assert len(ids) == 1000


def test_adopt_is_explicit_and_none_safe():
    with context.adopt(None):
        assert context.current_trace() is None
    with context.adopt("handed-off"):
        assert context.current_trace() == "handed-off"
    assert context.current_trace() is None


def test_context_kill_switch():
    context.configure(enabled=False)
    try:
        with context.trace_scope() as s:
            assert s.trace_id is None
            assert context.current_trace() is None
    finally:
        context.configure(enabled=True)


def test_threads_do_not_inherit_context_implicitly():
    got = []
    with context.trace_scope():
        t = threading.Thread(target=lambda: got.append(context.current_trace()))
        t.start()
        t.join()
    assert got == [None]  # handoff is explicit by design


# ---------------------------------------------------------------------------
# 16-thread hammer: trace ids never bleed across concurrent queries
# ---------------------------------------------------------------------------


def test_sixteen_thread_trace_isolation_hammer(recording):
    """Each worker runs real query executions under explicit per-worker
    trace ids; afterwards every recorded event must carry a trace id of
    the worker that owns the event's thread — a single cross-thread bleed
    fails (satellite: contextvar isolation)."""
    bms = _bitmaps(6, size=400)
    exprs = [
        (Q.leaf(bms[i % 6]) & Q.leaf(bms[(i + 1) % 6])) | Q.leaf(bms[(i + 2) % 6])
        for i in range(4)
    ]
    errors = []
    tid_to_worker = {}
    barrier = threading.Barrier(16)

    def worker(w):
        tid_to_worker[threading.get_ident()] = w
        barrier.wait()
        for j in range(12):
            tid = f"w{w}.{j}"
            with context.trace_scope(tid):
                execute(exprs[j % len(exprs)], cache=None)
                if context.current_trace() != tid:
                    errors.append(f"worker {w} lost its id at iter {j}")
            if context.current_trace() is not None:
                errors.append(f"worker {w} leaked a trace id")

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    evs = [e for e in tl.RECORDER.events() if e.tid in tid_to_worker]
    assert evs, "hammer recorded no events on worker threads"
    for e in evs:
        if e.trace is None:
            continue  # events outside any scope (none expected, but benign)
        want = f"w{tid_to_worker[e.tid]}."
        assert e.trace.startswith(want), (
            f"event {e.name} on worker {tid_to_worker[e.tid]} carries "
            f"foreign trace {e.trace}"
        )


def test_lane_handoff_attributes_stagings_to_their_queries(recording):
    """Explicit handoff across the ShipLane thread boundary: two stagings
    submitted under different trace ids; the lane-thread events of each
    must carry the submitting query's id (satellite: lane handoff)."""
    set_a = _bitmaps(2, size=600, seed=11)
    set_b = _bitmaps(3, size=600, seed=12)
    store.PACK_CACHE.close()
    overlap.LANE.drain()
    prev = overlap.LANE.threading_mode
    overlap.LANE.configure("on")
    try:
        with context.trace_scope("lane-a"):
            st_a = overlap.LANE.prefetch(set_a)
        assert st_a is not None and st_a.trace == "lane-a"
        with context.trace_scope("lane-a"):
            assert overlap.LANE.wait(set_a) is not None
        with context.trace_scope("lane-b"):
            st_b = overlap.LANE.prefetch(set_b)
        assert st_b is not None and st_b.trace == "lane-b"
        with context.trace_scope("lane-b"):
            assert overlap.LANE.wait(set_b) is not None
    finally:
        overlap.LANE.drain()
        overlap.LANE.configure(prev)
        store.PACK_CACHE.close()
    names = tl.thread_names()
    lane_evs = [
        e for e in tl.RECORDER.events()
        if names.get(e.tid, "").startswith("rb-ship-lane")
    ]
    assert lane_evs, "lane thread recorded nothing"
    assert all(e.trace in ("lane-a", "lane-b") for e in lane_evs), [
        (e.name, e.trace) for e in lane_evs
    ]
    # the two stagings are distinguishable by operand count; each span
    # must carry ITS OWN query's id, not the other's
    for e in lane_evs:
        if e.name == "overlap.stage":
            want = "lane-a" if e.attrs["n"] == 2 else "lane-b"
            assert e.trace == want, (e.attrs, e.trace)
    # flow events link submit -> stage -> join under matching flow ids
    flows = {}
    for e in tl.RECORDER.events():
        if e.ph in ("s", "t", "f"):
            flows.setdefault(e.attrs["flow"], []).append(e.ph)
    assert len(flows) == 2
    for phases in flows.values():
        assert phases == ["s", "t", "f"]


def test_lane_thread_name_registered_eagerly_without_any_event():
    """The satellite fix: the lane pool registers its thread name at
    thread START (executor initializer), so even a staging that records
    zero events (timeline off) leaves the tid named for later exports."""
    assert tl.mode_name() == "off"
    bms = _bitmaps(2, size=300, seed=21)
    store.PACK_CACHE.close()
    prev = overlap.LANE.threading_mode
    overlap.LANE.configure("on")
    try:
        with context.trace_scope("eager"):
            st = overlap.LANE.prefetch(bms)
        assert st is not None
        overlap.LANE.wait(bms)
    finally:
        overlap.LANE.drain()
        overlap.LANE.configure(prev)
        store.PACK_CACHE.close()
    assert any(
        n.startswith("rb-ship-lane") for n in tl.thread_names().values()
    )


# ---------------------------------------------------------------------------
# per-trace attribution + flow rendering
# ---------------------------------------------------------------------------


def test_stage_totals_per_trace(recording):
    with context.trace_scope("qa"):
        with tl.tspan("stage.x", "t"):
            time.sleep(0.002)
    with context.trace_scope("qb"):
        with tl.tspan("stage.x", "t"):
            time.sleep(0.002)
        with tl.tspan("stage.y", "t"):
            pass
    evs = tl.RECORDER.events()
    flat = tl.stage_totals(evs, ["stage.x", "stage.y"])
    per = tl.stage_totals(evs, ["stage.x", "stage.y"], per_trace=True)
    assert set(per) == {"qa", "qb"}
    assert per["qa"]["stage.x"] > 0 and "stage.y" not in per["qa"]
    assert flat["stage.x"] == pytest.approx(
        per["qa"]["stage.x"] + per["qb"]["stage.x"]
    )


def test_chrome_trace_renders_flows_and_trace_args(recording):
    fid = tl.flow_id("q1", "key")
    with context.trace_scope("q1"):
        tl.flow_point("handoff", "s", fid)
        with tl.tspan("work", "t"):
            pass
        tl.flow_point("handoff", "f", fid)
    trace = tl.chrome_trace()
    by_ph = {}
    for rec in trace["traceEvents"]:
        by_ph.setdefault(rec["ph"], []).append(rec)
    assert by_ph["s"][0]["id"] == fid
    assert by_ph["f"][0]["id"] == fid and by_ph["f"][0]["bp"] == "e"
    assert by_ph["X"][0]["args"]["trace"] == "q1"
    with pytest.raises(ValueError):
        tl.flow_point("handoff", "x", fid)


def test_timeline_event_trace_arg_is_optional():
    e = tl.TimelineEvent("n", "c", "X", 0, 5, 1, None)  # legacy 7-arg form
    assert e.trace is None and "trace" not in e.to_dict()
    e2 = tl.TimelineEvent("n", "c", "X", 0, 5, 1, None, trace="q1")
    assert e2.to_dict()["trace"] == "q1"


# ---------------------------------------------------------------------------
# decision provenance
# ---------------------------------------------------------------------------


def test_decision_log_bounded_ring_and_tail():
    log = decisions.DecisionLog(capacity=4)
    for i in range(10):
        log.record({"site": "s", "decision": str(i)})
    assert log.total() == 10
    tail = log.tail()
    assert [e["decision"] for e in tail] == ["6", "7", "8", "9"]
    assert [e["decision"] for e in log.tail(2)] == ["8", "9"]
    tail[0]["decision"] = "mutated"  # copies: the ring is unaffected
    assert log.tail()[0]["decision"] == "6"
    log.resize(2)
    assert [e["decision"] for e in log.tail()] == ["8", "9"]


def test_decisions_carry_trace_and_mirror_to_recorder(recording):
    with context.trace_scope("qd"):
        decisions.record_decision("test.site", "chosen", rows=7)
    entry = decisions.decisions(1)[0]
    assert entry["site"] == "test.site" and entry["decision"] == "chosen"
    assert entry["trace"] == "qd" and entry["inputs"] == {"rows": 7}
    evs = [e for e in tl.RECORDER.events() if e.name == "decision.test.site"]
    assert evs and evs[0].trace == "qd"
    assert evs[0].attrs["decision"] == "chosen"


def test_decisions_kill_switch():
    before = decisions.LOG.total()
    decisions.configure(enabled=False)
    try:
        decisions.record_decision("test.site", "nope")
    finally:
        decisions.configure(enabled=True)
    assert decisions.LOG.total() == before


def test_dispatch_planner_ladder_and_cache_decisions_end_to_end():
    from roaringbitmap_tpu.robust import ladder

    bms = _bitmaps(4, size=800, seed=5)
    store.PACK_CACHE.close()
    aggregation.FastAggregation.or_(*bms, mode="cpu")
    execute(Q.leaf(bms[0]) | Q.leaf(bms[1]), cache=None)
    aggregation.FastAggregation.or_(*bms, mode="device")
    ladder.LADDER.note_degrade("test.site", "device", "cpu")
    got = insights.decisions()
    sites = {d["site"] for d in got}
    assert {"agg.dispatch", "query.plan", "ladder.degrade",
            "pack_cache.admit", "columnar.cutoff"} <= sites
    disp = [d for d in got if d["site"] == "agg.dispatch"][-1]
    assert {"op", "rows", "operands"} <= set(disp["inputs"])
    plan_d = [d for d in got if d["site"] == "query.plan"][-1]
    assert {"op", "est_card", "est_rows"} <= set(plan_d["inputs"])
    # the fold entries ran inside a trace scope, so they carry an id
    assert disp["trace"]
    store.PACK_CACHE.close()


def test_columnar_cutoff_not_recorded_below_count_gate():
    """The 2 µs per-container floor must not pay a decision record: a
    small pair (below min_containers) routes without logging."""
    from roaringbitmap_tpu import columnar

    a = RoaringBitmap(np.array([1, 2, 3], dtype=np.uint32))
    b = RoaringBitmap(np.array([2, 3, 4], dtype=np.uint32))
    before = decisions.LOG.total()
    assert columnar.engine.enabled_for(
        a.high_low_container, b.high_low_container
    ) is False
    assert decisions.LOG.total() == before


# ---------------------------------------------------------------------------
# lock-wait observatory
# ---------------------------------------------------------------------------


def test_lockstats_install_uninstall_roundtrip():
    from roaringbitmap_tpu import native, tracing

    raw = tracing._TIMINGS_LOCK
    raw_native = native._lock
    lockstats.install(enable_timing=False)
    try:
        assert isinstance(tracing._TIMINGS_LOCK, lockstats.TimedLock)
        assert tracing._TIMINGS_LOCK._inner is raw
        names = set(lockstats.installed())
        assert {"tracing.timings", "observe.registry", "query.expr.intern",
                "query.exec.plan_memo", "query.cache", "agg.pool",
                "native.loader"} == names
        lockstats.install(enable_timing=False)  # idempotent
        assert tracing._TIMINGS_LOCK._inner is raw
    finally:
        lockstats.uninstall()
    assert tracing._TIMINGS_LOCK is raw
    assert native._lock is raw_native
    assert lockstats.installed() == []
    # metrics' captured registry-lock references are restored too
    m = observe.REGISTRY.get(observe.LOCK_WAIT_SECONDS)
    assert not isinstance(m._lock, lockstats.TimedLock)


def test_lockstats_records_waits_when_enabled_and_not_when_off():
    hist = observe.REGISTRY.get(observe.LOCK_WAIT_SECONDS)
    lockstats.install(enable_timing=True)
    try:
        observe.counter("rb_tpu_lockstats_probe_total", "", ("k",)).inc(1, ("x",))
        st = hist.get(("observe.registry",))
        assert st is not None and st["count"] > 0
        count_on = st["count"]
        lockstats.enable(False)
        for _ in range(50):
            observe.REGISTRY.get(observe.LOCK_WAIT_SECONDS)  # takes the lock
        st2 = hist.get(("observe.registry",))
        assert st2["count"] == count_on  # off-mode: the int compare only
    finally:
        lockstats.uninstall()


def test_lockstats_sampling():
    lockstats.install(enable_timing=True, sample=1000)
    try:
        hist = observe.REGISTRY.get(observe.LOCK_WAIT_SECONDS)
        before = (hist.get(("observe.registry",)) or {"count": 0})["count"]
        for i in range(50):
            observe.counter(
                "rb_tpu_lockstats_probe_total", "", ("k",)
            ).inc(1, ("y",))
        after = (hist.get(("observe.registry",)) or {"count": 0})["count"]
        assert after - before < 5  # ~1/1000 sampled, not every acquire
    finally:
        lockstats.uninstall()


def test_lock_wait_observe_is_leaf_safe_under_witness():
    """The observatory's histogram observe runs while HOLDING the wrapped
    lock — witness every inner lock under a query-execute hammer and
    assert the acquisition-order graph stays acyclic (the lockwitness
    leaf-safety contract from the ISSUE)."""
    bms = _bitmaps(4, size=500, seed=9)
    exprs = [
        Q.leaf(bms[0]) | Q.leaf(bms[1]),
        (Q.leaf(bms[1]) & Q.leaf(bms[2])) | Q.leaf(bms[3]),
    ]
    lockstats.install(enable_timing=True)
    w = LockWitness()
    try:
        for name, (tlock, _set) in list(lockstats._INSTALLED.items()):
            tlock._inner = w.wrap(name, tlock._inner)
        with ThreadPoolExecutor(max_workers=8) as pool:
            list(
                pool.map(
                    lambda i: execute(exprs[i % 2], cache=None),
                    range(32),
                )
            )
        w.assert_consistent()
        # the known nesting (metrics recorded under the cache lock) was
        # actually exercised THROUGH the timed proxies
        assert any(b == "observe.registry" for _a, b in w.edges)
    finally:
        for _name, (tlock, _set) in list(lockstats._INSTALLED.items()):
            if isinstance(tlock._inner, WitnessedLock):
                tlock._inner = tlock._inner._inner
        lockstats.uninstall()


# ---------------------------------------------------------------------------
# compile/retrace watcher
# ---------------------------------------------------------------------------


def test_tracked_counts_traces_not_calls():
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("k",))
    @compilewatch.tracked("observatory_test_fn")
    def f(x, k=1):
        return x * k

    def count():
        return compilewatch.compile_counts().get("observatory_test_fn", 0)

    base = count()
    x4 = jnp.arange(4, dtype=jnp.int32)
    f(x4, k=2)
    f(x4, k=2)  # cache hit: no retrace
    assert count() == base + 1
    f(x4, k=3)  # new static arg: retrace
    assert count() == base + 2
    f(jnp.arange(8, dtype=jnp.int32), k=3)  # new shape: retrace
    assert count() == base + 3
    f(x4, k=2)  # old signature still cached
    assert count() == base + 3


def test_tracked_preserves_donation():
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    @compilewatch.tracked("observatory_donate_fn")
    def g(x):
        return x + 1

    out = g(jnp.arange(4, dtype=jnp.int32))
    assert np.array_equal(np.asarray(out), [1, 2, 3, 4])
    assert compilewatch.compile_counts()["observatory_donate_fn"] >= 1


def test_compile_budget_anomaly_dump(tmp_path, recording, monkeypatch):
    import jax
    import jax.numpy as jnp

    dump = tmp_path / "compile_anomaly.jsonl"
    monkeypatch.setattr(compilewatch, "_BUDGET", 2)
    monkeypatch.setattr(compilewatch, "_DUMP_PATH", str(dump))
    monkeypatch.setattr(compilewatch, "_LAST_DUMP_NS", 0)

    @jax.jit
    @compilewatch.tracked("observatory_budget_fn")
    def h(x):
        return x + 1

    for n in (2, 4, 8, 16):  # 4 shapes: 4 traces > budget 2
        h(jnp.arange(n, dtype=jnp.int32))
    assert dump.is_file()
    header = json.loads(dump.read_text().splitlines()[0])
    assert header["trigger"]["compile_fn"] == "observatory_budget_fn"
    assert header["trigger"]["budget"] == 2
    anomalies = [
        e for e in tl.RECORDER.events() if e.name == "compile.anomaly"
    ]
    assert anomalies and anomalies[0].attrs["fn"] == "observatory_budget_fn"


def test_north_star_reduce_reaches_steady_state_with_zero_retraces():
    bms = _bitmaps(6, size=1500, seed=13)
    store.PACK_CACHE.close()
    packed = store.packed_for(bms)
    run, _layout = store.prepare_reduce(packed, op="or")
    run()  # cold one-shot (fused gather+reduce)
    run()  # second touch builds the resident padded block + compiles
    before = compilewatch.compile_counts()
    for _ in range(4):
        run()
    after = compilewatch.compile_counts()
    assert sum(after.values()) == sum(before.values()), (
        "steady-state reduce retraced: "
        f"{ {k: after[k] - before.get(k, 0) for k in after} }"
    )
    store.PACK_CACHE.close()


# ---------------------------------------------------------------------------
# device-memory reconciliation
# ---------------------------------------------------------------------------


def test_hbm_reconciliation_ledger_agrees():
    store.PACK_CACHE.close()
    recon0 = store.hbm_reconciliation()
    assert recon0["ledger_drift_bytes"] == 0
    bms = _bitmaps(4, size=900, seed=17)
    packed = store.packed_for(bms)
    packed.device_words.block_until_ready()
    recon = store.hbm_reconciliation()
    assert recon["entries"] >= 1
    assert recon["gauge_bytes"] == recon["ledger_bytes"] == recon["entry_sum_bytes"]
    assert recon["ledger_drift_bytes"] == 0
    drift = observe.REGISTRY.get(observe.HBM_ACCOUNTING_DRIFT_BYTES)
    assert drift.get(("ledger",)) == 0
    store.PACK_CACHE.close()
    assert store.hbm_reconciliation()["gauge_bytes"] == 0


def test_observatory_snapshot_shape():
    obs = insights.observatory()
    assert {"locks", "compile", "hbm", "breakers", "pack_cache",
            "decisions"} <= set(obs)
    assert isinstance(obs["decisions"], list)
    assert "ledger_drift_bytes" in obs["hbm"]


# ---------------------------------------------------------------------------
# golden exporter output for the new metrics (satellite)
# ---------------------------------------------------------------------------


def _observatory_registry() -> Registry:
    reg = Registry()
    lw = latency_histogram(
        "rb_tpu_lock_wait_seconds", "lock waits", ("lock",),
        buckets=(0.001, 0.1), registry=reg,
    )
    lw.observe(0.0005, ("pack.cache",))
    lw.observe(0.05, ("pack.cache",))
    lw.observe(0.05, ("pack.cache",))
    c = reg.counter("rb_tpu_compile_total", "traces", ("fn",))
    c.inc(3, ("wide_reduce",))
    g = reg.gauge("rb_tpu_hbm_accounting_drift_bytes", "drift", ("source",))
    g.set(0, ("ledger",))
    return reg


def test_prometheus_golden_lock_wait_and_compile():
    text = observe.prometheus_text(_observatory_registry())
    assert text.splitlines() == [
        "# HELP rb_tpu_compile_total traces",
        "# TYPE rb_tpu_compile_total counter",
        'rb_tpu_compile_total{fn="wide_reduce"} 3',
        "# HELP rb_tpu_hbm_accounting_drift_bytes drift",
        "# TYPE rb_tpu_hbm_accounting_drift_bytes gauge",
        'rb_tpu_hbm_accounting_drift_bytes{source="ledger"} 0',
        "# HELP rb_tpu_lock_wait_seconds lock waits",
        "# TYPE rb_tpu_lock_wait_seconds histogram",
        'rb_tpu_lock_wait_seconds_bucket{lock="pack.cache",le="0.001"} 1',
        'rb_tpu_lock_wait_seconds_bucket{lock="pack.cache",le="0.1"} 3',
        'rb_tpu_lock_wait_seconds_bucket{lock="pack.cache",le="+Inf"} 3',
        'rb_tpu_lock_wait_seconds_sum{lock="pack.cache"} 0.1005',
        'rb_tpu_lock_wait_seconds_count{lock="pack.cache"} 3',
        'rb_tpu_lock_wait_seconds{lock="pack.cache",quantile="0.5"} '
        "0.025750000000000002",
        'rb_tpu_lock_wait_seconds{lock="pack.cache",quantile="0.9"} '
        "0.08515000000000002",
        'rb_tpu_lock_wait_seconds{lock="pack.cache",quantile="0.99"} '
        "0.09851499999999999",
    ]


def test_jsonl_golden_lock_wait_and_compile():
    recs = [json.loads(l) for l in observe.jsonl_lines(_observatory_registry())]
    assert [r["name"] for r in recs] == [
        "rb_tpu_compile_total",
        "rb_tpu_hbm_accounting_drift_bytes",
        "rb_tpu_lock_wait_seconds",
    ]
    assert recs[0] == {
        "labels": {"fn": "wide_reduce"},
        "name": "rb_tpu_compile_total",
        "type": "counter",
        "value": 3,
    }
    lw = recs[2]
    assert lw["count"] == 3
    assert lw["buckets"] == {"0.001": 1, "0.1": 3, "+Inf": 3}
    assert set(lw["quantiles"]) == {"p50", "p90", "p99"}
    assert lw["quantiles"]["p50"] == pytest.approx(0.02575)


def test_sidecar_carries_observatory_blocks():
    side = observe.sidecar_snapshot(_observatory_registry())
    assert side["compile"] == {"wide_reduce": 3}
    assert side["hbm_drift"] == {"ledger": 0}
    assert side["lock_wait"]["pack.cache"]["count"] == 3
    assert "rb_tpu_lock_wait_seconds" in side["latency"]
    q = side["latency"]["rb_tpu_lock_wait_seconds"]["pack.cache"]
    assert {"count", "sum", "p50", "p90", "p99"} <= set(q)
