"""Container layer differential tests: every op vs Python-set semantics,
every container-type pairing (the reference's 9-combination op matrix,
Container.java:63-98, covered by TestArrayContainer/TestBitmapContainer/
TestRunContainer)."""

import numpy as np
import pytest

from roaringbitmap_tpu.models.container import (
    ARRAY_MAX_SIZE,
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
    best_container_of_words,
    container_from_values,
    container_range_of_ones,
)
from roaringbitmap_tpu.utils import bits


def make_array(values):
    return ArrayContainer(np.array(sorted(values), dtype=np.uint16))


def make_bitmap(values):
    return BitmapContainer(bits.words_from_values(np.array(sorted(values), dtype=np.uint16)))


def make_run(values):
    return RunContainer.from_values(np.array(sorted(values), dtype=np.uint16))


MAKERS = [make_array, make_bitmap, make_run]


def sample_sets(rng):
    sparse = set(rng.choice(1 << 16, size=500, replace=False).tolist())
    dense = set(rng.choice(1 << 16, size=9000, replace=False).tolist())
    runs = set()
    for s in rng.choice(np.arange(0, 60000, 100), size=40, replace=False).tolist():
        runs |= set(range(s, s + int(rng.integers(1, 80))))
    return [sparse, dense, runs, set(), {0}, {65535}, set(range(0, 65536))]


@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_pairwise_matrix(op):
    rng = np.random.default_rng(10)
    sets = sample_sets(rng)
    pairs = [(sets[0], sets[1]), (sets[1], sets[2]), (sets[0], sets[2]),
             (sets[3], sets[1]), (sets[4], sets[5]), (sets[6], sets[2])]
    for sa, sb in pairs:
        for ma in MAKERS:
            for mb in MAKERS:
                a, b = ma(sa), mb(sb)
                if op == "and":
                    got, want = a.and_(b), sa & sb
                elif op == "or":
                    got, want = a.or_(b), sa | sb
                elif op == "xor":
                    got, want = a.xor_(b), sa ^ sb
                else:
                    got, want = a.andnot(b), sa - sb
                assert set(got.to_array().tolist()) == want, (op, ma.__name__, mb.__name__)
                assert got.cardinality == len(want)


def test_and_cardinality_and_intersects():
    rng = np.random.default_rng(11)
    sets = sample_sets(rng)
    for sa in sets[:3]:
        for sb in sets[:3]:
            for ma in MAKERS:
                for mb in MAKERS:
                    a, b = ma(sa), mb(sb)
                    assert a.and_cardinality(b) == len(sa & sb)
                    assert a.intersects(b) == bool(sa & sb)


def test_add_remove_promotion():
    c: Container = ArrayContainer()
    for x in range(ARRAY_MAX_SIZE + 1):
        c = c.add(2 * x)
    assert isinstance(c, BitmapContainer)  # promoted past 4096 (ArrayContainer.java:158)
    assert c.cardinality == ARRAY_MAX_SIZE + 1
    c = c.remove(0)
    assert isinstance(c, ArrayContainer)  # demoted at <= 4096
    assert c.cardinality == ARRAY_MAX_SIZE
    # idempotent add/remove
    c2 = c.add(2)
    assert c2.cardinality == ARRAY_MAX_SIZE


def test_rank_select_roundtrip():
    rng = np.random.default_rng(12)
    for maker in MAKERS:
        values = sorted(rng.choice(1 << 16, size=700, replace=False).tolist())
        c = maker(set(values))
        for j in [0, 1, 350, 699]:
            assert c.select(j) == values[j]
            assert c.rank(values[j]) == j + 1
        assert c.first() == values[0]
        assert c.last() == values[-1]
        # rank of value below the minimum
        if values[0] > 0:
            assert c.rank(values[0] - 1) == 0


def test_next_previous_value():
    vals = {10, 11, 12, 100, 200, 65535}
    for maker in MAKERS:
        c = maker(vals)
        assert c.next_value(0) == 10
        assert c.next_value(10) == 10
        assert c.next_value(13) == 100
        assert c.next_value(65535) == 65535
        assert c.previous_value(65535) == 65535
        assert c.previous_value(99) == 12
        assert c.previous_value(9) == -1
        assert make_array(set()).next_value(0) == -1


def test_next_previous_absent_value():
    vals = set(range(10, 20)) | {30}
    for maker in MAKERS:
        c = maker(vals)
        assert c.next_absent_value(10) == 20
        assert c.next_absent_value(5) == 5
        assert c.previous_absent_value(19) == 9
        assert c.previous_absent_value(25) == 25


def test_range_ops():
    for maker in MAKERS:
        c = maker({1, 5, 100})
        c2 = c.add_range(10, 20)
        assert set(c2.to_array().tolist()) == {1, 5, 100} | set(range(10, 20))
        c3 = c2.remove_range(0, 6)
        assert set(c3.to_array().tolist()) == {100} | set(range(10, 20))
        c4 = c3.flip_range(15, 25)
        assert set(c4.to_array().tolist()) == {100} | set(range(10, 15)) | set(range(20, 25))
        assert c2.contains_range(10, 20)
        assert not c2.contains_range(10, 21)
        assert c2.intersects_range(19, 30)
        assert not c2.intersects_range(20, 100)


def test_run_optimize_thresholds():
    # long run -> run container wins
    c = make_bitmap(set(range(0, 30000)))
    opt = c.run_optimize()
    assert isinstance(opt, RunContainer)
    assert opt.num_runs() == 1
    # scattered values -> stays array
    rng = np.random.default_rng(13)
    scattered = set(rng.choice(1 << 16, size=1000, replace=False).tolist())
    opt2 = make_array(scattered).run_optimize()
    assert isinstance(opt2, ArrayContainer) or opt2.num_runs() * 4 + 2 < 2 + 2 * 1000
    # dense random -> stays bitmap
    dense = set(rng.choice(1 << 16, size=30000, replace=False).tolist())
    opt3 = make_bitmap(dense).run_optimize()
    assert isinstance(opt3, BitmapContainer)


def test_range_of_ones():
    c = container_range_of_ones(5, 7)  # 2 values -> array (Container.java:29-37)
    assert isinstance(c, ArrayContainer)
    c2 = container_range_of_ones(5, 9)
    assert isinstance(c2, RunContainer)
    assert set(c2.to_array().tolist()) == {5, 6, 7, 8}
    full = container_range_of_ones(0, 1 << 16)
    assert full.cardinality == 1 << 16
    assert full.is_full()


def test_contains_container():
    big = make_bitmap(set(range(0, 10000)))
    small = make_run(set(range(100, 200)))
    assert big.contains_container(small)
    assert not small.contains_container(big)
    assert big.contains_container(make_array(set()))


def test_equality_across_types():
    vals = set(range(50, 150))
    assert make_array(vals) == make_bitmap(vals) == make_run(vals)
    assert make_array(vals) != make_array(vals | {1})


def test_best_container_of_words():
    few = bits.words_from_values(np.arange(10, dtype=np.uint16))
    assert isinstance(best_container_of_words(few), ArrayContainer)
    many = bits.words_from_values(np.arange(5000, dtype=np.uint16))
    assert isinstance(best_container_of_words(many), BitmapContainer)


def test_contains_many_all_types(rng):
    from roaringbitmap_tpu.models.container import container_from_values

    probe = rng.integers(0, 1 << 16, size=2000).astype(np.uint16)
    for make in MAKERS:
        vals = set(rng.choice(1 << 16, size=800, replace=False).tolist())
        c = make(vals)
        got = c.contains_many(probe)
        assert got.tolist() == [int(p) in vals for p in probe.tolist()], make.__name__
    # run container with adjacent runs
    run = make_run(set(range(100, 500)) | set(range(60000, 60100)))
    got = run.contains_many(np.array([99, 100, 499, 500, 60099, 60100], dtype=np.uint16))
    assert got.tolist() == [False, True, True, False, True, False]


def test_absent_value_overrides_match_base():
    """Bitmap word-level and run-space next/previous_absent_value must agree
    with the generic to_array()-based recurrence (perf overrides added after
    the micro suite showed a 100us full unpack per call)."""
    import numpy as np

    from roaringbitmap_tpu.models.container import (
        ArrayContainer,
        BitmapContainer,
        Container,
        RunContainer,
        container_from_values,
    )

    rng = np.random.default_rng(77)
    cases = []
    dense = np.sort(rng.choice(1 << 16, size=30_000, replace=False)).astype(np.uint16)
    cases.append(container_from_values(dense))
    runs = np.concatenate(
        [np.arange(s, s + 200) for s in range(100, 60_000, 1_500)]
    ).astype(np.uint16)
    cases.append(container_from_values(runs).run_optimize())
    cases.append(container_from_values(np.arange(0, 500, dtype=np.uint16)).run_optimize())
    full = container_from_values(np.arange(1 << 16, dtype=np.uint16)).run_optimize()
    cases.append(full)
    for c in cases:
        arr = c.to_array()
        probes = {0, 1, 63, 64, 65, 12_345, 65_534, 65_535}
        probes.update(int(v) for v in arr[:: max(1, arr.size // 50)])
        probes.update(min(65_535, int(v) + 1) for v in arr[:: max(1, arr.size // 50)])
        for p in sorted(probes):
            want_next = Container.next_absent_value(c, p)
            want_prev = Container.previous_absent_value(c, p)
            assert c.next_absent_value(p) == want_next, (type(c).__name__, p)
            assert c.previous_absent_value(p) == want_prev, (type(c).__name__, p)


def test_full_container_op_type_matrix():
    """All 9 operand-type combinations x and/or/xor/andNot/andCardinality,
    each checked against a numpy set oracle — the one-sweep analogue of the
    reference's per-type suites (TestArrayContainer/TestBitmapContainer/
    TestRunContainer op matrices)."""
    import numpy as np

    from roaringbitmap_tpu.models.container import container_from_values

    seeds = {("array", 1): 11, ("array", 2): 12, ("bitmap", 1): 21,
             ("bitmap", 2): 22, ("run", 1): 31, ("run", 2): 32}

    def mk(kind, seed):
        r = np.random.default_rng(seed)
        if kind == "array":
            vals = np.sort(r.choice(1 << 16, size=3000, replace=False))
        elif kind == "bitmap":
            vals = np.sort(r.choice(1 << 16, size=20_000, replace=False))
        else:  # run
            starts = np.sort(r.choice(600, size=40, replace=False)) * 100
            vals = np.unique(
                np.concatenate([np.arange(s, s + 80) for s in starts])
            )
        c = container_from_values(vals.astype(np.uint16))
        if kind == "run":
            c = c.run_optimize()
        return c, set(vals.tolist())

    kinds = ("array", "bitmap", "run")
    for ka in kinds:
        for kb in kinds:
            a, sa = mk(ka, seeds[(ka, 1)])
            b, sb = mk(kb, seeds[(kb, 2)])
            cases = {
                "and": (a.and_(b), sa & sb),
                "or": (a.or_(b), sa | sb),
                "xor": (a.xor_(b), sa ^ sb),
                "andnot": (a.andnot(b), sa - sb),
            }
            for name, (got, want) in cases.items():
                assert set(got.to_array().tolist()) == want, (ka, kb, name)
                assert got.cardinality == len(want), (ka, kb, name)
            assert a.and_cardinality(b) == len(sa & sb), (ka, kb)
            assert a.intersects(b) == bool(sa & sb), (ka, kb)
            # operands unchanged (value semantics)
            assert set(a.to_array().tolist()) == sa, (ka, kb)
            assert set(b.to_array().tolist()) == sb, (ka, kb)


def test_container_range_ops_matrix():
    """add/remove/flip range across all three container kinds vs a numpy
    oracle, including promotions/demotions at the 4096 boundary."""
    import numpy as np

    from roaringbitmap_tpu.models.container import container_from_values

    def mk(kind):
        if kind == "array":
            vals = np.arange(0, 3000, 7, dtype=np.uint16)
        elif kind == "bitmap":
            vals = np.arange(0, 50000, 3, dtype=np.uint16)
        else:
            vals = np.concatenate(
                [np.arange(s, s + 500, dtype=np.uint16) for s in range(0, 60000, 4000)]
            )
        c = container_from_values(vals)
        if kind == "run":
            c = c.run_optimize()
        return c, set(int(v) for v in vals)

    ranges = [(0, 1), (100, 5000), (4000, 4100), (0, 65536), (65000, 65536)]
    for kind in ("array", "bitmap", "run"):
        for start, end in ranges:
            c, s = mk(kind)
            rng_set = set(range(start, end))
            got = c.add_range(start, end)
            assert set(got.to_array().tolist()) == s | rng_set, (kind, start, end, "add")
            got = c.remove_range(start, end)
            assert set(got.to_array().tolist()) == s - rng_set, (kind, start, end, "rm")
            got = c.flip_range(start, end)
            assert set(got.to_array().tolist()) == s ^ rng_set, (kind, start, end, "flip")
            assert set(c.to_array().tolist()) == s  # value semantics
