"""Cross-query fusion (ISSUE 13): the micro-batching executor
(query/fusion.py), the global in-flight dedup table (query/inflight.py)
with its validated-publication contract, the fusion-batch pricing
authority behind the cost facade, the fusion-queue-stall sentinel rule,
and the rb_top/sidecar fusion panels."""

import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import Q, RoaringBitmap, cost, insights, observe
from roaringbitmap_tpu.cost import fusion as fusion_cost
from roaringbitmap_tpu.observe import health, outcomes as rb_outcomes
from roaringbitmap_tpu.query import (
    FusionExecutor,
    ResultCache,
    evaluate_naive,
    execute,
    execute_fused,
    fusion,
    inflight,
)
from roaringbitmap_tpu.query import exec as query_exec
from roaringbitmap_tpu.robust import faults, ladder


def _bm(rng, n=2000, space=1 << 18):
    return RoaringBitmap(
        np.sort(rng.choice(space, n, replace=False)).astype(np.uint32)
    )


@pytest.fixture(autouse=True)
def _clean():
    # NOTE: no faults.clear() here — the ci.sh chaos gate runs this file
    # under the env-installed RB_TPU_FAULTS schedule, which a teardown
    # clear() would silently strip for the rest of the session; scoped
    # inject() contexts clean up after themselves
    ladder.LADDER.reset()
    inflight.TABLE.clear()
    yield
    ladder.LADDER.reset()
    inflight.TABLE.clear()
    fusion.configure(enabled=True)


def _overlapping_queries(rng, bms, n=6):
    """Shared hot AND under an OR (survives the flatten rewrite) plus
    per-query unique structure — the serving-shaped workload."""
    hot = Q.leaf(bms[0]) & Q.leaf(bms[1])
    qs = []
    for i in range(n):
        a = Q.leaf(bms[2 + i % (len(bms) - 2)])
        b = Q.leaf(bms[2 + (i + 1) % (len(bms) - 2)])
        qs.append((hot | a) - b if i % 2 else hot | (a & b))
    return qs


# ---------------------------------------------------------------------------
# fused == serial == naive (the tentpole's correctness contract)
# ---------------------------------------------------------------------------


def test_fused_matches_serial_and_naive():
    rng = np.random.default_rng(7)
    bms = [_bm(rng) for _ in range(6)]
    qs = _overlapping_queries(rng, bms)
    serial = [execute(q, cache=None) for q in qs]
    fused = execute_fused(qs, cache=ResultCache(max_entries=64))
    naive = [evaluate_naive(q) for q in qs]
    for s, f, nv in zip(serial, fused, naive):
        assert f == s == nv


def test_fused_covers_threshold_and_andnot_kernels():
    rng = np.random.default_rng(11)
    bms = [_bm(rng, n=4000) for _ in range(5)]
    leaves = [Q.leaf(b) for b in bms]
    qs = [
        Q.threshold(2, *leaves[:4]),
        Q.threshold(3, *leaves[1:]),
        Q.andnot(leaves[0], *leaves[2:4]),
        Q.andnot(leaves[1], *leaves[3:]),
        Q.or_(leaves[0], leaves[2], leaves[4]),
        Q.xor(leaves[1], leaves[2], leaves[3]),
    ]
    fused = execute_fused(qs, cache=None)
    for q, f in zip(qs, fused):
        assert f == evaluate_naive(q)


def test_fused_device_mode_matches_serial():
    """mode="device" plans device-routed engines; the merged device
    tiers (concatenated pair rows, fused andnot mask, concatenated
    threshold blocks) must stay bit-exact on the jax-CPU backend."""
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(13)
    bms = [_bm(rng, n=6000, space=1 << 20) for _ in range(5)]
    leaves = [Q.leaf(b) for b in bms]
    hot = leaves[0] & leaves[1]
    qs = [
        hot | leaves[2],
        hot | leaves[3],
        Q.andnot(leaves[0], leaves[2], leaves[3]),
        Q.andnot(leaves[1], leaves[3], leaves[4]),
        Q.threshold(2, *leaves[:4]),
        Q.threshold(2, leaves[1], leaves[2], leaves[3], leaves[4]),
    ]
    store.PACK_CACHE.close()
    try:
        serial = [execute(q, cache=None, mode="device") for q in qs]
        fused = execute_fused(qs, cache=None, mode="device")
        for s, f in zip(serial, fused):
            assert f == s
    finally:
        store.PACK_CACHE.close()


def test_fused_dedups_shared_subexpression_across_queries():
    rng = np.random.default_rng(17)
    bms = [_bm(rng) for _ in range(6)]
    qs = _overlapping_queries(rng, bms)
    before = {
        tuple(s["labels"].values()): s["value"]
        for s in observe.REGISTRY.snapshot()[observe.FUSION_STEPS_TOTAL][
            "samples"
        ]
    } if observe.REGISTRY.get(observe.FUSION_STEPS_TOTAL) else {}
    execute_fused(qs, cache=None)
    snap = observe.REGISTRY.snapshot()[observe.FUSION_STEPS_TOTAL]["samples"]
    after = {tuple(s["labels"].values()): s["value"] for s in snap}
    deduped = after.get(("deduped",), 0) - before.get(("deduped",), 0)
    assert deduped > 0, "shared hot AND was not deduped across the window"


def test_fusion_off_mode_is_plain_serial():
    rng = np.random.default_rng(19)
    bms = [_bm(rng, n=500) for _ in range(4)]
    qs = _overlapping_queries(rng, bms, n=3)
    fusion.configure(enabled=False)
    b = observe.REGISTRY.get(observe.FUSION_BATCH_TOTAL)
    before = sum(v for _lv, v in b.series().items()) if b else 0
    out = execute_fused(qs, cache=None)
    after = sum(v for _lv, v in b.series().items()) if b else 0
    assert after == before, "off mode must not drain windows"
    for q, o in zip(qs, out):
        assert o == evaluate_naive(q)


@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_pairwise_multi_device_tier_matches_solo(op):
    """The fused device pairwise tier: many pairs (with a SHARED operand,
    so the combined block dedups) through one concatenated
    pair_rows_reduce launch, bit-exact vs solo per-pair execution."""
    from roaringbitmap_tpu import columnar
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(47)
    bms = [_bm(rng, n=5000, space=1 << 20) for _ in range(4)]
    for b in bms:
        b.run_optimize()
    pairs = [
        (bms[0], bms[1]), (bms[0], bms[2]),  # shared left operand
        (bms[2], bms[3]), (bms[1], bms[3]),
    ]
    store.PACK_CACHE.close()
    try:
        # suspended: this is a unit parity test of the merged kernels
        # called directly (no ladder above them); chaos coverage of the
        # fused device paths rides the ladder-protected execute_fused
        # tests + fuzz family 27
        with faults.suspended():
            fused = columnar.pairwise_multi(op, pairs, tier="device")
            solo = [
                columnar.pairwise(op, a, b, tier="device") for a, b in pairs
            ]
            with columnar.disabled():
                want = [
                    getattr(RoaringBitmap, {"and": "and_", "or": "or_",
                                            "xor": "xor", "andnot": "andnot"}[op])(a, b)
                    for a, b in pairs
                ]
        for f, s, w in zip(fused, solo, want):
            assert f == s == w
    finally:
        store.PACK_CACHE.close()


def test_fold_multi_matches_per_set_folds():
    from roaringbitmap_tpu.columnar import engine as col_engine
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(53)
    sets = [
        [_bm(rng, n=3000) for _ in range(3)],
        [_bm(rng, n=1000) for _ in range(4)],
        [_bm(rng, n=200, space=1 << 16) for _ in range(2)],
    ]
    for op in ("or", "xor"):
        groups_list = [store.group_by_key(bms) for bms in sets]
        fused = col_engine.fold_multi(groups_list, op)
        want = [
            col_engine.fold(store.group_by_key(bms), op) for bms in sets
        ]
        for f, w in zip(fused, want):
            assert f == w
    with pytest.raises(ValueError):
        col_engine.fold_multi([], "and")


# ---------------------------------------------------------------------------
# faults + ladder: a failed fused batch degrades to per-query, bit-exact
# ---------------------------------------------------------------------------


def test_fused_batch_degrades_to_serial_under_fault():
    rng = np.random.default_rng(23)
    bms = [_bm(rng) for _ in range(5)]
    qs = _overlapping_queries(rng, bms, n=4)
    want = [execute(q, cache=None) for q in qs]
    with faults.inject("query.fusion", every=1):
        got = execute_fused(qs, cache=None)
    for g, w in zip(got, want):
        assert g == w
    snap = observe.REGISTRY.snapshot()[observe.FUSION_BATCH_TOTAL]["samples"]
    by = {tuple(s["labels"].values()): s["value"] for s in snap}
    assert by.get(("degraded",), 0) > 0, "fault did not ride the batch ladder"


def test_fuzz_family_27_pinned_seed():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_fusion_invariance("pinned", iterations=25, seed=57)


# ---------------------------------------------------------------------------
# in-flight dedup table (tentpole leg 1) + the cross-query key fix
# ---------------------------------------------------------------------------


def test_inflight_second_thread_joins_first():
    rng = np.random.default_rng(29)
    bms = [_bm(rng) for _ in range(3)]
    q = (Q.leaf(bms[0]) & Q.leaf(bms[1])) | Q.leaf(bms[2])
    cache = ResultCache(max_entries=32)
    gate = threading.Event()
    entered = threading.Event()
    orig = query_exec._run_step

    def slow_step(step, inputs, force_cpu=False):
        entered.set()
        gate.wait(10.0)
        return orig(step, inputs, force_cpu=force_cpu)

    stats0 = inflight.TABLE.stats()
    results = {}

    def runner(tag):
        results[tag] = execute(q, cache=cache)

    query_exec._run_step = slow_step
    try:
        t1 = threading.Thread(target=runner, args=("a",))
        t1.start()
        assert entered.wait(10.0)
        query_exec._run_step = orig  # joiner must not need the gate
        t2 = threading.Thread(target=runner, args=("b",))
        t2.start()
        time.sleep(0.05)  # let the joiner reach the pending entry
        gate.set()
        t1.join(10.0)
        t2.join(10.0)
    finally:
        query_exec._run_step = orig
        gate.set()
    assert results["a"] == results["b"] == evaluate_naive(q)
    stats1 = inflight.TABLE.stats()
    assert stats1["joins"] > stats0["joins"], "second thread never joined"


def test_joiner_never_observes_stale_bits_on_midflight_mutation():
    """ISSUE 13 satellite regression: mutate a leaf while an identical
    query is in flight — the owner's completion fails fingerprint
    validation, the joiner recomputes against fresh contents, and the
    stale value never reaches the shared cache."""
    rng = np.random.default_rng(31)
    a, b = _bm(rng, n=800), _bm(rng, n=800)
    q = Q.leaf(a) & Q.leaf(b)
    cache = ResultCache(max_entries=32)
    gate = threading.Event()
    entered = threading.Event()
    orig = query_exec._run_step

    def slow_step(step, inputs, force_cpu=False):
        val = orig(step, inputs, force_cpu=force_cpu)
        entered.set()
        gate.wait(10.0)  # hold the computed-but-unpublished window open
        return val

    query_exec._run_step = slow_step
    out = {}
    try:
        t1 = threading.Thread(target=lambda: out.setdefault("a", execute(q, cache=cache)))
        t1.start()
        assert entered.wait(10.0)
        # mutate the leaf while the identical query is in flight
        added = int(a.to_array()[0]) + 1_000_003
        a.add(added)
        query_exec._run_step = orig
        gate.set()
        t1.join(10.0)
        got = execute(q, cache=cache)  # post-mutation: fresh fingerprints
    finally:
        query_exec._run_step = orig
        gate.set()
    want = evaluate_naive(Q.leaf(a) & Q.leaf(b))
    assert got == want, "post-mutation execution observed stale bits"
    assert inflight.TABLE.stats()["stale"] >= 1, (
        "mid-flight mutation did not trip the validated-publication path"
    )


def test_inflight_poll_never_blocks():
    """The fused path's non-blocking form: a still-computing foreign
    entry polls None immediately (a claim-holding executor must never
    block on another executor's unpublished claim)."""
    t = inflight.InflightTable(join_timeout_s=60.0)
    owner, entry = t.begin(("k",))
    assert owner
    _o2, e2 = t.begin(("k",))
    t0 = time.perf_counter()
    assert t.poll(e2) is None  # still computing: no wait
    assert time.perf_counter() - t0 < 1.0
    t.complete(("k",), entry, "v", valid=True)
    assert t.poll(e2) == "v"
    owner, entry = t.begin(("k2",))
    t.complete(("k2",), entry, "stale", valid=False)
    assert t.poll(entry) is None  # stale publication never shared


def test_queue_depth_gauge_aggregates_across_executors():
    """Two live executors fold into ONE gauge value: a healthy
    executor's drains must not overwrite a stalled executor's parked
    depth (the fusion-queue-stall rule's whole signal)."""
    from roaringbitmap_tpu.query.fusion import _publish_depth

    g = observe.REGISTRY.get(observe.FUSION_QUEUED_COUNT)
    _publish_depth(101, 40)  # stalled executor, 40 parked
    _publish_depth(202, 0)   # healthy executor drained
    assert g.series()[()] == 40
    _publish_depth(202, 3)
    assert g.series()[()] == 43
    _publish_depth(101, None)  # stalled executor closed
    assert g.series()[()] == 3
    _publish_depth(202, None)
    assert g.series()[()] == 0


def test_inflight_owner_failure_wakes_joiners_to_recompute():
    t = inflight.InflightTable(join_timeout_s=5.0)
    owner, entry = t.begin(("k",))
    assert owner
    joined = {}

    def join():
        _o, e = t.begin(("k",))
        joined["val"] = t.join(e)

    th = threading.Thread(target=join)
    th.start()
    time.sleep(0.05)
    t.abort(("k",), entry)
    th.join(5.0)
    assert joined["val"] is None  # recompute, never inherit the exception
    assert t.pending_count() == 0


# ---------------------------------------------------------------------------
# the fusion.batch pricing authority (cost facade protocol)
# ---------------------------------------------------------------------------


def test_fusion_batch_site_joins_outcomes_and_prices_engines():
    rb_outcomes.reset()
    rng = np.random.default_rng(37)
    bms = [_bm(rng) for _ in range(5)]
    try:
        execute_fused(_overlapping_queries(rng, bms, n=4), cache=None)
        joins = [e for e in rb_outcomes.tail() if e["site"] == "fusion.batch"]
        assert joins, "fused window joined no fusion.batch outcome"
        e = joins[-1]
        assert e["engine"] in ("fused", "per-query")
        assert e["predicted_us"] is not None and e["error_ratio"] is not None
        assert set(e["inputs"]["est_us"]) == {"fused", "per-query"}
    finally:
        rb_outcomes.reset()


def test_fusion_cost_model_refits_from_samples_and_roundtrips():
    m = fusion_cost.FusionBatchModel()
    est0 = m.estimate(10, 3)
    assert est0["fused"] < est0["per-query"]  # the structural prior
    samples = [
        {"site": "fusion.batch", "engine": "fused",
         "predicted_us": 1000.0, "measured_s": 4000e-6},
        {"site": "fusion.batch", "engine": "fused",
         "predicted_us": 1000.0, "measured_s": 4000e-6},
    ]
    rep = m.refit_from_outcomes(samples=samples)
    assert rep["provenance"] == "refit-from-traffic"
    assert m.coeffs["tier_us"] == pytest.approx(
        fusion_cost.DEFAULT_COEFFS["tier_us"] * 4.0
    )
    d = m.to_dict()
    m2 = fusion_cost.FusionBatchModel()
    assert m2.from_dict(d)
    assert m2.coeffs == m.coeffs and m2.provenance == "refit-from-traffic"
    assert not m2.from_dict({"schema": "nope"})
    m2.reset()
    assert m2.provenance == "default"


def test_cost_facade_exposes_fusion_authority():
    assert "fusion-batch" in cost.names()
    auth = cost.authority("fusion-batch")
    assert "coeffs" in auth.curves()
    state = cost.calibration_state()
    assert "fusion-batch" in state["authorities"]
    reports = cost.refit_all()
    assert "fusion-batch" in reports


def test_fusion_state_rides_unified_persistence(tmp_path):
    path = str(tmp_path / "cost_state.json")
    try:
        with fusion_cost.MODEL._lock:
            fusion_cost.MODEL.coeffs["solo_step_us"] = 333.0
            fusion_cost.MODEL.provenance = "refit-from-traffic"
        assert cost.save_state(path) == path
        fusion_cost.MODEL.reset()
        verdicts = cost.load_state(path)
        assert verdicts["fusion-batch"]
        assert fusion_cost.MODEL.coeffs["solo_step_us"] == 333.0
        assert fusion_cost.MODEL.provenance == "refit-from-traffic"
    finally:
        fusion_cost.MODEL.reset()


# ---------------------------------------------------------------------------
# the serving window (FusionExecutor)
# ---------------------------------------------------------------------------


def test_executor_coalesces_and_respects_latency_bound():
    rng = np.random.default_rng(41)
    bms = [_bm(rng, n=500) for _ in range(5)]
    qs = _overlapping_queries(rng, bms, n=5)
    want = [evaluate_naive(q) for q in qs]
    with FusionExecutor(window=8, max_wait_ms=30.0, cache=None) as ex:
        outs = ex.map(qs)
        assert ex.batches >= 1
    for o, w in zip(outs, want):
        assert o == w


def test_executor_propagates_fatal_errors_to_futures():
    with FusionExecutor(window=2, max_wait_ms=5.0, cache=None) as ex:
        fut = ex.submit("not a query")  # type: ignore[arg-type]
        with pytest.raises(Exception):
            fut.result(timeout=10.0)


# ---------------------------------------------------------------------------
# sentinel rule: fusion-queue-stall
# ---------------------------------------------------------------------------


def test_fusion_queue_stall_rule_fires_on_stalled_depth():
    rule = next(r for r in health.DEFAULT_RULES if r.name == "fusion-queue-stall")
    assert rule.actuation == "alert"

    def snap(depth, batches, prev):
        metrics = {
            observe.FUSION_QUEUED_COUNT: {
                "samples": [{"labels": {}, "value": depth}]
            },
            observe.FUSION_BATCH_TOTAL: {
                "samples": [{"labels": {"outcome": "fused"}, "value": batches}]
            },
        }
        return health.Snapshot(
            metrics=metrics, breaker_open_ages={}, drift={},
            outcome_sites={}, now=0.0, prev_sums=prev,
        )

    st = health.RuleState()
    # tick 1 establishes the counter baseline; depth parked, no drains
    s1 = snap(depth=4, batches=10, prev=None)
    st.step(rule, rule.probe(s1), 1)
    # ticks 2-3: still no drained batch -> fires after the 2-tick hysteresis
    s2 = snap(depth=4, batches=10, prev=dict(s1.sums))
    st.step(rule, rule.probe(s2), 2)
    s3 = snap(depth=4, batches=10, prev=dict(s2.sums))
    ev = st.step(rule, rule.probe(s3), 3)
    assert ev["level"] == health.WARN
    # a draining queue is healthy backpressure: clears after clear_after
    s4 = snap(depth=4, batches=12, prev=dict(s3.sums))
    assert rule.probe(s4) == 0.0


# ---------------------------------------------------------------------------
# panels: sidecar fusion block + rb_top + insights
# ---------------------------------------------------------------------------


def test_sidecar_and_insights_fusion_block():
    rng = np.random.default_rng(43)
    bms = [_bm(rng, n=500) for _ in range(5)]
    execute_fused(_overlapping_queries(rng, bms, n=4), cache=None)
    side = observe.sidecar_snapshot()
    fu = side["fusion"]
    assert {"batches", "queries", "steps", "occupancy", "dedup_hit_ratio",
            "inflight", "queue_depth"} <= set(fu)
    assert sum(fu["batches"].values()) > 0
    live = insights.fusion_counters()
    assert live["queries"] >= 4
    assert "inflight_live" in live


def test_rb_top_report_carries_fusion_panel():
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        rb_top = importlib.import_module("rb_top")
    finally:
        sys.path.pop(0)
    r = rb_top.report(tail=4)
    assert r["schema"] == "rb_tpu_top/10"
    assert "fusion" in r
    assert "window_state" in r["fusion"]  # latency panel data (ISSUE 19)
    rendered = rb_top._render_console(r)
    assert "fusion (cross-query micro-batching)" in rendered
    assert "latency classes (SLO budgets & hedging)" in rendered


# ---------------------------------------------------------------------------
# tail-latency engineering (ISSUE 19): deadline-aware close, the priced
# hedge verdict, hedged solo dispatch, and window auto-tuning
# ---------------------------------------------------------------------------


def test_window_close_at_honours_tightest_member_slack():
    # pure fake-clock arithmetic: the close bound is the straggler bound
    # pulled earlier by every member deadline, never later
    assert fusion.window_close_at(100.0, 0.002, []) == 100.002
    assert fusion.window_close_at(
        100.0, 0.002, [None, 100.0005, 100.01]
    ) == 100.0005
    # an already-expired member deadline closes the window immediately
    assert fusion.window_close_at(100.0, 0.002, [99.9]) == 99.9
    # slack looser than the straggler bound never extends the hold
    assert fusion.window_close_at(100.0, 0.002, [200.0]) == 100.002


def test_window_never_holds_request_past_slack():
    """A batch-class request (never hedges) with a tight declared slack
    must be released by the deadline-aware close, even under a
    pathological straggler bound."""
    rng = np.random.default_rng(41)
    bms = [_bm(rng) for _ in range(4)]
    q = Q.leaf(bms[0]) & Q.leaf(bms[1])
    ex = FusionExecutor(max_wait_ms=5000.0)
    try:
        t0 = time.perf_counter()
        out = ex.submit(q, slack_ms=50.0, latency_class="batch").result(
            timeout=10
        )
        wall = time.perf_counter() - t0
    finally:
        ex.close()
    assert out == evaluate_naive(q)
    assert wall < 2.5, (
        f"deadline-aware close held a 50ms-slack request {wall:.3f}s "
        f"against a 5s straggler bound"
    )


def test_hedged_solo_dispatch_bypasses_window():
    """An interactive request whose slack the forming window would blow
    dispatches solo in the caller thread: no drained batch, the hedge
    counter moves, and the result stays bit-exact."""
    rng = np.random.default_rng(43)
    bms = [_bm(rng) for _ in range(4)]
    q = (Q.leaf(bms[0]) & Q.leaf(bms[1])) | Q.leaf(bms[2])
    ex = FusionExecutor(max_wait_ms=2000.0)
    try:
        out = ex.submit(
            q, slack_ms=1.0, latency_class="interactive"
        ).result(timeout=10)
    finally:
        ex.close()
    assert out == evaluate_naive(q)
    assert ex.hedges == 1
    assert ex.batches == 0, "hedged request still drained through a window"
    snap = observe.REGISTRY.snapshot()[observe.FUSION_HEDGE_TOTAL]["samples"]
    by = {tuple(s["labels"].values()): s["value"] for s in snap}
    assert by.get(("solo",), 0) >= 1


def test_hedge_verdict_records_joint_priced_decision():
    """Both verdict paths record at the ``fusion.hedge`` site with the
    RAW per-path completion estimates, and a solo dispatch resolves the
    join so the authority can refit its per-query curve from hedged
    traffic."""
    rb_outcomes.reset()
    rng = np.random.default_rng(47)
    bms = [_bm(rng) for _ in range(4)]
    q = Q.leaf(bms[0]) & Q.leaf(bms[1])
    ex = FusionExecutor(max_wait_ms=100.0)
    try:
        ex.submit(q, slack_ms=1.0, latency_class="interactive").result(
            timeout=10
        )
        ex.submit(q, slack_ms=5000.0, latency_class="batch").result(
            timeout=10
        )
    finally:
        ex.close()
    joined = [s for s in rb_outcomes.tail() if s.get("site") == "fusion.hedge"]
    engines = {s.get("engine") for s in joined}
    assert "solo" in engines, "hedged solo dispatch never joined its outcome"
    assert "window" in engines, "window verdict never joined its outcome"
    for s in joined:
        assert s.get("predicted_us", 0) > 0


def test_hedge_refit_scales_per_query_curve_from_solo_joins_only():
    """``fusion.hedge`` samples refit the per-query curve from SOLO
    dispatches only — window-verdict joins are queue-wait dominated
    (policy, not curve) and must not move any coefficient."""
    m = fusion_cost.FusionBatchModel()
    base_solo = m.coeffs["solo_step_us"]
    base_tier = m.coeffs["tier_us"]
    solo_samples = [
        {"site": "fusion.hedge", "engine": "solo",
         "predicted_us": 240.0, "measured_s": 960.0 / 1e6}
        for _ in range(4)
    ]
    rep = m.refit_from_outcomes(samples=solo_samples)
    assert "solo_step_us" in rep["moved"]
    assert m.coeffs["solo_step_us"] == pytest.approx(base_solo * 4.0)
    assert m.coeffs["tier_us"] == base_tier
    m2 = fusion_cost.FusionBatchModel()
    window_samples = [
        {"site": "fusion.hedge", "engine": "window",
         "predicted_us": 100.0, "measured_s": 0.5}
        for _ in range(4)
    ]
    rep2 = m2.refit_from_outcomes(samples=window_samples)
    assert rep2["moved"] == {}, "window-verdict joins moved the curves"


def test_hedge_fault_degrades_to_window_bit_exactly():
    """The ``query.hedge`` ladder: a fault on the solo rung falls back
    to the window rung — the latency hedge is lost, the answer is not."""
    rng = np.random.default_rng(53)
    bms = [_bm(rng) for _ in range(4)]
    q = (Q.leaf(bms[0]) & Q.leaf(bms[1])) | Q.leaf(bms[2])
    ex = FusionExecutor(max_wait_ms=20.0)
    try:
        with faults.inject("query.hedge", every=1):
            out = ex.submit(
                q, slack_ms=1.0, latency_class="interactive"
            ).result(timeout=10)
        assert ex.hedges == 1
        assert ex.batches >= 1, "fallback never drained through the window"
    finally:
        ex.close()
    assert out == evaluate_naive(q)


def test_hedged_solo_joins_pending_fused_subexpression():
    """ISSUE 19's dedup guarantee: a hedged solo request whose
    expression is already computing inside a fused window JOINS that
    pending in-flight entry instead of recomputing."""
    rng = np.random.default_rng(59)
    bms = [_bm(rng) for _ in range(3)]
    q = Q.leaf(bms[0]) & Q.leaf(bms[1])
    cache = ResultCache(max_entries=32)
    gate = threading.Event()
    entered = threading.Event()
    orig = query_exec._run_step

    def slow_step(step, inputs, force_cpu=False):
        entered.set()
        gate.wait(10.0)
        return orig(step, inputs, force_cpu=force_cpu)

    stats0 = inflight.TABLE.stats()
    out = {}
    query_exec._run_step = slow_step
    try:
        t1 = threading.Thread(
            target=lambda: out.setdefault(
                "fused", execute_fused([q], cache=cache)[0]
            )
        )
        t1.start()
        assert entered.wait(10.0), "fused window never claimed the step"
        query_exec._run_step = orig  # the joiner must not need the gate
        ex = FusionExecutor(cache=cache, max_wait_ms=2000.0)
        try:
            fut = ex.submit(q, slack_ms=1.0, latency_class="interactive")
            time.sleep(0.05)  # let the solo path reach the pending entry
            gate.set()
            out["hedged"] = fut.result(timeout=10)
            assert ex.hedges == 1
        finally:
            ex.close()
        t1.join(10.0)
    finally:
        query_exec._run_step = orig
        gate.set()
    assert out["fused"] == out["hedged"] == evaluate_naive(q)
    assert inflight.TABLE.stats()["joins"] > stats0["joins"], (
        "hedged solo request recomputed instead of joining the "
        "window's pending entry"
    )


def test_autotune_window_shrinks_and_regrows_from_curves():
    """The ``serving-p99-pressure`` actuation body: the effective window
    re-derives from the fusion authority's curves against the tightest
    declared interactive budget — shrinking under pressure, regrowing
    to the declared base once the budget fits (or nothing interactive
    is declared)."""
    from roaringbitmap_tpu.serve import slo as serve_slo

    base = fusion.config.window_base
    serve_slo.reset()
    try:
        fusion.configure(window=8, window_min=2)
        # a 0.2 ms budget cannot fit even the fixed per-tier cost
        serve_slo.TENANTS.declare(
            "int-t", latency_class="interactive", p99_budget_ms=0.2
        )
        rec = fusion.autotune_window(reason="test")
        assert rec["verdict"] == "shrink"
        assert fusion.config.window == 2
        assert rec["budget_ms"] == pytest.approx(0.2)
        # a generous budget regrows to (and is clamped at) the base
        serve_slo.TENANTS.declare(
            "int-t", latency_class="interactive", p99_budget_ms=10_000.0
        )
        rec2 = fusion.autotune_window(reason="test")
        assert rec2["verdict"] == "regrow"
        assert fusion.config.window == 8
        # no interactive tenants declared: nothing to protect, hold base
        serve_slo.reset()
        rec3 = fusion.autotune_window(reason="test")
        assert rec3["verdict"] == "hold"
        assert rec3["budget_ms"] is None
        # a live executor constructed WITHOUT an explicit window follows
        # the auto-tuned bound; an explicit window stays pinned
        ex_live = FusionExecutor()
        ex_pinned = FusionExecutor(window=6)
        try:
            serve_slo.TENANTS.declare(
                "int-t", latency_class="interactive", p99_budget_ms=0.2
            )
            fusion.autotune_window(reason="test")
            assert ex_live._target_window() == 2
            assert ex_pinned._target_window() == 6
        finally:
            ex_live.close()
            ex_pinned.close()
    finally:
        serve_slo.reset()
        fusion.configure(window=base)


def test_sentinel_autotune_actuation_rides_pressure_rule():
    """The closed loop end-to-end on a fake clock: a serving-p99-pressure
    breach actuates exactly one window auto-tune per cooldown."""
    from roaringbitmap_tpu.observe import health as health_mod
    from roaringbitmap_tpu.observe import sentinel as sentinel_mod
    from roaringbitmap_tpu.serve import slo as serve_slo

    base = fusion.config.window_base
    serve_slo.reset()
    try:
        fusion.configure(window=8, window_min=2)
        serve_slo.TENANTS.declare(
            "int-t", latency_class="interactive", p99_budget_ms=0.2
        )
        rule = next(
            r for r in health_mod.DEFAULT_RULES
            if r.name == "serving-p99-pressure"
        )
        assert rule.actuation == "autotune"
        dial = {"v": 3.0}
        probe_rule = health_mod.Rule(
            rule.name, rule.help, lambda s: dial["v"],
            warn=rule.warn, critical=rule.critical,
            fire_after=1, clear_after=1, actuation=rule.actuation,
        )
        s = sentinel_mod.Sentinel(
            rules=(probe_rule,), clock=lambda: 0.0, autotune_cooldown_s=30.0
        )
        stub = health.Snapshot(
            metrics={}, breaker_open_ages={}, drift={}, outcome_sites={},
            now=0.0,
        )
        r1 = s.tick(now=0.0, snap=stub)
        kinds = [a["kind"] for a in r1["actuated"]]
        assert "autotune" in kinds
        tuned = next(a for a in r1["actuated"] if a["kind"] == "autotune")
        assert tuned["verdict"] == "shrink"
        assert fusion.config.window == 2
        # cooldown: the still-firing rule must not thrash the window
        r2 = s.tick(now=1.0, snap=stub)
        assert "autotune" not in [a["kind"] for a in r2["actuated"]]
        r3 = s.tick(now=31.0, snap=stub)
        assert "autotune" in [a["kind"] for a in r3["actuated"]]
    finally:
        serve_slo.reset()
        fusion.configure(window=base)
