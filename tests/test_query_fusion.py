"""Cross-query fusion (ISSUE 13): the micro-batching executor
(query/fusion.py), the global in-flight dedup table (query/inflight.py)
with its validated-publication contract, the fusion-batch pricing
authority behind the cost facade, the fusion-queue-stall sentinel rule,
and the rb_top/sidecar fusion panels."""

import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import Q, RoaringBitmap, cost, insights, observe
from roaringbitmap_tpu.cost import fusion as fusion_cost
from roaringbitmap_tpu.observe import health, outcomes as rb_outcomes
from roaringbitmap_tpu.query import (
    FusionExecutor,
    ResultCache,
    evaluate_naive,
    execute,
    execute_fused,
    fusion,
    inflight,
)
from roaringbitmap_tpu.query import exec as query_exec
from roaringbitmap_tpu.robust import faults, ladder


def _bm(rng, n=2000, space=1 << 18):
    return RoaringBitmap(
        np.sort(rng.choice(space, n, replace=False)).astype(np.uint32)
    )


@pytest.fixture(autouse=True)
def _clean():
    # NOTE: no faults.clear() here — the ci.sh chaos gate runs this file
    # under the env-installed RB_TPU_FAULTS schedule, which a teardown
    # clear() would silently strip for the rest of the session; scoped
    # inject() contexts clean up after themselves
    ladder.LADDER.reset()
    inflight.TABLE.clear()
    yield
    ladder.LADDER.reset()
    inflight.TABLE.clear()
    fusion.configure(enabled=True)


def _overlapping_queries(rng, bms, n=6):
    """Shared hot AND under an OR (survives the flatten rewrite) plus
    per-query unique structure — the serving-shaped workload."""
    hot = Q.leaf(bms[0]) & Q.leaf(bms[1])
    qs = []
    for i in range(n):
        a = Q.leaf(bms[2 + i % (len(bms) - 2)])
        b = Q.leaf(bms[2 + (i + 1) % (len(bms) - 2)])
        qs.append((hot | a) - b if i % 2 else hot | (a & b))
    return qs


# ---------------------------------------------------------------------------
# fused == serial == naive (the tentpole's correctness contract)
# ---------------------------------------------------------------------------


def test_fused_matches_serial_and_naive():
    rng = np.random.default_rng(7)
    bms = [_bm(rng) for _ in range(6)]
    qs = _overlapping_queries(rng, bms)
    serial = [execute(q, cache=None) for q in qs]
    fused = execute_fused(qs, cache=ResultCache(max_entries=64))
    naive = [evaluate_naive(q) for q in qs]
    for s, f, nv in zip(serial, fused, naive):
        assert f == s == nv


def test_fused_covers_threshold_and_andnot_kernels():
    rng = np.random.default_rng(11)
    bms = [_bm(rng, n=4000) for _ in range(5)]
    leaves = [Q.leaf(b) for b in bms]
    qs = [
        Q.threshold(2, *leaves[:4]),
        Q.threshold(3, *leaves[1:]),
        Q.andnot(leaves[0], *leaves[2:4]),
        Q.andnot(leaves[1], *leaves[3:]),
        Q.or_(leaves[0], leaves[2], leaves[4]),
        Q.xor(leaves[1], leaves[2], leaves[3]),
    ]
    fused = execute_fused(qs, cache=None)
    for q, f in zip(qs, fused):
        assert f == evaluate_naive(q)


def test_fused_device_mode_matches_serial():
    """mode="device" plans device-routed engines; the merged device
    tiers (concatenated pair rows, fused andnot mask, concatenated
    threshold blocks) must stay bit-exact on the jax-CPU backend."""
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(13)
    bms = [_bm(rng, n=6000, space=1 << 20) for _ in range(5)]
    leaves = [Q.leaf(b) for b in bms]
    hot = leaves[0] & leaves[1]
    qs = [
        hot | leaves[2],
        hot | leaves[3],
        Q.andnot(leaves[0], leaves[2], leaves[3]),
        Q.andnot(leaves[1], leaves[3], leaves[4]),
        Q.threshold(2, *leaves[:4]),
        Q.threshold(2, leaves[1], leaves[2], leaves[3], leaves[4]),
    ]
    store.PACK_CACHE.close()
    try:
        serial = [execute(q, cache=None, mode="device") for q in qs]
        fused = execute_fused(qs, cache=None, mode="device")
        for s, f in zip(serial, fused):
            assert f == s
    finally:
        store.PACK_CACHE.close()


def test_fused_dedups_shared_subexpression_across_queries():
    rng = np.random.default_rng(17)
    bms = [_bm(rng) for _ in range(6)]
    qs = _overlapping_queries(rng, bms)
    before = {
        tuple(s["labels"].values()): s["value"]
        for s in observe.REGISTRY.snapshot()[observe.FUSION_STEPS_TOTAL][
            "samples"
        ]
    } if observe.REGISTRY.get(observe.FUSION_STEPS_TOTAL) else {}
    execute_fused(qs, cache=None)
    snap = observe.REGISTRY.snapshot()[observe.FUSION_STEPS_TOTAL]["samples"]
    after = {tuple(s["labels"].values()): s["value"] for s in snap}
    deduped = after.get(("deduped",), 0) - before.get(("deduped",), 0)
    assert deduped > 0, "shared hot AND was not deduped across the window"


def test_fusion_off_mode_is_plain_serial():
    rng = np.random.default_rng(19)
    bms = [_bm(rng, n=500) for _ in range(4)]
    qs = _overlapping_queries(rng, bms, n=3)
    fusion.configure(enabled=False)
    b = observe.REGISTRY.get(observe.FUSION_BATCH_TOTAL)
    before = sum(v for _lv, v in b.series().items()) if b else 0
    out = execute_fused(qs, cache=None)
    after = sum(v for _lv, v in b.series().items()) if b else 0
    assert after == before, "off mode must not drain windows"
    for q, o in zip(qs, out):
        assert o == evaluate_naive(q)


@pytest.mark.parametrize("op", ["and", "or", "xor", "andnot"])
def test_pairwise_multi_device_tier_matches_solo(op):
    """The fused device pairwise tier: many pairs (with a SHARED operand,
    so the combined block dedups) through one concatenated
    pair_rows_reduce launch, bit-exact vs solo per-pair execution."""
    from roaringbitmap_tpu import columnar
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(47)
    bms = [_bm(rng, n=5000, space=1 << 20) for _ in range(4)]
    for b in bms:
        b.run_optimize()
    pairs = [
        (bms[0], bms[1]), (bms[0], bms[2]),  # shared left operand
        (bms[2], bms[3]), (bms[1], bms[3]),
    ]
    store.PACK_CACHE.close()
    try:
        # suspended: this is a unit parity test of the merged kernels
        # called directly (no ladder above them); chaos coverage of the
        # fused device paths rides the ladder-protected execute_fused
        # tests + fuzz family 27
        with faults.suspended():
            fused = columnar.pairwise_multi(op, pairs, tier="device")
            solo = [
                columnar.pairwise(op, a, b, tier="device") for a, b in pairs
            ]
            with columnar.disabled():
                want = [
                    getattr(RoaringBitmap, {"and": "and_", "or": "or_",
                                            "xor": "xor", "andnot": "andnot"}[op])(a, b)
                    for a, b in pairs
                ]
        for f, s, w in zip(fused, solo, want):
            assert f == s == w
    finally:
        store.PACK_CACHE.close()


def test_fold_multi_matches_per_set_folds():
    from roaringbitmap_tpu.columnar import engine as col_engine
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(53)
    sets = [
        [_bm(rng, n=3000) for _ in range(3)],
        [_bm(rng, n=1000) for _ in range(4)],
        [_bm(rng, n=200, space=1 << 16) for _ in range(2)],
    ]
    for op in ("or", "xor"):
        groups_list = [store.group_by_key(bms) for bms in sets]
        fused = col_engine.fold_multi(groups_list, op)
        want = [
            col_engine.fold(store.group_by_key(bms), op) for bms in sets
        ]
        for f, w in zip(fused, want):
            assert f == w
    with pytest.raises(ValueError):
        col_engine.fold_multi([], "and")


# ---------------------------------------------------------------------------
# faults + ladder: a failed fused batch degrades to per-query, bit-exact
# ---------------------------------------------------------------------------


def test_fused_batch_degrades_to_serial_under_fault():
    rng = np.random.default_rng(23)
    bms = [_bm(rng) for _ in range(5)]
    qs = _overlapping_queries(rng, bms, n=4)
    want = [execute(q, cache=None) for q in qs]
    with faults.inject("query.fusion", every=1):
        got = execute_fused(qs, cache=None)
    for g, w in zip(got, want):
        assert g == w
    snap = observe.REGISTRY.snapshot()[observe.FUSION_BATCH_TOTAL]["samples"]
    by = {tuple(s["labels"].values()): s["value"] for s in snap}
    assert by.get(("degraded",), 0) > 0, "fault did not ride the batch ladder"


def test_fuzz_family_27_pinned_seed():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_fusion_invariance("pinned", iterations=25, seed=57)


# ---------------------------------------------------------------------------
# in-flight dedup table (tentpole leg 1) + the cross-query key fix
# ---------------------------------------------------------------------------


def test_inflight_second_thread_joins_first():
    rng = np.random.default_rng(29)
    bms = [_bm(rng) for _ in range(3)]
    q = (Q.leaf(bms[0]) & Q.leaf(bms[1])) | Q.leaf(bms[2])
    cache = ResultCache(max_entries=32)
    gate = threading.Event()
    entered = threading.Event()
    orig = query_exec._run_step

    def slow_step(step, inputs, force_cpu=False):
        entered.set()
        gate.wait(10.0)
        return orig(step, inputs, force_cpu=force_cpu)

    stats0 = inflight.TABLE.stats()
    results = {}

    def runner(tag):
        results[tag] = execute(q, cache=cache)

    query_exec._run_step = slow_step
    try:
        t1 = threading.Thread(target=runner, args=("a",))
        t1.start()
        assert entered.wait(10.0)
        query_exec._run_step = orig  # joiner must not need the gate
        t2 = threading.Thread(target=runner, args=("b",))
        t2.start()
        time.sleep(0.05)  # let the joiner reach the pending entry
        gate.set()
        t1.join(10.0)
        t2.join(10.0)
    finally:
        query_exec._run_step = orig
        gate.set()
    assert results["a"] == results["b"] == evaluate_naive(q)
    stats1 = inflight.TABLE.stats()
    assert stats1["joins"] > stats0["joins"], "second thread never joined"


def test_joiner_never_observes_stale_bits_on_midflight_mutation():
    """ISSUE 13 satellite regression: mutate a leaf while an identical
    query is in flight — the owner's completion fails fingerprint
    validation, the joiner recomputes against fresh contents, and the
    stale value never reaches the shared cache."""
    rng = np.random.default_rng(31)
    a, b = _bm(rng, n=800), _bm(rng, n=800)
    q = Q.leaf(a) & Q.leaf(b)
    cache = ResultCache(max_entries=32)
    gate = threading.Event()
    entered = threading.Event()
    orig = query_exec._run_step

    def slow_step(step, inputs, force_cpu=False):
        val = orig(step, inputs, force_cpu=force_cpu)
        entered.set()
        gate.wait(10.0)  # hold the computed-but-unpublished window open
        return val

    query_exec._run_step = slow_step
    out = {}
    try:
        t1 = threading.Thread(target=lambda: out.setdefault("a", execute(q, cache=cache)))
        t1.start()
        assert entered.wait(10.0)
        # mutate the leaf while the identical query is in flight
        added = int(a.to_array()[0]) + 1_000_003
        a.add(added)
        query_exec._run_step = orig
        gate.set()
        t1.join(10.0)
        got = execute(q, cache=cache)  # post-mutation: fresh fingerprints
    finally:
        query_exec._run_step = orig
        gate.set()
    want = evaluate_naive(Q.leaf(a) & Q.leaf(b))
    assert got == want, "post-mutation execution observed stale bits"
    assert inflight.TABLE.stats()["stale"] >= 1, (
        "mid-flight mutation did not trip the validated-publication path"
    )


def test_inflight_poll_never_blocks():
    """The fused path's non-blocking form: a still-computing foreign
    entry polls None immediately (a claim-holding executor must never
    block on another executor's unpublished claim)."""
    t = inflight.InflightTable(join_timeout_s=60.0)
    owner, entry = t.begin(("k",))
    assert owner
    _o2, e2 = t.begin(("k",))
    t0 = time.perf_counter()
    assert t.poll(e2) is None  # still computing: no wait
    assert time.perf_counter() - t0 < 1.0
    t.complete(("k",), entry, "v", valid=True)
    assert t.poll(e2) == "v"
    owner, entry = t.begin(("k2",))
    t.complete(("k2",), entry, "stale", valid=False)
    assert t.poll(entry) is None  # stale publication never shared


def test_queue_depth_gauge_aggregates_across_executors():
    """Two live executors fold into ONE gauge value: a healthy
    executor's drains must not overwrite a stalled executor's parked
    depth (the fusion-queue-stall rule's whole signal)."""
    from roaringbitmap_tpu.query.fusion import _publish_depth

    g = observe.REGISTRY.get(observe.FUSION_QUEUED_COUNT)
    _publish_depth(101, 40)  # stalled executor, 40 parked
    _publish_depth(202, 0)   # healthy executor drained
    assert g.series()[()] == 40
    _publish_depth(202, 3)
    assert g.series()[()] == 43
    _publish_depth(101, None)  # stalled executor closed
    assert g.series()[()] == 3
    _publish_depth(202, None)
    assert g.series()[()] == 0


def test_inflight_owner_failure_wakes_joiners_to_recompute():
    t = inflight.InflightTable(join_timeout_s=5.0)
    owner, entry = t.begin(("k",))
    assert owner
    joined = {}

    def join():
        _o, e = t.begin(("k",))
        joined["val"] = t.join(e)

    th = threading.Thread(target=join)
    th.start()
    time.sleep(0.05)
    t.abort(("k",), entry)
    th.join(5.0)
    assert joined["val"] is None  # recompute, never inherit the exception
    assert t.pending_count() == 0


# ---------------------------------------------------------------------------
# the fusion.batch pricing authority (cost facade protocol)
# ---------------------------------------------------------------------------


def test_fusion_batch_site_joins_outcomes_and_prices_engines():
    rb_outcomes.reset()
    rng = np.random.default_rng(37)
    bms = [_bm(rng) for _ in range(5)]
    try:
        execute_fused(_overlapping_queries(rng, bms, n=4), cache=None)
        joins = [e for e in rb_outcomes.tail() if e["site"] == "fusion.batch"]
        assert joins, "fused window joined no fusion.batch outcome"
        e = joins[-1]
        assert e["engine"] in ("fused", "per-query")
        assert e["predicted_us"] is not None and e["error_ratio"] is not None
        assert set(e["inputs"]["est_us"]) == {"fused", "per-query"}
    finally:
        rb_outcomes.reset()


def test_fusion_cost_model_refits_from_samples_and_roundtrips():
    m = fusion_cost.FusionBatchModel()
    est0 = m.estimate(10, 3)
    assert est0["fused"] < est0["per-query"]  # the structural prior
    samples = [
        {"site": "fusion.batch", "engine": "fused",
         "predicted_us": 1000.0, "measured_s": 4000e-6},
        {"site": "fusion.batch", "engine": "fused",
         "predicted_us": 1000.0, "measured_s": 4000e-6},
    ]
    rep = m.refit_from_outcomes(samples=samples)
    assert rep["provenance"] == "refit-from-traffic"
    assert m.coeffs["tier_us"] == pytest.approx(
        fusion_cost.DEFAULT_COEFFS["tier_us"] * 4.0
    )
    d = m.to_dict()
    m2 = fusion_cost.FusionBatchModel()
    assert m2.from_dict(d)
    assert m2.coeffs == m.coeffs and m2.provenance == "refit-from-traffic"
    assert not m2.from_dict({"schema": "nope"})
    m2.reset()
    assert m2.provenance == "default"


def test_cost_facade_exposes_fusion_authority():
    assert "fusion-batch" in cost.names()
    auth = cost.authority("fusion-batch")
    assert "coeffs" in auth.curves()
    state = cost.calibration_state()
    assert "fusion-batch" in state["authorities"]
    reports = cost.refit_all()
    assert "fusion-batch" in reports


def test_fusion_state_rides_unified_persistence(tmp_path):
    path = str(tmp_path / "cost_state.json")
    try:
        with fusion_cost.MODEL._lock:
            fusion_cost.MODEL.coeffs["solo_step_us"] = 333.0
            fusion_cost.MODEL.provenance = "refit-from-traffic"
        assert cost.save_state(path) == path
        fusion_cost.MODEL.reset()
        verdicts = cost.load_state(path)
        assert verdicts["fusion-batch"]
        assert fusion_cost.MODEL.coeffs["solo_step_us"] == 333.0
        assert fusion_cost.MODEL.provenance == "refit-from-traffic"
    finally:
        fusion_cost.MODEL.reset()


# ---------------------------------------------------------------------------
# the serving window (FusionExecutor)
# ---------------------------------------------------------------------------


def test_executor_coalesces_and_respects_latency_bound():
    rng = np.random.default_rng(41)
    bms = [_bm(rng, n=500) for _ in range(5)]
    qs = _overlapping_queries(rng, bms, n=5)
    want = [evaluate_naive(q) for q in qs]
    with FusionExecutor(window=8, max_wait_ms=30.0, cache=None) as ex:
        outs = ex.map(qs)
        assert ex.batches >= 1
    for o, w in zip(outs, want):
        assert o == w


def test_executor_propagates_fatal_errors_to_futures():
    with FusionExecutor(window=2, max_wait_ms=5.0, cache=None) as ex:
        fut = ex.submit("not a query")  # type: ignore[arg-type]
        with pytest.raises(Exception):
            fut.result(timeout=10.0)


# ---------------------------------------------------------------------------
# sentinel rule: fusion-queue-stall
# ---------------------------------------------------------------------------


def test_fusion_queue_stall_rule_fires_on_stalled_depth():
    rule = next(r for r in health.DEFAULT_RULES if r.name == "fusion-queue-stall")
    assert rule.actuation == "alert"

    def snap(depth, batches, prev):
        metrics = {
            observe.FUSION_QUEUED_COUNT: {
                "samples": [{"labels": {}, "value": depth}]
            },
            observe.FUSION_BATCH_TOTAL: {
                "samples": [{"labels": {"outcome": "fused"}, "value": batches}]
            },
        }
        return health.Snapshot(
            metrics=metrics, breaker_open_ages={}, drift={},
            outcome_sites={}, now=0.0, prev_sums=prev,
        )

    st = health.RuleState()
    # tick 1 establishes the counter baseline; depth parked, no drains
    s1 = snap(depth=4, batches=10, prev=None)
    st.step(rule, rule.probe(s1), 1)
    # ticks 2-3: still no drained batch -> fires after the 2-tick hysteresis
    s2 = snap(depth=4, batches=10, prev=dict(s1.sums))
    st.step(rule, rule.probe(s2), 2)
    s3 = snap(depth=4, batches=10, prev=dict(s2.sums))
    ev = st.step(rule, rule.probe(s3), 3)
    assert ev["level"] == health.WARN
    # a draining queue is healthy backpressure: clears after clear_after
    s4 = snap(depth=4, batches=12, prev=dict(s3.sums))
    assert rule.probe(s4) == 0.0


# ---------------------------------------------------------------------------
# panels: sidecar fusion block + rb_top + insights
# ---------------------------------------------------------------------------


def test_sidecar_and_insights_fusion_block():
    rng = np.random.default_rng(43)
    bms = [_bm(rng, n=500) for _ in range(5)]
    execute_fused(_overlapping_queries(rng, bms, n=4), cache=None)
    side = observe.sidecar_snapshot()
    fu = side["fusion"]
    assert {"batches", "queries", "steps", "occupancy", "dedup_hit_ratio",
            "inflight", "queue_depth"} <= set(fu)
    assert sum(fu["batches"].values()) > 0
    live = insights.fusion_counters()
    assert live["queries"] >= 4
    assert "inflight_live" in live


def test_rb_top_report_carries_fusion_panel():
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        rb_top = importlib.import_module("rb_top")
    finally:
        sys.path.pop(0)
    r = rb_top.report(tail=4)
    assert r["schema"] == "rb_tpu_top/9"
    assert "fusion" in r
    rendered = rb_top._render_console(r)
    assert "fusion (cross-query micro-batching)" in rendered
