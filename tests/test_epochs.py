"""Epoch ledger tests (ISSUE 15): the writer ingest surface, the
stamped mutation log, snapshot-isolated epoch flips (drain / repack /
publish / reclaim), the O(k) delta contract on the flip path, freshness
observability, the epoch.flip fault site failing CLOSED, the seventh
cost authority's round-trip + refit, the two new sentinel rules, the
read-write harness vs the epoch-replay oracle (fuzz family 29 seed
pin), validated publication across a flip, and the 16-thread hammer
with the lock witness proving the epoch store/ingest locks are leaves."""

import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import cost, insights, observe
from roaringbitmap_tpu.analysis.lockwitness import LockWitness
from roaringbitmap_tpu.cost import epoch as epoch_cost
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.models.writer import BitmapWriter
from roaringbitmap_tpu.observe import health, outcomes
from roaringbitmap_tpu.observe import timeline as tl
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.robust import faults
from roaringbitmap_tpu.robust.errors import TransientDeviceError
from roaringbitmap_tpu.serve import (
    AdmissionController,
    EpochStore,
    LoadHarness,
    TenantProfile,
    build_requests,
)
from roaringbitmap_tpu.serve import epochs as epochs_mod
from roaringbitmap_tpu.serve import ingest as ingest_mod
from roaringbitmap_tpu.serve import slo


@pytest.fixture(autouse=True)
def _epoch_state():
    """Every test starts from a clean tenant/ledger/model/fault state
    and leaves none behind."""
    slo.reset()
    outcomes.reset()
    epoch_cost.MODEL.reset()
    faults.clear()
    yield
    slo.reset()
    outcomes.reset()
    epoch_cost.MODEL.reset()
    faults.clear()
    store.PACK_CACHE.close()  # flip repacks must not leak residency


def _corpus(n=6, seed=3, card=1200):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(1 << 18, card, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


def _declare(name="ep-t"):
    slo.TENANTS.declare(name, quota_qps=1e9, burst=1e9)
    return name


# ---------------------------------------------------------------------------
# the writer ingest surface (models/writer.py into=)
# ---------------------------------------------------------------------------


def test_writer_into_streams_into_existing_bitmap_with_attribution():
    bm = RoaringBitmap(np.array([1, 2, (5 << 16) | 7], dtype=np.uint32))
    base_version = bm.high_low_container._version
    w = BitmapWriter(into=bm)
    w.add_many(np.array([3, (5 << 16) | 8, (9 << 16) | 1], dtype=np.int64))
    w.flush()
    assert bm.contains(3) and bm.contains((5 << 16) | 8)
    assert bm.contains((9 << 16) | 1) and bm.contains(1)
    # every flushed chunk landed through the attributed mutators: the
    # dirty scan names exactly the touched chunk keys (the O(k) delta
    # contract's substrate)
    dirty = bm.high_low_container.dirty_keys_since(base_version)
    assert dirty == {0, 5, 9}
    assert w.get() is bm


def test_writer_into_rejects_fast_rank_mismatch():
    with pytest.raises(ValueError):
        BitmapWriter(fast_rank=True, into=RoaringBitmap())


# ---------------------------------------------------------------------------
# the stamped mutation log
# ---------------------------------------------------------------------------


def test_ingest_log_submit_drain_and_depth_gauge():
    t = _declare()
    log = ingest_mod.IngestLog(max_batches=2)
    b1 = log.submit(t, {0: np.array([1, 2])}, stamp=10.0)
    b2 = log.submit(t, {1: np.array([3])}, stamp=11.0)
    assert log.depth() == 2 and log.pending_values() == 3
    assert log.stamps() == [10.0, 11.0]
    g = observe.REGISTRY.get(observe.SERVE_MUTLOG_COUNT)
    assert g.series().get(()) == 2
    with pytest.raises(OverflowError):
        log.submit(t, {0: np.array([9])})
    drained = log.drain()
    assert [b.batch_id for b in drained] == [b1.batch_id, b2.batch_id]
    assert log.depth() == 0 and g.series().get(()) == 0
    assert log.total() == 2
    # an empty mutation set is a no-op, not a batch
    assert log.submit(t, {0: np.array([], dtype=np.int64)}) is None


def test_ingest_log_rejects_undeclared_tenant_and_bad_values():
    log = ingest_mod.IngestLog()
    with pytest.raises(KeyError):
        log.submit("never-declared", {0: np.array([1])})
    t = _declare()
    with pytest.raises(ValueError):
        log.submit(t, {0: np.array([1 << 32])})


def test_merge_batches_coalesces_sorted_unique():
    t = _declare()
    b1 = ingest_mod.MutationBatch(t, {0: np.array([5, 1]), 2: np.array([7])})
    b2 = ingest_mod.MutationBatch(t, {0: np.array([5, 3])})
    merged = ingest_mod.merge_batches([b1, b2])
    assert list(merged) == [0, 2]
    assert merged[0].tolist() == [1, 3, 5]


def test_apply_batches_out_of_range_raises():
    t = _declare()
    corpus = _corpus(2)
    b = ingest_mod.MutationBatch(t, {5: np.array([1])})
    with pytest.raises(IndexError):
        ingest_mod.apply_batches(corpus, [b])


# ---------------------------------------------------------------------------
# the flip: publication, lineage, stages, delta contract
# ---------------------------------------------------------------------------


def test_flip_publishes_epoch_with_lineage_record():
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    assert es.current() == 0
    assert es.flip()["outcome"] == "noop"  # empty log: no epoch burned
    assert es.current() == 0
    b = es.submit(t, {1: np.array([7, 9])}, stamp=0.0)
    rec = es.flip(reason="test")
    assert rec["outcome"] == "flipped" and rec["epoch"] == 1
    assert rec["parent"] == 0 and rec["batches"] == [b.batch_id]
    assert rec["touched_bitmaps"] == [1] and rec["values"] == 2
    assert rec["wall_s"] > 0
    assert corpus[1].contains(7) and corpus[1].contains(9)
    assert es.current() == 1
    lin = es.lineage()
    assert lin[-1]["epoch"] == 1 and lin[-1]["tenants"] == [t]
    g = observe.REGISTRY.get(observe.SERVE_EPOCH_COUNT)
    assert g.series().get(()) == 1


def test_warm_flip_takes_the_delta_path_not_full_repack():
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    store.PACK_CACHE.close()
    try:
        store.packed_for(corpus)  # resident (cold pack happens HERE)
        hb = int(corpus[0].high_low_container.keys[0])
        es.submit(t, {0: np.array([(hb << 16) | 4242, (hb << 16) | 4243])})
        rec = es.flip()
        # the flip path itself pays ONE O(k) apply_delta, zero full packs
        assert rec["delta"]["full_repacks"] == 0, rec["delta"]
        assert rec["delta"]["delta_rows"] == 1
        assert rec["delta"]["working_sets"] == 1
    finally:
        store.PACK_CACHE.close()


def test_pack_cache_last_route_is_thread_local_classification():
    corpus = _corpus(4)
    store.PACK_CACHE.close()
    try:
        store.packed_for(corpus)
        assert store.PACK_CACHE.last_route() == ("full", 0)
        store.packed_for(corpus)
        assert store.PACK_CACHE.last_route() == ("hit", 0)
        hb = int(corpus[0].high_low_container.keys[0])
        corpus[0].add((hb << 16) | 4242)
        store.packed_for(corpus)
        assert store.PACK_CACHE.last_route() == ("delta", 1)
        # another thread's calls never clobber this thread's read
        done = {}

        def other():
            store.packed_for([bm.clone() for bm in corpus])  # a full pack
            done["route"] = store.PACK_CACHE.last_route()

        th = threading.Thread(target=other, daemon=True)
        th.start()
        th.join(10.0)
        assert done["route"] == ("full", 0)
        assert store.PACK_CACHE.last_route() == ("delta", 1)
    finally:
        store.PACK_CACHE.close()


def test_flip_stages_land_in_histogram_and_timeline():
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    hist = observe.REGISTRY.get(observe.SERVE_FLIP_STAGE_SECONDS)
    before = {
        stage: (hist.series().get((stage,)) or {"count": 0})["count"]
        for stage in epochs_mod.FLIP_STAGES
    }
    prev = tl.mode_name()
    tl.configure(mode="on")
    tl.RECORDER.clear()
    try:
        es.submit(t, {0: np.array([3])})
        es.flip()
    finally:
        tl.configure(mode=prev)
    after = {
        stage: hist.series()[(stage,)]["count"]
        for stage in epochs_mod.FLIP_STAGES
    }
    for stage in epochs_mod.FLIP_STAGES:
        assert after[stage] == before[stage] + 1, stage
    names = [e.name for e in tl.RECORDER.events()]
    assert "epoch.flip" in names
    for span in ("epoch.drain", "epoch.repack", "epoch.publish", "epoch.reclaim"):
        assert span in names, names
    pub = next(e for e in tl.RECORDER.events() if e.name == "epoch.publish")
    assert pub.attrs["epoch"] == 1  # the epoch id rides span ATTRS


def test_freshness_observed_at_publish_with_injected_stamps():
    t = _declare("fresh-t")
    corpus = _corpus(4)
    fake = [100.0]
    es = EpochStore(corpus, clock=lambda: fake[0])
    es.submit(t, {0: np.array([1])}, stamp=95.0)  # 5 s stale at publish
    es.submit(t, {1: np.array([2])}, stamp=99.0)  # 1 s stale
    es.flip()
    st = ingest_mod.FRESHNESS.series()[(t,)]
    assert st["count"] == 2
    assert 5.9 <= st["sum"] <= 6.1  # 5 + 1 seconds of lag


# ---------------------------------------------------------------------------
# snapshot isolation
# ---------------------------------------------------------------------------


def test_reader_pin_blocks_flip_until_release():
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    es.submit(t, {0: np.array([1])})
    ticket = es.reader()
    done = threading.Event()
    rec_box = {}

    def flipper():
        rec_box["rec"] = es.flip()
        done.set()

    th = threading.Thread(target=flipper, daemon=True)
    th.start()
    # the flip cannot publish while the reader pin is held
    assert not done.wait(0.15)
    assert es.current() == 0
    ticket.release()
    assert done.wait(5.0)
    assert rec_box["rec"]["outcome"] == "flipped" and es.current() == 1


def test_reader_admitted_during_flip_waits_and_gets_new_epoch():
    t = _declare()
    corpus = _corpus(4)
    # a slow flip window: the repack is real work, so park a reader pin
    # and release it from a timer to widen the drain stage
    es = EpochStore(corpus)
    es.submit(t, {0: np.array([1])})
    pin = es.reader()
    got = {}
    started = threading.Event()

    def flipper():
        started.set()
        es.flip()

    def late_reader():
        started.wait()
        time.sleep(0.05)  # flip is now draining on the held pin
        with es.reader() as tk2:
            got["epoch"] = tk2.epoch

    th1 = threading.Thread(target=flipper, daemon=True)
    th2 = threading.Thread(target=late_reader, daemon=True)
    th1.start()
    th2.start()
    time.sleep(0.15)
    pin.release()
    th1.join(5.0)
    th2.join(5.0)
    assert got["epoch"] == 1  # parked through the flip, woke on the NEW epoch


def test_snapshot_isolation_hammer_no_torn_reads():
    """XOR witness: each flip adds the SAME fresh value to bitmaps 0 and
    1 in one batch. A snapshot reader computing xor(bm0, bm1) must never
    see the value (pre-flip: in neither; post-flip: in both; torn: in
    exactly one — which is what the xor would expose)."""
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    # a chunk key past the corpus range (values < 2^18 = keys 0..3), so
    # the witness values are guaranteed absent from every bitmap
    witness = [(7 << 16) | (60000 + i) for i in range(40)]
    for v in witness:
        assert not corpus[0].contains(v) and not corpus[1].contains(v)
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            try:
                with es.reader():
                    x = RoaringBitmap.xor(corpus[0], corpus[1])
                    for v in witness:
                        assert not x.contains(v), f"torn read: {v}"
            except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)
                return

    readers = [threading.Thread(target=reader, daemon=True) for _ in range(6)]
    for th in readers:
        th.start()
    try:
        for v in witness:
            es.submit(t, {0: np.array([v]), 1: np.array([v])})
            rec = es.flip()
            assert rec["outcome"] == "flipped"
    finally:
        stop.set()
        for th in readers:
            th.join(10.0)
    assert not errors, errors[0]
    assert es.current() == len(witness)
    assert all(corpus[0].contains(v) and corpus[1].contains(v) for v in witness)


# ---------------------------------------------------------------------------
# fault site + drain stall
# ---------------------------------------------------------------------------


def test_epoch_flip_fault_fails_closed_to_old_epoch():
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    es.submit(t, {0: np.array([1])})
    with faults.inject("epoch.flip", TransientDeviceError("boom"), every=1):
        rec = es.flip()
    assert rec["outcome"] == "aborted"
    assert es.current() == 0
    assert es.log.depth() == 1  # the log keeps accumulating
    assert not corpus[0].contains(1)  # stale, never torn
    # a FATAL (programming) error is never laundered into a degrade
    with faults.inject("epoch.flip", ValueError("bug"), every=1):
        with pytest.raises(ValueError):
            es.flip()
    # after the fault clears, the flip drains everything
    rec = es.flip()
    assert rec["outcome"] == "flipped" and corpus[0].contains(1)


def test_drain_timeout_stalls_cleanly_and_recovers():
    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus, drain_timeout_s=0.05)
    es.submit(t, {0: np.array([1])})
    pin = es.reader()
    rec = es.flip()
    assert rec["outcome"] == "stalled" and es.current() == 0
    assert es.stats()["flipping"] is False  # admission reopened
    # new readers are not wedged by the aborted drain
    with es.reader() as tk:
        assert tk.epoch == 0
    pin.release()
    assert es.flip()["outcome"] == "flipped"


# ---------------------------------------------------------------------------
# the priced verdict + the seventh cost authority
# ---------------------------------------------------------------------------


def test_maybe_flip_accumulates_fresh_and_flips_stale():
    t = _declare()
    corpus = _corpus(4)
    fake = [1000.0]
    es = EpochStore(corpus, clock=lambda: fake[0])
    es.submit(t, {0: np.array([1])}, stamp=1000.0)
    # fresh log: accumulate (decision recorded, nothing joined)
    r = es.maybe_flip(now=1000.0001)
    assert r["outcome"] == "accumulate" and es.current() == 0
    d = insights.decisions(4)[-1]
    assert d["site"] == "epoch.flip" and d["decision"] == "accumulate"
    assert d["inputs"]["depth"] == 1 and "est_us" in d["inputs"]
    assert d["inputs"]["epoch"] == 0
    # stale log: flip, and the taken verdict joins with its measured wall
    r = es.maybe_flip(now=1030.0)
    assert r["outcome"] == "flipped" and es.current() == 1
    joined = [s for s in outcomes.tail() if s["site"] == "epoch.flip"]
    assert len(joined) == 1
    j = joined[0]
    assert j["engine"] == "flip" and j["predicted_us"] > 0
    assert j["measured_s"] > 0 and j["error_ratio"] is not None


def test_epoch_authority_registered_with_full_protocol():
    assert "epoch-flip" in cost.names()
    a = cost.authority("epoch-flip")
    assert a.provenance() == "default"
    curves = a.curves()
    assert curves["coeffs"]["staleness_us_per_s"] > 0
    assert set(curves["refit_keys"]) == {
        "flip_overhead_us", "repack_value_us", "drain_reader_us",
    }
    state = cost.calibration_state()
    assert "epoch-flip" in state["authorities"]


def test_epoch_refit_moves_toward_measured_truth_staleness_pinned():
    samples = [
        {"site": "epoch.flip", "engine": "flip",
         "predicted_us": 100.0, "measured_s": 0.0004}
        for _ in range(4)
    ]
    before = dict(epoch_cost.MODEL.coeffs)
    report = epoch_cost.MODEL.refit_from_outcomes(samples=samples)
    assert set(report["moved"]) == {
        "flip_overhead_us", "repack_value_us", "drain_reader_us",
    }
    assert report["provenance"] == "refit-from-traffic"
    after = epoch_cost.MODEL.coeffs
    # measured 4x the prediction: both flip coefficients scale up...
    assert after["flip_overhead_us"] == pytest.approx(
        before["flip_overhead_us"] * 4.0
    )
    # ...and the declared staleness exchange rate NEVER moves on refit
    assert after["staleness_us_per_s"] == before["staleness_us_per_s"]
    # poison is rejected, not averaged in
    bad = [{"site": "epoch.flip", "engine": "flip",
            "predicted_us": -1.0, "measured_s": 0.001}] * 3
    report2 = epoch_cost.MODEL.refit_from_outcomes(samples=bad)
    assert report2["rejected"] == 3 and not report2["moved"]


def test_epoch_model_state_roundtrip_and_foreign_rejection():
    epoch_cost.MODEL.refit_from_outcomes(samples=[
        {"site": "epoch.flip", "engine": "flip",
         "predicted_us": 100.0, "measured_s": 0.0002}
        for _ in range(2)
    ])
    d = epoch_cost.MODEL.to_dict()
    m2 = epoch_cost.EpochFlipModel()
    assert m2.from_dict(d) is True
    assert m2.coeffs == epoch_cost.MODEL.coeffs
    assert m2.provenance == "refit-from-traffic"
    assert m2.from_dict({"schema": "other/1"}) is False
    assert m2.from_dict({"schema": epoch_cost.SCHEMA,
                         "coeffs": {"flip_overhead_us": 1e12}}) is False


# ---------------------------------------------------------------------------
# sentinel rules
# ---------------------------------------------------------------------------


def _snap_pair(traffic_fn, rule_names):
    rules = [r for r in health.DEFAULT_RULES if r.name in rule_names]
    s1 = health.snapshot(refresh_hbm=False)
    for r in rules:
        r.probe(s1)  # arm the per-tick deltas
    traffic_fn()
    s2 = health.snapshot(prev_sums=s1.sums, refresh_hbm=False)
    return {r.name: r.probe(s2) for r in rules}


def test_freshness_lag_breach_rule_windows_the_histogram():
    t = _declare("lag-t")
    corpus = _corpus(4)
    fake = [50.0]
    es = EpochStore(corpus, clock=lambda: fake[0])
    # the series must exist before the arm tick (first sight reports 0)
    es.submit(t, {0: np.array([1])}, stamp=50.0)
    es.flip()

    def stale_publish():
        es.submit(t, {0: np.array([2])}, stamp=20.0)  # 30 s stale
        es.flip()

    values = _snap_pair(stale_publish, ("freshness-lag-breach",))
    rule = next(
        r for r in health.DEFAULT_RULES if r.name == "freshness-lag-breach"
    )
    assert values["freshness-lag-breach"] is not None
    assert values["freshness-lag-breach"] >= rule.critical
    # a quiet window clears (no histogram movement -> no data -> OK)
    values2 = _snap_pair(lambda: None, ("freshness-lag-breach",))
    assert rule.band(values2["freshness-lag-breach"]) == health.OK


def test_epoch_flip_stall_rule_judges_depth_without_flips():
    t = _declare("stall-t")
    corpus = _corpus(4)
    es = EpochStore(corpus)
    rule = next(
        r for r in health.DEFAULT_RULES if r.name == "epoch-flip-stall"
    )

    def park_batches():
        for i in range(6):
            es.submit(t, {0: np.array([i])})

    values = _snap_pair(park_batches, ("epoch-flip-stall",))
    assert values["epoch-flip-stall"] == 6.0
    assert rule.band(values["epoch-flip-stall"]) >= health.WARN
    # a window that flips is healthy accumulation, however deep
    def flip_and_refill():
        es.flip()
        es.submit(t, {0: np.array([99])})

    values2 = _snap_pair(flip_and_refill, ("epoch-flip-stall",))
    assert values2["epoch-flip-stall"] == 0.0


# ---------------------------------------------------------------------------
# the read-write harness vs the epoch-replay oracle
# ---------------------------------------------------------------------------


def test_harness_read_write_mix_bitexact_vs_epoch_oracle():
    corpus = _corpus(6, seed=7)
    clone = [bm.clone() for bm in corpus]
    profiles = [
        TenantProfile("rw-r", weight=3.0, quota_qps=1e6, burst=1e6),
        TenantProfile("rw-w", weight=1.0, quota_qps=1e6, burst=1e6,
                      writes=0.5),
    ]
    clone_reqs = build_requests(clone, profiles, 30, seed=99)
    reqs = build_requests(corpus, profiles, 30, seed=99)
    assert [(r.kind, r.tenant) for r in reqs] == \
        [(r.kind, r.tenant) for r in clone_reqs]
    es = EpochStore(corpus)
    h = LoadHarness(
        corpus, profiles, threads=4, window=4,
        admission=AdmissionController(max_inflight=8, queue_limit=64),
        epoch_store=es,
    )
    report = h.run(reqs)
    assert report.writes > 0 and report.shed == 0
    assert report.epoch_start == 0
    # run-end drain: every accepted batch became queryable
    assert es.log.depth() == 0
    want = LoadHarness.run_serial_epochs(clone_reqs, clone, report)
    for i, (g, w) in enumerate(zip(report.results, want)):
        assert g == w, f"position {i} diverged (epoch {report.epochs[i]})"
    # every query slot carries its admitted epoch
    for pos, r in enumerate(reqs):
        if r.kind == "query":
            assert report.epochs[pos] is not None
        else:
            assert report.batch_ids[pos] is not None


def test_harness_requires_epoch_store_for_writer_tenants():
    corpus = _corpus(4)
    with pytest.raises(ValueError):
        LoadHarness(
            corpus,
            [TenantProfile("w", quota_qps=10, writes=0.5)],
            threads=1,
        )
    with pytest.raises(ValueError):
        LoadHarness(
            corpus, [TenantProfile("r", quota_qps=10)], threads=1,
            epoch_store=EpochStore(_corpus(4, seed=8)),
        )


def test_fuzz_family_29_seed_pin():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_epoch_invariance(
        "concurrent-ingest-vs-epoch-oracle", iterations=3, seed=59
    )


# ---------------------------------------------------------------------------
# validated publication across a flip
# ---------------------------------------------------------------------------


def test_publication_from_outside_a_reader_pin_is_dropped_after_flip():
    """The in-flight table's validated-publication contract extends to
    epoch generation: a rogue computation racing a flip (no reader pin)
    still cannot publish under the pre-flip fingerprints — the flip's
    writer bumps every touched bitmap's fingerprint, so the completion
    re-validation fails and joiners recompute against fresh bits."""
    from roaringbitmap_tpu.query import Q
    from roaringbitmap_tpu.query import cache as qcache
    from roaringbitmap_tpu.query import inflight as qinflight

    t = _declare()
    corpus = _corpus(4)
    es = EpochStore(corpus)
    node = Q.leaf(corpus[0]) & Q.leaf(corpus[1])
    leaf_fps = {l.uid: l.fingerprint() for l in node.leaves}
    key = qcache.cache_key(node, leaf_fps)
    table = qinflight.InflightTable()
    owner, entry = table.begin(key)
    assert owner
    # ... the owner computes while a flip mutates its leaves ...
    es.submit(t, {0: np.array([123456])})
    assert es.flip()["outcome"] == "flipped"
    valid = qcache.leaf_fps_current(node, leaf_fps)
    assert valid is False  # the epoch moved: the snapshot is stale
    table.complete(key, entry, RoaringBitmap(), valid)
    assert table.poll(entry) is None  # joiners recompute, never stale bits
    assert table.stats()["stale"] == 1


def test_admission_decision_carries_the_epoch():
    t = _declare()
    c = AdmissionController(max_inflight=4, queue_limit=4)
    ticket = c.admit(t, epoch=7)
    ticket.release()
    d = [e for e in insights.decisions(8) if e["site"] == "serve.admit"][-1]
    assert d["inputs"]["epoch"] == 7


# ---------------------------------------------------------------------------
# surfaces: sidecar block, insights, observatory
# ---------------------------------------------------------------------------


def test_sidecar_epochs_block_and_insights_lineage():
    from roaringbitmap_tpu.observe import export as obs_export

    t = _declare("side-t")
    corpus = _corpus(4)
    es = EpochStore(corpus)
    es.submit(t, {0: np.array([5])})
    es.flip()
    side = obs_export.sidecar_snapshot()
    ep = side["epochs"]
    assert ep["epoch"] == 1 and ep["mutlog_depth"] == 0
    assert ep["flips"].get("flipped", 0) >= 1
    assert ep["ingest"].get("side-t") == 1
    assert ep["freshness"]["side-t"]["count"] >= 1
    assert set(ep["flip_stages"]) >= set(epochs_mod.FLIP_STAGES)
    blk = insights.epochs()
    assert blk["store_live"]["epoch"] == 1
    assert blk["lineage"][-1]["epoch"] == 1
    # the observatory view (the flight bundle's observatory.json) carries
    # the epoch panel, lineage included
    obs = insights.observatory()
    assert obs["epochs"]["lineage"][-1]["epoch"] == 1


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------


def test_epoch_locks_are_leaves_hammer():
    t = _declare("hammer-ep")
    corpus = _corpus(4, card=400)
    es = EpochStore(corpus)
    w = LockWitness()
    es._cond = threading.Condition(w.wrap("epoch.store", threading.Lock()))
    log_lock = es.log._lock
    es.log._lock = w.wrap("epoch.ingest", log_lock)
    reg_lock = observe.REGISTRY._lock
    observe.REGISTRY._lock = w.wrap("registry", reg_lock)
    rec_lock = tl.RECORDER._lock
    tl.RECORDER._lock = w.wrap("recorder", rec_lock)
    prev_mode = tl.mode_name()
    tl.configure(mode="on")
    stop = time.monotonic() + 1.0
    errors = []

    def reader(i):
        while time.monotonic() < stop:
            try:
                with es.reader():
                    RoaringBitmap.and_(corpus[0], corpus[1])
            except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)
                return

    def writer(i):
        k = 0
        while time.monotonic() < stop:
            k += 1
            try:
                es.submit(t, {k % 4: np.array([k % (1 << 16)])})
                if k % 3 == 0:
                    es.maybe_flip(now=time.monotonic() + 1e9)  # force-stale
                if k % 5 == 0:
                    es.lineage(4)
                    es.stats()
            except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)
                return

    threads = [
        threading.Thread(target=reader, args=(i,), daemon=True)
        for i in range(12)
    ] + [
        threading.Thread(target=writer, args=(i,), daemon=True)
        for i in range(4)
    ]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        tl.configure(mode=prev_mode)
        observe.REGISTRY._lock = reg_lock
        tl.RECORDER._lock = rec_lock
    assert not errors, errors[0]
    w.assert_consistent()
    assert w.acquisitions.get("epoch.store", 0) > 0
    assert w.acquisitions.get("epoch.ingest", 0) > 0
    # epoch.store is a LEAF: nothing is ever acquired while holding it.
    # epoch.ingest nests over the registry lock ONLY (the depth gauge is
    # set under it so a racing drain cannot be overwritten by a stale
    # pre-drain depth — the PACK_CACHE -> registry precedent)
    assert not [e for e in w.edges if e[0] == "epoch.store"], sorted(w.edges)
    ingest_edges = {e for e in w.edges if e[0] == "epoch.ingest"}
    assert ingest_edges <= {("epoch.ingest", "registry")}, sorted(w.edges)
