"""Pinned-bug regression suites ported from the reference, driven by the
same fixture files (TestConcatenation.java, PreviousValueTest.java,
RangeBitmapTest.betweenRegressionTest, TestRoaringBitmapOrNot.testBigOrNot):
each fixture reproduces a historical bug in addOffset / previousValue /
RangeBitmap.between / orNot."""

import base64
import json
import os

import numpy as np
import pytest

from roaringbitmap_tpu import ImmutableRoaringBitmap, RangeBitmap, RoaringBitmap

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_testdata = pytest.mark.skipif(
    not os.path.isdir(TESTDATA), reason="reference testdata not mounted"
)


def read_ints(name):
    with open(os.path.join(TESTDATA, name)) as f:
        return np.array([int(t) for t in f.read().split(",") if t.strip()], dtype=np.int64)


@needs_testdata
@pytest.mark.parametrize(
    "fixture,offset",
    [
        ("testIssue260.txt", 5950),  # issue #260 data set
        ("offset_failure_case_1.txt", 20),
        ("offset_failure_case_2.txt", 20),
        ("offset_failure_case_3.txt", 20),
    ],
)
def test_add_offset_elementwise(fixture, offset):
    """addOffset must equal elementwise addition
    (TestConcatenation.testElementwiseOffsetAppliedCorrectly)."""
    vals = read_ints(fixture)
    bm = RoaringBitmap(vals.astype(np.uint32))
    bm.run_optimize()
    shifted = RoaringBitmap.add_offset(bm, offset)
    want = (np.unique(vals) + offset).astype(np.uint64)
    want = want[want < 1 << 32]
    assert np.array_equal(shifted.to_array().astype(np.uint64), want), fixture


@pytest.mark.parametrize("offset", [20, 1 << 16, -20, -(1 << 16)])
def test_add_offset_shapes(random_bitmap_factory, offset):
    """Shaped addOffset sweep incl. negative offsets (the reference's
    divisor/awkward-offset matrix over mixed container types)."""
    for _ in range(6):
        bm, vals = random_bitmap_factory()
        shifted = RoaringBitmap.add_offset(bm, offset)
        want = np.unique(vals).astype(np.int64) + offset
        want = want[(want >= 0) & (want < 1 << 32)]
        assert np.array_equal(shifted.to_array().astype(np.int64), want)


@needs_testdata
def test_previous_value_regression():
    """previousValue past the last container (PreviousValueTest.java:14-23)."""
    test_value = 1828834057
    bm = RoaringBitmap(read_ints("prevvalue-regression.txt").astype(np.uint32))
    assert bm.previous_value(test_value) == bm.last()
    mapped = ImmutableRoaringBitmap(bm.serialize())
    assert mapped.previous_value(test_value) == mapped.last()


@needs_testdata
def test_rangebitmap_between_regression():
    """between == eq(l) | eq(l+1) on the regression column
    (RangeBitmapTest.betweenRegressionTest)."""
    values = read_ints("rangebitmap_regression.txt")
    app = RangeBitmap.appender(2175288)
    app.add_many(values.tolist())
    rb = app.build()
    for i in range(4):
        lower = 263501 + i
        want = RoaringBitmap.or_(rb.eq(lower), rb.eq(lower + 1))
        assert rb.between(lower, lower + 1) == want, lower


@needs_testdata
def test_big_ornot_regression():
    """orNot truncation fuzz failure (TestRoaringBitmapOrNot.testBigOrNot):
    l.orNot(r, last+1) == l | (range(0, last+1) \\ r)."""
    with open(os.path.join(TESTDATA, "ornot-fuzz-failure.json")) as f:
        info = json.load(f)
    l = RoaringBitmap.deserialize(base64.b64decode(info["bitmaps"][0]))
    r = RoaringBitmap.deserialize(base64.b64decode(info["bitmaps"][1]))
    limit = l.last() + 1
    rng = RoaringBitmap.bitmap_of_range(0, limit)
    rng.iandnot(r)
    expected = RoaringBitmap.or_(l, rng)
    assert RoaringBitmap.or_not(l, r, limit) == expected


def test_ornot_truncation_matrix():
    """orNot with a range end below existing values must never truncate them
    (OrNotTruncationTest.java:56-63, across the container-shape matrix)."""
    from roaringbitmap_tpu import RoaringBitmap

    rng = np.random.default_rng(0xFEEF1F0)

    def shape(kind, key):
        base = key << 16
        if kind == "array":
            return rng.choice(1 << 16, size=2000, replace=False).astype(np.int64) + base
        if kind == "bitmap":
            return rng.choice(1 << 16, size=9000, replace=False).astype(np.int64) + base
        return np.arange(0, 40000, dtype=np.int64) + base  # run

    others = [
        RoaringBitmap(),
        RoaringBitmap([2]),
        RoaringBitmap.bitmap_of_range(2, 5),
        RoaringBitmap.bitmap_of_range(3, 5),
        RoaringBitmap([2, 3, 4]),
        RoaringBitmap(list(range(7))),
    ]
    for kinds in (("array",), ("run",), ("bitmap",), ("array", "run"),
                  ("run", "run"), ("bitmap", "run")):
        for first_key in (0, 1):
            vals = np.concatenate(
                [shape(k, first_key + i) for i, k in enumerate(kinds)]
            )
            bm = RoaringBitmap(vals.astype(np.uint32))
            bm.run_optimize()
            others.append(bm)
    for other in others:
        one = RoaringBitmap([0, 10])
        one.ior_not(other, 7)
        assert one.contains(10), other


def test_concatenation_via_add_offset():
    """Concatenating bitmaps with addOffset keeps all values and cardinality
    (TestConcatenation.java's elementwise/cardinality families) across
    container-boundary offsets."""
    from roaringbitmap_tpu import RoaringBitmap

    rng = np.random.default_rng(0xFEEF1F0)
    vals = np.unique(rng.integers(0, 1 << 20, size=40_000, dtype=np.int64)).astype(np.uint32)
    bm = RoaringBitmap(vals)
    for offset in (0, 1, 1 << 16, (1 << 16) - 1, (1 << 16) + 1, 3 << 16, 1 << 20):
        shifted = RoaringBitmap.add_offset(bm, offset)
        assert shifted.get_cardinality() == bm.get_cardinality(), offset
        assert np.array_equal(
            shifted.to_array().astype(np.int64), vals.astype(np.int64) + offset
        ), offset
        # serialized round-trip of the shifted form is byte-stable
        blob = shifted.serialize()
        assert RoaringBitmap.deserialize(blob).serialize() == blob
    # concatenation: disjoint shifted copies OR'd together
    from roaringbitmap_tpu import FastAggregation

    parts = [RoaringBitmap.add_offset(bm, k << 21) for k in range(4)]
    cat = FastAggregation.or_(*parts)
    assert cat.get_cardinality() == 4 * bm.get_cardinality()
