"""Pinned-bug regression suites ported from the reference, driven by the
same fixture files (TestConcatenation.java, PreviousValueTest.java,
RangeBitmapTest.betweenRegressionTest, TestRoaringBitmapOrNot.testBigOrNot):
each fixture reproduces a historical bug in addOffset / previousValue /
RangeBitmap.between / orNot."""

import base64
import json
import os

import numpy as np
import pytest

from roaringbitmap_tpu import ImmutableRoaringBitmap, RangeBitmap, RoaringBitmap

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_testdata = pytest.mark.skipif(
    not os.path.isdir(TESTDATA), reason="reference testdata not mounted"
)


def read_ints(name):
    with open(os.path.join(TESTDATA, name)) as f:
        return np.array([int(t) for t in f.read().split(",") if t.strip()], dtype=np.int64)


@needs_testdata
@pytest.mark.parametrize(
    "fixture,offset",
    [
        ("testIssue260.txt", 5950),  # issue #260 data set
        ("offset_failure_case_1.txt", 20),
        ("offset_failure_case_2.txt", 20),
        ("offset_failure_case_3.txt", 20),
    ],
)
def test_add_offset_elementwise(fixture, offset):
    """addOffset must equal elementwise addition
    (TestConcatenation.testElementwiseOffsetAppliedCorrectly)."""
    vals = read_ints(fixture)
    bm = RoaringBitmap(vals.astype(np.uint32))
    bm.run_optimize()
    shifted = RoaringBitmap.add_offset(bm, offset)
    want = (np.unique(vals) + offset).astype(np.uint64)
    want = want[want < 1 << 32]
    assert np.array_equal(shifted.to_array().astype(np.uint64), want), fixture


@pytest.mark.parametrize("offset", [20, 1 << 16, -20, -(1 << 16)])
def test_add_offset_shapes(random_bitmap_factory, offset):
    """Shaped addOffset sweep incl. negative offsets (the reference's
    divisor/awkward-offset matrix over mixed container types)."""
    for _ in range(6):
        bm, vals = random_bitmap_factory()
        shifted = RoaringBitmap.add_offset(bm, offset)
        want = np.unique(vals).astype(np.int64) + offset
        want = want[(want >= 0) & (want < 1 << 32)]
        assert np.array_equal(shifted.to_array().astype(np.int64), want)


@needs_testdata
def test_previous_value_regression():
    """previousValue past the last container (PreviousValueTest.java:14-23)."""
    test_value = 1828834057
    bm = RoaringBitmap(read_ints("prevvalue-regression.txt").astype(np.uint32))
    assert bm.previous_value(test_value) == bm.last()
    mapped = ImmutableRoaringBitmap(bm.serialize())
    assert mapped.previous_value(test_value) == mapped.last()


@needs_testdata
def test_rangebitmap_between_regression():
    """between == eq(l) | eq(l+1) on the regression column
    (RangeBitmapTest.betweenRegressionTest)."""
    values = read_ints("rangebitmap_regression.txt")
    app = RangeBitmap.appender(2175288)
    app.add_many(values.tolist())
    rb = app.build()
    for i in range(4):
        lower = 263501 + i
        want = RoaringBitmap.or_(rb.eq(lower), rb.eq(lower + 1))
        assert rb.between(lower, lower + 1) == want, lower


@needs_testdata
def test_big_ornot_regression():
    """orNot truncation fuzz failure (TestRoaringBitmapOrNot.testBigOrNot):
    l.orNot(r, last+1) == l | (range(0, last+1) \\ r)."""
    with open(os.path.join(TESTDATA, "ornot-fuzz-failure.json")) as f:
        info = json.load(f)
    l = RoaringBitmap.deserialize(base64.b64decode(info["bitmaps"][0]))
    r = RoaringBitmap.deserialize(base64.b64decode(info["bitmaps"][1]))
    limit = l.last() + 1
    rng = RoaringBitmap.bitmap_of_range(0, limit)
    rng.iandnot(r)
    expected = RoaringBitmap.or_(l, rng)
    assert RoaringBitmap.or_not(l, r, limit) == expected
