"""Writers DSL, zero-copy immutable path, insights
(reference oracles: TestRoaringBitmapWriter, TestMemoryMapping,
insights/ suite)."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import (
    ImmutableRoaringBitmap,
    RoaringBitmap,
    RoaringBitmapWriter,
    insights,
)
from roaringbitmap_tpu.models.fastrank import FastRankRoaringBitmap


def test_writer_sorted_stream(rng):
    vals = np.sort(rng.choice(1 << 22, size=50000, replace=False))
    w = RoaringBitmapWriter.writer().get()
    w.add_many(vals)
    bm = w.get()
    assert np.array_equal(bm.to_array(), vals.astype(np.uint32))


def test_writer_point_adds_sorted():
    w = RoaringBitmapWriter.writer().constant_memory().get()
    for v in [1, 2, 3, 70000, 70001, 200000]:
        w.add(v)
    bm = w.get()
    assert bm.to_array().tolist() == [1, 2, 3, 70000, 70001, 200000]


def test_writer_unsorted_input(rng):
    vals = rng.choice(1 << 22, size=20000, replace=False)
    w = RoaringBitmapWriter.writer().partially_sort_values().get()
    w.add_many(vals)
    # interleave point adds out of order
    w.add(5)
    w.add(4)
    bm = w.get()
    want = np.unique(np.concatenate([vals, [4, 5]]))
    assert np.array_equal(bm.to_array(), want.astype(np.uint32))


def test_writer_run_optimise():
    w = RoaringBitmapWriter.writer().optimise_for_runs().get()
    w.add_many(np.arange(100000))
    bm = w.get()
    assert bm.has_run_compression()
    assert bm.get_cardinality() == 100000


def test_writer_fast_rank():
    w = RoaringBitmapWriter.writer().fast_rank().get()
    w.add_many([10, 20, 30])
    bm = w.get()
    assert isinstance(bm, FastRankRoaringBitmap)
    assert bm.select(1) == 20


def test_writer_flush_midstream():
    w = RoaringBitmapWriter.writer().get()
    w.add(100)
    w.flush()
    w.add(50)  # goes through the buffered path after flush reset
    bm = w.get()
    assert bm.to_array().tolist() == [50, 100]


def test_wizard_option_thresholds():
    # expected_values_per_container picks strategy (RoaringBitmapWriter.java:68-77)
    w1 = RoaringBitmapWriter.writer().expected_values_per_container(100)
    assert not w1._optimise_runs
    w2 = RoaringBitmapWriter.writer().expected_values_per_container(5000)
    assert w2._constant_memory
    w3 = RoaringBitmapWriter.writer().expected_values_per_container(1 << 15)
    assert w3._optimise_runs


# ---------------------------------------------------------------------------


@pytest.fixture
def serialized_bitmap(random_bitmap_factory):
    bm, _ = random_bitmap_factory()
    bm.run_optimize()
    return bm, bm.serialize()


def test_immutable_reads_without_copy(serialized_bitmap):
    bm, data = serialized_bitmap
    imm = ImmutableRoaringBitmap(data)
    assert imm.get_cardinality() == bm.get_cardinality()
    assert np.array_equal(imm.to_array(), bm.to_array())
    arr = bm.to_array()
    for x in [int(arr[0]), int(arr[-1]), int(arr[len(arr) // 2])]:
        assert imm.contains(x)
        assert imm.rank(x) == bm.rank(x)
    assert imm.first() == bm.first() and imm.last() == bm.last()
    assert imm.select(10) == bm.select(10)
    assert imm == bm
    assert imm.serialize() == data


def test_immutable_to_mutable(serialized_bitmap):
    bm, data = serialized_bitmap
    imm = ImmutableRoaringBitmap(data)
    mut = imm.to_mutable()
    assert mut == bm
    mut.add(0) if not mut.contains(0) else mut.remove(0)
    # source buffer unchanged
    assert ImmutableRoaringBitmap(data) == bm


def test_immutable_mmap_file(tmp_path, serialized_bitmap):
    bm, data = serialized_bitmap
    path = tmp_path / "bitmap.bin"
    path.write_bytes(data)
    imm = ImmutableRoaringBitmap.map_file(str(path))
    assert imm.get_cardinality() == bm.get_cardinality()
    assert np.array_equal(imm.to_array(), bm.to_array())


@pytest.mark.parametrize("name", ["bitmapwithruns.bin", "bitmapwithoutruns.bin"])
def test_immutable_on_golden_files(name):
    path = f"/root/reference/RoaringBitmap/src/test/resources/testdata/{name}"
    if not os.path.isfile(path):
        pytest.skip("reference not mounted")
    imm = ImmutableRoaringBitmap.map_file(path)
    assert imm.get_cardinality() == 200100


def test_immutable_rejects_garbage():
    from roaringbitmap_tpu import InvalidRoaringFormat

    with pytest.raises(InvalidRoaringFormat):
        ImmutableRoaringBitmap(b"\xde\xad\xbe\xef" * 4)


# ---------------------------------------------------------------------------


def test_insights_analyse_and_recommend():
    dense = RoaringBitmap()
    dense.add_range(0, 300000)
    dense.remove_run_compression()
    sparse = RoaringBitmap([1, 5, 100])
    runs = RoaringBitmap()
    runs.add_range(0, 100000)
    runs.run_optimize()
    stats = insights.analyse([dense, sparse, runs])
    assert stats.bitmaps_count == 3
    assert stats.run_containers_count >= 1
    assert stats.bitmap_containers_count >= 4
    assert stats.array_stats.containers_count >= 1
    assert stats.container_count() == (
        stats.array_stats.containers_count
        + stats.bitmap_containers_count
        + stats.run_containers_count
    )
    text = insights.recommend(stats)
    assert isinstance(text, str) and text
    assert insights.recommend(insights.analyse([])).startswith("No containers")


def test_immutable_select_negative_raises(serialized_bitmap):
    bm, data = serialized_bitmap
    imm = ImmutableRoaringBitmap(data)
    with pytest.raises(IndexError):
        imm.select(-1)


def test_insights_dispatch_counters():
    """Engine/layout observability (VERDICT r2 #8/#9): an aggregation must be
    attributable to a kernel path and a layout after the fact."""
    from roaringbitmap_tpu import insights
    from roaringbitmap_tpu.parallel import store

    insights.reset_dispatch_counters()
    bms = [RoaringBitmap(np.arange(i, 70000 + i, dtype=np.uint32)) for i in range(3)]
    packed = store.pack_groups(store.group_by_key(bms))
    store.reduce_packed(packed, op="or")
    counters = insights.dispatch_counters()
    assert sum(counters["layout"].values()) == 1
    assert sum(counters["kernel"].values()) >= 0  # xla on cpu backend
    # the serving host-kernel tier is attributable too
    assert counters["native"] in ("ext", "ctypes", "numpy")
    # pairwise-matrix dispatches are attributable to their engine
    from roaringbitmap_tpu.parallel.batch import pairwise_and_cardinality

    pairwise_and_cardinality(bms[:2], bms[1:], impl="vpu")
    assert insights.dispatch_counters()["pairwise"] == {"vpu": 1}
    # repeat aggregation on the same working set must not re-pad: the cached
    # padded device array object is reused identically (VERDICT r2 weak #8)
    cached = packed.padded_device(0)
    store.reduce_packed(packed, op="or")
    assert packed.padded_device(0) is cached


def test_tracing_timings_and_transfer_bytes():
    """Library tracing (SURVEY §5): host phases accumulate timings and
    device transfers are accounted in bytes."""
    from roaringbitmap_tpu import insights, tracing
    from roaringbitmap_tpu.parallel import store

    tracing.reset_timings()
    insights.reset_dispatch_counters()
    bms = [RoaringBitmap(np.arange(i, 70000 + i, dtype=np.uint32)) for i in range(3)]
    packed = store.pack_groups(store.group_by_key(bms))
    words, cards = store.reduce_packed(packed, op="or")
    store.unpack_to_bitmap(packed.group_keys, words, cards)
    t = tracing.timings()
    assert t["store.unpack_to_bitmap"]["count"] == 1
    # ISSUE 8: the cold marshal expands device-side — the flat rows move
    # under the payload_expand route, and the FIRST (one-shot) reduce
    # fuses the dense-pad gather into the fold without materializing the
    # padded block at all
    xfer = insights.dispatch_counters()["transfer_bytes"]
    m = int(np.diff(packed.group_offsets).max())
    assert xfer["payload_expand"] == packed.words_nbytes
    assert "padded_groups_built_on_device" not in xfer
    # the SECOND reduce builds the resident padded layout (repeat traffic
    # amortizes it) by an on-device gather — no second host
    # materialization, no padded ship
    words2, cards2 = store.reduce_packed(packed, op="or")
    assert np.array_equal(np.asarray(words2), np.asarray(words))
    xfer = insights.dispatch_counters()["transfer_bytes"]
    assert xfer["padded_groups_built_on_device"] == packed.n_groups * m * 2048 * 4
    # the host word block still materializes (once) on demand, under the
    # legacy pack span — the degradation path's observable
    _ = packed.words
    assert tracing.timings()["store.pack_rows_host"]["count"] == 1
    with tracing.annotate("probe-span"):
        pass
    assert tracing.timings()["probe-span"]["count"] == 1


def test_immutable_rejects_hostile_run_payload():
    """A mapped run container whose runs escape the 2^16 universe must raise
    InvalidRoaringFormat, not corrupt memory via to_words (code-review
    regression: the native interval fill previously wrote 8 KB past the
    words buffer on start=0xFFFF, length=0xFFFF)."""
    import struct

    from roaringbitmap_tpu import InvalidRoaringFormat
    from roaringbitmap_tpu.serialization import SERIAL_COOKIE

    # hand-built buffer: 1 run container, key 0, cardinality 2 (card-1=1),
    # runs [(0xFFFF, len 0xFFFF)] -> end 131070, out of universe
    cookie = SERIAL_COOKIE | (0 << 16)  # size-1=0
    buf = struct.pack("<I", cookie)
    buf += bytes([0b1])  # run marker: container 0 is a run
    buf += struct.pack("<HH", 0, 1)  # key 0, card-1
    buf += struct.pack("<H", 1)  # n_runs
    buf += struct.pack("<HH", 0xFFFF, 0xFFFF)  # hostile run
    imm = ImmutableRoaringBitmap(buf)
    with pytest.raises(InvalidRoaringFormat):
        imm.high_low_container.get_container_at_index(0)
    # the heap path rejects the same bytes
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(buf)
    # defense in depth: even if fed directly, the native kernel must clamp
    from roaringbitmap_tpu import native

    if native.available():
        got = native.words_from_intervals(
            np.array([0xFFFF], dtype=np.int64), np.array([0x1FFFE], dtype=np.int64)
        )
        assert got.shape == (1024,)
        assert got[1023] == np.uint64(1) << np.uint64(63)


def test_tracing_profile_writes_trace(tmp_path):
    """tracing.trace wraps jax.profiler and produces a trace dump."""
    import os

    from roaringbitmap_tpu import tracing

    logdir = str(tmp_path / "trace")
    import jax.numpy as jnp

    with tracing.trace(logdir):
        (jnp.arange(8) * 2).block_until_ready()
    found = []
    for root, _dirs, files in os.walk(logdir):
        found.extend(files)
    assert found, "no profiler artifacts written"


def test_writer_reset_reuse():
    """reset() (RoaringBitmapWriter.reset): one writer, many bitmaps —
    earlier results must not alias the post-reset state, INCLUDING dense
    (>4096 per key) containers emitted from the streaming word buffer
    (code-review regression: the buffer was zeroed in place while emitted
    BitmapContainers still referenced it)."""
    from roaringbitmap_tpu import RoaringBitmapWriter

    w = RoaringBitmapWriter.writer().get()
    for v in range(5000):  # point adds: the streaming word-buffer path
        w.add(v)
    first = w.get()
    assert first.get_cardinality() == 5000
    assert first.to_array().size == 5000  # container must own its words
    w.reset()
    w.add(7)
    second = w.get()
    assert second.to_array().tolist() == [7]
    assert first.get_cardinality() == 5000
    assert first.to_array().size == 5000  # untouched by post-reset adds
    # constant-memory path resets its word buffer too
    cw = RoaringBitmapWriter.writer().constant_memory().get()
    cw.add(70000)
    cw.reset()
    assert cw.get().is_empty()
