"""Writers DSL, zero-copy immutable path, insights
(reference oracles: TestRoaringBitmapWriter, TestMemoryMapping,
insights/ suite)."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import (
    ImmutableRoaringBitmap,
    RoaringBitmap,
    RoaringBitmapWriter,
    insights,
)
from roaringbitmap_tpu.models.fastrank import FastRankRoaringBitmap


def test_writer_sorted_stream(rng):
    vals = np.sort(rng.choice(1 << 22, size=50000, replace=False))
    w = RoaringBitmapWriter.writer().get()
    w.add_many(vals)
    bm = w.get()
    assert np.array_equal(bm.to_array(), vals.astype(np.uint32))


def test_writer_point_adds_sorted():
    w = RoaringBitmapWriter.writer().constant_memory().get()
    for v in [1, 2, 3, 70000, 70001, 200000]:
        w.add(v)
    bm = w.get()
    assert bm.to_array().tolist() == [1, 2, 3, 70000, 70001, 200000]


def test_writer_unsorted_input(rng):
    vals = rng.choice(1 << 22, size=20000, replace=False)
    w = RoaringBitmapWriter.writer().partially_sort_values().get()
    w.add_many(vals)
    # interleave point adds out of order
    w.add(5)
    w.add(4)
    bm = w.get()
    want = np.unique(np.concatenate([vals, [4, 5]]))
    assert np.array_equal(bm.to_array(), want.astype(np.uint32))


def test_writer_run_optimise():
    w = RoaringBitmapWriter.writer().optimise_for_runs().get()
    w.add_many(np.arange(100000))
    bm = w.get()
    assert bm.has_run_compression()
    assert bm.get_cardinality() == 100000


def test_writer_fast_rank():
    w = RoaringBitmapWriter.writer().fast_rank().get()
    w.add_many([10, 20, 30])
    bm = w.get()
    assert isinstance(bm, FastRankRoaringBitmap)
    assert bm.select(1) == 20


def test_writer_flush_midstream():
    w = RoaringBitmapWriter.writer().get()
    w.add(100)
    w.flush()
    w.add(50)  # goes through the buffered path after flush reset
    bm = w.get()
    assert bm.to_array().tolist() == [50, 100]


def test_wizard_option_thresholds():
    # expected_values_per_container picks strategy (RoaringBitmapWriter.java:68-77)
    w1 = RoaringBitmapWriter.writer().expected_values_per_container(100)
    assert not w1._optimise_runs
    w2 = RoaringBitmapWriter.writer().expected_values_per_container(5000)
    assert w2._constant_memory
    w3 = RoaringBitmapWriter.writer().expected_values_per_container(1 << 15)
    assert w3._optimise_runs


# ---------------------------------------------------------------------------


@pytest.fixture
def serialized_bitmap(random_bitmap_factory):
    bm, _ = random_bitmap_factory()
    bm.run_optimize()
    return bm, bm.serialize()


def test_immutable_reads_without_copy(serialized_bitmap):
    bm, data = serialized_bitmap
    imm = ImmutableRoaringBitmap(data)
    assert imm.get_cardinality() == bm.get_cardinality()
    assert np.array_equal(imm.to_array(), bm.to_array())
    arr = bm.to_array()
    for x in [int(arr[0]), int(arr[-1]), int(arr[len(arr) // 2])]:
        assert imm.contains(x)
        assert imm.rank(x) == bm.rank(x)
    assert imm.first() == bm.first() and imm.last() == bm.last()
    assert imm.select(10) == bm.select(10)
    assert imm == bm
    assert imm.serialize() == data


def test_immutable_to_mutable(serialized_bitmap):
    bm, data = serialized_bitmap
    imm = ImmutableRoaringBitmap(data)
    mut = imm.to_mutable()
    assert mut == bm
    mut.add(0) if not mut.contains(0) else mut.remove(0)
    # source buffer unchanged
    assert ImmutableRoaringBitmap(data) == bm


def test_immutable_mmap_file(tmp_path, serialized_bitmap):
    bm, data = serialized_bitmap
    path = tmp_path / "bitmap.bin"
    path.write_bytes(data)
    imm = ImmutableRoaringBitmap.map_file(str(path))
    assert imm.get_cardinality() == bm.get_cardinality()
    assert np.array_equal(imm.to_array(), bm.to_array())


@pytest.mark.parametrize("name", ["bitmapwithruns.bin", "bitmapwithoutruns.bin"])
def test_immutable_on_golden_files(name):
    path = f"/root/reference/RoaringBitmap/src/test/resources/testdata/{name}"
    if not os.path.isfile(path):
        pytest.skip("reference not mounted")
    imm = ImmutableRoaringBitmap.map_file(path)
    assert imm.get_cardinality() == 200100


def test_immutable_rejects_garbage():
    from roaringbitmap_tpu import InvalidRoaringFormat

    with pytest.raises(InvalidRoaringFormat):
        ImmutableRoaringBitmap(b"\xde\xad\xbe\xef" * 4)


# ---------------------------------------------------------------------------


def test_insights_analyse_and_recommend():
    dense = RoaringBitmap()
    dense.add_range(0, 300000)
    dense.remove_run_compression()
    sparse = RoaringBitmap([1, 5, 100])
    runs = RoaringBitmap()
    runs.add_range(0, 100000)
    runs.run_optimize()
    stats = insights.analyse([dense, sparse, runs])
    assert stats.bitmaps_count == 3
    assert stats.run_containers_count >= 1
    assert stats.bitmap_containers_count >= 4
    assert stats.array_stats.containers_count >= 1
    assert stats.container_count() == (
        stats.array_stats.containers_count
        + stats.bitmap_containers_count
        + stats.run_containers_count
    )
    text = insights.recommend(stats)
    assert isinstance(text, str) and text
    assert insights.recommend(insights.analyse([])).startswith("No containers")


def test_immutable_select_negative_raises(serialized_bitmap):
    bm, data = serialized_bitmap
    imm = ImmutableRoaringBitmap(data)
    with pytest.raises(IndexError):
        imm.select(-1)


def test_insights_dispatch_counters():
    """Engine/layout observability (VERDICT r2 #8/#9): an aggregation must be
    attributable to a kernel path and a layout after the fact."""
    from roaringbitmap_tpu import insights
    from roaringbitmap_tpu.parallel import store

    insights.reset_dispatch_counters()
    bms = [RoaringBitmap(np.arange(i, 70000 + i, dtype=np.uint32)) for i in range(3)]
    packed = store.pack_groups(store.group_by_key(bms))
    store.reduce_packed(packed, op="or")
    counters = insights.dispatch_counters()
    assert sum(counters["layout"].values()) == 1
    assert sum(counters["kernel"].values()) >= 0  # xla on cpu backend
    # repeat aggregation on the same working set must not re-pad: the cached
    # padded device array object is reused identically (VERDICT r2 weak #8)
    cached = packed.padded_device(0)
    store.reduce_packed(packed, op="or")
    assert packed.padded_device(0) is cached
