"""Fault model & degradation ladder (ISSUE 7): taxonomy classification,
deterministic fault schedules, breaker trip/half-open/recover (incl. under
a thread hammer with the lock witness attached), retry backoff, deadline
cancellation onto a cheaper tier, pack-cache pressure spill, and the
end-to-end bit-exactness of every injected degradation."""

import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, observe, robust
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.parallel.aggregation import FastAggregation as FA
from roaringbitmap_tpu.robust import errors, faults, ladder


@pytest.fixture(autouse=True)
def _fresh_robust_state():
    """Every test starts with no armed faults, closed breakers, default
    breaker policy, and an empty pack cache."""
    faults.clear()
    ladder.LADDER.reset()
    ladder.LADDER.configure(trip_after=3, cooldown_s=5.0)
    store.PACK_CACHE.close()
    yield
    faults.clear()
    ladder.LADDER.reset()
    ladder.LADDER.configure(trip_after=3, cooldown_s=5.0)
    store.PACK_CACHE.close()


def _bitmaps(n=4, seed=7):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(1 << 20, 4000, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


def _series(name):
    m = observe.REGISTRY.get(name)
    return m.series() if m else {}


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------


def test_classify_taxonomy():
    assert errors.classify(robust.TransientDeviceError("x")) == errors.TRANSIENT
    assert errors.classify(robust.ResourceExhausted("x")) == errors.RESOURCE
    assert errors.classify(robust.TierUnavailable("x")) == errors.UNAVAILABLE
    assert errors.classify(robust.DeadlineExceeded("x")) == errors.DEADLINE
    # runtime errors carrying status text classify by marker
    assert errors.classify(RuntimeError("RESOURCE_EXHAUSTED: hbm")) == errors.RESOURCE
    assert errors.classify(RuntimeError("UNAVAILABLE: socket closed")) == errors.TRANSIENT
    assert errors.classify(ConnectionError("reset")) == errors.TRANSIENT
    assert errors.classify(MemoryError()) == errors.RESOURCE
    # programming errors are fatal: never laundered into a degrade
    for exc in (ValueError("v"), TypeError("t"), KeyError("k"), AssertionError("a")):
        assert errors.classify(exc) == errors.FATAL, exc


def test_simulated_oom_classifies_resource():
    e = robust.simulated_oom("store.hbm")
    assert errors.classify(e) == errors.RESOURCE
    assert "RESOURCE_EXHAUSTED" in str(e) or isinstance(e, robust.ResourceExhausted)


# ---------------------------------------------------------------------------
# fault injection framework
# ---------------------------------------------------------------------------


def test_inject_every_after_times_semantics():
    fired = []
    with faults.inject("ops.dispatch", robust.TransientDeviceError, every=2):
        for _ in range(6):
            try:
                faults.fault_point("ops.dispatch")
                fired.append(0)
            except robust.TransientDeviceError:
                fired.append(1)
    assert fired == [0, 1, 0, 1, 0, 1]
    faults.clear()
    with faults.inject("ops.dispatch", robust.TransientDeviceError, after=2):
        fired = []
        for _ in range(4):
            try:
                faults.fault_point("ops.dispatch")
                fired.append(0)
            except robust.TransientDeviceError:
                fired.append(1)
    assert fired == [0, 0, 1, 1]
    faults.clear()
    with faults.inject("ops.dispatch", robust.TransientDeviceError, every=1, times=2) as inj:
        for _ in range(5):
            try:
                faults.fault_point("ops.dispatch")
            except robust.TransientDeviceError:
                pass
        assert inj.fired == 2


def test_unknown_site_is_loud():
    with pytest.raises(ValueError):
        faults.inject("no.such.site", robust.TransientDeviceError, every=1)


def test_bad_rule_arguments_are_loud():
    """Misuse fails at construction with ValueError, never later inside a
    production fault_point (an every=0 would otherwise surface as a
    ZeroDivisionError deep in store/ops code)."""
    for kw in ({"every": 0}, {"every": -1}, {"after": -1},
               {"every": 1, "times": 0}, {"prob": 1.5}, {}):
        with pytest.raises(ValueError):
            faults.inject("ops.dispatch", robust.TransientDeviceError, **kw)


def test_active_reflects_armed_scopes():
    assert not faults.active()
    with faults.inject("ops.dispatch", robust.TransientDeviceError, every=1):
        assert faults.active()
    assert not faults.active()


def test_suspended_masks_faults_without_advancing_hits():
    with faults.inject("ops.dispatch", robust.TransientDeviceError, every=1):
        with faults.suspended():
            for _ in range(5):
                faults.fault_point("ops.dispatch")  # must not raise
        assert faults.site_hits().get("ops.dispatch", 0) == 0
        with pytest.raises(robust.TransientDeviceError):
            faults.fault_point("ops.dispatch")


def test_schedule_replay_is_deterministic():
    """Same RB_TPU_FAULTS spec -> byte-identical fire/no-fire decision
    sequence at every site (the chaos gate's reproducibility contract)."""

    def decisions(spec):
        faults.install(spec)
        out = {}
        for site in faults.SITES:
            seq = []
            for _ in range(40):
                try:
                    faults.fault_point(site)
                    seq.append(0)
                except Exception:
                    seq.append(1)
            out[site] = seq
        faults.clear()
        return out

    a = decisions("ci-chaos-seed:0.3")
    b = decisions("ci-chaos-seed:0.3")
    assert a == b
    assert any(any(seq) for seq in a.values()), "schedule never fired at p=0.3"
    c = decisions("other-seed:0.3")
    assert c != a, "different seeds should give different schedules"


def test_env_schedule_install(monkeypatch):
    monkeypatch.setenv("RB_TPU_FAULTS", "test-seed:0.5:ops.dispatch")
    from roaringbitmap_tpu.robust.faults import install_env_schedule

    assert install_env_schedule()
    hits = 0
    for _ in range(30):
        try:
            faults.fault_point("ops.dispatch")
        except robust.TransientDeviceError:
            hits += 1
        faults.fault_point("store.ship")  # unlisted site: never fires
    assert hits > 0


# ---------------------------------------------------------------------------
# ladder + breaker
# ---------------------------------------------------------------------------


def test_ladder_degrades_and_counts():
    calls = []

    def bad():
        calls.append("device")
        raise robust.TransientDeviceError("x")

    def good():
        calls.append("cpu")
        return 41

    before = dict(_series(observe.DEGRADE_TOTAL))
    assert ladder.LADDER.run("agg", [("device", bad), ("per-container", good)]) == 41
    assert calls == ["device", "cpu"]
    after = _series(observe.DEGRADE_TOTAL)
    key = ("agg", "device", "per-container")
    assert after.get(key, 0) == before.get(key, 0) + 1


def test_ladder_fatal_errors_propagate():
    def buggy():
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        ladder.LADDER.run("agg", [("device", buggy), ("per-container", lambda: 1)])
    # and the breaker did NOT count it as tier ill-health
    assert ladder.LADDER.breaker_state("agg", "device") == "closed"


def test_bottom_tier_failure_escapes():
    def bad():
        raise robust.TransientDeviceError("x")

    with pytest.raises(robust.TransientDeviceError):
        ladder.LADDER.run("agg", [("pure-python", bad)])


def test_breaker_trips_skips_and_recovers():
    ladder.LADDER.configure(trip_after=3, cooldown_s=0.05)
    attempts = []

    def bad():
        attempts.append(1)
        raise robust.TransientDeviceError("x")

    for _ in range(5):
        ladder.LADDER.run("agg", [("device", bad), ("per-container", lambda: 0)])
    # attempts 1-3 trip the breaker; 4 and 5 are skipped without attempting
    assert len(attempts) == 3
    assert ladder.LADDER.breaker_state("agg", "device") == "open"
    # cooldown elapses -> half-open admits ONE probe; success closes
    time.sleep(0.06)
    ok = []
    ladder.LADDER.run("agg", [("device", lambda: ok.append(1) or 7), ("per-container", lambda: 0)])
    assert ok and ladder.LADDER.breaker_state("agg", "device") == "closed"
    tr = _series(observe.BREAKER_TRANSITIONS_TOTAL)
    assert tr.get(("agg", "device", "open"), 0) >= 1
    assert tr.get(("agg", "device", "half_open"), 0) >= 1
    assert tr.get(("agg", "device", "closed"), 0) >= 1


def test_breaker_half_open_failure_reopens():
    ladder.LADDER.configure(trip_after=1, cooldown_s=0.03)

    def bad():
        raise robust.TransientDeviceError("x")

    ladder.LADDER.run("agg", [("device", bad), ("per-container", lambda: 0)])
    assert ladder.LADDER.breaker_state("agg", "device") == "open"
    time.sleep(0.04)
    ladder.LADDER.run("agg", [("device", bad), ("per-container", lambda: 0)])  # failed probe
    assert ladder.LADDER.breaker_state("agg", "device") == "open"


def test_breaker_thread_hammer_with_lockwitness():
    """16 threads hammer a flapping tier through the ladder: no exception
    escapes, the breaker state machine stays consistent, and the health
    lock is a LEAF — witnessed: no lock is ever acquired while holding it,
    so it cannot participate in any cycle."""
    from roaringbitmap_tpu.analysis.lockwitness import LockWitness
    from roaringbitmap_tpu.observe import timeline as tl

    w = LockWitness()
    lad = ladder.Ladder(trip_after=3, cooldown_s=0.002)
    lad._lock = w.wrap("robust.health", lad._lock)
    reg_lock = observe.REGISTRY._lock
    observe.REGISTRY._lock = w.wrap("registry", reg_lock)
    rec_lock = tl.RECORDER._lock
    tl.RECORDER._lock = w.wrap("recorder", rec_lock)
    prev_mode = tl.mode_name()
    tl.configure(mode="on")  # recorder instants active during the hammer
    stop = time.monotonic() + 1.0
    errors_seen = []

    def worker(i):
        flip = 0
        while time.monotonic() < stop:
            flip += 1

            def tier():
                if flip % 3 == 0:
                    raise robust.TransientDeviceError("flap")
                return flip

            try:
                lad.run("agg", [("device", tier), ("per-container", lambda: -1)])
            except Exception as e:  # nothing may escape  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors_seen.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        tl.configure(mode=prev_mode)
        observe.REGISTRY._lock = reg_lock
        tl.RECORDER._lock = rec_lock
    assert not errors_seen
    w.assert_consistent()
    assert w.acquisitions.get("robust.health", 0) > 0
    # leaf property: no edge leaves the health lock
    assert not [e for e in w.edges if e[0] == "robust.health"], sorted(w.edges)
    assert lad.breaker_state("agg", "device") in ("closed", "open", "half_open")


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------


def test_retry_recovers_and_counts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise robust.TransientDeviceError("blip")
        return "ok"

    assert ladder.retry("store.ship", flaky, base_s=0.001) == "ok"
    assert len(calls) == 3


def test_retry_not_retryable_raises_immediately():
    calls = []

    def oom():
        calls.append(1)
        raise robust.ResourceExhausted("hbm full")

    with pytest.raises(robust.ResourceExhausted):
        ladder.retry("store.ship", oom, base_s=0.001)
    assert len(calls) == 1


def test_retry_exhausts_attempts():
    calls = []

    def always():
        calls.append(1)
        raise robust.TransientDeviceError("down")

    with pytest.raises(robust.TransientDeviceError):
        ladder.retry("store.ship", always, attempts=3, base_s=0.001)
    assert len(calls) == 3


def test_retry_respects_deadline():
    calls = []

    def always():
        calls.append(1)
        raise robust.TransientDeviceError("down")

    with ladder.deadline_scope(0.0005):
        time.sleep(0.001)
        with pytest.raises(robust.TransientDeviceError):
            ladder.retry("store.ship", always, attempts=10, base_s=0.05)
    assert len(calls) == 1  # no sleeping past an expired budget


def test_jitter_is_bounded_and_deterministic():
    for a in range(1, 6):
        d1 = ladder._jitter("store.ship", a, 0.01, 0.25)
        d2 = ladder._jitter("store.ship", a, 0.01, 0.25)
        assert d1 == d2
        assert 0 < d1 <= 0.25


# ---------------------------------------------------------------------------
# pipeline integration: injected faults end-to-end, bit-exact
# ---------------------------------------------------------------------------


def test_device_dispatch_fault_degrades_bit_exact():
    bms = _bitmaps()
    want = FA.naive_or(*bms)
    with faults.inject("ops.dispatch", robust.TransientDeviceError, every=1) as inj:
        got = FA.or_(*bms, mode="device")
    assert got == want
    assert inj.fired >= 1
    deg = _series(observe.DEGRADE_TOTAL)
    assert deg.get(("agg", "device", "columnar-cpu"), 0) >= 1 or deg.get(
        ("agg", "device", "per-container"), 0
    ) >= 1


def test_hbm_oom_fault_degrades_bit_exact():
    bms = _bitmaps(seed=11)
    want = FA.naive_or(*bms)
    with faults.inject("store.hbm", robust.simulated_oom, every=1) as inj:
        got = FA.or_(*bms, mode="device")
    assert got == want
    assert inj.fired >= 1


def test_transient_ship_fault_recovers_via_retry():
    bms = _bitmaps(seed=13)
    want = FA.naive_or(*bms)
    with faults.inject("store.ship", robust.TransientDeviceError, every=1, times=1):
        got = FA.or_(*bms, mode="device")
    assert got == want
    retry = _series(observe.RETRY_TOTAL)
    assert retry.get(("store.ship", "recovered"), 0) >= 1
    # the ladder saw NO failure: retry absorbed the blip below it
    assert ladder.LADDER.breaker_state("agg", "device") == "closed"


def test_pack_cache_pressure_spills_not_fails():
    bms = _bitmaps(seed=17)
    with faults.inject("pack_cache.budget", robust.ResourceExhausted, every=1) as inj:
        packed = store.packed_for(bms)
    assert inj.fired >= 1
    fresh = store.pack_groups(store.group_by_key(bms))
    assert np.array_equal(packed.words, fresh.words)
    assert len(store.PACK_CACHE) == 0  # served uncached under pressure
    deg = _series(observe.DEGRADE_TOTAL)
    assert deg.get(("pack_cache.budget", "resident", "uncached"), 0) >= 1
    # pressure gone: the next pack is resident again
    packed2 = store.packed_for(bms)
    assert len(store.PACK_CACHE) == 1
    assert store.packed_for(bms) is packed2


def test_columnar_native_fault_routes_to_numpy():
    from roaringbitmap_tpu import columnar
    from roaringbitmap_tpu.columnar import kernels as ck

    if not ck.has_native():
        pytest.skip("no native tier to fault")
    bms = _bitmaps(2, seed=19)
    a, b = bms
    a.run_optimize()
    with faults.inject("columnar.kernel", robust.TransientDeviceError, every=1):
        got_and = columnar.pairwise("and", a, b)
        got_card = columnar.and_cardinality_pair(a, b)
    with columnar.disabled():
        assert got_and == RoaringBitmap.and_(a, b)
        assert got_card == RoaringBitmap.and_cardinality(a, b)


def test_native_entry_fault_falls_to_numpy_tier():
    bms = _bitmaps(seed=23)
    want = FA.naive_or(*bms)
    with faults.inject("native.entry", robust.TransientDeviceError, every=1):
        assert FA.or_(*bms, mode="cpu") == want


def test_query_exec_fault_degrades_bit_exact():
    from roaringbitmap_tpu.query import Q, evaluate_naive, execute

    bms = _bitmaps(seed=29)
    expr = Q.andnot(Q.leaf(bms[0]), Q.leaf(bms[1]), Q.leaf(bms[2]))
    with faults.inject("query.exec", robust.TransientDeviceError, every=1):
        got = execute(expr, cache=None, mode="device")
    assert got == evaluate_naive(expr)


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


def test_deadline_cancels_to_cheaper_tier():
    """An expired budget forces every remaining step onto the cheapest CPU
    tier — same bits, counted as a degraded outcome."""
    from roaringbitmap_tpu.query import Q, evaluate_naive, execute

    bms = _bitmaps(seed=31)
    expr = Q.or_(
        Q.and_(Q.leaf(bms[0]), Q.leaf(bms[1])),
        Q.xor(Q.leaf(bms[2]), Q.leaf(bms[3])),
    )
    before = dict(_series(observe.DEADLINE_TOTAL))
    got = execute(expr, cache=None, mode="device", deadline_s=0.0)
    assert got == evaluate_naive(expr)
    after = _series(observe.DEADLINE_TOTAL)
    key = ("query.exec", "degraded")
    assert after.get(key, 0) == before.get(key, 0) + 1
    # a generous budget reports "met"
    got2 = execute(expr, cache=None, deadline_s=60.0)
    assert got2 == evaluate_naive(expr)
    assert _series(observe.DEADLINE_TOTAL).get(("query.exec", "met"), 0) >= 1


def test_deadline_scope_nesting_keeps_tighter():
    with ladder.deadline_scope(60.0):
        outer = ladder.deadline_remaining()
        with ladder.deadline_scope(0.001):
            inner = ladder.deadline_remaining()
            assert inner < outer
            with ladder.deadline_scope(None):  # inherits, never widens
                assert ladder.deadline_remaining() <= inner
        assert ladder.deadline_remaining() > 1.0
    assert ladder.deadline_remaining() is None


# ---------------------------------------------------------------------------
# fuzz family smoke (the 10k campaign runs it at scale)
# ---------------------------------------------------------------------------


def test_fault_schedule_fuzz_family_smoke():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_fault_schedule_invariance(
        "fault-schedule-vs-oracle", iterations=25, seed=55
    )


def test_insights_robust_counters_shape():
    from roaringbitmap_tpu import insights

    bms = _bitmaps(seed=37)
    with faults.inject("ops.dispatch", robust.TransientDeviceError, every=1):
        FA.or_(*bms, mode="device")
    rc = insights.robust_counters()
    assert set(rc) == {"degrade", "breaker", "retry", "deadline", "faults"}
    assert rc["faults"].get("ops.dispatch", 0) >= 1
    assert any(k.startswith("agg/device/") for k in rc["degrade"])
