"""The chip-suite sweep digest must call the flagship Pallas-vs-XLA
verdict correctly (it is the decision input for VERDICT r3 #2)."""

import importlib.util
import os

spec = importlib.util.spec_from_file_location(
    "sweep_digest",
    os.path.join(os.path.dirname(__file__), "..", "scripts", "sweep_digest.py"),
)
sweep_digest = importlib.util.module_from_spec(spec)
spec.loader.exec_module(sweep_digest)


def _sweep(flagship_pallas_gbps):
    return {
        "generated_utc": "2026-07-30T00:00:00Z",
        "backend": "tpu",
        "records": [
            {"kind": "wide", "shape": [16384, 2048], "config": "xla", "gbps": 59.0, "ms": 1.0},
            {"kind": "wide", "shape": [16384, 2048], "config": "xla 2stage g=128", "gbps": 140.0, "ms": 0.5},
            {"kind": "wide", "shape": [16384, 2048], "config": "pallas row_tile=256", "gbps": 80.0, "ms": 0.9},
            {"kind": "grouped", "shape": [66, 1450, 2048], "config": "xla", "gbps": 423.0, "ms": 1.9},
            {"kind": "grouped", "shape": [66, 1450, 2048], "config": "pallas g_tile=8 row_tile=64", "gbps": 137.0, "ms": 5.7},
            {"kind": "grouped", "shape": [66, 1450, 2048], "config": "pallas g_tile=8 row_tile=128 w_tile=512", "gbps": flagship_pallas_gbps, "ms": 1.0},
            {"kind": "grouped", "shape": [66, 1450, 2048], "config": "pallas broken", "error": "boom"},
        ],
    }


def test_digest_xla_holds():
    out = sweep_digest.digest(_sweep(300.0))
    f = out["flagship"]
    assert f["xla_gbps"] == 423.0 and f["best_pallas_gbps"] == 300.0
    assert f["pallas_over_xla"] == round(300.0 / 423.0, 3)
    assert "XLA holds" in out["flagship_verdict"]
    wide = next(r for r in out["shapes"] if r["kind"] == "wide")
    assert wide["best_2stage_gbps"] == 140.0


def test_digest_pallas_wins():
    out = sweep_digest.digest(_sweep(460.0))
    assert "PALLAS WINS" in out["flagship_verdict"]
    assert "w_tile=512" in out["flagship"]["best_pallas_config"]
    # the verdict must name the config to set, not just the flag to flip
    assert "GROUPED_PALLAS_CONFIG" in out["flagship_verdict"]


def test_digest_handles_missing_flagship():
    sweep = _sweep(1.0)
    sweep["records"] = [r for r in sweep["records"] if r["kind"] == "wide"]
    out = sweep_digest.digest(sweep)
    assert out["flagship"] is None and out["flagship_verdict"] is None


def test_digest_near_parity_is_not_a_win():
    """A sub-parity ratio that display-rounds to 1.0 must not advise
    flipping the dispatcher (code-review r4)."""
    out = sweep_digest.digest(_sweep(422.9))  # vs xla 423.0: ratio 0.99976
    assert out["flagship"]["pallas_over_xla"] == 1.0  # display rounding
    assert "XLA holds" in out["flagship_verdict"]


def test_digest_wide_family_verdict():
    """The wide family's winner (xla / two-stage / pallas) is called with
    the dispatch knobs to set."""
    out = sweep_digest.digest(_sweep(300.0))
    assert "two_stage" in out["wide_verdict"] and "WIDE_DISPATCH" in out["wide_verdict"]
    # without a 2stage row, xla wins the fixture's wide shape
    sweep = _sweep(300.0)
    sweep["records"] = [r for r in sweep["records"] if "2stage" not in r["config"]]
    out2 = sweep_digest.digest(sweep)
    assert "WIDE_DISPATCH='pallas'" in out2["wide_verdict"]  # pallas 80 vs xla 59


def test_wide_verdict_near_parity_and_shape_choice():
    """Within-2% edges over xla are parity (no engine-switch advice), and
    the verdict targets the largest wide shape."""
    sweep = _sweep(300.0)
    sweep["records"] = [
        {"kind": "wide", "shape": [4096, 2048], "config": "xla", "gbps": 500.0, "ms": 1.0},
        {"kind": "wide", "shape": [16384, 2048], "config": "xla", "gbps": 59.0, "ms": 1.0},
        {"kind": "wide", "shape": [16384, 2048], "config": "xla 2stage g=32", "gbps": 59.9, "ms": 1.0},
    ]
    out = sweep_digest.digest(sweep)
    assert "[16384, 2048]" in out["wide_verdict"]  # largest shape, not first sorted
    assert "WIDE_DISPATCH='xla'" in out["wide_verdict"]  # 59.9 < 59*1.02
