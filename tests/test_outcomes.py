"""Decision-outcome ledger (ISSUE 11): join mechanics (pending ring
overflow -> orphans, never a crash), regret pricing from the not-taken
alternatives, the calibrated-band anomaly watch, the 16-thread hammer
with the lock witness proving the ledger lock stays a leaf, the
refit round trip (poisoned outcomes rejected, provenance recorded and
persisted), the planner cardinality-model refit, the end-to-end joins at
every instrumented site, and the cached fingerprint walk satellite."""

import json
import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, columnar, insights, observe
from roaringbitmap_tpu.analysis.lockwitness import LockWitness
from roaringbitmap_tpu.columnar import costmodel
from roaringbitmap_tpu.observe import decisions, outcomes
from roaringbitmap_tpu.observe import timeline as tl
from roaringbitmap_tpu.parallel import aggregation, store
from roaringbitmap_tpu.query import Q, execute
from roaringbitmap_tpu.query.plan import CARD_MODEL


@pytest.fixture(autouse=True)
def clean_ledger():
    outcomes.reset()
    outcomes.configure(enabled=True, band=outcomes.DEFAULT_BAND)
    try:
        yield
    finally:
        outcomes.reset()
        outcomes.configure(
            enabled=True, band=outcomes.DEFAULT_BAND,
            capacity=outcomes.DEFAULT_CAPACITY,
            pending=outcomes.DEFAULT_PENDING,
        )


def _counter(name, labels):
    m = observe.REGISTRY.get(name)
    return m.series().get(labels, 0) if m is not None else 0


def _bitmaps(n=4, size=1200, span=1 << 18, seed=3):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(span, size, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# join mechanics
# ---------------------------------------------------------------------------


def test_register_resolve_joins_and_prices_regret():
    seq = decisions.record_decision(
        "columnar.cutoff", "columnar-cpu", outcome=True,
        op="and", na=32, nb=32, shape="run",
        est_us={"columnar-cpu": 100.0, "per-container": 2000.0},
    )
    joined = outcomes.resolve(seq, "columnar.cutoff", 120e-6, engine="columnar-cpu")
    assert joined is not None
    # model predicted 100us, measured 120us: truthful-ish pricing, and the
    # alternative (2000us) was predicted slower than what happened -> no
    # wall was lost to this verdict
    assert joined["error_ratio"] == pytest.approx(100.0 / 120.0, rel=1e-3)
    assert joined["regret_s"] == 0.0
    summ = outcomes.summary()["columnar.cutoff"]
    assert summ["count"] == 1 and summ["regret_s"] == 0.0


def test_regret_prices_the_not_taken_alternative():
    seq = decisions.record_decision(
        "columnar.cutoff", "columnar-cpu", outcome=True,
        op="and", na=32, nb=32, shape="bitmap",
        est_us={"columnar-cpu": 100.0, "per-container": 150.0},
    )
    # the chosen engine measured 500us; the alternative was predicted at
    # 150us: 350us of wall was lost to the wrong verdict
    joined = outcomes.resolve(seq, "columnar.cutoff", 500e-6, engine="columnar-cpu")
    assert joined["regret_s"] == pytest.approx(350e-6, rel=1e-6)
    worst = outcomes.summary()["columnar.cutoff"]["worst"]
    assert worst["seq"] == seq and worst["inputs"]["shape"] == "bitmap"


def test_pending_overflow_orphans_never_crash():
    outcomes.configure(pending=8)
    seqs = [
        decisions.record_decision(
            "columnar.cutoff", "columnar-cpu", outcome=True, na=20, nb=20
        )
        for _ in range(32)
    ]
    assert outcomes.LEDGER.pending_count() == 8
    before = _counter(observe.OUTCOME_ORPHANS_TOTAL, ("columnar.cutoff",))
    # the outcome of an evicted decision arrives late: counted, dropped
    assert outcomes.resolve(seqs[0], "columnar.cutoff", 1e-4, engine="x") is None
    assert (
        _counter(observe.OUTCOME_ORPHANS_TOTAL, ("columnar.cutoff",))
        == before + 1
    )
    # the newest pending still joins fine
    assert outcomes.resolve(
        seqs[-1], "columnar.cutoff", 1e-4, engine="columnar-cpu"
    ) is not None


def test_measure_scope_and_exception_drop():
    seq = decisions.record_decision(
        "columnar.cutoff", "per-container", outcome=True, na=20, nb=20
    )
    with pytest.raises(ValueError):
        with outcomes.measure(seq, "columnar.cutoff", engine="per-container"):
            raise ValueError("engine blew up")
    # the pending entry was dropped silently: no join, no orphan later
    assert outcomes.LEDGER.pending_count() == 0
    assert "columnar.cutoff" not in outcomes.summary()
    # seq=None scope is a no-op
    with outcomes.measure(None, "columnar.cutoff"):
        pass


def test_band_anomaly_counts_and_dumps(tmp_path):
    dump = str(tmp_path / "anomaly.jsonl")
    outcomes.configure(band=(0.5, 2.0), dump_path=dump)
    outcomes._LAST_DUMP_NS = 0  # re-arm the throttle for this test
    seq = decisions.record_decision(
        "columnar.cutoff", "columnar-cpu", outcome=True, na=32, nb=32,
        shape="run", op="and", est_us={"columnar-cpu": 10.0},
    )
    before = _counter(observe.OUTCOME_ANOMALY_TOTAL, ("columnar.cutoff",))
    # measured 100x the prediction: far outside the (0.5, 2.0) band
    outcomes.resolve(seq, "columnar.cutoff", 1000e-6, engine="columnar-cpu")
    assert (
        _counter(observe.OUTCOME_ANOMALY_TOTAL, ("columnar.cutoff",))
        == before + 1
    )
    for _ in range(100):  # dump thread races the assert
        try:
            lines = open(dump).read().splitlines()
            break
        except OSError:
            time.sleep(0.01)
    else:
        pytest.fail("anomaly dump never appeared")
    header = json.loads(lines[0])
    assert header["schema"] == outcomes.DUMP_SCHEMA
    assert header["trigger"]["site"] == "columnar.cutoff"
    assert header["band"] == [0.5, 2.0]


def test_band_exempts_unpriced_cardinality_ratios():
    outcomes.configure(band=(0.5, 2.0))
    before = _counter(observe.OUTCOME_ANOMALY_TOTAL, ("query.plan",))
    seq = decisions.record_decision(
        "query.plan", "pairwise", outcome=True, op="and", est_card=100_000
    )
    # the planner's structural bound missed 1000x — expected bias, not a
    # pricing anomaly: the error ratio records, the band does not fire
    joined = outcomes.resolve(
        seq, "query.plan", 1e-4, engine="pairwise", actual=100
    )
    assert joined["error_ratio"] == pytest.approx(1000.0)
    assert _counter(observe.OUTCOME_ANOMALY_TOTAL, ("query.plan",)) == before


def test_off_mode_is_inert():
    outcomes.configure(enabled=False)
    seq = decisions.record_decision(
        "columnar.cutoff", "columnar-cpu", outcome=True, na=20, nb=20
    )
    assert outcomes.LEDGER.pending_count() == 0  # nothing parked
    assert outcomes.resolve(seq, "columnar.cutoff", 1e-4, engine="x") is None
    assert outcomes.summary() == {}
    outcomes.configure(enabled=True)


# ---------------------------------------------------------------------------
# hammer + lock witness: the ledger lock is a leaf
# ---------------------------------------------------------------------------


def test_ledger_hammer_16_threads_lockwitness_leaf():
    w = LockWitness()
    led_lock = outcomes.LEDGER._lock
    outcomes.LEDGER._lock = w.wrap("outcomes.ledger", led_lock)
    reg_lock = observe.REGISTRY._lock
    observe.REGISTRY._lock = w.wrap("registry", reg_lock)
    log_lock = decisions.LOG._lock
    decisions.LOG._lock = w.wrap("decisions.log", log_lock)
    rec_lock = tl.RECORDER._lock
    tl.RECORDER._lock = w.wrap("recorder", rec_lock)
    prev_mode = tl.mode_name()
    tl.configure(mode="on")
    stop = time.monotonic() + 1.0
    errors = []

    def worker(i):
        k = 0
        while time.monotonic() < stop:
            k += 1
            try:
                seq = decisions.record_decision(
                    "columnar.cutoff", "columnar-cpu", outcome=True,
                    na=20 + i, nb=20, shape="run", op="and",
                    est_us={"columnar-cpu": 50.0, "per-container": 80.0},
                )
                if k % 3 == 0:
                    outcomes.summary()  # concurrent reader
                if k % 5 == 0:
                    outcomes.resolve(seq + 104729, "columnar.cutoff", 1e-5,
                                     engine="x")  # deliberate orphan
                else:
                    outcomes.resolve(seq, "columnar.cutoff", 60e-6,
                                     engine="columnar-cpu")
            except Exception as e:  # nothing may escape  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        tl.configure(mode=prev_mode)
        outcomes.LEDGER._lock = led_lock
        observe.REGISTRY._lock = reg_lock
        decisions.LOG._lock = log_lock
        tl.RECORDER._lock = rec_lock
    assert not errors
    w.assert_consistent()
    assert w.acquisitions.get("outcomes.ledger", 0) > 0
    # leaf property: no lock is ever acquired while holding the ledger lock
    assert not [e for e in w.edges if e[0] == "outcomes.ledger"], sorted(w.edges)


# ---------------------------------------------------------------------------
# refit round trip (cost model + planner cardinality)
# ---------------------------------------------------------------------------


@pytest.fixture
def calibrated_model():
    costmodel.MODEL.reset()
    columnar.calibrate(include_device=False)
    try:
        yield costmodel.MODEL
    finally:
        costmodel.MODEL.reset()


def test_refit_rejects_poison_and_records_provenance(calibrated_model, tmp_path):
    cell = calibrated_model.coeffs["and"]["columnar-cpu"].get("run")
    assert cell is not None
    # clean samples at two counts describing overhead=50, slope=3 ...
    samples = [
        {"op": "and", "engine": "columnar-cpu", "shape": "run",
         "n": n, "measured_us": 50.0 + 3.0 * n + jit}
        for n in (16, 64) for jit in (0.0, 0.5, -0.5)
    ]
    # ... plus poisoned ones: non-positive, NaN, unknown engine/shape,
    # and a 1000x outlier — all rejected, none crash the fit
    poison = [
        {"op": "and", "engine": "columnar-cpu", "shape": "run",
         "n": 16, "measured_us": -5.0},
        {"op": "and", "engine": "columnar-cpu", "shape": "run",
         "n": 16, "measured_us": float("nan")},
        {"op": "and", "engine": "warp-drive", "shape": "run",
         "n": 16, "measured_us": 10.0},
        {"op": "and", "engine": "columnar-cpu", "shape": "klein-bottle",
         "n": 16, "measured_us": 10.0},
        {"op": "and", "engine": "columnar-cpu", "shape": "run",
         "n": 16, "measured_us": 98_000.0},
        {"engine": "columnar-cpu"},  # missing fields
    ]
    path = str(tmp_path / "cal.json")
    report = columnar.refit_from_outcomes(
        samples + poison, min_samples=4, persist=path
    )
    assert report["rejected"] == len(poison)
    new = calibrated_model.coeffs["and"]["columnar-cpu"]["run"]
    assert new[0] == pytest.approx(50.0, abs=2.0)
    assert new[1] == pytest.approx(3.0, abs=0.2)
    assert calibrated_model.provenance == "refit-from-traffic"
    assert report["provenance"] == "refit-from-traffic"
    # provenance survives the persistence lifecycle
    fresh = costmodel.CostModel()
    assert fresh.load(path)
    assert fresh.provenance == "refit-from-traffic"
    assert fresh.coeffs["and"]["columnar-cpu"]["run"] == new
    # the refit decision landed in the provenance log
    sites = [d["site"] for d in insights.decisions()]
    assert "costmodel.refit" in sites


def test_refit_refuses_uncalibrated():
    costmodel.MODEL.reset()
    report = columnar.refit_from_outcomes([], min_samples=1)
    assert "refused" in report
    assert costmodel.MODEL.calibrated is False


def test_refit_moves_seeded_mispriced_cell_toward_truth(calibrated_model):
    # seed a mispricing: the cell claims 1/16th of its calibrated cost
    true_cell = list(calibrated_model.coeffs["and"]["columnar-cpu"]["run"])
    with calibrated_model._lock:
        calibrated_model.coeffs["and"]["columnar-cpu"]["run"] = [
            true_cell[0] / 16, true_cell[1] / 16,
        ]
    rng = np.random.default_rng(11)
    a, b = costmodel._synthetic_pair("run", 32, rng)
    outcomes.reset()
    for _ in range(6):  # live routed traffic under the poisoned pricing
        RoaringBitmap.and_(a, b)
    samples = outcomes.samples("columnar.cutoff")
    assert len(samples) >= 4
    report = columnar.refit_from_outcomes(min_samples=4)
    assert report["moved"], report
    refit_cell = calibrated_model.coeffs["and"]["columnar-cpu"]["run"]
    measured = np.median([
        s["measured_us"] for s in samples
        if s["engine"] == "columnar-cpu" and s["shape"] == "run"
    ])
    n = 32

    def cost(c):
        return c[0] + n * c[1]

    assert abs(cost(refit_cell) - measured) < abs(
        cost([true_cell[0] / 16, true_cell[1] / 16]) - measured
    )
    # routing decisions now carry the refit provenance
    tier = columnar.route(a.high_low_container, b.high_low_container, op="and")
    entry = [d for d in insights.decisions() if d["site"] == "columnar.cutoff"][-1]
    assert entry["inputs"]["model"] == "refit-from-traffic"
    with columnar.outcome(tier):
        pass  # drain the pending join this route registered


def test_cardinality_model_refit_and_reset():
    CARD_MODEL.reset()
    base = CARD_MODEL.corrected("and", 1000)
    assert base == 1000
    samples = [
        {"site": "query.plan", "inputs": {"op": "and", "est_card": 1000},
         "actual": 4000.0}
        for _ in range(6)
    ] + [
        # poisoned: million-fold miss and non-positive measurements
        {"site": "query.plan", "inputs": {"op": "and", "est_card": 1000},
         "actual": 1e12},
        {"site": "query.plan", "inputs": {"op": "and", "est_card": 1000},
         "actual": 0},
    ]
    try:
        report = CARD_MODEL.refit_from_outcomes(samples, min_samples=4)
        assert report["rejected"] == 2
        assert report["moved"]["and"]["to"] == pytest.approx(4.0, rel=0.01)
        assert CARD_MODEL.provenance == "refit-from-traffic"
        assert CARD_MODEL.corrected("and", 1000) == 4000
    finally:
        CARD_MODEL.reset()
    assert CARD_MODEL.provenance == "default"


# ---------------------------------------------------------------------------
# end-to-end joins at the instrumented sites
# ---------------------------------------------------------------------------


def test_agg_dispatch_join_records_absorbing_tier():
    bms = _bitmaps(6, seed=7)
    outcomes.reset()
    aggregation.FastAggregation.or_(*bms, mode="cpu")
    entries = [e for e in outcomes.tail() if e["site"] == "agg.dispatch"]
    assert entries, "agg dispatch produced no joined outcome"
    e = entries[-1]
    assert e["engine"] in ("columnar-cpu", "per-container", "pure-python")
    assert e["measured_s"] > 0
    assert e["inputs"]["op"] == "or"


def test_query_plan_join_carries_actual_cardinality():
    bms = _bitmaps(3, seed=9)
    outcomes.reset()
    res = execute((Q.leaf(bms[0]) & Q.leaf(bms[1])) | Q.leaf(bms[2]), cache=None)
    entries = [e for e in outcomes.tail() if e["site"] == "query.plan"]
    assert len(entries) == 2  # and-step + or-step
    for e in entries:
        assert e["actual"] >= 1
        assert e["error_ratio"] is not None  # est_card / actual
    # the or-step's actual is the final result's cardinality
    assert entries[-1]["actual"] == res.get_cardinality()


def test_memoized_plan_joins_once_no_orphans():
    bms = _bitmaps(2, seed=13)
    expr = Q.leaf(bms[0]) & Q.leaf(bms[1])
    outcomes.reset()
    before = _counter(observe.OUTCOME_ORPHANS_TOTAL, ("query.plan",))
    execute(expr, cache=None)
    first = len([e for e in outcomes.tail() if e["site"] == "query.plan"])
    execute(expr, cache=None)  # memoized plan: serial already cleared
    second = len([e for e in outcomes.tail() if e["site"] == "query.plan"])
    assert first == second == 1
    assert _counter(observe.OUTCOME_ORPHANS_TOTAL, ("query.plan",)) == before


def test_pack_cache_evict_regret_join():
    cache = store.PackCache(max_bytes=1)  # one survivor entry only
    rng = np.random.default_rng(5)
    sets = []
    for s in range(2):
        sets.append([
            RoaringBitmap(
                np.sort(rng.choice(1 << 20, 4000, replace=False)).astype(np.uint32)
            )
            for _ in range(3)
        ])
    outcomes.reset()
    cache.get_packed(sets[0])
    cache.get_packed(sets[1])   # evicts set 0 (budget of ~one entry)
    cache.get_packed(sets[0])   # re-pack of a remembered eviction
    entries = [e for e in outcomes.tail() if e["site"] == "pack_cache.evict"]
    assert entries, "evict-then-repack produced no regret join"
    e = entries[-1]
    assert e["engine"] == "repack"
    assert e["regret_s"] > 0
    assert e["regret_s"] == pytest.approx(e["measured_s"], rel=1e-6)
    cache.close()


def test_ladder_degrade_joins_wasted_wall():
    from roaringbitmap_tpu import robust
    from roaringbitmap_tpu.robust import ladder

    lad = ladder.Ladder(trip_after=5, cooldown_s=5.0)

    def bad():
        time.sleep(0.002)
        raise robust.TransientDeviceError("x")

    outcomes.reset()
    assert lad.run("agg", [("device", bad), ("per-container", lambda: 42)]) == 42
    entries = [e for e in outcomes.tail() if e["site"] == "ladder.degrade"]
    assert entries and entries[-1]["engine"] == "device"
    assert entries[-1]["regret_s"] >= 0.002


def test_columnar_route_join_above_gate(calibrated_model):
    rng = np.random.default_rng(21)
    a, b = costmodel._synthetic_pair("bitmap", 24, rng)
    outcomes.reset()
    RoaringBitmap.or_(a, b)
    entries = [e for e in outcomes.tail() if e["site"] == "columnar.cutoff"]
    assert entries
    e = entries[-1]
    assert e["engine"] in costmodel.ENGINES
    assert e["predicted_us"] is not None and e["error_ratio"] is not None
    # the join fed the per-coefficient drift gauge for this cell
    assert any(k.startswith("or/") for k in outcomes.drift())


def test_join_recorder_offline(calibrated_model):
    rng = np.random.default_rng(23)
    a, b = costmodel._synthetic_pair("run", 24, rng)
    prev = tl.mode_name()
    tl.configure(mode="on")
    tl.RECORDER.clear()
    try:
        outcomes.reset()
        RoaringBitmap.and_(a, b)
        events = tl.RECORDER.events()
    finally:
        tl.configure(mode=prev)
    joined = outcomes.join_recorder(events)
    assert joined, "no recorder span carried a decision serial"
    cut = [j for j in joined if j["site"] == "columnar.cutoff"]
    assert cut and cut[-1]["measured_s"] > 0
    assert cut[-1]["span"].startswith("outcome.")


# ---------------------------------------------------------------------------
# fingerprint-walk satellite (cached per-hlc fingerprints)
# ---------------------------------------------------------------------------


def test_fingerprint_cached_identity_and_invalidation():
    bm = RoaringBitmap([1, 2, 3, 70000])
    fp1 = bm.fingerprint()
    fp2 = bm.fingerprint()
    assert fp1 is fp2  # cached: the SAME tuple object until a mutation
    bm.add(5)
    fp3 = bm.fingerprint()
    assert fp3 is not fp1 and fp3 != fp1
    assert fp3[0] == fp1[0] and fp3[1] > fp1[1]  # same gen, moved version
    # wholesale mutations invalidate too
    bm.high_low_container.mark_all_dirty()
    assert bm.fingerprint() != fp3
    # clones get a fresh identity, not the parent's cached tuple
    cl = bm.clone()
    assert cl.fingerprint()[0] != bm.fingerprint()[0]


def test_walk_fingerprints_matches_percall_walk():
    bms = _bitmaps(8, seed=31)
    bms[3].high_low_container  # touch
    fps, idents = store._walk_fingerprints(bms)
    assert fps == tuple(bm.fingerprint() for bm in bms)
    assert idents == tuple(store._fp_ident(fp) for fp in fps)
    # warm second walk returns identical objects (zero fresh tuples)
    fps2, idents2 = store._walk_fingerprints(bms)
    assert all(a is b for a, b in zip(fps, fps2))
    assert all(a is b for a, b in zip(idents, idents2))
    # a mutation refreshes exactly the mutated operand's fingerprint
    bms[2].add(424242)
    fps3, _ = store._walk_fingerprints(bms)
    assert fps3[2] != fps[2]
    assert all(fps3[i] is fps[i] for i in range(8) if i != 2)


def test_walk_fingerprints_foreign_hlc_fallbacks():
    class SlottedForeign:  # mutable, no cache slots: per-call tuples
        __slots__ = ("_gen", "_version")

        def __init__(self):
            self._gen, self._version = 987654321, 3

    class DictForeign:  # mutable, __dict__: caches land in the dict
        def __init__(self):
            self._gen, self._version = 987654322, 4

    class Box:
        def __init__(self, hlc):
            self.high_low_container = hlc

    bms = [Box(SlottedForeign()), Box(DictForeign())]
    fps, idents = store._walk_fingerprints(bms)
    assert fps == ((987654321, 3), (987654322, 4))
    assert idents == (("g", 987654321), ("g", 987654322))
    # warm: the dict-carrying foreign hlc serves its cached tuples
    fps2, idents2 = store._walk_fingerprints(bms)
    assert fps2 == fps and idents2 == idents
    assert fps2[1] is fps[1] and idents2[1] is idents[1]
