"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax


def test_virtual_device_count():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual CPU devices"


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_dryrun_multichip(n_devices):
    import __graft_entry__ as ge

    ge.dryrun_multichip(n_devices)


def test_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    red, card = jax.jit(fn)(*args)
    host = np.asarray(args[0])
    for g in range(host.shape[0]):
        want = np.bitwise_or.reduce(host[g], axis=0)
        assert np.array_equal(np.asarray(red[g]), want)


def test_distributed_bsi_compare_matches_local():
    """Sharded O'Neil GE over an 8-device mesh == single-device fused path."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.models.bsi import o_neil_math
    from roaringbitmap_tpu.parallel import sharding

    mesh = sharding.make_mesh(8, words_axis=2)
    rng = np.random.default_rng(9)
    s, k, w = 5, 2 * mesh.devices.shape[0], 2048
    slices = rng.integers(0, 1 << 32, size=(s, k, w), dtype=np.uint64).astype(np.uint32)
    ebm = np.bitwise_or.reduce(slices, axis=0)
    predicate = 0b10110
    bits_rev = jnp.asarray([(predicate >> i) & 1 for i in range(s)][::-1], dtype=bool)
    for op in ("GE", "LT", "EQ"):
        step = sharding.distributed_bsi_compare(mesh, op)
        out, cards = step(jnp.asarray(slices), bits_rev, jnp.asarray(ebm), jnp.asarray(ebm))
        want_out, want_cards = o_neil_math(
            jnp.asarray(slices), bits_rev, jnp.asarray(ebm), jnp.asarray(ebm), op
        )
        assert np.array_equal(np.asarray(out), np.asarray(want_out)), op
        assert np.array_equal(np.asarray(cards), np.asarray(want_cards)), op


def test_engine_dispatch_through_mesh():
    """FastAggregation rides the mesh-sharded OR when config.mesh is set."""
    from roaringbitmap_tpu import FastAggregation, RoaringBitmap
    from roaringbitmap_tpu.parallel import sharding
    from roaringbitmap_tpu.parallel.aggregation import config

    rng = np.random.default_rng(31)
    bms = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 19, 3000)).astype(np.uint32))
        for _ in range(40)
    ]
    want = FastAggregation.naive_or(*bms)
    config.mesh = sharding.make_mesh(8, words_axis=2)
    try:
        got = FastAggregation.or_(*bms, mode="device")
    finally:
        config.mesh = None
    assert got == want
