"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax


def test_virtual_device_count():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual CPU devices"


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_dryrun_multichip(n_devices):
    import __graft_entry__ as ge

    ge.dryrun_multichip(n_devices)


def test_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    red, card = jax.jit(fn)(*args)
    host = np.asarray(args[0])
    for g in range(host.shape[0]):
        want = np.bitwise_or.reduce(host[g], axis=0)
        assert np.array_equal(np.asarray(red[g]), want)
