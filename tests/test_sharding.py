"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax


def test_virtual_device_count():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual CPU devices"


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_dryrun_multichip(n_devices):
    import __graft_entry__ as ge

    ge.dryrun_multichip(n_devices)


def test_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    red, card = jax.jit(fn)(*args)
    host = np.asarray(args[0])
    for g in range(host.shape[0]):
        want = np.bitwise_or.reduce(host[g], axis=0)
        assert np.array_equal(np.asarray(red[g]), want)


def test_distributed_bsi_compare_matches_local():
    """Sharded O'Neil GE over an 8-device mesh == single-device fused path."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.models.bsi import o_neil_math
    from roaringbitmap_tpu.parallel import sharding

    mesh = sharding.make_mesh(8, words_axis=2)
    rng = np.random.default_rng(9)
    s, k, w = 5, 2 * mesh.devices.shape[0], 2048
    slices = rng.integers(0, 1 << 32, size=(s, k, w), dtype=np.uint64).astype(np.uint32)
    ebm = np.bitwise_or.reduce(slices, axis=0)
    predicate = 0b10110
    bits_rev = jnp.asarray([(predicate >> i) & 1 for i in range(s)][::-1], dtype=bool)
    for op in ("GE", "LT", "EQ"):
        step = sharding.distributed_bsi_compare(mesh, op)
        out, cards = step(jnp.asarray(slices), bits_rev, jnp.asarray(ebm), jnp.asarray(ebm))
        want_out, want_cards = o_neil_math(
            jnp.asarray(slices), bits_rev, jnp.asarray(ebm), jnp.asarray(ebm), op
        )
        assert np.array_equal(np.asarray(out), np.asarray(want_out)), op
        assert np.array_equal(np.asarray(cards), np.asarray(want_cards)), op


def test_engine_dispatch_through_mesh():
    """FastAggregation rides the mesh-sharded reduce for all three ops when
    config.mesh is set (AND's identity padding is all-ones, the shape most
    likely to break if the fill is ever wrong)."""
    from roaringbitmap_tpu import FastAggregation, RoaringBitmap
    from roaringbitmap_tpu.parallel import sharding
    from roaringbitmap_tpu.parallel.aggregation import config

    rng = np.random.default_rng(31)
    bms = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 19, 3000)).astype(np.uint32))
        for _ in range(40)
    ]
    for op, engine, naive in (
        ("or", FastAggregation.or_, FastAggregation.naive_or),
        ("and", FastAggregation.and_, FastAggregation.naive_and),
        ("xor", FastAggregation.xor, FastAggregation.naive_xor),
    ):
        want = naive(*bms)
        config.mesh = sharding.make_mesh(8, words_axis=2)
        try:
            got = engine(*bms, mode="device")
        finally:
            config.mesh = None
        assert got == want, op


def test_cardinality_only_through_mesh():
    """Count-only engines ride the ICI-sharded reduce when a mesh is set,
    fetching only the per-group counts (cards_only)."""
    from roaringbitmap_tpu import FastAggregation, RoaringBitmap
    from roaringbitmap_tpu.parallel import sharding
    from roaringbitmap_tpu.parallel.aggregation import config

    rng = np.random.default_rng(59)
    bms = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 19, 3000)).astype(np.uint32))
        for _ in range(24)
    ]
    want = FastAggregation.naive_or(*bms).get_cardinality()
    config.mesh = sharding.make_mesh(8, words_axis=2)
    try:
        got = FastAggregation.or_cardinality(*bms, mode="device")
    finally:
        config.mesh = None
    assert got == want


def test_distributed_bsi_range_through_mesh():
    """BSI RANGE compares ride the mesh too (dual-walk bits [2, S])."""
    from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex
    from roaringbitmap_tpu.models.bsi import config as bsi_config
    from roaringbitmap_tpu.parallel import sharding

    rng = np.random.default_rng(77)
    n = 200_000
    cols = np.arange(n, dtype=np.uint32)
    vals = rng.integers(0, 1 << 20, size=n, dtype=np.uint64).astype(np.int64)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values((cols, vals))
    lo, hi = 1 << 18, 3 << 18
    want = bsi.compare(Operation.RANGE, lo, hi, None, mode="cpu")
    bsi_config.mesh = sharding.make_mesh(8, words_axis=2)
    try:
        got = bsi.compare(Operation.RANGE, lo, hi, None, mode="device")
    finally:
        bsi_config.mesh = None
    assert got == want


def test_distributed_bsi_sum():
    """Sharded masked-popcount sum vs host oracle on the 8-device mesh."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.parallel import sharding

    mesh = sharding.make_mesh(8, words_axis=2)
    rng = np.random.default_rng(3)
    s, k = 5, 2 * mesh.devices.shape[0]
    slices = rng.integers(0, 1 << 32, size=(s, k, 2048), dtype=np.uint64).astype(np.uint32)
    found = rng.integers(0, 1 << 32, size=(k, 2048), dtype=np.uint64).astype(np.uint32)
    step = sharding.distributed_bsi_sum(mesh)
    counts = np.asarray(step(jnp.asarray(slices), jnp.asarray(found)))
    assert counts.shape == (s, k)
    want = [int(np.unpackbits((slices[i] & found).view(np.uint8)).sum()) for i in range(s)]
    assert counts.sum(axis=1).tolist() == want
    # repeat queries reuse the cached compiled step (code-review regression)
    assert sharding.distributed_bsi_sum(mesh) is step


def test_bsi_facade_mesh_routing():
    """BSI compare/sum route through the sharded engines when config.mesh is
    set, with the key-chunk axis padded to the mesh (K=3 not a multiple of
    the 4-device containers axis), and agree with the unsharded paths."""
    import jax.numpy as jnp  # noqa: F401 (mesh build needs jax initialized)

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex, config
    from roaringbitmap_tpu.parallel import sharding

    rng = np.random.default_rng(17)
    cols = np.sort(rng.choice(3 << 16, size=60_000, replace=False)).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, size=cols.size).astype(np.int64)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values((cols, vals))
    found = RoaringBitmap(cols[::3].copy())
    med = int(np.median(vals))

    plain = {
        op: bsi.compare(op, med, 0, found, mode="device")
        for op in (Operation.GE, Operation.LT, Operation.EQ, Operation.NEQ)
    }
    plain_sum = bsi.sum(found, mode="device")

    config.mesh = sharding.make_mesh(8, words_axis=2)
    try:
        for op, want in plain.items():
            assert bsi.compare(op, med, 0, found, mode="device") == want, op
        assert bsi.sum(found, mode="device") == plain_sum
        # RANGE falls back to the unsharded fused path under a mesh
        assert bsi.compare(Operation.RANGE, med // 2, med, found, mode="device") == \
            bsi.compare(Operation.RANGE, med // 2, med, found, mode="cpu")
    finally:
        config.mesh = None


def test_wide_or_collective_layout():
    """Pin the compiled collective layout (VERDICT r3 weak #7): the sharded
    wide-OR must lower to exactly one containers-axis all-gather (the OR
    tree) plus one words-axis all-reduce (the popcount psum), and must
    never introduce all-to-all or collective-permute. The full per-family
    report is committed by scripts/hlo_report.py."""
    import jax.numpy as jnp

    from roaringbitmap_tpu.parallel import sharding

    mesh = sharding.make_mesh(8)
    rng = np.random.default_rng(3)
    rows = jnp.asarray(rng.integers(0, 1 << 32, (16, 1024), dtype=np.uint64).astype(np.uint32))
    counts = sharding.collective_summary(sharding.distributed_wide_or_cardinality(mesh), rows)
    assert counts.get("all-gather") == 1 and counts.get("all-reduce") == 1, counts
    assert "all-to-all" not in counts and "collective-permute" not in counts


def test_batched_counts_through_mesh():
    """compare_cardinality_many rides the sharded vmapped walk when a mesh
    is configured, equal to the CPU per-predicate engine (incl. RANGE with
    per-query ends and NEQ's outside-ebm remainder)."""
    from roaringbitmap_tpu import RoaringBitmap, insights
    from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex
    from roaringbitmap_tpu.models.bsi import config as bsi_config
    from roaringbitmap_tpu.parallel import sharding

    rng = np.random.default_rng(83)
    cols = np.sort(rng.choice(600_000, size=40_000, replace=False)).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, size=cols.size)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values((cols, vals))
    found = RoaringBitmap(
        rng.choice(900_000, size=30_000, replace=False).astype(np.uint32)
    )
    qs = np.quantile(vals, [0.2, 0.5, 0.8]).astype(np.int64)
    want_ge = [bsi.compare_cardinality(Operation.GE, int(v), 0, found, "cpu") for v in qs]
    want_neq = [bsi.compare_cardinality(Operation.NEQ, int(v), 0, found, "cpu") for v in qs]
    ends = qs + 5000
    want_rng = [
        bsi.compare_cardinality(Operation.RANGE, int(a), int(b), None, "cpu")
        for a, b in zip(qs, ends)
    ]
    insights.reset_dispatch_counters()
    bsi_config.mesh = sharding.make_mesh(8, words_axis=2)
    try:
        got_ge = bsi.compare_cardinality_many(Operation.GE, qs, found_set=found, mode="device")
        got_neq = bsi.compare_cardinality_many(Operation.NEQ, qs, found_set=found, mode="device")
        got_rng = bsi.compare_cardinality_many(Operation.RANGE, qs, ends=ends, mode="device")
    finally:
        bsi_config.mesh = None
    assert got_ge.tolist() == want_ge
    assert got_neq.tolist() == want_neq
    assert got_rng.tolist() == want_rng
    assert insights.dispatch_counters()["kernel"].get("oneil_batched/mesh") == 3


def test_batched_counts_64_through_mesh():
    """The 64-bit twin shares the mesh batched walk (same [S, K, 2048]
    physical pack over high-48 chunk keys)."""
    from roaringbitmap_tpu import Roaring64BitmapSliceIndex, insights
    from roaringbitmap_tpu.models.bsi import Operation
    from roaringbitmap_tpu.models.bsi64 import config as bsi64_config
    from roaringbitmap_tpu.parallel import sharding

    rng = np.random.default_rng(91)
    b = Roaring64BitmapSliceIndex()
    cols = rng.choice(1 << 40, size=6_000, replace=False).astype(np.int64)
    vals = rng.integers(0, 1 << 24, size=cols.size).astype(np.int64)
    b.set_values(list(zip(cols.tolist(), vals.tolist())))
    qs = np.quantile(vals, [0.25, 0.75]).astype(np.int64)
    want = [b.compare_cardinality(Operation.GE, int(v), 0, None, "cpu") for v in qs]
    insights.reset_dispatch_counters()
    bsi64_config.mesh = sharding.make_mesh(8, words_axis=2)
    try:
        got = b.compare_cardinality_many(Operation.GE, qs, mode="device")
    finally:
        bsi64_config.mesh = None
    assert got.tolist() == want
    assert insights.dispatch_counters()["kernel"].get("oneil_batched/mesh") == 1


_MULTIHOST_WORKER = r'''
import os, sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass

import numpy as np

pid, port = int(sys.argv[1]), sys.argv[2]
from roaringbitmap_tpu.parallel import sharding

try:
    n = sharding.initialize_multihost(f"127.0.0.1:{port}", 2, pid)
except Exception as e:
    print("DISTRIBUTED_INIT_FAILED:" + repr(e)[:200], flush=True)
    sys.exit(3)
assert n == 4, f"global device count {n} != 4"
assert jax.process_count() == 2

mesh = sharding.make_mesh(words_axis=2)
from jax.sharding import NamedSharding, PartitionSpec as P

rows = np.random.default_rng(0).integers(0, 1 << 32, (8, 2048), dtype=np.uint32)
spec = NamedSharding(mesh, P("containers", "words"))
garr = jax.make_array_from_callback(rows.shape, spec, lambda idx: rows[idx])

step = sharding.distributed_wide_or_cardinality(mesh)
total, card = step(garr)

expected = np.bitwise_or.reduce(rows, axis=0)
expected_card = int(np.unpackbits(expected.view(np.uint8)).sum())
assert int(np.asarray(card)) == expected_card, (int(np.asarray(card)), expected_card)
for s in total.addressable_shards:
    assert np.array_equal(np.asarray(s.data), expected[s.index]), "shard mismatch"
print(f"MULTIHOST_OK:{pid}", flush=True)
'''


def test_initialize_multihost_two_processes(tmp_path):
    """The actual multi-process init path (sharding.initialize_multihost)
    executes: two OS processes, a real coordinator port, a cross-process
    distributed wide-OR through the production shard_map engine, result
    asserted equal to the single-process oracle (VERDICT r4 weak #3 — the
    dryrun + pinned HLO validated the program, never the init path)."""
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = tmp_path / "multihost_worker.py"
    script.write_text(_MULTIHOST_WORKER)
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # the worker sets its own 2-device count
    procs = [
        subprocess.Popen(
            [_sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                # a worker can hang in jax.distributed.initialize (300 s
                # default) when its peer died at init; kill it and keep the
                # partial output so the skip check below still sees the
                # peer's DISTRIBUTED_INIT_FAILED marker
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    joined = "\n---\n".join(outs)
    if "DISTRIBUTED_INIT_FAILED" in joined:
        pytest.skip(f"sandbox forbids jax.distributed: {joined[-300:]}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        assert f"MULTIHOST_OK:{i}" in out, f"worker {i} missing OK:\n{out[-2000:]}"
