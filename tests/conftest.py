"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
logic is exercised without TPU hardware (SURVEY §4 implication: differential
testing with device_count fallbacks, no cluster needed)."""

import os

# Must happen before the first jax backend initialization. The environment
# may pre-import jax via a site hook (PYTHONPATH site that tunnels to a TPU),
# so setting JAX_PLATFORMS here is too late — use jax.config instead, which
# takes effect as long as no device has been queried yet.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; slow marks subprocess-heavy tests
    # (e.g. the durable kill-test family pin) that ci.sh runs separately
    config.addinivalue_line(
        "markers", "slow: deselected by the tier-1 `-m 'not slow'` run"
    )


# ---------------------------------------------------------------------------
# Seeded shape-diverse bitmap generator — the reference's fake-data oracle
# (SeededTestData.java:13 seed 0xfeef1f0; rleRegion/denseRegion/sparseRegion
# :55-62): per chunk key pick one of three region shapes so every container
# type and every type pairing shows up in differential tests.
# ---------------------------------------------------------------------------

SEED = 0xFEEF1F0


def rle_region(rng, max_runs=30):
    n_runs = rng.integers(1, max_runs + 1)
    starts = np.sort(
        rng.choice(np.arange(0, 1 << 16, 64), size=n_runs, replace=False)
    )
    out = []
    for s in starts:
        length = int(rng.integers(1, 64))
        out.append(np.arange(s, min(s + length, 1 << 16), dtype=np.int64))
    return np.unique(np.concatenate(out))


def dense_region(rng):
    card = int(rng.integers(4097, 60000))
    return np.sort(rng.choice(1 << 16, size=card, replace=False))


def sparse_region(rng):
    card = int(rng.integers(1, 4096))
    return np.sort(rng.choice(1 << 16, size=card, replace=False))


def random_chunk_values(rng):
    kind = int(rng.integers(0, 3))
    return [rle_region, dense_region, sparse_region][kind](rng)


def random_value_set(rng, max_keys=4):
    """Random 32-bit value array with shape-diverse chunks."""
    n_keys = int(rng.integers(1, max_keys + 1))
    keys = np.sort(rng.choice(64, size=n_keys, replace=False))
    parts = [random_chunk_values(rng) + (int(k) << 16) for k in keys]
    return np.concatenate(parts).astype(np.uint32)


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def random_bitmap_factory(rng):
    from roaringbitmap_tpu import RoaringBitmap

    def make(max_keys=4, optimize_prob=0.3):
        vals = random_value_set(rng, max_keys=max_keys)
        bm = RoaringBitmap(vals)
        if rng.random() < optimize_prob:
            bm.run_optimize()
        return bm, vals

    return make
