"""Facade parity sweep: every public method of the reference's
RoaringBitmap.java must have a counterpart here (camelCase -> snake_case,
python-idiom substitutions allowed), plus behavior tests for the long-tail
methods (signed order, visitors, ContainerPointer, cardinalityExceeds)."""

import os
import re

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap

REF = "/root/reference/RoaringBitmap/src/main/java/org/roaringbitmap/RoaringBitmap.java"

# reference name -> our name, when not the mechanical snake_case; "" = covered
# by a python idiom (operators, pickle, __repr__, iteration protocol)
SUBSTITUTIONS = {
    "and": "and_",
    "or": "or_",
    "xor": "xor",
    "andNot": "andnot",
    "andNotCardinality": "andnot_cardinality",
    "rank": "rank_long",
    "flip": "flip_range",
    "equals": "",  # __eq__
    "hashCode": "",  # __hash__
    "toString": "",  # __repr__
    "iterator": "",  # __iter__
    "hasNext": "",  # iterator objects
    "next": "",
    "peekNext": "",
    "advanceIfNeeded": "",  # PeekableIntIterator.advance_if_needed
    "readExternal": "",  # pickle
    "writeExternal": "",
    "forEach": "for_each",
    "forEachInRange": "for_each_in_range",
    "forAllInRange": "for_all_in_range",
}


def _parity_missing(java_path, obj, extra=None):
    src = open(java_path).read()
    names = sorted(
        set(re.findall(r"public (?:static )?(?:synchronized )?[\w<>\[\],\s]+? (\w+)\(", src))
    )
    alias = dict(SUBSTITUTIONS)
    # nested-class methods and python idioms common to all facades
    alias.update({"accept": "", "init": ""})
    if extra:
        alias.update(extra)
    missing = []
    for n in names:
        mapped = alias.get(n)
        if mapped == "":
            continue
        snake = re.sub(r"(?<!^)(?=[A-Z])", "_", n).lower()
        cands = {mapped or snake, snake, snake.replace("_long", "").replace("long_", "")}
        if not any(hasattr(obj, c) for c in cands if c):
            missing.append(n)
    return missing


BASE = "/root/reference/RoaringBitmap/src/main/java/org/roaringbitmap/"
needs_ref = pytest.mark.skipif(not os.path.isfile(REF), reason="reference not mounted")


@needs_ref
def test_all_reference_public_methods_have_counterparts():
    missing = _parity_missing(REF, RoaringBitmap())
    assert not missing, f"no counterpart for: {missing}"


@needs_ref
def test_buffer_and_64bit_facade_parity():
    import roaringbitmap_tpu as r

    checks = [
        (BASE + "buffer/MutableRoaringBitmap.java", r.MutableRoaringBitmap(), None),
        (
            BASE + "buffer/ImmutableRoaringBitmap.java",
            r.ImmutableRoaringBitmap(RoaringBitmap.bitmap_of(1).serialize()),
            {"andNotCardinality": "andnot_cardinality", "remove": ""},  # Iterator.remove
        ),
        (BASE + "longlong/Roaring64NavigableMap.java", r.Roaring64NavigableMap(), None),
        (BASE + "longlong/Roaring64Bitmap.java", r.Roaring64Bitmap(), None),
    ]
    problems = {}
    for path, obj, extra in checks:
        missing = _parity_missing(path, obj, extra)
        if missing:
            problems[type(obj).__name__] = missing
    assert not problems, f"no counterpart for: {problems}"


@pytest.fixture
def bm():
    return RoaringBitmap.bitmap_of(1, 5, 0x80000000, 0xFFFFFFFF, 70000)


def test_signed_order(bm):
    assert bm.first_signed() == -(1 << 31)
    assert bm.last_signed() == 70000
    assert list(bm.get_signed_int_iterator()) == [-(1 << 31), -1, 1, 5, 70000]


def test_signed_order_positive_only():
    b = RoaringBitmap.bitmap_of(3, 9)
    assert b.first_signed() == 3 and b.last_signed() == 9


def test_cardinality_exceeds(bm):
    assert bm.cardinality_exceeds(0) and bm.cardinality_exceeds(4)
    assert not bm.cardinality_exceeds(5)


def test_visitors(bm):
    seen = []
    bm.for_each(seen.append)
    assert seen == [1, 5, 70000, 1 << 31, 0xFFFFFFFF]
    inr = []
    bm.for_each_in_range(0, 70001, inr.append)
    assert inr == [1, 5, 70000]
    pos = []
    bm.for_all_in_range(0, 8, lambda p, f: pos.append((p, f)))
    assert len(pos) == 8
    assert [p for p, f in pos if f] == [1, 5]


def test_container_pointer(bm):
    cp = bm.get_container_pointer()
    keys, cards = [], []
    while cp.key() is not None:
        keys.append(cp.key())
        cards.append(cp.get_cardinality())
        cp.advance()
    assert keys == [0, 1, 0x8000, 0xFFFF]
    assert sum(cards) == bm.get_cardinality()
    assert cp.get_container() is None


def test_add_n_clear_trim():
    b = RoaringBitmap()
    b.add_n(np.array([9, 8, 7, 6]), offset=1, n=2)
    assert sorted(b) == [7, 8]
    b.trim()
    b.clear()
    assert b.is_empty()


def test_world_casts(bm):
    from roaringbitmap_tpu import MutableRoaringBitmap

    m = bm.to_mutable_roaring_bitmap()
    assert type(m) is MutableRoaringBitmap and m == bm


def test_64bit_lazy_protocol():
    from roaringbitmap_tpu import Roaring64NavigableMap

    a = Roaring64NavigableMap([1, 1 << 40])
    b = Roaring64NavigableMap([2, 1 << 41])
    a.naive_lazy_or(b)
    a.repair_after_lazy()
    assert a.get_long_cardinality() == 4 and a.contains(1 << 41)


def test_bitmap_of_unordered_stays_in_buffer_world():
    from roaringbitmap_tpu import MutableRoaringBitmap

    m = MutableRoaringBitmap.bitmap_of_unordered(3, 1, 2)
    assert type(m) is MutableRoaringBitmap
    m.to_immutable()


def test_for_all_in_range_chunk_boundary():
    b = RoaringBitmap.bitmap_of(65535, 65536, 200000)
    got = []
    b.for_all_in_range(65530, 65540, lambda p, f: got.append((p, f)))
    assert [p for p, f in got if f] == [5, 6] and len(got) == 10


def test_immutable_zero_copy_read_surface():
    import numpy as np

    from roaringbitmap_tpu import ImmutableRoaringBitmap

    src = RoaringBitmap(np.arange(100, 70000, 7, dtype=np.uint32))
    src.run_optimize()
    imm = ImmutableRoaringBitmap(src.serialize())
    assert imm.rank_long(5000) == src.rank_long(5000)
    assert imm.next_value(101) == src.next_value(101)
    assert imm.range_cardinality(0, 10000) == src.range_cardinality(0, 10000)
    assert imm.select_range(3, 10) == src.select_range(3, 10)
    assert imm.has_run_compression() == src.has_run_compression()
    it = imm.get_int_iterator()
    assert it.has_next() and it.next() == 100
    assert imm.to_roaring_bitmap() == src
    flipped = ImmutableRoaringBitmap.flip(imm, 0, 10)
    assert flipped.get_cardinality() == src.get_cardinality() + 10
    with pytest.raises(AttributeError, match="immutable"):
        imm.add(5)


def test_64bit_iterators_and_limits():
    from roaringbitmap_tpu import Roaring64Bitmap, Roaring64NavigableMap

    m = Roaring64NavigableMap([1, 2, (1 << 40) + 3])
    assert list(m.get_reverse_long_iterator()) == [(1 << 40) + 3, 2, 1]
    assert m.limit(2).to_array().tolist() == [1, 2]
    m.add_int(0xFFFFFFFF)
    assert m.get_int_cardinality() == 4

    b = Roaring64Bitmap([5, 70000, (1 << 40) + 9])
    assert list(b.get_long_iterator_from(70000)) == [70000, (1 << 40) + 9]
    assert list(b.get_reverse_long_iterator_from(70000)) == [70000, 5]
    flags = []
    b.for_all_in_range(4, 8, lambda p, f: flags.append(f))
    assert flags == [False, True, False, False]
    assert Roaring64Bitmap.and_cardinality(b, b) == 3
    b.clear()
    assert b.is_empty()


def test_64bit_range_validation_and_limit():
    from roaringbitmap_tpu import Roaring64Bitmap

    b = Roaring64Bitmap(range(100, 200))
    with pytest.raises(ValueError):
        b.for_all_in_range(1000, 50, lambda p, f: None)
    with pytest.raises(ValueError):
        b.for_each_in_range(1000, 50, lambda v: None)
    assert b.limit(30).to_array().tolist() == list(range(100, 130))
    big = Roaring64Bitmap()
    big.add_range(0, 70000)  # spans two containers
    assert big.limit(65540).get_cardinality() == 65540


def test_ior_not_matches_static():
    a = RoaringBitmap.bitmap_of(1, 5, 100)
    b = RoaringBitmap.bitmap_of(2, 5)
    want = RoaringBitmap.or_not(a, b, 50)
    got = a.clone()
    assert got.ior_not(b, 50) is got and got == want
