"""RangeBitmap differential tests (reference oracle: RangeBitmapTest.java)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.range_bitmap import RangeBitmap, RangeBitmapAppender
from roaringbitmap_tpu.serialization import InvalidRoaringFormat


@pytest.fixture
def rows(rng):
    return rng.integers(0, 1_000_000, size=150_000, dtype=np.uint64)


@pytest.fixture
def range_index(rows):
    app = RangeBitmap.appender(1_000_000)
    app.add_many(rows)
    return app.build()


def test_build_and_row_count(range_index, rows):
    assert range_index.row_count == rows.size


@pytest.mark.parametrize("q", [0, 1, 499_999, 999_999, 1_000_000])
def test_all_query_ops(range_index, rows, q):
    rids = np.arange(rows.size, dtype=np.int64)
    assert np.array_equal(range_index.lt(q).to_array().astype(np.int64), rids[rows < q])
    assert np.array_equal(range_index.lte(q).to_array().astype(np.int64), rids[rows <= q])
    assert np.array_equal(range_index.gt(q).to_array().astype(np.int64), rids[rows > q])
    assert np.array_equal(range_index.gte(q).to_array().astype(np.int64), rids[rows >= q])
    assert np.array_equal(range_index.eq(q).to_array().astype(np.int64), rids[rows == q])
    assert np.array_equal(range_index.neq(q).to_array().astype(np.int64), rids[rows != q])


def test_between_and_cardinalities(range_index, rows):
    rids = np.arange(rows.size, dtype=np.int64)
    lo, hi = 250_000, 750_000
    want = rids[(rows >= lo) & (rows <= hi)]
    assert np.array_equal(range_index.between(lo, hi).to_array().astype(np.int64), want)
    assert range_index.between_cardinality(lo, hi) == want.size
    assert range_index.lt_cardinality(lo) == int((rows < lo).sum())
    assert range_index.gte_cardinality(hi) == int((rows >= hi).sum())
    assert range_index.eq_cardinality(int(rows[0])) == int((rows == rows[0]).sum())


def test_context_prefilter(range_index, rows):
    context = RoaringBitmap(np.arange(0, rows.size, 2, dtype=np.uint32))
    got = range_index.lte(500_000, context)
    rids = np.arange(rows.size, dtype=np.int64)
    want = set(rids[rows <= 500_000].tolist()) & set(range(0, rows.size, 2))
    assert set(got.to_array().tolist()) == want
    # neq with context never returns rows outside the universe
    ctx2 = RoaringBitmap([0, 1, rows.size + 100])
    got2 = range_index.neq(int(rows[0]), ctx2)
    assert rows.size + 100 not in set(got2.to_array().tolist())


def test_serialize_map_roundtrip(range_index, rows):
    data = range_index.serialize()
    assert len(data) == range_index.serialized_size_in_bytes()
    mapped = RangeBitmap.map(data)
    assert mapped.row_count == rows.size
    q = 123_456
    assert np.array_equal(
        mapped.lte(q).to_array(), range_index.lte(q).to_array()
    )
    assert mapped.serialize() == data


def test_appender_point_adds():
    app = RangeBitmap.appender(100)
    for v in [5, 0, 100, 42]:
        app.add(v)
    rb = app.build()
    assert rb.row_count == 4
    assert rb.eq(5).to_array().tolist() == [0]
    assert rb.lte(42).to_array().tolist() == [1, 3] or set(
        rb.lte(42).to_array().tolist()
    ) == {0, 1, 3}
    with pytest.raises(ValueError):
        app.add(101)
    with pytest.raises(ValueError):
        app.add(-1)


def test_appender_chunk_boundary():
    """Values crossing the 2^16-row internal flush boundary."""
    n = (1 << 16) + 1000
    app = RangeBitmap.appender(2)
    vals = np.arange(n) % 3
    app.add_many(vals)
    rb = app.build()
    assert rb.row_count == n
    assert rb.eq_cardinality(2) == int((vals == 2).sum())
    assert rb.lt_cardinality(2) == int((vals < 2).sum())


def test_large_values_64bit():
    app = RangeBitmap.appender((1 << 62))
    vals = [0, 1 << 40, (1 << 62) - 1, 1 << 62, 12345]
    for v in vals:
        app.add(v)
    rb = app.build()
    assert rb.gte(1 << 40).get_cardinality() == 3
    assert rb.eq(1 << 62).to_array().tolist() == [3]
    assert rb.lt(1 << 62).get_cardinality() == 4


def test_map_rejects_garbage():
    with pytest.raises(InvalidRoaringFormat):
        RangeBitmap.map(b"\x00" * 20)
    with pytest.raises(InvalidRoaringFormat):
        RangeBitmap.map(b"\x0d\xf0\x02\x05")  # right cookie, truncated


def test_empty_appender():
    rb = RangeBitmap.appender(10).build()
    assert rb.row_count == 0
    assert rb.lte(10).is_empty()
    assert rb.neq(5).is_empty()
    data = rb.serialize()
    assert RangeBitmap.map(data).row_count == 0


def test_between_end_beyond_bit_depth():
    """Oversized upper bounds must not truncate (code-review regression)."""
    app = RangeBitmap.appender(5)
    for v in [0, 1, 2, 3, 4, 5]:
        app.add(v)
    rb = app.build()
    assert rb.between(2, 100).to_array().tolist() == [2, 3, 4, 5]
    assert rb.between_cardinality(2, 1 << 40) == 4


def test_interleaved_add_and_add_many():
    """Row-id order preserved across mixed add()/add_many() (code-review
    regression)."""
    app = RangeBitmap.appender(10)
    app.add(7)
    app.add_many([1, 2])
    app.add(9)
    rb = app.build()
    assert rb.eq(7).to_array().tolist() == [0]
    assert rb.eq(1).to_array().tolist() == [1]
    assert rb.eq(9).to_array().tolist() == [3]


def test_full_64bit_values():
    """No 2^63 clamp: thresholds above 2^63 behave (code-review regression)."""
    app = RangeBitmap.appender((1 << 64) - 1)
    app.add((1 << 64) - 1)
    app.add(5)
    rb = app.build()
    assert rb.lt(1 << 63).to_array().tolist() == [1]
    assert rb.eq((1 << 64) - 1).to_array().tolist() == [0]
    assert rb.gte(1 << 63).to_array().tolist() == [0]


def test_appender_bounded_memory_10m_rows():
    """The appender must hold at most one 2^16-row raw chunk: peak transient
    memory on a 10M-row ingest stays O(chunk), not O(rows)
    (RangeBitmap.Appender per-2^16-rid flush, RangeBitmap.java:1378-1520)."""
    import tracemalloc

    n = 10_000_000
    app = RangeBitmap.appender((1 << 20) - 1)
    batch = np.arange(1 << 16, dtype=np.uint64) % 1000  # compresses to runs/arrays
    tracemalloc.start()
    base = tracemalloc.get_traced_memory()[0]
    done = 0
    while done < n:
        m = min(1 << 16, n - done)
        app.add_many(batch[:m])
        done += m
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # raw values would be 80 MB; one chunk is 0.5 MB. Allow generous slack
    # for the compressed containers + numpy transients.
    assert peak - base < 24 * 2**20, f"peak transient {peak - base} bytes"
    # structural bound: the raw buffer is a single fixed chunk
    assert app._buf.nbytes == (1 << 16) * 8
    rb = app.build()
    assert rb.row_count == n
    per_chunk = int((batch == 999).sum())
    tail = int((batch[: n % (1 << 16)] == 999).sum())
    assert rb.eq_cardinality(999) == per_chunk * (n // (1 << 16)) + tail


def test_context_skips_untouched_chunks():
    """A context confined to two chunks must evaluate exactly those two
    chunks (context-masked skipping, RangeBitmap.java:551-620)."""
    n_chunks = 20
    app = RangeBitmap.appender(999)
    vals = (np.arange(n_chunks << 16, dtype=np.uint64) * 7) % 1000
    app.add_many(vals)
    rb = app.build()
    rids = [(5 << 16) + 3, (7 << 16) + 10, (7 << 16) + 11]
    ctx = RoaringBitmap(np.array(rids, dtype=np.uint32))
    before = rb.chunks_evaluated
    got = rb.between(10, 500, context=ctx)
    assert rb.chunks_evaluated - before == 2  # chunks 5 and 7 only
    want = {r for r in rids if 10 <= int(vals[r]) <= 500}
    assert set(got.to_array().tolist()) == want
    # all query ops honor the context mask
    for name, pred in [
        ("lt", vals < 300), ("lte", vals <= 300), ("gt", vals > 300),
        ("gte", vals >= 300), ("eq", vals == int(vals[rids[0]])),
        ("neq", vals != int(vals[rids[0]])),
    ]:
        q = 300 if name not in ("eq", "neq") else int(vals[rids[0]])
        got = getattr(rb, name)(q, ctx)
        want = {r for r in rids if pred[r]}
        assert set(got.to_array().tolist()) == want, name


def test_map_is_lazy_and_serialize_is_zero_decode():
    """map() must not decode slice payloads; serialize() of a mapped index
    re-emits stored payload bytes (RangeBitmap.map, RangeBitmap.java:66-96)."""
    app = RangeBitmap.appender(10_000)
    rng = np.random.default_rng(9)
    app.add_many(rng.integers(0, 10_000, size=200_000, dtype=np.uint64))
    data = app.serialize()
    mapped = RangeBitmap.map(data)
    assert all(s is None for s in mapped._slices), "map() decoded a slice"
    assert mapped.serialize() == data
    assert all(s is None for s in mapped._slices), "serialize() decoded a slice"
    # a context query touches containers zero-copy; results match the built index
    ctx = RoaringBitmap(np.arange(0, 200_000, 3, dtype=np.uint32))
    a = mapped.between(100, 5_000, context=ctx)
    b = app.build().between(100, 5_000, context=ctx)
    assert a == b
    # context-free query on a mapped index walks chunks lazily and agrees too
    assert mapped.gte(9_000) == app.build().gte(9_000)


def test_mapped_contextfree_equals_built_all_ops():
    """Differential: mapped (zero-copy slice views through the BSI engine)
    vs built, plus the streaming chunk walk vs the fused engine on the same
    queries (two independent evaluators must agree)."""
    from roaringbitmap_tpu.models.bsi import Operation

    app = RangeBitmap.appender(1 << 20)
    rng = np.random.default_rng(11)
    vals = rng.integers(0, 1 << 20, size=150_000, dtype=np.uint64)
    app.add_many(vals)
    built = app.build()
    mapped = RangeBitmap.map(built.serialize())
    ops = {
        "lt": Operation.LT, "lte": Operation.LE, "gt": Operation.GT,
        "gte": Operation.GE, "eq": Operation.EQ, "neq": Operation.NEQ,
    }
    for q in (0, 1, 12_345, (1 << 19), (1 << 20)):
        for name, op in ops.items():
            want = getattr(built, name)(q)
            assert getattr(mapped, name)(q) == want, (name, q)
            # the chunk walk is a second, independent evaluator
            assert built._chunk_walk(op, q, 0, None) == want, (name, q)
    assert mapped.between(1000, 500_000) == built.between(1000, 500_000)
    assert (
        built._chunk_walk(Operation.RANGE, 1000, 500_000, None)
        == built.between(1000, 500_000)
    )
    # a pickled (mapped) index keeps the batch engine for context-free
    # queries: the BSI view exists after one query (code-review regression)
    assert mapped._bsi is not None


def test_appender_usable_after_build():
    """build()/serialize() must not poison the appender: build, keep
    appending, build again (code-review regression)."""
    app = RangeBitmap.appender(100)
    app.add(1)
    rb1 = app.build()
    assert rb1.row_count == 1 and rb1.eq(1).to_array().tolist() == [0]
    app.add(2)
    rb2 = app.build()
    assert rb2.row_count == 2
    assert rb2.eq(2).to_array().tolist() == [1]
    # the first build is sealed: later appends must not leak into it
    assert rb1.row_count == 1
    assert rb1.eq(2).is_empty()
    data = app.serialize()  # serialize is also non-destructive
    app.add(3)
    rb3 = app.build()
    assert rb3.row_count == 3 and rb3.eq(3).to_array().tolist() == [2]
    assert RangeBitmap.map(data).row_count == 2
    # across a chunk boundary: sealed indexes stay frozen
    app2 = RangeBitmap.appender(7)
    app2.add_many(np.full(1 << 16, 5, dtype=np.uint64))
    first = app2.build()
    app2.add_many(np.full(100, 6, dtype=np.uint64))
    second = app2.build()
    assert first.row_count == 1 << 16 and first.eq_cardinality(6) == 0
    assert second.eq_cardinality(6) == 100


def test_cardinality_overloads_count_only():
    """*_cardinality == materialized count for built and mapped indexes,
    with and without context (context path walks chunks; context-free path
    is the count-only BSI fetch)."""
    rng = np.random.default_rng(31)
    vals = rng.integers(0, 1 << 20, size=200_000)
    ap = RangeBitmap.appender(int(vals.max()))
    ap.add_many(vals)
    built = ap.build()
    mapped = RangeBitmap.map(built.serialize())
    med = int(np.median(vals))
    ctx = RoaringBitmap(np.arange(0, 200_000, 3, dtype=np.uint32))
    for rb in (built, mapped):
        for name, args in (
            ("lt", (med,)), ("lte", (med,)), ("gt", (med,)), ("gte", (med,)),
            ("eq", (int(vals[7]),)), ("neq", (int(vals[7]),)),
            ("between", (med // 2, med + med // 2)),
        ):
            for context in (None, ctx):
                want = getattr(rb, name)(*args, context).get_cardinality()
                got = getattr(rb, f"{name}_cardinality")(*args, context)
                assert got == want, (name, context is not None, rb is mapped)
    with pytest.raises(ValueError):
        built.lt_cardinality(-1, ctx)
    with pytest.raises(ValueError):
        built.lt_cardinality(-1)


# ---------------------------------------------------------------------------
# Reference wire-format parity (VERDICT r3 #6): golden bytes hand-constructed
# from the spec in RangeBitmap.java:1483-1520 (serialize) / :66-96 (map),
# independently of the encoder under test.
# ---------------------------------------------------------------------------


def _java_golden_small():
    """values [5, 0, 7, 2, 3], maxValue 7 -> sliceCount 3, one chunk.

    Derived by hand from the Java appender: add() sets slice bits from
    ``~value & rangeMask`` (RangeBitmap.java:1510), i.e. slice i holds rid
    iff bit i of the value is 0:
      slice0 (bit0==0): values 0,2       -> rids {1, 3}
      slice1 (bit1==0): values 5,0       -> rids {0, 1}
      slice2 (bit2==0): values 0,2,3     -> rids {1, 3, 4}
    Slices < 5 grow as BitmapContainers (containerForSlice,
    RangeBitmap.java:1608-1613) whose runOptimize only ever converts to a
    RUN (BitmapContainer.java:1227-1245; 2+4*nruns < 8192 here), so the
    stream is type=1 (RUN, :27), u16 nruns, (start, length) u16 pairs —
    even where an array would be smaller.
    Header (:1488-1494): u16 0xF00D, u8 base 2, u8 sliceCount 3,
    u16 maxKey 1, u32 maxRid 5; then maxKey * 1 mask bytes (:1495-1497,
    bytesPerMask = (3+7)>>3 = 1) -- chunk 0 has containers for slices
    0,1,2 -> 0b111."""
    import struct

    header = struct.pack("<HBBHI", 0xF00D, 2, 3, 1, 5)
    masks = b"\x07"
    run = lambda pairs: struct.pack("<BH", 1, len(pairs)) + b"".join(
        struct.pack("<HH", s, l) for s, l in pairs
    )
    stream = run([(1, 0), (3, 0)]) + run([(0, 1)]) + run([(1, 0), (3, 1)])
    return header + masks + stream


def test_java_format_golden_bytes():
    app = RangeBitmap.appender(7)
    app.add_many([5, 0, 7, 2, 3])
    got = app.build().serialize()
    assert got == _java_golden_small(), (got.hex(), _java_golden_small().hex())


def test_java_format_golden_high_slice_array():
    """Slices >= 5 grow as RunContainers whose toEfficientContainer picks
    the smallest form (RunContainer.java) — scattered rids become an ARRAY
    there, while the same pattern in slices < 5 would stay RUN.

    values [0, 32, 0, 32, 0], maxValue 63 -> 6 slices:
      slices 0-4: bit==0 for every rid -> one full run (0, 4) each -> RUN
      slice 5 (bit5==0): rids {0, 2, 4} -> 3 runs (14 B) > array (8 B)
        -> type=2 ARRAY, u16 card 3, u16 values."""
    import struct

    app = RangeBitmap.appender(63)
    app.add_many([0, 32, 0, 32, 0])
    got = app.build().serialize()
    header = struct.pack("<HBBHI", 0xF00D, 2, 6, 1, 5)
    masks = b"\x3f"
    full_run = struct.pack("<BHHH", 1, 1, 0, 4)
    arr5 = struct.pack("<BH", 2, 3) + struct.pack("<HHH", 0, 2, 4)
    want = header + masks + full_run * 5 + arr5
    assert got == want, (got.hex(), want.hex())


def test_java_format_golden_map():
    """Mapping the hand-constructed reference bytes must answer queries
    correctly (proves the decoder against the spec, not just against the
    encoder)."""
    mapped = RangeBitmap.map(_java_golden_small())
    values = np.array([5, 0, 7, 2, 3], dtype=np.int64)
    rids = np.arange(values.size, dtype=np.int64)
    assert mapped.row_count == 5
    for q in range(9):
        assert np.array_equal(mapped.lte(q).to_array().astype(np.int64), rids[values <= q]), q
        assert np.array_equal(mapped.gt(q).to_array().astype(np.int64), rids[values > q]), q
        assert np.array_equal(mapped.eq(q).to_array().astype(np.int64), rids[values == q]), q


def test_java_format_multichunk_roundtrip(rng):
    """Multi-chunk (3 chunks incl. a partial tail), with runs of equal
    values (bitmap/run containers) and a stretch of all-bits-set values
    (rangeMask) whose complement is empty -> mask bit unset in that chunk."""
    n = 150_000
    vals = rng.integers(0, 1 << 20, size=n, dtype=np.uint64)
    vals[:40_000] = 123_456  # long runs in every slice
    vals[70_000:80_000] = (1 << 20) - 1  # ~value == 0: no slice containers
    app = RangeBitmap.appender((1 << 20) - 1)
    app.add_many(vals)
    built = app.build()
    data = built.serialize()
    mapped = RangeBitmap.map(data)
    assert mapped.serialize() == data  # mapped pass-through, no decode
    rids = np.arange(n, dtype=np.int64)
    for q in (0, 123_456, 500_000, (1 << 20) - 1):
        assert np.array_equal(mapped.lte(q).to_array().astype(np.int64), rids[vals <= q]), q
        assert np.array_equal(
            mapped.between(q // 2, q).to_array().astype(np.int64),
            rids[(vals >= q // 2) & (vals <= q)],
        ), q
    ctx = RoaringBitmap(np.arange(0, n, 7, dtype=np.uint32))
    got = mapped.lte(123_456, ctx)
    want = set(rids[vals <= 123_456].tolist()) & set(range(0, n, 7))
    assert set(got.to_array().tolist()) == want


def test_native_form_still_readable(range_index, rows):
    """The round-3 native layout stays readable and is re-emitted by
    serialize(form='native'); both forms answer identically."""
    native = range_index.serialize(form="native")
    java = range_index.serialize(form="java")
    assert native != java
    m_native, m_java = RangeBitmap.map(native), RangeBitmap.map(java)
    assert m_native._jmap is None and m_java._jmap is not None
    q = 321_987
    want = range_index.lte(q).to_array()
    assert np.array_equal(m_native.lte(q).to_array(), want)
    assert np.array_equal(m_java.lte(q).to_array(), want)
    # cross-encode: native-mapped -> java bytes -> map -> same answers
    rej = RangeBitmap.map(m_native.serialize(form="java"))
    assert np.array_equal(rej.lte(q).to_array(), want)
    assert m_native.serialize() == native  # mapped pass-through keeps its form


def test_native_maxvalue_zero_not_misdetected():
    """Code-review r4 repro: a native-form buffer with maxValue == 0 must
    not be mistaken for an empty reference-format map (its first 10 bytes
    alone parse as one; the exact-extent rule rejects it)."""
    app = RangeBitmap.appender(0)
    app.add_many([0, 0, 0])
    built = app.build()
    native = built.serialize(form="native")
    mapped = RangeBitmap.map(native)
    assert mapped._jmap is None and mapped.row_count == 3
    assert np.array_equal(mapped.lte(0).to_array(), np.array([0, 1, 2], dtype=np.uint32))
    # the reference form of the same index round-trips too
    remapped = RangeBitmap.map(built.serialize(form="java"))
    assert remapped.row_count == 3
    assert np.array_equal(remapped.lte(0).to_array(), np.array([0, 1, 2], dtype=np.uint32))


def test_mapped_java_native_size(range_index):
    """Code-review r4 repro: serialized_size_in_bytes(form='native') on a
    reference-format map must materialize slices, not crash."""
    mapped = RangeBitmap.map(range_index.serialize())
    assert mapped._jmap is not None
    assert mapped.serialized_size_in_bytes(form="native") == len(
        mapped.serialize(form="native")
    )


class TestJavaFormatAdversarial:
    """Hostile reference-format payloads must raise InvalidRoaringFormat
    (or fall through to a native-parse rejection), never crash or return
    corrupt data — the buffer-parse discipline of the crashproneinput
    corpus applied to the round-4 parser (_JavaMap)."""

    @staticmethod
    def _valid():
        app = RangeBitmap.appender(63)
        app.add_many([0, 32, 5, 63, 17])
        return bytearray(app.build().serialize())

    def _expect_reject(self, data):
        with pytest.raises(InvalidRoaringFormat):
            RangeBitmap.map(bytes(data))

    def test_bad_container_type(self):
        data = self._valid()
        # first container type byte sits right after the 10B header + 1 mask byte
        data[11] = 7
        self._expect_reject(data)

    def test_runaway_run_count(self):
        data = self._valid()
        t = data[11]
        assert t == 1  # RUN from the bitmap-grown slice
        data[12:14] = (60_000).to_bytes(2, "little")  # nruns far past the buffer
        self._expect_reject(data)

    def test_mask_claims_absent_container(self):
        data = self._valid()
        data[10] |= 0x40  # slice 6 flagged but sliceCount is 6 (bits 0-5)
        self._expect_reject(data)

    def test_truncated_stream_and_masks(self):
        data = self._valid()
        self._expect_reject(data[:9])   # inside the header
        self._expect_reject(data[:10])  # header only, masks missing
        self._expect_reject(data[:15])  # inside the first container
        self._expect_reject(data[:-1])  # one byte short

    def test_trailing_garbage_rejected(self):
        # exact-extent contract: java parse rejects, native parse rejects too
        self._expect_reject(self._valid() + b"\x00")

    def test_chunk_count_inconsistent(self):
        data = self._valid()
        data[4:6] = (3).to_bytes(2, "little")  # maxKey=3 but maxRid says 1 chunk
        self._expect_reject(data)

    def test_overlapping_run_payload_rejected_on_decode(self):
        """Hand-crafted container with overlapping runs: map() succeeds
        (the directory walk is lazy and only sizes containers), and the
        hostile payload is rejected when first decoded by a query — the
        same lazy contract as the mapped-bitmap path."""
        import struct

        header = struct.pack("<HBBHI", 0xF00D, 2, 1, 1, 5)
        masks = bytes([0b1])
        # runs (0, 3) then (2, 1): second start <= first end
        bad_run = struct.pack("<BHHHHH", 1, 2, 0, 3, 2, 1)
        mapped = RangeBitmap.map(header + masks + bad_run)
        with pytest.raises(InvalidRoaringFormat):
            mapped.lte_cardinality(0)

    def test_fuzzed_header_mutations(self):
        rng = np.random.default_rng(0xBAD)
        base = self._valid()
        for _ in range(300):
            data = bytearray(base)
            for _ in range(rng.integers(1, 4)):
                data[rng.integers(0, len(data))] = rng.integers(0, 256)
            try:
                m = RangeBitmap.map(bytes(data))
                # parse may legitimately succeed; results must stay sane
                m.lte_cardinality(63)
            except InvalidRoaringFormat:
                pass


def test_cardinality_many_matches_single():
    """Batched threshold counts == per-threshold *_cardinality on every
    query family, incl. context-masked (chunk-walk loop) and a mapped
    index (zero-copy slices feeding the same batched engine)."""
    import numpy as np

    from roaringbitmap_tpu import RangeBitmap, RoaringBitmap

    rng = np.random.default_rng(11)
    vals = rng.integers(0, 1 << 20, size=150_000)
    ap = RangeBitmap.appender(int(vals.max()))
    for v in vals.tolist():
        ap.add(v)
    rb = ap.build()
    qs = np.quantile(vals, [0.1, 0.5, 0.9]).astype(np.int64).tolist() + [0, 1 << 30]
    ctx = RoaringBitmap(
        rng.choice(vals.size, size=vals.size // 10, replace=False).astype(np.uint32)
    )
    for many, single in (
        (rb.lt_cardinality_many, rb.lt_cardinality),
        (rb.lte_cardinality_many, rb.lte_cardinality),
        (rb.gt_cardinality_many, rb.gt_cardinality),
        (rb.gte_cardinality_many, rb.gte_cardinality),
        (rb.eq_cardinality_many, rb.eq_cardinality),
        (rb.neq_cardinality_many, rb.neq_cardinality),
    ):
        for context in (None, ctx):
            got = many(qs, context=context)
            want = [single(int(v), context=context) for v in qs]
            assert got.tolist() == want, (single.__name__, context is not None)
    los = qs
    his = [q + 5000 for q in qs]
    assert rb.between_cardinality_many(los, his).tolist() == [
        rb.between_cardinality(a, b) for a, b in zip(los, his)
    ]
    # mapped index answers the same batch
    mapped = RangeBitmap.map(rb.serialize())
    assert np.array_equal(mapped.gte_cardinality_many(qs), rb.gte_cardinality_many(qs))
    # unsigned validation
    import pytest

    with pytest.raises(ValueError):
        rb.lt_cardinality_many([-1])


def test_cardinality_many_range_validation_with_context():
    """Context path enforces the same RANGE ends contract as the
    context-free engine (code-review r4: zip() was silently truncating)."""
    import numpy as np
    import pytest

    from roaringbitmap_tpu import RangeBitmap, RoaringBitmap

    ap = RangeBitmap.appender(1000)
    for v in range(100):
        ap.add(v * 7 % 1000)
    rb = ap.build()
    ctx = RoaringBitmap(np.arange(50, dtype=np.uint32))
    for context in (None, ctx):
        with pytest.raises(ValueError):
            rb.between_cardinality_many([1, 2, 3], None, context=context)
        with pytest.raises(ValueError):
            rb.between_cardinality_many([1, 2, 3], [5], context=context)
