"""RangeBitmap differential tests (reference oracle: RangeBitmapTest.java)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.range_bitmap import RangeBitmap, RangeBitmapAppender
from roaringbitmap_tpu.serialization import InvalidRoaringFormat


@pytest.fixture
def rows(rng):
    return rng.integers(0, 1_000_000, size=150_000, dtype=np.uint64)


@pytest.fixture
def range_index(rows):
    app = RangeBitmap.appender(1_000_000)
    app.add_many(rows)
    return app.build()


def test_build_and_row_count(range_index, rows):
    assert range_index.row_count == rows.size


@pytest.mark.parametrize("q", [0, 1, 499_999, 999_999, 1_000_000])
def test_all_query_ops(range_index, rows, q):
    rids = np.arange(rows.size, dtype=np.int64)
    assert np.array_equal(range_index.lt(q).to_array().astype(np.int64), rids[rows < q])
    assert np.array_equal(range_index.lte(q).to_array().astype(np.int64), rids[rows <= q])
    assert np.array_equal(range_index.gt(q).to_array().astype(np.int64), rids[rows > q])
    assert np.array_equal(range_index.gte(q).to_array().astype(np.int64), rids[rows >= q])
    assert np.array_equal(range_index.eq(q).to_array().astype(np.int64), rids[rows == q])
    assert np.array_equal(range_index.neq(q).to_array().astype(np.int64), rids[rows != q])


def test_between_and_cardinalities(range_index, rows):
    rids = np.arange(rows.size, dtype=np.int64)
    lo, hi = 250_000, 750_000
    want = rids[(rows >= lo) & (rows <= hi)]
    assert np.array_equal(range_index.between(lo, hi).to_array().astype(np.int64), want)
    assert range_index.between_cardinality(lo, hi) == want.size
    assert range_index.lt_cardinality(lo) == int((rows < lo).sum())
    assert range_index.gte_cardinality(hi) == int((rows >= hi).sum())
    assert range_index.eq_cardinality(int(rows[0])) == int((rows == rows[0]).sum())


def test_context_prefilter(range_index, rows):
    context = RoaringBitmap(np.arange(0, rows.size, 2, dtype=np.uint32))
    got = range_index.lte(500_000, context)
    rids = np.arange(rows.size, dtype=np.int64)
    want = set(rids[rows <= 500_000].tolist()) & set(range(0, rows.size, 2))
    assert set(got.to_array().tolist()) == want
    # neq with context never returns rows outside the universe
    ctx2 = RoaringBitmap([0, 1, rows.size + 100])
    got2 = range_index.neq(int(rows[0]), ctx2)
    assert rows.size + 100 not in set(got2.to_array().tolist())


def test_serialize_map_roundtrip(range_index, rows):
    data = range_index.serialize()
    assert len(data) == range_index.serialized_size_in_bytes()
    mapped = RangeBitmap.map(data)
    assert mapped.row_count == rows.size
    q = 123_456
    assert np.array_equal(
        mapped.lte(q).to_array(), range_index.lte(q).to_array()
    )
    assert mapped.serialize() == data


def test_appender_point_adds():
    app = RangeBitmap.appender(100)
    for v in [5, 0, 100, 42]:
        app.add(v)
    rb = app.build()
    assert rb.row_count == 4
    assert rb.eq(5).to_array().tolist() == [0]
    assert rb.lte(42).to_array().tolist() == [1, 3] or set(
        rb.lte(42).to_array().tolist()
    ) == {0, 1, 3}
    with pytest.raises(ValueError):
        app.add(101)
    with pytest.raises(ValueError):
        app.add(-1)


def test_appender_chunk_boundary():
    """Values crossing the 2^16-row internal flush boundary."""
    n = (1 << 16) + 1000
    app = RangeBitmap.appender(2)
    vals = np.arange(n) % 3
    app.add_many(vals)
    rb = app.build()
    assert rb.row_count == n
    assert rb.eq_cardinality(2) == int((vals == 2).sum())
    assert rb.lt_cardinality(2) == int((vals < 2).sum())


def test_large_values_64bit():
    app = RangeBitmap.appender((1 << 62))
    vals = [0, 1 << 40, (1 << 62) - 1, 1 << 62, 12345]
    for v in vals:
        app.add(v)
    rb = app.build()
    assert rb.gte(1 << 40).get_cardinality() == 3
    assert rb.eq(1 << 62).to_array().tolist() == [3]
    assert rb.lt(1 << 62).get_cardinality() == 4


def test_map_rejects_garbage():
    with pytest.raises(InvalidRoaringFormat):
        RangeBitmap.map(b"\x00" * 20)
    with pytest.raises(InvalidRoaringFormat):
        RangeBitmap.map(b"\x0d\xf0\x02\x05")  # right cookie, truncated


def test_empty_appender():
    rb = RangeBitmap.appender(10).build()
    assert rb.row_count == 0
    assert rb.lte(10).is_empty()
    assert rb.neq(5).is_empty()
    data = rb.serialize()
    assert RangeBitmap.map(data).row_count == 0


def test_between_end_beyond_bit_depth():
    """Oversized upper bounds must not truncate (code-review regression)."""
    app = RangeBitmap.appender(5)
    for v in [0, 1, 2, 3, 4, 5]:
        app.add(v)
    rb = app.build()
    assert rb.between(2, 100).to_array().tolist() == [2, 3, 4, 5]
    assert rb.between_cardinality(2, 1 << 40) == 4


def test_interleaved_add_and_add_many():
    """Row-id order preserved across mixed add()/add_many() (code-review
    regression)."""
    app = RangeBitmap.appender(10)
    app.add(7)
    app.add_many([1, 2])
    app.add(9)
    rb = app.build()
    assert rb.eq(7).to_array().tolist() == [0]
    assert rb.eq(1).to_array().tolist() == [1]
    assert rb.eq(9).to_array().tolist() == [3]


def test_full_64bit_values():
    """No 2^63 clamp: thresholds above 2^63 behave (code-review regression)."""
    app = RangeBitmap.appender((1 << 64) - 1)
    app.add((1 << 64) - 1)
    app.add(5)
    rb = app.build()
    assert rb.lt(1 << 63).to_array().tolist() == [1]
    assert rb.eq((1 << 64) - 1).to_array().tolist() == [0]
    assert rb.gte(1 << 63).to_array().tolist() == [0]
