"""Serialization: round-trip, byte-identity on the reference's golden files,
adversarial input rejection (reference oracles: TestSerialization,
TestAdversarialInputs.java:18-55)."""

import os

import numpy as np
import pytest

from roaringbitmap_tpu import InvalidRoaringFormat, RoaringBitmap
from roaringbitmap_tpu.serialization import (
    maximum_serialized_size,
    serialize,
    serialized_size_in_bytes,
)

TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"
needs_testdata = pytest.mark.skipif(
    not os.path.isdir(TESTDATA), reason="reference golden files not mounted"
)


def test_roundtrip_random(random_bitmap_factory):
    for _ in range(8):
        bm, _ = random_bitmap_factory()
        data = bm.serialize()
        assert len(data) == serialized_size_in_bytes(bm)
        back = RoaringBitmap.deserialize(data)
        assert back == bm
        # serialized form of the deserialized bitmap is byte-identical
        assert back.serialize() == data


def test_roundtrip_empty():
    bm = RoaringBitmap()
    data = bm.serialize()
    assert RoaringBitmap.deserialize(data) == bm


def test_roundtrip_all_container_types():
    bm = RoaringBitmap()
    bm.add_many(range(0, 100))  # array
    bm.add_range(1 << 16, (1 << 16) + 40000)  # becomes run after optimize
    bm.add_many((np.arange(9000) * 7 % 65536 + (2 << 16)).tolist())  # bitmap
    bm.run_optimize()
    assert bm.has_run_compression()
    back = RoaringBitmap.deserialize(bm.serialize())
    assert back == bm
    assert back.serialize() == bm.serialize()


def test_run_cookie_offset_threshold():
    # < 4 containers with runs: no offset header (RoaringArray.java:25)
    bm = RoaringBitmap()
    bm.add_range(0, 70000)
    bm.run_optimize()
    assert bm.has_run_compression()
    assert bm.get_container_count() < 4
    assert RoaringBitmap.deserialize(bm.serialize()) == bm
    # >= 4 containers with runs: offset header present
    bm2 = RoaringBitmap()
    bm2.add_range(0, 5 << 16)
    bm2.run_optimize()
    assert bm2.get_container_count() >= 4
    assert RoaringBitmap.deserialize(bm2.serialize()) == bm2


@needs_testdata
@pytest.mark.parametrize("name", ["bitmapwithruns.bin", "bitmapwithoutruns.bin"])
def test_golden_files_parse_and_reserialize_identically(name):
    """The reference asserts these parse to cardinality 200100
    (TestAdversarialInputs.java:18-35); we additionally require byte-identical
    re-serialization, proving writer parity with the Java implementation."""
    with open(os.path.join(TESTDATA, name), "rb") as f:
        data = f.read()
    bm = RoaringBitmap.deserialize(data)
    assert bm.get_cardinality() == 200100
    assert serialize(bm) == data


@needs_testdata
@pytest.mark.parametrize("i", range(1, 8))
def test_adversarial_inputs_rejected(i):
    """crashproneinput*.bin must raise (TestAdversarialInputs.java:40-55)."""
    with open(os.path.join(TESTDATA, f"crashproneinput{i}.bin"), "rb") as f:
        data = f.read()
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(data)


def test_bad_cookie_rejected():
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b"\x00\x00\x00\x00")
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(b"\x01")


def test_truncated_input_rejected(random_bitmap_factory):
    bm, _ = random_bitmap_factory()
    data = bm.serialize()
    for cut in [4, len(data) // 2, len(data) - 1]:
        with pytest.raises(InvalidRoaringFormat):
            RoaringBitmap.deserialize(data[:cut])


def test_maximum_serialized_size_bound(random_bitmap_factory):
    """README.md:486-496 bound holds for arbitrary bitmaps."""
    for _ in range(5):
        bm, vals = random_bitmap_factory()
        card = bm.get_cardinality()
        universe = int(bm.last()) + 1
        assert len(bm.serialize()) <= maximum_serialized_size(card, universe)
    # and for the pathological all-dense case
    bm = RoaringBitmap.bitmap_of_range(0, 200000)
    bm.remove_run_compression()
    assert len(bm.serialize()) <= maximum_serialized_size(200000, 200000)


def test_overlapping_runs_rejected():
    """Overlapping runs corrupt value semantics; adjacency is merely
    non-canonical and stays accepted (code-review regression)."""
    import struct

    bad = (
        struct.pack("<I", 12347 | (0 << 16))
        + b"\x01"
        + struct.pack("<HH", 0, 111)
        + struct.pack("<H", 2)
        + struct.pack("<HHHH", 0, 100, 50, 10)
    )
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(bad)
    adjacent = (
        struct.pack("<I", 12347 | (0 << 16))
        + b"\x01"
        + struct.pack("<HH", 0, 3)
        + struct.pack("<H", 2)
        + struct.pack("<HHHH", 0, 1, 2, 1)
    )
    assert RoaringBitmap.deserialize(adjacent).get_cardinality() == 4


def test_lying_bitmap_cardinality_rejected():
    """Descriptive-header cardinality must match the payload popcount
    (code-review regression)."""
    import struct

    words = np.zeros(1024, dtype="<u8")
    words[0] = 0x3FF
    payload = (
        struct.pack("<II", 12346, 1)
        + struct.pack("<HH", 0, 4999)
        + struct.pack("<I", 16)
        + words.tobytes()
    )
    with pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize(payload)


def test_stream_serialize_roundtrip(tmp_path):
    """serialize_into/deserialize_from (the DataOutput/DataInput overloads):
    consecutive bitmaps stream back-to-back through one file."""
    import io

    bms = [
        RoaringBitmap([1, 2, 3]),
        RoaringBitmap(np.arange(100_000, dtype=np.uint32)),
        RoaringBitmap([7]),
    ]
    bms[1].run_optimize()
    buf = io.BytesIO()
    written = [b.serialize_into(buf) for b in bms]
    assert buf.tell() == sum(written)
    buf.seek(0)
    back = [RoaringBitmap.deserialize_from(buf) for _ in bms]
    assert back == bms
    assert buf.tell() == sum(written)  # consumed exactly, no overread
    # file-backed too
    path = tmp_path / "bitmaps.bin"
    with open(path, "wb") as f:
        for b in bms:
            b.serialize_into(f)
    with open(path, "rb") as f:
        assert [RoaringBitmap.deserialize_from(f) for _ in bms] == bms

    # forward-only: non-seekable, SHORT-READING sources (raw sockets/pipes
    # may return fewer bytes than asked per read) must work
    class NoSeekShortReads:
        def __init__(self, data):
            self._b = io.BytesIO(data)

        def read(self, n):
            return self._b.read(min(n, 7))  # pathological 7-byte segments

    src = NoSeekShortReads(b"".join(b.serialize() for b in bms))
    assert [RoaringBitmap.deserialize_from(src) for _ in bms] == bms

    # classmethod: subclasses deserialize to their own type
    from roaringbitmap_tpu import MutableRoaringBitmap

    buf2 = io.BytesIO(bms[0].serialize())
    m = MutableRoaringBitmap.deserialize_from(buf2)
    assert isinstance(m, MutableRoaringBitmap) and m == bms[0]

    # truncated stream fails cleanly
    import pytest as _pytest

    from roaringbitmap_tpu import InvalidRoaringFormat

    blob = bms[1].serialize()
    with _pytest.raises(InvalidRoaringFormat):
        RoaringBitmap.deserialize_from(io.BytesIO(blob[: len(blob) - 3]))
