"""Resident pack cache (ISSUE 4): warm hits, incremental delta repack,
byte-budget LRU eviction, pinning, cache-aware close, clone identity,
BSI/query unification, and the lock-order hammer.

The acceptance claims are asserted the way production observes them — via
the ``rb_tpu_pack_cache_*`` registry counters and the
``store.pack_rows_host`` op-timer count (a "host pack" is exactly one
observation of that timer).
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from roaringbitmap_tpu import observe
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.parallel.aggregation import FastAggregation as FA


def _bm(rng, n=2000, spread=1 << 18):
    return RoaringBitmap(
        np.sort(rng.choice(spread, size=n, replace=False)).astype(np.uint32)
    )


def _working_set(seed=7, k=5):
    rng = np.random.default_rng(seed)
    return [_bm(rng) for _ in range(k)]


def _host_pack_count() -> int:
    """Observations of the store.pack_rows_host op timer — one per host
    pack, the quantity the warm path must hold at zero."""
    h = observe.REGISTRY.get(observe.HOST_OP_SECONDS)
    if h is None:
        return 0
    st = h.get(("store.pack_rows_host",))
    return 0 if st is None else st["count"]


def _agg_counts():
    hits = observe.REGISTRY.get(observe.PACK_CACHE_HITS_TOTAL)
    misses = observe.REGISTRY.get(observe.PACK_CACHE_MISSES_TOTAL)
    delta = observe.REGISTRY.get(observe.PACK_CACHE_DELTA_ROWS_TOTAL)
    return (
        hits.get(("agg",)) if hits else 0,
        misses.get(("agg",)) if misses else 0,
        delta.get(("agg",)) if delta else 0,
    )


# ---------------------------------------------------------------------------
# warm hits: zero host packs after the first call
# ---------------------------------------------------------------------------


def test_repeated_wide_or_zero_host_packs():
    bms = _working_set(seed=1)
    want = FA.naive_or(*bms)
    assert FA.or_(*bms, mode="device") == want
    h0, m0, _ = _agg_counts()
    packs0 = _host_pack_count()
    for _ in range(3):
        assert FA.or_(*bms, mode="device") == want
    h1, m1, _ = _agg_counts()
    assert h1 == h0 + 3, "every repeat must be served resident"
    assert m1 == m0, "no repeat may pay a full pack"
    assert _host_pack_count() == packs0, "zero host packs on the warm path"


def test_or_xor_and_cardinality_share_one_entry():
    """The pack is op-independent: OR, XOR, and the cardinality-only
    engines over the same bitmaps ride one resident entry."""
    bms = _working_set(seed=2)
    FA.or_(*bms, mode="device")  # populate
    h0, m0, _ = _agg_counts()
    FA.xor(*bms, mode="device")
    FA.or_cardinality(*bms, mode="device")
    FA.xor_cardinality(*bms, mode="device")
    h1, m1, _ = _agg_counts()
    assert h1 == h0 + 3 and m1 == m0


def test_and_uses_separate_filtered_entry():
    bms = _working_set(seed=3)
    FA.or_(*bms, mode="device")
    _, m0, _ = _agg_counts()
    want = FA.naive_and(*bms)
    assert FA.and_(*bms, mode="device") == want
    _, m1, _ = _agg_counts()
    assert m1 == m0 + 1, "AND packs the key-intersection layout (own entry)"
    h0, _, _ = _agg_counts()
    assert FA.and_(*bms, mode="device") == want
    h1, _, _ = _agg_counts()
    assert h1 == h0 + 1


# ---------------------------------------------------------------------------
# incremental delta repack
# ---------------------------------------------------------------------------


def test_delta_repack_ships_o_k_rows():
    bms = _working_set(seed=4, k=8)
    want = FA.naive_or(*bms)
    assert FA.or_(*bms, mode="device") == want
    # make the flat rows device-resident so the delta has something to
    # patch (the padded layout alone never ships them on this backend)
    _ = store.packed_for(bms).device_words
    n_rows = sum(bm.high_low_container.size for bm in bms)
    k = 3
    for bm in bms[:k]:  # one container each, existing chunk keys
        hb = int(bm.high_low_container.keys[0])
        bm.add((hb << 16) | 54321)
    h0, m0, d0 = _agg_counts()
    xfer0 = observe.REGISTRY.get(observe.STORE_TRANSFER_BYTES_TOTAL).get(("pack_delta",))
    got = FA.or_(*bms, mode="device")
    assert got == FA.naive_or(*bms)
    h1, m1, d1 = _agg_counts()
    assert (h1, m1) == (h0 + 1, m0), "delta refresh counts as a hit"
    assert d1 - d0 == k, f"must re-pack exactly {k} of {n_rows} rows"
    xfer1 = observe.REGISTRY.get(observe.STORE_TRANSFER_BYTES_TOTAL).get(("pack_delta",))
    assert xfer1 - xfer0 == k * 2048 * 4, "delta ships k rows of words, not O(N)"


def test_delta_equals_full_repack_differential():
    """The fuzz-family predicate at unit scale: a mutation sequence mixing
    in-place edits with structural changes always yields a pack identical
    to a from-scratch rebuild."""
    from roaringbitmap_tpu import fuzz

    fuzz.verify_pack_cache_invariance("unit-pack-cache", iterations=25, seed=99)


def test_structural_mutation_forces_full_repack():
    bms = _working_set(seed=5)
    FA.or_(*bms, mode="device")
    bms[0].add((300 << 16) | 1)  # brand-new chunk key
    h0, m0, _ = _agg_counts()
    assert FA.or_(*bms, mode="device") == FA.naive_or(*bms)
    h1, m1, _ = _agg_counts()
    assert m1 == m0 + 1 and h1 == h0


def test_wholesale_deserialize_forces_full_repack():
    """read_into rebinds the container lists without key attribution —
    mark_all_dirty must veto the delta path."""
    from roaringbitmap_tpu.serialization import read_into

    bms = _working_set(seed=6)
    FA.or_(*bms, mode="device")
    read_into(bms[0], bms[1].serialize())
    h0, m0, _ = _agg_counts()
    assert FA.or_(*bms, mode="device") == FA.naive_or(*bms)
    h1, m1, _ = _agg_counts()
    assert m1 == m0 + 1 and h1 == h0


def test_and_intersection_change_forces_full_repack():
    rng = np.random.default_rng(11)
    # two bitmaps sharing keys 0..3; bm0 additionally holds key 9
    a = RoaringBitmap((np.arange(4000) + (0 << 16)).astype(np.uint32))
    for key in (1, 2, 3, 9):
        a.add_many(((np.arange(50) * 7) + (key << 16)).astype(np.uint32))
    b = RoaringBitmap(np.concatenate(
        [(rng.choice(1 << 16, 200, replace=False) + (k << 16)) for k in range(4)]
    ).astype(np.uint32))
    cache = store.PackCache(max_bytes=1 << 30)
    keys = store.intersect_keys([a, b])
    p1 = cache.get_packed([a, b], keys)
    # grow the intersection: b gains key 9 (already in a)
    b.add((9 << 16) | 5)
    keys2 = store.intersect_keys([a, b])
    assert keys2 != keys
    p2 = cache.get_packed([a, b], keys2)
    want = store.pack_groups(store.group_by_key([a, b], keys_filter=keys2))
    assert np.array_equal(p2.group_keys, want.group_keys)
    assert np.array_equal(p2.words, want.words)
    assert cache.stats()["misses"] == 2, "intersection change cannot delta"
    assert p1 is not p2
    cache.close()


def test_dirty_tracking_unit():
    from roaringbitmap_tpu.models.roaring_array import RoaringArray
    from roaringbitmap_tpu.models.container import ArrayContainer

    ra = RoaringArray()
    c = ArrayContainer(np.array([1, 2], dtype=np.uint16))
    ra.append(3, c)
    v0 = ra._version
    assert ra.dirty_keys_since(v0) == set()
    ra.append(7, c.clone())
    ra.set_container_at_index(0, c.clone())
    assert ra.dirty_keys_since(v0) == {3, 7}
    ra.remove_at_index(1)  # removal of key 7 is attributed too
    assert 7 in ra.dirty_keys_since(v0)
    ra.mark_all_dirty()
    assert ra.dirty_keys_since(v0) is None, "wholesale mutation -> unknowable"
    assert ra.dirty_keys_since(ra._version) == set()


# ---------------------------------------------------------------------------
# clone identity (satellite: RoaringArray.clone fingerprint semantics)
# ---------------------------------------------------------------------------


def test_clone_mutations_never_touch_parent_cache():
    bms = _working_set(seed=8)
    want = FA.naive_or(*bms)
    assert FA.or_(*bms, mode="device") == want
    clones = [bm.clone() for bm in bms]
    for cl in clones:  # hammer the clones
        cl.add(12345)
        cl.remove(int(cl.to_array()[0]))
    h0, m0, d0 = _agg_counts()
    # parent working set is untouched: exact resident hit, no delta rows
    assert FA.or_(*bms, mode="device") == want
    h1, m1, d1 = _agg_counts()
    assert (h1, m1, d1) == (h0 + 1, m0, d0)
    # and the clones never alias the parent's entry: fresh gen -> full pack
    got = FA.or_(*clones, mode="device")
    assert got == FA.naive_or(*clones)
    _, m2, _ = _agg_counts()
    assert m2 == m1 + 1


def test_clone_fingerprint_identity():
    bm = _working_set(seed=9, k=1)[0]
    cl = bm.clone()
    assert bm.fingerprint() != cl.fingerprint(), "process-unique generations"
    fp = bm.fingerprint()
    cl.add(1)
    cl.remove(int(cl.to_array()[-1]))
    assert bm.fingerprint() == fp, "clone mutations must not move the parent"


# ---------------------------------------------------------------------------
# byte-budget LRU eviction + pinning
# ---------------------------------------------------------------------------


def test_byte_budget_evicts_in_lru_order():
    sets = [_working_set(seed=20 + i, k=2) for i in range(3)]
    cache = store.PackCache(max_bytes=1 << 60)
    packs = [cache.get_packed(s) for s in sets]
    per_entry = packs[0].words.nbytes
    cache.get_packed(sets[0])  # touch set 0: set 1 becomes LRU
    cache.configure(max_bytes=int(per_entry * 2.5))  # room for two entries
    st = cache.stats()
    assert st["entries"] == 2 and st["evictions"] == 1
    keys = [("agg", "all", tuple(b.fingerprint() for b in s)) for s in sets]
    assert keys[0] in cache and keys[2] in cache and keys[1] not in cache
    evicted = observe.REGISTRY.get(observe.PACK_CACHE_EVICTED_BYTES_TOTAL)
    assert evicted.get(("agg",)) > 0
    cache.close()
    assert len(cache) == 0


def test_pinned_entries_survive_eviction():
    sets = [_working_set(seed=30 + i, k=2) for i in range(2)]
    cache = store.PackCache(max_bytes=1 << 60)
    pinned = cache.pin_packed(sets[0])
    cache.get_packed(sets[1])
    cache.configure(max_bytes=pinned.words.nbytes + 1)  # room for one
    st = cache.stats()
    assert st["pinned"] == 1
    key0 = ("agg", "all", tuple(b.fingerprint() for b in sets[0]))
    assert key0 in cache, "pinned LRU entry must be skipped by the evictor"
    cache.unpin_packed(sets[0])
    assert cache.stats()["pinned"] == 0
    cache.close()


def test_budget_counts_lazily_built_device_layouts():
    """Derived layouts (flat ship, padded blocks) are built AFTER the
    entry is stored; their bytes must flow into the cache's budget — a
    words-only weight would let real HBM run multiples past max_bytes."""
    bms = _working_set(seed=36, k=3)
    cache = store.PackCache(max_bytes=1 << 60)
    packed = cache.get_packed(bms)
    base = cache.stats()["bytes"]
    assert base == packed.words.nbytes
    _ = packed.device_words
    after_flat = cache.stats()["bytes"]
    assert after_flat == base + packed.words.nbytes
    _ = packed.padded_device(0)
    after_padded = cache.stats()["bytes"]
    assert after_padded > after_flat
    # growth past the budget triggers eviction of colder entries
    other = cache.get_packed(_working_set(seed=37, k=2))
    cache.configure(max_bytes=after_padded + other.words.nbytes - 1)
    assert cache.stats()["entries"] == 1, "layout growth must count"
    cache.close()
    assert cache.stats()["bytes"] == 0


def test_pin_is_a_refcount():
    bms = _working_set(seed=38, k=2)
    cache = store.PackCache(max_bytes=1 << 60)
    cache.pin_packed(bms)
    cache.pin_packed(bms)  # second consumer pins the same working set
    cache.unpin_packed(bms)  # first consumer releases
    cache.configure(max_bytes=1)
    assert cache.stats()["entries"] == 1, "still pinned by the second consumer"
    cache.unpin_packed(bms)
    cache.get_packed(_working_set(seed=39, k=2))  # pressure: now evictable
    key = ("agg", "all", tuple(b.fingerprint() for b in bms))
    assert key not in cache
    cache.close()


def test_unpin_survives_mutation_between_pin_and_unpin():
    """unpin must resolve the entry by identity (generations): the entry
    rekeys on every delta, so an exact-fingerprint lookup after a mutation
    would silently leak the pin forever."""
    bms = _working_set(seed=43, k=2)
    cache = store.PackCache(max_bytes=1 << 60)
    cache.pin_packed(bms)
    hb = int(bms[0].high_low_container.keys[0])
    bms[0].add((hb << 16) | 77)  # mutate between pin and unpin
    cache.unpin_packed(bms)
    assert cache.stats()["pinned"] == 0, "pin leaked across the mutation"
    cache.close()


def test_pinned_budget_does_not_thrash_new_entries():
    """When pinned bytes alone exceed the budget, a freshly stored
    unpinned entry must still survive as the anti-thrash survivor — not
    be evicted inside its own store call."""
    pinned_set = _working_set(seed=44, k=2)
    cache = store.PackCache(max_bytes=1 << 60)
    cache.pin_packed(pinned_set)
    cache.configure(max_bytes=1)  # pinned entry alone blows the budget
    bms = _working_set(seed=45, k=2)
    p1 = cache.get_packed(bms)
    p2 = cache.get_packed(bms)
    assert p1 is p2, "new unpinned entry must not be store->evict thrashed"
    assert cache.stats()["entries"] == 2
    cache.close()


def test_configure_zero_releases_everything():
    bms = _working_set(seed=46, k=2)
    cache = store.PackCache(max_bytes=1 << 60)
    packed = cache.get_packed(bms)
    _ = packed.device_words
    cache.configure(0)
    st = cache.stats()
    assert st["entries"] == 0 and st["bytes"] == 0, "disable must free HBM"
    assert getattr(packed, "_device_words", None) is None
    # and the disabled path stays functional (fresh packs)
    assert np.array_equal(cache.get_packed(bms).words, packed.words)
    cache.close()


def test_threshold_skew_fallback_leaves_no_resident_entry():
    """A too-skewed-to-pad threshold working set falls back to the CPU
    fold; its pack must not squat on the shared budget."""
    from roaringbitmap_tpu.query import kernels

    rng = np.random.default_rng(47)
    # one giant key group + a long geometric tail defeats dense padding
    bms = []
    for i in range(24):
        parts = [rng.choice(1 << 16, 300, replace=False).astype(np.uint32)]
        if i < 2:
            for key in range(1, 40):
                parts.append(
                    (rng.choice(1 << 16, 300, replace=False) + (key << 16)).astype(np.uint32)
                )
        bms.append(RoaringBitmap(np.concatenate(parts)))
    want = kernels.threshold(3, bms, mode="cpu")
    before = len(store.PACK_CACHE)
    got = kernels.threshold(3, bms, mode="device")
    assert got == want
    keys = [k for k in list(store.PACK_CACHE._entries) if k[0] == "threshold"]
    for k in keys:
        packed = store.PACK_CACHE._entries[k].value
        assert packed.padded_device(0) is not None, (
            "skew-fallback threshold pack must be discarded, not resident"
        )
    assert len(store.PACK_CACHE) <= before + 1


def test_static_fingerprint_ids_are_pinned_while_resident():
    """("static", id) keys must keep the mapped container array alive —
    a recycled id on a different immutable bitmap would be a stale hit."""
    import gc

    from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap

    bms = _working_set(seed=42, k=2)
    imm = ImmutableRoaringBitmap(bms[0].serialize())
    operands = [imm, bms[1]]
    cache = store.PackCache(max_bytes=1 << 60)
    cache.get_packed(operands)
    hlc_id = id(imm.high_low_container)
    key = ("agg", "all", tuple(b.fingerprint() for b in operands))
    e = cache._entries[key]
    assert any(id(r) == hlc_id for r in e.refs)
    del imm, operands
    gc.collect()
    assert any(id(r) == hlc_id for r in e.refs), "entry keeps the id live"
    cache.close()


def test_single_oversized_entry_is_kept_not_thrashed():
    """A working set larger than the whole budget must stay resident (the
    north-star pack alone can exceed any fixed budget) — store->evict
    thrash would turn every call into a cold pack."""
    bms = _working_set(seed=34, k=2)
    cache = store.PackCache(max_bytes=1)  # smaller than any real entry
    p1 = cache.get_packed(bms)
    p2 = cache.get_packed(bms)
    assert p1 is p2, "the only entry survives the byte budget"
    st = cache.stats()
    assert st["entries"] == 1 and st["hits"] == 1
    # a second working set still displaces it (LRU under pressure)
    other = _working_set(seed=35, k=2)
    cache.get_packed(other)
    assert cache.stats()["entries"] == 1
    cache.close()


def test_disabled_cache_always_packs_fresh():
    cache = store.PackCache(max_bytes=0)
    bms = _working_set(seed=33, k=2)
    p1 = cache.get_packed(bms)
    p2 = cache.get_packed(bms)
    assert p1 is not p2 and len(cache) == 0
    assert np.array_equal(p1.words, p2.words)
    # uncached packs are consumer-owned: close really frees
    p1.close()
    assert getattr(p1, "_device_words", None) is None


# ---------------------------------------------------------------------------
# lifetime: cache-aware close (satellite)
# ---------------------------------------------------------------------------


def test_close_while_cached_is_noop_and_eviction_really_closes():
    cache = store.PackCache(max_bytes=1 << 60)
    bms = _working_set(seed=40, k=2)
    packed = cache.get_packed(bms)
    _ = packed.device_words  # make device state resident
    packed.close()  # consumer close: the cache owns lifetime -> no-op
    assert getattr(packed, "_device_words", None) is not None
    packed.close()  # double close: still a no-op, still safe
    assert cache.get_packed(bms) is packed
    cache.close()  # the OWNER close frees for real
    assert getattr(packed, "_device_words", None) is None
    packed.close()  # double close after the real one: idempotent
    # a closed-but-alive working set stays usable (rebuilds on touch)
    assert packed.device_words is not None


def test_uncached_close_still_idempotent():
    bms = _working_set(seed=41, k=2)
    packed = store.pack_groups(store.group_by_key(bms))
    _ = packed.device_words
    packed.close()
    assert getattr(packed, "_device_words", None) is None
    packed.close()


# ---------------------------------------------------------------------------
# unified consumers: BSI + planned queries
# ---------------------------------------------------------------------------


def test_bsi_pack_rides_shared_cache():
    from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex

    rng = np.random.default_rng(50)
    cols = np.sort(rng.choice(1 << 17, size=3000, replace=False)).astype(np.uint32)
    vals = (cols.astype(np.int64) * 31) % 1000
    b = RoaringBitmapSliceIndex()
    b.set_values((cols, vals))
    want = b.compare(Operation.GE, 500, 0, None, mode="cpu")
    assert b.compare(Operation.GE, 500, 0, None, mode="device") == want
    hits = observe.REGISTRY.get(observe.PACK_CACHE_HITS_TOTAL)
    resident = observe.REGISTRY.get(observe.PACK_CACHE_RESIDENT_BYTES)
    assert resident.get(("bsi",)) > 0, "BSI tensors live in the shared budget"
    h0 = hits.get(("bsi",))
    packs0 = _host_pack_count()
    assert b.compare(Operation.LT, 200, 0, None, mode="device") == b.compare(
        Operation.LT, 200, 0, None, mode="cpu"
    )
    assert hits.get(("bsi",)) == h0 + 1, "second compare reuses the resident pack"
    assert _host_pack_count() == packs0
    # mutation re-keys: the next compare pays a miss, never a stale hit
    b.set_value(int(cols[0]), 999)
    m0 = observe.REGISTRY.get(observe.PACK_CACHE_MISSES_TOTAL).get(("bsi",))
    assert b.compare(Operation.GE, 500, 0, None, mode="device") == b.compare(
        Operation.GE, 500, 0, None, mode="cpu"
    )
    assert observe.REGISTRY.get(observe.PACK_CACHE_MISSES_TOTAL).get(("bsi",)) == m0 + 1


def test_planned_query_reuses_packs_without_result_cache():
    """ISSUE 4 acceptance for query/exec.py: repeated planned queries with
    the RESULT cache disabled still perform zero host packs on their
    leaf-level steps — the leaf fingerprints key the same resident packs
    across executions AND across structurally different queries sharing a
    subexpression."""
    from roaringbitmap_tpu.query import Q, evaluate_naive, execute

    rng = np.random.default_rng(60)
    leaves = [_bm(rng, n=3000) for _ in range(6)]
    q = Q.or_(*[Q.leaf(b) for b in leaves])
    want = evaluate_naive(q)
    assert execute(q, cache=None, mode="device") == want  # cold: pack builds
    packs0 = _host_pack_count()
    for _ in range(2):
        assert execute(q, cache=None, mode="device") == want
    assert _host_pack_count() == packs0, "warm planned query must not host-pack"
    # across queries: a different expression embedding the same wide-OR
    # reuses its aggregation pack (the top andnot step works on a fresh
    # intermediate, so only non-agg kinds may pack)
    h0, m0, _ = _agg_counts()
    q2 = Q.andnot(Q.or_(*[Q.leaf(b) for b in leaves]), Q.leaf(leaves[0]))
    assert execute(q2, cache=None, mode="device") == evaluate_naive(q2)
    h1, m1, _ = _agg_counts()
    assert m1 == m0, "shared wide-OR subexpression must not re-pack"
    assert h1 == h0 + 1


def test_planned_query_result_cache_plus_delta_repack():
    """The serving steady state: result cache ON, a leaf mutates — the
    re-execution stays correct and the leaf-level working set refreshes by
    delta repack (O(changed containers) rows), not a full rebuild."""
    from roaringbitmap_tpu.query import Q, ResultCache, evaluate_naive, execute

    rng = np.random.default_rng(62)
    # well-separated cardinalities: a one-value mutation must not reorder
    # the planner's cost-sorted operands (which would re-key the pack)
    leaves = [_bm(rng, n=1500 + 500 * i) for i in range(5)]
    q = Q.or_(*[Q.leaf(b) for b in leaves])
    cache = ResultCache(max_entries=32)
    assert execute(q, cache=cache, mode="device") == evaluate_naive(q)
    _ = store.packed_for(leaves).device_words  # flat rows resident
    packs0 = _host_pack_count()
    assert execute(q, cache=cache, mode="device") == evaluate_naive(q)
    assert _host_pack_count() == packs0, "result-cache hit: zero packs"
    hb = int(leaves[0].high_low_container.keys[0])
    leaves[0].add((hb << 16) | 4321)
    _, _, d0 = _agg_counts()
    assert execute(q, cache=cache, mode="device") == evaluate_naive(q)
    _, _, d1 = _agg_counts()
    assert d1 - d0 == 1, "one mutated container -> one delta row"


def test_andnot_kernel_pack_reuse():
    from roaringbitmap_tpu.query import kernels

    rng = np.random.default_rng(61)
    first, r1, r2 = _bm(rng), _bm(rng), _bm(rng)
    want = kernels.andnot_nway(first, r1, r2, mode="cpu")
    assert kernels.andnot_nway(first, r1, r2, mode="device") == want
    packs0 = _host_pack_count()
    assert kernels.andnot_nway(first, r1, r2, mode="device") == want
    assert kernels.andnot_nway_cardinality(
        first, r1, r2, mode="device"
    ) == want.get_cardinality()
    assert _host_pack_count() == packs0


# ---------------------------------------------------------------------------
# concurrency: hammer + lock-order witness
# ---------------------------------------------------------------------------


def test_pack_cache_hammer_threadsafe():
    """8 threads x shared working sets through one cache: every result is
    correct and the per-instance counters add up exactly."""
    sets = [_working_set(seed=70 + i, k=3) for i in range(4)]
    wants = [
        store.pack_groups(store.group_by_key(s)).words.copy() for s in sets
    ]
    cache = store.PackCache(max_bytes=1 << 60)
    errors = []
    barrier = threading.Barrier(8)

    def work(i):
        try:
            barrier.wait(timeout=10)
            for j in range(40):
                si = (i + j) % len(sets)
                got = cache.get_packed(sets[si])
                if not np.array_equal(got.words, wants[si]):
                    errors.append((i, j, si))
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(repr(e))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    st = cache.stats()
    assert st["hits"] + st["misses"] == 8 * 40
    assert st["entries"] == len(sets)
    cache.close()


def test_pack_cache_lock_joins_order_graph_cycle_free(monkeypatch):
    """The ISSUE 4 lockwitness hammer: the new pack-cache lock instrumented
    alongside the registry lock (its only nesting partner) plus the query
    caches it composes with in a serving process — concurrent aggregations,
    BSI compares, and delta repacks must witness the pack.cache ->
    observe.registry edge and keep the global acquisition graph acyclic."""
    from roaringbitmap_tpu.analysis import LockWitness
    from roaringbitmap_tpu.query import ResultCache, Q, execute

    w = LockWitness()
    reg_lock = observe.REGISTRY._lock
    for metric in (store._PACK_HITS, store._PACK_MISSES, store._PACK_DELTA_ROWS,
                   store._PACK_EVICTED_BYTES, store._PACK_RESIDENT,
                   store._TRANSFER_TOTAL, store._LAYOUT_TOTAL):
        monkeypatch.setattr(metric, "_lock", w.wrap("observe.registry", reg_lock))
    cache = store.PackCache(max_bytes=1 << 60)
    cache._lock = w.wrap("pack.cache", cache._lock)
    monkeypatch.setattr(store, "PACK_CACHE", cache)
    rcache = ResultCache(max_entries=16)
    rcache._lock = w.wrap("query.cache", rcache._lock)

    sets = [_working_set(seed=80 + i, k=3) for i in range(3)]
    wants = [FA.naive_or(*s) for s in sets]
    errors = []
    barrier = threading.Barrier(6)

    def work(i):
        try:
            barrier.wait(timeout=10)
            for j in range(12):
                si = (i + j) % len(sets)
                if FA.or_(*sets[si], mode="device") != wants[si]:
                    errors.append((i, j, si))
                if j % 4 == 0:
                    q = Q.leaf(sets[si][0]) & Q.leaf(sets[si][1])
                    execute(q, cache=rcache)
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(repr(e))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # exercise the delta path under instrumentation too
    hb = int(sets[0][0].high_low_container.keys[0])
    sets[0][0].add((hb << 16) | 4242)
    assert FA.or_(*sets[0], mode="device") == FA.naive_or(*sets[0])
    assert not errors
    assert w.acquisitions.get("pack.cache", 0) > 0
    assert ("pack.cache", "observe.registry") in w.edges
    w.assert_consistent()


# ---------------------------------------------------------------------------
# resident-gauge reconciliation after a donation-consumed buffer (ISSUE 9
# satellite): the delta path's donation-failure branches used to null the
# flat device rows WITHOUT settling their resident accounting — the next
# rebuild then re-accounted the same rows and the gauge drifted one block
# high per failed delta. The fix (_drop_flat) releases bytes with the
# buffer; this regression asserts gauge == sum of live entries across a
# full delta + failed-donation + rebuild cycle.
# ---------------------------------------------------------------------------


def test_resident_gauge_reconciles_after_failed_donation_delta():
    from roaringbitmap_tpu import robust
    from roaringbitmap_tpu.robust import faults

    gauge = observe.REGISTRY.get(observe.STORE_RESIDENT_BYTES)
    store.PACK_CACHE.close()
    store.hbm_reconciliation()  # settle any dropped test caches first
    bms = _working_set(seed=91, k=4)
    base_flat = gauge.get(("flat_rows",))
    packed = store.packed_for(bms)
    packed.device_words.block_until_ready()
    assert gauge.get(("flat_rows",)) - base_flat == packed.words_nbytes

    # a successful delta first (donation path), so the failed one below
    # patches a resident, already-delta'd buffer — the exact r10 shape
    hb = int(bms[0].high_low_container.keys[0])
    bms[0].add((hb << 16) | 901)
    assert store.packed_for(bms) is packed
    packed.device_words.block_until_ready()
    assert gauge.get(("flat_rows",)) - base_flat == packed.words_nbytes

    # now a delta whose donated scatter FAILS (transient at store.ship):
    # the flat rows drop AND their bytes settle — the gauge must return
    # to base, not carry phantom bytes for a consumed buffer
    bms[0].add((hb << 16) | 902)
    with faults.inject("store.ship", robust.TransientDeviceError, every=1):
        p2 = store.packed_for(bms)
    assert p2 is packed
    assert packed._device_words is None
    assert gauge.get(("flat_rows",)) - base_flat == 0, (
        "failed donation left phantom flat_rows bytes on the gauge"
    )

    # rebuild re-accounts exactly once (pre-fix this doubled)
    packed.device_words.block_until_ready()
    assert gauge.get(("flat_rows",)) - base_flat == packed.words_nbytes

    # and the cache-level invariant: resident gauge == entry ledger ==
    # sum of live entries (hbm_reconciliation's ledger check)
    recon = store.hbm_reconciliation()
    assert recon["ledger_drift_bytes"] == 0
    assert recon["gauge_bytes"] == recon["entry_sum_bytes"]
    # bits stayed correct through the degrade: delta == full repack
    fresh = store.pack_groups(store.group_by_key(bms))
    assert np.array_equal(packed.words, fresh.words)
    store.PACK_CACHE.close()
    del fresh  # its __del__ settles its own (uncached) flat rows
    assert gauge.get(("flat_rows",)) - base_flat == 0
