"""Structure observatory + background compaction tests (ISSUE 16): the
incremental corpus-shape ledger (O(dirty) refresh reconciling with the
full census, drift targets, accretion depth), the priced maintenance
pass (bit-identity audit, the serve.maintain fault site failing CLOSED,
compact-vs-ride pricing, the outcome join + refit), the EIGHTH cost
authority's round-trip, the two new sentinel rules firing -> actuating
a pass -> clearing, the serving-path runOptimize regression (satellite:
BitmapWriter merge + apply_merged re-select formats), the sidecar /
insights structure block, and the fuzz family 30 seed pin."""

import numpy as np
import pytest

from roaringbitmap_tpu import cost, insights, observe
from roaringbitmap_tpu.cost import compaction as compaction_cost
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.models.writer import BitmapWriter
from roaringbitmap_tpu.observe import export as obs_export
from roaringbitmap_tpu.observe import health, outcomes, sentinel
from roaringbitmap_tpu.observe import structure as structure_mod
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.robust import faults
from roaringbitmap_tpu.robust import ladder as ladder_mod
from roaringbitmap_tpu.robust.errors import TransientDeviceError
from roaringbitmap_tpu.serve import EpochStore
from roaringbitmap_tpu.serve import maintain as maintain_mod
from roaringbitmap_tpu.serve import slo

LEDGER = structure_mod.LEDGER


@pytest.fixture(autouse=True)
def _clean_state():
    """Every test starts from a clean ledger/model/fault/sentinel state
    and leaves none behind."""
    slo.reset()
    outcomes.reset()
    faults.clear()
    LEDGER.reset()
    maintain_mod.reset()
    compaction_cost.MODEL.reset()
    sentinel.SENTINEL.reset()
    ladder_mod.LADDER.reset()
    yield
    slo.reset()
    outcomes.reset()
    faults.clear()
    LEDGER.reset()
    maintain_mod.reset()
    compaction_cost.MODEL.reset()
    sentinel.SENTINEL.reset()
    ladder_mod.LADDER.reset()
    store.PACK_CACHE.close()


def _corpus(n=4, seed=3, card=1500):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(1 << 18, card, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


def _drift(corpus, lo=50000, hi=58000):
    """Append a contiguous run to every bitmap: the touched containers
    become run-compressible but stay in their mutated array/bitmap
    format until something re-runs format selection."""
    for bm in corpus:
        bm |= RoaringBitmap(np.arange(lo, hi))


def _declare(name="st-t"):
    slo.TENANTS.declare(name, quota_qps=1e6, burst=1e6)
    return name


# ---------------------------------------------------------------------------
# the incremental structure ledger
# ---------------------------------------------------------------------------


def test_ledger_incremental_refresh_reconciles_with_full_census():
    corpus = _corpus()
    LEDGER.watch("ws", corpus)
    LEDGER.refresh()
    # mutate a few keys through attributed mutators, then drift one set
    corpus[0].add(123456)
    corpus[1] |= RoaringBitmap(np.arange(9000, 12000))
    s = LEDGER.refresh()
    c = LEDGER.census()
    assert s["containers"] == c["containers"]
    assert s["actual_bytes"] == c["actual_bytes"]
    assert s["optimal_bytes"] == c["optimal_bytes"]
    assert s["drift_ratio"] == c["drift_ratio"]


def test_ledger_refresh_is_o_dirty_not_o_corpus(monkeypatch):
    corpus = _corpus()
    LEDGER.watch("ws", corpus)
    LEDGER.refresh()
    calls = []
    real = structure_mod._measure
    monkeypatch.setattr(
        structure_mod, "_measure", lambda ct: calls.append(1) or real(ct)
    )
    # a clean refresh measures nothing at all
    LEDGER.refresh()
    assert calls == []
    # one dirty key re-measures one container, not the corpus
    corpus[0].add(42)
    LEDGER.refresh()
    assert len(calls) == 1


def test_ledger_drift_targets_price_excess_bytes():
    corpus = _corpus()
    LEDGER.watch("ws", corpus)
    _drift(corpus)
    s = LEDGER.refresh()
    targets = LEDGER.drift_targets()
    assert targets, "run-compressible containers must surface as targets"
    assert all(excess > 0 for _, _, excess in targets)
    assert s["drift_ratio"] > 1.05
    # the gauges exported what the books say
    snap = observe.REGISTRY.snapshot()
    drift = snap[observe.STRUCTURE_DRIFT_RATIO]["samples"][0]["value"]
    assert drift == s["drift_ratio"]


def test_ledger_accretion_depth_tracks_and_settles():
    corpus = _corpus(2)
    LEDGER.watch("ws", corpus)
    LEDGER.accrete(3)
    LEDGER.accrete(2)
    assert LEDGER.refresh()["accretion_depth"] == 5
    LEDGER.settle_accretion()
    assert LEDGER.refresh()["accretion_depth"] == 0


def test_ledger_wholesale_mutation_triggers_full_rescan():
    corpus = _corpus(2)
    LEDGER.watch("ws", corpus)
    LEDGER.refresh()
    # a wholesale mutation (mark_all_dirty path) must not desync books
    corpus[0].high_low_container.mark_all_dirty()
    corpus[0].add(777)
    s = LEDGER.refresh()
    c = LEDGER.census()
    assert s["containers"] == c["containers"]
    assert s["actual_bytes"] == c["actual_bytes"]


# ---------------------------------------------------------------------------
# the priced maintenance pass
# ---------------------------------------------------------------------------


def test_forced_pass_compacts_bit_identically_and_reclaims():
    corpus = _corpus()
    es = EpochStore(corpus)
    LEDGER.watch("ws", corpus)
    _drift(corpus)
    LEDGER.refresh()
    before = [bm.to_array() for bm in corpus]
    rec = maintain_mod.run_pass(store=es, reason="test", force=True)
    assert rec["outcome"] == "compacted"
    assert rec["rewritten_keys"] > 0
    assert rec["reclaimed_bytes"] > 0
    assert rec["anomalies"] == 0
    assert rec["flip"]["outcome"] == "flipped"
    for bm, want in zip(corpus, before):
        assert np.array_equal(bm.to_array(), want)
    # the compaction collapsed the drift the ledger saw
    assert LEDGER.refresh()["drift_ratio"] <= 1.05
    assert maintain_mod.last_pass()["outcome"] == "compacted"


def test_pass_rides_when_drift_is_cheaper_than_the_pass():
    corpus = _corpus()
    es = EpochStore(corpus)
    LEDGER.watch("ws", corpus)
    LEDGER.refresh()
    # no drift, no log: ride (0 us) beats the pass overhead
    rec = maintain_mod.run_pass(store=es, reason="test")
    assert rec["outcome"] == "rode"
    assert rec["est_us"]["ride"] < rec["est_us"]["compact"]


def test_pass_compacts_when_ride_cost_exceeds_pass_cost():
    corpus = _corpus()
    es = EpochStore(corpus)
    LEDGER.watch("ws", corpus)
    _drift(corpus, lo=0, hi=120000)  # massive excess bytes
    LEDGER.refresh()
    LEDGER.accrete(10)  # deep accretion scales the ride cost
    rec = maintain_mod.run_pass(store=es, reason="test")
    assert rec["outcome"] == "compacted"
    assert rec["est_us"]["ride"] >= rec["est_us"]["compact"]


def test_pass_noop_without_store_or_watch():
    assert maintain_mod.run_pass(store=None)["outcome"] == "noop"
    es = EpochStore(_corpus(2))
    assert maintain_mod.run_pass(store=es)["outcome"] == "noop"


def test_pass_fault_fails_closed_to_uncompacted_epoch():
    corpus = _corpus()
    es = EpochStore(corpus)
    LEDGER.watch("ws", corpus)
    _drift(corpus)
    LEDGER.refresh()
    before = [bm.serialize() for bm in corpus]
    epoch_before = es.stats()["epoch"]
    with faults.inject("serve.maintain", TransientDeviceError, every=1):
        rec = maintain_mod.run_pass(store=es, reason="test", force=True)
    assert rec["outcome"] == "aborted"
    assert es.stats()["epoch"] == epoch_before
    for bm, want in zip(corpus, before):
        assert bm.serialize() == want
    # the degrade edge is recorded, and the next clean pass recovers
    deg = observe.REGISTRY.get(observe.DEGRADE_TOTAL)
    assert deg.get(("serve.maintain", "compact", "ride")) >= 1
    rec2 = maintain_mod.run_pass(store=es, reason="test", force=True)
    assert rec2["outcome"] == "compacted"


def test_pass_joins_outcome_and_refit_consumes_it():
    corpus = _corpus()
    es = EpochStore(corpus)
    LEDGER.watch("ws", corpus)
    _drift(corpus)
    LEDGER.refresh()
    rec = maintain_mod.run_pass(store=es, reason="test", force=True)
    assert rec["outcome"] == "compacted"
    samples = [
        s for s in outcomes.LEDGER.tail(32)
        if s.get("site") == "serve.maintain"
    ]
    assert samples, "a taken pass must join its measured wall"
    assert samples[-1]["engine"] == "compact"
    report = compaction_cost.MODEL.refit_from_outcomes(
        samples=samples, min_samples=1
    )
    assert report["provenance"] == "refit-from-traffic"


# ---------------------------------------------------------------------------
# the eighth cost authority
# ---------------------------------------------------------------------------


def test_compaction_authority_registered_with_full_protocol():
    assert "compaction" in cost.names()
    a = cost.authority("compaction")
    assert a.provenance() == "default"
    curves = a.curves()
    assert curves["coeffs"]["drift_us_per_kb"] > 0
    assert set(curves["refit_keys"]) == {
        "pass_overhead_us", "rewrite_key_us", "merge_batch_us",
    }
    state = cost.calibration_state()
    assert "compaction" in state["authorities"]


def test_compaction_refit_moves_toward_truth_exchange_rate_pinned():
    samples = [
        {"site": "serve.maintain", "engine": "compact",
         "predicted_us": 100.0, "measured_s": 0.0004}
        for _ in range(4)
    ]
    before = dict(compaction_cost.MODEL.coeffs)
    report = compaction_cost.MODEL.refit_from_outcomes(samples=samples)
    assert set(report["moved"]) == {
        "pass_overhead_us", "rewrite_key_us", "merge_batch_us",
    }
    after = compaction_cost.MODEL.coeffs
    assert after["pass_overhead_us"] == pytest.approx(
        before["pass_overhead_us"] * 4.0
    )
    # the declared let-it-ride exchange rate NEVER moves on refit
    assert after["drift_us_per_kb"] == before["drift_us_per_kb"]
    bad = [{"site": "serve.maintain", "engine": "compact",
            "predicted_us": -1.0, "measured_s": 0.001}] * 3
    report2 = compaction_cost.MODEL.refit_from_outcomes(samples=bad)
    assert report2["rejected"] == 3 and not report2["moved"]


def test_compaction_model_state_roundtrip_and_foreign_rejection():
    compaction_cost.MODEL.refit_from_outcomes(samples=[
        {"site": "serve.maintain", "engine": "compact",
         "predicted_us": 100.0, "measured_s": 0.0002}
        for _ in range(2)
    ])
    d = compaction_cost.MODEL.to_dict()
    m2 = compaction_cost.CompactionModel()
    assert m2.from_dict(d) is True
    assert m2.coeffs == compaction_cost.MODEL.coeffs
    assert m2.from_dict({"schema": "other/1"}) is False
    assert m2.from_dict({"schema": compaction_cost.SCHEMA,
                         "coeffs": {"pass_overhead_us": 1e12}}) is False


# ---------------------------------------------------------------------------
# sentinel rules: fire -> actuate a pass -> clear
# ---------------------------------------------------------------------------


def test_structure_drift_rule_fires_actuates_pass_and_clears():
    corpus = _corpus()
    es = EpochStore(corpus)
    import roaringbitmap_tpu.serve.epochs as epochs_mod
    assert epochs_mod.current_store() is es
    LEDGER.watch("ws", corpus)
    _drift(corpus, lo=0, hi=190000)
    s = LEDGER.refresh()
    assert s["drift_ratio"] >= 2.0, "setup must reach the critical band"
    rules = tuple(
        r for r in health.DEFAULT_RULES
        if r.name in ("structure-drift", "delta-accretion")
    )
    assert len(rules) == 2
    assert all(r.actuation == "maintain" for r in rules)
    sen = sentinel.Sentinel(
        rules=rules, clock=lambda: 0.0, maintain_cooldown_s=30.0,
    )
    r1 = sen.tick(now=0.0)
    assert r1["actuated"] == []  # fire_after=2: first sight arms only
    r2 = sen.tick(now=1.0)
    # critical drift turns the process red, so a flight bundle may ride
    # along — the maintain actuation is the one under test
    maintains = [a for a in r2["actuated"] if a["kind"] == "maintain"]
    assert len(maintains) == 1
    act = maintains[0]
    assert act["rule"] == "structure-drift"
    assert act["outcome"] == "compacted"
    assert "error" not in act
    # the pass collapsed the drift: the rule clears over the next window
    sen.tick(now=2.0)
    r4 = sen.tick(now=3.0)
    assert r4["rules"]["structure-drift"]["level"] == health.OK
    assert r4["status_name"] == "green"
    # still green + cooldown: no second pass was scheduled
    assert sum(
        1 for a in sen.actuations() if a["kind"] == "maintain"
    ) == 1


def test_delta_accretion_rule_reads_the_depth_gauge():
    corpus = _corpus(2)
    LEDGER.watch("ws", corpus)
    LEDGER.accrete(9)  # warn band (>= 8)
    LEDGER.refresh()
    rule = next(
        r for r in health.DEFAULT_RULES if r.name == "delta-accretion"
    )
    snap = health.snapshot(refresh_hbm=False)
    assert rule.probe(snap) == 9.0
    assert rule.band(rule.probe(snap)) == health.WARN
    LEDGER.settle_accretion()
    LEDGER.refresh()
    snap2 = health.snapshot(refresh_hbm=False)
    assert rule.band(rule.probe(snap2)) == health.OK


def test_maintain_actuation_cooldown(monkeypatch):
    calls = []
    monkeypatch.setattr(
        maintain_mod, "run_pass",
        lambda **kw: calls.append(kw) or {"outcome": "compacted"},
    )
    dial = [5.0]
    rule = health.Rule("r", "", lambda s: dial[0], warn=1.0, critical=100.0,
                       fire_after=1, clear_after=1, actuation="maintain")
    sen = sentinel.Sentinel(
        rules=(rule,), clock=lambda: 0.0, maintain_cooldown_s=60.0,
    )
    sen.tick(now=0.0)
    assert len(calls) == 1
    assert calls[0]["reason"] == "sentinel:r"
    sen.tick(now=1.0)
    sen.tick(now=59.0)
    assert len(calls) == 1, "pass re-ran inside its cooldown"
    sen.tick(now=61.0)
    assert len(calls) == 2


def test_maintain_actuation_failure_is_recorded_not_fatal(monkeypatch):
    def boom(**kw):
        raise RuntimeError("pass broke")

    monkeypatch.setattr(maintain_mod, "run_pass", boom)
    rule = health.Rule("r", "", lambda s: 5.0, warn=1.0, critical=100.0,
                       fire_after=1, clear_after=1, actuation="maintain")
    sen = sentinel.Sentinel(rules=(rule,), clock=lambda: 0.0)
    r = sen.tick(now=0.0)
    acts = [a for a in r["actuated"] if a["kind"] == "maintain"]
    assert len(acts) == 1
    assert "pass broke" in acts[0]["error"]


# ---------------------------------------------------------------------------
# satellite: the serving-path runOptimize gap
# ---------------------------------------------------------------------------


def test_writer_merge_reselects_formats_when_optimising_runs():
    base = RoaringBitmap(np.array([1, 5, 9], np.uint32))
    w = BitmapWriter(into=base, optimise_runs=True)
    w.add_many(np.arange(100, 5000, dtype=np.uint32))
    w.flush()
    assert base.high_low_container.get_container_at_index(0).TYPE == "run"
    # default path unchanged: Java-parity merge keeps the or_ result
    base2 = RoaringBitmap(np.array([1, 5, 9], np.uint32))
    w2 = BitmapWriter(into=base2)
    w2.add_many(np.arange(100, 5000, dtype=np.uint32))
    w2.flush()
    assert base2.high_low_container.get_container_at_index(0).TYPE != "run"


def test_apply_merged_ingest_lands_run_heavy_batches_as_runs():
    t = _declare()
    corpus = _corpus(2)
    es = EpochStore(corpus)
    es.submit(t, {0: np.arange(600000, 640000)})
    flip = es.flip()
    assert flip["outcome"] == "flipped"
    hlc = corpus[0].high_low_container
    key = 600000 >> 16
    i = hlc.get_index(key)
    assert i >= 0
    assert hlc.get_container_at_index(i).TYPE == "run", (
        "serving-path ingest must re-run format selection on touched keys"
    )


def test_flip_with_rewrite_publishes_without_batches():
    corpus = _corpus(2)
    es = EpochStore(corpus)
    epoch_before = es.stats()["epoch"]

    def rewrite(live):
        return {0}, {"rewritten_keys": 1}

    flip = es.flip(rewrite=rewrite)
    assert flip["outcome"] == "flipped"
    assert flip["rewrite"] == {"rewritten_keys": 1}
    assert es.stats()["epoch"] == epoch_before + 1
    # a plain empty flip is still a noop
    assert es.flip()["outcome"] == "noop"


# ---------------------------------------------------------------------------
# export / insights / fuzz pin
# ---------------------------------------------------------------------------


def test_sidecar_structure_block_and_insights():
    corpus = _corpus()
    es = EpochStore(corpus)
    LEDGER.watch("ws", corpus)
    _drift(corpus)
    LEDGER.refresh()
    maintain_mod.run_pass(store=es, reason="test", force=True)
    side = obs_export.sidecar_snapshot()
    st = side["structure"]
    assert sum(st["containers"].values()) > 0
    assert set(st["containers"]) <= {"array", "bitmap", "run"}
    assert st["drift_ratio"] is not None
    assert st["passes"].get("compacted", 0) >= 1
    assert st["reclaimed_bytes"] and st["reclaimed_bytes"] > 0
    live = insights.structure()
    assert live["last_pass"]["outcome"] == "compacted"
    assert live["authority"] == "default"
    assert live["ledger_live"]["working_sets"] == 1
    obs = insights.observatory()
    assert "structure" in obs


def test_fuzz_family_30_seed_pin():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_compaction_invariance(
        "compaction-vs-identity-oracle", iterations=3, seed=60
    )
