"""L0 host word kernels vs naive references (SURVEY §7 step 1)."""

import numpy as np
import pytest

from roaringbitmap_tpu.utils import bits


def naive_popcount(words):
    return sum(bin(int(w)).count("1") for w in words)


def test_popcount64_random():
    rng = np.random.default_rng(1)
    words = rng.integers(0, 1 << 64, size=256, dtype=np.uint64)
    assert int(bits.popcount64(words).sum()) == naive_popcount(words)


def test_popcount64_edges():
    words = np.array([0, 0xFFFFFFFFFFFFFFFF, 1, 1 << 63], dtype=np.uint64)
    assert bits.popcount64(words).tolist() == [0, 64, 1, 1]


def test_words_values_roundtrip():
    rng = np.random.default_rng(2)
    values = np.unique(rng.integers(0, 1 << 16, size=5000)).astype(np.uint16)
    words = bits.words_from_values(values)
    assert np.array_equal(bits.values_from_words(words), values)
    assert bits.cardinality_of_words(words) == values.size


def test_set_clear_flip_range():
    for start, end in [(0, 65536), (0, 1), (65535, 65536), (100, 8000), (63, 65), (64, 128), (5, 5)]:
        words = bits.new_words()
        bits.set_bitmap_range(words, start, end)
        expected = np.arange(start, end, dtype=np.uint16)
        assert np.array_equal(bits.values_from_words(words), expected), (start, end)

        bits.clear_bitmap_range(words, start, end)
        assert bits.cardinality_of_words(words) == 0

        bits.flip_bitmap_range(words, start, end)
        assert np.array_equal(bits.values_from_words(words), expected)


def test_cardinality_in_range():
    rng = np.random.default_rng(3)
    values = np.unique(rng.integers(0, 1 << 16, size=3000))
    words = bits.words_from_values(values.astype(np.uint16))
    for start, end in [(0, 65536), (1000, 2000), (0, 1), (65535, 65536), (500, 500), (63, 64), (64, 65)]:
        expected = int(((values >= start) & (values < end)).sum())
        assert bits.cardinality_in_range(words, start, end) == expected, (start, end)


def test_select_in_words():
    rng = np.random.default_rng(4)
    values = np.unique(rng.integers(0, 1 << 16, size=2000))
    words = bits.words_from_values(values.astype(np.uint16))
    for j in [0, 1, len(values) // 2, len(values) - 1]:
        assert bits.select_in_words(words, j) == values[j]
    with pytest.raises(IndexError):
        bits.select_in_words(words, len(values))


def test_runs_roundtrip():
    cases = [
        np.array([], dtype=np.uint16),
        np.array([5], dtype=np.uint16),
        np.array([0, 1, 2, 10, 11, 65535], dtype=np.uint16),
        np.arange(0, 65536, dtype=np.uint16),
    ]
    for values in cases:
        s, l = bits.runs_from_values(values)
        assert np.array_equal(bits.values_from_runs(s, l), values)


def test_num_runs_in_words():
    values = np.array([0, 1, 2, 10, 11, 63, 64, 65, 1000], dtype=np.uint16)
    words = bits.words_from_values(values)
    # runs: [0-2], [10-11], [63-65], [1000] -> 4
    assert bits.num_runs_in_words(words) == 4
    assert bits.num_runs_in_words(bits.new_words()) == 0
    full = bits.new_words()
    bits.set_bitmap_range(full, 0, 65536)
    assert bits.num_runs_in_words(full) == 1


def test_sorted_set_ops():
    rng = np.random.default_rng(5)
    a = np.unique(rng.integers(0, 1 << 16, size=300)).astype(np.uint16)
    b = np.unique(rng.integers(0, 1 << 16, size=400)).astype(np.uint16)
    sa, sb = set(a.tolist()), set(b.tolist())
    assert set(bits.merge_sorted_unique(a, b).tolist()) == sa | sb
    assert set(bits.intersect_sorted(a, b).tolist()) == sa & sb
    assert set(bits.difference_sorted(a, b).tolist()) == sa - sb
    assert set(bits.xor_sorted(a, b).tolist()) == sa ^ sb


def test_high_low_bits():
    assert bits.highbits(0x12345678) == 0x1234
    assert bits.lowbits(0x12345678) == 0x5678
    assert bits.combine(0x1234, 0x5678) == 0x12345678


def test_or_values_into_words_accumulates():
    """or_values_into_words ORs into the existing accumulator (the fold's
    array-container scatter) — differential vs the allocate-then-or path,
    exercised on whatever native tier is live."""
    rng = np.random.default_rng(17)
    acc = rng.integers(0, 1 << 64, 1024, dtype=np.uint64)
    vals = rng.integers(0, 1 << 16, 5000).astype(np.uint16)
    want = acc | bits.words_from_values(vals)
    got = acc.copy()
    ret = bits.or_values_into_words(got, vals)
    assert ret is got and np.array_equal(got, want)
    # empty scatter is a no-op
    before = got.copy()
    bits.or_values_into_words(got, np.empty(0, dtype=np.uint16))
    assert np.array_equal(got, before)
