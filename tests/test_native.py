"""Differential tests: native C++ kernels vs the numpy oracle.

Mirrors the reference's cross-implementation equivalence strategy (SURVEY §4:
heap vs buffer vs 64-bit variants agree); here the pair is compiled
native/kernels.cpp vs utils/bits.py numpy, over randomized shape-diverse
inputs (sparse / dense / run-heavy, like SeededTestData.java:55-62).
"""

import numpy as np
import pytest

from roaringbitmap_tpu import native
from roaringbitmap_tpu.utils import bits

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

rng = np.random.default_rng(0xFEEF1F0)


def random_sorted(max_card=6000):
    n = int(rng.integers(0, max_card))
    return np.unique(rng.integers(0, 1 << 16, size=n).astype(np.uint16))


def random_run_heavy():
    vals = []
    pos = 0
    while pos < (1 << 16) - 300:
        pos += int(rng.integers(1, 500))
        ln = int(rng.integers(1, 200))
        vals.extend(range(pos, min(pos + ln, 1 << 16)))
        pos += ln
        if len(vals) > 30000:
            break
    return np.array(sorted(set(vals)), dtype=np.uint16)


CASES = [(random_sorted(), random_sorted()) for _ in range(25)] + [
    (random_run_heavy(), random_sorted()),
    (random_run_heavy(), random_run_heavy()),
    (np.empty(0, dtype=np.uint16), random_sorted()),
    (random_sorted(), np.empty(0, dtype=np.uint16)),
    (np.array([7], dtype=np.uint16), random_sorted(60000)),  # galloping path
]


@pytest.mark.parametrize("a,b", CASES)
def test_set_algebra(a, b):
    assert np.array_equal(native.intersect_sorted(a, b), bits.intersect_sorted_numpy(a, b))
    assert np.array_equal(native.merge_sorted_unique(a, b), bits.merge_sorted_unique_numpy(a, b))
    assert np.array_equal(native.difference_sorted(a, b), bits.difference_sorted_numpy(a, b))
    assert np.array_equal(native.xor_sorted(a, b), bits.xor_sorted_numpy(a, b))
    assert native.intersect_cardinality(a, b) == bits.intersect_sorted_numpy(a, b).size


def test_word_kernels():
    for _ in range(20):
        vals = random_sorted()
        words_np = bits.words_from_values_numpy(vals)
        words_nat = native.words_from_values(vals)
        assert np.array_equal(words_np, words_nat)
        assert native.cardinality_of_words(words_np) == bits.cardinality_of_words_numpy(words_np)
        assert np.array_equal(native.values_from_words(words_np), bits.values_from_words_numpy(words_np))
        assert native.num_runs_in_words(words_np) == bits.num_runs_in_words_numpy(words_np)
        if vals.size:
            j = int(rng.integers(0, vals.size))
            assert native.select_in_words(words_np, j) == bits.select_in_words_numpy(words_np, j)
            s, e = sorted(rng.integers(0, 1 << 16, size=2).tolist())
            assert native.cardinality_in_range(words_np, s, e + 1) == bits.cardinality_in_range_numpy(
                words_np, s, e + 1
            )


def test_select_out_of_range():
    words = bits.words_from_values_numpy(np.array([1, 5], dtype=np.uint16))
    with pytest.raises(IndexError):
        native.select_in_words(words, 2)


def test_runs_roundtrip():
    for vals in (random_run_heavy(), random_sorted(), np.empty(0, dtype=np.uint16)):
        s_nat, l_nat = native.runs_from_values(vals)
        s_np, l_np = bits.runs_from_values_numpy(vals)
        assert np.array_equal(s_nat, s_np) and np.array_equal(l_nat, l_np)
        assert native.num_runs_in_values(vals) == s_np.size


def test_wide_op_fold():
    rows = rng.integers(0, 1 << 63, size=(17, 1024), dtype=np.uint64)
    for op, fn in (("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)):
        out, card = native.wide_op_words(rows, op)
        want = fn.reduce(rows, axis=0)
        assert np.array_equal(out, want)
        assert card == bits.cardinality_of_words_numpy(want)
    out, card = native.wide_op_words(rows[:0], "or")
    assert card == 0 and not out.any()


def test_contains_many_and_advance_until():
    a = random_sorted()
    q = rng.integers(0, 1 << 16, size=500).astype(np.uint16)
    got = native.contains_many(a, q)
    want = np.isin(q, a)
    assert np.array_equal(got, want)
    if a.size > 2:
        pos = native.advance_until(a, -1, int(a[a.size // 2]))
        assert a[pos] == a[a.size // 2]
        assert native.advance_until(a, -1, int(a[-1]) + 1 if a[-1] < 0xFFFF else 0xFFFF) >= a.size - 1


def test_words_from_intervals_differential():
    """Native masked-word interval fill vs the numpy boundary-cumsum oracle,
    incl. word-boundary and full-universe edges."""
    if not native.available():
        pytest.skip("native unavailable")
    rng = np.random.default_rng(123)
    cases = [
        (np.array([0], dtype=np.int64), np.array([65536], dtype=np.int64)),
        (np.array([65535], dtype=np.int64), np.array([65536], dtype=np.int64)),
        (np.array([0], dtype=np.int64), np.array([1], dtype=np.int64)),
        (np.array([63], dtype=np.int64), np.array([65], dtype=np.int64)),
        (np.array([0, 64], dtype=np.int64), np.array([64, 128], dtype=np.int64)),
        (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)),
    ]
    for _ in range(20):
        n = int(rng.integers(1, 200))
        starts = np.sort(rng.choice(65536 - 1, size=n, replace=False)).astype(np.int64)
        ends = np.minimum(
            starts + rng.integers(1, 300, size=n), 
            np.append(starts[1:], 65536),
        ).astype(np.int64)
        cases.append((starts, ends))
    for starts, ends in cases:
        got = native.words_from_intervals(starts, ends)
        want = bits.words_from_intervals_numpy(starts, ends)
        assert np.array_equal(got, want), (starts[:5], ends[:5])


def test_lower_bound_matches_searchsorted():
    """lower_bound (ext advance_until at pos=-1) == np.searchsorted on
    randomized edge shapes incl. first/last/absent/0xFFFF probes
    (regression: pos=0 skipped index 0 under Util.advanceUntil's
    strictly-after semantics)."""
    from roaringbitmap_tpu.utils import bits

    rng = np.random.default_rng(5)
    for _ in range(200):
        n = int(rng.integers(1, 40))
        a = np.unique(rng.integers(0, 1 << 16, size=n).astype(np.uint16))
        probes = [0, int(a[0]), int(a[-1]), 0xFFFF] + [
            int(v) for v in rng.integers(0, 1 << 16, 4)
        ]
        for x in probes:
            assert bits.lower_bound(a, x) == int(np.searchsorted(a, np.uint16(x)))
