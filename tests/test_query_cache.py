"""Query result cache (query/cache.py): LRU hit/miss/eviction semantics,
the byte budget, fingerprint-keyed invalidation on leaf mutation across
every mutator family, and a thread-safety hammer mirroring
tests/test_observe.py style."""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from roaringbitmap_tpu import Q, RoaringBitmap
from roaringbitmap_tpu.query import ResultCache, cache_key, evaluate_naive, execute


def _bm(start, end, step=1):
    return RoaringBitmap(np.arange(start, end, step, dtype=np.uint32))


# ---------------------------------------------------------------------------
# LRU semantics
# ---------------------------------------------------------------------------


def test_hit_miss_and_lru_eviction():
    c = ResultCache(max_entries=2)
    r1, r2, r3 = _bm(0, 10), _bm(10, 20), _bm(20, 30)
    assert c.get(("k1",)) is None  # miss
    c.put(("k1",), r1)
    c.put(("k2",), r2)
    assert c.get(("k1",)) is r1  # hit refreshes recency
    c.put(("k3",), r3)  # evicts k2 (LRU), not the just-touched k1
    assert c.get(("k2",)) is None
    assert c.get(("k1",)) is r1 and c.get(("k3",)) is r3
    s = c.stats()
    assert s["hits"] == 3 and s["misses"] == 2 and s["evictions"] == 1
    assert s["entries"] == len(c) == 2


def test_put_same_key_replaces_without_eviction():
    c = ResultCache(max_entries=2)
    c.put(("k",), _bm(0, 10))
    c.put(("k",), _bm(0, 20))
    assert len(c) == 1 and c.stats()["evictions"] == 0
    assert c.get(("k",)).get_cardinality() == 20


def test_byte_budget_eviction():
    big = _bm(0, 200_000)
    small = _bm(0, 64)
    c = ResultCache(max_entries=64, max_bytes=big.get_size_in_bytes() + 1)
    c.put(("big",), big)
    c.put(("small",), small)  # pushes bytes over budget -> big evicted first
    assert ("big",) not in c and ("small",) in c
    assert c.stats()["bytes"] == small.get_size_in_bytes()


def test_clear_and_validation():
    c = ResultCache(max_entries=4)
    c.put(("k",), _bm(0, 4))
    c.clear()
    assert len(c) == 0 and c.stats()["bytes"] == 0
    with pytest.raises(ValueError):
        ResultCache(max_entries=0)


# ---------------------------------------------------------------------------
# fingerprint-keyed invalidation
# ---------------------------------------------------------------------------


def test_fingerprint_bumps_on_every_mutator_family():
    bm = _bm(0, 1000, 3)
    seen = {bm.fingerprint()}

    def mutated():
        fp = bm.fingerprint()
        fresh = fp not in seen
        seen.add(fp)
        return fresh

    bm.add(7)
    assert mutated()
    bm.remove(7)
    assert mutated()
    bm.add_many(np.arange(5000, 5100, dtype=np.uint32))
    assert mutated()
    bm.add_range(1 << 20, (1 << 20) + 50)
    assert mutated()
    bm.remove_range(1 << 20, (1 << 20) + 10)
    assert mutated()
    bm.flip_range(0, 100)
    assert mutated()
    bm.ior(_bm(9000, 9100))
    assert mutated()
    bm.iand(_bm(0, 1 << 21))
    assert mutated()
    bm.ixor(_bm(40, 60))
    assert mutated()
    bm.iandnot(_bm(40, 50))
    assert mutated()
    bm.clear()
    assert mutated()


def test_fingerprint_stable_across_reads():
    bm = _bm(0, 100_000, 7)
    fp = bm.fingerprint()
    bm.contains(49)
    bm.get_cardinality()
    bm.rank(1000)
    bm.to_array()
    bm.serialize()
    assert bm.fingerprint() == fp
    assert bm.clone().fingerprint() != fp  # a clone is a distinct identity


def test_cache_key_tracks_leaf_mutation():
    a, b = _bm(0, 100), _bm(50, 150)
    q = Q.leaf(a) & Q.leaf(b)
    fps = {l.uid: l.fingerprint() for l in q.leaves}
    k1 = cache_key(q, fps)
    a.add(1234)
    fps2 = {l.uid: l.fingerprint() for l in q.leaves}
    assert cache_key(q, fps2) != k1


def test_stale_entries_age_out_after_mutation():
    """Mutating a leaf in a loop must not grow the cache unboundedly: old
    fingerprints' entries fall off the LRU tail."""
    a, b = _bm(0, 1000, 2), _bm(0, 1000, 5)
    q = Q.leaf(a) & Q.leaf(b)
    cache = ResultCache(max_entries=4)
    for i in range(20):
        a.add(100_000 + i)
        assert execute(q, cache=cache) == evaluate_naive(q)
    assert len(cache) <= 4


def test_fingerprint_bumps_on_deserialize_into():
    """read_into refills the container array by rebinding its lists, which
    bypasses the versioned mutators — it must bump the version itself or
    the result cache serves pre-deserialize results (code-review fix)."""
    from roaringbitmap_tpu import serialization

    a = _bm(0, 100)
    b = _bm(0, 1000)
    q = Q.leaf(a) & Q.leaf(b)
    cache = ResultCache()
    assert execute(q, cache=cache).get_cardinality() == 100
    fp = a.fingerprint()
    serialization.read_into(a, _bm(5000, 5600).serialize())
    assert a.fingerprint() != fp
    got = execute(q, cache=cache)
    assert got == evaluate_naive(q) and got.is_empty()


def test_plan_memoized_on_warm_path():
    """Repeated execute() over unchanged leaves must not replan (planning
    reads every leaf; the warm path should be cache probes only), and a
    leaf mutation must re-plan by fingerprint-key miss."""
    from roaringbitmap_tpu import tracing

    a, b, c = _bm(0, 1000, 2), _bm(0, 1000, 3), _bm(200, 800)
    q = (Q.leaf(a) & Q.leaf(b)) | Q.leaf(c)
    cache = ResultCache()

    def plan_count():
        return tracing.timings().get("query.plan", {}).get("count", 0)

    execute(q, cache=cache)
    warm = plan_count()
    for _ in range(3):
        execute(q, cache=cache)
    assert plan_count() == warm  # served from the plan memo
    a.add(7)
    execute(q, cache=cache)
    assert plan_count() == warm + 1  # mutation re-planned once


# ---------------------------------------------------------------------------
# thread safety (test_observe.py hammer style)
# ---------------------------------------------------------------------------


def test_cache_hammer_threadsafe():
    """8 writers x 500 get/put rounds over 16 shared keys: counters add up
    exactly (hits + misses == gets) and nothing is lost or corrupted."""
    c = ResultCache(max_entries=8)
    payloads = {k: _bm(k * 10, k * 10 + 10) for k in range(16)}

    def work(i):
        for j in range(500):
            k = ((i + j) % 16,)
            got = c.get(k)
            if got is None:
                c.put(k, payloads[k[0]])
            else:
                assert got.get_cardinality() == 10

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(work, range(8)))
    s = c.stats()
    assert s["hits"] + s["misses"] == 8 * 500
    assert len(c) <= 8


def test_execute_hammer_shared_cache():
    """Concurrent executions of overlapping queries through one shared
    cache all return correct results."""
    rng = np.random.default_rng(5)
    leaves = [
        RoaringBitmap(rng.choice(1 << 16, size=500, replace=False).astype(np.uint32))
        for _ in range(4)
    ]
    qs = [
        Q.leaf(leaves[0]) & Q.leaf(leaves[1]),
        (Q.leaf(leaves[0]) & Q.leaf(leaves[1])) | Q.leaf(leaves[2]),
        Q.andnot(Q.leaf(leaves[2]), Q.leaf(leaves[3])),
        Q.threshold(2, *[Q.leaf(l) for l in leaves]),
    ]
    wants = [evaluate_naive(q) for q in qs]
    cache = ResultCache(max_entries=32)
    errors = []
    barrier = threading.Barrier(8)

    def work(i):
        try:
            barrier.wait(timeout=10)
            for j in range(50):
                qi = (i + j) % len(qs)
                if execute(qs[qi], cache=cache) != wants[qi]:
                    errors.append((i, j, qi))
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(repr(e))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert cache.stats()["hits"] > 0


# ---------------------------------------------------------------------------
# lock-order witness (ISSUE 3: dynamic complement of the static
# lock-discipline rule): the execute hammer re-run with all seven framework
# locks instrumented — any inconsistent acquisition ordering (potential
# deadlock cycle) fails, and the one real nesting (cache lock -> registry
# lock, from the hit/miss counters inside ResultCache's critical section)
# must actually be witnessed.
# ---------------------------------------------------------------------------


def test_execute_hammer_seven_lock_order_witness(monkeypatch):
    from roaringbitmap_tpu import native, observe, tracing
    from roaringbitmap_tpu.analysis import LockWitness
    from roaringbitmap_tpu.observe import spans
    from roaringbitmap_tpu.parallel import aggregation
    import importlib

    from roaringbitmap_tpu.query import cache as cache_mod
    from roaringbitmap_tpu.query import exec as exec_mod
    from roaringbitmap_tpu.query import expr as expr_mod

    # `query.plan` the module is shadowed by the `plan()` function the
    # package re-exports; resolve the module itself
    plan_mod = importlib.import_module("roaringbitmap_tpu.query.plan")

    w = LockWitness()
    reg_lock = observe.REGISTRY._lock  # one RLock behind every metric
    for obj in (cache_mod._CACHE_TOTAL, plan_mod._PLAN_TOTAL,
                tracing._OP_SECONDS, spans.SPAN_SECONDS):
        monkeypatch.setattr(obj, "_lock", w.wrap("observe.registry", reg_lock))
    monkeypatch.setattr(
        expr_mod, "_INTERN_LOCK", w.wrap("query.expr.intern", expr_mod._INTERN_LOCK))
    monkeypatch.setattr(
        exec_mod, "_PLAN_MEMO_LOCK",
        w.wrap("query.exec.plan_memo", exec_mod._PLAN_MEMO_LOCK))
    monkeypatch.setattr(
        tracing, "_TIMINGS_LOCK", w.wrap("tracing._TIMINGS", tracing._TIMINGS_LOCK))
    monkeypatch.setattr(native, "_lock", w.wrap("native.load", native._lock))
    monkeypatch.setattr(
        aggregation.ParallelAggregation, "_POOL_LOCK",
        w.wrap("parallel.agg.pool", aggregation.ParallelAggregation._POOL_LOCK))
    cache = ResultCache(max_entries=32)
    cache._lock = w.wrap("query.cache", cache._lock)
    # force the quiescent lazy-init locks to actually fire under the hammer:
    # a fresh pool build and one (disabled -> cheap) native load attempt
    monkeypatch.setattr(aggregation.ParallelAggregation, "_POOL", None)
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setenv("ROARINGBITMAP_TPU_NO_NATIVE", "1")

    rng = np.random.default_rng(11)
    leaves = [
        RoaringBitmap(rng.choice(1 << 14, size=300, replace=False).astype(np.uint32))
        for _ in range(4)
    ]
    errors = []
    barrier = threading.Barrier(8)

    def work(i):
        try:
            barrier.wait(timeout=10)
            native.available()  # native._lock (double-checked slow path)
            for j in range(25):
                q = (Q.leaf(leaves[(i + j) % 4]) & Q.leaf(leaves[(i + j + 1) % 4])) \
                    | Q.leaf(leaves[j % 4])
                execute(q, cache=cache)
                aggregation.ParallelAggregation.or_(
                    leaves[i % 4], leaves[(i + 1) % 4], mode="cpu")
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(repr(e))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pool = aggregation.ParallelAggregation._POOL
    if pool is not None:
        pool.shutdown(wait=False)
    assert not errors
    # every instrumented lock family was exercised
    for name in ("observe.registry", "query.expr.intern", "query.exec.plan_memo",
                 "tracing._TIMINGS", "native.load", "query.cache"):
        assert w.acquisitions.get(name, 0) > 0, (name, w.acquisitions)
    # the known nesting was witnessed, and the global order graph is acyclic
    assert ("query.cache", "observe.registry") in w.edges
    w.assert_consistent()
