"""One-vs-many batched pairwise algebra (parallel/batch.py) — differential
vs the pairwise facade ops."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import batch


@pytest.fixture(scope="module")
def workload():
    rng = np.random.default_rng(41)
    filt = RoaringBitmap(rng.choice(1 << 20, 200_000, replace=False).astype(np.uint32))
    many = [
        RoaringBitmap(rng.choice(1 << 20, 1500, replace=False).astype(np.uint32))
        for _ in range(20)
    ]
    return filt, many


@pytest.mark.parametrize("op,ref", [("and", RoaringBitmap.and_), ("andnot", RoaringBitmap.andnot)])
def test_batched_matches_pairwise(workload, op, ref):
    filt, many = workload
    want = [ref(m, filt) for m in many]
    cards = batch.batched_cardinality(filt, many, op=op)
    assert cards.tolist() == [w.get_cardinality() for w in want]
    assert batch.batched_op(filt, many, op=op) == want


def test_batched_intersects(workload):
    filt, many = workload
    got = batch.batched_intersects(filt, many + [RoaringBitmap()])
    assert got.tolist() == [RoaringBitmap.intersects(m, filt) for m in many] + [False]


def test_prepare_reusable(workload):
    filt, many = workload
    run = batch.prepare_batched_cardinality(filt, many)
    first = run()
    assert np.array_equal(first, run())


def test_empty_inputs(workload):
    filt, _ = workload
    assert batch.batched_cardinality(filt, []).size == 0
    assert batch.batched_op(filt, []) == []
    assert batch.batched_op(filt, [RoaringBitmap()]) == [RoaringBitmap()]
    assert batch.batched_op(RoaringBitmap(), [RoaringBitmap.bitmap_of(1)]) == [RoaringBitmap()]


def test_pairwise_and_cardinality_matrix():
    """All-pairs intersection matrix == n*m pairwise and_cardinality loop,
    incl. disjoint-key pairs, empty sets, and the tiled left axis."""
    from roaringbitmap_tpu.parallel.batch import (
        pairwise_and_cardinality,
        pairwise_jaccard,
    )

    rng = np.random.default_rng(61)
    lefts = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 20, 3000)).astype(np.uint32))
        for _ in range(7)
    ]
    lefts.append(RoaringBitmap())  # empty set row
    rights = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 20, 2000)).astype(np.uint32))
        for _ in range(5)
    ]
    rights.append(RoaringBitmap([1 << 25]))  # key-disjoint from most lefts
    got = pairwise_and_cardinality(lefts, rights, tile_bytes=1 << 20)  # forces tiling
    for i, L in enumerate(lefts):
        for j, R in enumerate(rights):
            assert got[i, j] == RoaringBitmap.and_cardinality(L, R), (i, j)
    sim = pairwise_jaccard(lefts, rights)
    for i, L in enumerate(lefts):
        for j, R in enumerate(rights):
            u = RoaringBitmap.or_cardinality(L, R)
            want = (got[i, j] / u) if u else 0.0
            assert abs(sim[i, j] - want) < 1e-12, (i, j)
    # degenerate shapes
    assert pairwise_and_cardinality([], rights).shape == (0, len(rights))
    assert pairwise_and_cardinality(lefts, []).shape == (len(lefts), 0)


def test_pairwise_matrix_impls_agree():
    """VPU broadcast and MXU bit-matmul formulations produce identical
    matrices (the matmul is exact: 0/1 bf16 operands, per-chunk f32
    partials cast to an int32 accumulator — bound 2^31)."""
    from roaringbitmap_tpu.parallel.batch import pairwise_and_cardinality

    rng = np.random.default_rng(67)
    sets = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 21, 4000)).astype(np.uint32))
        for _ in range(16)
    ]
    L, R = sets[:8], sets[8:]
    a = pairwise_and_cardinality(L, R, impl="vpu")
    b = pairwise_and_cardinality(L, R, impl="mxu")
    assert a.tolist() == b.tolist()


def test_pairwise_cardinality_all_ops():
    """The four-op matrix family agrees with the scalar pairwise statics
    (the oracle the reference computes one cell at a time)."""
    from roaringbitmap_tpu.parallel.batch import pairwise_cardinality

    rng = np.random.default_rng(0xCA2D)
    lefts = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 18, 2000)).astype(np.uint32))
        for _ in range(5)
    ]
    rights = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 18, 3000)).astype(np.uint32))
        for _ in range(4)
    ] + [RoaringBitmap()]  # empty operand edge
    scalar = {
        "and": RoaringBitmap.and_cardinality,
        "or": RoaringBitmap.or_cardinality,
        "xor": RoaringBitmap.xor_cardinality,
        "andnot": RoaringBitmap.andnot_cardinality,
    }
    for op, fn in scalar.items():
        got = pairwise_cardinality(lefts, rights, op=op)
        for i, l in enumerate(lefts):
            for j, r in enumerate(rights):
                assert got[i, j] == fn(l, r), (op, i, j)
    with pytest.raises(ValueError, match="op must be"):
        pairwise_cardinality(lefts, rights, op="nand")


def test_pairwise_mxu_exact_beyond_f32():
    """Intersections past f32's 2^24 integer range must stay exact — the
    case the old f32 cross-chunk accumulator silently rounded (round 4:
    per-chunk partials now cast to an int32 accumulator)."""
    from roaringbitmap_tpu.parallel.batch import pairwise_and_cardinality

    n = (1 << 24) + 3  # 16777219: not representable in f32
    a = RoaringBitmap.bitmap_of_range(0, n)
    b = RoaringBitmap.bitmap_of_range(0, n)
    got = pairwise_and_cardinality([a], [b], impl="mxu")
    assert got[0, 0] == n
    # and the raised guard rejects only truly unrepresentable operands
    with pytest.raises(ValueError, match="2\\^31"):
        huge = RoaringBitmap.bitmap_of_range(0, 1 << 31)
        pairwise_and_cardinality([huge], [huge], impl="mxu")
