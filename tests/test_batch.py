"""One-vs-many batched pairwise algebra (parallel/batch.py) — differential
vs the pairwise facade ops."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import batch


@pytest.fixture(scope="module")
def workload():
    rng = np.random.default_rng(41)
    filt = RoaringBitmap(rng.choice(1 << 20, 200_000, replace=False).astype(np.uint32))
    many = [
        RoaringBitmap(rng.choice(1 << 20, 1500, replace=False).astype(np.uint32))
        for _ in range(20)
    ]
    return filt, many


@pytest.mark.parametrize("op,ref", [("and", RoaringBitmap.and_), ("andnot", RoaringBitmap.andnot)])
def test_batched_matches_pairwise(workload, op, ref):
    filt, many = workload
    want = [ref(m, filt) for m in many]
    cards = batch.batched_cardinality(filt, many, op=op)
    assert cards.tolist() == [w.get_cardinality() for w in want]
    assert batch.batched_op(filt, many, op=op) == want


def test_batched_intersects(workload):
    filt, many = workload
    got = batch.batched_intersects(filt, many + [RoaringBitmap()])
    assert got.tolist() == [RoaringBitmap.intersects(m, filt) for m in many] + [False]


def test_prepare_reusable(workload):
    filt, many = workload
    run = batch.prepare_batched_cardinality(filt, many)
    first = run()
    assert np.array_equal(first, run())


def test_empty_inputs(workload):
    filt, _ = workload
    assert batch.batched_cardinality(filt, []).size == 0
    assert batch.batched_op(filt, []) == []
    assert batch.batched_op(filt, [RoaringBitmap()]) == [RoaringBitmap()]
    assert batch.batched_op(RoaringBitmap(), [RoaringBitmap.bitmap_of(1)]) == [RoaringBitmap()]
