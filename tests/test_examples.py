"""Examples smoke test — the runAll gate (reference runs examples via
``./gradlew :examples:runAll``; each example asserts internally)."""

import importlib

import pytest

from examples import EXAMPLES


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name, monkeypatch, capsys):
    mod = importlib.import_module(f"examples.{name}")
    # shrink the heavyweight one for smoke purposes
    if name == "device_aggregation":
        monkeypatch.setattr(mod, "N_BITMAPS", 50)
        monkeypatch.setattr(mod, "VALUES_PER_BITMAP", 500)
    mod.main()
    out = capsys.readouterr().out
    assert out.strip(), name
