"""Columnar pairwise engine (ISSUE 5): differential coverage vs the
per-container engine across all 9 type-pair classes, both kernel tiers
(native batch / numpy fallback), the routing cutoff, key-plan edge cases,
member-op reuse semantics, the N-way folds, and the metrics surface."""

import numpy as np
import pytest

from roaringbitmap_tpu import columnar, insights
from roaringbitmap_tpu.columnar import engine as col_engine
from roaringbitmap_tpu.columnar import kernels as col_kernels
from roaringbitmap_tpu.models.container import (
    ArrayContainer,
    BitmapContainer,
    RunContainer,
)
from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.parallel.aggregation import FastAggregation

OPS = {
    "and": RoaringBitmap.and_,
    "or": RoaringBitmap.or_,
    "xor": RoaringBitmap.xor,
    "andnot": RoaringBitmap.andnot,
}


def _chunk_values(kind: str, key: int, rng) -> np.ndarray:
    """Values for one 2^16 chunk shaped to settle into the given container
    type after construction (+ run_optimize for 'run')."""
    base = key << 16
    if kind == "array":
        vals = np.sort(rng.choice(1 << 16, 500, replace=False))
    elif kind == "bitmap":
        vals = np.sort(rng.choice(1 << 16, 9000, replace=False))
    else:  # run
        starts = np.arange(0, 1 << 16, 1 << 11)[:20]
        vals = np.unique(
            np.concatenate([np.arange(s, s + 900) for s in starts])
        )
    return (vals + base).astype(np.uint32)


def _typed_bitmap(kinds, rng) -> RoaringBitmap:
    bm = RoaringBitmap(
        np.concatenate([_chunk_values(k, i, rng) for i, k in enumerate(kinds)])
    )
    bm.run_optimize()
    return bm


@pytest.mark.parametrize("op", list(OPS))
def test_all_nine_classes_parity(op):
    """Every (array|bitmap|run)^2 matched class, both operand orders, vs
    the per-container engine."""
    rng = np.random.default_rng(5)
    kinds = ["array", "bitmap", "run"]
    a = _typed_bitmap([k for k in kinds for _ in kinds], rng)  # a,a,a,b,b,b,r,r,r
    b = _typed_bitmap([k for _ in kinds for k in kinds], rng)  # a,b,r,a,b,r,...
    got = columnar.pairwise(op, a, b)
    with columnar.disabled():
        want = OPS[op](a, b)
    assert got == want
    assert got.get_cardinality() == want.get_cardinality()
    # container *types* on the two sides really were the 9-class grid
    ca = columnar.classify(a.high_low_container.containers)
    cb = columnar.classify(b.high_low_container.containers)
    assert columnar.class_histogram(ca, cb).tolist() == [1] * 9


@pytest.mark.parametrize("force_numpy", [False, True])
def test_random_parity_both_tiers(monkeypatch, force_numpy):
    """Randomized differential on the native AND the numpy fallback tier:
    identical results with the C extension unavailable."""
    if force_numpy:
        monkeypatch.setattr(col_kernels, "_native", lambda: None)
    from roaringbitmap_tpu import fuzz

    rng = np.random.default_rng(17)
    for _ in range(40):
        a = fuzz.random_bitmap(rng)
        b = fuzz.random_bitmap(rng)
        for op, ref in OPS.items():
            with columnar.disabled():
                want = ref(a, b)
            assert columnar.pairwise(op, a, b) == want, op
        with columnar.disabled():
            want_c = RoaringBitmap.and_cardinality(a, b)
            want_i = RoaringBitmap.intersects(a, b)
        assert columnar.and_cardinality_pair(a, b) == want_c
        assert columnar.intersects_pair(a, b) == want_i


def test_numpy_tier_fold_parity(monkeypatch):
    monkeypatch.setattr(col_kernels, "_native", lambda: None)
    from roaringbitmap_tpu import fuzz

    rng = np.random.default_rng(23)
    bms = [fuzz.random_bitmap(rng) for _ in range(5)]
    groups = store.group_by_key(bms)
    assert columnar.fold(groups, "or") == FastAggregation.naive_or(*bms)
    assert columnar.fold(groups, "xor") == FastAggregation.naive_xor(*bms)
    keys = store.intersect_keys(bms)
    if keys:
        g2 = store.group_by_key(bms, keys_filter=keys)
        assert columnar.fold(g2, "and") == FastAggregation.naive_and(*bms)


def test_empty_and_disjoint_key_plans():
    empty = RoaringBitmap()
    disj_a = RoaringBitmap((np.arange(100) + (1 << 16)).astype(np.uint32))
    disj_b = RoaringBitmap((np.arange(100) + (9 << 16)).astype(np.uint32))
    for op, ref in OPS.items():
        for x1, x2 in [
            (empty, disj_a),
            (disj_a, empty),
            (empty, empty.clone()),
            (disj_a, disj_b),
            (disj_b, disj_a),
        ]:
            with columnar.disabled():
                want = ref(x1, x2)
            assert columnar.pairwise(op, x1, x2) == want, op
    assert columnar.and_cardinality_pair(disj_a, disj_b) == 0
    assert not columnar.intersects_pair(disj_a, disj_b)
    # key plan internals: disjoint -> no matched pairs, full pass-throughs
    plan = columnar.key_plan(
        disj_a.high_low_container.keys, disj_b.high_low_container.keys, "or"
    )
    assert plan.ia.size == 0 and plan.a_only.size == 1 and plan.b_only.size == 1


def _runny(n_keys: int) -> RoaringBitmap:
    """n_keys run containers (the shape the router's dense hint admits)."""
    bm = RoaringBitmap(
        np.concatenate(
            [np.arange(k << 16, (k << 16) + 40) for k in range(n_keys)]
        ).astype(np.uint32)
    )
    bm.run_optimize()
    return bm


def test_cutoff_boundary_routes():
    """Below config.min_containers the facade keeps the per-container
    walk; at the cutoff it switches to the columnar engine (visible in
    rb_tpu_columnar_batch_total)."""
    cut = columnar.config.min_containers

    def counter_total():
        return sum(insights.columnar_counters()["batch"].values())

    small = _runny(cut - 1)
    at_cut = _runny(cut)
    before = counter_total()
    RoaringBitmap.and_(small, small.clone())
    assert counter_total() == before  # routed per-container
    RoaringBitmap.and_(at_cut, at_cut.clone())
    assert counter_total() > before  # routed columnar
    # results agree on both sides of the boundary
    for bm in (small, at_cut):
        with columnar.disabled():
            want = RoaringBitmap.and_(bm, bm.clone())
        assert RoaringBitmap.and_(bm, bm.clone()) == want


def test_array_only_operands_keep_percontainer_walk():
    """The dense-shape hint: array-only pairs (whose scalar ops already
    sit at the C floor) never route columnar, whatever their count."""
    cut = columnar.config.min_containers

    def counter_total():
        return sum(insights.columnar_counters()["batch"].values())

    arrays = RoaringBitmap((np.arange(cut * 2) << 16).astype(np.uint32))
    before = counter_total()
    RoaringBitmap.and_(arrays, arrays.clone())
    RoaringBitmap.or_(arrays, arrays.clone())
    assert counter_total() == before
    # one run container on either side flips the hint
    mixed = arrays.clone()
    mixed.add_range(100 << 16, (100 << 16) + 50)
    mixed.run_optimize()
    RoaringBitmap.and_(arrays, mixed)
    assert counter_total() > before


def test_inplace_reuse_semantics():
    """ior/ixor/iandnot above the cutoff: pass-through containers of self
    TRANSFER (no clone), matched results are fresh, and the right operand
    is never touched."""
    rng = np.random.default_rng(3)
    n = columnar.config.min_containers + 8
    a = _typed_bitmap(["array", "run"] * (n // 2), rng)
    # b shares only the last few keys, so a has pass-throughs
    b_vals = np.concatenate(
        [_chunk_values("run", k, rng) for k in range(n - 4, n + 4)]
    )
    b = RoaringBitmap(b_vals)
    b.run_optimize()
    b_before = b.clone()
    passthrough = a.high_low_container.containers[0]
    ref = RoaringBitmap.or_(a, b)
    a.ior(b)
    assert a == ref
    assert a.high_low_container.containers[0] is passthrough  # transferred
    assert b == b_before
    # static path must NOT transfer: x1 stays usable
    a2 = _typed_bitmap(["array", "run"] * (n // 2), rng)
    keep = a2.high_low_container.containers[0]
    out = RoaringBitmap.xor(a2, b)
    assert out.high_low_container.containers[0] is not keep
    a3 = a2.clone()
    a3.ixor(b)
    a4 = a2.clone()
    a4.iandnot(b)
    with columnar.disabled():
        assert a3 == RoaringBitmap.xor(a2, b)
        assert a4 == RoaringBitmap.andnot(a2, b)


def test_ior_not_tail_passthrough_transfer():
    """ior_not transfers self's beyond-range chunks unclone'd (member-op
    semantics), value-equal to the static or_not."""
    a = RoaringBitmap([1, 5, (40 << 16) | 3])
    b = RoaringBitmap([5, 6])
    tail = a.high_low_container.containers[-1]
    want = RoaringBitmap.or_not(a.clone(), b, 1 << 10)
    a.ior_not(b, 1 << 10)
    assert a == want
    assert a.high_low_container.containers[-1] is tail


def test_mapped_operands_route_columnar():
    rng = np.random.default_rng(11)
    n = columnar.config.min_containers + 2
    a = _typed_bitmap(["array", "run"] * n, rng)
    b = _typed_bitmap(["run", "array"] * n, rng)
    mapped = ImmutableRoaringBitmap(b.serialize())
    for op, ref in OPS.items():
        with columnar.disabled():
            want = ref(a, b)
        assert ref(a, mapped) == want, op
    with columnar.disabled():
        want_c = RoaringBitmap.and_cardinality(a, b)
    assert RoaringBitmap.and_cardinality(a, mapped) == want_c


def test_fold_parity_and_type_preserving_singles():
    """Columnar fold == pooled word fold == naive engines; single-container
    groups pass through as type-preserving clones (run stays run)."""
    rng = np.random.default_rng(29)
    bms = [_typed_bitmap(["run", "array", "bitmap"], rng) for _ in range(6)]
    solo = RoaringBitmap(_chunk_values("run", 40, rng))
    solo.run_optimize()
    bms.append(solo)
    groups = store.group_by_key(bms)
    got = columnar.fold(groups, "or")
    assert got == FastAggregation.naive_or(*bms)
    assert got == FastAggregation.horizontal_or(*bms)
    # key 40 exists only in solo -> its run container must stay a run
    c = got.high_low_container.get_container(40)
    assert isinstance(c, RunContainer)
    assert columnar.fold(groups, "xor") == FastAggregation.naive_xor(*bms)


def test_cpu_aggregation_routes_columnar():
    """FastAggregation/ParallelAggregation CPU folds route through the
    columnar fold above min_fold_rows and stay equal to the naive fold."""
    from roaringbitmap_tpu.parallel.aggregation import ParallelAggregation

    rng = np.random.default_rng(31)
    bms = [
        RoaringBitmap(
            np.concatenate(
                [_chunk_values("array", k, rng) for k in range(24)]
            )
        )
        for _ in range(6)
    ]  # 144 rows >= min_fold_rows
    want = FastAggregation.naive_or(*bms)
    before = insights.columnar_counters()["batch"].get("fold_or/rows", 0)
    assert FastAggregation.or_(*bms, mode="cpu") == want
    assert ParallelAggregation.or_(*bms, mode="cpu") == want
    after = insights.columnar_counters()["batch"].get("fold_or/rows", 0)
    assert after > before
    assert FastAggregation.and_(*bms, mode="cpu") == FastAggregation.naive_and(*bms)
    assert FastAggregation.xor(*bms, mode="cpu") == FastAggregation.naive_xor(*bms)


def test_query_kernel_cpu_fallback_uses_columnar_union():
    """andnot_nway's CPU path (subtrahend union) equals the composed
    reference on working sets large enough to take or_fold_words."""
    from roaringbitmap_tpu.query import kernels as qk

    rng = np.random.default_rng(37)
    first = _typed_bitmap(["array"] * 30, rng)
    rest = [_typed_bitmap(["array", "run"] * 15, rng) for _ in range(4)]
    got = qk.andnot_nway(first, *rest, mode="cpu")
    want = RoaringBitmap.andnot(first, FastAggregation.or_(*rest, mode="cpu"))
    assert got == want
    assert qk.andnot_nway_cardinality(first, *rest, mode="cpu") == want.get_cardinality()


def test_interval_batch_edges():
    """Full-range runs, touching array-born singletons, and the cards-only
    path of the banded interval kernel."""
    full = RunContainer(np.array([0], np.uint16), np.array([0xFFFF], np.uint16))
    arr = ArrayContainer(np.array([0, 1, 2, 65535], np.uint16))
    a = RoaringBitmap()
    b = RoaringBitmap()
    for k in range(columnar.config.min_containers):
        a.high_low_container.append(k, full.clone())
        b.high_low_container.append(k, arr.clone())
    for op, ref in OPS.items():
        with columnar.disabled():
            want = ref(a, b)
        assert columnar.pairwise(op, a, b) == want, op
        assert columnar.pairwise(op, b, a) == ref(b, a), op
    assert (
        columnar.and_cardinality_pair(a, b)
        == columnar.config.min_containers * 4
    )


def test_columnar_counters_shape():
    rng = np.random.default_rng(41)
    a = _typed_bitmap(["array"] * 20, rng)
    columnar.pairwise("and", a, a.clone())
    snap = insights.columnar_counters()
    assert set(snap) == {"batch", "route"}
    assert snap["batch"].get("and/aa", 0) >= 20
    for key in snap["batch"]:
        op, klass = key.split("/")
        assert klass in columnar.CLASS_NAMES or klass in (
            "rows", "device_pair", "device_gather",
        )
    for tier in snap["route"]:
        assert tier in ("per-container", "columnar-cpu", "columnar-device")


def test_dense_chunking():
    """The word-matrix classes honor config.chunk_rows (bounded peak
    memory) without changing results."""
    rng = np.random.default_rng(43)
    a = _typed_bitmap(["bitmap"] * 24, rng)
    b = _typed_bitmap(["bitmap"] * 24, rng)
    old = columnar.config.chunk_rows
    columnar.config.chunk_rows = 5  # force many chunks
    try:
        for op, ref in OPS.items():
            with columnar.disabled():
                want = ref(a, b)
            assert columnar.pairwise(op, a, b) == want, op
    finally:
        columnar.config.chunk_rows = old


def test_pairwise_results_are_independent_buffers():
    """Batched results must not alias the shared scratch: mutating one
    result cannot corrupt a sibling."""
    rng = np.random.default_rng(47)
    a = _typed_bitmap(["array"] * 20, rng)
    b = _typed_bitmap(["array"] * 20, rng)
    out = columnar.pairwise("or", a, b)
    c0 = out.high_low_container.containers[0]
    before = out.high_low_container.containers[1].to_array().copy()
    for v in range(200):
        c0.add(v)
    assert np.array_equal(out.high_low_container.containers[1].to_array(), before)


def test_fuzz_family_smoke():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_columnar_invariance("columnar-vs-percontainer", iterations=25, seed=54)
