"""Aggregation engines: CPU and device paths must agree with the naive
pairwise fold (the reference's own oracle pattern — jmh smoke tests assert
optimized aggregation equals naive before timing)."""

import functools

import numpy as np
import pytest

from roaringbitmap_tpu import FastAggregation, ParallelAggregation, RoaringBitmap


@pytest.fixture
def bitmap_set(random_bitmap_factory):
    return [random_bitmap_factory()[0] for _ in range(12)]


def naive(bitmaps, op):
    fn = {
        "or": RoaringBitmap.or_,
        "and": RoaringBitmap.and_,
        "xor": RoaringBitmap.xor,
    }[op]
    return functools.reduce(fn, bitmaps[1:], bitmaps[0])


@pytest.mark.parametrize("op", ["or", "and", "xor"])
@pytest.mark.parametrize("mode", ["cpu", "device"])
def test_fast_aggregation_matches_naive(bitmap_set, op, mode):
    want = naive(bitmap_set, op)
    fn = {"or": FastAggregation.or_, "and": FastAggregation.and_, "xor": FastAggregation.xor}[op]
    got = fn(*bitmap_set, mode=mode)
    assert got == want, f"{op}/{mode}"


@pytest.mark.parametrize("op", ["or", "xor"])
@pytest.mark.parametrize("mode", ["cpu", "device"])
def test_parallel_aggregation_matches_naive(bitmap_set, op, mode):
    want = naive(bitmap_set, op)
    fn = {"or": ParallelAggregation.or_, "xor": ParallelAggregation.xor}[op]
    got = fn(*bitmap_set, mode=mode)
    assert got == want


@pytest.mark.parametrize("mode", ["cpu", "device"])
def test_cardinality_shortcuts(bitmap_set, mode):
    """Cardinality-only N-way engines (device path fetches only per-group
    popcounts — no materialized result) match materialize-then-count."""
    for op, fn in (
        ("or", FastAggregation.or_cardinality),
        ("and", FastAggregation.and_cardinality),
        ("xor", FastAggregation.xor_cardinality),
    ):
        want = naive(bitmap_set, op).get_cardinality()
        assert fn(*bitmap_set, mode=mode) == want, (op, mode)
    assert FastAggregation.or_cardinality() == 0
    assert FastAggregation.and_cardinality() == 0
    one = RoaringBitmap([5, 9])
    assert FastAggregation.and_cardinality(one) == 2


def test_edge_cases():
    assert FastAggregation.or_().is_empty()
    assert FastAggregation.and_().is_empty()
    one = RoaringBitmap([1, 2, 3])
    assert FastAggregation.or_(one) == one
    assert FastAggregation.and_(one) == one
    empty = RoaringBitmap()
    assert FastAggregation.and_(one, empty).is_empty()
    assert FastAggregation.or_(one, empty) == one


def test_iterable_input():
    bms = [RoaringBitmap([i, i + 10]) for i in range(5)]
    got = FastAggregation.or_(bms)  # list form, like the Java iterator overloads
    assert got.get_cardinality() == len(set(range(5)) | set(range(10, 15)))


def test_group_by_key():
    b1 = RoaringBitmap([1, 1 << 16])
    b2 = RoaringBitmap([2, 2 << 16])
    groups = ParallelAggregation.group_by_key(b1, b2)
    assert set(groups.keys()) == {0, 1, 2}
    assert len(groups[0]) == 2


def test_device_path_with_many_containers(random_bitmap_factory):
    """Wide-OR across enough containers to exercise padded and skewed paths."""
    bms = [random_bitmap_factory()[0] for _ in range(30)]
    # add one bitmap with a unique far key to skew group sizes
    skew = RoaringBitmap([(1 << 31) + 5])
    bms.append(skew)
    want = naive(bms, "or")
    assert FastAggregation.or_(*bms, mode="device") == want
    assert FastAggregation.or_(*bms, mode="cpu") == want


def test_bucket_plan_properties():
    """bucket_plan must cover every group exactly once and never cost more
    padded rows than the single-block layout."""
    from roaringbitmap_tpu.parallel import store

    rng = np.random.default_rng(9)
    for counts in (
        np.array([1450, 1200, 700, 650, 300, 10, 5]),
        rng.integers(1, 2000, size=66),
        np.array([7]),
        np.array([5, 5, 5, 5]),
        np.array([], dtype=np.int64),
    ):
        for k in (1, 2, 3, 5):
            plan = store.bucket_plan(counts, k)
            seen = np.concatenate(plan) if plan else np.empty(0, np.int64)
            assert sorted(seen.tolist()) == list(range(len(counts)))
            cost = sum(len(idx) * counts[idx].max() for idx in plan)
            single = len(counts) * counts.max() if len(counts) else 0
            assert cost <= single
            assert len(plan) <= max(1, min(k, len(counts)))


def test_bucketed_reduce_matches_flat(random_bitmap_factory):
    """prepare_reduce_bucketed must agree with reduce_packed on a skewed
    working set, for every op and bucket count."""
    from roaringbitmap_tpu.parallel import store

    bms = [random_bitmap_factory()[0] for _ in range(24)]
    bms.append(RoaringBitmap([(1 << 30) + 3]))  # lone far key -> skew
    groups = store.group_by_key(bms)
    packed = store.pack_groups(groups)
    for op in ("or", "and", "xor"):
        want_words, want_cards = store.reduce_packed(packed, op=op)
        for k in (1, 3, 6):
            run, layout = store.prepare_reduce_bucketed(packed, op=op, n_buckets=k)
            assert layout == "bucketed"
            got_words, got_cards = (np.asarray(x) for x in run())
            assert np.array_equal(got_words, want_words), (op, k)
            assert np.array_equal(got_cards, want_cards), (op, k)


def test_prepare_reduce_layout_policy(random_bitmap_factory):
    """The cost-model choice: near-full single block -> padded; skewed but
    bucketable -> bucketed (rescued from the segmented fallback); results
    identical either way."""
    from roaringbitmap_tpu.parallel import store

    # uniform groups: occupancy 1.0 -> single padded block
    uniform = [RoaringBitmap([k << 16 for k in range(8)]) for _ in range(10)]
    packed_u = store.pack_groups(store.group_by_key(uniform))
    _, layout_u = store.prepare_reduce(packed_u)
    assert layout_u == "padded"

    # one giant group + many singletons: single-block occupancy ~tiny
    # (pad_groups_dense returns None), but bucketing pads to ~100%
    skew = [RoaringBitmap(np.arange(2000, dtype=np.uint32))] * 40
    skew += [RoaringBitmap([(k + 2) << 16]) for k in range(30)]
    packed_s = store.pack_groups(store.group_by_key(skew))
    run_s, layout_s = store.prepare_reduce(packed_s)
    assert layout_s == "bucketed"
    # host oracle (not reduce_packed, which now routes through the same
    # dispatcher): per-group numpy fold over the packed rows
    offs = packed_s.group_offsets
    want_words = np.stack(
        [np.bitwise_or.reduce(packed_s.words[offs[i] : offs[i + 1]], axis=0)
         for i in range(packed_s.n_groups)]
    )
    got_words, got_cards = (np.asarray(x) for x in run_s())
    assert np.array_equal(got_words, want_words)
    want_cards = [int(np.unpackbits(w.view(np.uint8)).sum()) for w in want_words]
    assert got_cards.tolist() == want_cards
