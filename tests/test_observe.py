"""The unified metrics & span subsystem (ISSUE 1): registry semantics
(incl. under a thread hammer), span nesting, exporter golden formats, the
bench sidecar, and facade parity — ``insights.dispatch_counters()`` /
``tracing.timings()`` must keep their pre-migration shapes."""

import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, insights, observe, tracing
from roaringbitmap_tpu.observe import Registry, MetricError
from roaringbitmap_tpu.parallel import store


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counter_gauge_basics():
    reg = Registry()
    c = reg.counter("rb_tpu_test_total", "help text", ("kind",))
    c.inc(labels=("a",))
    c.inc(2, ("a",))
    c.inc(labels={"kind": "b"})
    assert c.get(("a",)) == 3 and c.get(("b",)) == 1
    assert c.get(("missing",)) == 0  # read-only: no series created
    assert set(c.series()) == {("a",), ("b",)}
    with pytest.raises(MetricError):
        c.inc(-1, ("a",))  # counters only go up
    g = reg.gauge("rb_tpu_test_gauge", "", ("kind",))
    g.set(10, ("x",))
    g.dec(4, ("x",))
    assert g.get(("x",)) == 6


def test_registration_idempotent_and_conflicts_loud():
    reg = Registry()
    c1 = reg.counter("rb_tpu_dup_total", "h", ("a",))
    assert reg.counter("rb_tpu_dup_total", "h", ("a",)) is c1
    with pytest.raises(MetricError):
        reg.gauge("rb_tpu_dup_total", "h", ("a",))  # kind conflict
    with pytest.raises(MetricError):
        reg.counter("rb_tpu_dup_total", "h", ("a", "b"))  # label conflict
    with pytest.raises(MetricError):
        reg.counter("0bad name")


def test_label_arity_checked():
    reg = Registry()
    c = reg.counter("rb_tpu_arity_total", "", ("a", "b"))
    with pytest.raises(MetricError):
        c.inc(1, ("only-one",))
    with pytest.raises(MetricError):
        c.inc(1, {"a": "x", "wrong": "y"})


def test_histogram_buckets_and_snapshot():
    reg = Registry()
    h = reg.histogram("rb_tpu_test_seconds", "", ("name",), buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 3.0, 99.0):
        h.observe(v, ("x",))
    st = h.get(("x",))
    assert st["count"] == 5 and st["sum"] == pytest.approx(102.65)
    # per-slot: <=0.1 gets 0.05 and the exactly-equal 0.1; 0.5 -> <=1;
    # 3.0 -> <=10; 99.0 -> +Inf overflow
    assert st["slots"] == [2, 1, 1, 1]
    snap = reg.snapshot()
    sample = snap["rb_tpu_test_seconds"]["samples"][0]
    assert sample["labels"] == {"name": "x"}
    assert sample["buckets"] == {"0.1": 2, "1": 3, "10": 4, "+Inf": 5}
    json.dumps(snap)  # plain dicts only


def test_reset_keeps_definitions():
    reg = Registry()
    c = reg.counter("rb_tpu_reset_total", "", ("k",))
    c.inc(5, ("a",))
    reg.reset()
    assert c.get(("a",)) == 0
    assert reg.get("rb_tpu_reset_total") is c


def test_counter_hammer_threadsafe():
    """8 writers x 2000 atomic incs across 4 label series lose nothing."""
    reg = Registry()
    c = reg.counter("rb_tpu_hammer_total", "", ("k",))
    h = reg.histogram("rb_tpu_hammer_seconds", "", ("k",), buckets=(1.0,))

    def work(i):
        for j in range(2000):
            c.inc(1, (str(j % 4),))
            h.observe(0.5, ("h",))

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(work, range(8)))
    assert sum(c.get((str(k),)) for k in range(4)) == 16000
    assert h.get(("h",))["count"] == 16000


def test_op_timer_hammer_threadsafe():
    """The ISSUE 1 satellite: concurrent op_timer must not lose increments
    (the old bare defaultdict mutation could)."""
    tracing.reset_timings()

    def work(i):
        for _ in range(500):
            with tracing.op_timer("hammer-phase"):
                pass

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(work, range(8)))
    t = tracing.timings()["hammer-phase"]
    assert t["count"] == 4000
    assert tracing._TIMINGS["hammer-phase"][0] == 4000  # legacy path agrees


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_paths():
    observe.reset_spans()
    with observe.span("outer"):
        assert observe.current_path() == "outer" and observe.depth() == 1
        with observe.span("inner") as path:
            assert path == "outer/inner"
            assert observe.depth() == 2
    assert observe.depth() == 0
    t = observe.span_timings()
    assert set(t) == {"outer", "outer/inner"}
    assert t["outer/inner"]["count"] == 1


def test_span_stack_unwinds_on_exception():
    observe.reset_spans()
    with pytest.raises(RuntimeError):
        with observe.span("boom"):
            raise RuntimeError("x")
    assert observe.depth() == 0
    assert observe.span_timings()["boom"]["count"] == 1  # still recorded


def test_span_stacks_are_thread_local():
    observe.reset_spans()
    seen = {}
    barrier = threading.Barrier(2)

    def work(name):
        with observe.span(name):
            barrier.wait(timeout=10)
            seen[name] = observe.current_path()

    threads = [threading.Thread(target=work, args=(n,)) for n in ("t1", "t2")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen == {"t1": "t1", "t2": "t2"}  # no cross-thread nesting


def test_op_timer_records_span_nesting():
    tracing.reset_timings()
    with tracing.op_timer("a"):
        with tracing.op_timer("b"):
            pass
    assert set(observe.span_timings()) == {"a", "a/b"}
    # flat facade unaffected by nesting
    assert set(tracing.timings()) == {"a", "b"}


def test_annotate_only_swallows_missing_jax(monkeypatch):
    """The over-broad `except Exception` fix: a real TraceAnnotation
    failure propagates; only ImportError/AttributeError degrade."""
    import jax

    class Boom:
        def __init__(self, name):
            raise RuntimeError("real profiler bug")

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", Boom)
    with pytest.raises(RuntimeError, match="real profiler bug"):
        with tracing.annotate("x"):
            pass
    monkeypatch.delattr(jax.profiler, "TraceAnnotation")
    tracing.reset_timings()
    with tracing.annotate("degraded"):  # AttributeError -> plain timer
        pass
    assert tracing.timings()["degraded"]["count"] == 1


# ---------------------------------------------------------------------------
# exporters: golden formats
# ---------------------------------------------------------------------------


def _golden_registry():
    reg = Registry()
    c = reg.counter("rb_tpu_g_total", "dispatches", ("kind", "engine"))
    c.inc(3, ("wide", "xla"))
    g = reg.gauge("rb_tpu_g_bytes", "resident", ("kind",))
    g.set(512, ("flat",))
    h = reg.histogram("rb_tpu_g_seconds", "spans", ("name",), buckets=(0.5, 2.0))
    h.observe(0.25, ("pack",))
    h.observe(1.0, ("pack",))
    h.observe(9.0, ("pack",))
    return reg


def test_prometheus_golden_format():
    text = observe.prometheus_text(_golden_registry())
    assert text.splitlines() == [
        "# HELP rb_tpu_g_bytes resident",
        "# TYPE rb_tpu_g_bytes gauge",
        'rb_tpu_g_bytes{kind="flat"} 512',
        "# HELP rb_tpu_g_seconds spans",
        "# TYPE rb_tpu_g_seconds histogram",
        'rb_tpu_g_seconds_bucket{name="pack",le="0.5"} 1',
        'rb_tpu_g_seconds_bucket{name="pack",le="2"} 2',
        'rb_tpu_g_seconds_bucket{name="pack",le="+Inf"} 3',
        'rb_tpu_g_seconds_sum{name="pack"} 10.25',
        'rb_tpu_g_seconds_count{name="pack"} 3',
        "# HELP rb_tpu_g_total dispatches",
        "# TYPE rb_tpu_g_total counter",
        'rb_tpu_g_total{kind="wide",engine="xla"} 3',
    ]


def test_prometheus_label_escaping():
    reg = Registry()
    reg.counter("rb_tpu_esc_total", "", ("p",)).inc(1, ('we"ird\\pa\nth',))
    line = observe.prometheus_text(reg).splitlines()[-1]
    assert line == 'rb_tpu_esc_total{p="we\\"ird\\\\pa\\nth"} 1'


def test_jsonl_golden_format():
    lines = observe.jsonl_lines(_golden_registry())
    recs = [json.loads(l) for l in lines]
    assert [r["name"] for r in recs] == [
        "rb_tpu_g_bytes",
        "rb_tpu_g_seconds",
        "rb_tpu_g_total",
    ]
    assert recs[0] == {
        "labels": {"kind": "flat"},
        "name": "rb_tpu_g_bytes",
        "type": "gauge",
        "value": 512,
    }
    assert recs[1]["count"] == 3 and recs[1]["buckets"] == {
        "0.5": 1,
        "2": 2,
        "+Inf": 3,
    }
    assert recs[2]["value"] == 3 and recs[2]["labels"] == {
        "kind": "wide",
        "engine": "xla",
    }


def test_write_exports_atomic(tmp_path):
    reg = _golden_registry()
    prom = tmp_path / "metrics.prom"
    jl = tmp_path / "metrics.jsonl"
    observe.write_prometheus(str(prom), reg)
    observe.write_jsonl(str(jl), reg)
    assert prom.read_text() == observe.prometheus_text(reg)
    for line in jl.read_text().splitlines():
        json.loads(line)
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


def test_metrics_sidecar_written_even_on_failure(tmp_path):
    path = tmp_path / "side" / "BENCH_METRICS.json"
    with pytest.raises(RuntimeError):
        with observe.metrics_sidecar(str(path)):
            raise RuntimeError("bench died")
    m = json.loads(path.read_text())
    assert m["schema"] == observe.SIDECAR_SCHEMA
    assert {"kernel", "layout", "transfer_bytes", "spans", "registry"} <= set(m)


# ---------------------------------------------------------------------------
# facade parity + migration wiring
# ---------------------------------------------------------------------------


def _workload():
    bms = [RoaringBitmap(np.arange(i, 70000 + i, dtype=np.uint32)) for i in range(3)]
    packed = store.pack_groups(store.group_by_key(bms))
    words, cards = store.reduce_packed(packed, op="or")
    store.unpack_to_bitmap(packed.group_keys, words, cards)
    return insights.dispatch_counters(), tracing.timings()


def test_facade_parity_shapes_and_determinism():
    """dispatch_counters()/timings() keep their pre-registry shapes, and an
    identical workload after reset reproduces identical counters — the
    'before vs after migration' equivalence, observable from either side."""
    insights.reset_dispatch_counters()
    tracing.reset_timings()
    first_counters, first_timings = _workload()
    # legacy shape: exactly these top-level keys, str keys, int values
    assert set(first_counters) == {
        "kernel", "layout", "transfer_bytes", "pairwise", "probes", "native",
    }
    for section in ("kernel", "layout", "transfer_bytes", "pairwise"):
        assert all(
            isinstance(k, str) and isinstance(v, int)
            for k, v in first_counters[section].items()
        )
    # ISSUE 8 tiering: a freshly packed set's first (and here only)
    # reduce rides the fused gather+reduce dispatch
    assert first_counters["kernel"] == {"grouped_fused/xla": 1}
    assert sum(first_counters["layout"].values()) == 1
    for entry in first_timings.values():
        assert set(entry) == {"count", "total_s", "mean_ms"}
    # ISSUE 8: the cold marshal no longer host-packs (device-side
    # expansion); the unpack span is the stable host phase of the workload
    assert first_timings["store.unpack_to_bitmap"]["count"] == 1

    insights.reset_dispatch_counters()
    tracing.reset_timings()
    second_counters, second_timings = _workload()
    assert second_counters == first_counters
    assert set(second_timings) == set(first_timings)


def test_facades_are_registry_views():
    """The legacy module globals and the registry are the same numbers."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    insights.reset_dispatch_counters()
    _workload()
    reg_counter = observe.REGISTRY.get(observe.KERNEL_DISPATCH_TOTAL)
    assert (
        reg_counter.get(("grouped_fused", "xla"))
        == pk.DISPATCH_COUNTS[("grouped_fused", "xla")]
        == 1
    )
    layout = observe.REGISTRY.get(observe.STORE_LAYOUT_TOTAL)
    assert {lv[0]: v for lv, v in layout.series().items()} == dict(store.LAYOUT_COUNTS)
    xfer = observe.REGISTRY.get(observe.STORE_TRANSFER_BYTES_TOTAL)
    assert {lv[0]: v for lv, v in xfer.series().items()} == dict(store.TRANSFER_BYTES)


def test_countermap_legacy_mutation_roundtrip():
    """External `COUNTS[key] += 1` writers keep working on the facades."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    pk.DISPATCH_COUNTS.clear()
    pk.DISPATCH_COUNTS[("custom", "engine")] += 1
    pk.DISPATCH_COUNTS[("custom", "engine")] += 2
    assert pk.DISPATCH_COUNTS[("custom", "engine")] == 3
    assert ("custom", "engine") in pk.DISPATCH_COUNTS
    assert ("absent", "engine") not in pk.DISPATCH_COUNTS
    assert pk.DISPATCH_COUNTS[("absent", "engine")] == 0
    assert insights.dispatch_counters()["kernel"] == {"custom/engine": 3}
    del pk.DISPATCH_COUNTS[("custom", "engine")]
    assert len(pk.DISPATCH_COUNTS) == 0


def test_resident_gauge_rises_and_falls_with_working_set():
    """rb_tpu_store_resident_bytes tracks what is resident NOW: freeing a
    PackedGroups (and its cached device arrays) decrements the gauge."""
    gauge = observe.REGISTRY.get(observe.STORE_RESIDENT_BYTES)
    gauge.clear()
    bms = [RoaringBitmap(np.arange(i, 70000 + i, dtype=np.uint32)) for i in range(3)]
    packed = store.pack_groups(store.group_by_key(bms))
    packed.device_words
    packed.padded_device(0)
    flat = gauge.get(("flat_rows",))
    padded = gauge.get(("padded_groups",))
    assert flat == packed.words.nbytes and padded > 0
    del packed
    assert gauge.get(("flat_rows",)) == 0
    assert gauge.get(("padded_groups",)) == 0


def test_packed_groups_close_is_explicit_and_idempotent():
    """The ISSUE 2 satellite: long-lived processes must not depend on GC
    timing for truthful residency — close() settles the gauge NOW, the
    context manager drives it, __del__ after close() is a no-op, and a
    closed working set re-accounts if touched again."""
    gauge = observe.REGISTRY.get(observe.STORE_RESIDENT_BYTES)
    gauge.clear()
    bms = [RoaringBitmap(np.arange(i, 70000 + i, dtype=np.uint32)) for i in range(3)]
    with store.pack_groups(store.group_by_key(bms)) as packed:
        packed.device_words
        packed.padded_device(0)
        assert gauge.get(("flat_rows",)) == packed.words.nbytes
        assert gauge.get(("padded_groups",)) > 0
    # context exit closed it: gauge settled with the object still alive
    assert gauge.get(("flat_rows",)) == 0
    assert gauge.get(("padded_groups",)) == 0
    packed.close()  # idempotent: no double-decrement below zero
    assert gauge.get(("flat_rows",)) == 0
    # a closed set stays usable and re-accounts on next touch
    packed.device_words
    assert gauge.get(("flat_rows",)) == packed.words.nbytes
    del packed  # __del__ closes the re-opened state exactly once
    assert gauge.get(("flat_rows",)) == 0


def test_probe_ledgers_survive_reset_consistently():
    """reset_dispatch_counters leaves BOTH probe views (the _PROBED cache
    and the registry probe counter) alone — clearing only one would make
    dispatch_counters()['probes'] and BENCH_METRICS.json disagree."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    probe = observe.REGISTRY.get(observe.KERNEL_PROBE_TOTAL)
    probe.inc(1, ("testkind", "or", "cpu", "ok"))
    pk._PROBED[("testkind", "or", (1, 2048), "cpu")] = True
    try:
        insights.reset_dispatch_counters()
        assert probe.get(("testkind", "or", "cpu", "ok")) == 1
        assert ("testkind", "or", (1, 2048), "cpu") in pk._PROBED
    finally:
        probe.remove(("testkind", "or", "cpu", "ok"))
        pk._PROBED.pop(("testkind", "or", (1, 2048), "cpu"), None)


def test_serialization_byte_accounting():
    observe.REGISTRY.get(observe.SERIAL_BYTES_TOTAL).clear()
    bm = RoaringBitmap(np.arange(0, 100000, 3, dtype=np.uint32))
    data = bm.serialize()
    from roaringbitmap_tpu import serialization

    assert serialization.deserialize(data) == bm
    ser = observe.REGISTRY.get(observe.SERIAL_BYTES_TOTAL)
    assert ser.get(("serialize",)) == len(data)
    assert ser.get(("deserialize",)) == len(data)


def test_sidecar_snapshot_reflects_workload():
    insights.reset_dispatch_counters()
    tracing.reset_timings()
    _workload()
    side = observe.sidecar_snapshot()
    assert side["kernel"] == {"grouped_fused/xla": 1}
    assert sum(side["layout"].values()) == 1
    assert side["transfer_bytes"]  # the working set shipped at least once
    assert "store.unpack_to_bitmap" in side["spans"]
    # reduce span nests the probe/dispatch work under the layout it chose
    assert any(p.startswith("store.reduce.") for p in side["spans"])
    # ISSUE 8: the marshal records as the device_expand pack stage now
    lat = observe.sidecar_snapshot()["latency"]
    assert "device_expand" in lat["rb_tpu_store_pack_stage_seconds"]


# ---------------------------------------------------------------------------
# lock-order witness (ISSUE 3: dynamic complement of the static
# lock-discipline rule) — the op_timer hammer re-run with the tracing-side
# locks instrumented: registry RLock + legacy _TIMINGS lock must never
# nest inconsistently (a cycle is a potential deadlock).
# ---------------------------------------------------------------------------


def test_op_timer_hammer_lock_order_witness(monkeypatch):
    from roaringbitmap_tpu.analysis import LockWitness
    from roaringbitmap_tpu.observe import spans

    tracing.reset_timings()
    w = LockWitness()
    reg_lock = observe.REGISTRY._lock  # one RLock shared by every metric
    monkeypatch.setattr(
        tracing._OP_SECONDS, "_lock", w.wrap("observe.registry", reg_lock)
    )
    monkeypatch.setattr(
        spans.SPAN_SECONDS, "_lock", w.wrap("observe.registry", reg_lock)
    )
    monkeypatch.setattr(
        tracing, "_TIMINGS_LOCK", w.wrap("tracing._TIMINGS", tracing._TIMINGS_LOCK)
    )

    def work(i):
        for _ in range(300):
            with tracing.op_timer("witness-phase"):
                pass

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(work, range(8)))
    assert tracing.timings()["witness-phase"]["count"] == 2400
    # both instrumented locks were actually exercised...
    assert w.acquisitions["observe.registry"] >= 2400
    assert w.acquisitions["tracing._TIMINGS"] >= 2400
    # ...and no inconsistent ordering (cycle) was observed: op_timer takes
    # the registry lock and the legacy lock sequentially, never nested both
    # ways
    w.assert_consistent()
