"""Serving tier tests (ISSUE 14): bounded tenant registry, admission
determinism under a fake clock, shed-never-loses-a-result semantics,
the 16-thread hammer with the lock witness proving the serve
queue/quota locks are leaves, p99-from-histogram vs the numpy
percentile oracle under concurrent load, the serving sentinel rules,
per-tenant byte-share accounting, and the concurrent-vs-serial
differential (fuzz family 28 seed pin)."""

import threading
import time

import numpy as np
import pytest

from roaringbitmap_tpu import observe
from roaringbitmap_tpu.analysis.lockwitness import LockWitness
from roaringbitmap_tpu.models.roaring import RoaringBitmap
from roaringbitmap_tpu.observe import health, outcomes
from roaringbitmap_tpu.observe import timeline as tl
from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.robust import faults
from roaringbitmap_tpu.robust.errors import TransientDeviceError
from roaringbitmap_tpu.serve import (
    AdmissionController,
    LoadHarness,
    ShedRejection,
    TenantProfile,
    build_requests,
)
from roaringbitmap_tpu.serve import admission as adm_mod
from roaringbitmap_tpu.serve import slo
from roaringbitmap_tpu.cost import admission as admission_cost


@pytest.fixture(autouse=True)
def _serve_state():
    """Every test starts from a clean tenant registry / admission /
    ledger state and leaves none behind."""
    slo.reset()
    adm_mod.CONTROLLER.reset()
    outcomes.reset()
    admission_cost.MODEL.reset()
    yield
    slo.reset()
    adm_mod.CONTROLLER.reset()
    outcomes.reset()
    admission_cost.MODEL.reset()


def _corpus(n=6, seed=3):
    rng = np.random.default_rng(seed)
    return [
        RoaringBitmap(
            np.sort(rng.choice(1 << 18, 1200, replace=False)).astype(np.uint32)
        )
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# bounded declared tenant registry
# ---------------------------------------------------------------------------


def test_tenant_registry_is_bounded_and_declared():
    reg = slo.TenantRegistry(max_tenants=2)
    reg.declare("a", quota_qps=10)
    reg.declare("b", quota_qps=10)
    assert reg["a"] == "a" and "b" in reg
    with pytest.raises(KeyError):
        reg["undeclared"]
    with pytest.raises(ValueError):
        reg.declare("c", quota_qps=10)  # capacity: the cardinality bound
    # idempotent re-declaration updates the quota, no new slot
    reg.declare("a", quota_qps=99)
    assert reg.quota("a")["quota_qps"] == 99


def test_record_rejects_undeclared_tenant_and_unknown_outcome():
    slo.TENANTS.declare("t-known", quota_qps=10)
    with pytest.raises(KeyError):
        slo.record("t-unknown", "ok", execute_s=0.01)
    with pytest.raises(ValueError):
        slo.record("t-known", "not-an-outcome")
    slo.record("t-known", "ok", queue_s=0.001, execute_s=0.01)
    assert slo.quantiles("t-known", "execute")["p99"] > 0


# ---------------------------------------------------------------------------
# admission determinism under a fake clock
# ---------------------------------------------------------------------------


def _verdict_seq(controller, script):
    out = []
    for tenant, now in script:
        t = controller.admit(tenant, now=now, wait=False)
        out.append(t.verdict)
        if t.admitted:
            t.release()
    return out


def test_admission_deterministic_under_fake_clock():
    slo.TENANTS.declare("det", quota_qps=2.0, burst=2.0)
    script = [("det", 0.0)] * 4 + [("det", 1.0)] * 3 + [("det", 10.0)] * 3
    a = AdmissionController(max_inflight=8, queue_limit=0, clock=lambda: 0.0)
    b = AdmissionController(max_inflight=8, queue_limit=0, clock=lambda: 0.0)
    va, vb = _verdict_seq(a, script), _verdict_seq(b, script)
    assert va == vb, "same (tenant, now) script produced different verdicts"
    # burst 2 at t=0: two admits then sheds; rate 2/s refills 2 by t=1,
    # and the t=10 batch is back to a full burst
    assert va[:4] == ["admit", "admit", "shed", "shed"]
    assert va[4:7] == ["admit", "admit", "shed"]
    assert va[7:9] == ["admit", "admit"]


def test_admission_queue_verdict_blocks_until_release_and_joins():
    slo.TENANTS.declare("q-t", quota_qps=1000, burst=1000)
    c = AdmissionController(max_inflight=1, queue_limit=4, queue_timeout_s=5.0)
    first = c.admit("q-t")
    assert first.verdict == "admit" and first.admitted
    got = {}

    def second():
        got["ticket"] = c.admit("q-t")

    t = threading.Thread(target=second)
    t.start()
    time.sleep(0.05)
    assert "ticket" not in got, "queued request did not block on the full cap"
    first.release()
    t.join(timeout=5.0)
    tk = got["ticket"]
    assert tk.verdict == "queue" and tk.admitted and tk.queue_s > 0
    tk.release()
    # the queue verdict joined its measured wait against the predicted one
    joined = [e for e in outcomes.tail() if e["site"] == "serve.admit"]
    assert any(e["engine"] == "queue" and e["measured_s"] > 0 for e in joined)


def test_admission_queue_timeout_degrades_to_typed_shed():
    slo.TENANTS.declare("to-t", quota_qps=1000, burst=1000)
    c = AdmissionController(max_inflight=1, queue_limit=4, queue_timeout_s=0.05)
    first = c.admit("to-t")
    second = c.admit("to-t")  # cap full, queue, times out
    assert second.verdict == "shed" and not second.admitted
    first.release()
    with pytest.raises(ShedRejection):
        held = c.admit("to-t")
        try:
            c.admit_or_raise("to-t")
        finally:
            held.release()


def test_admission_fails_open_under_injected_fault():
    slo.TENANTS.declare("fault-t", quota_qps=0.5, burst=1.0)
    c = AdmissionController(max_inflight=4, queue_limit=0)
    with faults.inject("serve.admit", TransientDeviceError, every=1):
        tickets = [c.admit("fault-t") for _ in range(5)]
    # quota would have shed 4 of 5; the broken verdict path must admit
    # everything (fail open) — admission is never a correctness gate
    assert all(t.admitted and t.degraded for t in tickets)
    for t in tickets:
        t.release()
    assert c.stats()["inflight"] == 0


# ---------------------------------------------------------------------------
# shed-never-loses-a-result
# ---------------------------------------------------------------------------


def test_shed_returns_typed_rejection_never_a_wrong_answer():
    corpus = _corpus()
    profiles = [TenantProfile("tight", quota_qps=2.0, burst=2.0)]
    harness = LoadHarness(
        corpus, profiles, threads=4, use_fusion=False,
        admission=AdmissionController(max_inflight=8, queue_limit=0),
    )
    requests = build_requests(corpus, profiles, 20, seed=5)
    oracle = harness.run_serial(requests)
    report = harness.run(requests)
    assert report.shed > 0, "tight quota shed nothing"
    assert report.served > 0, "burst budget served nothing"
    for got, want in zip(report.results, oracle):
        if isinstance(got, ShedRejection):
            assert got.tenant == "tight"
        else:
            assert got == want, "a served result diverged from the oracle"
    n_typed = sum(1 for r in report.results if isinstance(r, ShedRejection))
    assert n_typed == report.shed
    assert all(r is not None for r in report.results)


def test_concurrent_harness_bitexact_vs_serial_two_levels():
    corpus = _corpus()
    profiles = [
        TenantProfile("lv-a", weight=2.0, quota_qps=1e6),
        TenantProfile("lv-b", weight=1.0, quota_qps=1e6),
    ]
    requests = build_requests(corpus, profiles, 18, seed=9)
    oracle = None
    for threads in (2, 6):
        harness = LoadHarness(
            corpus, profiles, threads=threads,
            admission=AdmissionController(max_inflight=2 * threads),
        )
        if oracle is None:
            oracle = harness.run_serial(requests)
        report = harness.run(requests)
        assert report.shed == 0
        for got, want in zip(report.results, oracle):
            assert got == want
        rows = report.tenant_rows()
        assert sum(1 for r in rows.values() if r["served"]) == 2


def test_fuzz_family_28_seed_pin():
    from roaringbitmap_tpu import fuzz

    fuzz.verify_serve_invariance(
        "concurrent-serve-vs-serial", iterations=3, seed=58
    )


# ---------------------------------------------------------------------------
# 16-thread hammer: serve queue/quota locks are leaves
# ---------------------------------------------------------------------------


def test_serve_locks_are_leaves_hammer_16_threads():
    slo.TENANTS.declare("hammer-t", quota_qps=1e9, burst=1e9)
    c = AdmissionController(max_inflight=64, queue_limit=8)
    w = LockWitness()
    adm_lock = threading.Lock()
    c._cond = threading.Condition(w.wrap("serve.admission", adm_lock))
    slo_lock = slo.TENANTS._lock
    slo.TENANTS._lock = w.wrap("serve.slo", slo_lock)
    reg_lock = observe.REGISTRY._lock
    observe.REGISTRY._lock = w.wrap("registry", reg_lock)
    rec_lock = tl.RECORDER._lock
    tl.RECORDER._lock = w.wrap("recorder", rec_lock)
    prev_mode = tl.mode_name()
    tl.configure(mode="on")
    stop = time.monotonic() + 1.0
    errors = []

    def worker(i):
        k = 0
        while time.monotonic() < stop:
            k += 1
            try:
                t = c.admit("hammer-t")
                slo.record(
                    "hammer-t", "ok", queue_s=t.queue_s, execute_s=1e-5 * (i + 1)
                )
                if k % 3 == 0:
                    c.stats()
                if k % 5 == 0:
                    slo.tenant_rows()  # concurrent reader
                t.release()
            except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        tl.configure(mode=prev_mode)
        slo.TENANTS._lock = slo_lock
        observe.REGISTRY._lock = reg_lock
        tl.RECORDER._lock = rec_lock
    assert not errors
    w.assert_consistent()
    assert w.acquisitions.get("serve.admission", 0) > 0
    assert w.acquisitions.get("serve.slo", 0) > 0
    # leaf property: nothing is ever acquired while holding a serve lock
    for leaf in ("serve.admission", "serve.slo"):
        assert not [e for e in w.edges if e[0] == leaf], sorted(w.edges)


# ---------------------------------------------------------------------------
# p99 from the registry histogram vs the numpy percentile oracle
# ---------------------------------------------------------------------------


def test_p99_histogram_matches_numpy_oracle_under_concurrent_load():
    slo.TENANTS.declare("p99-t", quota_qps=1e9, burst=1e9)
    all_vals = []
    vals_lock = threading.Lock()
    errors = []

    def worker(i):
        rng = np.random.default_rng(1000 + i)
        vals = np.exp(rng.normal(-6.0, 1.0, size=400))  # ~ms-scale, heavy tail
        try:
            for v in vals:
                slo.record("p99-t", "ok", execute_s=float(v))
        except Exception as e:  # rb-ok: exception-hygiene -- hammer collects escapes to assert none happened
            errors.append(e)
        with vals_lock:
            all_vals.extend(vals.tolist())

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    want = float(np.percentile(np.asarray(all_vals), 99))
    got = slo.quantiles("p99-t", "execute")["p99"]
    # the log grid has ratio 10^(1/8) ~ 1.334 between bounds: the
    # estimate must land within one bucket ratio of the order statistic
    ratio = 10 ** (1 / 8)
    assert want / ratio <= got <= want * ratio, (got, want)
    st = observe.REGISTRY.get(observe.registry.SERVE_LATENCY_SECONDS).get(
        ("p99-t", "execute")
    )
    assert st["count"] == len(all_vals), "concurrent observes lost samples"


# ---------------------------------------------------------------------------
# the serving sentinel rules
# ---------------------------------------------------------------------------


def _snap_pair(traffic_fn):
    """Two chained health snapshots around ``traffic_fn`` so windowed
    probes see exactly that traffic as their per-tick delta."""
    rules = [
        r for r in health.DEFAULT_RULES
        if r.name in (
            "serving-p99-breach", "tenant-saturation", "serving-p99-pressure"
        )
    ]
    s1 = health.snapshot(refresh_hbm=False)
    for r in rules:
        r.probe(s1)  # populate s1.sums (the arm tick)
    traffic_fn()
    s2 = health.snapshot(prev_sums=s1.sums, refresh_hbm=False)
    return {r.name: r.probe(s2) for r in rules}


def test_serving_p99_breach_rule_windows_the_histogram():
    slo.TENANTS.declare("slow-t", quota_qps=1e9, burst=1e9)
    slo.record("slow-t", "ok", execute_s=0.001)  # series exists pre-arm

    def slow_burst():
        for _ in range(10):
            slo.record("slow-t", "ok", execute_s=1.2)

    values = _snap_pair(slow_burst)
    rule = next(r for r in health.DEFAULT_RULES if r.name == "serving-p99-breach")
    assert values["serving-p99-breach"] is not None
    assert values["serving-p99-breach"] >= rule.warn
    # a quiet window clears: the next delta has no movement
    values2 = _snap_pair(lambda: None)
    assert rule.band(values2["serving-p99-breach"]) == health.OK


def test_tenant_saturation_rule_judges_shed_fraction():
    slo.TENANTS.declare("sat-t", quota_qps=0.5, burst=1.0)
    c = AdmissionController(max_inflight=8, queue_limit=0, clock=lambda: 0.0)
    # series must exist before the arm tick (first sight reports 0)
    c.admit("sat-t", now=0.0, wait=False)
    for _ in range(3):
        c.admit("sat-t", now=0.0, wait=False)  # mint the shed series

    def overload():
        for _ in range(20):
            t = c.admit("sat-t", now=0.0, wait=False)
            if t.admitted:
                t.release()

    values = _snap_pair(overload)
    rule = next(r for r in health.DEFAULT_RULES if r.name == "tenant-saturation")
    assert values["tenant-saturation"] is not None
    assert values["tenant-saturation"] >= rule.critical
    # below the per-tick volume floor the rule abstains (no data), so a
    # single stray shed can never page anyone
    values2 = _snap_pair(
        lambda: c.admit("sat-t", now=0.0, wait=False)
    )
    assert values2["tenant-saturation"] is None


# ---------------------------------------------------------------------------
# byte share + sidecar/observatory surfaces
# ---------------------------------------------------------------------------


def test_tenant_byte_share_over_pack_cache():
    corpus = _corpus(6, seed=11)
    other = _corpus(4, seed=12)
    slo.TENANTS.declare("bs-t", quota_qps=10)
    store.PACK_CACHE.close()
    try:
        store.packed_for(corpus)
        share = slo.note_tenant_bytes("bs-t", corpus)
        assert share > 0
        assert store.PACK_CACHE.resident_bytes_for(
            {bm.fingerprint() for bm in other}
        ) == 0
        g = observe.REGISTRY.get(observe.registry.SERVE_TENANT_BYTES)
        assert g.get(("bs-t",)) == share
    finally:
        store.PACK_CACHE.close()


def test_sidecar_and_insights_serving_block():
    from roaringbitmap_tpu import insights
    from roaringbitmap_tpu.observe import export as obs_export

    slo.TENANTS.declare("side-t", quota_qps=1e6)
    c = AdmissionController(max_inflight=4)
    t = c.admit("side-t")
    slo.record("side-t", "ok", queue_s=t.queue_s, execute_s=0.002)
    t.release()
    side = obs_export.sidecar_snapshot()
    sv = side["serving"]
    assert "side-t" in sv["tenants"]
    row = sv["tenants"]["side-t"]
    assert row["latency"]["execute"]["p99"] > 0
    assert any(k.startswith("side-t/") for k in sv["admit"])
    live = insights.serving()
    assert isinstance(live["admission_live"], dict)
    assert "side-t" in live["tenants"]


def test_serving_off_mode_is_one_bool_check():
    slo.TENANTS.declare("off-t", quota_qps=10)
    slo.configure(enabled=False)
    try:
        # disabled: no tenant lookup, no histogram, no KeyError even for
        # an undeclared tenant — the kill switch short-circuits first
        slo.record("never-declared", "ok", execute_s=1.0)
        assert slo.note_tenant_bytes("never-declared", []) == 0
    finally:
        slo.configure(enabled=True)
    assert slo.quantiles("off-t", "execute")["p99"] == 0.0


def test_admission_refit_moves_toward_measured_truth():
    slo.TENANTS.declare("refit-t", quota_qps=1e9, burst=1e9)
    c = AdmissionController(max_inflight=8)
    # poison the admit coefficient far from reality, drive traffic, refit
    with admission_cost.MODEL._lock:
        admission_cost.MODEL.coeffs = dict(
            admission_cost.MODEL.coeffs, admit_us=admission_cost.DEFAULT_COEFFS[
                "admit_us"] * 64,
        )
    poisoned = admission_cost.MODEL.coeffs["admit_us"]
    for _ in range(12):
        c.admit("refit-t").release()
    report = admission_cost.MODEL.refit_from_outcomes(min_samples=4)
    assert "admit_us" in report["moved"]
    assert admission_cost.MODEL.coeffs["admit_us"] < poisoned
    assert admission_cost.MODEL.provenance == "refit-from-traffic"
    # round-trip through the facade state protocol
    from roaringbitmap_tpu import cost

    state = cost.AUTHORITIES["serve-admission"].state()
    admission_cost.MODEL.reset()
    assert cost.AUTHORITIES["serve-admission"].load_state(state)
    assert admission_cost.MODEL.provenance == "refit-from-traffic"


# ---------------------------------------------------------------------------
# latency classes + SLO budgets (ISSUE 19)
# ---------------------------------------------------------------------------


def test_latency_class_declaration_and_budget_gauge():
    slo.TENANTS.declare("lc-int", latency_class="interactive")
    slo.TENANTS.declare("lc-bal", latency_class="balanced", p99_budget_ms=40.0)
    slo.TENANTS.declare("lc-def")  # default class: batch
    assert slo.TENANTS.latency_class("lc-int") == "interactive"
    assert slo.TENANTS.p99_budget_ms("lc-int") == slo.LATENCY_CLASSES["interactive"]
    assert slo.TENANTS.p99_budget_ms("lc-bal") == 40.0
    assert slo.TENANTS.latency_class("lc-def") == slo.DEFAULT_LATENCY_CLASS
    with pytest.raises(ValueError):
        slo.TENANTS.declare("lc-bad", latency_class="platinum")
    with pytest.raises(ValueError):
        slo.TENANTS.declare("lc-neg", p99_budget_ms=-1.0)
    with pytest.raises(KeyError):
        slo.TENANTS.p99_budget_ms("never-declared")
    snap = observe.REGISTRY.snapshot()[observe.SERVE_SLO_BUDGET_SECONDS]
    by = {s["labels"]["tenant"]: s["value"] for s in snap["samples"]}
    assert by["lc-int"] == pytest.approx(
        slo.LATENCY_CLASSES["interactive"] / 1e3
    )
    assert by["lc-bal"] == pytest.approx(0.04)


def test_interactive_admission_clamps_queue_wait_to_budget():
    """An interactive tenant must never be parked in the admission queue
    past its whole declared p99 budget — queueing longer guarantees the
    breach; shedding at the budget lets the caller act."""
    slo.TENANTS.declare(
        "clamp-int", quota_qps=1e6, burst=1e6,
        latency_class="interactive", p99_budget_ms=80.0,
    )
    slo.TENANTS.declare("clamp-bat", quota_qps=1e6, burst=1e6)  # batch
    c = AdmissionController(max_inflight=1, queue_limit=8, queue_timeout_s=5.0)
    holder = c.admit("clamp-bat")
    assert holder.admitted
    try:
        t0 = time.perf_counter()
        t = c.admit("clamp-int")
        waited = time.perf_counter() - t0
    finally:
        holder.release()
    assert not t.admitted
    assert t.verdict == "shed"
    assert waited < 1.0, (
        f"interactive admit waited {waited:.3f}s against an 80ms budget"
    )


def test_serving_p99_pressure_rule_judges_declared_budgets():
    """The per-tenant-budget rule: the same absolute latency is pressure
    for a 25ms interactive tenant and nothing for a 1s batch tenant."""
    slo.TENANTS.declare(
        "pr-int", quota_qps=1e9, burst=1e9, latency_class="interactive"
    )
    slo.record("pr-int", "ok", execute_s=0.001)  # series exists pre-arm

    def hot_burst():
        for _ in range(10):
            slo.record("pr-int", "ok", execute_s=0.2)  # 8x the 25ms budget

    values = _snap_pair(hot_burst)
    rule = next(
        r for r in health.DEFAULT_RULES if r.name == "serving-p99-pressure"
    )
    assert rule.actuation == "autotune"
    assert values["serving-p99-pressure"] is not None
    assert values["serving-p99-pressure"] >= rule.critical
    # the identical burst under a batch tenant's 1s budget judges green
    slo.reset()
    slo.TENANTS.declare("pr-bat", quota_qps=1e9, burst=1e9)  # batch: 1000ms
    slo.record("pr-bat", "ok", execute_s=0.001)

    def same_burst():
        for _ in range(10):
            slo.record("pr-bat", "ok", execute_s=0.2)

    values2 = _snap_pair(same_burst)
    assert rule.band(values2["serving-p99-pressure"]) == health.OK
    # no declared budgets at all: the rule abstains (no data)
    slo.reset()
    values3 = _snap_pair(lambda: None)
    assert values3["serving-p99-pressure"] is None


def test_harness_mixed_class_profiles_report_per_class_quantiles():
    """The mixed interactive+batch workload the all-batch harness could
    not express: per-class p50/p99 rows, per-tenant SLO verdicts, and
    bit-exactness against the serial oracle under hedged dispatch."""
    corpus = _corpus(6, seed=21)
    profiles = [
        TenantProfile(
            "mx-int", weight=1.0, quota_qps=1e6,
            latency_class="interactive",
        ),
        TenantProfile("mx-bat", weight=2.0, quota_qps=1e6),  # batch default
    ]
    h = LoadHarness(
        corpus, profiles, threads=4, use_fusion=True,
        admission=AdmissionController(max_inflight=64, queue_limit=64),
    )
    reqs = build_requests(corpus, profiles, n_requests=60, seed=5)
    report = h.run(reqs)
    assert report.shed == 0
    serial = h.run_serial(reqs)
    for got, want in zip(report.results, serial):
        assert got == want
    rows = report.tenant_rows()
    assert rows["mx-int"]["latency_class"] == "interactive"
    assert rows["mx-int"]["p99_budget_ms"] == slo.LATENCY_CLASSES["interactive"]
    assert rows["mx-bat"]["latency_class"] == "batch"
    assert rows["mx-int"]["total_p99_ms"] is not None
    assert rows["mx-int"]["slo_ok"] in (True, False)
    classes = report.class_rows()
    assert set(classes) == {"interactive", "batch"}
    assert classes["interactive"]["tenants"] == ["mx-int"]
    assert classes["interactive"]["budget_ms"] == (
        slo.LATENCY_CLASSES["interactive"]
    )
    assert classes["batch"]["served"] + classes["interactive"]["served"] == 60
    for cls in classes.values():
        assert cls["p99_ms"] is not None and cls["p50_ms"] <= cls["p99_ms"]
