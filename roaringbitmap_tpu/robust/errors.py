"""Project exception taxonomy for the fault model (ISSUE 7 satellite).

Every fallback chain in the framework — device → columnar-CPU →
per-container → pure-python, native C → banded-numpy, PACK_CACHE resident
→ delta → cold repack — degrades on *some* failure; before this module
each chain decided ad hoc what "some" meant, usually with a broad except.
The taxonomy makes the routing decision a declared, classifiable fact:

* :class:`TransientDeviceError` — a transfer/dispatch hiccup that may
  succeed on retry (tunnel drop, queue timeout). Retried with jittered
  backoff at the site; degrades a tier only once retries are exhausted.
* :class:`ResourceExhausted` — HBM OOM, cache byte-budget pressure. Never
  retried at the same tier (the resource will still be exhausted);
  degrades immediately (or, for caches, evicts/spills).
* :class:`TierUnavailable` — the tier cannot serve at all right now:
  circuit breaker open, backend missing, toolchain absent. Routed past
  without retry.
* :class:`DeadlineExceeded` — a per-query deadline budget blew; remaining
  work cancels to the cheapest tier instead of blowing the caller's
  latency.

``classify(exc)`` maps *any* exception — ours, jax's ``XlaRuntimeError``
family, OS-level transport errors — onto those categories, with one
deliberate asymmetry: programming errors (``TypeError``, ``ValueError``,
``KeyError``, ``AssertionError``...) classify **fatal** and are re-raised
by the ladder. A wrong-answer bug must never be silently laundered into a
degrade — bit-exactness across tiers is the contract that makes
degradation safe in the first place (PAPER.md §L0-L4; arXiv:1709.07821's
cross-implementation equivalence argument).
"""

from __future__ import annotations

# classification categories (returned by classify())
TRANSIENT = "transient"
RESOURCE = "resource"
UNAVAILABLE = "unavailable"
DEADLINE = "deadline"
FATAL = "fatal"


class RobustError(Exception):
    """Base of the fault-model taxonomy."""

    category = FATAL


class TransientDeviceError(RobustError):
    """Retryable transfer/dispatch failure (tunnel drop, queue timeout)."""

    category = TRANSIENT


class ResourceExhausted(RobustError):
    """HBM / cache-budget exhaustion: degrade or spill, never retry."""

    category = RESOURCE


class TierUnavailable(RobustError):
    """The tier cannot serve (breaker open, backend/toolchain missing)."""

    category = UNAVAILABLE


class DeadlineExceeded(RobustError):
    """A per-query deadline budget expired mid-flight."""

    category = DEADLINE


# Substrings in an XlaRuntimeError/RuntimeError message that identify the
# runtime's own status codes (jax surfaces absl::Status codes as text).
# Only the resource family needs markers: every OTHER runtime-family error
# deliberately defaults to transient (see classify below).
_RESOURCE_MARKERS = ("RESOURCE_EXHAUSTED", "OUT_OF_MEMORY", "out of memory")


def _xla_error_types() -> tuple:
    """The live jaxlib runtime-error types, when importable (CPU-only and
    jax-free installs simply classify by the stdlib rules)."""
    types = []
    try:
        from jax.errors import JaxRuntimeError  # jax >= 0.4.14

        types.append(JaxRuntimeError)
    except (ImportError, AttributeError):
        pass
    try:
        from jax._src.lib import xla_client

        types.append(xla_client.XlaRuntimeError)
    except (ImportError, AttributeError):
        pass
    return tuple(types)


def classify(exc: BaseException) -> str:
    """Map an exception to a fault category: ``"transient"``,
    ``"resource"``, ``"unavailable"``, ``"deadline"``, or ``"fatal"``.

    The ladder degrades on everything except ``"fatal"``; retry loops act
    only on ``"transient"``. Unknown ``RuntimeError`` kinds (and the
    transport ``OSError`` subclasses) default to transient — the device
    runtimes surface transport and scheduling failures as bare
    RuntimeErrors, and misclassifying one as fatal turns a recoverable
    blip into an outage, while misclassifying it as transient costs one
    bounded retry before degrading (results stay bit-exact on the lower
    tier either way). Bare ``OSError`` stays fatal: a missing file or a
    permission error is a deterministic misconfiguration to surface."""
    if isinstance(exc, RobustError):
        return exc.category
    if isinstance(exc, MemoryError):
        return RESOURCE
    if isinstance(exc, (RuntimeError,) + _xla_error_types()):
        msg = str(exc)
        if any(m in msg for m in _RESOURCE_MARKERS):
            return RESOURCE
        return TRANSIENT
    # transport errors only — NOT bare OSError: FileNotFoundError /
    # PermissionError and friends are deterministic misconfigurations that
    # must surface, not be retried and silently degraded around
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return TRANSIENT
    return FATAL


def simulated_oom(site: str) -> Exception:
    """An HBM-OOM lookalike for fault injection: the real
    ``XlaRuntimeError`` class carrying a ``RESOURCE_EXHAUSTED`` status
    message when jaxlib exposes a constructible one, else
    :class:`ResourceExhausted`. Either way ``classify()`` returns
    ``"resource"`` — injection tests exercise the same routing the real
    allocator failure would."""
    msg = (
        f"RESOURCE_EXHAUSTED: simulated HBM OOM injected at fault site "
        f"{site!r} (rb_tpu fault injection)"
    )
    for t in _xla_error_types():
        try:
            e = t(msg)
        except TypeError:  # non-constructible binding
            continue
        if classify(e) == RESOURCE:
            return e
    return ResourceExhausted(msg)
