"""Fault model & degradation ladder (ISSUE 7 tentpole).

The robustness substrate under the pack/query pipeline: a deterministic
fault-injection framework with named sites threaded through the real
marshal path (``faults``), a project exception taxonomy with a
classify-then-route contract (``errors``), and the execution-tier ladder —
device → columnar-CPU → per-container → pure-python — with per-tier
health tracking, circuit breakers, retry-with-jittered-backoff, and
per-query deadline budgets (``ladder``). See ARCHITECTURE.md "Fault model
& degradation ladder".

Importing this package arms the ``RB_TPU_FAULTS`` seeded chaos schedule
when the env var is set (the CI chaos gate's entry point).
"""

from .errors import (
    DeadlineExceeded,
    ResourceExhausted,
    RobustError,
    TierUnavailable,
    TransientDeviceError,
    classify,
    simulated_oom,
)
from .faults import SITES, clear, fault_point, inject, install, suspended
from .faults import active
from .ladder import (
    LADDER,
    TIERS,
    Ladder,
    deadline_expired,
    deadline_remaining,
    deadline_scope,
    retry,
)

__all__ = [
    "RobustError",
    "TransientDeviceError",
    "ResourceExhausted",
    "TierUnavailable",
    "DeadlineExceeded",
    "classify",
    "simulated_oom",
    "SITES",
    "active",
    "fault_point",
    "inject",
    "install",
    "suspended",
    "clear",
    "LADDER",
    "TIERS",
    "Ladder",
    "retry",
    "deadline_scope",
    "deadline_remaining",
    "deadline_expired",
]

# Arm the env-specified chaos schedule once, at first import of the fault
# framework (scripts/ci.sh: RB_TPU_FAULTS=ci-chaos-seed).
from .faults import install_env_schedule as _install_env_schedule

_install_env_schedule()
