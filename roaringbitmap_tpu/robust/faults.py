"""Deterministic, thread-safe fault injection for the pack/query pipeline
(ISSUE 7 tentpole, part a).

The framework's four fallback chains (device → columnar-CPU →
per-container → pure-python; native C → banded-numpy; PACK_CACHE resident
→ delta → cold repack; fenced → untraced timeline) had never been
*exercised* under failure — the paths existed, the failures didn't. This
module threads named **fault sites** through the real pipeline; each site
is one ``fault_point(site)`` call at the exact place a production failure
would surface (the host→HBM ship, the device reduce dispatch, the native
kernel entry, the cache-budget admission). When no injection is active a
fault point is ONE module-int compare — the production cost is nil.

Two ways to arm faults:

* **Scoped**: ``with inject("store.ship", TransientDeviceError, every=3):``
  — a context manager installing one rule (``every=`` k-th hit, ``after=``
  all hits past the first k, ``prob=`` seeded pseudo-probability,
  ``times=`` total-fire cap). Rules are global (faults cross threads,
  exactly like real ones) but reference-counted per scope, so overlapping
  test scopes compose.
* **Seeded schedule**: ``RB_TPU_FAULTS=<seed-name>[:prob[:site+site]]``
  installs a chaos schedule at import — every listed site fires with the
  given probability (default 0.02), the error kind chosen per site
  (budget pressure → ResourceExhausted, HBM → simulated XlaRuntimeError
  OOM, the rest → TransientDeviceError). Decisions are a pure function of
  ``(seed, site, per-site hit index)``, so a replay with the same spec
  makes byte-identical decisions at every site regardless of thread
  interleaving — the determinism the fuzz family and the CI chaos gate
  (``RB_TPU_FAULTS=ci-chaos-seed``) rely on.

``suspended()`` masks every fault on the current thread — how the fuzz
oracle computes the no-fault reference result mid-schedule.
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from .. import observe as _observe
from ..observe import timeline as _timeline
from .errors import ResourceExhausted, TransientDeviceError, simulated_oom

# The registered fault sites, each one real call site in the pipeline.
# fault_point() on an unregistered site raises MetricError-style loudly —
# a typo'd site would silently never fire.
SITES: Tuple[str, ...] = (
    "store.ship",        # host->HBM transfer of packed rows (store.py)
    "store.hbm",         # HBM allocation during the ship (OOM simulation)  # rb-ok: fault-site-contract -- no route of its own: an HBM fault surfaces inside the ship transfer, so it rides store.ship's re-ship/degrade ladder route
    "store.expand",      # device-side payload expansion + overlap lane (ISSUE 8)
    "ops.dispatch",      # device reduce dispatch (store run closures, ops/)  # rb-ok: fault-site-contract -- no route of its own: dispatch faults propagate into the aggregation run and ride the "agg" ladder site's degrade/retry route
    "query.exec",        # query executor device-engine step dispatch
    "query.fusion",      # fused micro-batch execution (query/fusion.py)
    "query.hedge",       # hedged solo dispatch of an SLO-priced request (query/fusion.py)
    "serve.admit",       # serving-tier admission verdict (serve/admission.py)
    "epoch.flip",        # epoch flip of the streaming ingest log (serve/epochs.py)
    "columnar.kernel",   # columnar native batch-kernel entry (kernels.py)
    "columnar.device",   # columnar device-tier entry (columnar/device.py)
    "native.entry",      # native C tier entry probe (native/__init__.py)
    "pack_cache.budget", # resident pack-cache byte-budget admission
    "serve.maintain",    # background maintenance/compaction pass (serve/maintain.py)
    "durable.persist",   # atomic epoch snapshot persist (durable/store.py)
)

_FAULT_TOTAL = _observe.counter(
    _observe.FAULT_INJECTED_TOTAL,
    "Faults fired by the injection framework, by site",
    ("site",),
)

_lock = threading.Lock()
# every installed rule, newest last; fault_point fires the FIRST matching
# rule per hit (rule order is deterministic: install order)
_RULES: List["FaultRule"] = []  # guarded-by: _lock
_SITE_HITS: Dict[str, int] = {}  # guarded-by: _lock
# lock-free fast-path flag: number of installed rules. fault_point reads it
# unlocked — worst case a racing install is seen one call late, exactly
# like a real fault arriving one call later.
_ACTIVE = 0

_TLS = threading.local()  # .suspend: int depth of suspended() scopes


class FaultRule:
    """One armed fault: fires at ``site`` per its schedule.

    ``exc`` may be an exception class, instance, or ``callable(site) ->
    exception``. Exactly one of ``every``/``after``/``prob`` selects hits
    (``every=1`` == every hit); ``times`` caps total fires."""

    __slots__ = ("site", "exc", "every", "after", "prob", "times", "seed", "fired")

    def __init__(self, site, exc, every=None, after=None, prob=None,
                 times=None, seed=0):
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (known: {SITES})")
        if sum(x is not None for x in (every, after, prob)) != 1:
            raise ValueError("exactly one of every=/after=/prob= is required")
        if prob is not None and not (0.0 <= prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        if every is not None and every < 1:
            raise ValueError(f"every must be >= 1 (1 == every hit), got {every}")
        if after is not None and after < 0:
            raise ValueError(f"after must be >= 0, got {after}")
        if times is not None and times < 1:
            raise ValueError(f"times must be >= 1, got {times}")
        self.site = site
        self.exc = exc
        self.every = every
        self.after = after
        self.prob = prob
        self.times = times
        self.seed = int(seed)
        self.fired = 0  # guarded-by: _lock

    def _decides(self, hit: int) -> bool:
        """Pure decision for per-site hit index ``hit`` (1-based)."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.every is not None:
            return hit % self.every == 0
        if self.after is not None:
            return hit > self.after
        # seeded pseudo-probability: crc32 of (seed, site, hit) -> [0, 1).
        # A pure function of the triple, so schedule replay is exact and
        # thread-interleaving-independent (per-site hit order is the only
        # input, and the counter is advanced under the lock).
        h = zlib.crc32(f"{self.seed}:{self.site}:{hit}".encode())
        return (h & 0xFFFFFF) / float(1 << 24) < self.prob

    def _raise(self) -> None:
        e = self.exc
        if callable(e) and not isinstance(e, type):
            raise e(self.site)
        if isinstance(e, type):
            raise e(f"injected fault at site {self.site!r}")
        raise e


def fault_point(site: str) -> None:
    """The pipeline hook: raises this hit's scheduled fault, if any.

    No injection active (the production state): one global-int compare.
    Suspended on this thread (the fuzz oracle): counters do not advance,
    so the oracle run is invisible to the schedule."""
    if not _ACTIVE:
        return
    if getattr(_TLS, "suspend", 0):
        return
    with _lock:
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (known: {SITES})")
        hit = _SITE_HITS.get(site, 0) + 1
        _SITE_HITS[site] = hit
        rule = None
        for r in _RULES:
            if r.site == site and r._decides(hit):
                r.fired += 1
                rule = r
                break
    if rule is not None:
        _FAULT_TOTAL.inc(1, (site,))
        _timeline.instant("fault.injected", "fault", site=site, hit=hit)
        rule._raise()


def active() -> bool:
    return bool(_ACTIVE)


class inject:
    """Scoped fault rule (context manager)::

        with inject("ops.dispatch", TransientDeviceError, every=1):
            ...  # every device dispatch raises

    Thread-safe and composable: overlapping scopes each install their own
    rule; exiting removes exactly that rule."""

    def __init__(self, site: str, exc=TransientDeviceError, *, every=None,
                 after=None, prob=None, times=None, seed=0):
        self._rule = FaultRule(
            site, exc, every=every, after=after, prob=prob, times=times,
            seed=seed,
        )

    @property
    def fired(self) -> int:
        with _lock:
            return self._rule.fired

    def __enter__(self) -> "inject":
        global _ACTIVE
        with _lock:
            _RULES.append(self._rule)
            _ACTIVE = len(_RULES)
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with _lock:
            try:
                _RULES.remove(self._rule)
            except ValueError:  # clear() raced us: already gone
                pass
            _ACTIVE = len(_RULES)


class suspended:
    """Mask every fault point on the current thread (re-entrant): the fuzz
    family's no-fault oracle runs inside one of these, mid-schedule,
    without advancing the per-site hit counters."""

    def __enter__(self) -> "suspended":
        _TLS.suspend = getattr(_TLS, "suspend", 0) + 1
        return self

    def __exit__(self, *exc) -> None:
        _TLS.suspend -= 1


def clear() -> None:
    """Remove every installed rule and reset the per-site hit counters."""
    global _ACTIVE
    with _lock:
        _RULES.clear()
        _SITE_HITS.clear()
        _ACTIVE = 0


def site_hits() -> Dict[str, int]:
    with _lock:
        return dict(_SITE_HITS)


# ---------------------------------------------------------------------------
# seeded schedules (RB_TPU_FAULTS)
# ---------------------------------------------------------------------------

# per-site error kind for chaos schedules: the failure a production run of
# that site would actually see
_SCHEDULE_ERRORS: Dict[str, object] = {
    "store.hbm": simulated_oom,
    "pack_cache.budget": ResourceExhausted,
}


def schedule_rules(spec: str) -> List[FaultRule]:
    """Parse ``<seed-name>[:prob[:site+site+...]]`` into rules — e.g.
    ``ci-chaos-seed``, ``my-seed:0.1``, ``s1:0.5:store.ship+ops.dispatch``.
    The seed-name hashes to the decision seed, so a named schedule is fully
    reproducible from its spec string alone."""
    parts = spec.split(":")
    seed = zlib.crc32(parts[0].encode())
    prob = float(parts[1]) if len(parts) > 1 and parts[1] else 0.02
    sites = parts[2].split("+") if len(parts) > 2 and parts[2] else list(SITES)
    rules = []
    for site in sites:
        exc = _SCHEDULE_ERRORS.get(site, TransientDeviceError)
        rules.append(FaultRule(site, exc, prob=prob, seed=seed))
    return rules


def install(spec: str) -> None:
    """Install a seeded schedule (replacing any current rules)."""
    global _ACTIVE
    rules = schedule_rules(spec)
    with _lock:
        _RULES.clear()
        _SITE_HITS.clear()
        _RULES.extend(rules)
        _ACTIVE = len(_RULES)


def install_env_schedule() -> bool:
    """Arm the ``RB_TPU_FAULTS`` schedule, if the env var is set (called
    once at package import). Returns whether a schedule was installed."""
    spec = os.environ.get("RB_TPU_FAULTS", "").strip()
    if not spec:
        return False
    install(spec)
    return True
