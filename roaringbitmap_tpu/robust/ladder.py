"""Execution-tier degradation ladder (ISSUE 7 tentpole, part b/c).

One code path for every degradation the framework performs. A **site**
(``"agg"``, ``"query.exec"``, ``"columnar.device"``, ...) runs an ordered
list of **tiers** — callables producing the *same bit-exact result* by
different machinery (device reduce, columnar-CPU fold, per-container
walk, pure-python naive fold; since ISSUE 10 the columnar pairwise
engine rides the ``columnar.device`` site: device tier → columnar-CPU,
the whole pair re-executed on the host batch engine on any non-fatal
device failure). :meth:`Ladder.run` walks them top down:

* a tier whose circuit breaker is open is skipped (no attempt, no latency
  paid on a path known to be failing);
* a tier that raises is **classified** (robust/errors.py): fatal errors
  re-raise unchanged (a wrong-answer bug must never become a degrade),
  everything else records a failure against the tier's health, emits
  ``rb_tpu_degrade_total{site,from,to}`` plus a flight-recorder instant,
  and falls to the next tier;
* the bottom tier is last-resort: it is attempted even when its breaker
  is open, and its failure propagates (there is nothing below).

**Health + breaker** (per site,tier): ``trip_after`` consecutive failures
open the breaker; while open, traffic rides the next tier down without
attempting this one; after ``cooldown_s`` the breaker half-opens and
admits ONE probe — success closes it (recovery), failure re-opens it for
another cooldown. Transitions emit
``rb_tpu_breaker_transitions_total{site,tier,state}``.

**Retry with jittered backoff** (:func:`retry`): for transient-classified
failures on the transfer sites (host→HBM ship). Bounded attempts,
exponential backoff with deterministic decorrelated jitter, and
deadline-aware — a retry that cannot finish before the ambient deadline
raises immediately instead of sleeping through the caller's budget.

**Deadline budgets** (:func:`deadline_scope` / :func:`deadline_expired`):
a per-query wall-clock budget carried in a thread-local; the query
executor checks it per step and cancels remaining device work to the
cheapest tier (bit-exact, just slower) rather than blowing the caller's
latency. ``rb_tpu_deadline_total{site,outcome}`` counts the outcomes.

Lock discipline: the ladder's health lock (``robust.health``) is a leaf —
metrics and recorder writes happen OUTSIDE it, so it never nests over the
registry or recorder locks (witnessed in tests/test_robust.py).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, List, Optional, Sequence, Tuple

from .. import observe as _observe
from ..observe import decisions as _decisions
from ..observe import timeline as _timeline
from .errors import FATAL, TRANSIENT, classify

# canonical tier names, fastest first (the pack/reduce path's rungs)
TIERS: Tuple[str, ...] = ("device", "columnar-cpu", "per-container", "pure-python")

_DEGRADE_TOTAL = _observe.counter(
    _observe.DEGRADE_TOTAL,
    "Degradations routed by the execution-tier ladder (site, failing tier, "
    "tier that absorbed the traffic)",
    ("site", "from", "to"),
)
_BREAKER_TOTAL = _observe.counter(
    _observe.BREAKER_TRANSITIONS_TOTAL,
    "Circuit-breaker state transitions by site, tier, and entered state",
    ("site", "tier", "state"),
)
_RETRY_TOTAL = _observe.counter(
    _observe.RETRY_TOTAL,
    "Retry-loop attempts on transient-classified sites, by outcome "
    "(retried | recovered | exhausted | not_retryable)",
    ("site", "outcome"),
)
_DEADLINE_TOTAL = _observe.counter(
    _observe.DEADLINE_TOTAL,
    "Deadline-budget outcomes by site (met | degraded)",
    ("site", "outcome"),
)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class Breaker:
    """Per-(site, tier) health tracker + circuit breaker. All state is
    guarded by the owning Ladder's health lock; transition METRICS are
    returned to the caller and emitted outside it (leaf-lock discipline)."""

    __slots__ = ("state", "consecutive", "opened_at", "first_opened_at",
                 "trip_after", "cooldown_s", "probing")

    def __init__(self, trip_after: int, cooldown_s: float):
        self.state = CLOSED
        self.consecutive = 0       # consecutive failures while closed
        self.opened_at = 0.0       # monotonic time of the last trip
        # when the CURRENT unhealthy episode began: set on the CLOSED->OPEN
        # trip, NOT reset by failed half-open probes (each probe failure
        # re-trips and moves opened_at, so opened_at alone can never age
        # past one cooldown under traffic — the stuck-open health rule
        # needs the episode start, ISSUE 12), cleared on recovery
        self.first_opened_at = 0.0
        self.trip_after = trip_after
        self.cooldown_s = cooldown_s
        self.probing = False       # a half-open probe is in flight

    def allow(self, now: float) -> Tuple[bool, Optional[str]]:
        """(admit?, transition-entered-or-None). Open breakers admit one
        half-open probe per cooldown expiry."""
        if self.state == CLOSED:
            return True, None
        if self.state == OPEN and now - self.opened_at >= self.cooldown_s:
            self.state = HALF_OPEN
            self.probing = True
            return True, HALF_OPEN
        if self.state == HALF_OPEN and not self.probing:
            # previous probe concluded elsewhere; admit the next one
            self.probing = True
            return True, None
        return False, None

    def success(self) -> Optional[str]:
        self.consecutive = 0
        self.probing = False
        self.first_opened_at = 0.0
        if self.state != CLOSED:
            self.state = CLOSED
            return CLOSED
        return None

    def failure(self, now: float) -> Optional[str]:
        self.probing = False
        if self.state == HALF_OPEN:
            # failed probe re-trips: the episode continues, its start stays
            self.state = OPEN
            self.opened_at = now
            return OPEN
        self.consecutive += 1
        if self.state == CLOSED and self.consecutive >= self.trip_after:
            self.state = OPEN
            self.opened_at = now
            self.first_opened_at = now
            return OPEN
        return None


class Ladder:
    """The process-wide degradation router (module singleton ``LADDER``)."""

    def __init__(self, trip_after: int = 3, cooldown_s: float = 5.0):
        self.trip_after = int(trip_after)
        self.cooldown_s = float(cooldown_s)
        # leaf lock: never held while taking any other framework lock
        self._lock = threading.Lock()
        self._breakers: dict = {}  # guarded-by: self._lock

    def configure(self, trip_after: Optional[int] = None,
                  cooldown_s: Optional[float] = None) -> None:
        """Adjust breaker policy for breakers created from now on (tests
        use tiny cooldowns; existing breakers keep their policy)."""
        with self._lock:
            if trip_after is not None:
                self.trip_after = int(trip_after)
            if cooldown_s is not None:
                self.cooldown_s = float(cooldown_s)

    def reset(self) -> None:
        """Drop all breaker state (fresh ladder; tests and fuzz iterations)."""
        with self._lock:
            self._breakers.clear()

    def _breaker(self, site: str, tier: str) -> Breaker:
        # caller holds self._lock (private helper of the locked regions)
        b = self._breakers.get((site, tier))
        if b is None:
            b = self._breakers[(site, tier)] = Breaker(
                self.trip_after, self.cooldown_s
            )
        return b

    def breaker_state(self, site: str, tier: str) -> str:
        with self._lock:
            b = self._breakers.get((site, tier))
            return b.state if b is not None else CLOSED

    def states(self) -> dict:
        """Point-in-time ``{"site/tier": state}`` over every breaker that
        has seen traffic — the resource observatory's breaker panel
        (scripts/rb_top.py)."""
        with self._lock:
            return {
                f"{site}/{tier}": b.state
                for (site, tier), b in sorted(self._breakers.items())
            }

    def open_ages(self, now: Optional[float] = None) -> dict:
        """``{"site/tier": seconds-since-the-episode-opened}`` for every
        breaker currently OPEN (half-open probes in flight count as open —
        the tier is still not absorbing traffic). Ages are measured from
        the EPISODE start (``first_opened_at``), not the last re-trip:
        under steady traffic a stuck tier fails one half-open probe per
        cooldown, each re-trip moving ``opened_at`` — measured from there
        the age could never exceed one cooldown. The health sentinel's
        breaker-stuck-open rule judges the max (ISSUE 12); ``now`` is
        injectable monotonic time for fake-clock tests."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            return {
                f"{site}/{tier}": max(
                    0.0, now - (b.first_opened_at or b.opened_at)
                )
                for (site, tier), b in sorted(self._breakers.items())
                if b.state in (OPEN, HALF_OPEN)
            }

    # -- recording helpers (metrics OUTSIDE the health lock) ---------------

    def _transition(self, site: str, tier: str, state: Optional[str]) -> None:
        if state is not None:
            _BREAKER_TOTAL.inc(1, (site, tier, state))
            _timeline.instant(
                "ladder.breaker", "robust", site=site, tier=tier, state=state
            )
            _decisions.record_decision(
                "ladder.breaker", state, site=site, tier=tier
            )

    def note_degrade(self, site: str, frm: str, to: str,
                     exc: Optional[BaseException] = None,
                     wasted_s: Optional[float] = None) -> None:
        """Record one degradation edge (also the public hook for the
        chains that keep their own fallback mechanics, e.g. the columnar
        kernels' native→numpy inline fallbacks). ``wasted_s`` is the wall
        clock the failing tier burned before the degrade — a measured
        counterfactual, joined straight into the outcome ledger as pure
        regret (ISSUE 11): wall lost to a verdict that started on a tier
        which then failed."""
        _DEGRADE_TOTAL.inc(1, (site, frm, to))
        _timeline.instant(
            "ladder.degrade", "robust", site=site,
            frm=frm, to=to, error=type(exc).__name__ if exc else None,
        )
        inputs = {"site": site, "error": type(exc).__name__ if exc else None}
        if wasted_s is not None:  # breaker-skips burn no wall: no null key
            inputs["wasted_ms"] = round(wasted_s * 1e3, 3)
        seq = _decisions.record_decision(
            "ladder.degrade", f"{frm}->{to}", outcome=wasted_s is not None,
            **inputs,
        )
        if wasted_s is not None and seq is not None:
            from ..observe import outcomes as _outcomes

            _outcomes.resolve(
                seq, "ladder.degrade", wasted_s, engine=frm,
                regret_s=wasted_s,
            )

    def record_failure(self, site: str, tier: str) -> None:
        now = time.monotonic()
        with self._lock:
            t = self._breaker(site, tier).failure(now)
        self._transition(site, tier, t)

    def _probe_abort(self, site: str, tier: str) -> None:
        """Release an in-flight half-open probe without judging the tier —
        a FATAL error re-raises out of run() and must not wedge the
        breaker in a forever-denying probing state."""
        with self._lock:
            self._breaker(site, tier).probing = False

    def record_success(self, site: str, tier: str) -> None:
        with self._lock:
            t = self._breaker(site, tier).success()
        self._transition(site, tier, t)

    # -- the router --------------------------------------------------------

    def run(self, site: str, tiers: Sequence[Tuple[str, Callable[[], object]]],
            outcome_seq: Optional[int] = None,
            outcome_site: Optional[str] = None):
        """Execute ``tiers`` (ordered fastest→cheapest) through the health
        machinery; returns the first success. Every tier must compute the
        same result — degradation is a latency decision, never a
        correctness one.

        ``outcome_seq`` is the dispatch decision's serial (ISSUE 11): the
        ladder times every attempt, resolves the decision with the tier
        that actually absorbed the traffic and its measured wall clock,
        and threads the serial into the per-attempt recorder span
        (``ladder.attempt``) so the decision–outcome join works both live
        and from a dumped trace. ``outcome_site`` is the DECISION's site
        (e.g. ``"agg.dispatch"`` for ladder site ``"agg"``) — it labels
        the orphan counter when the pending entry already aged out, so
        per-site join-vs-orphan series reconcile. Failed attempts feed
        their burned wall into the degrade edge as measured regret
        (``note_degrade``)."""
        if not tiers:
            raise ValueError(f"ladder site {site!r} has no tiers")
        last = len(tiers) - 1
        now = time.monotonic()
        for i, (tier, fn) in enumerate(tiers):
            with self._lock:
                admit, trans = self._breaker(site, tier).allow(now)
            self._transition(site, tier, trans)
            if not admit and i < last:
                # open breaker: ride the next tier down without attempting
                self.note_degrade(site, tier, tiers[i + 1][0])
                continue
            t0 = time.perf_counter()
            try:
                with _timeline.tspan(
                    "ladder.attempt", "robust", site=site, tier=tier,
                    decision=outcome_seq,
                ):
                    val = fn()
            except Exception as e:
                attempt_s = time.perf_counter() - t0
                if classify(e) == FATAL:
                    self._probe_abort(site, tier)
                    raise
                self.record_failure(site, tier)
                if i == last:
                    raise  # nothing below the bottom rung
                self.note_degrade(
                    site, tier, tiers[i + 1][0], e, wasted_s=attempt_s
                )
                continue
            self.record_success(site, tier)
            if outcome_seq is not None:
                from ..observe import outcomes as _outcomes

                _outcomes.resolve(
                    outcome_seq, outcome_site or site,
                    time.perf_counter() - t0, engine=tier,
                )
            return val
        raise AssertionError("unreachable: bottom tier returns or raises")  # pragma: no cover


LADDER = Ladder()


# ---------------------------------------------------------------------------
# retry with jittered backoff (transient sites)
# ---------------------------------------------------------------------------


def _jitter(site: str, attempt: int, base_s: float, cap_s: float) -> float:
    """Bounded exponential backoff with deterministic decorrelated jitter:
    delay in [base·2^(a-1)/2, base·2^(a-1)], capped. Deterministic (a pure
    function of site+attempt) so schedule replays sleep identically."""
    exp = min(cap_s, base_s * (1 << max(0, attempt - 1)))
    h = zlib.crc32(f"retry:{site}:{attempt}".encode())
    frac = 0.5 + 0.5 * ((h & 0xFFFF) / float(1 << 16))
    return exp * frac


def retry(site: str, fn: Callable[[], object], *, attempts: int = 3,
          base_s: float = 0.01, cap_s: float = 0.25):
    """Run ``fn``, retrying transient-classified failures with jittered
    backoff. Non-transient failures raise immediately (a resource
    exhaustion will not un-exhaust on the same tier; the ladder above
    decides where the traffic goes). Deadline-aware: when the ambient
    deadline budget cannot absorb the next backoff, the last error raises
    now instead of sleeping the caller past its budget."""
    a = 0
    while True:
        a += 1
        try:
            val = fn()
        except Exception as e:
            if classify(e) != TRANSIENT:
                _RETRY_TOTAL.inc(1, (site, "not_retryable"))
                raise
            if a >= attempts:
                _RETRY_TOTAL.inc(1, (site, "exhausted"))
                raise
            delay = _jitter(site, a, base_s, cap_s)
            rem = deadline_remaining()
            if rem is not None and delay >= rem:
                _RETRY_TOTAL.inc(1, (site, "exhausted"))
                raise
            _RETRY_TOTAL.inc(1, (site, "retried"))
            _timeline.instant(
                "ladder.retry", "robust", site=site, attempt=a,
                delay_ms=round(delay * 1e3, 3),
            )
            time.sleep(delay)
            continue
        if a > 1:
            _RETRY_TOTAL.inc(1, (site, "recovered"))
        return val


# ---------------------------------------------------------------------------
# per-query deadline budgets
# ---------------------------------------------------------------------------

_TLS = threading.local()  # .deadline: monotonic deadline stack


class deadline_scope:
    """Arm a wall-clock budget for the enclosed work on this thread.
    Nested scopes keep the TIGHTER deadline (a sub-query cannot outlive
    its parent's budget)."""

    def __init__(self, seconds: Optional[float]):
        self._seconds = seconds
        self._token = None

    def __enter__(self) -> "deadline_scope":
        stack = getattr(_TLS, "deadline", None)
        if stack is None:
            stack = _TLS.deadline = []
        if self._seconds is None:
            dl = stack[-1] if stack else None
        else:
            dl = time.monotonic() + float(self._seconds)
            if stack and stack[-1] is not None:
                dl = min(dl, stack[-1])
        stack.append(dl)
        return self

    def __exit__(self, *exc) -> None:
        _TLS.deadline.pop()


def deadline_remaining() -> Optional[float]:
    """Seconds left in the ambient budget; None when no scope is armed."""
    stack = getattr(_TLS, "deadline", None)
    if not stack or stack[-1] is None:
        return None
    return stack[-1] - time.monotonic()


def deadline_expired() -> bool:
    rem = deadline_remaining()
    return rem is not None and rem <= 0


def note_deadline(site: str, outcome: str) -> None:
    _DEADLINE_TOTAL.inc(1, (site, outcome))
    if outcome != "met":
        _timeline.instant("ladder.deadline", "robust", site=site, outcome=outcome)
