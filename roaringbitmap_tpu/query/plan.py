"""Cost-based planner: algebraic rewrites + engine selection over the DAG.

Pipeline (all host-side, microseconds against container-op costs):

1. **Rewrites** (`rewrite`) — exact identities only, bottom-up and memoized
   over the hash-consed DAG so shared subtrees fold once:

   * flatten associative ops (``and(and(a,b),c) -> and(a,b,c)``) and n-ary
     differences (``andnot(andnot(a,B),C) -> andnot(a,B,C)``; an ``or``
     subtrahend splices into the subtrahend set);
   * De Morgan push-down of ``not`` through ``or``:
     ``U \\ (a|b) = (U\\a) & (U\\b)`` — profitable because the resulting
     conjunction then re-fuses into one n-ary ``andnot(U, a, b)`` via the
     pull-up rule below. ``not`` through ``and`` would manufacture unions
     of complements (strictly more work) and is deliberately NOT applied —
     the "only when profitable" half of the AndNot<->And(Not) equivalence;
   * pull differences out of conjunctions:
     ``a & (c \\ D) = (a & c) \\ D`` (exact for any operands), which is how
     lowered ``not`` nodes and user ``andnot`` nodes consolidate into a
     single subtraction per conjunction;
   * constant folding: empty leaves annihilate ``and``/minuends and vanish
     from ``or``/``xor``/subtrahends/threshold children; a full
     (2^32-cardinality) leaf absorbs ``or`` and vanishes from ``and``;
     ``xor`` cancels duplicate children pairwise; ``threshold`` folds
     k=1 -> or, k=N -> and, k>N -> empty.

   Hash-consing (expr.py) makes CSE structural: after rewriting, each
   distinct subcomputation is one node, planned and executed once.

2. **Cost model** — per-node estimated cardinality and container-row count
   from per-leaf ``get_cardinality()`` + container statistics
   (``insights.analyse``): and=min, or/xor=sum, andnot=minuend,
   threshold=sum/k. AND operands are ordered ascending by estimated
   cardinality (the workShyAnd/priorityqueue ordering heuristic); so are OR
   operands (cheapest merges first) and subtrahend sets.

3. **Engine choice** per node, the same strategy menu FastAggregation
   exposes plus the new kernels: ``pairwise`` host merges for 2 operands,
   ``workshy-and``/``naive-*``/``horizontal-*`` CPU folds,
   ``device-*`` batched reductions when
   ``parallel.aggregation._use_device`` says the working set earns a
   dispatch (``-sharded`` when ``aggregation.config.mesh`` is set),
   ``andnot-batch`` (grouped OR of the subtrahends + one fused
   ``parallel.batch``-style mask, kernels.py), and
   ``threshold-bitsliced`` (the bit-sliced adder, kernels.py).

The emitted :class:`Plan` is inspectable (``explain()``) and is what the
executor (exec.py) runs bottom-up with result memoization.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

from .. import observe as _observe
from ..observe import decisions as _decisions
from .expr import Expr, Leaf, Q

_MAX32 = 1 << 32

_PLAN_TOTAL = _observe.counter(
    _observe.QUERY_PLAN_TOTAL,
    "Planned query steps by chosen engine",
    ("engine",),
)


class CardinalityModel:
    """The planner's refittable cardinality model (ISSUE 11).

    The structural estimators below (and=min, or/xor=capped sum,
    andnot=minuend, threshold=sum/k) are exact bounds but systematically
    biased on real traffic (an AND of correlated filters lands far under
    ``min``; a union of overlapping dimensions far under ``sum``). Each
    op carries a multiplicative correction, 1.0 until
    :meth:`refit_from_outcomes` learns a better one from the decision–
    outcome join: every executed plan step resolves its ``query.plan``
    decision with the measured result cardinality, and the refit moves
    ``correction[op]`` by the geometric mean of measured/estimated over
    the joined samples — the same measured-not-guessed discipline as
    ``columnar.costmodel``, applied to the planner's own prediction.

    Thread-safe: corrections swap under a leaf lock; reads are lock-free
    dict gets (atomic under the GIL)."""

    OPS = ("and", "or", "xor", "andnot", "threshold")
    # a single refit moves a correction at most this factor either way —
    # one weird traffic window must not be able to invert the planner's
    # operand ordering outright
    MAX_STEP = 8.0
    MAX_CORRECTION = 64.0

    def __init__(self):
        self._lock = threading.Lock()
        self.corrections: Dict[str, float] = {op: 1.0 for op in self.OPS}
        self.provenance = "default"

    def corrected(self, op: str, est: int) -> int:
        c = self.corrections.get(op, 1.0)
        if c == 1.0:
            return est
        return max(0, min(_MAX32, int(est * c)))

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 4
    ) -> dict:
        """Refit the per-op corrections from joined ``query.plan``
        outcomes (default: the live outcome ledger). A sample must carry
        the op, a positive estimate, and a positive measured cardinality;
        ratios outside ``[2^-20, 2^20]`` are poisoned (a joined sample
        cannot legitimately miss by a million-fold — that is corrupt
        telemetry, not bias) and are rejected, counted in the report."""
        if samples is None:
            from ..observe import outcomes as _outcomes

            samples = _outcomes.tail()
        ratios: Dict[str, List[float]] = {}
        rejected = 0
        for s in samples:
            if s.get("site") not in (None, "query.plan"):
                continue
            inputs = s.get("inputs") or {}
            op = inputs.get("op") or s.get("op")
            est = inputs.get("est_card", s.get("est_card"))
            actual = s.get("actual")
            if op not in self.corrections:
                continue
            try:
                est = float(est)
                actual = float(actual)
            except (TypeError, ValueError):
                rejected += 1
                continue
            if not (est > 0 and actual > 0 and math.isfinite(est)
                    and math.isfinite(actual)):
                rejected += 1
                continue
            r = actual / est
            if not (2.0 ** -20 <= r <= 2.0 ** 20):
                rejected += 1
                continue
            ratios.setdefault(op, []).append(r)
        moved = {}
        with self._lock:
            for op, rs in ratios.items():
                if len(rs) < min_samples:
                    continue
                step = math.exp(sum(math.log(r) for r in rs) / len(rs))
                step = min(self.MAX_STEP, max(1.0 / self.MAX_STEP, step))
                new = self.corrections[op] * step
                new = min(self.MAX_CORRECTION, max(1.0 / self.MAX_CORRECTION, new))
                if new != self.corrections[op]:
                    moved[op] = {
                        "from": round(self.corrections[op], 4),
                        "to": round(new, 4),
                        "samples": len(rs),
                    }
                    self.corrections[op] = new
            if moved:
                self.provenance = "refit-from-traffic"
        report = {"moved": moved, "rejected": rejected,
                  "provenance": self.provenance}
        _decisions.record_decision(
            "costmodel.refit", "query-cardinality",
            moved=len(moved), rejected=rejected, provenance=self.provenance,
        )
        return report

    SCHEMA = "rb_tpu_planner_cardmodel/1"

    def to_dict(self) -> dict:
        """Serializable correction state — the planner's half of the
        unified ``cost/`` calibration lifecycle (ISSUE 12)."""
        with self._lock:
            return {
                "schema": self.SCHEMA,
                "corrections": dict(self.corrections),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        """Adopt serialized corrections; False (state untouched) on a
        schema mismatch or out-of-clamp values — a corrupt state file
        must not hand the planner an inverted operand ordering."""
        if not isinstance(d, dict) or d.get("schema") != self.SCHEMA:
            return False
        corrections = d.get("corrections")
        if not isinstance(corrections, dict):
            return False
        clean = {op: 1.0 for op in self.OPS}
        for op, c in corrections.items():
            if op not in clean:
                continue
            try:
                c = float(c)
            except (TypeError, ValueError):
                return False
            if not (1.0 / self.MAX_CORRECTION <= c <= self.MAX_CORRECTION):
                return False
            clean[op] = c
        with self._lock:
            self.corrections = clean
            self.provenance = str(d.get("provenance") or "default")
        return True

    def reset(self) -> None:
        with self._lock:
            self.corrections = {op: 1.0 for op in self.OPS}
            self.provenance = "default"


CARD_MODEL = CardinalityModel()


# ---------------------------------------------------------------------------
# rewrites
# ---------------------------------------------------------------------------


def _leaf_card(n: Leaf, cards: Optional[Dict[int, int]] = None) -> int:
    """Leaf cardinality, memoized per planning pass: get_cardinality() is
    O(#containers) and the rewrite's empty/full probes would otherwise
    re-sum the same leaf many times (code-review: plan cost must not
    dominate the warm cache-hit path)."""
    if cards is None:
        return n.bitmap.get_cardinality()
    c = cards.get(n.uid)
    if c is None:
        c = cards[n.uid] = n.bitmap.get_cardinality()
    return c


def _is_empty(n: Expr, cards=None) -> bool:
    return n.op == "leaf" and _leaf_card(n, cards) == 0


def _is_full(n: Expr, cards=None) -> bool:
    return n.op == "leaf" and _leaf_card(n, cards) == _MAX32


def rewrite(expr: Expr, _cards: Optional[Dict[int, int]] = None) -> Expr:
    """Fold the DAG through the exact identities above. Constant folds are
    pinned to leaf contents *at plan time* — ``execute(expr)`` replans when
    any leaf fingerprint changes, so a mutated leaf is re-folded; a held
    :class:`Plan` is a snapshot."""
    memo: Dict[int, Expr] = {}
    cards: Dict[int, int] = {} if _cards is None else _cards

    def fold(n: Expr) -> Expr:
        got = memo.get(n.uid)
        if got is not None:
            return got
        out = _fold_node(n, fold, cards)
        memo[n.uid] = out
        return out

    return fold(expr)


def _fold_node(n: Expr, fold, cards) -> Expr:
    if n.op == "leaf":
        return n
    if n.op == "not":
        return _fold_not(fold(n.children[0]), fold(n.children[1]), fold, cards)
    kids = [fold(c) for c in n.children]
    if n.op == "andnot":
        return _fold_andnot(kids[0], kids[1:], cards)
    if n.op == "threshold":
        kids = [c for c in kids if not _is_empty(c, cards)]
        k = n.k
        if not kids or k > len(kids):
            return Q.empty()
        if k == 1:
            return fold(Q.or_(*kids))
        if k == len(kids):
            return fold(Q.and_(*kids))
        return Q.threshold(k, *kids)
    # associative and/or/xor: flatten one level (children already folded,
    # so nested same-op nodes are themselves flat)
    flat: List[Expr] = []
    for c in kids:
        if c.op == n.op:
            flat.extend(c.children)
        else:
            flat.append(c)
    if n.op == "and":
        return _fold_and(flat, fold, cards)
    if n.op == "or":
        return _fold_or(flat, cards)
    return _fold_xor(flat, cards)


def _dedup(kids: List[Expr]) -> List[Expr]:
    seen, out = set(), []
    for c in kids:
        if c.uid not in seen:
            seen.add(c.uid)
            out.append(c)
    return out


def _fold_and(kids: List[Expr], fold, cards) -> Expr:
    if any(_is_empty(c, cards) for c in kids):
        return Q.empty()
    kept = [c for c in kids if not _is_full(c, cards)]
    kids = _dedup(kept) if kept else [kids[0]]
    if len(kids) == 1:
        return kids[0]
    # pull differences up: a & (c \ D) & (e \ F) = (a & c & e) \ (D | F)
    plain = [c for c in kids if c.op != "andnot"]
    diffs = [c for c in kids if c.op == "andnot"]
    if diffs:
        minuends = plain + [d.children[0] for d in diffs]
        subs = [s for d in diffs for s in d.children[1:]]
        return fold(Q.andnot(Q.and_(*minuends), *subs))
    return Q.and_(*kids)


def _fold_or(kids: List[Expr], cards) -> Expr:
    for c in kids:
        if _is_full(c, cards):
            return c
    kids = _dedup([c for c in kids if not _is_empty(c, cards)])
    if not kids:
        return Q.empty()
    if len(kids) == 1:
        return kids[0]
    return Q.or_(*kids)


def _fold_xor(kids: List[Expr], cards) -> Expr:
    counts: Dict[int, int] = {}
    by_uid: Dict[int, Expr] = {}
    order: List[int] = []
    for c in kids:
        if _is_empty(c, cards):
            continue
        if c.uid not in counts:
            order.append(c.uid)
            by_uid[c.uid] = c
        counts[c.uid] = counts.get(c.uid, 0) + 1
    kids = [by_uid[u] for u in order if counts[u] % 2]  # a ^ a = empty
    if not kids:
        return Q.empty()
    if len(kids) == 1:
        return kids[0]
    return Q.xor(*kids)


def _fold_andnot(minuend: Expr, subs: List[Expr], cards) -> Expr:
    if _is_empty(minuend, cards):
        return Q.empty()
    if minuend.op == "andnot":  # (a \ B) \ C = a \ (B u C)
        subs = list(minuend.children[1:]) + subs
        minuend = minuend.children[0]
    flat: List[Expr] = []
    for s in subs:
        if s.op == "or":  # a \ (b|c) folds into the n-ary subtrahend set
            flat.extend(s.children)
        else:
            flat.append(s)
    flat = _dedup([s for s in flat if not _is_empty(s, cards)])
    if any(_is_full(s, cards) for s in flat):
        return Q.empty()
    if any(s.uid == minuend.uid for s in flat):
        return Q.empty()
    if not flat:
        return minuend
    return Q.andnot(minuend, *flat)


def _fold_not(x: Expr, universe: Expr, fold, cards) -> Expr:
    if _is_empty(x, cards):
        return universe
    if x.op == "or":  # De Morgan: U \ (a|b) = (U\a) & (U\b) -> andnot(U, a, b)
        return fold(Q.and_(*[Q.not_(c, universe) for c in x.children]))
    if x.op == "andnot" and x.children[0].uid == universe.uid:
        # the double-not, post-lowering: U \ (U \ S) = U & S (NOT S in
        # general — only S's part inside U)
        return fold(Q.and_(universe, Q.or_(*x.children[1:])))
    return fold(Q.andnot(universe, x))


# ---------------------------------------------------------------------------
# cost model + engine choice
# ---------------------------------------------------------------------------


class PlanStep:
    """One executable node: ``engine`` applied to ``operands`` (child nodes
    in chosen evaluation order). ``decision_seq`` is the planner
    decision's serial (ISSUE 11) — the executor resolves it once with the
    measured step wall + result cardinality, then clears it (a memoized
    plan re-executes, but one decision joins one outcome)."""

    __slots__ = ("index", "node", "engine", "operands", "est_card",
                 "est_rows", "decision_seq")

    def __init__(self, index, node, engine, operands, est_card, est_rows,
                 decision_seq=None):
        self.index = index
        self.node = node
        self.engine = engine
        self.operands = operands
        self.est_card = est_card
        self.est_rows = est_rows
        self.decision_seq = decision_seq


class Plan:
    """Inspectable bottom-up execution plan over the rewritten DAG."""

    def __init__(
        self,
        root: Expr,
        steps: List[PlanStep],
        labels: Dict[int, str],
        leaf_cards: Dict[int, int],
    ):
        self.root = root
        self.steps = steps
        self._labels = labels
        self._leaf_cards = leaf_cards  # plan-time snapshot, what the model saw

    def explain(self) -> str:
        """Stable human-readable rendering: one line per leaf (first-use
        DFS order) and per step (bottom-up order), with the chosen engine
        and estimated cardinality/container-rows."""
        lines = [f"plan: {len(self.steps)} steps over {len(self.root.leaves)} leaves"]
        for leaf in self.root.leaves:
            lines.append(
                f"  {self._labels[leaf.uid]} leaf card={self._leaf_cards[leaf.uid]}"
            )
        for s in self.steps:
            ops = ", ".join(self._labels[o.uid] for o in s.operands)
            head = s.node.op + (f"[k={s.node.k}]" if s.node.k is not None else "")
            lines.append(
                f"  {self._labels[s.node.uid]} {head}({ops}) engine={s.engine}"
                f" est_card={s.est_card} est_rows={s.est_rows}"
            )
        lines.append(f"  root: {self._labels[self.root.uid]}")
        return "\n".join(lines)


def _estimates(node: Expr, est: Dict[int, Tuple[int, int]], cards) -> Tuple[int, int]:
    """(est_cardinality, est_container_rows) from the children's entries."""
    if node.op == "leaf":
        card = _leaf_card(node, cards)
        try:
            rows = node.bitmap.get_container_count()  # O(1) on the facade
        except (AttributeError, TypeError):
            try:  # foreign bitmap types: the insights container walk
                from .. import insights

                rows = insights.analyse([node.bitmap]).container_count()
            except (AttributeError, TypeError):
                rows = max(1, card // 4096)
        return card, rows
    kid = [est[c.uid] for c in node.children]
    # structural bound first, then the refittable per-op correction
    # (ISSUE 11): CARD_MODEL learns the traffic's systematic bias from
    # the decision-outcome join (measured result cardinalities)
    if node.op == "and":
        card, rows = min(c for c, _ in kid), len(kid) * min(r for _, r in kid)
    elif node.op in ("or", "xor"):
        card, rows = min(sum(c for c, _ in kid), _MAX32), sum(r for _, r in kid)
    elif node.op == "andnot":
        # the difference is bounded by the minuend; subtrahend rows count
        # because the n-way kernel folds them over the minuend's keys
        card, rows = kid[0][0], sum(r for _, r in kid)
    elif node.op == "threshold":
        card, rows = sum(c for c, _ in kid) // node.k, sum(r for _, r in kid)
    else:
        raise ValueError(f"unplannable op {node.op!r} (rewrite should have lowered it)")
    return CARD_MODEL.corrected(node.op, card), rows


def _choose_engine(node: Expr, est_rows: int, mode: Optional[str]) -> str:
    from ..parallel import aggregation

    n = len(node.children)
    device = aggregation._use_device(est_rows, mode)
    sharded = "-sharded" if (device and aggregation.config.mesh is not None) else ""
    if node.op in ("and", "or", "xor"):
        if n == 2 and not device:
            return "pairwise"
        if device:
            return f"device-{node.op}{sharded}"
        if node.op == "and":
            return "workshy-and"
        return ("horizontal-" if n >= 8 else "naive-") + node.op
    if node.op == "andnot":
        if n == 2 and not device:
            return "pairwise"
        return f"andnot-batch[{'device' if device else 'cpu'}]"
    if node.op == "threshold":
        return f"threshold-bitsliced[{'device' if device else 'cpu'}]"
    raise ValueError(f"unplannable op {node.op!r}")


def plan(expr: Expr, mode: Optional[str] = None) -> Plan:
    """Rewrite + cost-order + engine-select ``expr`` into a :class:`Plan`.

    ``mode`` forwards to the engine dispatcher: ``'cpu'``/``'device'``
    force the regime, ``None`` lets ``_use_device`` decide per node.
    """
    from .. import tracing

    with tracing.op_timer("query.plan"):
        cards: Dict[int, int] = {}
        root = rewrite(expr, _cards=cards)
        labels: Dict[int, str] = {}
        for i, leaf in enumerate(root.leaves):
            labels[leaf.uid] = f"L{i}"
        est: Dict[int, Tuple[int, int]] = {}
        steps: List[PlanStep] = []
        # iterative post-order over the DAG, each node once
        stack: List[Tuple[Expr, bool]] = [(root, False)]
        while stack:
            node, ready = stack.pop()
            if node.uid in est:
                continue
            if not ready:
                stack.append((node, True))
                for c in reversed(node.children):
                    if c.uid not in est:
                        stack.append((c, False))
                continue
            card, rows = _estimates(node, est, cards)
            est[node.uid] = (card, rows)
            if node.op == "leaf":
                continue
            operands = _order_operands(node, est)
            engine = _choose_engine(node, rows, mode)
            _PLAN_TOTAL.inc(1, (engine,))
            labels[node.uid] = f"s{len(steps)}"
            # decision provenance (ISSUE 9): the per-node engine choice
            # with the cost-model inputs that drove it — "why did this
            # node ride the device" is answerable from insights.decisions().
            # outcome=True (ISSUE 11): the executor resolves the serial
            # with the measured step wall + actual result cardinality,
            # which is what the cardinality model refits from.
            seq = _decisions.record_decision(
                "query.plan", engine, outcome=True, op=node.op,
                est_card=int(card), est_rows=int(rows),
                operands=len(node.children), mode=mode,
            )
            steps.append(
                PlanStep(len(steps), node, engine, operands, card, rows,
                         decision_seq=seq)
            )
        leaf_cards = {l.uid: _leaf_card(l, cards) for l in root.leaves}
        return Plan(root, steps, labels, leaf_cards)


def _order_operands(node: Expr, est) -> Tuple[Expr, ...]:
    kids = node.children
    if node.op in ("and", "or"):
        # ascending estimated cardinality, original position as tiebreak:
        # cheap operands first keeps intermediate results small (AND) and
        # merges cheap-into-cheap first (OR, the priorityqueue_or idea)
        order = sorted(range(len(kids)), key=lambda i: (est[kids[i].uid][0], i))
        return tuple(kids[i] for i in order)
    if node.op == "andnot":
        rest = sorted(range(1, len(kids)), key=lambda i: (est[kids[i].uid][0], i))
        return (kids[0],) + tuple(kids[i] for i in rest)
    return kids  # xor order is free; threshold children are a multiset
