"""Bounded memoizing result cache for executed query nodes.

Keyed by ``(node uid, leaf fingerprints)``: the hash-consed DAG makes the
uid a structural identity (the same subexpression over the same bitmap
objects is the same node), and the fingerprint tuple
(``RoaringBitmap.fingerprint()``, models/roaring.py — bumped by every
mutator) pins the leaf *contents* at execution time. A repeated query over
unchanged bitmaps therefore short-circuits at every memoized interior node;
mutating any contributing leaf changes its fingerprint, the key misses, and
the stale entry ages out through the LRU bound — no explicit invalidation
hooks in the hot mutation paths.

LRU by entry count plus an optional byte budget (entries weighed by
``get_size_in_bytes()``). Thread-safe: one lock around the OrderedDict, the
same discipline as ``observe.registry``. Every hit/miss/store/evict lands
in the ``rb_tpu_query_cache_total{event}`` registry counter and in
per-instance ints (``stats()``) so a single cache's behavior is assertable
without resetting the process-wide registry.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from .. import observe as _observe
from ..models.roaring import RoaringBitmap

_CACHE_TOTAL = _observe.counter(
    _observe.QUERY_CACHE_TOTAL,
    "Query result-cache events (hit | miss | store | evict)",
    ("event",),
)


class ResultCache:
    """LRU (node uid, leaf fingerprints) -> RoaringBitmap."""

    def __init__(self, max_entries: int = 256, max_bytes: Optional[int] = None):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = int(max_entries)
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Tuple[RoaringBitmap, int]]" = OrderedDict()  # guarded-by: self._lock
        self._bytes = 0  # guarded-by: self._lock
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock
        self.evictions = 0  # guarded-by: self._lock

    def get(self, key: tuple) -> Optional[RoaringBitmap]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                _CACHE_TOTAL.inc(1, ("miss",))
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            _CACHE_TOTAL.inc(1, ("hit",))
            return entry[0]

    def put(self, key: tuple, value: RoaringBitmap) -> None:
        nbytes = value.get_size_in_bytes() if self.max_bytes is not None else 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            _CACHE_TOTAL.inc(1, ("store",))
            while len(self._entries) > self.max_entries or (
                self.max_bytes is not None
                and self._bytes > self.max_bytes
                and len(self._entries) > 1
            ):
                _k, (_v, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1
                _CACHE_TOTAL.inc(1, ("evict",))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "entries": len(self._entries),
                "bytes": self._bytes,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries


# the process-default cache exec.execute() memoizes into when the caller
# does not pass one (a serving process wants cross-request sharing)
DEFAULT_CACHE = ResultCache(max_entries=512)


def cache_key(node, leaf_fps: dict) -> tuple:
    """The memo key of one DAG node: its structural uid + the fingerprint
    of every leaf feeding it (``leaf_fps``: leaf uid -> fingerprint,
    computed once per execution so all steps see one consistent view)."""
    return (node.uid,) + tuple(leaf_fps[l.uid] for l in node.leaves)


def leaf_fps_current(node, leaf_fps: dict) -> bool:
    """Cross-query key validation (ISSUE 13 satellite): do the node's
    leaves STILL carry the snapshotted fingerprints? The executor reads
    live bitmaps, so a leaf mutated mid-computation leaves the computed
    value matching neither the key's snapshot nor the new contents (a
    torn read). Every publication — a ``cache.put`` and an in-flight
    completion alike — re-validates through this one helper and drops
    stale values instead of keying them under fingerprints they do not
    correspond to (the entry would otherwise be served to any concurrent
    joiner holding the pre-mutation key)."""
    return all(l.fingerprint() == leaf_fps[l.uid] for l in node.leaves)
