"""Cross-query fusion: the micro-batching executor (ISSUE 13 tentpole).

At serving QPS the accelerator is wasted twice: per-dispatch overhead on
small queries, and duplicated work across concurrent queries over the
same corpus. This module lifts the batched-per-class argument
(arXiv:1709.07821) one level — from containers to queries: a window of
concurrent queries coalesces into fused per-tier device programs instead
of executing one query, one node, one dispatch at a time.

**The pipeline per drained window:**

1. **Plan + dedup.** Every query plans through the shared memo
   (exec._memo_plan); the hash-consed DAG (ISSUE 2) makes shared
   subexpressions across queries the SAME node by construction, so the
   window's step set dedups on node uid — the hot ``A & B`` under a
   thousand user predicates is one step, not a thousand. Leaf
   fingerprints snapshot once for the whole window (one consistent view),
   and every computed node publishes through the result cache + in-flight
   table (inflight.py) under validated fingerprints, so the dedup also
   reaches queries OUTSIDE the window.

2. **Tier merge.** Unique steps level by topological depth, then group
   by merge class; each merged group executes as ONE dispatch:

   ========================= ============================================
   merge class               fused execution
   ========================= ============================================
   pairwise and/or/xor/      ``columnar.pairwise_multi`` — every pair's
   andnot                    matched containers in one per-class batch;
                             on the device tier one ``pair_rows_reduce``
                             gather+op+popcount launch over the
                             concatenated resident row blocks, per-query
                             result slicing
   or/xor CPU folds          ``columnar.fold_multi`` — all working sets
                             in one multi-band scatter + popcount pass
   n-way ANDNOT (CPU)        one ``or_fold_words`` call unions EVERY
                             query's subtrahend groups (keys namespaced
                             per query), then per-query word folds
   n-way ANDNOT (device)     per-query union reduce, then ONE fused
                             ``first & ~union`` + popcount dispatch over
                             the concatenated ``[G, 2048]`` blocks
                             (``pair_rows_reduce`` on row-aligned pairs)
   Threshold(k) (device)     same-(k, slices, M) blocks concatenate
                             along G into one bit-sliced-adder dispatch
   workshy-and / threshold   solo (AND's key-intersection fold and the
   CPU / device-* n-ary      per-key CPU adder have no batched band to
                             merge; the n-ary reduces already amortize
                             their own working set)
   ========================= ============================================

   Merged results are bit-exact with per-query execution by
   construction: every fused path feeds the same partition and the same
   assembly helpers as its solo twin (no second result-format rule
   anywhere).

3. **Priced verdict + degradation.** Each window records a
   ``fusion.batch`` decision (batch vs solo, with per-engine ``est_us``
   from the fusion-batch pricing authority, cost/fusion.py) and executes
   under the decision–outcome join: measured wall joins the prediction,
   mispricing shows up as regret/error rows, and the authority refits
   from live windows through the ``cost/`` facade like every other
   pricing authority. The fused attempt rides the ``query.fusion``
   ladder site (fault-injectable): any non-fatal failure degrades the
   whole window to per-query serial execution — bit-exact, just without
   the batching win.

**Windowing:** :func:`execute_fused` is the synchronous batch entry
(callers that already hold a window); :class:`FusionExecutor` is the
serving shape — ``submit()`` returns a future, a drain loop coalesces up
to ``RB_TPU_FUSION_WINDOW`` queries (default 8) or whatever arrived
within ``RB_TPU_FUSION_LATENCY_MS`` (default 2 ms), so the executor
never waits long for a window that isn't coming. ``RB_TPU_FUSION=off``
(or ``configure(enabled=False)``) reduces :func:`execute_fused` to the
plain serial loop — the bench's off-mode twin bounds that path under the
house <1 % budget.

Observability: ``rb_tpu_fusion_batch_total{outcome}``,
``rb_tpu_fusion_queries_total``, ``rb_tpu_fusion_steps_total{kind}``,
``rb_tpu_fusion_batch_seconds{phase}`` (batch wall | queued wait), the
``rb_tpu_fusion_queued_count`` gauge (the sentinel's
``fusion-queue-stall`` rule watches it), and the in-flight table's
``rb_tpu_query_inflight_total{event}`` — all surfaced in the rb_top
fusion panel and the metrics-sidecar ``fusion`` block.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .. import observe as _observe
from ..observe import context as _context
from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import timeline as _timeline
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..models.roaring import RoaringBitmap
from ..cost import fusion as _fusion_cost
from . import exec as _exec
from . import inflight as _inflight
from .cache import DEFAULT_CACHE, ResultCache, cache_key, leaf_fps_current
from .expr import Expr
from .plan import Plan, PlanStep

_BATCH_TOTAL = _observe.counter(
    _observe.FUSION_BATCH_TOTAL,
    "Fusion windows drained, by execution outcome "
    "(fused | per-query | degraded)",
    ("outcome",),
)
_QUERIES_TOTAL = _observe.counter(
    _observe.FUSION_QUERIES_TOTAL,
    "Queries that entered a fusion window",
)
_STEPS_TOTAL = _observe.counter(
    _observe.FUSION_STEPS_TOTAL,
    "Window plan-step fates (executed = unique steps run, deduped = "
    "steps shared across the window's queries, merged = steps that rode "
    "a merged-tier dispatch)",
    ("kind",),
)
_BATCH_SECONDS = _observe.latency_histogram(
    _observe.FUSION_BATCH_SECONDS,
    "Fusion latencies by phase (batch = drained-window execution wall, "
    "queued = per-query wait in the window queue)",
    ("phase",),
)
_QUEUED_COUNT = _observe.gauge(
    _observe.FUSION_QUEUED_COUNT,
    "Queries currently waiting across every live fusion window queue "
    "(the fusion-queue-stall sentinel rule's depth signal)",
)
_HEDGE_TOTAL = _observe.counter(
    _observe.FUSION_HEDGE_TOTAL,
    "Joint priced batch-vs-solo verdicts for budgeted requests (window "
    "= rode the forming window, solo = hedged solo dispatch through the "
    "in-flight dedup table because the window would blow the tenant's "
    "p99 budget)",
    ("verdict",),
)
_WINDOW_COUNT = _observe.gauge(
    _observe.FUSION_WINDOW_COUNT,
    "Effective fusion window bound (queries per drained batch) — the "
    "serving-p99-pressure actuation auto-tunes this between "
    "RB_TPU_FUSION_WINDOW_MIN and the configured base from the fusion "
    "authority's refitted curves",
)

# per-executor queue depths folded into ONE gauge value: a process may
# run several FusionExecutors (per tenant, per cache), and letting each
# .set() the shared series would have a healthy executor's drains
# overwrite a stalled one's parked depth — exactly the signal the
# fusion-queue-stall rule exists to see
_DEPTH_LOCK = threading.Lock()
_QUEUE_DEPTHS: Dict[int, int] = {}  # id(executor) -> depth, guarded-by: _DEPTH_LOCK


def _publish_depth(executor_id: int, depth: Optional[int]) -> None:
    """Record one executor's live queue depth (None = executor closed)
    and export the sum over every live executor."""
    with _DEPTH_LOCK:
        if depth is None:
            _QUEUE_DEPTHS.pop(executor_id, None)
        else:
            _QUEUE_DEPTHS[executor_id] = depth
        total = sum(_QUEUE_DEPTHS.values())
    _QUEUED_COUNT.set(total)


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "off", "false", "no")


class config:
    """Fusion dispatch knobs (env-seeded, runtime-overridable via
    :func:`configure`). ``window`` is the EFFECTIVE window bound (queries
    one drained batch coalesces) — a refittable policy since ISSUE 19:
    the ``serving-p99-pressure`` actuation moves it between
    ``window_min`` and ``window_base`` from the fusion authority's
    refitted curves (:func:`autotune_window`). ``max_wait_ms`` bounds how
    long the drain loop holds an open window for stragglers — a member's
    declared slack can only CLOSE the window earlier, never extend it.
    ``hedge`` arms the solo bypass for interactive requests whose priced
    verdict says the forming window would blow their budget."""

    enabled: bool = _env_flag("RB_TPU_FUSION", True)
    window: int = max(2, int(os.environ.get("RB_TPU_FUSION_WINDOW") or 8))
    window_base: int = window
    window_min: int = max(2, int(os.environ.get("RB_TPU_FUSION_WINDOW_MIN") or 2))
    max_wait_ms: float = float(os.environ.get("RB_TPU_FUSION_LATENCY_MS") or 2.0)
    hedge: bool = _env_flag("RB_TPU_FUSION_HEDGE", True)


_WINDOW_COUNT.set(config.window)


def configure(
    enabled: Optional[bool] = None,
    window: Optional[int] = None,
    max_wait_ms: Optional[float] = None,
    window_min: Optional[int] = None,
    hedge: Optional[bool] = None,
) -> None:
    if enabled is not None:
        config.enabled = bool(enabled)
    if window is not None:
        if window < 2:
            raise ValueError(f"fusion window must be >= 2, got {window}")
        # an explicit window is a new BASE: the auto-tuner shrinks from
        # (and regrows back to) whatever the operator last declared
        config.window = int(window)
        config.window_base = int(window)
        _WINDOW_COUNT.set(config.window)
    if max_wait_ms is not None:
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        config.max_wait_ms = float(max_wait_ms)
    if window_min is not None:
        if window_min < 2:
            raise ValueError(f"window_min must be >= 2, got {window_min}")
        config.window_min = int(window_min)
    if hedge is not None:
        config.hedge = bool(hedge)


def autotune_window(
    budget_ms: Optional[float] = None, reason: str = "manual"
) -> dict:
    """Recompute the effective window bound from the fusion authority's
    CURRENT (refitted) curves against the tightest declared interactive
    p99 budget (ISSUE 19 leg 4 — the ``serving-p99-pressure``
    actuation's body, PR 12's drift→refit actuation shape). Shrinks when
    the curves say a full base window cannot fit inside the budget,
    regrows toward ``config.window_base`` when they say it can (or when
    no interactive tenant is declared — nothing to protect). Returns the
    tuning record; the verdict lands in the decision log as
    ``fusion.autotune``."""
    if budget_ms is None:
        try:
            from ..serve import slo as _slo

            budget_ms = min(
                (
                    _slo.TENANTS.p99_budget_ms(t)
                    for t in _slo.TENANTS.names()
                    if _slo.TENANTS.latency_class(t) == "interactive"
                ),
                default=None,
            )
        except Exception:  # rb-ok: exception-hygiene -- the auto-tuner must stay a no-op when the serve tier is absent/torn down mid-process-exit; the window simply holds its current bound
            budget_ms = None
    frm = config.window
    if budget_ms is None:
        target = config.window_base
    else:
        target = _fusion_cost.MODEL.window_for_budget(float(budget_ms) * 1e3)
        target = min(config.window_base, max(config.window_min, target))
    verdict = (
        "shrink" if target < frm else ("regrow" if target > frm else "hold")
    )
    config.window = target
    _WINDOW_COUNT.set(target)
    _decisions.record_decision(
        "fusion.autotune", verdict, window_from=frm, window_to=target,
        budget_ms=budget_ms, reason=reason,
        provenance=_fusion_cost.MODEL.provenance,
    )
    return {
        "verdict": verdict, "window_from": frm, "window_to": target,
        "budget_ms": budget_ms, "reason": reason,
    }


# ---------------------------------------------------------------------------
# merge classes
# ---------------------------------------------------------------------------

# classes the fused tiers can merge into one dispatch; anything else runs
# solo through the serial executor's step runner (same engines, same
# ladder, bit-exact by construction)
_MERGEABLE = ("pairwise", "fold", "andnot", "threshold-device")


def _merge_class(step: PlanStep) -> tuple:
    eng, op = step.engine, step.node.op
    if eng == "pairwise":
        return ("pairwise", op)
    if eng in ("naive-or", "horizontal-or"):
        return ("fold", "or")
    if eng in ("naive-xor", "horizontal-xor"):
        return ("fold", "xor")
    if eng.startswith("andnot-batch"):
        return ("andnot", "device" if eng.endswith("[device]") else "cpu")
    if eng == "threshold-bitsliced[device]":
        return ("threshold-device",)
    # workshy-and (key-intersection fold), threshold CPU (per-key python
    # adder), device-* n-ary reduces (own amortized working set)
    return ("solo", eng)


# ---------------------------------------------------------------------------
# the batch entry
# ---------------------------------------------------------------------------


def execute_fused(
    queries: Sequence[Union[Expr, Plan]],
    cache: Optional[ResultCache] = DEFAULT_CACHE,
    mode: Optional[str] = None,
    deadline_s: Optional[float] = None,
) -> List[RoaringBitmap]:
    """Execute a window of concurrent queries as fused per-tier device
    programs. Results are bit-exact with ``[execute(q, ...) for q in
    queries]`` — fusion is a latency decision, never a correctness one.
    Fusion off (or a single query) routes straight to the serial loop."""
    qs = list(queries)
    if not qs:
        return []
    if not config.enabled or len(qs) == 1:
        return [
            _exec.execute(q, cache=cache, mode=mode, deadline_s=deadline_s)
            for q in qs
        ]
    with _context.trace_scope():
        return _execute_window(qs, cache, mode, deadline_s)


def _execute_window(qs, cache, mode, deadline_s) -> List[RoaringBitmap]:
    plans = [q if isinstance(q, Plan) else _exec._memo_plan(q, mode) for q in qs]
    unique: Dict[int, PlanStep] = {}
    deduped = 0
    for p in plans:
        for s in p.steps:
            if s.node.uid in unique:
                deduped += 1
            else:
                unique[s.node.uid] = s
    levels = _levels(unique)
    # cache-aware pricing: a warm window's steps are dict probes, not
    # dispatches — price only the steps the cache cannot serve, or the
    # verdict would predict a full recompute against a near-zero
    # measured wall on every warm drain (a perpetual mispricing anomaly
    # the ledger would rightly flag). The probe is __contains__ (no LRU
    # touch, no hit/miss accounting); cross-thread drift between probe
    # and execution is ordinary pricing noise.
    if cache is not None:
        leaf_fps = {}
        for p in plans:
            for l in p.root.leaves:
                if l.uid not in leaf_fps:
                    leaf_fps[l.uid] = l.fingerprint()
        live = {
            uid for uid, s in unique.items()
            if cache_key(s.node, leaf_fps) not in cache
        }
    else:
        live = set(unique)
    n_steps = len(live)
    n_tiers = sum(
        len(_group([s for s in steps if s.node.uid in live]))
        for steps in levels.values()
    )
    _QUERIES_TOTAL.inc(len(qs))
    if n_steps:
        _STEPS_TOTAL.inc(n_steps, ("executed",))
    if deduped:
        _STEPS_TOTAL.inc(deduped, ("deduped",))
    est = _fusion_cost.MODEL.estimate(n_steps, n_tiers)
    verdict = "fused" if est["fused"] <= est["per-query"] else "per-query"
    seq = _decisions.record_decision(
        "fusion.batch", verdict, outcome=_outcomes.enabled(),
        est_us=est, queries=len(qs), steps=n_steps, tiers=n_tiers,
        deduped=deduped,
    )

    def _serial() -> List[RoaringBitmap]:
        return [
            _exec.execute(p, cache=cache, mode=mode, deadline_s=deadline_s)
            for p in plans
        ]

    t0 = time.perf_counter()
    if verdict == "per-query" or n_steps == 0:
        with _outcomes.measure(seq, "fusion.batch", engine="per-query"):
            out = _serial()
        _BATCH_TOTAL.inc(1, ("per-query",))
        _BATCH_SECONDS.observe(time.perf_counter() - t0, ("batch",))
        return out

    state = {"degraded": False}

    def _serial_degraded() -> List[RoaringBitmap]:
        state["degraded"] = True
        return _serial()

    def _fused() -> List[RoaringBitmap]:
        _faults.fault_point("query.fusion")
        return _run_fused(plans, unique, levels, cache, deadline_s)

    out = _ladder.LADDER.run(
        "query.fusion",
        [("fused", _fused), ("per-query", _serial_degraded)],
        outcome_seq=seq, outcome_site="fusion.batch",
    )
    outcome = "degraded" if state["degraded"] else "fused"
    _BATCH_TOTAL.inc(1, (outcome,))
    _BATCH_SECONDS.observe(time.perf_counter() - t0, ("batch",))
    return out


def _levels(unique: Dict[int, PlanStep]) -> Dict[int, List[PlanStep]]:
    """Unique steps by topological depth: a tier at depth d has every
    operand materialized by depths < d, so merged groups never need a
    barrier inside a level."""
    depth: Dict[int, int] = {}

    def _depth(node) -> int:
        d = depth.get(node.uid)
        if d is not None:
            return d
        step = unique.get(node.uid)
        if step is None:  # leaf
            depth[node.uid] = 0
            return 0
        d = 1 + max((_depth(o) for o in step.operands), default=0)
        depth[node.uid] = d
        return d

    levels: Dict[int, List[PlanStep]] = {}
    for s in unique.values():
        levels.setdefault(_depth(s.node), []).append(s)
    return levels


def _group(steps: List[PlanStep]) -> Dict[tuple, List[PlanStep]]:
    groups: Dict[tuple, List[PlanStep]] = {}
    for s in steps:
        groups.setdefault(_merge_class(s), []).append(s)
    return groups


def _run_fused(plans, unique, levels, cache, deadline_s) -> List[RoaringBitmap]:
    leaf_fps: Dict[int, tuple] = {}
    results: Dict[int, RoaringBitmap] = {}
    for p in plans:
        for l in p.root.leaves:
            if l.uid not in leaf_fps:
                leaf_fps[l.uid] = l.fingerprint()
                results[l.uid] = l.bitmap
    with _timeline.tspan(
        "fusion.window", "fusion", queries=len(plans), steps=len(unique),
    ), _ladder.deadline_scope(deadline_s):
        for d in sorted(levels):
            for cls, steps in sorted(_group(levels[d]).items()):
                _run_group(cls, steps, results, leaf_fps, cache)
    return [results[p.root.uid].clone() for p in plans]


def _run_group(cls, steps, results, leaf_fps, cache) -> None:
    # cache + in-flight claim per step: hits and successful joins drop
    # out of the merge; owners publish after the group computes
    ready: List[Tuple[PlanStep, tuple, Optional[object]]] = []
    for s in steps:
        key = cache_key(s.node, leaf_fps)
        if cache is not None:
            hit = cache.get(key)
            if hit is not None:
                results[s.node.uid] = hit
                continue
            owner, pending = _inflight.TABLE.begin(key)
            if not owner:
                # non-blocking poll, NEVER join(): this executor already
                # holds unpublished claims for earlier steps of this
                # group — blocking on a foreign owner here could mutually
                # stall two windows that claimed shared nodes in opposite
                # orders (each waiting 30 s on the other's unpublished
                # claim). A still-computing foreign node is simply
                # recomputed inside the merge, unclaimed.
                joined = _inflight.TABLE.poll(pending)
                if joined is not None:
                    results[s.node.uid] = joined
                    continue
                ready.append((s, key, None))
            else:
                ready.append((s, key, pending))
        else:
            ready.append((s, key, None))
    if not ready:
        return
    force_cpu = _ladder.deadline_expired()
    merged = (
        not force_cpu and len(ready) >= 2 and cls[0] in _MERGEABLE
    )
    t0 = time.perf_counter()
    try:
        if merged:
            with _timeline.tspan(
                "fusion.tier", "fusion", cls="/".join(cls), steps=len(ready),
            ):
                vals = _run_merged(cls, ready, results)
        else:
            vals = []
            for s, _key, _entry in ready:
                inputs = [results[o.uid] for o in s.operands]
                vals.append(_exec._run_step(s, inputs, force_cpu=force_cpu))
    except BaseException:
        for _s, key, entry in ready:
            if entry is not None:
                _inflight.TABLE.abort(key, entry)
        raise
    wall = time.perf_counter() - t0
    if merged:
        _STEPS_TOTAL.inc(len(ready), ("merged",))
    per_step_s = wall / len(ready)
    for (s, key, entry), val in zip(ready, vals):
        seq = s.decision_seq
        if seq is not None:
            # the planner decision's measured join (ISSUE 11): merged
            # steps share the bucket wall pro-rata; the cardinality
            # refit only needs `actual`, which is exact either way
            s.decision_seq = None
            _outcomes.resolve(
                seq, "query.plan", per_step_s, engine=s.engine,
                actual=max(1, val.get_cardinality()),
            )
        if cache is not None:
            valid = leaf_fps_current(s.node, leaf_fps)
            if entry is not None:
                _inflight.TABLE.complete(key, entry, val, valid)
            if valid:
                cache.put(key, val)
        results[s.node.uid] = val


def _run_merged(cls, ready, results) -> List[RoaringBitmap]:
    if cls[0] == "pairwise":
        return _merged_pairwise(cls[1], ready, results)
    if cls[0] == "fold":
        return _merged_fold(cls[1], ready, results)
    if cls[0] == "andnot":
        if cls[1] == "device":
            return _merged_andnot_device(ready, results)
        return _merged_andnot_cpu(ready, results)
    return _merged_threshold_device(ready, results)


# ---------------------------------------------------------------------------
# merged tier implementations (each: ONE dispatch for the whole group)
# ---------------------------------------------------------------------------


def _merged_pairwise(op, ready, results) -> List[RoaringBitmap]:
    from .. import columnar
    from ..columnar import engine as _col_engine

    pairs = [
        (results[s.operands[0].uid], results[s.operands[1].uid])
        for s, _k, _e in ready
    ]
    # the window's largest pair prices the tier for the whole group
    # (record=False: the fusion.batch site is this window's provenance)
    big = max(
        pairs,
        key=lambda ab: min(
            ab[0].high_low_container.size, ab[1].high_low_container.size
        ),
    )
    tier = _col_engine.route(
        big[0].high_low_container, big[1].high_low_container,
        record=False, op=op,
    )
    dev = "device" if str(tier) == "columnar-device" else "cpu"
    return columnar.pairwise_multi(op, pairs, tier=dev)


def _merged_fold(op, ready, results) -> List[RoaringBitmap]:
    from ..columnar import engine as _col_engine
    from ..parallel import store

    groups_list = [
        store.group_by_key([results[o.uid] for o in s.operands])
        for s, _k, _e in ready
    ]
    return _col_engine.fold_multi(groups_list, op)


def _merged_andnot_cpu(ready, results) -> List[RoaringBitmap]:
    from .. import columnar
    from ..models.container import best_container_of_words
    from . import kernels as _qk

    jobs = []
    namespaced: dict = {}
    for si, (s, _k, _e) in enumerate(ready):
        first = results[s.operands[0].uid]
        rest = [results[o.uid] for o in s.operands[1:]]
        groups = _qk._rest_groups(first, rest)
        jobs.append((first, groups))
        for k, cs in groups.items():
            namespaced[(si, k)] = cs
    union = columnar.or_fold_words(namespaced) if namespaced else {}
    outs = []
    for si, (first, groups) in enumerate(jobs):
        hlc = first.high_low_container
        out = RoaringBitmap()
        for k, c in zip(hlc.keys, hlc.containers):
            if k not in groups:
                out.high_low_container.append(k, c.clone())
                continue
            acc = c.to_words()
            acc &= ~union[(si, k)]
            res = best_container_of_words(acc)
            if res.cardinality:
                out.high_low_container.append(k, res)
        outs.append(out)
    return outs


def _merged_andnot_device(ready, results) -> List[RoaringBitmap]:
    from ..ops import pallas_kernels as pk
    from ..parallel import store
    from . import kernels as _qk

    vals: List[Optional[RoaringBitmap]] = [None] * len(ready)
    stages = []
    for i, (s, _k, _e) in enumerate(ready):
        first = results[s.operands[0].uid]
        rest = [results[o.uid] for o in s.operands[1:]]
        ckeys, crows = _qk._covered(first, rest)
        if not crows:  # no subtrahend overlaps any of first's keys
            vals[i] = first.clone()
            continue
        stages.append((i, _qk._device_andnot_stage(first, rest, ckeys)))
    if stages:
        rows_list = [st[0] for _i, st in stages]
        union_list = [st[1] for _i, st in stages]
        total = sum(int(r.shape[0]) for r in rows_list)
        rows_all = pk.concat_rows(rows_list)
        union_all = pk.concat_rows(union_list)
        idx = np.arange(total, dtype=np.int64)
        words, cards = pk.pair_rows_reduce(rows_all, idx, union_all, idx, "andnot")
        off = 0
        for i, (first_rows, _union, passthrough, keys) in stages:
            g = int(first_rows.shape[0])
            computed = dict(
                store.iter_group_containers(
                    keys, words[off : off + g], cards[off : off + g]
                )
            )
            off += g
            out = RoaringBitmap()
            by_key = {k: c.clone() for k, c in passthrough}
            by_key.update(computed)
            for k in sorted(by_key):
                out.high_low_container.append(k, by_key[k])
            vals[i] = out
    return vals


def _merged_threshold_device(ready, results) -> List[RoaringBitmap]:
    import jax.numpy as jnp

    from ..parallel import store
    from . import kernels as _qk

    vals: List[Optional[RoaringBitmap]] = [None] * len(ready)
    buckets: dict = {}
    for i, (s, _k, _e) in enumerate(ready):
        bms = [results[o.uid] for o in s.operands]
        k = s.node.k
        if k > len(bms):
            vals[i] = RoaringBitmap()
            continue
        keys_ok, _rows = _qk._threshold_keys_ok(bms, k)
        if not keys_ok:
            vals[i] = RoaringBitmap()
            continue
        block = _qk._threshold_device_block(bms, k, keys_ok)
        if block is None:  # too skewed to pad: the CPU fold serves it
            vals[i] = _qk.threshold(k, bms, mode="cpu")
            continue
        packed, words3, n_slices = block
        if (k >> n_slices) != 0:
            vals[i] = RoaringBitmap()
            continue
        buckets.setdefault((k, n_slices, int(words3.shape[1])), []).append(
            (i, packed, words3)
        )
    for (k, n_slices, _m), items in sorted(buckets.items()):
        words_all = (
            jnp.concatenate([w for _i, _p, w in items], axis=0)
            if len(items) > 1 else items[0][2]
        )
        red, cards = _qk._threshold_kernel(k, n_slices)(words_all)
        red = np.asarray(red)
        cards = np.asarray(cards).astype(np.int64)
        off = 0
        for i, packed, w3 in items:
            g = int(w3.shape[0])
            vals[i] = store.unpack_to_bitmap(
                packed.group_keys, red[off : off + g], cards[off : off + g]
            )
            off += g
    return vals


# ---------------------------------------------------------------------------
# the serving window (submit -> future, latency/size/deadline-bounded drain)
# ---------------------------------------------------------------------------


def window_close_at(
    t_open: float, max_wait_s: float, deadlines: Sequence[Optional[float]]
) -> float:
    """When the open window must close (ISSUE 19): the straggler bound
    (``t_open + max_wait_s``) pulled EARLIER by the tightest member
    deadline — a member's slack can only close the window sooner, never
    hold it open longer. Pure arithmetic so the fake-clock tests pin
    "never held past its slack" with no threads or clocks at all."""
    close = t_open + max_wait_s
    for d in deadlines:
        if d is not None and d < close:
            close = d
    return close


class FusionExecutor:
    """Micro-batching front door: ``submit()`` enqueues and returns a
    future; the drain loop coalesces up to ``window`` queries (or
    whatever arrived within ``max_wait_ms`` of the window opening, or —
    since ISSUE 19 — whatever fits before the tightest member deadline)
    and executes the batch through :func:`execute_fused`. A budgeted
    submit (``tenant``/``slack_ms``) records the joint priced
    window-vs-solo verdict (``fusion.hedge``); an interactive request
    the verdict prices out of the window dispatches solo in the caller
    thread through the in-flight dedup table instead. One drain thread,
    lazily started; ``close()`` drains what is queued and stops."""

    def __init__(
        self,
        window: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        cache: Optional[ResultCache] = DEFAULT_CACHE,
        mode: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ):
        # an explicit window pins this executor; None tracks config.window
        # live, so the serving-p99-pressure auto-tune reaches running
        # executors, not just future ones
        self._window_override = window is not None
        self.window = int(window) if window is not None else config.window
        self.max_wait_s = (
            float(max_wait_ms) if max_wait_ms is not None else config.max_wait_ms
        ) / 1e3
        self.cache = cache
        self.mode = mode
        self.deadline_s = deadline_s
        self._cond = threading.Condition()
        self._queue: "deque[tuple]" = deque()  # guarded-by: self._cond
        self._closed = False  # guarded-by: self._cond
        self._thread: Optional[threading.Thread] = None  # guarded-by: self._cond
        self.batches = 0
        self.hedges = 0

    def _target_window(self) -> int:
        if self._window_override:
            return self.window
        return max(2, config.window)

    @staticmethod
    def _slack_for(
        tenant: Optional[str], slack_ms: Optional[float],
        latency_class: Optional[str],
    ) -> Tuple[Optional[float], Optional[str]]:
        """Resolve the request's latency budget: explicit args win, else
        the tenant's declared SLO from the serve-tier registry (lazily
        imported — the query layer must work without the serve tier)."""
        if slack_ms is None and tenant is not None:
            try:
                from ..serve import slo as _slo

                slack_ms = _slo.TENANTS.p99_budget_ms(tenant)
                if latency_class is None:
                    latency_class = _slo.TENANTS.latency_class(tenant)
            except KeyError:
                return None, None
        if slack_ms is None:
            return None, None
        return float(slack_ms) / 1e3, latency_class

    def submit(
        self,
        query: Union[Expr, Plan],
        tenant: Optional[str] = None,
        slack_ms: Optional[float] = None,
        latency_class: Optional[str] = None,
    ) -> "Future[RoaringBitmap]":
        fut: "Future[RoaringBitmap]" = Future()
        t_enq = time.perf_counter()
        slack_s, cls = self._slack_for(tenant, slack_ms, latency_class)
        deadline = (t_enq + slack_s) if slack_s is not None else None
        seq = None
        if slack_s is not None and config.enabled:
            verdict, seq = self._hedge_verdict(query, t_enq, slack_s, cls)
            if verdict == "solo":
                return self._dispatch_solo(query, fut, deadline, seq)
            if seq is not None:
                _HEDGE_TOTAL.inc(1, ("window",))
        self._enqueue(query, fut, t_enq, deadline, seq)
        return fut

    def _hedge_verdict(self, query, t_enq, slack_s, cls):
        """The per-request JOINT priced decision (ISSUE 19): predicted
        window completion (deadline-bounded hold + fused estimate of the
        forming batch) vs this request's own solo curve, each penalized
        past the slack — one comparison covering device efficiency AND
        the declared budget. Only latency-gold (interactive) requests act
        on a solo verdict; everyone budgeted records it."""
        try:
            plan = query if isinstance(query, Plan) else _exec._memo_plan(
                query, self.mode
            )
            steps = max(1, len(plan.steps))
        except Exception:  # rb-ok: exception-hygiene -- a plan error must surface on the window path (the future), not turn the hedge pricing probe into the request's failure point
            return "window", None
        with self._cond:
            depth = len(self._queue)
            t_open = self._queue[0][2] if self._queue else t_enq
            deadlines = [e[3] for e in self._queue]
        close_at = window_close_at(t_open, self.max_wait_s, deadlines)
        # the deadline-aware drain would close our window by our own
        # slack anyway: the hold we'd pay is bounded by both
        wait_us = max(0.0, (min(close_at, t_enq + slack_s) - t_enq)) * 1e6
        verdict, est = _fusion_cost.MODEL.choose_dispatch(
            steps, depth, wait_us, slack_s * 1e6
        )
        hedged = verdict == "solo" and cls == "interactive" and config.hedge
        recorded = "solo" if hedged else "window"
        seq = _decisions.record_decision(
            "fusion.hedge", recorded, outcome=_outcomes.enabled(),
            est_us=est, latency_class=cls, slack_ms=round(slack_s * 1e3, 3),
            depth=depth, steps=steps, priced=verdict,
        )
        return recorded, seq

    def _run_solo(self, query) -> RoaringBitmap:
        """The hedge's solo rung (fault-injectable at ``query.hedge``):
        the serial executor in the caller thread — its claim/join loop
        rides the SAME in-flight dedup table as the fused path."""
        _faults.fault_point("query.hedge")
        return _exec.execute(
            query, cache=self.cache, mode=self.mode,
            deadline_s=self.deadline_s,
        )

    def _dispatch_solo(self, query, fut, deadline, seq):
        """Hedged solo dispatch: bypass the window, execute in the caller
        thread through the serial executor — whose claim/join loop rides
        the SAME in-flight dedup table, so a shared subexpression already
        pending under a fused window still joins that result instead of
        recomputing. Degradation rung: a failing solo path falls back to
        the window (losing the latency hedge, keeping the answer)."""
        self.hedges += 1
        _HEDGE_TOTAL.inc(1, ("solo",))

        def _window_fallback() -> RoaringBitmap:
            f2: "Future[RoaringBitmap]" = Future()
            self._enqueue(query, f2, time.perf_counter(), deadline, None)
            return f2.result()

        try:
            val = _ladder.LADDER.run(
                "query.hedge",
                [
                    ("solo", lambda: self._run_solo(query)),
                    ("window", _window_fallback),
                ],
                outcome_seq=seq, outcome_site="fusion.hedge",
            )
        except Exception as e:  # rb-ok: exception-hygiene -- both rungs failed: the error belongs to this caller's future, exactly like a drained-batch failure
            fut.set_exception(e)
        else:
            fut.set_result(val)
        return fut

    def _enqueue(self, query, fut, t_enq, deadline, seq) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("FusionExecutor is closed")
            self._queue.append((query, fut, t_enq, deadline, seq))
            _publish_depth(id(self), len(self._queue))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain_loop, name="rb-fusion", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()

    def map(self, queries: Sequence[Union[Expr, Plan]]) -> List[RoaringBitmap]:
        """Submit all, wait for all — per-query latencies still land in
        the queued-phase histogram, unlike a direct execute_fused call."""
        futs = [self.submit(q) for q in queries]
        return [f.result() for f in futs]

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                t_open = self._queue[0][2]
                while len(self._queue) < self._target_window() and not self._closed:
                    # deadline-aware close (ISSUE 19): the tightest
                    # member slack pulls the close earlier than the
                    # straggler bound; a submit arriving mid-wait
                    # re-evaluates via notify_all
                    close_at = window_close_at(
                        t_open, self.max_wait_s,
                        [e[3] for e in self._queue],
                    )
                    remaining = close_at - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch = [
                    self._queue.popleft()
                    for _ in range(min(self._target_window(), len(self._queue)))
                ]
                _publish_depth(id(self), len(self._queue))
            now = time.perf_counter()
            for _q, _fut, t_enq, _dl, _seq in batch:
                _BATCH_SECONDS.observe(now - t_enq, ("queued",))
            try:
                outs = execute_fused(
                    [q for q, _f, _t, _dl, _seq in batch],
                    cache=self.cache, mode=self.mode, deadline_s=self.deadline_s,
                )
            except Exception as e:  # rb-ok: exception-hygiene -- a fatal batch error belongs to the submitting callers (their futures), not the drain thread, which must survive to serve the next window
                for _q, fut, _t, _dl, _seq in batch:
                    fut.set_exception(e)
            else:
                self.batches += 1
                done = time.perf_counter()
                for (_q, fut, t_enq, _dl, seq), val in zip(batch, outs):
                    fut.set_result(val)
                    if seq is not None:
                        # the window-verdict half of the fusion.hedge
                        # join: measured enqueue->result wall vs the
                        # predicted window completion
                        _outcomes.resolve(
                            seq, "fusion.hedge", done - t_enq, engine="window",
                        )

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        # a closed executor's parked depth must neither pin the stall
        # rule firing nor mask another executor's live depth
        _publish_depth(id(self), None)

    def __enter__(self) -> "FusionExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
