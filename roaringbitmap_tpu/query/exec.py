"""Memoizing plan executor.

Runs a :class:`~.plan.Plan` bottom-up. Every interior step first consults
the result cache (cache.py) under ``(node uid, leaf fingerprints)``; a hit
short-circuits that whole subtree, so a repeated query over unchanged
bitmaps is a handful of dict probes, and a query sharing subtrees with a
previous one recomputes only the novel nodes. Leaf fingerprints are
snapshotted once per execution so all steps key against one consistent
view even if another thread mutates a bitmap mid-run.

The returned bitmap is a private clone — callers may mutate it freely
without corrupting memoized results.

Plans are memoized too: planning reads leaf contents (constant folding,
cardinality estimates), so a plan is reusable exactly as long as the result
cache entries are — same (expression, leaf fingerprints, dispatch knobs).
A bounded plan memo keyed that way keeps the warm repeated-query path free
of rewrite/estimate work (code-review: planning must not dominate the
cache-hit steady state); a leaf mutation re-plans by key miss.

Below the result cache sits the resident pack cache (ISSUE 4,
parallel/store.PACK_CACHE): every device engine a step dispatches to —
FastAggregation and/or/xor, the n-way andnot batch, the bit-sliced
threshold — keys its packed working set by the SAME leaf fingerprints
this executor snapshots for result keys. A repeated query whose result
cache was disabled (or evicted) therefore still performs zero host packs:
the leaf packs come back resident, shared across the query's own nodes
and across queries over the same leaves. A leaf mutation delta-repacks
O(changed containers) rows instead of rebuilding the working set.

Instrumentation: ``rb_tpu_host_op_seconds{name="query.execute"}`` (and the
matching span) around the run, ``rb_tpu_query_cache_total{event}`` from the
cache, ``rb_tpu_query_plan_total{engine}`` from the planner, and
``rb_tpu_pack_cache_*`` from the pack cache underneath.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Union

from .. import observe as _observe
from ..observe import context as _context
from ..observe import outcomes as _outcomes
from ..observe import timeline as _timeline
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..models.roaring import RoaringBitmap
from . import inflight as _inflight
from . import kernels
from .cache import DEFAULT_CACHE, ResultCache, cache_key, leaf_fps_current
from .expr import Expr
from .plan import Plan, PlanStep
from .plan import plan as build_plan

# end-to-end query latency quantiles (ISSUE 6): p50/p99 per phase in every
# export — the serving-layer measurement ROADMAP item 3 builds on
_QUERY_LATENCY = _observe.latency_histogram(
    _observe.QUERY_LATENCY_SECONDS,
    "End-to-end query latencies by phase (plan | execute)",
    ("phase",),
)

_PLAN_MEMO_MAX = 128
_PLAN_MEMO_LOCK = threading.Lock()
_PLAN_MEMO: "OrderedDict[tuple, Plan]" = OrderedDict()  # guarded-by: _PLAN_MEMO_LOCK


def _memo_plan(expr: Expr, mode: Optional[str]) -> Plan:
    from ..parallel import aggregation

    key = (
        expr.uid,
        mode,
        # the dispatch knobs _use_device consults: a changed regime must
        # not be served a plan built for the old one
        aggregation.config.mode,
        aggregation.config.min_device_containers,
        aggregation.config.mesh is None,
        tuple(l.fingerprint() for l in expr.leaves),
    )
    with _PLAN_MEMO_LOCK:
        p = _PLAN_MEMO.get(key)
        if p is not None:
            _PLAN_MEMO.move_to_end(key)
            return p
    with _timeline.stage(_QUERY_LATENCY, "plan", "query.plan", cat="query"):
        p = build_plan(expr, mode=mode)
    with _PLAN_MEMO_LOCK:
        _PLAN_MEMO[key] = p
        while len(_PLAN_MEMO) > _PLAN_MEMO_MAX:
            _PLAN_MEMO.popitem(last=False)
    return p


def execute(
    query: Union[Expr, Plan],
    cache: Optional[ResultCache] = DEFAULT_CACHE,
    mode: Optional[str] = None,
    deadline_s: Optional[float] = None,
) -> RoaringBitmap:
    """Plan (if given an expression) and evaluate, memoizing interior
    results in ``cache`` (pass ``cache=None`` to disable memoization;
    ``mode`` forwards to the planner's engine choice).

    ``deadline_s`` arms a per-query wall-clock budget (ISSUE 7): once it
    expires, every remaining step cancels its device engine choice down to
    the cheapest CPU tier — the result stays bit-exact (tiers agree by
    construction), only the remaining latency profile changes, instead of
    queueing more device work onto a query that already blew its budget.
    ``rb_tpu_deadline_total{site="query.exec",outcome}`` counts the
    outcomes (met | degraded)."""
    # top-level trace entry (ISSUE 9): the whole plan+execute runs under
    # one query trace id (reused when a pipelined driver pre-assigned it),
    # so every step span, engine span, and cache instant attributes here
    with _context.trace_scope():
        return _execute_traced(query, cache, mode, deadline_s)


def _execute_traced(query, cache, mode, deadline_s) -> RoaringBitmap:
    from .. import tracing

    p = query if isinstance(query, Plan) else _memo_plan(query, mode)
    degraded = False
    with tracing.op_timer("query.execute"), _timeline.stage(
        _QUERY_LATENCY, "execute", "query.execute", cat="query",
        steps=len(p.steps),
    ), _ladder.deadline_scope(deadline_s):
        leaf_fps = {l.uid: l.fingerprint() for l in p.root.leaves}
        results: Dict[int, RoaringBitmap] = {
            l.uid: l.bitmap for l in p.root.leaves
        }
        for step in p.steps:
            key = cache_key(step.node, leaf_fps)
            entry = None
            if cache is not None:
                hit = cache.get(key)
                if hit is not None:
                    results[step.node.uid] = hit
                    _timeline.instant(
                        "query.cache_hit", "query", op=step.node.op
                    )
                    continue
                # in-flight dedup (ISSUE 13): an identical node computing
                # in ANOTHER query right now is joined, not recomputed;
                # a None join (stale / owner failed / timeout) falls
                # through to computing it ourselves, unclaimed
                owner, pending = _inflight.TABLE.begin(key)
                if owner:
                    entry = pending
                else:
                    joined = _inflight.TABLE.join(pending)
                    if joined is not None:
                        results[step.node.uid] = joined
                        _timeline.instant(
                            "query.inflight_join", "query", op=step.node.op
                        )
                        continue
            inputs = [results[o.uid] for o in step.operands]
            force_cpu = _ladder.deadline_expired()
            if force_cpu and not degraded:
                degraded = True
                _timeline.instant(
                    "query.deadline_degrade", "query", engine=step.engine
                )
            seq = step.decision_seq
            t0 = time.perf_counter() if seq is not None else 0.0
            try:
                with _timeline.tspan(
                    "query.step", "query", engine=step.engine, op=step.node.op,
                    decision=seq,
                ):
                    val = _run_step(step, inputs, force_cpu=force_cpu)
            except BaseException:
                if entry is not None:  # joiners recompute on their own ladder
                    _inflight.TABLE.abort(key, entry)
                raise
            if seq is not None:
                # resolve the planner decision ONCE (ISSUE 11): measured
                # step wall + actual result cardinality against the
                # plan-time estimate; a memoized plan's later executions
                # ride with the serial already cleared
                step.decision_seq = None
                _outcomes.resolve(
                    seq, "query.plan", time.perf_counter() - t0,
                    engine=step.engine, actual=max(1, val.get_cardinality()),
                )
            if cache is not None:
                # validated publication (ISSUE 13 satellite): a leaf
                # mutated mid-computation makes this value match neither
                # the key's snapshot nor the new contents — joiners get
                # None (recompute fresh) and the cache never stores it
                valid = leaf_fps_current(step.node, leaf_fps)
                if entry is not None:
                    _inflight.TABLE.complete(key, entry, val, valid)
                if valid:
                    cache.put(key, val)
            results[step.node.uid] = val
        if deadline_s is not None:
            _ladder.note_deadline(
                "query.exec", "degraded" if degraded else "met"
            )
        return results[p.root.uid].clone()


def execute_pipelined(
    queries: Sequence[Union[Expr, Plan]],
    cache: Optional[ResultCache] = DEFAULT_CACHE,
    mode: Optional[str] = None,
    deadline_s: Optional[float] = None,
) -> List[RoaringBitmap]:
    """Execute back-to-back queries with the overlap shipping lane
    (ISSUE 8 leg 3): while query i runs, query i+1's device-routed leaf
    working sets stage host→HBM on the lane thread, so steady-state
    multi-query traffic never idles the device on the marshal. Results are
    identical to ``[execute(q, ...) for q in queries]`` — staging only
    warms the resident pack cache the engines read anyway.

    Every query gets its own pre-assigned trace id (ISSUE 9); query
    i+1's prefetch runs under query i+1's id even though query i's loop
    iteration drives it — the staged marshal belongs to its consumer."""
    plans = [q if isinstance(q, Plan) else _memo_plan(q, mode) for q in queries]
    tids = [_context.new_trace_id() for _ in plans]
    out = []
    for i, p in enumerate(plans):
        # join our own stagings FIRST (prefetched while query i-1 ran):
        # popping them frees the lane window for the next prefetch and
        # accounts the overlap_wait stage; the staged packs are resident
        # in PACK_CACHE, so the engines' lookups below hit warm
        with _context.trace_scope(tids[i]):
            _join_plan(p)
        if i + 1 < len(plans):
            with _context.trace_scope(tids[i + 1]):
                _prefetch_plan(plans[i + 1], mode)
        with _context.trace_scope(tids[i]):
            out.append(
                execute(p, cache=cache, mode=mode, deadline_s=deadline_s)
            )
    return out


def _device_step_leaves(p: Plan):
    """Yield ``(leaves, op)`` for the plan's device-routed all-leaf steps —
    device-* n-ary and/or/xor only: the andnot/threshold kernels key their
    packs differently (kind-prefixed get_or_build keys), and the mesh
    -sharded engines consume the HOST word block (pad_groups_dense), so
    staging a device expansion for either would be pure waste."""
    for step in p.steps:
        if not step.engine.startswith("device-") or step.engine.endswith(
            "-sharded"
        ):
            continue
        leaves = [getattr(o, "bitmap", None) for o in step.operands]
        if len(leaves) >= 2 and all(b is not None for b in leaves):
            yield leaves, step.node.op


def _prefetch_plan(p: Plan, mode: Optional[str]) -> None:
    """Stage the plan's device-routed all-leaf steps on the overlap lane
    (the prelude in aggregation.prefetch re-checks the device gate, so a
    step the executor would run on CPU stages nothing)."""
    from ..parallel import aggregation

    for leaves, op in _device_step_leaves(p):
        aggregation.prefetch(leaves, op, mode=mode)


def _join_plan(p: Plan) -> None:
    """Pop the plan's stagings off the overlap lane (no-op for steps that
    never staged); results landed in PACK_CACHE, so only the window slot
    and the overlap accounting ride on the join."""
    from ..parallel import overlap

    for leaves, op in _device_step_leaves(p):
        overlap.LANE.join(leaves, op)


def _run_step(
    step: PlanStep, inputs: List[RoaringBitmap], force_cpu: bool = False
) -> RoaringBitmap:
    from ..parallel.aggregation import FastAggregation as FA

    eng, op = step.engine, step.node.op
    if eng == "pairwise":
        fn = {
            "and": RoaringBitmap.and_,
            "or": RoaringBitmap.or_,
            "xor": RoaringBitmap.xor,
            "andnot": RoaringBitmap.andnot,
        }[op]
        return fn(inputs[0], inputs[1])
    if eng.startswith("device-"):
        fn = {"and": FA.and_, "or": FA.or_, "xor": FA.xor}[op]
        if force_cpu:  # deadline blown: cancel to the cheapest tier
            return fn(*inputs, mode="cpu")

        def _device_step():
            _faults.fault_point("query.exec")
            return fn(*inputs, mode="device")

        return _ladder.LADDER.run(
            "query.exec",
            [
                ("device", _device_step),
                ("per-container", lambda: fn(*inputs, mode="cpu")),
            ],
        )
    if eng == "workshy-and":
        return FA.and_(*inputs, mode="cpu")
    if eng == "naive-or":
        return FA.naive_or(*inputs)
    if eng == "horizontal-or":
        return FA.horizontal_or(*inputs)
    if eng == "naive-xor":
        return FA.naive_xor(*inputs)
    if eng == "horizontal-xor":
        return FA.horizontal_xor(*inputs)
    if eng.startswith("andnot-batch"):
        mode = "device" if eng.endswith("[device]") and not force_cpu else "cpu"
        return kernels.andnot_nway(inputs[0], *inputs[1:], mode=mode)
    if eng.startswith("threshold-bitsliced"):
        mode = "device" if eng.endswith("[device]") and not force_cpu else "cpu"
        return kernels.threshold(step.node.k, inputs, mode=mode)
    raise ValueError(f"unknown engine {eng!r}")  # pragma: no cover
