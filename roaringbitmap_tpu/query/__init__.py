"""Lazy query expression engine (ISSUE 2 tentpole).

A serving-system hot path evaluates whole boolean expressions —
``(users_in_A & users_in_B) - opted_out | Q.threshold(2, x, y, z)`` — over
many bitmaps. The reference library's ``FastAggregation`` chooses an
algorithm per *call* and leaves operand ordering to the caller; this layer
plans over the whole expression instead ("beyond unions and intersections",
PAPERS.md):

* ``expr.py`` — lazy, hash-consed DAG nodes (And/Or/Xor/AndNot/Not over an
  explicit universe/Threshold(k)) built via operator overloading or the
  :class:`Q` API; repeated subtrees share one node.
* ``plan.py`` — exact algebraic rewrites (flattening, De Morgan push-down,
  difference pull-up, constant folding), a cardinality-driven cost model,
  and per-node engine selection over the full FastAggregation/device/batch
  menu; emits an inspectable :class:`Plan` with ``explain()``.
* ``exec.py`` — bottom-up execution with interior-result memoization in a
  bounded LRU cache (``cache.py``) keyed by (node, leaf fingerprints), so
  repeated queries over unchanged bitmaps short-circuit and leaf mutation
  invalidates by key miss.
* ``kernels.py`` — the aggregation-gap fillers: n-way ANDNOT and the
  bit-sliced-adder Threshold(k), each with CPU and packed-device paths.
* ``inflight.py`` / ``fusion.py`` — the serving tier (ISSUE 13): a
  global in-flight table (a second identical node joins the first's
  pending computation instead of recomputing — dedup across queries),
  and the micro-batching executor coalescing windows of concurrent
  queries into fused per-tier dispatches (``execute_fused`` /
  ``FusionExecutor``).

Quick start::

    from roaringbitmap_tpu.query import Q, execute, plan

    q = (Q.leaf(a) & Q.leaf(b) | Q.leaf(c)) - Q.leaf(opted_out)
    print(plan(q).explain())           # rewrites + engines + estimates
    result = execute(q)                # planned, memoized
    result = execute(q)                # cache hit (bitmaps unchanged)
"""

from .cache import DEFAULT_CACHE, ResultCache, cache_key, leaf_fps_current
from .exec import execute, execute_pipelined
from .expr import Expr, Leaf, Q, as_expr, evaluate_naive
from .fusion import FusionExecutor, execute_fused
from .inflight import TABLE as INFLIGHT
from .inflight import InflightTable
from .kernels import andnot_nway, andnot_nway_cardinality, threshold
from .plan import Plan, PlanStep, plan, rewrite
from . import fusion

__all__ = [
    "Q",
    "Expr",
    "Leaf",
    "as_expr",
    "evaluate_naive",
    "plan",
    "rewrite",
    "Plan",
    "PlanStep",
    "execute",
    "execute_pipelined",
    "execute_fused",
    "fusion",
    "FusionExecutor",
    "InflightTable",
    "INFLIGHT",
    "ResultCache",
    "DEFAULT_CACHE",
    "cache_key",
    "leaf_fps_current",
    "andnot_nway",
    "andnot_nway_cardinality",
    "threshold",
]
