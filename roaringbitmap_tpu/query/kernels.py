"""N-way ANDNOT and Threshold(k) kernels — the aggregation gap fillers.

The aggregation layer (parallel/aggregation.py) covers n-ary AND/OR/XOR;
difference exists only pairwise and "element in >= k of N" not at all.
Both kernels here follow the house two-regime design:

* **andnot_nway(first, \\*rest)** — ``first \\ (rest_1 | ... | rest_n)``.
  Only ``first``'s keys can survive (the workShyAnd observation applied to
  subtraction), so the subtrahends transpose into key groups *restricted to
  first's keys*, the union reduces per group (CPU word fold, or the packed
  device reduction via ``store.prepare_reduce``), and the subtraction is a
  single fused ``first & ~union`` mask + popcount — on device this is
  exactly the ``parallel.batch`` pairwise-mask shape, run once per working
  set instead of once per operand.

* **threshold(k, bitmaps)** — the bit-sliced adder trick from "beyond
  unions and intersections": per key group, fold each container's words
  into a binary counter held as L = ceil(log2(count+1)) bit-slices (XOR =
  sum bit, AND = carry), then compare the per-bit counters against the
  constant k with a bitwise >= circuit (one pass MSB->LSB maintaining
  eq/gt masks). O(N·log N) word-ops instead of materializing per-element
  counts. The device path runs the same adder as a ``lax.scan`` over the
  row axis of the dense-padded ``[G, M, W]`` group block (zero fill rows
  add nothing), with the compare + popcount fused into the same dispatch;
  distributions too skewed to pad fall back to the CPU fold.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..models.container import BitmapContainer, best_container_of_words
from ..models.roaring import RoaringBitmap
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..utils import bits


def _container_words(c) -> np.ndarray:
    return c.words if isinstance(c, BitmapContainer) else c.to_words()


def _rest_groups(first: RoaringBitmap, rest: Sequence[RoaringBitmap]):
    """Subtrahend containers keyed by first's keys only (other keys cannot
    affect the difference)."""
    first_keys = set(first.high_low_container.keys)
    groups: dict = {}
    for bm in rest:
        hlc = bm.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            if k in first_keys:
                groups.setdefault(k, []).append(c)
    return groups


def _covered(first: RoaringBitmap, rest):
    """``(covered_keys, covered_rows)`` — the keys of ``first`` that any
    subtrahend shares, and the count of subtrahend containers on them —
    from the key lists alone, so the warm device path (resident pack-cache
    hit) never pays the container transpose. The single source of the
    key-partition rule for both andnot entry points and the device core."""
    fk = set(first.high_low_container.keys)
    keys: set = set()
    rows = 0
    for bm in rest:
        for k in bm.high_low_container.keys:
            if k in fk:
                keys.add(k)
                rows += 1
    return keys, rows


def _cpu_folds(first: RoaringBitmap, groups: dict):
    """The shared CPU core: per key of ``first`` yield ``(key, container,
    folded_words)`` — folded_words is None for pass-through keys with no
    subtrahend containers. One fold body serves both the materializing and
    the count-only entry points so they cannot desynchronize.

    Large subtrahend sets route the per-key union through the columnar
    batched OR fold (one scatter/fill/reduceat pass over every subtrahend
    container, ISSUE 5) instead of the per-container ``acc &= ~words``
    walk — gated by the measured fold cutoff when the columnar cost
    model has calibrated one (ISSUE 10), the config default otherwise."""
    from .. import columnar

    hlc = first.high_low_container
    union_words = None
    if columnar.enabled_for_fold(sum(len(cs) for cs in groups.values())):
        union_words = columnar.or_fold_words(groups)
    for k, c in zip(hlc.keys, hlc.containers):
        cs = groups.get(k)
        if not cs:
            yield k, c, None
            continue
        acc = c.to_words()
        if union_words is not None:
            acc &= ~union_words[k]
        else:
            for rc in cs:
                acc &= ~_container_words(rc)
        yield k, c, acc


def andnot_nway(
    first: RoaringBitmap, *rest: RoaringBitmap, mode: Optional[str] = None
) -> RoaringBitmap:
    """``first \\ (rest_1 | rest_2 | ...)`` without materializing the union
    as a bitmap (single word fold per surviving key)."""
    from ..parallel.aggregation import _use_device

    if not rest:
        return first.clone()
    ckeys, crows = _covered(first, rest)

    def _cpu_tier() -> RoaringBitmap:
        groups = _rest_groups(first, rest)
        out = RoaringBitmap()
        for k, c, acc in _cpu_folds(first, groups):
            if acc is None:
                out.high_low_container.append(k, c.clone())
                continue
            res = best_container_of_words(acc)
            if res.cardinality:
                out.high_low_container.append(k, res)
        return out

    if (
        crows
        and _use_device(first.high_low_container.size + crows, mode)
        and not _ladder.deadline_expired()
    ):

        def _device_tier() -> RoaringBitmap:
            _faults.fault_point("query.exec")
            return _device_andnot(first, rest, ckeys)

        return _ladder.LADDER.run(
            "query.exec",
            [("device", _device_tier), ("per-container", _cpu_tier)],
        )
    return _cpu_tier()


def andnot_nway_cardinality(
    first: RoaringBitmap, *rest: RoaringBitmap, mode: Optional[str] = None
) -> int:
    """``|first \\ (rest_1 | ...)|``; the device path fetches only the
    per-group popcounts (the count-only asymmetry, ARCHITECTURE.md)."""
    from ..parallel.aggregation import _use_device

    if not rest:
        return first.get_cardinality()
    ckeys, crows = _covered(first, rest)

    def _cpu_tier() -> int:
        groups = _rest_groups(first, rest)
        return sum(
            c.cardinality if acc is None else bits.cardinality_of_words(acc)
            for _k, c, acc in _cpu_folds(first, groups)
        )

    if (
        crows
        and _use_device(first.high_low_container.size + crows, mode)
        and not _ladder.deadline_expired()
    ):

        def _device_tier() -> int:
            _faults.fault_point("query.exec")
            _, cards, passthrough, _keys = _device_andnot_parts(first, rest, ckeys)
            return int(np.asarray(cards).astype(np.int64).sum()) + sum(
                c.cardinality for _, c in passthrough
            )

        return _ladder.LADDER.run(
            "query.exec",
            [("device", _device_tier), ("per-container", _cpu_tier)],
        )
    return _cpu_tier()


def _device_andnot_stage(first: RoaringBitmap, rest, covered_keys: set):
    """The device andnot's union stage: pack (resident) + per-covered-key
    subtrahend union reduce. Returns (first's covered rows on device
    [G, 2048], union rows on device [G, 2048], passthrough key/container
    pairs, sorted covered keys int64[G]) — the solo path fuses the
    ``first & ~union`` mask + popcount right here; the fused executor
    (ISSUE 13) collects SEVERAL queries' stages and runs their masks +
    popcounts as one concatenated dispatch instead.

    Both packs — the subtrahend groups AND first's covered rows — live in
    the resident pack cache (store.PACK_CACHE, ISSUE 4) under the operand
    fingerprints; the group transpose itself happens only inside the miss
    build, so a repeated andnot over unchanged bitmaps performs zero host
    packs AND no per-container walk (only the key partition of first)."""
    import jax.numpy as jnp

    from ..parallel import store
    from .. import tracing

    hlc = first.high_low_container
    covered = [(k, c) for k, c in zip(hlc.keys, hlc.containers) if k in covered_keys]
    passthrough = [
        (k, c) for k, c in zip(hlc.keys, hlc.containers) if k not in covered_keys
    ]
    operands = (first,) + tuple(rest)
    key = (
        "andnot",
        first.fingerprint(),
        tuple(bm.fingerprint() for bm in rest),
    )

    def build():
        packed = store.pack_groups(_rest_groups(first, rest))
        # first's covered rows ride the device-side expansion too (ISSUE 8)
        first_rows = store.ship_rows([c for _, c in covered])
        return (packed, first_rows), packed.words_nbytes + int(first_rows.nbytes)

    with tracing.op_timer("query.andnot.device"):
        packed, first_rows = store.PACK_CACHE.get_or_build(
            key, build, refs=store.static_fp_refs(operands)
        )
        run, _layout = store.prepare_reduce(packed, op="or")
        union, _ = run()
    return (
        first_rows, jnp.asarray(union), passthrough,
        np.asarray(sorted(covered_keys), dtype=np.int64),
    )


def _device_andnot_parts(first: RoaringBitmap, rest, covered_keys: set):
    """Shared device core: the union stage above plus the fused
    ``first & ~union`` mask + popcount in one dispatch. Returns (masked
    device words [G, 2048], cards [G], passthrough key/container pairs
    for first's uncovered keys, sorted covered keys int64[G]).

    No second ``query.andnot.device`` timer here: the stage above owns
    the op's (one) timing span — the mask + popcount is an async device
    enqueue, and doubling the span count would halve the op's telemetry
    mean versus pre-ISSUE-13 rounds."""
    from ..ops import device as dev

    first_rows, union, passthrough, keys = _device_andnot_stage(
        first, rest, covered_keys
    )
    masked = first_rows & ~union
    cards = dev.popcount_rows(masked)
    return masked, cards, passthrough, keys


def _device_andnot(first: RoaringBitmap, rest, covered_keys: set) -> RoaringBitmap:
    from ..parallel import store

    masked, cards, passthrough, keys = _device_andnot_parts(first, rest, covered_keys)
    computed = dict(
        store.iter_group_containers(
            keys, np.asarray(masked), np.asarray(cards).astype(np.int64)
        )
    )
    out = RoaringBitmap()
    merged = {k: c.clone() for k, c in passthrough}
    merged.update(computed)
    for k in sorted(merged):
        out.high_low_container.append(k, merged[k])
    return out


# ---------------------------------------------------------------------------
# Threshold(k): bit-sliced adder
# ---------------------------------------------------------------------------


def _add_word_slices(slices: List[np.ndarray], carry: np.ndarray) -> None:
    """Binary counter increment: add the 0/1 word ``carry`` into the LSB of
    the bit-sliced counter (XOR = sum, AND = carry ripple)."""
    i = 0
    while i < len(slices) and carry.any():
        s = slices[i]
        slices[i] = s ^ carry
        carry = s & carry
        i += 1
    if carry.any():
        slices.append(carry)


def _ge_const_words(slices: List[np.ndarray], k: int) -> Optional[np.ndarray]:
    """Bitwise compare of the per-position counters against the constant k:
    one MSB->LSB pass maintaining equal-so-far / greater masks. None when
    the counter width cannot reach k."""
    L = len(slices)
    if (k >> L) != 0:
        return None
    eq = np.full_like(slices[0], ~np.uint64(0))
    gt = np.zeros_like(slices[0])
    for b in range(L - 1, -1, -1):
        s = slices[b]
        if (k >> b) & 1:
            eq = eq & s
        else:
            gt = gt | (eq & s)
            eq = eq & ~s
    return gt | eq


def threshold(
    k: int, bitmaps: Sequence[RoaringBitmap], mode: Optional[str] = None
) -> RoaringBitmap:
    """Values present in at least ``k`` of ``bitmaps`` (multiset: a bitmap
    passed twice counts twice). k=1 is OR, k=N is AND, k>N is empty."""
    from ..parallel import aggregation, store

    k = int(k)
    if k < 1:
        raise ValueError(f"threshold k must be >= 1, got {k}")
    bms = list(bitmaps)
    if k > len(bms):
        return RoaringBitmap()
    if k == 1:
        return aggregation.FastAggregation.or_(*bms, mode=mode)
    if k == len(bms):
        return aggregation.FastAggregation.and_(*bms, mode=mode)
    keys_ok, n_rows = _threshold_keys_ok(bms, k)
    out = RoaringBitmap()
    if not keys_ok:
        return out
    if aggregation._use_device(n_rows, mode) and not _ladder.deadline_expired():

        def _device_tier():
            _faults.fault_point("query.exec")
            return _device_threshold(bms, k, keys_ok)

        # a None return is the documented too-skewed-to-pad signal, not a
        # failure: it falls through to the CPU fold below either way
        dev_out = _ladder.LADDER.run(
            "query.exec",
            [("device", _device_tier), ("per-container", lambda: None)],
        )
        if dev_out is not None:
            return dev_out
    groups = store.group_by_key(bms, keys_filter=keys_ok)
    for key in sorted(groups):
        slices: List[np.ndarray] = []
        for c in groups[key]:
            _add_word_slices(slices, c.to_words())
        words = _ge_const_words(slices, k)
        if words is None:
            continue
        res = best_container_of_words(words)
        if res.cardinality:
            out.high_low_container.append(key, res)
    return out


def _threshold_keys_ok(bms, k: int):
    """The >= k key pre-filter: a key present in fewer than k containers
    can never reach the threshold — decided from the key lists alone so
    the warm device path (resident pack-cache hit) skips the container
    transpose entirely. Returns (surviving key set, surviving row count);
    shared by the solo kernel and the fused executor (ISSUE 13)."""
    from collections import Counter

    key_counts = Counter()
    for bm in bms:
        key_counts.update(bm.high_low_container.keys)
    keys_ok = {key for key, c in key_counts.items() if c >= k}
    n_rows = sum(c for key, c in key_counts.items() if key in keys_ok)
    return keys_ok, n_rows


_threshold_steps: dict = {}


def _threshold_kernel(k: int, n_slices: int):
    """Jitted [G, M, W] bit-sliced adder + >=k compare + popcount, one
    dispatch; cached per (k, slice count) like the batch steps."""
    fn = _threshold_steps.get((k, n_slices))
    if fn is None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import device as dev

        def run(words3):
            g, _m, w = words3.shape

            def body(slices, row):  # slices [L, G, W] uint32, row [G, W]
                carry = row
                outs = []
                for i in range(n_slices):
                    s = slices[i]
                    outs.append(s ^ carry)
                    carry = s & carry
                return jnp.stack(outs), None

            init = jnp.zeros((n_slices, g, w), dtype=jnp.uint32)
            slices, _ = lax.scan(body, init, jnp.swapaxes(words3, 0, 1))
            eq = jnp.full((g, w), jnp.uint32(0xFFFFFFFF))
            gt = jnp.zeros((g, w), jnp.uint32)
            for b in range(n_slices - 1, -1, -1):
                s = slices[b]
                if (k >> b) & 1:
                    eq = eq & s
                else:
                    gt = gt | (eq & s)
                    eq = eq & ~s
            res = gt | eq
            return res, dev.popcount_rows(res)

        fn = _threshold_steps[(k, n_slices)] = jax.jit(run)
    return fn


def _threshold_device_block(bms, k: int, keys_ok: set):
    """The device threshold's resident pack + dense-padded block: returns
    ``(packed, words3 [G, M, W], n_slices)``, or None when the group
    distribution is too skewed to pad (callers fall back to the CPU
    fold). The pack is resident in the shared cache (k participates in
    the key: it decides which key groups survive the >= k pre-filter,
    hence the pack contents); the group transpose runs only inside the
    miss build. Shared by the solo kernel and the fused executor
    (ISSUE 13), whose windows concatenate same-(k, M) blocks along G."""
    from ..parallel import store

    def _build():
        p = store.pack_groups(store.group_by_key(bms, keys_filter=keys_ok))
        return p, p.words_nbytes

    key = ("threshold", k, tuple(bm.fingerprint() for bm in bms))
    packed = store.PACK_CACHE.get_or_build(
        key, _build, refs=store.static_fp_refs(bms)
    )
    words3 = packed.padded_device(0)  # zero fill rows add nothing to counts
    if words3 is None:
        # too skewed to pad: the CPU fold serves this working set, so a
        # resident pack would only squat on the shared budget — drop it
        store.PACK_CACHE.discard(key)
        return None
    m = int(words3.shape[1])
    n_slices = max(1, m.bit_length())  # counters reach at most m < 2^L
    return packed, words3, n_slices


def _device_threshold(bms, k: int, keys_ok: set) -> Optional[RoaringBitmap]:
    """Dense-padded device path; None when the group distribution is too
    skewed to pad (caller falls back to the CPU fold)."""
    from ..parallel import store
    from .. import tracing

    block = _threshold_device_block(bms, k, keys_ok)
    if block is None:
        return None
    packed, words3, n_slices = block
    if (k >> n_slices) != 0:
        return RoaringBitmap()
    with tracing.op_timer("query.threshold.device"):
        red, cards = _threshold_kernel(k, n_slices)(words3)
        return store.unpack_to_bitmap(
            packed.group_keys, np.asarray(red), np.asarray(cards).astype(np.int64)
        )
