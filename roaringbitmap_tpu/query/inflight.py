"""Global in-flight table: cross-query dedup of *pending* node results
(ISSUE 13 tentpole, leg 1).

The result cache (cache.py) dedups *completed* work: a second identical
query over unchanged bitmaps short-circuits at every memoized node. But
at serving QPS the second identical query usually arrives while the
first is still COMPUTING — a cache miss — and before this module both
executed the full subtree. This table upgrades the cache with a pending
tier: the first executor to reach a node key becomes the **owner** and
computes; any executor reaching the same key mid-flight becomes a
**joiner** and blocks on the owner's completion instead of recomputing.
Keys are the result cache's own ``(node uid, leaf fingerprints)`` —
dedup across queries falls out of hash-consing (same subexpression over
the same bitmaps IS the same node) plus the fingerprint snapshot.

**The dedup contract** (the ISSUE-13 satellite fix to the cross-query
key semantics): a published value must correspond to the leaf
fingerprints in its key. The executor reads *live* leaf bitmaps, so a
leaf mutated mid-computation can leave the owner holding bits that match
neither the old nor the new fingerprint (a torn read — acceptable for
the owner, whose caller raced the mutation and gets some valid
interleaving, but POISON for a joiner or cache entry keyed by the
pre-mutation fingerprints). Publication is therefore **validated**: the
owner re-fingerprints the node's leaves at completion and publishes only
when they still equal the key's snapshot; a stale completion counts
``stale``, hands joiners ``None`` (recompute against fresh contents),
and never reaches the cache. An owner that raises fails the entry the
same way — joiners recompute rather than inheriting the exception,
because *their* attempt may ride a healthy tier.

Bounds & cost: one leaf lock around a plain dict; entries exist only
while a computation is in flight (completion removes them), so the table
is bounded by executor concurrency, not traffic. Joiner waits carry a
timeout (default 30 s) — a wedged owner degrades the joiner to
recomputation, never to a deadlock. Events land in
``rb_tpu_query_inflight_total{event}`` (lead | join | stale | fail).
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from .. import observe as _observe

_INFLIGHT_TOTAL = _observe.counter(
    _observe.QUERY_INFLIGHT_TOTAL,
    "In-flight dedup table events (lead = became owner, join = joined a "
    "pending computation, stale = completion failed fingerprint "
    "validation, fail = owner raised)",
    ("event",),
)

# a joiner never waits forever on a wedged owner: past this it recomputes
DEFAULT_JOIN_TIMEOUT_S = 30.0


class _Entry:
    __slots__ = ("event", "value", "valid")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.valid = False


class InflightTable:
    """Thread-safe pending-computation table keyed like the result cache."""

    def __init__(self, join_timeout_s: float = DEFAULT_JOIN_TIMEOUT_S):
        self.join_timeout_s = float(join_timeout_s)
        self._lock = threading.Lock()  # leaf: guards the dict only
        self._pending: dict = {}  # guarded-by: self._lock
        self.leads = 0  # guarded-by: self._lock
        self.joins = 0  # guarded-by: self._lock
        self.stale = 0  # guarded-by: self._lock

    def begin(self, key: tuple) -> Tuple[bool, Optional[_Entry]]:
        """Claim ``key``: ``(True, entry)`` makes the caller the owner
        (it MUST later call :meth:`complete` or :meth:`abort` on the
        entry); ``(False, entry)`` means another executor owns it — wait
        via :meth:`join`."""
        with self._lock:
            entry = self._pending.get(key)
            if entry is not None:
                self.joins += 1
                owner = False
            else:
                entry = self._pending[key] = _Entry()
                self.leads += 1
                owner = True
        _INFLIGHT_TOTAL.inc(1, ("lead" if owner else "join",))
        return owner, entry

    def complete(self, key: tuple, entry: _Entry, value, valid: bool) -> None:
        """Owner publication. ``valid=False`` is the stale-fingerprint
        path: joiners wake to ``None`` and recompute — mid-mutation bits
        are never shared across queries."""
        entry.value = value if valid else None
        entry.valid = valid
        if not valid:
            with self._lock:
                self.stale += 1
            _INFLIGHT_TOTAL.inc(1, ("stale",))
        self._remove(key, entry)
        entry.event.set()

    def abort(self, key: tuple, entry: _Entry) -> None:
        """Owner failure: wake joiners empty-handed (they recompute on
        their own ladder — inheriting the owner's exception would couple
        unrelated queries' failure domains)."""
        _INFLIGHT_TOTAL.inc(1, ("fail",))
        self._remove(key, entry)
        entry.event.set()

    def join(self, entry: _Entry):
        """Block until the owner publishes; returns the validated value or
        ``None`` (stale / failed / timed out — recompute). Only callers
        holding NO unpublished claims of their own may block here (the
        serial executor's claim→compute→publish loop) — a claim-holding
        blocker could stall another executor's join on ITS claim."""
        if not entry.event.wait(self.join_timeout_s):
            return None
        return entry.value if entry.valid else None

    def poll(self, entry: _Entry):
        """Non-blocking join: the already-published validated value, or
        ``None`` (still computing / stale / failed — compute it yourself).
        The fused executor's form: it claims a whole merged group before
        publishing any of it, so a BLOCKING join there could mutually
        stall two windows claiming shared nodes in opposite orders."""
        if not entry.event.is_set():
            return None
        return entry.value if entry.valid else None

    def _remove(self, key: tuple, entry: _Entry) -> None:
        with self._lock:
            if self._pending.get(key) is entry:
                del self._pending[key]

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats(self) -> dict:
        with self._lock:
            return {
                "leads": self.leads,
                "joins": self.joins,
                "stale": self.stale,
                "pending": len(self._pending),
            }

    def clear(self) -> None:
        """Tests only: wake anything parked and drop all entries."""
        with self._lock:
            entries = list(self._pending.values())
            self._pending.clear()
        for e in entries:
            e.event.set()


# The process-wide table: every executor (serial and fused) dedups
# through this one instance, which is what makes the dedup CROSS-query.
TABLE = InflightTable()
