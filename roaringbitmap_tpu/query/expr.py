"""Lazy query expression DAG over RoaringBitmap leaves.

The reference's ``FastAggregation`` picks one algorithm per *call*; richer
boolean queries ("beyond unions and intersections", PAPERS.md) want the whole
expression visible before anything executes. Nodes here are **lazy** —
building ``(a & b) - c | Q.threshold(2, x, y, z)`` allocates a few interned
objects and touches no container — and **hash-consed**: constructing the same
(op, children) twice returns the same node object, so repeated subtrees
share one node and common-subexpression elimination is structural, not a
planner search.

Node kinds::

    leaf        one RoaringBitmap (Q.leaf)
    and/or/xor  n-ary associative algebra
    andnot      minuend \\ (sub_1 | sub_2 | ...)      (n-ary difference)
    not         universe \\ child  (explicit universe expression)
    threshold   values present in >= k of the children (multiset counting)

Identity semantics: leaves intern on the *bitmap object* (``Q.leaf(bm)``
twice is one node; two equal-content bitmaps are two leaves). Equality of
nodes is object identity — structural equality is what hash-consing already
guarantees. Leaf *contents* are pinned at execution time instead, via
``RoaringBitmap.fingerprint()`` in the result-cache key (cache.py).
"""

from __future__ import annotations

import itertools
import threading
import weakref
from typing import Iterable, Optional, Tuple, Union

from ..models.roaring import RoaringBitmap

_UID = itertools.count(1)
# op, k, child uids (+ bitmap id for leaves) -> node; weak values so dropping
# every external reference to an expression frees its whole subtree
_INTERN_LOCK = threading.Lock()
_INTERN: "weakref.WeakValueDictionary[tuple, Expr]" = weakref.WeakValueDictionary()  # guarded-by: _INTERN_LOCK

ExprLike = Union["Expr", RoaringBitmap]


def _intern(key: tuple, build) -> "Expr":
    with _INTERN_LOCK:
        node = _INTERN.get(key)
        if node is None:
            node = build()
            _INTERN[key] = node
        return node


class Expr:
    """One interned DAG node. Construct via :class:`Q` or the operators;
    the constructor itself is internal (it does not intern)."""

    __slots__ = ("op", "children", "k", "uid", "_leaves", "__weakref__")

    def __init__(self, op: str, children: Tuple["Expr", ...], k: Optional[int] = None):
        self.op = op
        self.children = children
        self.k = k
        self.uid = next(_UID)
        self._leaves: Optional[Tuple["Leaf", ...]] = None

    # hash-consing makes structural equality == identity; keep the default
    # object __eq__/__hash__ (Leaf holds a RoaringBitmap, whose own __eq__
    # must not leak into node identity)

    @property
    def leaves(self) -> Tuple["Leaf", ...]:
        """Distinct leaf nodes of this subtree, first-visit DFS order
        (computed once; the DAG is immutable)."""
        if self._leaves is None:
            seen = set()
            stack = [self]
            order = []
            while stack:
                n = stack.pop()
                if n.uid in seen:
                    continue
                seen.add(n.uid)
                if n.op == "leaf":
                    order.append(n)
                else:
                    # push in reverse so DFS visits children left-to-right
                    for c in reversed(n.children):
                        stack.append(c)
            self._leaves = tuple(order)
        return self._leaves

    # ---- operator overloading (the ergonomic construction surface) -------
    def __and__(self, other: ExprLike) -> "Expr":
        return Q.and_(self, other)

    def __rand__(self, other: ExprLike) -> "Expr":
        return Q.and_(other, self)

    def __or__(self, other: ExprLike) -> "Expr":
        return Q.or_(self, other)

    def __ror__(self, other: ExprLike) -> "Expr":
        return Q.or_(other, self)

    def __xor__(self, other: ExprLike) -> "Expr":
        return Q.xor(self, other)

    def __rxor__(self, other: ExprLike) -> "Expr":
        return Q.xor(other, self)

    def __sub__(self, other: ExprLike) -> "Expr":
        return Q.andnot(self, other)

    def __rsub__(self, other: ExprLike) -> "Expr":
        return Q.andnot(other, self)

    def not_(self, universe: ExprLike) -> "Expr":
        """Complement against an explicit universe: ``universe \\ self``."""
        return Q.not_(self, universe)

    def __repr__(self) -> str:
        if self.op == "leaf":
            return f"Leaf#{self.uid}"
        head = f"{self.op}" + (f"[k={self.k}]" if self.k is not None else "")
        return f"{head}({', '.join(repr(c) for c in self.children)})"


class Leaf(Expr):
    __slots__ = ("bitmap",)

    def __init__(self, bitmap: RoaringBitmap):
        super().__init__("leaf", ())
        self.bitmap = bitmap

    def fingerprint(self) -> tuple:
        """The leaf bitmap's mutation token (models/roaring.py); falls back
        to object identity for foreign read-only bitmap types."""
        fp = getattr(self.bitmap, "fingerprint", None)
        if fp is None:
            return ("static", id(self.bitmap))
        return fp()


def as_expr(x: ExprLike) -> Expr:
    """Coerce operands: Expr passes through, bitmaps become (interned) leaves."""
    if isinstance(x, Expr):
        return x
    if hasattr(x, "high_low_container"):
        return Q.leaf(x)
    raise TypeError(f"expected Expr or RoaringBitmap, got {type(x).__name__}")


class Q:
    """Construction API: ``Q.leaf(bm)``, ``Q.and_/or_/xor(*xs)``,
    ``Q.andnot(first, *rest)``, ``Q.not_(x, universe)``,
    ``Q.threshold(k, *xs)`` — every constructor interns."""

    @staticmethod
    def leaf(bitmap: RoaringBitmap) -> Leaf:
        if not hasattr(bitmap, "high_low_container"):
            raise TypeError(f"Q.leaf expects a bitmap, got {type(bitmap).__name__}")
        # the node holds a strong reference to the bitmap, so id() cannot be
        # recycled while the interned entry is alive
        return _intern(("leaf", id(bitmap)), lambda: Leaf(bitmap))

    @staticmethod
    def empty() -> Leaf:
        """The canonical empty leaf (constant-folding target)."""
        return Q.leaf(_EMPTY_BITMAP)

    @staticmethod
    def _nary(op: str, xs: Iterable[ExprLike], k: Optional[int] = None) -> Expr:
        children = tuple(as_expr(x) for x in xs)
        if not children:
            raise ValueError(f"{op} needs at least one operand")
        if len(children) == 1 and k is None:
            return children[0]
        key = (op, k, tuple(c.uid for c in children))
        return _intern(key, lambda: Expr(op, children, k))

    @staticmethod
    def and_(*xs: ExprLike) -> Expr:
        return Q._nary("and", xs)

    @staticmethod
    def or_(*xs: ExprLike) -> Expr:
        return Q._nary("or", xs)

    @staticmethod
    def xor(*xs: ExprLike) -> Expr:
        return Q._nary("xor", xs)

    @staticmethod
    def andnot(first: ExprLike, *rest: ExprLike) -> Expr:
        """n-ary difference: ``first \\ (rest_1 | rest_2 | ...)``."""
        children = (as_expr(first),) + tuple(as_expr(x) for x in rest)
        if len(children) == 1:
            return children[0]
        key = ("andnot", None, tuple(c.uid for c in children))
        return _intern(key, lambda: Expr("andnot", children))

    @staticmethod
    def not_(x: ExprLike, universe: ExprLike) -> Expr:
        """``universe \\ x`` — complement against an explicit universe
        expression (a 32-bit universe is never materialized implicitly)."""
        cx, cu = as_expr(x), as_expr(universe)
        key = ("not", None, (cx.uid, cu.uid))
        return _intern(key, lambda: Expr("not", (cx, cu)))

    @staticmethod
    def threshold(k: int, *xs: ExprLike) -> Expr:
        """Values present in at least ``k`` of the operands (a multiset:
        a repeated child counts with multiplicity)."""
        k = int(k)
        if k < 1:
            raise ValueError(f"threshold k must be >= 1, got {k}")
        children = tuple(as_expr(x) for x in xs)
        if not children:
            raise ValueError("threshold needs at least one operand")
        key = ("threshold", k, tuple(c.uid for c in children))
        return _intern(key, lambda: Expr("threshold", children, k))


_EMPTY_BITMAP = RoaringBitmap()


def evaluate_naive(expr: Expr) -> RoaringBitmap:
    """Reference evaluator: plain recursive set algebra with pairwise folds,
    no planner, no cache, no device. The differential oracle for the fuzz
    invariant (fuzz.random_expression) and the benchmark baseline."""
    import numpy as np

    memo: dict = {}

    def ev(n: Expr) -> RoaringBitmap:
        got = memo.get(n.uid)
        if got is not None:
            return got
        if n.op == "leaf":
            out = n.bitmap
        elif n.op == "and":
            out = ev(n.children[0]).clone()
            for c in n.children[1:]:
                out.iand(ev(c))
        elif n.op == "or":
            out = ev(n.children[0]).clone()
            for c in n.children[1:]:
                out.ior(ev(c))
        elif n.op == "xor":
            out = ev(n.children[0]).clone()
            for c in n.children[1:]:
                out.ixor(ev(c))
        elif n.op == "andnot":
            out = ev(n.children[0]).clone()
            for c in n.children[1:]:
                out.iandnot(ev(c))
        elif n.op == "not":
            out = RoaringBitmap.andnot(ev(n.children[1]), ev(n.children[0]))
        elif n.op == "threshold":
            arrs = [ev(c).to_array() for c in n.children]
            vals = np.concatenate(arrs) if arrs else np.empty(0, np.uint32)
            uniq, counts = np.unique(vals, return_counts=True)
            out = RoaringBitmap(uniq[counts >= n.k])
        else:  # pragma: no cover - unreachable
            raise ValueError(f"unknown op {n.op}")
        memo[n.uid] = out
        return out

    out = ev(expr)
    # a leaf root (including single-operand constructors that collapse to
    # their child, and Q.empty()'s shared sentinel) would hand out the live
    # internal bitmap — clone so callers can mutate the result freely, the
    # same contract execute() gives
    return out.clone() if expr.op == "leaf" else out
