"""Zero-copy read path: the buffer/memory-map package analogue.

The reference's ``buffer`` package re-implements every container over
``java.nio`` buffers so serialized bitmaps can be queried without
deserialization (ImmutableRoaringBitmap: "only metadata in RAM",
README.md:244-247; ImmutableRoaringArray.java:43-53 parses the cookie and
computes offsets, containers are buffer slices).

Python/numpy collapses that entire 17k-LoC parallel hierarchy:
``np.frombuffer`` views over ``bytes``/``mmap`` ARE the Mappeable
containers — same dtype math as the heap containers, zero copy, no twin
classes. This module parses only the header (keys, cardinalities, offsets)
eagerly; container payloads stay views into the source buffer and are
wrapped lazily on access. This is also the host->device donation path: the
packed payload of a bitmap container can be shipped to the TPU directly
from the mapped file.
"""

from __future__ import annotations

import mmap as _mmap
import struct
from bisect import bisect_left
from typing import Iterator, List, Optional, Union

import numpy as np

from ..serialization import (
    InvalidRoaringFormat,
    NO_OFFSET_THRESHOLD,
    SERIAL_COOKIE,
    SERIAL_COOKIE_NO_RUNCONTAINER,
)
from ..utils import bits
from .container import ARRAY_MAX_SIZE, ArrayContainer, BitmapContainer, Container, RunContainer
from .roaring import RoaringBitmap

Source = Union[bytes, bytearray, memoryview, _mmap.mmap, np.ndarray]


class ImmutableRoaringArray:
    """PointableRoaringArray over a mapped bitmap
    (buffer/ImmutableRoaringArray.java:43, PointableRoaringArray.java:15):
    the key index lives in the parsed header; containers are materialized
    lazily as zero-copy buffer views (memoized) so the whole pairwise and
    N-way algebra runs directly on the serialized form.
    """

    __slots__ = ("_bm", "keys", "_cache", "containers")

    def __init__(self, bm: "ImmutableRoaringBitmap"):
        self._bm = bm
        self.keys = bm._keys_list
        self._cache: dict = {}
        self.containers = _LazyContainers(self)

    @property
    def size(self) -> int:
        return self._bm._size

    def get_index(self, key: int) -> int:
        i = bisect_left(self.keys, key)
        if i < self._bm._size and self.keys[i] == key:
            return i
        return -i - 1

    def get_key_at_index(self, i: int) -> int:
        return self.keys[i]

    def get_container_at_index(self, i: int) -> Container:
        c = self._cache.get(i)
        if c is None:
            c = self._bm._build_container(i)
            self._cache[i] = c
        return c

    def advance_until(self, key: int, pos: int) -> int:
        """Exponential + binary search (ImmutableRoaringArray advanceUntil,
        PointableRoaringArray.java:25)."""
        return bisect_left(self.keys, key, pos + 1)

    def get_container(self, key: int) -> Optional[Container]:
        i = self.get_index(key)
        return self.get_container_at_index(i) if i >= 0 else None

    def items(self):
        return [(self.keys[i], self.get_container_at_index(i)) for i in range(self.size)]


class _LazyContainers:
    """Sequence view over an ImmutableRoaringArray's containers."""

    __slots__ = ("_arr",)

    def __init__(self, arr: ImmutableRoaringArray):
        self._arr = arr

    def __len__(self):
        return self._arr.size

    def __getitem__(self, i):
        return self._arr.get_container_at_index(i)

    def __iter__(self):
        for i in range(self._arr.size):
            yield self._arr.get_container_at_index(i)


class ImmutableRoaringBitmap:
    """Read-only bitmap over a serialized buffer (buffer/ImmutableRoaringBitmap).

    Constructor cost is O(#containers) header parsing; container payloads are
    zero-copy numpy views into the source buffer.
    """

    __slots__ = ("_buf", "_keys", "_keys_list", "_cards", "_types", "_offsets", "_size", "_hlc", "_ro", "_cum")

    ARRAY, BITMAP, RUN = 0, 1, 2

    # Read-only facade methods borrowed from RoaringBitmap via __getattr__:
    # they run zero-copy over the mapped containers (the high_low_container
    # duck-type), covering the reference ImmutableRoaringBitmap query
    # surface without a second 2k-line twin class.
    _DELEGATED_READS = frozenset(
        {
            # identity token for the result/pack caches: the mapped array
            # never mutates, so the ("static", id) form is stable for the
            # life of this object (the facade shares one high_low_container)
            "fingerprint",
            "rank_long",
            "next_value",
            "previous_value",
            "next_absent_value",
            "previous_absent_value",
            "first_signed",
            "last_signed",
            "cardinality_exceeds",
            "contains_range",
            "intersects_range",
            "range_cardinality",
            "limit",
            "select_range",
            "has_run_compression",
            "is_hamming_similar",
            "contains_bitmap",
            "get_int_iterator",
            "get_reverse_int_iterator",
            "get_int_rank_iterator",
            "get_batch_iterator",
            "batch_iterator",
            "get_signed_int_iterator",
            "for_each",
            "for_each_in_range",
            "for_all_in_range",
            "get_container_pointer",
            "trim",
        }
    )

    def __init__(self, source: Source, offset: int = 0):
        if isinstance(source, np.ndarray):
            # contiguous arrays map zero-copy (ISSUE 17: tobytes() copied
            # the whole buffer, defeating the mapped design for ndarray
            # sources — e.g. a durable artifact's frombuffer slice)
            source = (
                source.data if source.flags["C_CONTIGUOUS"] else source.tobytes()
            )
        buf = memoryview(source).cast("B")[offset:]
        self._buf = buf
        pos = 0
        if len(buf) < 4:
            raise InvalidRoaringFormat("truncated input")
        (cookie,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        if (cookie & 0xFFFF) == SERIAL_COOKIE:
            size = (cookie >> 16) + 1
            marker_len = (size + 7) // 8
            if pos + marker_len > len(buf):
                raise InvalidRoaringFormat("truncated run marker")
            run_marker = bytes(buf[pos : pos + marker_len])
            pos += marker_len
            has_run = True
        elif cookie == SERIAL_COOKIE_NO_RUNCONTAINER:
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated size")
            (size,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            has_run = False
            run_marker = b""
        else:
            raise InvalidRoaringFormat(f"invalid cookie {cookie}")
        if size > 1 << 16 or pos + 4 * size > len(buf):
            raise InvalidRoaringFormat("implausible container count")
        desc = np.frombuffer(buf, dtype="<u2", count=2 * size, offset=pos)
        pos += 4 * size
        self._keys = desc[0::2].astype(np.int64)
        # Python-list twin for scalar probes: bisect on a list is ~7x
        # cheaper than a scalar np.searchsorted through the ufunc wrappers,
        # and the metadata-only memory cost is the mapped design's budget
        self._keys_list = self._keys.tolist()
        self._cards = desc[1::2].astype(np.int64) + 1
        if size and np.any(np.diff(self._keys) <= 0):
            raise InvalidRoaringFormat("container keys not strictly increasing")

        types = np.empty(size, dtype=np.int8)
        for i in range(size):
            if has_run and run_marker[i // 8] & (1 << (i % 8)):
                types[i] = self.RUN
            elif self._cards[i] > ARRAY_MAX_SIZE:
                types[i] = self.BITMAP
            else:
                types[i] = self.ARRAY
        self._types = types

        if (not has_run) or size >= NO_OFFSET_THRESHOLD:
            if pos + 4 * size > len(buf):
                raise InvalidRoaringFormat("truncated offset header")
            self._offsets = np.frombuffer(
                buf, dtype="<u4", count=size, offset=pos
            ).astype(np.int64)
            pos += 4 * size
        else:
            # compute offsets sequentially (small: < NO_OFFSET_THRESHOLD)
            offsets = np.empty(size, dtype=np.int64)
            p = pos
            for i in range(size):
                offsets[i] = p
                p += self._payload_len(i, p)
            self._offsets = offsets
        self._size = size
        self._hlc = None
        self._cum = None
        # validate payload extents
        for i in range(size):
            end = self._offsets[i] + self._payload_len(i, int(self._offsets[i]))
            if end > len(buf):
                raise InvalidRoaringFormat("container payload out of bounds")

    def _payload_len(self, i: int, at: int) -> int:
        t = self._types[i]
        if t == self.BITMAP:
            return 8192
        if t == self.ARRAY:
            return 2 * int(self._cards[i])
        if at + 2 > len(self._buf):
            raise InvalidRoaringFormat("truncated run container")
        (n_runs,) = struct.unpack_from("<H", self._buf, at)
        return 2 + 4 * n_runs

    # ------------------------------------------------------------------
    def _container(self, i: int) -> Container:
        """Zero-copy container view (the Mappeable analogue), memoized via
        the high_low_container cache — rebuilding the numpy views per call
        cost ~4x on point probes."""
        return self.high_low_container.get_container_at_index(i)

    def _build_container(self, i: int) -> Container:
        """Materialize a fresh zero-copy container view (cache fill path).

        All three payload kinds stay views into the source buffer — bitmap
        words, array values, AND run (start, length) slices (the strided
        pairs[0::2]/[1::2] views below): the buffer-view contract of
        MappeableRunContainer.java, whose run algebra operates off the
        buffer. The run-space interval kernels (container.py
        _interval_binary, _run_contains_many, run-space rank/select/next)
        consume these views directly, so a mapped run-heavy bitmap answers
        and/contains/rank without materializing words or copying payloads
        (pinned by tests/test_buffer.py::test_mapped_run_views_zero_copy).
        Only the one-time hostile-payload validation reads the pages."""
        off = int(self._offsets[i])
        t = self._types[i]
        if t == self.BITMAP:
            words = np.frombuffer(self._buf, dtype="<u8", count=1024, offset=off)
            return BitmapContainer(words, int(self._cards[i]))
        if t == self.ARRAY:
            values = np.frombuffer(
                self._buf, dtype="<u2", count=int(self._cards[i]), offset=off
            )
            return ArrayContainer(values)
        (n_runs,) = struct.unpack_from("<H", self._buf, off)
        pairs = np.frombuffer(self._buf, dtype="<u2", count=2 * n_runs, offset=off + 2)
        starts, lengths = pairs[0::2], pairs[1::2]
        # same hostile-payload checks as the heap deserialize path
        # (serialization.py): sorted disjoint runs inside the 2^16 universe
        s64 = starts.astype(np.int64)
        ends = s64 + lengths.astype(np.int64)
        if n_runs and (np.any(s64[1:] <= ends[:-1]) or np.any(ends > 0xFFFF)):
            raise InvalidRoaringFormat("invalid run container")
        return RunContainer(starts, lengths)

    def _key_index(self, key: int) -> int:
        keys = self._keys_list
        i = bisect_left(keys, key)
        return i if i < self._size and keys[i] == key else -1

    # ------------------------------------------------------------------
    # read API (ImmutableBitmapDataProvider surface)
    # ------------------------------------------------------------------
    @property
    def high_low_container(self) -> ImmutableRoaringArray:
        """Zero-copy PointableRoaringArray view — makes a mapped bitmap a
        first-class operand of every pairwise op and aggregation engine."""
        if self._hlc is None:
            self._hlc = ImmutableRoaringArray(self)
        return self._hlc

    def clone(self) -> RoaringBitmap:
        """Deep copy; the writable result matches the engines' contract that
        ``clone()`` of an operand may be mutated."""
        return self.to_mutable()

    def get_size_in_bytes(self) -> int:
        if not self._size:
            return 8
        return int(self._offsets[-1]) + self._payload_len(
            self._size - 1, int(self._offsets[-1])
        )

    def serialized_size_in_bytes(self) -> int:
        return self.get_size_in_bytes()

    # -- mixed-operand pairwise algebra (buffer/ImmutableRoaringBitmap
    #    statics; operands may be heap RoaringBitmap or mapped) ----------
    @staticmethod
    def and_(x1, x2) -> RoaringBitmap:
        return RoaringBitmap.and_(x1, x2)

    @staticmethod
    def or_(x1, x2) -> RoaringBitmap:
        return RoaringBitmap.or_(x1, x2)

    @staticmethod
    def xor(x1, x2) -> RoaringBitmap:
        return RoaringBitmap.xor(x1, x2)

    @staticmethod
    def andnot(x1, x2) -> RoaringBitmap:
        return RoaringBitmap.andnot(x1, x2)

    @staticmethod
    def and_cardinality(x1, x2) -> int:
        return RoaringBitmap.and_cardinality(x1, x2)

    @staticmethod
    def or_cardinality(x1, x2) -> int:
        return RoaringBitmap.or_cardinality(x1, x2)

    @staticmethod
    def intersects(x1, x2) -> bool:
        return RoaringBitmap.intersects(x1, x2)

    def get_cardinality(self) -> int:
        return int(self._cards.sum())

    def is_empty(self) -> bool:
        return self._size == 0

    def get_container_count(self) -> int:
        return self._size

    def contains(self, x: int) -> bool:
        x = int(x)
        if not 0 <= x < 1 << 32:
            return False
        # frame-flat like the heap facade: scalar probes are the mapped
        # simplebenchmark contains row
        keys = self._keys_list
        key = x >> 16
        i = bisect_left(keys, key)
        if i == self._size or keys[i] != key:
            return False
        return self._container(i).contains(x & 0xFFFF)

    def rank(self, x: int) -> int:
        from ..utils.order_stats import bucketed_rank

        x = int(x)
        hb, lb = x >> 16, x & 0xFFFF
        return bucketed_rank(
            self._keys_list,
            self._cum_cards(),
            hb,
            lambda i: self._container(i).rank(lb),
        )

    def select(self, j: int) -> int:
        from ..utils.order_stats import bucketed_select

        return bucketed_select(
            self._keys_list,
            self._cum_cards(),
            j,
            lambda i, lj: (int(self._keys[i]) << 16) | self._container(i).select(lj),
        )

    # bulk probes shared with the heap facade: ImmutableRoaringArray
    # exposes the same keys/containers surface, so the vectorized
    # implementations run unchanged over the lazily mapped views
    contains_many = RoaringBitmap.contains_many
    rank_many = RoaringBitmap.rank_many
    select_many = RoaringBitmap.select_many

    def _cum_cards(self) -> np.ndarray:
        # header cardinalities, computed once — an immutable bitmap's
        # prefix never changes and costs no payload decode
        if self._cum is None:
            self._cum = np.cumsum(np.asarray(self._cards, dtype=np.int64))
        return self._cum

    def first(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        return (int(self._keys[0]) << 16) | self._container(0).first()

    def last(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        return (int(self._keys[-1]) << 16) | self._container(self._size - 1).last()

    def to_array(self) -> np.ndarray:
        parts = [
            self._container(i).to_array().astype(np.uint32)
            + np.uint32(int(self._keys[i]) << 16)
            for i in range(self._size)
        ]
        return np.concatenate(parts) if parts else np.empty(0, dtype=np.uint32)

    def __iter__(self) -> Iterator[int]:
        for i in range(self._size):
            base = int(self._keys[i]) << 16
            for v in self._container(i).to_array().tolist():
                yield base | v

    def __contains__(self, x) -> bool:
        return self.contains(x)

    def __len__(self) -> int:
        return self.get_cardinality()

    def __eq__(self, other):
        if isinstance(other, (ImmutableRoaringBitmap, RoaringBitmap)):
            return np.array_equal(self.to_array(), other.to_array())
        return NotImplemented

    def __hash__(self):
        return hash(self.to_array().tobytes())

    # ------------------------------------------------------------------
    def _readonly_facade(self) -> RoaringBitmap:
        """A RoaringBitmap whose high_low_container IS the mapped array —
        shared read-only view, no copy."""
        try:
            return self._ro
        except AttributeError:
            rb = RoaringBitmap.__new__(RoaringBitmap)
            rb.high_low_container = self.high_low_container
            self._ro = rb
            return rb

    def __getattr__(self, name):
        if name in ImmutableRoaringBitmap._DELEGATED_READS:
            return getattr(self._readonly_facade(), name)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
            + (" (immutable: mutators unavailable)" if hasattr(RoaringBitmap, name) else "")
        )

    # -- statics mirroring the reference's (results are heap bitmaps) ------
    @staticmethod
    def bitmap_of(*values: int) -> "ImmutableRoaringBitmap":
        return ImmutableRoaringBitmap(RoaringBitmap.bitmap_of(*values).serialize())

    bitmap_of_unordered = bitmap_of

    @staticmethod
    def flip(bm, start: int, end: int) -> RoaringBitmap:
        # clone() of a mapped operand is already the heap deep copy
        return RoaringBitmap.flip(bm, start, end)

    @staticmethod
    def or_not(x1, x2, range_end: int) -> RoaringBitmap:
        return RoaringBitmap.or_not(x1, x2, range_end)

    @staticmethod
    def xor_cardinality(x1, x2) -> int:
        return RoaringBitmap.xor_cardinality(x1, x2)

    @staticmethod
    def andnot_cardinality(x1, x2) -> int:
        return RoaringBitmap.andnot_cardinality(x1, x2)

    def to_roaring_bitmap(self) -> RoaringBitmap:
        """Deep copy to a heap RoaringBitmap (toRoaringBitmap)."""
        return self.to_mutable()

    def to_mutable_roaring_bitmap(self):
        """Deep copy to the buffer-world mutable twin."""
        from .buffer import MutableRoaringBitmap

        return MutableRoaringBitmap.of(self)

    def to_mutable(self) -> RoaringBitmap:
        """Deep copy into a mutable RoaringBitmap
        (ImmutableRoaringBitmap.toMutableRoaringBitmap)."""
        out = RoaringBitmap()
        for i in range(self._size):
            c = self._container(i)
            out.high_low_container.append(int(self._keys[i]), c.clone())
        return out

    def serialize(self) -> bytes:
        """The serialized form IS the backing buffer (zero cost)."""
        end = int(self._offsets[-1]) + self._payload_len(
            self._size - 1, int(self._offsets[-1])
        ) if self._size else 8
        return bytes(self._buf[:end])

    @staticmethod
    def map_file(path: str) -> "ImmutableRoaringBitmap":
        """Memory-map a serialized bitmap file (MemoryMappingExample
        analogue): the OS pages container payloads in on demand."""
        with open(path, "rb") as f:
            mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        return ImmutableRoaringBitmap(mm)

    def __reduce__(self):
        """Pickle as owned serialized bytes (an mmap/view source itself
        is not picklable)."""
        return ImmutableRoaringBitmap, (self.serialize(),)

    def __repr__(self):
        return f"ImmutableRoaringBitmap(card={self.get_cardinality()}, containers={self._size})"


