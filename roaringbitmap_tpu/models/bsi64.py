"""64-bit bit-sliced index: ``Roaring64BitmapSliceIndex``
(bsi/longlong/Roaring64BitmapSliceIndex.java:16) — 64-bit values over
64-bit column ids, backed by the ART-based ``Roaring64Bitmap``.

Same vertical layout and O'Neil compare as the 32-bit index (models/bsi.py;
RoaringBitmapSliceIndex.java:432-469), with up to 64 slices. The compare
chain runs on the CPU path of the 64-bit bitmaps (whose buckets are full
32-bit bitmaps, so wide chains still batch per bucket); the 32-bit
device-fused engine applies per high-32 bucket when indexes grow past the
dispatch threshold — 64-bit column universes shard naturally along the
bucket axis (SURVEY §5 long-context analogue).

Also carries the reference's ranking helpers: ``top_k``
(Roaring64BitmapSliceIndex.java:572 slice-descent), ``transpose`` (:596) and
``transpose_with_count`` (:603).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from ..serialization import InvalidRoaringFormat
from .bsi import Operation, min_max_verdict
from .roaring64art import Roaring64Bitmap

_MAX64 = 1 << 64


class config:
    """Device dispatch knobs for the 64-bit index (mirror of bsi.config)."""

    mode: str = "auto"  # 'auto' | 'cpu' | 'device'
    min_device_cells = 4096  # slices x key-chunks below which CPU wins
    # jax.sharding.Mesh: when set, BATCHED compare_cardinality_many
    # dispatches run sharded over the (containers, words) mesh — the same
    # physical [S, K, 2048] pack as the 32-bit twin, so they share the
    # mesh kernel; single-predicate 64-bit dispatches stay unsharded
    mesh = None


class Roaring64BitmapSliceIndex:
    """64-bit BSI (bsi/longlong/Roaring64BitmapSliceIndex.java:16)."""

    def __init__(self, min_value: int = 0, max_value: int = 0):
        if min_value < 0 or max_value < 0:
            raise ValueError("BSI values must be non-negative")
        self.min_value = int(min_value)
        self.max_value = int(max_value)
        self.ebm = Roaring64Bitmap()
        self.slices: List[Roaring64Bitmap] = [
            Roaring64Bitmap() for _ in range(max(0, int(max_value)).bit_length())
        ]
        self.run_optimized = False
        # mutation counter: keys this index's resident pack in the shared
        # PACK_CACHE (the 64-bit designs have no per-array fingerprint, so
        # the entry key is (id(self), _version) with self held as a ref —
        # see _pack_dense64)
        self._version = 0

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def bit_count(self) -> int:
        return len(self.slices)

    def _grow(self, bit_depth: int) -> None:
        while len(self.slices) < bit_depth:
            self.slices.append(Roaring64Bitmap())

    def _ensure_capacity(self, lo: int, hi: int) -> None:
        if self.ebm.is_empty():
            self.min_value, self.max_value = lo, hi
            self._grow(max(1, hi.bit_length()))
        else:
            if lo < self.min_value:
                self.min_value = lo
            if hi > self.max_value:
                self.max_value = hi
                self._grow(max(1, hi.bit_length()))

    def set_value(self, column_id: int, value: int) -> None:
        """setValue (Roaring64BitmapSliceIndex.java:291)."""
        value = int(value)
        if value < 0:
            raise ValueError("BSI values must be non-negative")
        self._ensure_capacity(value, value)
        for i in range(self.bit_count()):
            if (value >> i) & 1:
                self.slices[i].add(column_id)
            else:
                self.slices[i].remove(column_id)
        self.ebm.add(column_id)
        self._version += 1

    def set_values(self, pairs) -> None:
        """Vectorized bulk load (setValues, Roaring64BitmapSliceIndex.java:341);
        accepts (columns, values) parallel arrays or an iterable of pairs,
        last-pair-wins on duplicate columns."""
        if isinstance(pairs, tuple) and len(pairs) == 2:
            cols, vals = pairs
        else:
            seq = list(pairs)
            if not seq:
                return
            cols = [p[0] for p in seq]
            vals = [p[1] for p in seq]
        cols = np.asarray(cols, dtype=np.uint64)
        vals_arr = np.asarray(vals)
        if (
            vals_arr.size
            and not np.issubdtype(vals_arr.dtype, np.unsignedinteger)
            and vals_arr.min() < 0
        ):
            raise ValueError("BSI values must be non-negative")
        vals = vals_arr.astype(np.uint64)
        if cols.size == 0:
            return
        _, last_idx = np.unique(cols[::-1], return_index=True)
        keep = np.sort(cols.size - 1 - last_idx)
        if keep.size != cols.size:
            cols, vals = cols[keep], vals[keep]
        self._ensure_capacity(int(vals.min()), int(vals.max()))
        if not self.ebm.is_empty():
            existing = Roaring64Bitmap(cols)
            overlap = Roaring64Bitmap.and_(self.ebm, existing)
            if not overlap.is_empty():
                for s in self.slices:
                    s.iandnot(overlap)
        for i in range(self.bit_count()):
            mask = (vals >> np.uint64(i)) & np.uint64(1) == 1
            if mask.any():
                self.slices[i].add_many(cols[mask])
        self.ebm.add_many(cols)
        self._version += 1

    def get_value(self, column_id: int) -> Tuple[int, bool]:
        """Single-column read; batch reads should use :meth:`get_values`
        (one vectorized membership pass per slice)."""
        if not self.ebm.contains(column_id):
            return 0, False
        value = 0
        for i, s in enumerate(self.slices):
            if s.contains(column_id):
                value |= 1 << i
        return value, True

    def get_values(self, columns) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized bulk read: ``(values, exists)`` parallel to
        ``columns`` — the 64-bit twin of the 32-bit BSI ``get_values``
        (shared core: bsi._bulk_get_values; object-dtype exact above 63
        slices, int64 otherwise)."""
        from .bsi import _bulk_get_values

        return _bulk_get_values(self, np.asarray(columns).astype(np.uint64, copy=False).ravel())

    def value_exist(self, column_id: int) -> bool:
        return self.ebm.contains(column_id)

    def get_existence_bitmap(self) -> Roaring64Bitmap:
        return self.ebm

    def get_long_cardinality(self) -> int:
        return self.ebm.get_cardinality()

    get_cardinality = get_long_cardinality

    def clone(self) -> "Roaring64BitmapSliceIndex":
        out = Roaring64BitmapSliceIndex()
        out.min_value, out.max_value = self.min_value, self.max_value
        out.ebm = self.ebm.clone()
        out.slices = [s.clone() for s in self.slices]
        out.run_optimized = self.run_optimized
        return out

    def run_optimize(self) -> None:
        self.ebm.run_optimize()
        for s in self.slices:
            s.run_optimize()
        self.run_optimized = True
        self._version += 1

    def has_run_compression(self) -> bool:
        return self.run_optimized

    # ------------------------------------------------------------------
    # combination (add :64 / merge :357)
    # ------------------------------------------------------------------
    def merge(self, other: "Roaring64BitmapSliceIndex") -> None:
        if other is None or other.ebm.is_empty():
            return
        if self.ebm.intersects(other.ebm):
            raise ValueError("merge requires disjoint column sets")
        depth = max(self.bit_count(), other.bit_count())
        self._grow(depth)
        for i in range(other.bit_count()):
            self.slices[i].ior(other.slices[i])
        self.ebm.ior(other.ebm)
        self.min_value = min(self.min_value, other.min_value)
        self.max_value = max(self.max_value, other.max_value)
        self._version += 1

    def add(self, other: "Roaring64BitmapSliceIndex") -> None:
        if other is None or other.ebm.is_empty():
            return
        self.ebm.ior(other.ebm)
        if other.bit_count() > self.bit_count():
            self._grow(other.bit_count())
        for i in range(other.bit_count()):
            self._add_digit(other.slices[i], i)
        self.min_value = self._min_value()
        self.max_value = self._max_value()
        self._version += 1

    add_digit = None  # set below

    def _add_digit(self, found_set: Roaring64Bitmap, i: int) -> None:
        carry = Roaring64Bitmap.and_(self.slices[i], found_set)
        self.slices[i].ixor(found_set)
        if not carry.is_empty():
            if i + 1 >= self.bit_count():
                self._grow(self.bit_count() + 1)
            self._add_digit(carry, i + 1)

    def _min_value(self) -> int:
        if self.ebm.is_empty():
            return 0
        ids = self.ebm
        for i in range(self.bit_count() - 1, -1, -1):
            tmp = Roaring64Bitmap.andnot(ids, self.slices[i])
            if not tmp.is_empty():
                ids = tmp
        return self.get_value(ids.first())[0]

    def _max_value(self) -> int:
        if self.ebm.is_empty():
            return 0
        ids = self.ebm
        for i in range(self.bit_count() - 1, -1, -1):
            tmp = Roaring64Bitmap.and_(ids, self.slices[i])
            if not tmp.is_empty():
                ids = tmp
        return self.get_value(ids.first())[0]

    # ------------------------------------------------------------------
    # queries (compare :460, o'neil :398-458)
    # ------------------------------------------------------------------
    def compare(
        self,
        operation: Operation,
        start_or_value: int,
        end: int = 0,
        found_set: Optional[Roaring64Bitmap] = None,
        mode: Optional[str] = None,
    ) -> Roaring64Bitmap:
        res = self._compare_using_min_max(operation, start_or_value, end, found_set)
        if res is not None:
            return res
        if operation == Operation.RANGE:
            end = min(int(end), (1 << self.bit_count()) - 1)
            if self._use_device(mode):
                return self._o_neil_device(operation, start_or_value, found_set, end=end)
            left = self._o_neil(Operation.GE, start_or_value, found_set)
            right = self._o_neil(Operation.LE, end, found_set)
            return Roaring64Bitmap.and_(left, right)
        if self._use_device(mode):
            return self._o_neil_device(operation, start_or_value, found_set)
        return self._o_neil(operation, start_or_value, found_set)

    def compare_cardinality(
        self,
        operation: Operation,
        start_or_value: int,
        end: int = 0,
        found_set: Optional[Roaring64Bitmap] = None,
        mode: Optional[str] = None,
    ) -> int:
        """Count-only compare (the 32-bit compare_cardinality twin): the
        min/max verdicts resolve without materializing, and the device path
        fetches only per-chunk popcounts — no result words, no container
        rebuild."""
        verdict = min_max_verdict(
            operation, start_or_value, end, self.min_value, self.max_value
        )
        if verdict == "empty":
            return 0
        if verdict == "fixed":
            return (self.ebm if found_set is None else found_set).get_cardinality()
        if verdict == "all":
            if found_set is None:
                return self.ebm.get_cardinality()
            return Roaring64Bitmap.and_cardinality(self.ebm, found_set)
        if self._use_device(mode):
            if operation == Operation.RANGE:
                end = min(int(end), (1 << self.bit_count()) - 1)
            keys, _out, cards, = self._o_neil_device_walk(
                operation, start_or_value, found_set, end
            )
            total = int(np.asarray(cards).astype(np.int64).sum())
            if operation == Operation.NEQ and found_set is not None:
                total += self._neq_outside_ebm(found_set, keys)
            return total
        return self.compare(
            operation, start_or_value, end, found_set, mode="cpu"
        ).get_cardinality()

    def compare_cardinality_many(
        self,
        operation: Operation,
        values,
        ends=None,
        found_set: Optional[Roaring64Bitmap] = None,
        mode: Optional[str] = None,
    ) -> np.ndarray:
        """Batched count-only compare over [Q] 64-bit thresholds in one
        device dispatch (the 32-bit compare_cardinality_many twin: the
        vmapped O'Neil walk shares one HBM pass over the [S, K, 2048]
        high-48-chunk pack across all Q predicates)."""
        from .bsi import _counts_many

        return _counts_many(
            self,
            operation,
            values,
            ends,
            found_set,
            mode,
            batched_ok=self._use_device(mode),
            pack_fixed=lambda: self._pack_with_fixed(found_set),
            neq_remainder=lambda keys: self._neq_outside_ebm(found_set, keys),
            mesh=config.mesh,
        )

    def _pack_with_fixed(self, found_set: Optional[Roaring64Bitmap]):
        """(keys, ebm_w, slices_w, fixed_w) over high-48 chunk keys — shared
        pack+found-set marshal (32-bit twin: bsi._pack_with_fixed)."""
        import jax.numpy as jnp

        keys, ebm_w, slices_w = self._pack_dense64()
        if found_set is None:
            fixed_w = ebm_w
        else:
            fixed_w = jnp.asarray(
                self._found_words(keys, (len(keys), ebm_w.shape[1]), found_set)
            )
        return keys, ebm_w, slices_w, fixed_w

    @staticmethod
    def _neq_outside_ebm(found_set: Roaring64Bitmap, keys) -> int:
        """Clone-free count of found-set columns in chunks outside the
        packed ebm keys (NEQ qualifies them wholesale)."""
        kset = set(keys)
        return sum(c.cardinality for k, c in found_set._kv() if k not in kset)

    def _use_device(self, mode: Optional[str]) -> bool:
        mode = mode or config.mode
        if mode == "cpu":
            return False
        if mode == "device":
            return True
        # auto: same guard as the 32-bit engine (bsi._use_device) — no jax
        # or a CPU-only backend means the device marshal never pays off
        try:
            import jax

            backend = jax.default_backend()
        except (ImportError, RuntimeError):  # no jax / no usable backend
            return False
        cells = self.bit_count() * self._key_count()
        return backend != "cpu" and cells >= config.min_device_cells

    def _key_count(self) -> int:
        # O(1): the Containers store tracks its live count
        return len(self.ebm._containers)

    def _pack_dense64(self):
        """[S, K, 2048] slice tensor + [K, 2048] ebm over the ebm's high-48
        chunk keys — the 64-bit twin of bsi._pack_dense; the K axis IS the
        long-context scaling axis (SURVEY §5: 64-bit universes shard along
        the key axis). Resident in the shared PACK_CACHE (ISSUE 4) so
        64-bit BSI tensors share the same byte budget, LRU, and close()
        as everything else. The 64-bit container stores have no
        per-array fingerprint, so the key is ``(id(self), _version)``
        with ``self`` held as an entry ref — the id cannot be recycled
        by a different index while the entry is resident, and every
        mutation re-keys it."""
        from ..parallel import store

        key = ("bsi64", id(self), self._version)

        def build():
            import jax.numpy as jnp

            from ..ops import device as dev
            from ..parallel.store import container_words_u32

            kv = list(self.ebm._kv())
            keys = [k for k, _ in kv]
            kidx = {k: i for i, k in enumerate(keys)}
            K, S = len(keys), self.bit_count()
            ebm_w = np.zeros((K, dev.DEVICE_WORDS), dtype=np.uint32)
            for k, c in kv:
                ebm_w[kidx[k]] = container_words_u32(c)
            slices_w = np.zeros((S, K, dev.DEVICE_WORDS), dtype=np.uint32)
            for i, sl in enumerate(self.slices):
                for k, c in sl._kv():
                    ki = kidx.get(k)
                    if ki is not None:  # slice columns are always ebm columns
                        slices_w[i, ki] = container_words_u32(c)
            value = (keys, jnp.asarray(ebm_w), jnp.asarray(slices_w))
            return value, int(ebm_w.nbytes) + int(slices_w.nbytes)

        return store.PACK_CACHE.get_or_build(key, build, refs=(self,))

    def _found_words(self, keys, shape, found_set) -> np.ndarray:
        from ..parallel.store import container_words_u32

        kidx = {k: i for i, k in enumerate(keys)}
        out = np.zeros(shape, dtype=np.uint32)
        for k, c in found_set._kv():
            ki = kidx.get(k)
            if ki is not None:
                out[ki] = container_words_u32(c)
        return out

    def _o_neil_device_walk(self, op, predicate, found_set, end: int = 0):
        """Fused device walk over high-48 chunk keys; returns (keys,
        out_device, cards_device) with nothing fetched — compare pulls the
        words, compare_cardinality only the popcounts (32-bit twin:
        bsi._o_neil_device_walk)."""
        import jax.numpy as jnp

        from ..ops import pallas_kernels as pk

        keys, ebm_w, slices_w, fixed_w = self._pack_with_fixed(found_set)
        S = self.bit_count()
        bits_vec = np.array(
            [(predicate >> i) & 1 for i in range(S - 1, -1, -1)], dtype=bool
        )
        if op == Operation.RANGE:
            bits_hi = np.array(
                [(end >> i) & 1 for i in range(S - 1, -1, -1)], dtype=bool
            )
            bits_vec = np.stack([bits_vec, bits_hi])
        out, cards = pk.best_oneil_compare(
            slices_w, jnp.asarray(bits_vec), ebm_w, fixed_w, op.value
        )
        return keys, out, cards

    def _o_neil_device(
        self, op, predicate, found_set, end: int = 0
    ) -> Roaring64Bitmap:
        """The fused device O'Neil over high-48 chunk keys (the 32-bit
        engine's kernels, ops/pallas_kernels.best_oneil_compare, apply
        unchanged — the key width only changes the host-side directory)."""
        from ..models.container import best_container_of_words

        keys, out, cards = self._o_neil_device_walk(op, predicate, found_set, end)
        out_np = np.ascontiguousarray(np.asarray(out)).view(np.uint64)
        cards_np = np.asarray(cards)
        result = Roaring64Bitmap()
        for ki, key in enumerate(keys):
            if int(cards_np[ki]):
                result._put(key, best_container_of_words(out_np[ki].copy()))
        if op == Operation.NEQ and found_set is not None:
            # foundSet columns in chunks outside the ebm cannot be EQ, so
            # they all qualify (same Java semantics as the 32-bit engine)
            kset = set(keys)
            for k, c in found_set._kv():
                if k not in kset:
                    result._put(k, c.clone())
        return result

    def _compare_using_min_max(self, op, start_or_value, end, found_set):
        verdict = min_max_verdict(
            op, start_or_value, end, self.min_value, self.max_value
        )
        if verdict is None:
            return None
        if verdict == "empty":
            return Roaring64Bitmap()
        if verdict == "fixed":
            return self.ebm.clone() if found_set is None else found_set.clone()
        return (
            self.ebm.clone()
            if found_set is None
            else Roaring64Bitmap.and_(self.ebm, found_set)
        )

    def _o_neil(self, op, predicate, found_set) -> Roaring64Bitmap:
        fixed = self.ebm if found_set is None else found_set
        gt, lt, eq = Roaring64Bitmap(), Roaring64Bitmap(), self.ebm
        for i in range(self.bit_count() - 1, -1, -1):
            if (predicate >> i) & 1:
                lt = Roaring64Bitmap.or_(lt, Roaring64Bitmap.andnot(eq, self.slices[i]))
                eq = Roaring64Bitmap.and_(eq, self.slices[i])
            else:
                gt = Roaring64Bitmap.or_(gt, Roaring64Bitmap.and_(eq, self.slices[i]))
                eq = Roaring64Bitmap.andnot(eq, self.slices[i])
        eq = Roaring64Bitmap.and_(fixed, eq)
        if op == Operation.EQ:
            return eq
        if op == Operation.NEQ:
            return Roaring64Bitmap.andnot(fixed, eq)
        if op == Operation.GT:
            return Roaring64Bitmap.and_(gt, fixed)
        if op == Operation.LT:
            return Roaring64Bitmap.and_(lt, fixed)
        if op == Operation.LE:
            return Roaring64Bitmap.and_(Roaring64Bitmap.or_(lt, eq), fixed)
        if op == Operation.GE:
            return Roaring64Bitmap.and_(Roaring64Bitmap.or_(gt, eq), fixed)
        raise ValueError(f"unsupported operation {op}")

    def sum(self, found_set: Optional[Roaring64Bitmap] = None) -> Tuple[int, int]:
        """(sum, count) (Roaring64BitmapSliceIndex.java:559)."""
        if found_set is None or found_set.is_empty():
            return 0, 0
        count = found_set.get_cardinality()
        total = sum(
            (1 << i) * Roaring64Bitmap.and_(s, found_set).get_cardinality()
            for i, s in enumerate(self.slices)
        )
        return total, count

    def top_k(self, found_set: Optional[Roaring64Bitmap], k: int) -> Roaring64Bitmap:
        """Columns holding the k largest values — slice descent from the
        MSB (Roaring64BitmapSliceIndex.java:572)."""
        if found_set is None:
            found_set = self.ebm
        if found_set.is_empty() or k <= 0:
            return Roaring64Bitmap()
        if k >= found_set.get_cardinality():
            return found_set.clone()
        result = Roaring64Bitmap()
        candidates = found_set.clone()
        for i in range(self.bit_count() - 1, -1, -1):
            if candidates.is_empty() or k <= 0:
                break
            with_bit = Roaring64Bitmap.and_(candidates, self.slices[i])
            card = with_bit.get_cardinality()
            if card > k:
                candidates = with_bit
            else:
                result.ior(with_bit)
                candidates.iandnot(self.slices[i])
                k -= card
        if k > 0 and not candidates.is_empty():
            # fill remaining seats from the leftover (equal-valued) pool
            fill = Roaring64Bitmap()
            for idx, col in enumerate(candidates):
                if idx >= k:
                    break
                fill.add(col)
            result.ior(fill)
        return result

    def transpose(self, found_set: Optional[Roaring64Bitmap] = None) -> Roaring64Bitmap:
        """Bitmap of distinct values over the found columns
        (Roaring64BitmapSliceIndex.java:596)."""
        cols = (
            self.ebm if found_set is None else Roaring64Bitmap.and_(self.ebm, found_set)
        ).to_array()
        if cols.size == 0:
            return Roaring64Bitmap()
        from .bsi import values_for_columns

        return Roaring64Bitmap(
            np.unique(values_for_columns(cols, self.slices, dtype=np.uint64))
        )

    def transpose_with_count(
        self, found_set: Optional[Roaring64Bitmap] = None
    ) -> "Roaring64BitmapSliceIndex":
        """BSI mapping value -> multiplicity (Roaring64BitmapSliceIndex.java:603)."""
        cols = (
            self.ebm if found_set is None else Roaring64Bitmap.and_(self.ebm, found_set)
        ).to_array()
        out = Roaring64BitmapSliceIndex()
        if cols.size == 0:
            return out
        from .bsi import transpose_value_counts

        uniq, counts = transpose_value_counts(cols, self.slices, dtype=np.uint64)
        out.set_values((uniq, counts.astype(np.uint64)))
        return out

    # ------------------------------------------------------------------
    # serialization (ByteBuffer layout :234-271, little-endian):
    # int64 minValue, int64 maxValue, byte runOptimized, ebm (portable
    # 64-bit spec), int32 sliceCount, slices
    # ------------------------------------------------------------------
    def serialize(self) -> bytes:
        parts = [
            struct.pack(
                "<QQb", self.min_value, self.max_value, 1 if self.run_optimized else 0
            ),
            self.ebm.serialize(),
            struct.pack("<i", self.bit_count()),
        ]
        parts.extend(s.serialize() for s in self.slices)
        return b"".join(parts)

    def serialized_size_in_bytes(self) -> int:
        return (
            8 + 8 + 1 + 4
            + self.ebm.serialized_size_in_bytes()
            + sum(s.serialized_size_in_bytes() for s in self.slices)
        )

    def __reduce__(self):
        return Roaring64BitmapSliceIndex.deserialize, (self.serialize(),)

    @staticmethod
    def deserialize(data) -> "Roaring64BitmapSliceIndex":
        buf = memoryview(
            data if isinstance(data, (bytes, bytearray, memoryview)) else bytes(data)
        )
        if len(buf) < 17:
            raise InvalidRoaringFormat("truncated 64-bit BSI header")
        min_v, max_v, ro = struct.unpack_from("<QQb", buf, 0)
        pos = 17
        out = Roaring64BitmapSliceIndex()
        out.min_value, out.max_value = min_v, max_v
        out.run_optimized = bool(ro)
        out.ebm, n = _read_r64(buf[pos:])
        pos += n
        if pos + 4 > len(buf):
            raise InvalidRoaringFormat("truncated BSI slice count")
        (depth,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        if depth < 0 or depth > 64:
            raise InvalidRoaringFormat(f"implausible BSI depth {depth}")
        out.slices = []
        for _ in range(depth):
            s, n = _read_r64(buf[pos:])
            pos += n
            out.slices.append(s)
        return out

    def serialize_into(self, fileobj) -> int:
        """Stream overload (the reference's WritableUtils DataOutput path);
        returns bytes written."""
        data = self.serialize()
        fileobj.write(data)
        return len(data)

    @staticmethod
    def deserialize_from(fileobj) -> "Roaring64BitmapSliceIndex":
        """Stream twin: consumes exactly one 64-bit BSI (header, ebm,
        depth, slices — each member through Roaring64Bitmap's
        exact-consumption stream reader)."""
        from ..serialization import read_exact

        min_v, max_v, ro = struct.unpack("<QQb", read_exact(fileobj, 17))
        out = Roaring64BitmapSliceIndex()
        out.min_value, out.max_value = min_v, max_v
        out.run_optimized = bool(ro)
        out.ebm = Roaring64Bitmap.deserialize_from(fileobj)
        (depth,) = struct.unpack("<i", read_exact(fileobj, 4))
        if depth < 0 or depth > 64:
            raise InvalidRoaringFormat(f"implausible BSI depth {depth}")
        out.slices = [Roaring64Bitmap.deserialize_from(fileobj) for _ in range(depth)]
        return out

    def __eq__(self, other):
        if not isinstance(other, Roaring64BitmapSliceIndex):
            return NotImplemented
        return (
            self.ebm == other.ebm
            and len(self.slices) == len(other.slices)
            and all(a == b for a, b in zip(self.slices, other.slices))
        )

    def __repr__(self):
        return (
            f"Roaring64BitmapSliceIndex(cols={self.get_long_cardinality()}, "
            f"slices={self.bit_count()}, min={self.min_value}, max={self.max_value})"
        )


Roaring64BitmapSliceIndex.add_digit = Roaring64BitmapSliceIndex._add_digit

# consuming reader shared with Roaring64Bitmap.deserialize
_read_r64 = Roaring64Bitmap.read_from
