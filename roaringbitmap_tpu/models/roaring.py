"""L3' facade: the 32-bit RoaringBitmap.

API parity with the reference facade (RoaringBitmap.java:50): point ops
(add :1162, contains :1693, remove :2637), range ops (add(long,long) :1181,
flip :1893), pairwise static algebra (and/or/xor/andNot/orNot
:377/860/1071/444/1521) plus cardinality-only variants, rank/select
(:2622/2820), next/previous(+absent) value (:2838-2929), addOffset (:230),
selectRange (:3095), limit (:2457), runOptimize (:2764), contains-subset
(:2781), isHammingSimilar (:1831), rangeCardinality (:2590), iterators and
batch iteration, and the RoaringFormatSpec serialization (:3012-3051).

Values are unsigned 32-bit ints; ranges are half-open ``[start, end)`` with
``0 <= start <= end <= 2^32``.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Iterable, Iterator, List, Optional

import numpy as np

from ..utils import bits
from .container import (
    ARRAY_MAX_SIZE,
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
    container_from_values,
    container_range_of_ones,
)
from .roaring_array import RoaringArray

_MAX32 = 1 << 32


# the grouping idiom shared by the bulk-probe paths (contains_many /
# rank_many / select_many) — one home in utils.order_stats
from ..utils.order_stats import group_positions as _group_positions

# columnar pairwise engine (ISSUE 5): bound lazily because the package
# imports this module; one global probe per process, ~no per-call cost
_COLUMNAR = None


def _columnar():
    global _COLUMNAR
    if _COLUMNAR is None:
        from .. import columnar

        _COLUMNAR = columnar
    return _COLUMNAR


def hlc_fingerprint(hlc) -> tuple:
    """The canonical mutation-tracking token computed from a high-low
    container — the SINGLE source of the fingerprint scheme:
    ``RoaringBitmap.fingerprint()`` delegates here, and consumers that
    only hold an hlc (the columnar router's PACK_CACHE residency probe)
    must use this same function so their cache keys can never drift from
    what ``device.rows_for`` stores under.

    The tuple is CACHED on the container array (``_fp``, invalidated by
    every version bump — ISSUE 11 satellite): the warm pack-cache lookup
    walks 10k of these per call, and rebuilding 10k tuples per lookup was
    the delta wall's dominant stage (r12). A cached fingerprint is also
    the SAME object across calls, so the pack-cache key comparison on a
    warm hit degenerates to identity checks."""
    fp = getattr(hlc, "_fp", None)
    if fp is not None:
        return fp
    gen = getattr(hlc, "_gen", None)
    if gen is None:  # mapped/immutable container arrays never mutate
        return ("static", id(hlc))
    fp = (gen, hlc._version)
    try:
        hlc._fp = fp
    except AttributeError:  # foreign mutable hlc without the cache slot
        pass
    return fp


def _check_value(x: int) -> int:
    x = int(x)
    if not 0 <= x < _MAX32:
        raise ValueError(f"value {x} outside unsigned 32-bit range")
    return x


def _check_range(start: int, end: int):
    start, end = int(start), int(end)
    if not 0 <= start <= end <= _MAX32:
        raise ValueError(f"invalid range [{start}, {end})")
    return start, end


class RoaringBitmap:
    __slots__ = ("high_low_container",)

    def __init__(self, values: Optional[Iterable[int]] = None):
        self.high_low_container = RoaringArray()
        if values is not None:
            self.add_many(values)

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    @staticmethod
    def bitmap_of(*values: int) -> "RoaringBitmap":
        return RoaringBitmap(values)

    @staticmethod
    def bitmap_of_range(start: int, end: int) -> "RoaringBitmap":
        out = RoaringBitmap()
        out.add_range(start, end)
        return out

    # bitmapOfUnordered: add_many sorts internally, so one name serves both
    bitmap_of_unordered = bitmap_of

    def add_n(self, values, offset: int = 0, n: Optional[int] = None) -> None:
        """Add a slice of a value array (RoaringBitmap.addN(vals, offset, n))."""
        v = np.asarray(values).ravel()
        self.add_many(v[offset : None if n is None else offset + n])

    def to_mutable_roaring_bitmap(self):
        """Deep-copy into the buffer-world mutable twin
        (RoaringBitmap.toMutableRoaringBitmap)."""
        from .buffer import MutableRoaringBitmap

        return MutableRoaringBitmap.of(self)

    def clone(self) -> "RoaringBitmap":
        out = RoaringBitmap()
        out.high_low_container = self.high_low_container.clone()
        return out

    # ------------------------------------------------------------------
    # point ops
    # ------------------------------------------------------------------
    def add(self, x: int) -> None:
        """RoaringBitmap.add (RoaringBitmap.java:1162). Frame-flat like
        contains: the key probe is inlined on this point-mutation hot
        path."""
        x = int(x)
        if not 0 <= x < _MAX32:
            raise ValueError(f"value {x} outside unsigned 32-bit range")
        hb, lb = x >> 16, x & 0xFFFF
        hlc = self.high_low_container
        keys = hlc.keys
        i = bisect_left(keys, hb)
        if i < len(keys) and keys[i] == hb:
            containers = hlc.containers
            containers[i] = containers[i].add(lb)
            hlc.touch_key(hb)  # frame-flat path bypasses set_container_at_index
        else:
            hlc.insert_new_key_value_at(
                i, hb, ArrayContainer(np.array([lb], dtype=np.uint16))
            )

    def checked_add(self, x: int) -> bool:
        """Add, returning True if the bitmap changed (RoaringBitmap.java:1610)."""
        before = self.contains(x)
        if not before:
            self.add(x)
        return not before

    def add_many(self, values: Iterable[int]) -> None:
        """Bulk add via per-key grouping (the writer path is faster for huge
        sorted streams; see models/writer.py)."""
        if not isinstance(values, np.ndarray):
            values = np.fromiter(iter(values), dtype=np.int64)
        v = np.asarray(values, dtype=np.int64).ravel()
        if v.size == 0:
            return
        if v.min() < 0 or v.max() >= _MAX32:
            raise ValueError("values outside unsigned 32-bit range")
        u = v.astype(np.uint32)
        # strictly-increasing input (the common bulk shape: BSI slice masks,
        # pre-sorted ingest) skips the unique's O(n log n) sort
        v = u if bits.is_strictly_increasing(u) else np.unique(u)
        keys = (v >> 16).astype(np.int64)
        lows = (v & 0xFFFF).astype(np.uint16)
        boundaries = np.nonzero(np.diff(keys))[0] + 1
        key_starts = np.concatenate(([0], boundaries))
        key_ends = np.concatenate((boundaries, [v.size]))
        hlc = self.high_low_container
        for s, e in zip(key_starts.tolist(), key_ends.tolist()):
            key = int(keys[s])
            chunk = lows[s:e]
            i = hlc.get_index(key)
            if i >= 0:
                existing = hlc.get_container_at_index(i)
                hlc.set_container_at_index(
                    i, existing.or_(container_from_values(chunk))
                )
            else:
                hlc.insert_new_key_value_at(-i - 1, key, container_from_values(chunk))

    def remove(self, x: int) -> None:
        """RoaringBitmap.remove (RoaringBitmap.java:2637)."""
        x = _check_value(x)
        hb, lb = x >> 16, x & 0xFFFF
        hlc = self.high_low_container
        i = hlc.get_index(hb)
        if i < 0:
            return
        c = hlc.get_container_at_index(i).remove(lb)
        if c.cardinality == 0:
            hlc.remove_at_index(i)
        else:
            hlc.set_container_at_index(i, c)

    def checked_remove(self, x: int) -> bool:
        before = self.contains(x)
        if before:
            self.remove(x)
        return before

    def contains(self, x: int) -> bool:
        """RoaringBitmap.contains (RoaringBitmap.java:1693).

        Deliberately frame-flat: the key probe and container lookup are
        inlined (no _check_value/get_container hops) because this is the
        per-call latency floor the simplebenchmark contains row measures —
        each avoided Python frame is ~70 ns (Util.java:697's
        unsignedBinarySearch plays this role for the JVM)."""
        x = int(x)
        if not 0 <= x < _MAX32:
            raise ValueError(f"value {x} outside unsigned 32-bit range")
        hlc = self.high_low_container
        keys = hlc.keys
        key = x >> 16
        i = bisect_left(keys, key)
        if i == len(keys) or keys[i] != key:
            return False
        return hlc.containers[i].contains(x & 0xFFFF)

    # ------------------------------------------------------------------
    # range ops
    # ------------------------------------------------------------------
    def add_range(self, start: int, end: int) -> None:
        """Add [start, end) (RoaringBitmap.add(long,long), RoaringBitmap.java:1181)."""
        start, end = _check_range(start, end)
        if start == end:
            return
        self._apply_range(start, end, "add")

    def remove_range(self, start: int, end: int) -> None:
        """Remove [start, end) (RoaringBitmap.java:2656)."""
        start, end = _check_range(start, end)
        if start == end:
            return
        self._apply_range(start, end, "remove")

    def flip_range(self, start: int, end: int) -> None:
        """In-place flip of [start, end) (RoaringBitmap.flip, RoaringBitmap.java:1893)."""
        start, end = _check_range(start, end)
        if start == end:
            return
        self._apply_range(start, end, "flip")

    @staticmethod
    def flip(bm: "RoaringBitmap", start: int, end: int) -> "RoaringBitmap":
        out = bm.clone()
        out.flip_range(start, end)
        return out

    def _apply_range(self, start: int, end: int, mode: str) -> None:
        hb_start, hb_end = start >> 16, (end - 1) >> 16
        hlc = self.high_low_container
        for hb in range(hb_start, hb_end + 1):
            lo = start & 0xFFFF if hb == hb_start else 0
            hi = ((end - 1) & 0xFFFF) + 1 if hb == hb_end else 1 << 16
            i = hlc.get_index(hb)
            full_chunk = lo == 0 and hi == (1 << 16)
            if i >= 0:
                c = hlc.get_container_at_index(i)
                if mode == "add":
                    c = (
                        container_range_of_ones(0, 1 << 16)
                        if full_chunk
                        else c.add_range(lo, hi)
                    )
                elif mode == "remove":
                    c = c.remove_range(lo, hi)
                else:
                    c = c.flip_range(lo, hi)
                if c.cardinality == 0:
                    hlc.remove_at_index(i)
                else:
                    hlc.set_container_at_index(i, c)
            else:
                if mode == "remove":
                    continue
                # add and flip are identical on an absent container
                c = container_range_of_ones(lo, hi)
                if c.cardinality:
                    hlc.insert_new_key_value_at(-i - 1, hb, c)

    def contains_many(self, values) -> np.ndarray:
        """Vectorized membership: bool array aligned with ``values`` (the
        batch analogue of contains; what a retrieval stack calls to filter
        an ANN candidate list).

        One searchsorted against the bitmap's own key array classifies
        every probe, then each LIVE container answers its probes in one
        call — iterating the bitmap's (few) keys, not the probes' (many)
        key groups, so probes landing in absent chunks cost nothing (the
        workShyAnd pre-filter idea applied to point probes)."""
        v = np.asarray(values, dtype=np.int64).ravel()
        out = np.zeros(v.size, dtype=bool)
        if v.size == 0:
            return out
        keys = v >> 16
        hlc = self.high_low_container
        if len(hlc.keys) > v.size:
            # many-key bitmap, few probes: classifying probes against the
            # whole key array would cost more than per-group bisects
            for key, idx in _group_positions(keys):
                c = hlc.get_container(int(key))
                if c is not None:
                    out[idx] = c.contains_many((v[idx] & 0xFFFF).astype(np.uint16))
            return out
        hkeys = np.asarray(hlc.keys, dtype=np.int64)
        if hkeys.size == 0:
            return out
        pos = np.searchsorted(hkeys, keys)
        pos_c = np.minimum(pos, hkeys.size - 1)
        hit = hkeys[pos_c] == keys
        if not hit.any():
            return out
        containers = hlc.containers
        lows = (v & 0xFFFF).astype(np.uint16)
        hid = np.flatnonzero(hit)
        for ci, seg in _group_positions(pos_c[hid]):
            s = hid[seg]
            out[s] = containers[int(ci)].contains_many(lows[s])
        return out

    def rank_many(self, values) -> np.ndarray:
        """Vectorized rank: int64 array aligned with ``values``, each the
        count of set values <= v (the bulk twin of rank_long; the
        reference answers batch order statistics one rank() at a time,
        RoaringBitmap.java:2622). One container-level ``rank_many`` pass
        per distinct key chunk plus an exclusive cardinality prefix."""
        v = np.asarray(values, dtype=np.int64).ravel()
        out = np.zeros(v.size, dtype=np.int64)
        hlc = self.high_low_container
        if v.size == 0:
            return out
        if v.min() < 0 or v.max() >= _MAX32:
            raise ValueError("values outside unsigned 32-bit range")
        if hlc.size == 0:
            return out
        from ..utils.order_stats import bucketed_rank_many

        keys_arr = np.asarray(hlc.keys, dtype=np.int64)
        return bucketed_rank_many(
            keys_arr,
            self._cum_cards(),
            v >> 16,
            lambda i, pos: hlc.containers[i].rank_many(
                (v[pos] & 0xFFFF).astype(np.uint16)
            ),
        )

    def _cum_cards(self) -> np.ndarray:
        """Inclusive per-container cardinality cumsum — FastRank overrides
        with its invalidation-tracked cache."""
        return np.cumsum(
            np.array(
                [c.cardinality for c in self.high_low_container.containers],
                dtype=np.int64,
            )
        )

    def select_many(self, ranks) -> np.ndarray:
        """Vectorized select: uint32 array of the rank-th smallest values,
        aligned with ``ranks`` (bulk twin of select; a retrieval stack's
        "docIDs at ranks [r0..rk]" pagination ask). Raises IndexError when
        any rank is out of range, like the scalar."""
        from ..utils.order_stats import bucketed_select_many

        js = np.asarray(ranks, dtype=np.int64).ravel()
        if js.size == 0:  # skip the uncached cumsum for an empty page
            return np.zeros(0, dtype=np.uint32)
        hlc = self.high_low_container
        keys_arr = np.asarray(hlc.keys, dtype=np.int64)
        return bucketed_select_many(
            self._cum_cards(),
            js,
            lambda i, j: np.uint32(keys_arr[i] << 16)
            | hlc.containers[i].select_many(j).astype(np.uint32),
            dtype=np.uint32,
        )

    def contains_range(self, start: int, end: int) -> bool:
        """RoaringBitmap.contains(long,long)."""
        start, end = _check_range(start, end)
        if start == end:
            return True
        hb_start, hb_end = start >> 16, (end - 1) >> 16
        hlc = self.high_low_container
        for hb in range(hb_start, hb_end + 1):
            lo = start & 0xFFFF if hb == hb_start else 0
            hi = ((end - 1) & 0xFFFF) + 1 if hb == hb_end else 1 << 16
            i = hlc.get_index(hb)
            if i < 0 or not hlc.get_container_at_index(i).contains_range(lo, hi):
                return False
        return True

    def range_cardinality(self, start: int, end: int) -> int:
        """Number of set values in [start, end) (RoaringBitmap.java:2590)."""
        start, end = _check_range(start, end)
        if start >= end:
            return 0
        return self.rank_long(end - 1) - (self.rank_long(start - 1) if start else 0)

    def intersects_range(self, start: int, end: int) -> bool:
        start, end = _check_range(start, end)
        if start >= end:
            return False
        nv = self.next_value(start)
        return nv >= 0 and nv < end

    # ------------------------------------------------------------------
    # pairwise algebra (static, like the reference)
    # ------------------------------------------------------------------
    @staticmethod
    def and_(x1: "RoaringBitmap", x2: "RoaringBitmap", *more: "RoaringBitmap") -> "RoaringBitmap":
        """RoaringBitmap.and (RoaringBitmap.java:377): intersect keys, drop empties.

        With more than two operands this delegates to FastAggregation like
        the reference's ``and(Iterator)`` facade overload (:831-844). Above
        the columnar cutoff the whole pair executes as one batched op
        (columnar/, ISSUE 5); the per-container walk below stays the
        small-operand fast path and the differential reference."""
        if more:
            from ..parallel.aggregation import FastAggregation

            return FastAggregation.and_(x1, x2, *more)
        col = _columnar()
        tier = col.route(
            x1.high_low_container, x2.high_low_container, op="and"
        )
        # outcome scope (ISSUE 11): the verdict's measured wall joins the
        # decision it came from; per-container executions join too (the
        # refit needs live samples from every engine)
        with col.outcome(tier):
            if tier != "per-container":
                return col.pairwise("and", x1, x2, tier=tier)
            return RoaringBitmap._and_percontainer(x1, x2)

    @staticmethod
    def _and_percontainer(x1: "RoaringBitmap", x2: "RoaringBitmap") -> "RoaringBitmap":
        out = RoaringBitmap()
        a, b = x1.high_low_container, x2.high_low_container
        akeys, acont, na = a.keys, a.containers, len(a.keys)
        bkeys, bcont, nb = b.keys, b.containers, len(b.keys)
        okeys, ocont = out.high_low_container.keys, out.high_low_container.containers
        ia = ib = 0
        while ia < na and ib < nb:
            ka, kb = akeys[ia], bkeys[ib]
            if ka == kb:
                c = acont[ia].and_(bcont[ib])
                if c.cardinality:
                    okeys.append(ka)
                    ocont.append(c)
                ia += 1
                ib += 1
            elif ka < kb:
                ia = a.advance_until(kb, ia)
            else:
                ib = b.advance_until(ka, ib)
        return out

    @staticmethod
    def or_(x1: "RoaringBitmap", x2: "RoaringBitmap", *more: "RoaringBitmap") -> "RoaringBitmap":
        """RoaringBitmap.or (RoaringBitmap.java:860): two-pointer key merge.

        With more than two operands this delegates to FastAggregation like the
        reference's ``or(RoaringBitmap...)`` facade overload (:831-844)."""
        if more:
            from ..parallel.aggregation import FastAggregation

            return FastAggregation.or_(x1, x2, *more)
        col = _columnar()
        tier = col.route(
            x1.high_low_container, x2.high_low_container, op="or"
        )
        with col.outcome(tier):
            if tier != "per-container":
                return col.pairwise("or", x1, x2, tier=tier)
            return RoaringBitmap._merge_op(x1, x2, "or")

    @staticmethod
    def xor(x1: "RoaringBitmap", x2: "RoaringBitmap", *more: "RoaringBitmap") -> "RoaringBitmap":
        if more:
            from ..parallel.aggregation import FastAggregation

            return FastAggregation.xor(x1, x2, *more)
        col = _columnar()
        tier = col.route(
            x1.high_low_container, x2.high_low_container, op="xor"
        )
        with col.outcome(tier):
            if tier != "per-container":
                return col.pairwise("xor", x1, x2, tier=tier)
            return RoaringBitmap._merge_op(x1, x2, "xor")

    @staticmethod
    def _merge_op(x1, x2, op: str, reuse_left: bool = False) -> "RoaringBitmap":
        """Two-pointer key merge. ``reuse_left`` transfers x1's pass-through
        containers without cloning — the in-place ops use it the way the
        reference's member or/xor mutate ``this`` but never alias ``x2``
        (RoaringBitmap.java member or :926; matched-key results are always
        fresh objects from the container op, so only pass-through clones
        are at stake)."""
        out = RoaringBitmap()
        a, b = x1.high_low_container, x2.high_low_container
        # loop-local bindings: the merge touches size/keys/containers every
        # iteration, and property + attribute hops were a third of or2by2
        akeys, acont, na = a.keys, a.containers, len(a.keys)
        bkeys, bcont, nb = b.keys, b.containers, len(b.keys)
        okeys, ocont = out.high_low_container.keys, out.high_low_container.containers
        ia = ib = 0
        while ia < na and ib < nb:
            ka, kb = akeys[ia], bkeys[ib]
            if ka == kb:
                c = (
                    acont[ia].or_(bcont[ib])
                    if op == "or"
                    else acont[ia].xor_(bcont[ib])
                )
                if c.cardinality:
                    okeys.append(ka)
                    ocont.append(c)
                ia += 1
                ib += 1
            elif ka < kb:
                okeys.append(ka)
                ocont.append(acont[ia] if reuse_left else acont[ia].clone())
                ia += 1
            else:
                okeys.append(kb)
                ocont.append(bcont[ib].clone())
                ib += 1
        while ia < na:
            okeys.append(akeys[ia])
            ocont.append(acont[ia] if reuse_left else acont[ia].clone())
            ia += 1
        while ib < nb:
            okeys.append(bkeys[ib])
            ocont.append(bcont[ib].clone())
            ib += 1
        return out

    @staticmethod
    def _restrict(bm: "RoaringBitmap", start: int, end: int) -> "RoaringBitmap":
        """Values of ``bm`` in ``[start, end)`` (selectRangeWithoutCopy,
        RoaringBitmap.java:3135): interior containers are shared-cloned,
        only the two boundary chunks are masked."""
        out = RoaringBitmap()
        if start >= end:
            return out
        hlc = bm.high_low_container
        first_key, last_key = start >> 16, (end - 1) >> 16
        i = hlc.advance_until(first_key, -1)
        while i < hlc.size and hlc.keys[i] <= last_key:
            k = hlc.keys[i]
            c = hlc.containers[i]
            lo = start - (k << 16) if k == first_key else 0
            hi = end - (k << 16) if k == last_key else 1 << 16
            if lo > 0 or hi < (1 << 16):
                c = c.and_(container_range_of_ones(lo, hi))
            # interior containers are shared, not cloned: the result is only
            # ever fed to non-mutating static algebra
            if c.cardinality:
                out.high_low_container.append(k, c)
            i += 1
        return out

    @staticmethod
    def andnot_range(
        x1: "RoaringBitmap", x2: "RoaringBitmap", range_start: int, range_end: int
    ) -> "RoaringBitmap":
        """Ranged difference: (x1 \\ x2) restricted to [range_start, range_end)
        (RoaringBitmap.andNot(x1, x2, rangeStart, rangeEnd),
        RoaringBitmap.java:1396-1402 — both operands are restricted to the
        range before the subtraction, so values of x1 outside it are dropped)."""
        range_start, range_end = _check_range(range_start, range_end)
        return RoaringBitmap.andnot(
            RoaringBitmap._restrict(x1, range_start, range_end),
            RoaringBitmap._restrict(x2, range_start, range_end),
        )

    @staticmethod
    def andnot(
        x1: "RoaringBitmap", x2: "RoaringBitmap", *, _reuse_left: bool = False
    ) -> "RoaringBitmap":
        """RoaringBitmap.andNot (RoaringBitmap.java:444). ``_reuse_left``
        transfers x1's pass-through containers unclone'd — ONLY for the
        in-place iandnot, which discards x1's old index; the static path
        must keep cloning because andnot_range feeds it _restrict views
        that share containers with live bitmaps."""
        col = _columnar()
        tier = col.route(
            x1.high_low_container, x2.high_low_container, op="andnot"
        )
        with col.outcome(tier):
            if tier != "per-container":
                return col.pairwise(
                    "andnot", x1, x2, reuse_left=_reuse_left, tier=tier
                )
            return RoaringBitmap._andnot_percontainer(x1, x2, _reuse_left)

    @staticmethod
    def _andnot_percontainer(
        x1: "RoaringBitmap", x2: "RoaringBitmap", _reuse_left: bool
    ) -> "RoaringBitmap":
        out = RoaringBitmap()
        a, b = x1.high_low_container, x2.high_low_container
        akeys, acont, na = a.keys, a.containers, len(a.keys)
        bkeys, bcont, nb = b.keys, b.containers, len(b.keys)
        okeys, ocont = out.high_low_container.keys, out.high_low_container.containers
        ia = ib = 0
        while ia < na:
            ka = akeys[ia]
            while ib < nb and bkeys[ib] < ka:
                ib += 1
            if ib < nb and bkeys[ib] == ka:
                c = acont[ia].andnot(bcont[ib])
                if c.cardinality:
                    okeys.append(ka)
                    ocont.append(c)
            else:
                okeys.append(ka)
                ocont.append(acont[ia] if _reuse_left else acont[ia].clone())
            ia += 1
        return out

    def ior_not(self, other: "RoaringBitmap", range_end: int) -> "RoaringBitmap":
        """In-place orNot (the reference's member orNot(x2, rangeEnd)):
        this |= (~other restricted to [0, range_end)). Member-op
        semantics: self's old index is discarded, so its beyond-range
        pass-through chunks transfer unclone'd (the same reuse_left
        elision ior/ixor/iandnot already have)."""
        self.high_low_container = RoaringBitmap.or_not(
            self, other, range_end, _reuse_left=True
        ).high_low_container
        return self

    @staticmethod
    def or_not(
        x1: "RoaringBitmap", x2: "RoaringBitmap", range_end: int,
        *, _reuse_left: bool = False,
    ) -> "RoaringBitmap":
        """x1 | (~x2 ∩ [0, range_end)) (RoaringBitmap.orNot, RoaringBitmap.java:1521).

        Container walk: every key chunk of [0, range_end) gets the in-chunk
        complement of x2's container (full-range when absent) OR'd with x1's —
        no whole-universe bitmap is ever materialized. ``_reuse_left`` (the
        ior_not path only) transfers x1's beyond-range chunks unclone'd."""
        _, range_end = _check_range(0, range_end)
        out = RoaringBitmap()
        if range_end == 0:
            return RoaringBitmap.or_(x1, out)
        a, b = x1.high_low_container, x2.high_low_container
        last_key = (range_end - 1) >> 16
        for k in range(last_key + 1):
            range_len = min(1 << 16, range_end - (k << 16))
            ib = b.get_index(k)
            comp: Container = container_range_of_ones(0, range_len)
            if ib >= 0:
                comp = comp.andnot(b.containers[ib])
            ia = a.get_index(k)
            if ia >= 0:
                comp = comp.or_(a.containers[ia])
            if comp.cardinality:
                out.high_low_container.append(k, comp)
        # x1's chunks beyond the range pass through untouched
        ia = a.advance_until(last_key + 1, -1)
        while ia < a.size:
            out.high_low_container.append(
                a.keys[ia],
                a.containers[ia] if _reuse_left else a.containers[ia].clone(),
            )
            ia += 1
        return out

    @staticmethod
    def and_cardinality(x1: "RoaringBitmap", x2: "RoaringBitmap") -> int:
        """RoaringBitmap.andCardinality (RoaringBitmap.java:413). Above
        the columnar cutoff the count comes from the batched
        cardinality-only kernels — nothing materializes."""
        col = _columnar()
        if col.enabled_for(x1.high_low_container, x2.high_low_container):
            return col.and_cardinality_pair(x1, x2)
        return RoaringBitmap._and_cardinality_percontainer(x1, x2)

    @staticmethod
    def _and_cardinality_percontainer(x1: "RoaringBitmap", x2: "RoaringBitmap") -> int:
        total = 0
        a, b = x1.high_low_container, x2.high_low_container
        ia = ib = 0
        while ia < a.size and ib < b.size:
            ka, kb = a.keys[ia], b.keys[ib]
            if ka == kb:
                total += a.containers[ia].and_cardinality(b.containers[ib])
                ia += 1
                ib += 1
            elif ka < kb:
                ia = a.advance_until(kb, ia)
            else:
                ib = b.advance_until(ka, ib)
        return total

    @staticmethod
    def or_cardinality(x1: "RoaringBitmap", x2: "RoaringBitmap") -> int:
        """Inclusion-exclusion (RoaringBitmap.java:916)."""
        return (
            x1.get_cardinality()
            + x2.get_cardinality()
            - RoaringBitmap.and_cardinality(x1, x2)
        )

    @staticmethod
    def xor_cardinality(x1: "RoaringBitmap", x2: "RoaringBitmap") -> int:
        return (
            x1.get_cardinality()
            + x2.get_cardinality()
            - 2 * RoaringBitmap.and_cardinality(x1, x2)
        )

    @staticmethod
    def andnot_cardinality(x1: "RoaringBitmap", x2: "RoaringBitmap") -> int:
        return x1.get_cardinality() - RoaringBitmap.and_cardinality(x1, x2)

    @staticmethod
    def intersects(x1: "RoaringBitmap", x2: "RoaringBitmap") -> bool:
        """RoaringBitmap.intersects (RoaringBitmap.java:698). The columnar
        path short-circuits between class batches instead of between
        containers."""
        col = _columnar()
        if col.enabled_for(x1.high_low_container, x2.high_low_container):
            return col.intersects_pair(x1, x2)
        return RoaringBitmap._intersects_percontainer(x1, x2)

    @staticmethod
    def _intersects_percontainer(x1: "RoaringBitmap", x2: "RoaringBitmap") -> bool:
        a, b = x1.high_low_container, x2.high_low_container
        ia = ib = 0
        while ia < a.size and ib < b.size:
            ka, kb = a.keys[ia], b.keys[ib]
            if ka == kb:
                if a.containers[ia].intersects(b.containers[ib]):
                    return True
                ia += 1
                ib += 1
            elif ka < kb:
                ia = a.advance_until(kb, ia)
            else:
                ib = b.advance_until(ka, ib)
        return False

    # in-place variants + operators. The member-op pass-through transfer
    # (reuse_left — round 4's ior win, extended to ixor/iandnot and now
    # uniform on the columnar engine too) is safe exactly because these
    # discard self's old index.
    def ior(self, other: "RoaringBitmap") -> "RoaringBitmap":
        self.high_low_container = self._inplace_merge(other, "or")
        return self

    def iand(self, other: "RoaringBitmap") -> "RoaringBitmap":
        self.high_low_container = RoaringBitmap.and_(self, other).high_low_container
        return self

    def ixor(self, other: "RoaringBitmap") -> "RoaringBitmap":
        self.high_low_container = self._inplace_merge(other, "xor")
        return self

    def _inplace_merge(self, other: "RoaringBitmap", op: str):
        col = _columnar()
        tier = col.route(self.high_low_container, other.high_low_container, op=op)
        with col.outcome(tier):
            if tier != "per-container":
                return col.pairwise(
                    op, self, other, reuse_left=True, tier=tier
                ).high_low_container
            return RoaringBitmap._merge_op(
                self, other, op, reuse_left=True
            ).high_low_container

    def iandnot(self, other: "RoaringBitmap") -> "RoaringBitmap":
        self.high_low_container = RoaringBitmap.andnot(
            self, other, _reuse_left=True
        ).high_low_container
        return self

    __or__ = lambda self, o: RoaringBitmap.or_(self, o)
    __and__ = lambda self, o: RoaringBitmap.and_(self, o)
    __xor__ = lambda self, o: RoaringBitmap.xor(self, o)
    __sub__ = lambda self, o: RoaringBitmap.andnot(self, o)
    __ior__ = ior
    __iand__ = iand
    __ixor__ = ixor
    __isub__ = iandnot

    # ------------------------------------------------------------------
    # cardinality / order statistics
    # ------------------------------------------------------------------
    def get_cardinality(self) -> int:
        return sum(c.cardinality for c in self.high_low_container.containers)

    get_long_cardinality = get_cardinality  # getLongCardinality alias

    def is_empty(self) -> bool:
        return self.high_low_container.size == 0

    def rank_long(self, x: int) -> int:
        """Values <= x (RoaringBitmap.rank, RoaringBitmap.java:2622)."""
        x = _check_value(x)
        hb, lb = x >> 16, x & 0xFFFF
        total = 0
        hlc = self.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            if k < hb:
                total += c.cardinality
            elif k == hb:
                total += c.rank(lb)
            else:
                break
        return total

    rank = rank_long

    def select(self, j: int) -> int:
        """j-th smallest value, 0-based (RoaringBitmap.select, RoaringBitmap.java:2820)."""
        j = int(j)
        if j < 0:
            raise IndexError(j)
        hlc = self.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            card = c.cardinality
            if j < card:
                return (k << 16) | c.select(j)
            j -= card
        raise IndexError("select out of range")

    def first(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        hlc = self.high_low_container
        return (hlc.keys[0] << 16) | hlc.containers[0].first()

    def last(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        hlc = self.high_low_container
        return (hlc.keys[-1] << 16) | hlc.containers[-1].last()

    def first_signed(self) -> int:
        """Smallest value in signed-int32 order (RoaringBitmap.firstSigned):
        the first value >= 2^31 if any negative-half values exist."""
        v = self.next_value(1 << 31)
        if v >= 0:
            return v - _MAX32
        return self.first()

    def last_signed(self) -> int:
        """Largest value in signed-int32 order (RoaringBitmap.lastSigned)."""
        v = self.previous_value((1 << 31) - 1)
        if v >= 0:
            return v
        return self.last() - _MAX32

    def cardinality_exceeds(self, threshold: int) -> bool:
        """True once the running cardinality passes threshold, without
        visiting remaining containers (RoaringBitmap.cardinalityExceeds)."""
        total = 0
        for c in self.high_low_container.containers:
            total += c.cardinality
            if total > threshold:
                return True
        return False

    def clear(self) -> None:
        """Empty the bitmap in place (RoaringBitmap.clear)."""
        from .roaring_array import RoaringArray

        self.high_low_container = RoaringArray()

    def trim(self) -> None:
        """Release excess capacity (RoaringBitmap.trim). Storage here is
        exact-sized numpy arrays, so this is a documented no-op."""

    def append(self, key: int, container) -> None:
        """Append a (key, container) pair; ``key`` must exceed the current
        maximum key (RoaringBitmap.append, RoaringBitmap.java:3237 — the
        expert bulk-construction hook used by the writers)."""
        self.high_low_container.append(int(key), container)

    def for_each(self, consumer) -> None:
        """Visit every value in ascending order (RoaringBitmap.forEach,
        IntConsumer contract)."""
        for k, c in zip(self.high_low_container.keys, self.high_low_container.containers):
            base = k << 16
            for v in c.to_array().tolist():
                consumer(base | v)

    def _values_in_value_range(self, start: int, end: int) -> "RoaringBitmap":
        """Members with start <= value < end, as a bitmap (cheap: the range
        mask is a handful of run containers)."""
        if start >= end:
            return RoaringBitmap()
        return RoaringBitmap.and_(self, RoaringBitmap.bitmap_of_range(start, end))

    def for_each_in_range(self, start: int, end: int, consumer) -> None:
        """Visit every *present* value in [start, end) ascending
        (RoaringBitmap.forEachInRange)."""
        start, end = _check_range(start, end)
        for v in self._values_in_value_range(start, end):
            consumer(v)

    def for_all_in_range(self, start: int, end: int, consumer) -> None:
        """Visit every *position* in [start, end) with its membership —
        the RelativeRangeConsumer contract (RoaringBitmap.forAllInRange):
        ``consumer(relative_pos, present)``. Streams per 2^16-chunk so wide
        ranges stay O(chunk) in memory, like the Java per-container walk."""
        start, end = _check_range(start, end)
        for cs in range(start, end, 1 << 16):
            ce = min(cs + (1 << 16), end)
            present = self._values_in_value_range(cs, ce)
            flags = np.zeros(ce - cs, dtype=bool)
            if present.get_cardinality():
                flags[present.to_array().astype(np.int64) - cs] = True
            base = cs - start
            for pos, flag in enumerate(flags):
                consumer(base + pos, bool(flag))

    def get_container_pointer(self) -> "ContainerPointer":
        """Ordered cursor over (key, container) pairs — the SPI used by
        horizontal aggregation (ContainerPointer.java, RoaringBitmap
        .getContainerPointer)."""
        return ContainerPointer(self)

    def next_value(self, from_value: int) -> int:
        """Smallest value >= from_value, or -1 (RoaringBitmap.java:2838)."""
        from_value = _check_value(from_value)
        hb, lb = from_value >> 16, from_value & 0xFFFF
        hlc = self.high_low_container
        i = hlc.get_index(hb)
        start = i if i >= 0 else -i - 1
        for j in range(start, hlc.size):
            k = hlc.keys[j]
            v = hlc.containers[j].next_value(lb if k == hb else 0)
            if v >= 0:
                return (k << 16) | v
        return -1

    def previous_value(self, from_value: int) -> int:
        from_value = _check_value(from_value)
        hb, lb = from_value >> 16, from_value & 0xFFFF
        hlc = self.high_low_container
        i = hlc.get_index(hb)
        start = i if i >= 0 else -i - 2
        for j in range(start, -1, -1):
            k = hlc.keys[j]
            v = hlc.containers[j].previous_value(lb if k == hb else 0xFFFF)
            if v >= 0:
                return (k << 16) | v
        return -1

    def next_absent_value(self, from_value: int) -> int:
        from_value = _check_value(from_value)
        x = from_value
        while x < _MAX32:
            hb, lb = x >> 16, x & 0xFFFF
            c = self.high_low_container.get_container(hb)
            if c is None:
                return x
            v = c.next_absent_value(lb)
            if v < (1 << 16):
                return (hb << 16) | v
            x = (hb + 1) << 16
        return -1

    def previous_absent_value(self, from_value: int) -> int:
        from_value = _check_value(from_value)
        x = from_value
        while x >= 0:
            hb, lb = x >> 16, x & 0xFFFF
            c = self.high_low_container.get_container(hb)
            if c is None:
                return x
            v = c.previous_absent_value(lb)
            if v >= 0:
                return (hb << 16) | v
            x = (hb << 16) - 1
        return -1

    # ------------------------------------------------------------------
    # structural ops
    # ------------------------------------------------------------------
    @staticmethod
    def add_offset(bm: "RoaringBitmap", offset: int) -> "RoaringBitmap":
        """Shift all values by a (possibly negative) offset, dropping values
        leaving the 32-bit universe (RoaringBitmap.addOffset, RoaringBitmap.java:230).

        Each shifted container splits into a (low, high) pair
        (Util.addOffset, Util.java:32-45) — realized here vectorized on the
        value arrays.
        """
        offset = int(offset)
        out = RoaringBitmap()
        hlc = bm.high_low_container
        pieces = {}
        for k, c in zip(hlc.keys, hlc.containers):
            vals = c.to_array().astype(np.int64) + (k << 16) + offset
            vals = vals[(vals >= 0) & (vals < _MAX32)]
            if vals.size == 0:
                continue
            keys = vals >> 16
            for key in np.unique(keys):
                chunk = (vals[keys == key] & 0xFFFF).astype(np.uint16)
                if int(key) in pieces:
                    pieces[int(key)] = np.concatenate([pieces[int(key)], chunk])
                else:
                    pieces[int(key)] = chunk
        for key in sorted(pieces):
            out.high_low_container.append(
                key, container_from_values(np.sort(pieces[key]))
            )
        return out

    def limit(self, max_cardinality: int) -> "RoaringBitmap":
        """Bitmap of the max_cardinality smallest values (RoaringBitmap.java:2457)."""
        out = RoaringBitmap()
        remaining = int(max_cardinality)
        hlc = self.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            if remaining <= 0:
                break
            card = c.cardinality
            if card <= remaining:
                out.high_low_container.append(k, c.clone())
                remaining -= card
            else:
                out.high_low_container.append(
                    k, container_from_values(c.to_array()[:remaining])
                )
                remaining = 0
        return out

    def select_range(self, start: int, end: int) -> "RoaringBitmap":
        """Bitmap of values with rank in [start, end) (RoaringBitmap.selectRange,
        RoaringBitmap.java:3095)."""
        start, end = int(start), int(end)
        out = RoaringBitmap()
        if start >= end:
            return out
        seen = 0  # cumulative cardinality before the current container
        hlc = self.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            card = c.cardinality
            if seen + card <= start:
                seen += card
                continue
            if seen >= end:
                break
            lo, hi = max(start - seen, 0), min(end - seen, card)
            if lo == 0 and hi == card:
                out.high_low_container.append(k, c.clone())
            else:
                out.high_low_container.append(
                    k, container_from_values(c.to_array()[lo:hi])
                )
            seen += card
        return out

    def run_optimize(self) -> bool:
        """Convert containers to their smallest form; True if any became a run
        (RoaringBitmap.java:2764)."""
        changed = False
        hlc = self.high_low_container
        for i, c in enumerate(hlc.containers):
            n = c.run_optimize()
            if isinstance(n, RunContainer) and not isinstance(c, RunContainer):
                changed = True
            hlc.set_container_at_index(i, n)
        return changed

    def remove_run_compression(self) -> bool:
        changed = False
        hlc = self.high_low_container
        for i, c in enumerate(hlc.containers):
            if isinstance(c, RunContainer):
                hlc.set_container_at_index(i, c.to_efficient_non_run())
                changed = True
        return changed

    def has_run_compression(self) -> bool:
        return any(
            isinstance(c, RunContainer) for c in self.high_low_container.containers
        )

    def contains_bitmap(self, subset: "RoaringBitmap") -> bool:
        """True if subset ⊆ self (RoaringBitmap.contains(RoaringBitmap),
        RoaringBitmap.java:2781)."""
        a, b = self.high_low_container, subset.high_low_container
        ib = 0
        for kb, cb in zip(b.keys, b.containers):
            i = a.get_index(kb)
            if i < 0 or not a.containers[i].contains_container(cb):
                return False
        return True

    def is_hamming_similar(self, other: "RoaringBitmap", tolerance: int) -> bool:
        """|self XOR other| <= tolerance (RoaringBitmap.java:1831)."""
        return RoaringBitmap.xor_cardinality(self, other) <= int(tolerance)

    # ------------------------------------------------------------------
    # iteration / export
    # ------------------------------------------------------------------
    def to_array(self) -> np.ndarray:
        """All values, sorted, as uint32."""
        hlc = self.high_low_container
        if hlc.size == 0:
            return np.empty(0, dtype=np.uint32)
        parts = [
            c.to_array().astype(np.uint32) + np.uint32(k << 16)
            for k, c in zip(hlc.keys, hlc.containers)
        ]
        return np.concatenate(parts)

    def __iter__(self) -> Iterator[int]:
        for k, c in zip(
            self.high_low_container.keys, self.high_low_container.containers
        ):
            base = k << 16
            for v in c.to_array().tolist():
                yield base | v

    def __reversed__(self) -> Iterator[int]:
        for k, c in zip(
            reversed(self.high_low_container.keys),
            reversed(self.high_low_container.containers),
        ):
            base = k << 16
            for v in reversed(c.to_array().tolist()):
                yield base | v

    def get_int_iterator(self):
        """Peekable forward iterator (getIntIterator; PeekableIntIterator)."""
        from .iterators import PeekableIntIterator

        return PeekableIntIterator(self)

    def get_signed_int_iterator(self) -> Iterator[int]:
        """Values in signed-int32 order: negative half (>= 2^31, as
        negatives) first (RoaringBitmap.getSignedIntIterator). The first
        pass container-skips straight to the negative half."""
        half = 1 << 31
        it = self.get_int_iterator()
        it.advance_if_needed(half)
        while it.has_next():
            yield it.next() - _MAX32
        for v in self:
            if v >= half:
                break
            yield v

    def get_reverse_int_iterator(self):
        """Descending iterator (getReverseIntIterator)."""
        from .iterators import ReverseIntIterator

        return ReverseIntIterator(self)

    def get_int_rank_iterator(self):
        """Rank-tracking peekable iterator (getIntRankIterator)."""
        from .iterators import PeekableIntRankIterator

        return PeekableIntRankIterator(self)

    def get_batch_iterator(self):
        """Buffer-filling iterator (getBatchIterator, BatchIterator.java:12)."""
        from .iterators import RoaringBatchIterator

        return RoaringBatchIterator(self)

    def batch_iterator(self, batch_size: int = 256) -> Iterator[np.ndarray]:
        """Buffer-filling iteration (BatchIterator.nextBatch contract,
        BatchIterator.java:12), yielding uint32 chunks."""
        buf: List[np.ndarray] = []
        count = 0
        for k, c in zip(
            self.high_low_container.keys, self.high_low_container.containers
        ):
            arr = c.to_array().astype(np.uint32) + np.uint32(k << 16)
            buf.append(arr)
            count += arr.size
            while count >= batch_size:
                joined = np.concatenate(buf) if len(buf) > 1 else buf[0]
                yield joined[:batch_size]
                rest = joined[batch_size:]
                buf = [rest] if rest.size else []
                count = rest.size
        if count:
            yield np.concatenate(buf) if len(buf) > 1 else buf[0]

    # ------------------------------------------------------------------
    # introspection (SURVEY §5 observability)
    # ------------------------------------------------------------------
    def fingerprint(self) -> tuple:
        """Cheap mutation-tracking token: ``(array generation, mutation
        version)``. Every mutator bumps the version (or, for the in-place
        algebra that swaps in a fresh ``RoaringArray``, changes the
        generation), so two equal fingerprints of the same bitmap object
        guarantee unchanged contents — the invalidation key of the query
        result cache (query/cache.py). O(1); NOT a content hash: two equal
        bitmaps have different fingerprints."""
        return hlc_fingerprint(self.high_low_container)

    def get_container_count(self) -> int:
        return self.high_low_container.size

    def get_size_in_bytes(self) -> int:
        from ..serialization import serialized_size_in_bytes

        return serialized_size_in_bytes(self)

    get_long_size_in_bytes = get_size_in_bytes

    # serialization facade (implementation in serialization.py)
    def serialize(self) -> bytes:
        from ..serialization import serialize

        return serialize(self)

    def serialized_size_in_bytes(self) -> int:
        """Exact byte size of serialize() (RoaringBitmap.serializedSizeInBytes)."""
        from ..serialization import serialized_size_in_bytes

        return serialized_size_in_bytes(self)

    def serialize_into(self, stream) -> int:
        """Write the portable format to a binary file-like object; returns
        bytes written (the DataOutput/stream overloads of
        RoaringBitmap.serialize, RoaringBitmap.java:3012)."""
        data = self.serialize()
        stream.write(data)
        return len(data)

    @staticmethod
    def deserialize(data, copy: bool = True) -> "RoaringBitmap":
        from ..serialization import deserialize

        return deserialize(data, copy=copy)

    @classmethod
    def deserialize_from(cls, stream) -> "RoaringBitmap":
        """Read one bitmap from a binary file-like object positioned at its
        start; forward-only reads consume exactly the bitmap's bytes, so
        consecutive bitmaps stream back-to-back and non-seekable sources
        (sockets, pipes) work (the DataInput overload of
        RoaringBitmap.deserialize). Classmethod: subclasses deserialize to
        their own type."""
        from ..serialization import read_from_stream

        bm = cls()
        read_from_stream(bm, stream)
        return bm

    @staticmethod
    def maximum_serialized_size(cardinality: int, universe_size: int) -> int:
        from ..serialization import maximum_serialized_size

        return maximum_serialized_size(cardinality, universe_size)

    def __reduce__(self):
        """Pickle via the portable wire format — the Externalizable/Kryo
        analogue (RoaringBitmap.java:2627/3287, README.md:285-312).
        Subclasses (MutableRoaringBitmap, FastRankRoaringBitmap)
        round-trip to their own type."""
        return _roaring_from_bytes, (type(self), self.serialize())

    # ------------------------------------------------------------------
    def __eq__(self, other):
        if not isinstance(other, RoaringBitmap):
            return NotImplemented
        return self.high_low_container == other.high_low_container

    def __hash__(self):
        return hash(self.to_array().tobytes())

    def __len__(self) -> int:
        return self.get_cardinality()

    def __contains__(self, x: int) -> bool:
        return self.contains(x)

    def __bool__(self) -> bool:
        return not self.is_empty()

    def __repr__(self) -> str:
        card = self.get_cardinality()
        head = ",".join(str(v) for v in self.to_array()[:10].tolist())
        return f"RoaringBitmap(card={card}, values=[{head}{'...' if card > 10 else ''}])"


def _roaring_from_bytes(cls, blob: bytes) -> "RoaringBitmap":
    """Pickle reconstructor: deserialize then adopt into the target class."""
    out = cls()
    out.high_low_container = RoaringBitmap.deserialize(blob).high_low_container
    return out


class ContainerPointer:
    """Ordered cursor over a bitmap's (key, container) pairs
    (ContainerPointer.java:62): the SPI horizontal aggregation uses to
    merge many bitmaps key-by-key. ``key()`` is None when exhausted."""

    __slots__ = ("_hlc", "_i")

    def __init__(self, bm: "RoaringBitmap"):
        self._hlc = bm.high_low_container
        self._i = 0

    def key(self) -> Optional[int]:
        return self._hlc.keys[self._i] if self._i < self._hlc.size else None

    def get_container(self) -> Optional["Container"]:
        return (
            self._hlc.get_container_at_index(self._i)
            if self._i < self._hlc.size
            else None
        )

    def get_cardinality(self) -> int:
        c = self.get_container()
        return c.cardinality if c is not None else 0

    def is_bitmap_container(self) -> bool:
        return isinstance(self.get_container(), BitmapContainer)

    def is_run_container(self) -> bool:
        return isinstance(self.get_container(), RunContainer)

    def advance(self) -> None:
        self._i += 1

    def __lt__(self, other: "ContainerPointer") -> bool:
        a, b = self.key(), other.key()
        if a is None:
            return False
        if b is None:
            return True
        return a < b
