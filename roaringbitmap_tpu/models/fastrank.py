"""Rank/select-accelerated bitmap (FastRankRoaringBitmap.java:21-39):
cumulative per-key cardinalities cached and invalidated on writes."""

from __future__ import annotations

import numpy as np

from .roaring import RoaringBitmap


class FastRankRoaringBitmap(RoaringBitmap):
    __slots__ = ("_cum", "_dirty")

    def __init__(self, values=None):
        self._cum = None
        self._dirty = True
        super().__init__(values)

    def _invalidate(self):
        self._dirty = True

    # every mutator invalidates the cache (FastRankRoaringBitmap.java:30-39)
    def add(self, x):
        self._invalidate()
        return super().add(x)

    def add_many(self, values):
        self._invalidate()
        return super().add_many(values)

    def remove(self, x):
        self._invalidate()
        return super().remove(x)

    def add_range(self, s, e):
        self._invalidate()
        return super().add_range(s, e)

    def remove_range(self, s, e):
        self._invalidate()
        return super().remove_range(s, e)

    def flip_range(self, s, e):
        self._invalidate()
        return super().flip_range(s, e)

    def ior(self, o):
        self._invalidate()
        return super().ior(o)

    def iand(self, o):
        self._invalidate()
        return super().iand(o)

    def ixor(self, o):
        self._invalidate()
        return super().ixor(o)

    def iandnot(self, o):
        self._invalidate()
        return super().iandnot(o)

    def _cum_cards(self) -> np.ndarray:
        if self._dirty or self._cum is None:
            cards = np.array(
                [c.cardinality for c in self.high_low_container.containers],
                dtype=np.int64,
            )
            self._cum = np.cumsum(cards) if cards.size else np.empty(0, dtype=np.int64)
            self._dirty = False
        return self._cum

    def rank_long(self, x: int) -> int:
        from ..utils.order_stats import bucketed_rank

        x = int(x)
        hb, lb = x >> 16, x & 0xFFFF
        hlc = self.high_low_container
        return bucketed_rank(
            hlc.keys, self._cum_cards(), hb, lambda i: hlc.containers[i].rank(lb)
        )

    rank = rank_long

    def select(self, j: int) -> int:
        from ..utils.order_stats import bucketed_select

        hlc = self.high_low_container
        return bucketed_select(
            hlc.keys,
            self._cum_cards(),
            j,
            lambda i, lj: (hlc.keys[i] << 16) | hlc.containers[i].select(lj),
        )
