"""Buffer-package twins (org.roaringbitmap.buffer, SURVEY §2.2).

The reference re-implements its whole container hierarchy over ``java.nio``
buffers (17k LoC: MappeableContainer.java:19, MutableRoaringBitmap.java,
BufferFastAggregation.java:20, BufferParallelAggregation.java:41) so bitmaps
can live off-heap / memory-mapped. The TPU-native design collapses the twin
hierarchy: ``ImmutableRoaringBitmap`` (models/immutable.py) already
materializes zero-copy numpy views over the serialized buffer that satisfy
the ordinary ``Container`` protocol, so ONE algebra serves both worlds.

This module supplies the remaining public surface of the buffer package:

* ``MutableRoaringBitmap`` — the writable buffer-world facade
  (buffer/MutableRoaringBitmap.java), castable to an immutable view in O(1)
  (README.md:205-207) and constructible from one.
* Mixed-operand pairwise algebra — ``and_``/``or_``/``xor``/``andnot``/
  ``or_not`` and the cardinality variants accept any combination of heap
  ``RoaringBitmap``, ``MutableRoaringBitmap`` and mapped
  ``ImmutableRoaringBitmap`` operands, exactly like the reference's
  ImmutableRoaringBitmap static ops (buffer/ImmutableRoaringBitmap.java).
* ``BufferFastAggregation`` (BufferFastAggregation.java:20) /
  ``BufferParallelAggregation`` (BufferParallelAggregation.java:41) — the
  N-way engines over mixed/mapped inputs, including the workShy AND
  dispatch (BufferFastAggregation.java:29-33). They reuse the batched
  CPU/TPU engines of parallel/aggregation.py unchanged: mapped containers
  are packed to the device straight from their buffer views.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Union

from .container import Container
from .immutable import ImmutableRoaringBitmap
from .roaring import RoaringBitmap

AnyRoaring = Union[RoaringBitmap, ImmutableRoaringBitmap]


def _flatten_mixed(bitmaps) -> List[AnyRoaring]:
    from ..parallel.aggregation import _flatten

    return _flatten(bitmaps)


class MutableRoaringBitmap(RoaringBitmap):
    """Writable buffer-world bitmap (buffer/MutableRoaringBitmap.java).

    Same algebra and mutation API as :class:`RoaringBitmap` (inherited);
    adds the buffer-world casts. ``to_immutable`` serializes once and wraps
    the bytes zero-copy; ``as_immutable_view`` is the reference's O(1) cast
    (README.md:205-207) — a read-only facade over the *live* containers
    (safe for concurrent reads while unmutated, the documented contract,
    README.md:280).
    """

    @staticmethod
    def _adopt(rb: RoaringBitmap) -> "MutableRoaringBitmap":
        out = MutableRoaringBitmap()
        out.high_low_container = rb.high_low_container
        return out

    @staticmethod
    def of(source: AnyRoaring) -> "MutableRoaringBitmap":
        """Deep-copy construction from heap or mapped bitmap."""
        if isinstance(source, ImmutableRoaringBitmap):
            return MutableRoaringBitmap._adopt(source.to_mutable())
        return MutableRoaringBitmap._adopt(source.clone())

    # -- inherited factories re-typed so they stay in the buffer world ----
    @staticmethod
    def bitmap_of(*values: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.bitmap_of(*values))

    bitmap_of_unordered = bitmap_of

    @staticmethod
    def bitmap_of_range(start: int, end: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.bitmap_of_range(start, end))

    @staticmethod
    def flip(bm: AnyRoaring, start: int, end: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.flip(bm, start, end))

    @staticmethod
    def add_offset(bm: AnyRoaring, offset: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.add_offset(bm, offset))

    def clone(self) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(super().clone())

    def limit(self, max_cardinality: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(super().limit(max_cardinality))

    def select_range(self, start: int, end: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(super().select_range(start, end))

    def to_immutable(self) -> ImmutableRoaringBitmap:
        """Freeze into a buffer-backed immutable (one serialization pass)."""
        return ImmutableRoaringBitmap(self.serialize())

    to_immutable_roaring_bitmap = to_immutable  # reference naming

    def get_mappeable_roaring_array(self):
        """The backing index (MutableRoaringBitmap.getMappeableRoaringArray)."""
        return self.high_low_container

    def as_immutable_view(self) -> "ImmutableView":
        """O(1) cast to a read-only view sharing this bitmap's containers."""
        return ImmutableView(self)

    @staticmethod
    def deserialize(data, copy: bool = True) -> "MutableRoaringBitmap":
        """``copy=False`` builds zero-copy container views over ``data``
        (serialization.read_into's frozen-consumer contract): sound only
        when the result will not be mutated — a mutable twin built over a
        read-only mmap raises on the first in-place word patch."""
        return MutableRoaringBitmap._adopt(
            RoaringBitmap.deserialize(data, copy=copy)
        )

    # -- mixed-operand pairwise algebra (ImmutableRoaringBitmap statics) ---
    @staticmethod
    def and_(x1: AnyRoaring, x2: AnyRoaring) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.and_(x1, x2))

    @staticmethod
    def or_(x1: AnyRoaring, x2: AnyRoaring) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.or_(x1, x2))

    @staticmethod
    def xor(x1: AnyRoaring, x2: AnyRoaring) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.xor(x1, x2))

    @staticmethod
    def andnot(x1: AnyRoaring, x2: AnyRoaring) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.andnot(x1, x2))

    @staticmethod
    def or_not(x1: AnyRoaring, x2: AnyRoaring, range_end: int) -> "MutableRoaringBitmap":
        return MutableRoaringBitmap._adopt(RoaringBitmap.or_not(x1, x2, range_end))

    @staticmethod
    def and_cardinality(x1: AnyRoaring, x2: AnyRoaring) -> int:
        return RoaringBitmap.and_cardinality(x1, x2)

    @staticmethod
    def or_cardinality(x1: AnyRoaring, x2: AnyRoaring) -> int:
        return RoaringBitmap.or_cardinality(x1, x2)

    @staticmethod
    def xor_cardinality(x1: AnyRoaring, x2: AnyRoaring) -> int:
        return RoaringBitmap.xor_cardinality(x1, x2)

    @staticmethod
    def andnot_cardinality(x1: AnyRoaring, x2: AnyRoaring) -> int:
        return RoaringBitmap.andnot_cardinality(x1, x2)

    @staticmethod
    def intersects(x1: AnyRoaring, x2: AnyRoaring) -> bool:
        return RoaringBitmap.intersects(x1, x2)

    def __repr__(self) -> str:
        return f"MutableRoaringBitmap(card={self.get_cardinality()})"


class ImmutableView:
    """O(1) read-only cast of a live MutableRoaringBitmap
    (MutableRoaringBitmap→ImmutableRoaringBitmap upcast, README.md:205-207).

    Shares the underlying containers — no copy, no serialization. Exposes
    the read API plus ``high_low_container`` so it interoperates with all
    algebra/aggregation engines as an operand.
    """

    __slots__ = ("_bm",)

    def __init__(self, bm: RoaringBitmap):
        self._bm = bm

    @property
    def high_low_container(self):
        return self._bm.high_low_container

    def __getattr__(self, name):
        # read-only delegation: block the mutating surface
        if name in _MUTATORS:
            raise AttributeError(f"ImmutableView is read-only (no {name})")
        return getattr(self._bm, name)

    def __iter__(self):
        return iter(self._bm)

    def __contains__(self, x):
        return x in self._bm

    def __len__(self):
        return len(self._bm)

    def __eq__(self, other):
        return self._bm == other

    def __hash__(self):
        return hash(self._bm)

    def __repr__(self):
        return f"ImmutableView({self._bm!r})"


_MUTATORS = frozenset(
    {
        "add",
        "checked_add",
        "add_many",
        "remove",
        "checked_remove",
        "add_range",
        "remove_range",
        "flip_range",
        "ior",
        "iand",
        "ixor",
        "iandnot",
        "run_optimize",
        "remove_run_compression",
    }
)


class BufferFastAggregation:
    """N-way aggregation over mixed heap/mapped operands
    (BufferFastAggregation.java:20). Same engine + dispatch as
    FastAggregation — including workShy key-intersection AND for many
    inputs (BufferFastAggregation.java:29-33) and the CPU-vs-TPU batched
    dispatcher; mapped containers stream to the device from their buffer
    views without deserialization."""

    @staticmethod
    def and_(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        from ..parallel.aggregation import _aggregate

        return MutableRoaringBitmap._adopt(_aggregate(_flatten_mixed(bitmaps), "and", mode))

    @staticmethod
    def or_(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        from ..parallel.aggregation import _aggregate

        return MutableRoaringBitmap._adopt(_aggregate(_flatten_mixed(bitmaps), "or", mode))

    @staticmethod
    def xor(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        from ..parallel.aggregation import _aggregate

        return MutableRoaringBitmap._adopt(_aggregate(_flatten_mixed(bitmaps), "xor", mode))

    @staticmethod
    def naive_or(*bitmaps: AnyRoaring) -> MutableRoaringBitmap:
        from ..parallel.aggregation import FastAggregation

        return MutableRoaringBitmap._adopt(FastAggregation.naive_or(*_flatten_mixed(bitmaps)))

    @staticmethod
    def naive_and(*bitmaps: AnyRoaring) -> MutableRoaringBitmap:
        from ..parallel.aggregation import FastAggregation

        return MutableRoaringBitmap._adopt(FastAggregation.naive_and(*_flatten_mixed(bitmaps)))

    @staticmethod
    def horizontal_or(*bitmaps: AnyRoaring) -> MutableRoaringBitmap:
        from ..parallel.aggregation import FastAggregation

        return MutableRoaringBitmap._adopt(
            FastAggregation.horizontal_or(*_flatten_mixed(bitmaps))
        )

    @staticmethod
    def priorityqueue_or(*bitmaps: AnyRoaring) -> MutableRoaringBitmap:
        from ..parallel.aggregation import FastAggregation

        return MutableRoaringBitmap._adopt(
            FastAggregation.priorityqueue_or(*_flatten_mixed(bitmaps))
        )

    @staticmethod
    def workshy_and(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        return BufferFastAggregation.and_(*bitmaps, mode=mode)

    @staticmethod
    def and_cardinality(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> int:
        from ..parallel.aggregation import FastAggregation

        return FastAggregation.and_cardinality(*_flatten_mixed(bitmaps), mode=mode)

    @staticmethod
    def or_cardinality(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> int:
        from ..parallel.aggregation import FastAggregation

        return FastAggregation.or_cardinality(*_flatten_mixed(bitmaps), mode=mode)

    @staticmethod
    def xor_cardinality(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> int:
        from ..parallel.aggregation import FastAggregation

        return FastAggregation.xor_cardinality(*_flatten_mixed(bitmaps), mode=mode)


class BufferParallelAggregation:
    """Fork-join OR/XOR over mixed/mapped operands
    (BufferParallelAggregation.java:41): key-major transpose + pooled
    per-key reduction on CPU, or the single batched device kernel."""

    @staticmethod
    def group_by_key(*bitmaps: AnyRoaring) -> Dict[int, List[Container]]:
        from ..parallel import store

        return store.group_by_key(_flatten_mixed(bitmaps))

    @staticmethod
    def or_(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        from ..parallel.aggregation import ParallelAggregation

        return MutableRoaringBitmap._adopt(
            ParallelAggregation.or_(*_flatten_mixed(bitmaps), mode=mode)
        )

    @staticmethod
    def xor(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        from ..parallel.aggregation import ParallelAggregation

        return MutableRoaringBitmap._adopt(
            ParallelAggregation.xor(*_flatten_mixed(bitmaps), mode=mode)
        )

    @staticmethod
    def and_(*bitmaps: AnyRoaring, mode: Optional[str] = None) -> MutableRoaringBitmap:
        return BufferFastAggregation.and_(*bitmaps, mode=mode)
